"""Addon resizer ("nanny"): scale one workload's requests with cluster size.

Reference counterpart: addon-resizer/nanny/ — nanny_lib.go watches the node
count and patches the dependent Deployment when its resources drift outside a
tolerance from the linear formula base + extra×nodes (estimator.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ResourceEstimatorSpec:
    """base + extra_per_node, per resource (reference: nanny/estimator.go)."""

    base: dict[str, float] = field(default_factory=dict)        # cpu cores, memory bytes
    extra_per_node: dict[str, float] = field(default_factory=dict)
    # acceptance range ±% before patching (reference: --threshold)
    threshold_pct: float = 10.0


def estimate(spec: ResourceEstimatorSpec, node_count: int) -> dict[str, float]:
    out = {}
    for name in set(spec.base) | set(spec.extra_per_node):
        out[name] = spec.base.get(name, 0.0) + spec.extra_per_node.get(name, 0.0) * node_count
    return out


def needs_update(spec: ResourceEstimatorSpec, current: dict[str, float],
                 node_count: int) -> bool:
    """True when any resource is outside ±threshold of the estimate
    (reference: checkResource / shouldOverwriteResources)."""
    want = estimate(spec, node_count)
    for name, target in want.items():
        cur = current.get(name, 0.0)
        if target <= 0:
            if cur != 0:
                return True
            continue
        if abs(cur - target) / target * 100.0 > spec.threshold_pct:
            return True
    return False


class Nanny:
    """The watch loop body (reference: nanny_lib.go PollAPIServer)."""

    def __init__(self, spec: ResourceEstimatorSpec, patch_resources):
        self.spec = spec
        self.patch_resources = patch_resources  # (dict resources) -> None

    def poll_once(self, node_count: int, current: dict[str, float]) -> bool:
        if needs_update(self.spec, current, node_count):
            self.patch_resources(estimate(self.spec, node_count))
            return True
        return False
