from kubernetes_autoscaler_tpu.observers.nodegroupchange import (
    NodeGroupChangeObserverList,
)

__all__ = ["NodeGroupChangeObserverList"]
