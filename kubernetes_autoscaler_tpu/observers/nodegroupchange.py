"""Node-group change broadcast: cross-component scale event fan-out.

Reference counterpart: observers/nodegroupchange/ (SURVEY.md §2.7) — a
`ScaleStateNotifier` observer list the orchestrators/actuator call into on
every scale-up, scale-down, and failure; default subscribers update metrics
and the status document. Observers are plain callables here.
"""

from __future__ import annotations

from typing import Protocol


class NodeGroupChangeObserver(Protocol):
    def register_scale_up(self, group_id: str, delta: int, now: float) -> None: ...

    def register_scale_down(self, group_id: str, node_name: str, now: float) -> None: ...

    def register_failed_scale_up(self, group_id: str, reason: str, now: float) -> None: ...

    def register_failed_scale_down(self, group_id: str, node_name: str,
                                   reason: str, now: float) -> None: ...


class NodeGroupChangeObserverList:
    """Fan-out with isolation: one failing observer never blocks the rest
    (reference: nodegroupchange broadcaster iterates all registered)."""

    def __init__(self):
        self._observers: list[NodeGroupChangeObserver] = []

    def register(self, obs: NodeGroupChangeObserver) -> None:
        self._observers.append(obs)

    def _fan(self, method: str, *args) -> None:
        for o in self._observers:
            fn = getattr(o, method, None)
            if fn is None:
                continue
            try:
                fn(*args)
            except Exception:
                pass

    def register_scale_up(self, group_id: str, delta: int, now: float) -> None:
        self._fan("register_scale_up", group_id, delta, now)

    def register_scale_down(self, group_id: str, node_name: str, now: float) -> None:
        self._fan("register_scale_down", group_id, node_name, now)

    def register_failed_scale_up(self, group_id: str, reason: str, now: float) -> None:
        self._fan("register_failed_scale_up", group_id, reason, now)

    def register_failed_scale_down(self, group_id: str, node_name: str,
                                   reason: str, now: float) -> None:
        self._fan("register_failed_scale_down", group_id, node_name, reason, now)
