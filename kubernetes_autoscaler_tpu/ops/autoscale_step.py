"""The fused autoscaling simulation step — the framework's flagship kernel.

One jitted program covering the simulation content of a whole
StaticAutoscaler.RunOnce (core/static_autoscaler.go:296): filter-out-
schedulable, every node group's binpacking expansion option, expander scoring,
and the scale-down eligibility + drain sweep. The reference spreads this over
three serial hot loops (SURVEY.md §3.1/§3.2 loops A/B/C); here it is one
device dispatch over the pods×nodes×nodegroups tensors.

The host control plane (core/) calls these; __graft_entry__.py exposes them
for compile checking and multi-chip dry runs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_autoscaler_tpu.models.cluster_state import (
    ClusterTensors,
    Dims,
    NodeGroupTensors,
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
)
from kubernetes_autoscaler_tpu.ops import drain, schedule, scoring, utilization
from kubernetes_autoscaler_tpu.ops.binpack import EstimateResult, estimate_all
from kubernetes_autoscaler_tpu.ops.scoring import OptionScores


class ScaleUpSim(struct.PyTreeNode):
    fits_existing: jax.Array    # i32[G] pending pods absorbed by current capacity
    remaining: jax.Array        # i32[G] pods that actually need new nodes
    estimate: EstimateResult    # per-nodegroup expansion options
    scores: OptionScores
    best: jax.Array             # i32 winning node group index (-1 = none)


class ScaleDownSim(struct.PyTreeNode):
    eligible: jax.Array         # bool[N] below utilization threshold
    removal: drain.RemovalResult  # per-candidate drain verdicts (C == N here)
    utilization: jax.Array      # f32[N]


@partial(jax.jit, static_argnames=("dims", "max_new_nodes", "strategy",
                                   "with_constraints", "mesh"))
def scale_up_sim(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    groups: NodeGroupTensors,
    dims: Dims,
    max_new_nodes: int = 256,
    strategy: str = "least-waste",
    planes=None,
    with_constraints: bool = False,
    mesh=None,
    wavefront_plan=None,
) -> ScaleUpSim:
    """Loops A+B of the reference hot path as one program.

    `mesh` (static: a jax.sharding.Mesh) distributes both halves — the
    existing-nodes pack over NODES_AXIS, the NG expansion options over
    PODS_AXIS (parallel/mesh.py axis mapping). `wavefront_plan`
    (ops/pack.build_wavefront_plan over the host feasibility mask, cached by
    WavefrontCache) batches the single-device pack scan to depth W < G; both
    default to the unchanged serial single-chip path."""
    packed = schedule.schedule_pending_on_existing(
        nodes, specs, scheduled, planes=planes, max_zones=dims.max_zones,
        with_constraints=with_constraints, mesh=mesh,
        wavefront_plan=wavefront_plan)
    remaining = jnp.maximum(specs.count - packed.scheduled, 0)
    pending = specs.replace(count=remaining)
    est = estimate_all(pending, groups, dims, max_new_nodes,
                       planes=planes, nodes=nodes,
                       with_constraints=with_constraints, mesh=mesh)
    sc = scoring.score_options(est, groups)
    best = scoring.best_option(sc, strategy)
    return ScaleUpSim(
        fits_existing=packed.scheduled,
        remaining=remaining,
        estimate=est,
        scores=sc,
        best=best,
    )


@partial(jax.jit, static_argnames=("max_pods_per_node", "chunk",
                                   "max_zones", "with_constraints"))
def scale_down_sim(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    threshold: float = 0.5,
    max_pods_per_node: int = 128,
    chunk: int = 32,
    planes=None,
    max_zones: int = 16,
    with_constraints: bool = False,
) -> ScaleDownSim:
    """Loop C of the reference hot path: eligibility + full drain sweep.

    Every node is a candidate (the reference caps candidates and applies a
    simulation timeout, planner.go:297-309 — unnecessary at TPU throughput);
    the planner applies policy (unneeded time, limits) on the verdicts."""
    util = utilization.node_utilization(nodes)
    eligible = utilization.eligible_for_scale_down(nodes, threshold)
    candidates = jnp.arange(nodes.n, dtype=jnp.int32)
    removal = drain.simulate_removals(
        nodes,
        specs,
        scheduled,
        candidates,
        # Destinations: every node but the candidate itself (the planner's
        # policy — consolidation onto fellow candidates is allowed; each
        # verdict is per-candidate-in-isolation, and the planner's sequential
        # confirmation pass resolves interactions between accepted drains).
        dest_allowed=jnp.ones((nodes.n,), bool),
        max_pods_per_node=max_pods_per_node,
        chunk=chunk,
        planes=planes,
        max_zones=max_zones,
        with_constraints=with_constraints,
    )
    return ScaleDownSim(eligible=eligible, removal=removal, utilization=util)


@partial(jax.jit, static_argnames=("dims", "max_new_nodes", "strategy"))
def scale_up_sim_batch(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    groups: NodeGroupTensors,
    dims: Dims,
    max_new_nodes: int = 256,
    strategy: str = "least-waste",
) -> ScaleUpSim:
    """`scale_up_sim` vmapped over a leading tenant axis — the multi-cluster
    serving dispatch (docs/SERVING.md). Every input tensor gains axis 0 of
    size B (one lane per tenant world, stacked by sidecar/batch.py); the
    output is the SAME pytree with every leaf batched. Lane i is
    bit-identical to a serial `scale_up_sim` call on lane i's world
    (tests/test_batched_sim.py) — batching is a dispatch-shape change only.

    The per-lane body is the unsharded single-device path (no mesh, no
    wavefront plan, no constraint planes): tenants with a constraint overlay
    are dispatched serially by the sidecar instead of batched."""
    def one(nt, gt, pt, gr):
        return scale_up_sim.__wrapped__(
            nt, gt, pt, gr, dims, max_new_nodes, strategy,
            None, False, None, None)

    return jax.vmap(one)(nodes, specs, scheduled, groups)


@partial(jax.jit, static_argnames=("max_pods_per_node", "chunk", "max_zones"))
def scale_down_sim_batch(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    thresholds: jax.Array,       # f32[B] per-tenant utilization threshold
    max_pods_per_node: int = 128,
    chunk: int = 32,
    max_zones: int = 16,
) -> ScaleDownSim:
    """`scale_down_sim` vmapped over a leading tenant axis. The utilization
    threshold is a TRACED per-lane scalar (f32[B]) — tenants with different
    thresholds share one compiled program, so threshold knobs never fragment
    the batch. Lane-exact vs serial, like `scale_up_sim_batch`."""
    def one(nt, gt, pt, th):
        return scale_down_sim.__wrapped__(
            nt, gt, pt, th, max_pods_per_node, chunk,
            None, max_zones, False)

    return jax.vmap(one)(nodes, specs, scheduled, thresholds)


class FusedDecision(struct.PyTreeNode):
    """Compact decision tensors of one fused RunOnce step — the ONLY thing
    the host fetches on the fused hot path (docs/FUSED_LOOP.md). Everything
    here is O(G + NG + N) — a few KB at the 50k-pod shape cut — and rides a
    single bit-packed `ops/hostfetch.fetch_pytree` transfer. Host code
    consumes these as pure policy inputs: the verdict bitplane feeds the
    journal/shadow-audit surfaces, the estimate/score rows feed
    `options_from_scores` + the expander unchanged, and the utilization +
    drain verdict planes feed the scale-down planner's host screen."""

    verdict: jax.Array        # i32[G] pods of each group placed on existing
                              #   capacity (filter-out-schedulable verdicts)
    pending_after: jax.Array  # i32[G] pod counts still pending after the
                              #   filter placement (the scale-up problem)
    est_node_count: jax.Array # i32[NG] nodes each expansion option adds
    est_scheduled: jax.Array  # i32[NG, G] pods each option schedules
    scores: OptionScores      # expander inputs incl. helped_req f32[NG, R]
    util: jax.Array           # f32[N] post-placement node utilization
    drainable: jax.Array      # bool[N] scale-down candidate screen verdicts
    has_blocker: jax.Array    # bool[N] drain refused by a blocking pod
    alloc_after: jax.Array    # i32[N, R] post-placement allocations — seeds
                              #   the planner's host view so nodes_to_delete
                              #   needs no extra `nodes.alloc` fetch


class FusedResident(struct.PyTreeNode):
    """Device-resident outputs of the fused step: the post-placement world
    the rest of the loop continues from (snapshot.state.nodes/specs), the
    full drain sweep for the planner's confirmation subset gather, and the
    device verdict plane for shadow-audit sampling. Never fetched whole."""

    nodes: NodeTensors
    specs: PodGroupTensors
    removal: drain.RemovalResult  # C == N (all-nodes sweep)
    verdict: jax.Array            # i32[G] device copy of decision.verdict


@partial(jax.jit, static_argnames=("dims", "max_new_nodes",
                                   "max_pods_per_node", "chunk",
                                   "with_constraints"))
def run_once_fused(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    groups: NodeGroupTensors,
    limit_cap: jax.Array,       # i32[NG] host-composed scale-up limiter cap
    dims: Dims,
    max_new_nodes: int = 256,
    max_pods_per_node: int = 128,
    chunk: int = 32,
    planes=None,
    with_constraints: bool = False,
) -> tuple[FusedDecision, FusedResident]:
    """The whole control-loop device content as ONE compiled program.

    Composes the LIVE loop's three phases exactly as StaticAutoscaler runs
    them phased (not the `run_once_sim` research shape): filter-out-
    schedulable, then the scale-up estimate on the POST-placement world with
    the group caps pre-composed on host (`limit_cap` replicates
    BinpackingEstimator.combined_limit_vec — integer min of the static,
    cluster-capacity and SNG limiters), then the scale-down drain sweep over
    every node of the post-placement world. All integer/predicate arithmetic,
    so decisions are bit-identical to the phased path by construction
    (tests/test_fused_loop.py pins this per loop).

    Inputs are NOT donated: the resident planes live in the WorldStore and
    back the speculative next-loop dispatch (docs/FUSED_LOOP.md §speculation),
    so every input buffer outlives the call by design.

    The `jax.named_scope` blocks keep the three phases visible as separate
    ranges inside the single fused span on device profiles."""
    with jax.named_scope("fused_filter"):
        packed = schedule.schedule_pending_on_existing(
            nodes, specs, scheduled, planes=planes, max_zones=dims.max_zones,
            with_constraints=with_constraints)
        # identical arithmetic to TensorClusterSnapshot.apply_placement
        add = jnp.einsum("gn,gr->nr",
                         packed.placed.astype(jnp.int32), specs.req)
        nodes2 = nodes.replace(alloc=nodes.alloc + add)
        specs2 = specs.replace(
            count=jnp.maximum(specs.count - packed.placed.sum(axis=1), 0))
    with jax.named_scope("fused_scale_up"):
        capped = groups.replace(
            max_new=jnp.minimum(groups.max_new, limit_cap))
        est = estimate_all(specs2, capped, dims, max_new_nodes,
                           planes=planes, nodes=nodes2,
                           with_constraints=with_constraints)
        # scores on the UNCAPPED group tensors + post-placement specs —
        # exactly ScaleUpOrchestrator's phased score_options call
        sc = scoring.score_options(est, groups, specs=specs2)
    with jax.named_scope("fused_scale_down"):
        util = utilization.node_utilization(nodes2)
        removal = drain.simulate_removals(
            nodes2, specs2, scheduled,
            jnp.arange(nodes.n, dtype=jnp.int32),
            dest_allowed=jnp.ones((nodes.n,), bool),
            max_pods_per_node=max_pods_per_node, chunk=chunk,
            planes=planes, max_zones=dims.max_zones,
            with_constraints=with_constraints)
    decision = FusedDecision(
        verdict=packed.scheduled,
        pending_after=specs2.count,
        est_node_count=est.node_count,
        est_scheduled=est.scheduled,
        scores=sc,
        util=util,
        drainable=removal.drainable,
        has_blocker=removal.has_blocker,
        alloc_after=nodes2.alloc,
    )
    resident = FusedResident(nodes=nodes2, specs=specs2, removal=removal,
                             verdict=packed.scheduled)
    return decision, resident


@partial(jax.jit, static_argnames=("dims", "max_new_nodes", "strategy",
                                   "max_pods_per_node", "with_constraints"))
def run_once_sim(
    cluster: ClusterTensors,
    dims: Dims,
    max_new_nodes: int = 256,
    strategy: str = "least-waste",
    threshold: float = 0.5,
    max_pods_per_node: int = 128,
    with_constraints: bool = False,
) -> tuple[ScaleUpSim, ScaleDownSim]:
    """Full RunOnce simulation content in a single dispatch."""
    planes = cluster.planes if with_constraints else None
    up = scale_up_sim.__wrapped__(
        cluster.nodes, cluster.pending, cluster.scheduled, cluster.groups,
        dims, max_new_nodes, strategy, planes, with_constraints,
    )
    down = scale_down_sim.__wrapped__(
        cluster.nodes, cluster.pending, cluster.scheduled, threshold,
        max_pods_per_node, 32, planes, dims.max_zones, with_constraints,
    )
    return up, down
