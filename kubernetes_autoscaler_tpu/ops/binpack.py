"""Binpacking node estimation: all node groups' expansion options in one kernel.

Reference counterpart: BinpackingNodeEstimator.Estimate
(estimator/binpacking_estimator.go:102-161) — for ONE node group, simulate
adding template nodes one at a time and first-fit pods onto them, with an
arithmetic fastpath (:274-324). The orchestrator then loops node groups
serially (core/scaleup/orchestrator/orchestrator.go:379-414).

TPU re-design: all node groups are estimated simultaneously. Each group gets a
pool of `max_new` identical empty template bins; a vmapped first-fit scan
(ops/pack.py) packs every pod equivalence group into every pool at once. The
reference's fastpath extrapolation is unnecessary — the full pack is already
one fused device program — and its early-exit for pods that do not fit an
empty template node (:234) falls out of fit_count()==0.

Output shapes: NG node groups × G pod groups × M max-new-nodes (static).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_autoscaler_tpu.models.cluster_state import (
    Dims,
    NodeGroupTensors,
    PodGroupTensors,
)
from kubernetes_autoscaler_tpu.ops import predicates
from kubernetes_autoscaler_tpu.ops.pack import (
    _SHARD_MAP_KW,
    _shard_map,
    ffd_order,
    pack_groups,
)


def pack_backend() -> str:
    """Which FFD pack implementation estimate_all uses.

    'pallas' — one fused Mosaic kernel over (nodegroup, node-tile) with the
    free-capacity carry resident in VMEM (ops/pallas/pack_kernel.py); the
    measured-faster path on TPU. 'xla' — the lax.scan formulation (ops/pack.py),
    used on CPU where Pallas would run interpreted. Override with
    KA_TPU_PACK=xla|pallas.

    The choice is read at TRACE time: once a jitted caller (e.g.
    scale_up_sim) has compiled, changing the env var does not affect the
    cached executable — set it before the first call."""
    choice = os.environ.get("KA_TPU_PACK", "auto")
    if choice in ("xla", "pallas"):
        return choice
    return "pallas" if jax.default_backend() == "tpu" else "xla"


class EstimateResult(struct.PyTreeNode):
    node_count: jax.Array      # i32[NG] new nodes needed by each expansion option
    scheduled: jax.Array       # i32[NG, G] pods of group g the option schedules
    pods_per_node: jax.Array   # i32[NG, M] pods landing on each new node
    free_after: jax.Array      # i32[NG, M, R] leftover capacity (expander scoring input)
    template_fits: jax.Array   # bool[NG, G] group's exemplar passes template predicates


def _pack_option(cap_row, max_new, feas_col, max_new_nodes,
                 req, count, order, limit_one):
    """One node group's expansion option: pack every pending group into
    `max_new` empty template bins. The single body both the serial vmap and
    the shard_map estimator paths dispatch — their bit-identical contract
    lives here, not in two copies."""
    free0 = jnp.broadcast_to(cap_row[None, :],
                             (max_new_nodes, cap_row.shape[0]))
    bin_open = jnp.arange(max_new_nodes, dtype=jnp.int32) < max_new
    mask = feas_col[:, None] & bin_open[None, :]
    res = pack_groups(free0, mask, req, count, order, limit_one)
    pods_per_node = res.placed.sum(axis=0)
    node_cnt = (pods_per_node > 0).sum().astype(jnp.int32)
    return node_cnt, res.scheduled, pods_per_node, res.free_after


def _pack_options_pallas(cap, max_new, feas_gt, max_new_nodes,
                         req, count, order, limit_one):
    """All (local) expansion options as ONE fused Pallas launch: batch row =
    option, bins = `max_new_nodes` empty template nodes. The single pallas
    body both the single-device branch and the shard_map estimator shards
    dispatch — the kernel is collective-free, so running it per shard is
    exactly the single-device program on the shard's option slice."""
    from kubernetes_autoscaler_tpu.ops.pallas.pack_kernel import (
        pack_groups_batched,
    )

    ng, r = cap.shape
    free3 = jnp.broadcast_to(cap[:, None, :], (ng, max_new_nodes, r))
    bin_open = (jnp.arange(max_new_nodes, dtype=jnp.int32)[None, :]
                < max_new[:, None])
    mask3 = feas_gt.T[:, :, None] & bin_open[:, None, :]
    res = pack_groups_batched(free3, mask3, req, count, order, limit_one)
    pods_per_node = res.placed.sum(axis=1)
    node_count = (pods_per_node > 0).sum(axis=-1).astype(jnp.int32)
    return node_count, res.scheduled, pods_per_node, res.free_after


def estimate_all(
    specs: PodGroupTensors,
    groups: NodeGroupTensors,
    dims: Dims,
    max_new_nodes: int,
    planes=None,
    nodes=None,
    with_constraints: bool = False,
    mesh=None,
) -> EstimateResult:
    """Compute every node group's expansion option for the pending pod set.

    `with_constraints` (STATIC) routes through the topology-coupled pack:
    fresh template bins inherit the template's zone, so zone-level spread
    counts / affinity satisfaction from the REAL cluster (planes over `nodes`)
    carry into the estimate — the reference gets this for free because its
    estimator schedules against the forked real snapshot
    (binpacking_estimator.go:126).

    `mesh` shards the NG expansion options over PODS_AXIS (each option is an
    independent pack — no collectives), so a multi-chip mesh computes NG/P
    options per chip instead of replicating all of them; bit-identical to the
    unsharded path. Falls back silently when NG does not divide the axis or
    the constrained tier is active (its planes are node-indexed). The shard
    body honors pack_backend() exactly like the single-device path: with
    'pallas' each shard runs the fused Mosaic kernel over its option slice
    (pack_groups_batched is collective-free, so pallas-inside-shard_map is
    the same program per shard) — the scan-per-shard fallback that used to
    ignore KA_TPU_PACK on the mesh path is gone."""
    tmpl_nodes = groups.as_node_tensors(dims)
    # bool[G, NG]: placement-independent predicates vs each template
    # (capacity is enforced by the packer against the empty bins).
    mask_gt = predicates.feasibility_mask(tmpl_nodes, specs, check_resources=False)
    order = ffd_order(specs.req, specs.valid & (specs.count > 0))
    count = jnp.where(specs.valid, specs.count, 0)

    if with_constraints and planes is not None and nodes is not None:
        return _estimate_constrained(
            specs, groups, dims, max_new_nodes, planes, nodes,
            mask_gt, order, count)

    if mesh is not None:
        from kubernetes_autoscaler_tpu.parallel.mesh import PODS_AXIS

        if groups.ng % mesh.shape[PODS_AXIS] == 0:
            return _estimate_all_sharded(
                specs, groups, max_new_nodes, mask_gt, order, count, mesh)

    if pack_backend() == "pallas":
        node_count, scheduled, pods_per_node, free_after = _pack_options_pallas(
            groups.cap, groups.max_new, mask_gt, max_new_nodes,
            specs.req, count, order, specs.one_per_node())
        node_count = jnp.where(groups.valid, node_count, 0)
        return EstimateResult(
            node_count=node_count,
            scheduled=scheduled * groups.valid[:, None],
            pods_per_node=pods_per_node,
            free_after=free_after,
            template_fits=mask_gt.T,
        )

    def one_group(cap_row, max_new, feas_col):
        return _pack_option(cap_row, max_new, feas_col, max_new_nodes,
                            specs.req, count, order, specs.one_per_node())

    node_count, scheduled, pods_per_node, free_after = jax.vmap(one_group)(
        groups.cap, groups.max_new, mask_gt.T
    )
    node_count = jnp.where(groups.valid, node_count, 0)
    scheduled = scheduled * groups.valid[:, None]
    return EstimateResult(
        node_count=node_count,
        scheduled=scheduled,
        pods_per_node=pods_per_node,
        free_after=free_after,
        template_fits=mask_gt.T,
    )


def _estimate_all_sharded(
    specs: PodGroupTensors,
    groups: NodeGroupTensors,
    max_new_nodes: int,
    mask_gt: jax.Array,   # bool[G, NG]
    order: jax.Array,
    count: jax.Array,
    mesh,
) -> EstimateResult:
    """NG expansion options sharded over PODS_AXIS (no inter-shard traffic).

    Each device packs its slice of node groups against the full (replicated)
    pending set — the distributed form of the reference's per-nodegroup
    estimator goroutines (orchestrator.go:379), mapped onto the mesh axis the
    way Tesserae shards its machine axis. The NODES_AXIS of the mesh is left
    replicated here: template bins are per-option scratch, not cluster nodes.

    The shard body honors pack_backend(): 'pallas' runs the fused Mosaic
    kernel on each shard's option slice (options are independent, the kernel
    has no collectives — per shard it IS the single-device program), 'xla'
    keeps the lax.scan formulation. Both are byte-identical to the unsharded
    estimate (tests/test_sharded_estimator.py runs the suite under each)."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from kubernetes_autoscaler_tpu.parallel.mesh import PODS_AXIS

    limit_one = specs.one_per_node()
    use_pallas = pack_backend() == "pallas"

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(PODS_AXIS, None), P(PODS_AXIS), P(PODS_AXIS, None),
                  P(None, None), P(None), P(None), P(None)),
        out_specs=(P(PODS_AXIS), P(PODS_AXIS, None), P(PODS_AXIS, None),
                   P(PODS_AXIS, None, None)),
        **_SHARD_MAP_KW,
    )
    def run(cap_l, max_new_l, feas_l, req_r, count_r, order_r, limone_r):
        if use_pallas:
            return _pack_options_pallas(
                cap_l, max_new_l, feas_l.T, max_new_nodes,
                req_r, count_r, order_r, limone_r)

        def one_group(cap_row, max_new, feas_col):
            return _pack_option(cap_row, max_new, feas_col, max_new_nodes,
                                req_r, count_r, order_r, limone_r)

        return jax.vmap(one_group)(cap_l, max_new_l, feas_l)

    node_count, scheduled, pods_per_node, free_after = run(
        groups.cap, groups.max_new, mask_gt.T,
        specs.req, count, order, limit_one)
    node_count = jnp.where(groups.valid, node_count, 0)
    scheduled = scheduled * groups.valid[:, None]
    return EstimateResult(
        node_count=node_count,
        scheduled=scheduled,
        pods_per_node=pods_per_node,
        free_after=free_after,
        template_fits=mask_gt.T,
    )


def _estimate_constrained(
    specs: PodGroupTensors,
    groups: NodeGroupTensors,
    dims: Dims,
    max_new_nodes: int,
    planes,
    nodes,
    mask_gt: jax.Array,   # bool[G, NG]
    order: jax.Array,
    count: jax.Array,
) -> EstimateResult:
    """Topology-aware expansion options: every fresh bin carries the template's
    zone; resident-derived zone state comes from the real cluster."""
    from kubernetes_autoscaler_tpu.ops import constrained
    from kubernetes_autoscaler_tpu.ops.constrained import (
        BIG,
        GroupConstraints,
        zone_agg,
    )

    z_dim = dims.max_zones
    g = specs.g
    m = max_new_nodes

    # cluster-wide aggregates over REAL nodes
    sel_real = predicates.selector_match(nodes.label_hash, specs)       # [G, N]
    zval_real = nodes.zone_id > 0
    elig_host_real = sel_real & nodes.valid[None, :]
    s_elig_real = jnp.where((specs.spread_kind == 2)[:, None],
                            elig_host_real & zval_real[None, :], elig_host_real)
    cnt_zone = zone_agg(planes.spread_cnt, nodes.zone_id, z_dim)        # [G, Z]
    elig_zone = zone_agg(s_elig_real.astype(jnp.int32), nodes.zone_id, z_dim) > 0
    aff_zone = zone_agg(planes.aff_cnt, nodes.zone_id, z_dim)
    anti_zone = zone_agg(planes.anti_zone_cnt, nodes.zone_id, z_dim)
    min_host = jnp.min(
        jnp.where(s_elig_real, planes.spread_cnt, BIG), axis=1
    ).astype(jnp.int32)                                                 # [G]

    # template-level static gates (fresh node in the template's zone)
    tzc = jnp.clip(groups.zone_id, 0, z_dim - 1)                        # [NG]
    tval = groups.zone_id > 0
    anti_at_t = jnp.where(tval[None, :], anti_zone[:, tzc], 0)
    gate = anti_at_t == 0
    aff_ok_t = tval[None, :] & (aff_zone[:, tzc] > 0)
    need_static = (specs.aff_kind > 0) & ~specs.aff_self
    # hostname-affinity (kind 1) can never be resident-satisfied on a fresh
    # node; zone-affinity needs a matching resident in the template's zone
    aff_gate = jnp.where((specs.aff_kind == 2)[:, None], aff_ok_t, False)
    gate &= jnp.where(need_static[:, None], aff_gate, True)
    zone_kinds = (specs.spread_kind == 2) | (specs.aff_kind == 2)
    gate &= jnp.where(zone_kinds[:, None], tval[None, :],
                      jnp.ones_like(tval)[None, :])
    mask_gt = mask_gt & gate
    sel_t = predicates.selector_match(
        groups.as_node_tensors(dims).label_hash, specs)                 # [G, NG]

    limit_one = specs.one_per_node()

    def one_group(cap_row, max_new, feas_col, sel_col, tzc_s, tval_s):
        r = cap_row.shape[0]
        free0 = jnp.broadcast_to(cap_row[None, :], (m, r))
        bin_open = jnp.arange(m, dtype=jnp.int32) < max_new
        mask = feas_col[:, None] & bin_open[None, :]                    # [G, M]
        s_elig_bins = sel_col[:, None] & bin_open[None, :]
        s_elig_bins &= jnp.where((specs.spread_kind == 2)[:, None], tval_s, True)
        a_ok_bins = jnp.broadcast_to(
            (((specs.aff_kind == 2) & tval_s) & (aff_zone[:, tzc_s] > 0))[:, None],
            (g, m))
        elig_zone_bins = elig_zone | (
            (jnp.arange(z_dim) == tzc_s)[None, :]
            & (sel_col & tval_s)[:, None])
        cons = GroupConstraints(
            s_kind=specs.spread_kind, s_skew=specs.max_skew,
            s_self=specs.spread_self,
            s_cnt_node=jnp.zeros((g, m), jnp.int32),
            s_elig=s_elig_bins,
            a_kind=specs.aff_kind, a_self=specs.aff_self,
            a_any=specs.aff_match_any,
            a_ok_node=a_ok_bins,
            anti_self_zone=specs.anti_self_zone,
            cnt_zone_base=cnt_zone,
            elig_zone_base=elig_zone_bins,
            min_host_base=min_host,
            zone_cl=jnp.full((m,), tzc_s, jnp.int32),
            zone_valid=jnp.full((m,), tval_s, bool),
        )
        res = constrained.pack_groups_constrained(
            free0, mask, specs.req, count, order, limit_one, cons, z_dim)
        pods_per_node = res.placed.sum(axis=0)
        node_cnt = (pods_per_node > 0).sum().astype(jnp.int32)
        return node_cnt, res.scheduled, pods_per_node, res.free_after

    node_count, scheduled, pods_per_node, free_after = jax.vmap(one_group)(
        groups.cap, groups.max_new, mask_gt.T, sel_t.T, tzc, tval
    )
    node_count = jnp.where(groups.valid, node_count, 0)
    scheduled = scheduled * groups.valid[:, None]
    return EstimateResult(
        node_count=node_count,
        scheduled=scheduled,
        pods_per_node=pods_per_node,
        free_after=free_after,
        template_fits=mask_gt.T,
    )
