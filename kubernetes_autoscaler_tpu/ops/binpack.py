"""Binpacking node estimation: all node groups' expansion options in one kernel.

Reference counterpart: BinpackingNodeEstimator.Estimate
(estimator/binpacking_estimator.go:102-161) — for ONE node group, simulate
adding template nodes one at a time and first-fit pods onto them, with an
arithmetic fastpath (:274-324). The orchestrator then loops node groups
serially (core/scaleup/orchestrator/orchestrator.go:379-414).

TPU re-design: all node groups are estimated simultaneously. Each group gets a
pool of `max_new` identical empty template bins; a vmapped first-fit scan
(ops/pack.py) packs every pod equivalence group into every pool at once. The
reference's fastpath extrapolation is unnecessary — the full pack is already
one fused device program — and its early-exit for pods that do not fit an
empty template node (:234) falls out of fit_count()==0.

Output shapes: NG node groups × G pod groups × M max-new-nodes (static).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_autoscaler_tpu.models.cluster_state import (
    Dims,
    NodeGroupTensors,
    PodGroupTensors,
)
from kubernetes_autoscaler_tpu.ops import predicates
from kubernetes_autoscaler_tpu.ops.pack import ffd_order, pack_groups


def pack_backend() -> str:
    """Which FFD pack implementation estimate_all uses.

    'pallas' — one fused Mosaic kernel over (nodegroup, node-tile) with the
    free-capacity carry resident in VMEM (ops/pallas/pack_kernel.py); the
    measured-faster path on TPU. 'xla' — the lax.scan formulation (ops/pack.py),
    used on CPU where Pallas would run interpreted. Override with
    KA_TPU_PACK=xla|pallas.

    The choice is read at TRACE time: once a jitted caller (e.g.
    scale_up_sim) has compiled, changing the env var does not affect the
    cached executable — set it before the first call."""
    choice = os.environ.get("KA_TPU_PACK", "auto")
    if choice in ("xla", "pallas"):
        return choice
    return "pallas" if jax.default_backend() == "tpu" else "xla"


class EstimateResult(struct.PyTreeNode):
    node_count: jax.Array      # i32[NG] new nodes needed by each expansion option
    scheduled: jax.Array       # i32[NG, G] pods of group g the option schedules
    pods_per_node: jax.Array   # i32[NG, M] pods landing on each new node
    free_after: jax.Array      # i32[NG, M, R] leftover capacity (expander scoring input)
    template_fits: jax.Array   # bool[NG, G] group's exemplar passes template predicates


def estimate_all(
    specs: PodGroupTensors,
    groups: NodeGroupTensors,
    dims: Dims,
    max_new_nodes: int,
) -> EstimateResult:
    """Compute every node group's expansion option for the pending pod set."""
    tmpl_nodes = groups.as_node_tensors(dims)
    # bool[G, NG]: placement-independent predicates vs each template
    # (capacity is enforced by the packer against the empty bins).
    mask_gt = predicates.feasibility_mask(tmpl_nodes, specs, check_resources=False)
    order = ffd_order(specs.req, specs.valid & (specs.count > 0))
    count = jnp.where(specs.valid, specs.count, 0)

    if pack_backend() == "pallas":
        from kubernetes_autoscaler_tpu.ops.pallas.pack_kernel import (
            pack_groups_batched,
        )

        ng, r = groups.cap.shape
        free3 = jnp.broadcast_to(groups.cap[:, None, :], (ng, max_new_nodes, r))
        bin_open = jnp.arange(max_new_nodes, dtype=jnp.int32)[None, :] < groups.max_new[:, None]
        mask3 = mask_gt.T[:, :, None] & bin_open[:, None, :]
        res = pack_groups_batched(
            free3, mask3, specs.req, count, order, specs.one_per_node()
        )
        pods_per_node = res.placed.sum(axis=1)
        node_count = (pods_per_node > 0).sum(axis=-1).astype(jnp.int32)
        node_count = jnp.where(groups.valid, node_count, 0)
        return EstimateResult(
            node_count=node_count,
            scheduled=res.scheduled * groups.valid[:, None],
            pods_per_node=pods_per_node,
            free_after=res.free_after,
            template_fits=mask_gt.T,
        )

    def one_group(cap_row, max_new, feas_col):
        free0 = jnp.broadcast_to(cap_row[None, :], (max_new_nodes, cap_row.shape[0]))
        bin_open = jnp.arange(max_new_nodes, dtype=jnp.int32) < max_new
        mask = feas_col[:, None] & bin_open[None, :]
        res = pack_groups(
            free0, mask, specs.req, count, order, specs.one_per_node()
        )
        pods_per_node = res.placed.sum(axis=0)
        node_cnt = (pods_per_node > 0).sum().astype(jnp.int32)
        return node_cnt, res.scheduled, pods_per_node, res.free_after

    node_count, scheduled, pods_per_node, free_after = jax.vmap(one_group)(
        groups.cap, groups.max_new, mask_gt.T
    )
    node_count = jnp.where(groups.valid, node_count, 0)
    scheduled = scheduled * groups.valid[:, None]
    return EstimateResult(
        node_count=node_count,
        scheduled=scheduled,
        pods_per_node=pods_per_node,
        free_after=free_after,
        template_fits=mask_gt.T,
    )
