"""Binpacking node estimation: all node groups' expansion options in one kernel.

Reference counterpart: BinpackingNodeEstimator.Estimate
(estimator/binpacking_estimator.go:102-161) — for ONE node group, simulate
adding template nodes one at a time and first-fit pods onto them, with an
arithmetic fastpath (:274-324). The orchestrator then loops node groups
serially (core/scaleup/orchestrator/orchestrator.go:379-414).

TPU re-design: all node groups are estimated simultaneously. Each group gets a
pool of `max_new` identical empty template bins; a vmapped first-fit scan
(ops/pack.py) packs every pod equivalence group into every pool at once. The
reference's fastpath extrapolation is unnecessary — the full pack is already
one fused device program — and its early-exit for pods that do not fit an
empty template node (:234) falls out of fit_count()==0.

Output shapes: NG node groups × G pod groups × M max-new-nodes (static).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_autoscaler_tpu.models.cluster_state import (
    Dims,
    NodeGroupTensors,
    PodGroupTensors,
)
from kubernetes_autoscaler_tpu.ops import predicates
from kubernetes_autoscaler_tpu.ops.pack import ffd_order, pack_groups


class EstimateResult(struct.PyTreeNode):
    node_count: jax.Array      # i32[NG] new nodes needed by each expansion option
    scheduled: jax.Array       # i32[NG, G] pods of group g the option schedules
    pods_per_node: jax.Array   # i32[NG, M] pods landing on each new node
    free_after: jax.Array      # i32[NG, M, R] leftover capacity (expander scoring input)
    template_fits: jax.Array   # bool[NG, G] group's exemplar passes template predicates


def estimate_all(
    specs: PodGroupTensors,
    groups: NodeGroupTensors,
    dims: Dims,
    max_new_nodes: int,
) -> EstimateResult:
    """Compute every node group's expansion option for the pending pod set."""
    tmpl_nodes = groups.as_node_tensors(dims)
    # bool[G, NG]: placement-independent predicates vs each template
    # (capacity is enforced by the packer against the empty bins).
    mask_gt = predicates.feasibility_mask(tmpl_nodes, specs, check_resources=False)
    order = ffd_order(specs.req, specs.valid & (specs.count > 0))
    count = jnp.where(specs.valid, specs.count, 0)

    def one_group(cap_row, max_new, feas_col):
        free0 = jnp.broadcast_to(cap_row[None, :], (max_new_nodes, cap_row.shape[0]))
        bin_open = jnp.arange(max_new_nodes, dtype=jnp.int32) < max_new
        mask = feas_col[:, None] & bin_open[None, :]
        res = pack_groups(
            free0, mask, specs.req, count, order, specs.one_per_node()
        )
        pods_per_node = res.placed.sum(axis=0)
        node_cnt = (pods_per_node > 0).sum().astype(jnp.int32)
        return node_cnt, res.scheduled, pods_per_node, res.free_after

    node_count, scheduled, pods_per_node, free_after = jax.vmap(one_group)(
        groups.cap, groups.max_new, mask_gt.T
    )
    node_count = jnp.where(groups.valid, node_count, 0)
    scheduled = scheduled * groups.valid[:, None]
    return EstimateResult(
        node_count=node_count,
        scheduled=scheduled,
        pods_per_node=pods_per_node,
        free_after=free_after,
        template_fits=mask_gt.T,
    )
