"""Bit-packed boolean planes: 32 predicate verdicts per int32 lane word.

The simulator's boolean planes — the (pod-group × node) feasibility mask,
selector/taint match planes, wavefront plan masks — are semantically one bit
per pair but have been carried as bool (1 byte on the wire, 4 bytes as the
int32 mask blocks the Pallas kernels stage into VMEM). At bench shape
(64 groups × 5k nodes padded) that is megabytes of plane traffic per control
loop for kilobytes of information. This module is the single home for the
packed layout the PR 4 uint16 reason plane hinted at, taken to 1 bit:

  * `pack_group_bits` / `unpack_group_bits` — pack along the GROUP axis
    (axis -2): `bool[..., G, N] → int32[..., ceil(G/32), N]`. This is the
    layout the pack kernels consume: lane l of word row w carries groups
    `32w..32w+31` for node l, so a kernel resolving group g reads word row
    `g // 32` and shifts by `g % 32` — a dynamic-uniform scalar shift, no
    gather. VMEM mask footprint drops 32× vs the int32 staging blocks.
  * `pack_flat_bits` / `unpack_flat_bits_np` — pack a flat bool stream into
    int32 words (device) and unpack on the host (numpy). ops/hostfetch uses
    this pair so every bool leaf of a batched device→host fetch moves 1 bit
    per element instead of 1 byte (~8× fewer tunnel bytes).
  * numpy mirrors (`*_np`) for host-side consumers (wavefront planning,
    cache fingerprints, tests).

Contract: packing is little-endian within a word (bit j of word w is element
`32w + j`) on both device and host, and every pair round-trips bit-for-bit —
property-tested in tests/test_bitplane.py together with the
`feasible ⇔ reason_bits == 0` invariant on packed planes.

Words are int32, not uint32: the Pallas TPU toolchain and the existing
int32 fetch buffer class both prefer i32, and all bit arithmetic here uses
logical shifts, so the sign bit is just bit 31.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

WORD_BITS = 32


def words_for(n: int) -> int:
    """How many int32 words hold `n` bits."""
    return (n + WORD_BITS - 1) // WORD_BITS


def pack_group_bits(mask: jnp.ndarray) -> jnp.ndarray:
    """bool[..., G, N] → int32[..., ceil(G/32), N], packed along axis -2.

    Bit `g % 32` of word row `g // 32` is group g's verdict for each node
    lane. Padding rows are zero (infeasible), which is exactly what the
    pack kernels want for nonexistent groups."""
    m = jnp.asarray(mask).astype(bool)
    g = m.shape[-2]
    gw = words_for(g)
    pad = gw * WORD_BITS - g
    if pad:
        widths = [(0, 0)] * (m.ndim - 2) + [(0, pad), (0, 0)]
        m = jnp.pad(m, widths)
    m = m.reshape(*m.shape[:-2], gw, WORD_BITS, m.shape[-1]).astype(jnp.int32)
    shifts = jnp.arange(WORD_BITS, dtype=jnp.int32).reshape(
        (1,) * (m.ndim - 3) + (1, WORD_BITS, 1))
    words = jax.lax.shift_left(m, jnp.broadcast_to(shifts, m.shape))
    # sum ≡ or here: element j contributes only bit j, so there are no
    # carries — and sum-reductions lower everywhere (CPU XLA rejects an
    # s32 or-reduction inside spmd-partitioned programs)
    return jnp.sum(words, axis=m.ndim - 2, dtype=jnp.int32)


def unpack_group_bits(words: jnp.ndarray, g: int) -> jnp.ndarray:
    """Inverse of pack_group_bits: int32[..., Gw, N] → bool[..., G, N]."""
    w = jnp.asarray(words)
    shifts = jnp.arange(WORD_BITS, dtype=jnp.int32).reshape(
        (1,) * (w.ndim - 2) + (WORD_BITS, 1))
    bits = jax.lax.shift_right_logical(
        w[..., :, None, :], jnp.broadcast_to(shifts, (*w.shape[:-1],
                                                      WORD_BITS, w.shape[-1]))
    ) & 1
    full = bits.reshape(*w.shape[:-2], w.shape[-2] * WORD_BITS, w.shape[-1])
    return full[..., :g, :].astype(bool)


def pack_flat_bits(flat: jnp.ndarray) -> jnp.ndarray:
    """bool[n] → int32[ceil(n/32)] little-endian bit stream (device)."""
    m = jnp.asarray(flat).astype(bool).ravel()
    n = m.shape[0]
    nw = words_for(max(n, 1))
    pad = nw * WORD_BITS - n
    if pad:
        m = jnp.pad(m, (0, pad))
    m = m.reshape(nw, WORD_BITS).astype(jnp.int32)
    shifts = jnp.arange(WORD_BITS, dtype=jnp.int32)[None, :]
    words = jax.lax.shift_left(m, jnp.broadcast_to(shifts, m.shape))
    # sum ≡ or over disjoint bit positions (see pack_group_bits)
    return jnp.sum(words, axis=1, dtype=jnp.int32)


def unpack_flat_bits_np(words: np.ndarray, n: int) -> np.ndarray:
    """Host inverse of pack_flat_bits: int32 words → bool[n]."""
    w = np.asarray(words).astype(np.uint32)
    if n == 0:
        return np.zeros((0,), bool)
    bits = (w[:, None] >> np.arange(WORD_BITS, dtype=np.uint32)[None, :]) & 1
    return bits.reshape(-1)[:n].astype(bool)


def pack_group_bits_np(mask: np.ndarray) -> np.ndarray:
    """Host mirror of pack_group_bits (numpy, for plans/fingerprints)."""
    m = np.asarray(mask, bool)
    g = m.shape[-2]
    gw = words_for(g)
    pad = gw * WORD_BITS - g
    if pad:
        widths = [(0, 0)] * (m.ndim - 2) + [(0, pad), (0, 0)]
        m = np.pad(m, widths)
    m = m.reshape(*m.shape[:-2], gw, WORD_BITS, m.shape[-1]).astype(np.uint32)
    words = (m << np.arange(WORD_BITS, dtype=np.uint32)
             .reshape((1,) * (m.ndim - 3) + (1, WORD_BITS, 1)))
    return np.bitwise_or.reduce(words, axis=-2).astype(np.uint32).view(np.int32)


def unpack_group_bits_np(words: np.ndarray, g: int) -> np.ndarray:
    """Host inverse of pack_group_bits_np."""
    w = np.asarray(words).view(np.uint32)
    bits = (w[..., :, None, :]
            >> np.arange(WORD_BITS, dtype=np.uint32)
            .reshape((1,) * (w.ndim - 1) + (WORD_BITS, 1))) & 1
    full = bits.reshape(*w.shape[:-2], w.shape[-2] * WORD_BITS, w.shape[-1])
    return full[..., :g, :].astype(bool)
