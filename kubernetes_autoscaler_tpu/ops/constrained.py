"""Topology-coupled placement: spread skew + inter-pod affinity on device.

Reference counterpart: the vendored PodTopologySpread and InterPodAffinity
Filter plugins run per (pod, node) by SchedulerPluginRunner
(simulator/clustersnapshot/predicate/plugin_runner.go:54-143), with Reserve
side effects making each placement visible to the next pod's check. These are
the constraints SURVEY.md §7 calls out as breaking pods×nodes independence —
the FAQ.md:178 predicates that slow the reference ~3 orders of magnitude.

TPU re-design: constraint state lives in small per-domain count tensors.
Resident pods contribute via encode-time planes (models/cluster_state.py
AffinityPlanes); the group's OWN placements are tracked inside a bounded
`lax.while_loop` of placement WAVES:

  each wave computes, per domain, the remaining allowance
      spread:    min(count over eligible domains) + max_skew - count[d]
      anti-self: 1 - placed[d]
  clips the per-node first-fit counts by a segmented within-domain prefix sum,
  places globally in node-index order, updates the counts, and repeats until
  no progress. A fixed point of the wave loop admits exactly the placements a
  serial one-pod-at-a-time greedy (the reference's order) would admit; waves
  only batch the order.

Positive affinity satisfaction comes from the resident planes, plus — for a
self-matching selector — domains opened by the group's own placements, with
the first-pod exception (no match anywhere + self-match => first placement
unconstrained) bootstrapping a single seed node.

Everything is static-shaped; the wave count is capped (placements beyond the
cap are conservatively dropped — under-admission never fabricates capacity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from kubernetes_autoscaler_tpu.models.cluster_state import (
    AffinityPlanes,
    NodeTensors,
    PodGroupTensors,
)
from kubernetes_autoscaler_tpu.ops.pack import fit_count

# a CONCRETE numpy scalar, deliberately not jnp: this module is imported
# lazily from inside jitted bodies (ops/drain.py, ops/binpack.py), and a
# module-level jnp constant created mid-trace would be a leaked tracer that
# poisons every later trace (UnexpectedTracerError — surfaced when the
# native-tier tests came back online)
BIG = np.int32(1 << 28)
MAX_WAVES = 128


def _zcl(zone_id: jnp.ndarray, max_zones: int) -> jnp.ndarray:
    return jnp.clip(zone_id, 0, max_zones - 1)


def zone_onehot(zone_id: jnp.ndarray, max_zones: int) -> jnp.ndarray:
    """bool[N, Z]; nodes without a zone label (id 0) contribute to no zone."""
    oh = _zcl(zone_id, max_zones)[:, None] == jnp.arange(max_zones)[None, :]
    return oh & (zone_id > 0)[:, None]


def zone_agg(plane_gn: jnp.ndarray, zone_id: jnp.ndarray, max_zones: int) -> jnp.ndarray:
    """i32[G, Z]: per-zone totals of a per-node count plane."""
    oh = zone_onehot(zone_id, max_zones).astype(jnp.int32)
    return plane_gn.astype(jnp.int32) @ oh


def planes_static_mask(
    specs: PodGroupTensors,
    planes: AffinityPlanes,
    node_zone_id: jnp.ndarray,
    max_zones: int,
) -> jnp.ndarray:
    """bool[G, N]: the resident-derived (placement-independent) part of the
    topology constraints — anti-affinity blocks, non-self positive-affinity
    satisfaction, and domain-presence requirements."""
    n = node_zone_id.shape[0]
    zcl = _zcl(node_zone_id, max_zones)
    has_zone = (node_zone_id > 0)[None, :]
    anti_zone_z = zone_agg(planes.anti_zone_cnt, node_zone_id, max_zones)
    aff_zone_z = zone_agg(planes.aff_cnt, node_zone_id, max_zones)

    ok = planes.anti_host_cnt == 0
    ok &= ~(has_zone & (anti_zone_z[:, zcl] > 0))
    kind = specs.aff_kind
    aff_ok = jnp.where((kind == 1)[:, None], planes.aff_cnt > 0,
                       has_zone & (aff_zone_z[:, zcl] > 0))
    need_static = (kind > 0) & ~specs.aff_self
    ok &= jnp.where(need_static[:, None], aff_ok, True)
    # zone-domain constraints need the node to HAVE a zone
    zone_kinds = (specs.spread_kind == 2) | (kind == 2)
    ok &= jnp.where(zone_kinds[:, None], has_zone, jnp.ones((1, n), bool))
    return ok


class GroupConstraints(struct.PyTreeNode):
    """Per-group topology-constraint state over one destination node set.

    Built by `constraints_for_nodes` (real nodes) or inside the estimator
    (fresh template bins). Leading dim G everywhere; node planes [G, N]."""

    s_kind: jax.Array         # i32[G] 0 none / 1 hostname / 2 zone
    s_skew: jax.Array         # i32[G]
    s_self: jax.Array         # bool[G] own placements count toward spread
    s_cnt_node: jax.Array     # i32[G, N] resident matching counts per node
    s_elig: jax.Array         # bool[G, N] node's domain eligible for the min
    a_kind: jax.Array         # i32[G]
    a_self: jax.Array         # bool[G]
    a_any: jax.Array          # bool[G] >=1 resident matches (first-pod gate)
    a_ok_node: jax.Array      # bool[G, N] satisfied-by-residents per node
    anti_self_zone: jax.Array  # bool[G] at most one of the group per zone
    cnt_zone_base: jax.Array  # i32[G, Z] spread counts per zone (residents)
    elig_zone_base: jax.Array  # bool[G, Z] zones eligible for the min
    min_host_base: jax.Array  # i32[G] min hostname-domain count OUTSIDE this
                              # node set (BIG when the set covers the world)
    zone_cl: jax.Array        # i32[N] clipped zone id per node (shared)
    zone_valid: jax.Array     # bool[N] node has a zone label

    def is_constrained(self) -> jax.Array:
        return (self.s_kind > 0) | (self.a_kind > 0) | self.anti_self_zone


def constraints_for_nodes(
    specs: PodGroupTensors,
    planes: AffinityPlanes,
    nodes: NodeTensors,
    max_zones: int,
    sel_mask: jnp.ndarray | None = None,
) -> GroupConstraints:
    """Constraint state for packing onto the REAL node set."""
    from kubernetes_autoscaler_tpu.ops import predicates

    sel = (predicates.selector_match(nodes.label_hash, specs)
           if sel_mask is None else sel_mask)
    zval = nodes.zone_id > 0
    zcl = _zcl(nodes.zone_id, max_zones)
    elig_host = sel & nodes.valid[None, :]
    s_elig = jnp.where((specs.spread_kind == 2)[:, None],
                       elig_host & zval[None, :], elig_host)
    oh = zone_onehot(nodes.zone_id, max_zones).astype(jnp.int32)
    aff_zone_z = zone_agg(planes.aff_cnt, nodes.zone_id, max_zones)
    a_ok = jnp.where((specs.aff_kind == 1)[:, None], planes.aff_cnt > 0,
                     zval[None, :] & (aff_zone_z[:, zcl] > 0))
    g = specs.g
    return GroupConstraints(
        s_kind=specs.spread_kind, s_skew=specs.max_skew, s_self=specs.spread_self,
        s_cnt_node=planes.spread_cnt,
        s_elig=s_elig,
        a_kind=specs.aff_kind, a_self=specs.aff_self, a_any=specs.aff_match_any,
        a_ok_node=a_ok,
        anti_self_zone=specs.anti_self_zone,
        cnt_zone_base=planes.spread_cnt.astype(jnp.int32) @ oh,
        elig_zone_base=(s_elig.astype(jnp.int32) @ oh) > 0,
        min_host_base=jnp.full((g,), BIG, jnp.int32),
        zone_cl=zcl,
        zone_valid=zval,
    )


def place_group_constrained(
    free: jnp.ndarray,       # i32[N, R]
    feas_n: jnp.ndarray,     # bool[N] full feasibility for this group
    req: jnp.ndarray,        # i32[R]
    want: jnp.ndarray,       # i32 scalar
    limit_one: jnp.ndarray,  # bool scalar
    cons: GroupConstraints,  # gathered to one group (leading G dim removed)
    max_zones: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Wave-greedy placement of one constrained group; returns (free', place[N])."""
    n = feas_n.shape[0]
    oh = (cons.zone_cl[:, None] == jnp.arange(max_zones)[None, :]) & cons.zone_valid[:, None]
    ohi = oh.astype(jnp.int32)

    def cond(st):
        _, _, rem, it, done = st
        return (rem > 0) & ~done & (it < MAX_WAVES)

    def body(st):
        free_c, placed, rem, it, _ = st
        fit = jnp.minimum(fit_count(free_c, req), rem)
        fit = jnp.where(feas_n, fit, 0)
        fit = jnp.where(limit_one,
                        jnp.clip(1 - (placed > 0).astype(jnp.int32), 0, fit), fit)

        # --- positive affinity: resident-satisfied, self-opened, or bootstrap
        zone_placed = (placed[:, None] * ohi).sum(axis=0)          # i32[Z]
        open_host = placed > 0
        open_zone = cons.zone_valid & (zone_placed[cons.zone_cl] > 0)
        dom_open = jnp.where(cons.a_kind == 1, open_host, open_zone)
        aff_ok = cons.a_ok_node | (cons.a_self & dom_open)
        can = feas_n & (fit > 0)
        bootstrap = (cons.a_kind > 0) & cons.a_self & ~cons.a_any & (placed.sum() == 0)
        first = jnp.argmax(can)
        boot_mask = (jnp.arange(n) == first) & can.any()
        aff_ok = jnp.where(bootstrap, boot_mask,
                           jnp.where(cons.a_kind > 0, aff_ok, True))
        fit = jnp.where(aff_ok, fit, 0)

        # --- hostname-domain spread: per-node allowance
        cnt_n = cons.s_cnt_node + jnp.where(cons.s_self, placed, 0)
        elig_cnt = jnp.where(cons.s_elig, cnt_n, BIG)
        min_h = jnp.minimum(jnp.min(elig_cnt), cons.min_host_base)
        min_h = jnp.where(min_h >= BIG, 0, min_h)
        allow_h = jnp.clip(min_h + cons.s_skew - cnt_n, 0, None)
        fit = jnp.where(cons.s_kind == 1, jnp.minimum(fit, allow_h), fit)

        # --- zone-domain caps: spread allowance and/or anti-self 1-per-zone
        cnt_z = cons.cnt_zone_base + jnp.where(cons.s_self, zone_placed, 0)
        min_z = jnp.min(jnp.where(cons.elig_zone_base, cnt_z, BIG))
        min_z = jnp.where(min_z >= BIG, 0, min_z)
        allow_z = jnp.clip(min_z + cons.s_skew - cnt_z, 0, None)
        zone_cap = jnp.where(cons.s_kind == 2, allow_z, BIG)
        zone_cap = jnp.where(cons.anti_self_zone,
                             jnp.minimum(zone_cap, jnp.clip(1 - zone_placed, 0, None)),
                             zone_cap)
        # keyless nodes have no zone domain: uncapped by zone constraints
        # (zone-domain kinds already excluded them via the static mask)
        excl = ((jnp.cumsum(fit[:, None] * ohi, axis=0) - fit[:, None] * ohi) * ohi).sum(axis=1)
        capped = jnp.clip(zone_cap[cons.zone_cl] - excl, 0, None)
        fit_z = jnp.where(cons.zone_valid, jnp.minimum(fit, capped), fit)

        # --- global first-fit in node-index order
        cum = jnp.cumsum(fit_z)
        place = jnp.clip(rem - (cum - fit_z), 0, fit_z)
        n_placed = place.sum()
        return (free_c - place[:, None] * req[None, :], placed + place,
                rem - n_placed, it + 1, n_placed == 0)

    init = (free, jnp.zeros((n,), jnp.int32), want.astype(jnp.int32),
            jnp.int32(0), jnp.bool_(False))
    free_out, placed, _, _, _ = jax.lax.while_loop(cond, body, init)
    return free_out, placed


def pack_groups_constrained(
    free: jnp.ndarray,       # i32[N, R]
    mask: jnp.ndarray,       # bool[G, N] full static feasibility (planes included)
    req: jnp.ndarray,        # i32[G, R]
    count: jnp.ndarray,      # i32[G]
    order: jnp.ndarray,      # i32[G]
    limit_one: jnp.ndarray,  # bool[G]
    cons: GroupConstraints,
    max_zones: int,
):
    """First-fit-decreasing pack with topology-coupled groups handled by the
    wave placer; unconstrained groups take the one-shot fast path (identical
    to ops/pack.pack_groups)."""
    from kubernetes_autoscaler_tpu.ops.pack import PackResult

    is_con = cons.is_constrained()

    def step(free_c, g):
        reqg = req[g]

        def fast(fr):
            c = fit_count(fr, reqg)
            c = jnp.where(mask[g], c, 0)
            c = jnp.where(limit_one[g], jnp.minimum(c, 1), c)
            c = jnp.minimum(c, count[g])
            cum = jnp.cumsum(c)
            place = jnp.clip(count[g] - (cum - c), 0, c)
            return fr - place[:, None] * reqg[None, :], place

        def slow(fr):
            cg = GroupConstraints(
                s_kind=cons.s_kind[g], s_skew=cons.s_skew[g], s_self=cons.s_self[g],
                s_cnt_node=cons.s_cnt_node[g], s_elig=cons.s_elig[g],
                a_kind=cons.a_kind[g], a_self=cons.a_self[g], a_any=cons.a_any[g],
                a_ok_node=cons.a_ok_node[g],
                anti_self_zone=cons.anti_self_zone[g],
                cnt_zone_base=cons.cnt_zone_base[g],
                elig_zone_base=cons.elig_zone_base[g],
                min_host_base=cons.min_host_base[g],
                zone_cl=cons.zone_cl, zone_valid=cons.zone_valid,
            )
            return place_group_constrained(
                fr, mask[g], reqg, count[g], limit_one[g], cg, max_zones
            )

        free_c, place = jax.lax.cond(is_con[g], slow, fast, free_c)
        return free_c, place

    free_after, placed_in_order = jax.lax.scan(step, free, order)
    placed = jnp.zeros_like(placed_in_order).at[order].set(placed_in_order)
    return PackResult(free_after=free_after, placed=placed,
                      scheduled=placed.sum(axis=-1))
