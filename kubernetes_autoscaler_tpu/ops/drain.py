"""Batched node-removal (drain) simulation for scale-down.

Reference counterpart: RemovalSimulator.SimulateNodeRemoval
(simulator/cluster.go:131-172) — per candidate node, serially: collect movable
pods (GetPodsToMove), fork the snapshot, unschedule them, replace the node
with a tainted ghost, and try to re-place every pod via the HintingSimulator
(findPlaceFor :190-228), bounded by a wall-clock timeout and a candidate limit
(core/scaledown/planner/planner.go:297-309,385).

TPU re-design: ALL candidates are simulated in one device program, and the
serial depth per candidate is the number of DISTINCT POD SHAPES on the node
(compacted equivalence groups, K slots), not the pod count — the same
"shapes, not pods" principle as the FFD pack (ops/pack.py). Per candidate:

  1. its resident movable pods are aggregated into per-equivalence-group
     counts (a window gather + scatter-add),
  2. a K-step scan first-fits each group's count onto the destination nodes
     with the cumulative-fit trick (whole group placed in one step; pods
     spill across nodes in index order exactly as serial first-fit would),
  3. per-pod destinations are reconstructed from the groups' cumulative
     placement curves by binary search (a static K-loop of vectorized
     searchsorted calls — nothing of size pods x nodes is materialized).

Candidates are evaluated independently — equivalent to the reference's
fork/revert-per-candidate semantics — and vmapped in chunks so memory stays
bounded. A node carrying more than `max_groups_per_node` distinct shapes is
conservatively reported undrainable (n_failed counts the overflow pods).

The final *selection* of nodes to delete must not double-book destination
capacity across candidates; core/scaledown/planner.py re-simulates the
accepted candidates sequentially over the `feas` plane returned here —
through the native C++ pass (sidecar/native/kaconfirm.cc) in the common
case, or the Python group-block pass when PDBs/exact-oracle/atomic policy
needs per-move host decisions — mirroring the reference's commit-on-success
ordering (cluster.go:174-188).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_autoscaler_tpu.models.cluster_state import (
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
)
from kubernetes_autoscaler_tpu.ops.pack import fit_count
from kubernetes_autoscaler_tpu.ops.predicates import feasibility_mask
from kubernetes_autoscaler_tpu.ops.schedule import resident_group_counts


# ---- per-candidate drain failure reasons (the scale-down reason plane) ----
#
# Codes align with the reference unremovable enum (simulator/cluster.go:63-103)
# where the device sim can attribute the failure; TooManyPodShapes is this
# framework's own conservative K-overflow verdict (see simulate_removals).
DRAIN_OK = 0
DRAIN_BLOCKED_BY_POD = 1       # reference: BlockedByPod (drainability rules)
DRAIN_NO_PLACE_FOR_GROUP = 2   # reference: NoPlaceToMovePods; fail_group says
                               # WHICH pod shape found no destination
DRAIN_TOO_MANY_SHAPES = 3      # > max_groups_per_node distinct shapes resident
DRAIN_REASON_NAMES = {
    DRAIN_OK: "",
    DRAIN_BLOCKED_BY_POD: "BlockedByPod",
    DRAIN_NO_PLACE_FOR_GROUP: "NoPlaceToMovePods",
    DRAIN_TOO_MANY_SHAPES: "TooManyPodShapes",
}


class RemovalReasons(struct.PyTreeNode):
    """Explanation record per failed candidate (lazy second dispatch)."""

    reason: jax.Array      # i32[C] DRAIN_* code
    fail_group: jax.Array  # i32[C] first equivalence row with unplaced pods (-1)
    n_unplaced: jax.Array  # i32[C] movable pods with no destination


class RemovalResult(struct.PyTreeNode):
    drainable: jax.Array   # bool[C] all movable pods re-placed & no blockers
    has_blocker: jax.Array # bool[C] a pod forbids draining (drainability rules)
    n_moved: jax.Array     # i32[C] pods that found a new home
    n_failed: jax.Array    # i32[C] movable pods with no destination
    dest_node: jax.Array   # i32[C, MPN] destination node per pod slot (-1 = none)
    pod_slot: jax.Array    # i32[C, MPN] index into ScheduledPodTensors per slot
    feas: jax.Array        # bool[G, N] shared predicate plane (pre-capacity);
                           # lets the host's sequential confirmation pass
                           # re-pick destinations without re-running predicates


def fetch_result(r: "RemovalResult", phases=None) -> "RemovalResult":
    """Device→host with at most three transfers (ops/hostfetch) instead of
    one per leaf — each leaf transfer is a ~70 ms round trip over the TPU
    tunnel. The bool `feas` plane rides bit-packed (1 bit/verdict); `phases`
    turns on the moved/logical byte counters."""
    from kubernetes_autoscaler_tpu.ops.hostfetch import fetch_pytree

    return fetch_pytree(r, phases=phases)


def simulate_removals(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    candidates: jnp.ndarray,
    dest_allowed: jnp.ndarray,
    max_pods_per_node: int = 128,
    chunk: int = 256,
    max_groups_per_node: int = 16,
    planes=None,
    max_zones: int = 16,
    with_constraints: bool = False,
) -> RemovalResult:
    """Jit-cache-stable entry: candidates are padded to a chunk multiple on
    the host (index 0 is a harmless dummy; results are sliced back), so the
    compiled executable is reused as the candidate count drifts loop-to-loop."""
    c_total = int(candidates.shape[0])
    pad_c = max(((c_total + chunk - 1) // chunk) * chunk, chunk)
    cand_pad = jnp.concatenate([
        jnp.asarray(candidates, jnp.int32),
        jnp.zeros((pad_c - c_total,), jnp.int32),
    ])
    try:
        res = _simulate_removals_jit(
            nodes, specs, scheduled, cand_pad, jnp.asarray(dest_allowed),
            max_pods_per_node, chunk, max_groups_per_node, planes, max_zones,
            with_constraints)
    except ValueError as e:
        # jax 0.9.0 executable-cache corruption: after compiles at OTHER
        # shapes, a dispatch can nondeterministically pair the call with an
        # executable expecting one more (hoisted-constant) parameter —
        # "Execution supplied N buffers but compiled program expected N+1".
        # Avals/treedefs are verified identical across such calls, and a
        # fresh compile of the same call succeeds, so: drop the poisoned
        # entries and retry once.
        if "buffers but compiled program expected" not in str(e):
            raise
        _simulate_removals_jit.clear_cache()
        res = _simulate_removals_jit(
            nodes, specs, scheduled, cand_pad, jnp.asarray(dest_allowed),
            max_pods_per_node, chunk, max_groups_per_node, planes, max_zones,
            with_constraints)
    return RemovalResult(
        drainable=res.drainable[:c_total],
        has_blocker=res.has_blocker[:c_total],
        n_moved=res.n_moved[:c_total],
        n_failed=res.n_failed[:c_total],
        dest_node=res.dest_node[:c_total],
        pod_slot=res.pod_slot[:c_total],
        feas=res.feas,
    )


@partial(jax.jit, static_argnames=("max_pods_per_node", "chunk",
                                   "max_groups_per_node", "max_zones",
                                   "with_constraints"))
def _simulate_removals_jit(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    candidates: jnp.ndarray,     # i32[C] node indices to try draining
    dest_allowed: jnp.ndarray,   # bool[N] allowed destination nodes
    max_pods_per_node: int = 128,
    chunk: int = 256,
    max_groups_per_node: int = 16,
    planes=None,
    max_zones: int = 16,
    with_constraints: bool = False,
) -> RemovalResult:
    """Simulate removing every candidate node independently.

    `with_constraints` (STATIC) makes re-placement topology-aware: the
    candidate's own residents are subtracted from the zone-level constraint
    state (the analog of the reference's ghost-node trick,
    simulator/cluster.go:230-238 — the drained node stops being a domain
    member before its pods are re-placed), and constrained groups re-place
    through the wave placer (ops/constrained.py)."""
    n = nodes.n
    g_total = specs.g
    mpn = max_pods_per_node
    kk = max_groups_per_node

    # Shared predicate plane: bool[G, N], placement-independent (capacity is
    # checked against the live free tensor during per-candidate packing).
    feas_gn = feasibility_mask(nodes, specs, check_resources=False)
    resident = resident_group_counts(scheduled, g_total, n)
    anti_block = specs.anti_affinity_self[:, None] & (resident > 0)
    feas_gn = feas_gn & ~anti_block
    limit_g = specs.one_per_node()   # bool[G]
    free0 = nodes.free()

    if with_constraints and planes is not None:
        from kubernetes_autoscaler_tpu.ops import constrained as con
        from kubernetes_autoscaler_tpu.ops import predicates as preds

        z_dim = max_zones
        zval = nodes.zone_id > 0
        zcl_n = jnp.clip(nodes.zone_id, 0, z_dim - 1)
        # host-level (candidate-independent) gates
        feas_gn &= planes.anti_host_cnt == 0
        feas_gn &= jnp.where(((specs.aff_kind == 1) & ~specs.aff_self)[:, None],
                             planes.aff_cnt > 0, True)
        zone_kinds = (specs.spread_kind == 2) | (specs.aff_kind == 2)
        feas_gn &= jnp.where(zone_kinds[:, None], zval[None, :],
                             jnp.ones((1, n), bool))
        # zone-level aggregates, adjusted per candidate below
        anti_zone_z = con.zone_agg(planes.anti_zone_cnt, nodes.zone_id, z_dim)
        aff_zone_z = con.zone_agg(planes.aff_cnt, nodes.zone_id, z_dim)
        cnt_zone = con.zone_agg(planes.spread_cnt, nodes.zone_id, z_dim)
        sel_real = preds.selector_match(nodes.label_hash, specs)
        elig_host = sel_real & nodes.valid[None, :]
        s_elig = jnp.where((specs.spread_kind == 2)[:, None],
                           elig_host & zval[None, :], elig_host)
        elig_zone_cnt = con.zone_agg(s_elig.astype(jnp.int32), nodes.zone_id, z_dim)
        is_con = ((specs.spread_kind > 0) | (specs.aff_kind > 0)
                  | specs.anti_self_zone)

    # Sort resident pods by node so each candidate's pods are one contiguous
    # window — the device-side equivalent of NodeInfo.Pods lists.
    sort_key = jnp.where(scheduled.valid, scheduled.node_idx, n + 1)
    pod_order = jnp.argsort(sort_key).astype(jnp.int32)          # i32[Ps]
    sorted_nodes = sort_key[pod_order]
    starts = jnp.searchsorted(sorted_nodes, jnp.arange(n)).astype(jnp.int32)

    pad_order = jnp.concatenate(
        [pod_order, jnp.full((mpn,), -1, jnp.int32)]
    )

    def one_candidate(c):
        start = starts[c]
        slots = jax.lax.dynamic_slice(pad_order, (start,), (mpn,))   # i32[MPN]
        safe = jnp.maximum(slots, 0)
        on_c = (slots >= 0) & (scheduled.node_idx[safe] == c) & scheduled.valid[safe]
        movable = on_c & scheduled.movable[safe]
        blocker = (on_c & scheduled.blocks[safe]).any()

        # --- compact this node's movable pods into K group slots ---
        gref = jnp.where(movable, scheduled.group_ref[safe], g_total)  # sentinel
        counts = jnp.zeros((g_total + 1,), jnp.int32).at[gref].add(
            movable.astype(jnp.int32))
        nz = counts[:g_total] > 0                                   # bool[G]
        rank = jnp.cumsum(nz) - 1                                   # i32[G]
        compact_of_g = jnp.where(nz & (rank < kk), rank, kk)        # [G] -> K slot
        gidx = (jnp.zeros((kk + 1,), jnp.int32)
                .at[compact_of_g].set(jnp.arange(g_total, dtype=jnp.int32))[:kk])
        filled = jnp.arange(kk) < jnp.minimum(nz.sum(), kk)
        cnt_k = jnp.where(filled, counts[:g_total][gidx], 0)        # i32[K]
        # groups beyond K never enter the scan -> their pods stay unplaced
        # and surface in n_failed (conservatively undrainable)

        dest = dest_allowed & nodes.valid & nodes.ready & nodes.schedulable
        dest = dest & (jnp.arange(n) != c)

        if with_constraints and planes is not None:
            # ghost-node analog: the candidate's residents leave its domain
            # before re-placement — subtract its column from the zone state
            zc = zcl_n[c]
            dz = ((jnp.arange(z_dim) == zc) & zval[c]).astype(jnp.int32)  # [Z]
            anti_adj = anti_zone_z - dz[None, :] * planes.anti_zone_cnt[:, c][:, None]
            aff_adj = aff_zone_z - dz[None, :] * planes.aff_cnt[:, c][:, None]
            cnt_adj = cnt_zone - dz[None, :] * planes.spread_cnt[:, c][:, None]
            elig_adj = (elig_zone_cnt
                        - dz[None, :] * s_elig[:, c].astype(jnp.int32)[:, None]) > 0
            zone_gate = ~(zval[None, :] & (anti_adj[:, zcl_n] > 0))      # [G, N]
            aff2 = (specs.aff_kind == 2) & ~specs.aff_self
            zone_gate &= jnp.where(aff2[:, None],
                                   zval[None, :] & (aff_adj[:, zcl_n] > 0), True)
            s_elig_c = s_elig & (jnp.arange(n) != c)[None, :]

            def step(free_c, j):
                gi = gidx[j]
                want = cnt_k[j]
                reqg = specs.req[gi]
                feas_row = feas_gn[gi] & zone_gate[gi] & dest

                def fast(fr):
                    fit = fit_count(fr, reqg)
                    fit = jnp.where(feas_row, fit, 0)
                    fit = jnp.where(limit_g[gi], jnp.minimum(fit, 1), fit)
                    fit = jnp.minimum(fit, want)
                    cum = jnp.cumsum(fit)
                    place = jnp.clip(want - (cum - fit), 0, fit)
                    return fr - place[:, None] * reqg[None, :], place

                def slow(fr):
                    cg = con.GroupConstraints(
                        s_kind=specs.spread_kind[gi], s_skew=specs.max_skew[gi],
                        s_self=specs.spread_self[gi],
                        s_cnt_node=planes.spread_cnt[gi],
                        s_elig=s_elig_c[gi],
                        a_kind=specs.aff_kind[gi], a_self=specs.aff_self[gi],
                        a_any=specs.aff_match_any[gi],
                        a_ok_node=jnp.where(
                            specs.aff_kind[gi] == 1, planes.aff_cnt[gi] > 0,
                            zval & (aff_adj[gi, zcl_n] > 0)),
                        anti_self_zone=specs.anti_self_zone[gi],
                        cnt_zone_base=cnt_adj[gi],
                        elig_zone_base=elig_adj[gi],
                        min_host_base=con.BIG,
                        zone_cl=zcl_n, zone_valid=zval,
                    )
                    return con.place_group_constrained(
                        fr, feas_row, reqg, want, limit_g[gi], cg, z_dim)

                free_c, place = jax.lax.cond(is_con[gi], slow, fast, free_c)
                return free_c, (place.sum(), jnp.cumsum(place))
        else:
            # --- K-step first-fit of whole groups onto destinations ---
            def step(free_c, j):
                gi = gidx[j]
                want = cnt_k[j]
                fit = fit_count(free_c, specs.req[gi])
                fit = jnp.where(feas_gn[gi] & dest, fit, 0)
                fit = jnp.where(limit_g[gi], jnp.minimum(fit, 1), fit)
                fit = jnp.minimum(fit, want)
                cum = jnp.cumsum(fit)
                place = jnp.clip(want - (cum - fit), 0, fit)
                free_c = free_c - place[:, None] * specs.req[gi][None, :]
                return free_c, (place.sum(), jnp.cumsum(place))

        _, (placed_k, cumplace_k) = jax.lax.scan(
            step, free0, jnp.arange(kk, dtype=jnp.int32))
        n_moved = placed_k.sum().astype(jnp.int32)
        n_failed = (movable.sum() - n_moved).astype(jnp.int32)
        drainable = (~blocker) & (n_failed == 0)

        # --- reconstruct per-pod destinations from the placement curves ---
        # rank of each window slot among same-group movable slots before it
        same = (gref[:, None] == gref[None, :]) & movable[:, None] & movable[None, :]
        before = jnp.sum(jnp.tril(same, -1), axis=1).astype(jnp.int32)  # [MPN]
        j_of_slot = jnp.concatenate(
            [compact_of_g, jnp.full((1,), kk, jnp.int32)])[gref]        # [MPN]
        dests = jnp.full((mpn,), -1, jnp.int32)
        for j in range(kk):  # static unroll: vectorized searchsorted per slot
            d_j = jnp.searchsorted(cumplace_k[j], before + 1).astype(jnp.int32)
            hit = movable & (j_of_slot == j) & (before < placed_k[j])
            dests = jnp.where(hit, d_j, dests)
        return drainable, blocker, n_moved, n_failed, dests, jnp.where(on_c, safe, -1)

    c_total = candidates.shape[0]
    # chunk stays FIXED (not fitted to c_total): padded shapes quantize to
    # chunk multiples so the jit cache hits as the candidate count drifts
    # loop-to-loop
    pad_c = ((c_total + chunk - 1) // chunk) * chunk
    cand_pad = jnp.concatenate(
        [candidates, jnp.zeros((pad_c - c_total,), jnp.int32)]
    ).reshape(-1, chunk)

    outs = jax.lax.map(jax.vmap(one_candidate), cand_pad)
    drainable, blocker, n_moved, n_failed, dests, pod_slot = jax.tree_util.tree_map(
        lambda x: x.reshape((pad_c,) + x.shape[2:])[:c_total], outs
    )
    return RemovalResult(
        drainable=drainable,
        has_blocker=blocker,
        n_moved=n_moved,
        n_failed=n_failed,
        dest_node=dests,
        pod_slot=pod_slot,
        feas=feas_gn,
    )


def failure_reasons(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    candidates: jnp.ndarray,        # i32[C] FAILED candidate node indices
    dest_allowed: jnp.ndarray,
    max_pods_per_node: int = 128,
    chunk: int = 256,
    max_groups_per_node: int = 16,
) -> RemovalReasons:
    """The lazy drain reason pass: re-run the per-candidate group compaction +
    first-fit for the candidates the main sweep reported undrainable, and say
    WHY — blocked-by-pod, no-place-for-pod-group-k, or shape overflow.

    Off the hot path by contract: the planner dispatches this only when some
    candidate failed (counted under `reason_extraction_dispatches`; a loop
    where every candidate drains performs zero extra dispatches), and only
    over the failed subset (padded to a chunk multiple so the executable is
    reused as the failure count drifts). The pass is EXPLANATORY, not a
    verdict: it runs the plain-capacity re-placement, so a candidate that
    failed only on topology constraints (with_constraints sims) comes back
    DRAIN_OK and the caller keeps the generic NoPlaceToMovePods reason —
    drainability truth always stays with `simulate_removals`."""
    c_total = int(candidates.shape[0])
    pad_c = max(((c_total + chunk - 1) // chunk) * chunk, chunk)
    cand_pad = jnp.concatenate([
        jnp.asarray(candidates, jnp.int32),
        jnp.zeros((pad_c - c_total,), jnp.int32),
    ])
    res = _failure_reasons_jit(
        nodes, specs, scheduled, cand_pad, jnp.asarray(dest_allowed),
        max_pods_per_node, chunk, max_groups_per_node)
    return RemovalReasons(
        reason=res.reason[:c_total],
        fail_group=res.fail_group[:c_total],
        n_unplaced=res.n_unplaced[:c_total],
    )


@partial(jax.jit, static_argnames=("max_pods_per_node", "chunk",
                                   "max_groups_per_node"))
def _failure_reasons_jit(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    candidates: jnp.ndarray,
    dest_allowed: jnp.ndarray,
    max_pods_per_node: int = 128,
    chunk: int = 256,
    max_groups_per_node: int = 16,
) -> RemovalReasons:
    """Trimmed sibling of `_simulate_removals_jit`: same window gather, group
    compaction and K-step first-fit, but no per-pod destination
    reconstruction (the MPN-quadratic part) — only the failure attribution."""
    n = nodes.n
    g_total = specs.g
    mpn = max_pods_per_node
    kk = max_groups_per_node

    feas_gn = feasibility_mask(nodes, specs, check_resources=False)
    resident = resident_group_counts(scheduled, g_total, n)
    feas_gn = feas_gn & ~(specs.anti_affinity_self[:, None] & (resident > 0))
    limit_g = specs.one_per_node()
    free0 = nodes.free()

    sort_key = jnp.where(scheduled.valid, scheduled.node_idx, n + 1)
    pod_order = jnp.argsort(sort_key).astype(jnp.int32)
    sorted_nodes = sort_key[pod_order]
    starts = jnp.searchsorted(sorted_nodes, jnp.arange(n)).astype(jnp.int32)
    pad_order = jnp.concatenate([pod_order, jnp.full((mpn,), -1, jnp.int32)])

    def one_candidate(c):
        start = starts[c]
        slots = jax.lax.dynamic_slice(pad_order, (start,), (mpn,))
        safe = jnp.maximum(slots, 0)
        on_c = (slots >= 0) & (scheduled.node_idx[safe] == c) & scheduled.valid[safe]
        movable = on_c & scheduled.movable[safe]
        blocker = (on_c & scheduled.blocks[safe]).any()

        gref = jnp.where(movable, scheduled.group_ref[safe], g_total)
        counts = jnp.zeros((g_total + 1,), jnp.int32).at[gref].add(
            movable.astype(jnp.int32))
        nz = counts[:g_total] > 0
        rank = jnp.cumsum(nz) - 1
        compact_of_g = jnp.where(nz & (rank < kk), rank, kk)
        gidx = (jnp.zeros((kk + 1,), jnp.int32)
                .at[compact_of_g].set(jnp.arange(g_total, dtype=jnp.int32))[:kk])
        filled = jnp.arange(kk) < jnp.minimum(nz.sum(), kk)
        cnt_k = jnp.where(filled, counts[:g_total][gidx], 0)
        overflow = nz.sum() > kk

        dest = dest_allowed & nodes.valid & nodes.ready & nodes.schedulable
        dest = dest & (jnp.arange(n) != c)

        def step(free_c, j):
            gi = gidx[j]
            want = cnt_k[j]
            fit = fit_count(free_c, specs.req[gi])
            fit = jnp.where(feas_gn[gi] & dest, fit, 0)
            fit = jnp.where(limit_g[gi], jnp.minimum(fit, 1), fit)
            fit = jnp.minimum(fit, want)
            cum = jnp.cumsum(fit)
            place = jnp.clip(want - (cum - fit), 0, fit)
            free_c = free_c - place[:, None] * specs.req[gi][None, :]
            return free_c, place.sum()

        _, placed_k = jax.lax.scan(step, free0,
                                   jnp.arange(kk, dtype=jnp.int32))
        unplaced_k = cnt_k - placed_k
        scan_fail = (unplaced_k > 0).any()
        first_j = jnp.argmax(unplaced_k > 0)
        fail_group = jnp.where(scan_fail, gidx[first_j], -1)
        n_unplaced = (movable.sum() - placed_k.sum()).astype(jnp.int32)
        reason = jnp.where(
            blocker, DRAIN_BLOCKED_BY_POD,
            jnp.where(scan_fail, DRAIN_NO_PLACE_FOR_GROUP,
                      jnp.where(overflow, DRAIN_TOO_MANY_SHAPES, DRAIN_OK)))
        return (reason.astype(jnp.int32), fail_group.astype(jnp.int32),
                n_unplaced)

    c_total = candidates.shape[0]
    pad_c = ((c_total + chunk - 1) // chunk) * chunk
    cand_pad = jnp.concatenate(
        [candidates, jnp.zeros((pad_c - c_total,), jnp.int32)]
    ).reshape(-1, chunk)
    outs = jax.lax.map(jax.vmap(one_candidate), cand_pad)
    reason, fail_group, n_unplaced = jax.tree_util.tree_map(
        lambda x: x.reshape((pad_c,) + x.shape[2:])[:c_total], outs)
    return RemovalReasons(reason=reason, fail_group=fail_group,
                          n_unplaced=n_unplaced)

