"""Batched node-removal (drain) simulation for scale-down.

Reference counterpart: RemovalSimulator.SimulateNodeRemoval
(simulator/cluster.go:131-172) — per candidate node, serially: collect movable
pods (GetPodsToMove), fork the snapshot, unschedule them, replace the node
with a tainted ghost, and try to re-place every pod via the HintingSimulator
(findPlaceFor :190-228), bounded by a wall-clock timeout and a candidate limit
(core/scaledown/planner/planner.go:297-309,385).

TPU re-design: ALL candidates are simulated in one device program. For each
candidate, its resident movable pods are first-fit re-placed onto the
destination nodes (excluding the candidate itself) against a shared
group×node predicate plane computed once. Candidates are evaluated
independently — equivalent to the reference's fork/revert-per-candidate
semantics — and vmapped in chunks so memory stays bounded; no timeout or
candidate cap is needed because the whole sweep is O(ms).

The final *selection* of nodes to delete must not double-book destination
capacity across candidates; core/scaledown/planner.py does a greedy host-side
confirmation pass over the (cheap, already-computed) per-candidate results,
mirroring the reference's commit-on-success ordering (cluster.go:174-188).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_autoscaler_tpu.models.cluster_state import (
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
)
from kubernetes_autoscaler_tpu.ops import predicates
from kubernetes_autoscaler_tpu.ops.schedule import resident_group_counts


class RemovalResult(struct.PyTreeNode):
    drainable: jax.Array   # bool[C] all movable pods re-placed & no blockers
    has_blocker: jax.Array # bool[C] a pod forbids draining (drainability rules)
    n_moved: jax.Array     # i32[C] pods that found a new home
    n_failed: jax.Array    # i32[C] movable pods with no destination
    dest_node: jax.Array   # i32[C, MPN] destination node per pod slot (-1 = none)
    pod_slot: jax.Array    # i32[C, MPN] index into ScheduledPodTensors per slot
    feas: jax.Array        # bool[G, N] shared predicate plane (pre-capacity);
                           # lets the host's sequential confirmation pass
                           # re-pick destinations without re-running predicates


def simulate_removals(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    candidates: jnp.ndarray,     # i32[C] node indices to try draining
    dest_allowed: jnp.ndarray,   # bool[N] allowed destination nodes
    max_pods_per_node: int = 128,
    chunk: int = 32,
) -> RemovalResult:
    """Simulate removing every candidate node independently."""
    n = nodes.n
    mpn = max_pods_per_node

    # Shared predicate plane: bool[G, N], placement-independent (capacity is
    # checked against the live free tensor during per-candidate packing).
    feas_gn = predicates.feasibility_mask(nodes, specs, check_resources=False)
    resident = resident_group_counts(scheduled, specs.g, n)
    anti_block = specs.anti_affinity_self[:, None] & (resident > 0)
    feas_gn = feas_gn & ~anti_block
    limit_g = specs.one_per_node()   # bool[G]
    free0 = nodes.free()
    ring_k = 4                       # one-per-node groups landing on one node during one drain

    # Sort resident pods by node so each candidate's pods are one contiguous
    # window — the device-side equivalent of NodeInfo.Pods lists.
    sort_key = jnp.where(scheduled.valid, scheduled.node_idx, n + 1)
    pod_order = jnp.argsort(sort_key).astype(jnp.int32)          # i32[Ps]
    sorted_nodes = sort_key[pod_order]
    starts = jnp.searchsorted(sorted_nodes, jnp.arange(n)).astype(jnp.int32)

    pad_order = jnp.concatenate(
        [pod_order, jnp.full((mpn,), -1, jnp.int32)]
    )

    def one_candidate(c):
        start = starts[c]
        slots = jax.lax.dynamic_slice(pad_order, (start,), (mpn,))   # i32[MPN]
        safe = jnp.maximum(slots, 0)
        on_c = (slots >= 0) & (scheduled.node_idx[safe] == c) & scheduled.valid[safe]
        movable = on_c & scheduled.movable[safe]
        blocker = (on_c & scheduled.blocks[safe]).any()

        dest = dest_allowed & nodes.valid & nodes.ready & nodes.schedulable
        dest = dest & (jnp.arange(n) != c)

        def place_pod(carry, slot_and_active):
            free, ring, ring_cnt = carry
            slot, active = slot_and_active
            req = scheduled.req[slot]
            gref = scheduled.group_ref[slot]
            is_lim = limit_g[gref]
            fits = (req[None, :] <= free).all(axis=-1)
            # One-per-node groups: forbid nodes that already received a sibling
            # during THIS candidate's drain (the pre-drain resident check is in
            # feas_gn; this covers intra-drain staleness).
            sib_here = (ring == gref).any(axis=-1)
            ok = feas_gn[gref] & dest & fits & ~(is_lim & sib_here)
            found = ok.any() & active
            idx = jnp.argmax(ok)  # first feasible node in index order
            upd = jnp.where(found, 1, 0)
            free = free.at[idx].add(-req * upd)
            mark = found & is_lim
            pos = ring_cnt[idx] % ring_k
            ring = ring.at[idx, pos].set(jnp.where(mark, gref, ring[idx, pos]))
            ring_cnt = ring_cnt.at[idx].add(jnp.where(mark, 1, 0))
            return (free, ring, ring_cnt), jnp.where(found, idx, -1)

        ring0 = jnp.full((n, ring_k), -1, jnp.int32)
        cnt0 = jnp.zeros((n,), jnp.int32)
        _, dests = jax.lax.scan(place_pod, (free0, ring0, cnt0), (safe, movable))
        n_moved = (dests >= 0).sum().astype(jnp.int32)
        n_failed = (movable.sum() - n_moved).astype(jnp.int32)
        drainable = (~blocker) & (n_failed == 0)
        return drainable, blocker, n_moved, n_failed, dests, jnp.where(on_c, safe, -1)

    c_total = candidates.shape[0]
    pad_c = ((c_total + chunk - 1) // chunk) * chunk
    cand_pad = jnp.concatenate(
        [candidates, jnp.zeros((pad_c - c_total,), jnp.int32)]
    ).reshape(-1, chunk)

    outs = jax.lax.map(jax.vmap(one_candidate), cand_pad)
    drainable, blocker, n_moved, n_failed, dests, pod_slot = jax.tree_util.tree_map(
        lambda x: x.reshape((pad_c,) + x.shape[2:])[:c_total], outs
    )
    return RemovalResult(
        drainable=drainable,
        has_blocker=blocker,
        n_moved=n_moved,
        n_failed=n_failed,
        dest_node=dests,
        pod_slot=pod_slot,
        feas=feas_gn,
    )
