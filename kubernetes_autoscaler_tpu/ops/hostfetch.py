"""Single-shot device→host fetch for result pytrees, with bit-packed bools
and an async double-buffered variant.

jax.device_get walks pytree leaves one transfer each; over the TPU tunnel
every transfer is a ~70 ms round trip, so a 7-leaf result costs ~0.5 s per
control loop. `fetch_pytree` concatenates the leaves into at most three
dtype-class buffers ON DEVICE and reconstructs the exact original structure,
shapes and dtypes on the host — three transfers worst case, independent of
leaf count.

Boolean leaves — the big predicate planes — are BIT-PACKED into int32 words
(ops/bitplane.pack_flat_bits) instead of widened to uint8: one bit per
verdict on the wire, ~8× fewer tunnel bytes for a pure-bool fetch. The
packer is one jitted function whose cache keys on the pytree
structure+shapes, so there is nothing to keep in sync when a result struct
gains or reorders fields.

Transfer accounting: pass `phases` (a metrics/phases.PhaseStats) and every
fetch bumps `batched_fetch_bytes_moved` (actual buffer bytes shipped) and
`batched_fetch_bytes_logical` (what the pre-bit-packing layout — bool→uint8,
int→int32, float→float32 — would have shipped). The ratio is the measured
plane-compression win; bench.py asserts ≥4× on the wavefront-plan fetch.

`fetch_pytree_async` is the double-buffering half: it launches the pack
program, starts the device→host copies (`copy_to_host_async`), and returns
immediately with an `AsyncFetch` handle — the caller overlaps the next
loop's encode upload / dispatch with the in-flight fetch and harvests with
`.get()`. The handle opens a `fetch` span (attr `async=true`) on the active
tracer at issue time and closes it at harvest, so the overlap is VISIBLE on
the flight-recorder timeline: encode/dispatch spans of the next loop nest
inside the still-open fetch span of the previous one. Harvest the handle
before issuing the next one — the Tracer's span stack is LIFO.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_autoscaler_tpu.metrics import trace as _trace
from kubernetes_autoscaler_tpu.ops.bitplane import (
    pack_flat_bits,
    unpack_flat_bits_np,
)
from kubernetes_autoscaler_tpu.sidecar import faults as _faults

# Chaos plane (sidecar/faults.py, grown to the local path for the control
# loop's survival layer — docs/ROBUSTNESS.md "Control loop"): every
# synchronous fetch and async harvest passes the `local_fetch` hook, so a
# seeded hang/delay/raise exercises the REAL device→host transfer point the
# supervisor's fetch guard watches. The `if _faults.PLAN is not None`
# global-load guard is the zero-overhead-when-disabled contract.

_SUPPORTED = ("bool", "int8", "int16", "int32", "uint8", "uint16",
              "float32")

# Device round-trip accounting (docs/FUSED_LOOP.md): every synchronous
# fetch and async harvest is one device→host round trip, counted here at
# the layer where the transfer actually happens so no caller can forget to
# report one. StaticAutoscaler resets the counter at loop start and stamps
# the total into the journal record and the `loop_device_round_trips`
# gauge; CI asserts <=2 on the fused steady state. Side-band transfers
# that are not part of the decision path (shadow-audit samples, debugging
# captures) run under `suppress_counting()` so sampled overhead does not
# break the budget assertion.
_ROUND_TRIPS = 0
_COUNT_SUPPRESSED = 0


def reset_round_trips() -> None:
    global _ROUND_TRIPS
    _ROUND_TRIPS = 0


def round_trips() -> int:
    return _ROUND_TRIPS


def _bump_round_trip() -> None:
    global _ROUND_TRIPS
    if not _COUNT_SUPPRESSED:
        _ROUND_TRIPS += 1


class suppress_counting:
    """Context manager: fetches inside do not count as loop round trips."""

    def __enter__(self):
        global _COUNT_SUPPRESSED
        _COUNT_SUPPRESSED += 1
        return self

    def __exit__(self, *exc):
        global _COUNT_SUPPRESSED
        _COUNT_SUPPRESSED -= 1
        return False


@jax.jit
def _packed(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    bools, ints, floats = [], [], []
    for leaf in leaves:
        # silent-corruption guard: wider types would wrap in the i32/f32
        # buffers, and bfloat16 classifies differently on device vs host
        assert str(leaf.dtype) in _SUPPORTED, (
            f"fetch_pytree cannot pack dtype {leaf.dtype}; widen _SUPPORTED "
            f"and the buffer classes first")
        if leaf.dtype == jnp.bool_:
            bools.append(leaf.ravel())
        elif jnp.issubdtype(leaf.dtype, jnp.floating):
            floats.append(leaf.ravel().astype(jnp.float32))
        else:
            ints.append(leaf.ravel().astype(jnp.int32))
    empty = lambda dt: jnp.zeros((0,), dt)  # noqa: E731
    return (
        # one bit per bool on the wire: the whole bool stream packs into
        # int32 words (little-endian bit order, ops/bitplane contract)
        pack_flat_bits(jnp.concatenate(bools)) if bools else empty(jnp.int32),
        jnp.concatenate(ints) if ints else empty(jnp.int32),
        jnp.concatenate(floats) if floats else empty(jnp.float32),
    )


def _logical_nbytes(leaves) -> int:
    """Bytes the pre-bit-packing buffer classes would have moved
    (bool→uint8, integer→int32, float→float32) — the denominator of the
    transfer-compression counters."""
    total = 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.ndim else 1
        total += n * (1 if np.dtype(leaf.dtype) == np.bool_ else 4)
    return total


def _account(phases, bufs, leaves) -> None:
    if phases is None:
        return
    moved = sum(int(np.prod(b.shape)) * np.dtype(b.dtype).itemsize
                for b in bufs)
    phases.bump("batched_fetch_bytes_moved", moved)
    phases.bump("batched_fetch_bytes_logical", _logical_nbytes(leaves))


def _unflatten(leaves, treedef, b_words, i, f):
    """Slice the three host buffers back into the original leaves."""
    n_bool = sum(int(np.prod(leaf.shape)) if leaf.ndim else 1
                 for leaf in leaves if np.dtype(leaf.dtype) == np.bool_)
    b = unpack_flat_bits_np(b_words, n_bool)
    offs = {"b": 0, "i": 0, "f": 0}
    out = []
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.ndim else 1
        dt = np.dtype(leaf.dtype)
        if dt == np.bool_:
            chunk, key = b, "b"
        elif np.issubdtype(dt, np.floating):
            chunk, key = f, "f"
        else:
            chunk, key = i, "i"
        out.append(chunk[offs[key]:offs[key] + n]
                   .reshape(leaf.shape).astype(dt))
        offs[key] += n
    return jax.tree_util.tree_unflatten(treedef, out)


def fetch_pytree(tree, phases=None):
    """Return the same pytree with every leaf as a host numpy array of the
    ORIGINAL shape and dtype, using at most three device→host transfers
    (bool leaves ride bit-packed). `phases` enables byte accounting."""
    if _faults.PLAN is not None:
        _faults.PLAN.fire("local_fetch")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if leaves and all(isinstance(x, np.ndarray) for x in leaves):
        # already on host (fused harvest hands precomputed numpy scores to
        # downstream consumers): no transfer, no round trip, and crucially
        # no bounce through the pack program
        return tree
    _bump_round_trip()
    if len(leaves) <= 1:
        # one leaf is one transfer either way — skip the pack program (and
        # its per-structure jit cache entry; the planner's batched host
        # views hand in many distinct small dict shapes)
        return jax.tree_util.tree_unflatten(
            treedef, [np.asarray(jax.device_get(x)) for x in leaves])
    bufs = _packed(tree)
    _account(phases, bufs, leaves)
    b, i, f = jax.device_get(bufs)
    return _unflatten(leaves, treedef, b, i, f)


class AsyncFetch:
    """In-flight batched fetch: issued now, harvested with `.get()`.

    Between issue and harvest the caller runs the NEXT loop's encode/dispatch
    — that is the double buffer. The handle owns a `fetch` span (async=true)
    on the tracer that was active at issue time; `.get()` closes it, so
    whatever ran in between shows up nested inside the fetch span on the
    timeline. `.get()` is idempotent."""

    __slots__ = ("_leaves", "_treedef", "_bufs", "_result", "_done",
                 "_tracer", "_span")

    def __init__(self, tree, phases=None, span_name: str = "fetch",
                 trace: bool = True):
        self._leaves, self._treedef = jax.tree_util.tree_flatten(tree)
        if self._leaves and all(isinstance(x, np.ndarray)
                                for x in self._leaves):
            # every leaf already host-resident (planner mirror hits): no
            # transfer, no round trip — and no bounce through the device
            # pack program (same short-circuit as fetch_pytree)
            self._result = tree
            self._done = True
            self._bufs = None
            self._tracer = None
            self._span = None
            return
        self._bufs = _packed(tree)
        _account(phases, self._bufs, self._leaves)
        for buf in self._bufs:
            start = getattr(buf, "copy_to_host_async", None)
            if start is not None:
                start()
        self._result = None
        self._done = False
        # trace=False is for speculative issues (docs/FUSED_LOOP.md): the
        # handle may be harvested a full loop later — or never — so it must
        # not hold a slot on the LIFO span stack of the issuing loop's tracer
        self._tracer = _trace.current_tracer() if trace else None
        self._span = (self._tracer.begin(span_name, cat="fetch",
                                         **{"async": True})
                      if self._tracer is not None else None)

    def get(self):
        """Block for the transfers (already overlapped with whatever the
        caller did since issue) and rebuild the original pytree."""
        if self._done:
            return self._result
        if _faults.PLAN is not None:
            _faults.PLAN.fire("local_fetch")
        _bump_round_trip()
        b, i, f = jax.device_get(self._bufs)
        self._result = _unflatten(self._leaves, self._treedef, b, i, f)
        self._done = True
        self._bufs = None
        if self._tracer is not None:
            self._tracer.end(self._span)
            self._tracer = None
        return self._result


def fetch_pytree_async(tree, phases=None, trace: bool = True) -> AsyncFetch:
    """Issue a batched fetch without blocking; see AsyncFetch."""
    return AsyncFetch(tree, phases=phases, trace=trace)
