"""Single-shot device→host fetch for result pytrees.

jax.device_get walks pytree leaves one transfer each; over the TPU tunnel
every transfer is a ~70 ms round trip, so a 7-leaf result costs ~0.5 s per
control loop. `fetch_pytree` concatenates the leaves into at most three
dtype-class buffers ON DEVICE (bool→uint8 so the big feasibility planes are
not widened 4x, integers→int32, floats→float32) and reconstructs the exact
original structure, shapes and dtypes on the host — three transfers worst
case, independent of leaf count. The packer is one jitted function whose
cache keys on the pytree structure+shapes, so there is nothing to keep in
sync when a result struct gains or reorders fields.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


_SUPPORTED = ("bool", "int8", "int16", "int32", "uint8", "uint16",
              "float32")


@jax.jit
def _packed(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    bools, ints, floats = [], [], []
    for leaf in leaves:
        # silent-corruption guard: wider types would wrap in the i32/f32
        # buffers, and bfloat16 classifies differently on device vs host
        assert str(leaf.dtype) in _SUPPORTED, (
            f"fetch_pytree cannot pack dtype {leaf.dtype}; widen _SUPPORTED "
            f"and the buffer classes first")
        if leaf.dtype == jnp.bool_:
            bools.append(leaf.ravel().astype(jnp.uint8))
        elif jnp.issubdtype(leaf.dtype, jnp.floating):
            floats.append(leaf.ravel().astype(jnp.float32))
        else:
            ints.append(leaf.ravel().astype(jnp.int32))
    empty = lambda dt: jnp.zeros((0,), dt)  # noqa: E731
    return (
        jnp.concatenate(bools) if bools else empty(jnp.uint8),
        jnp.concatenate(ints) if ints else empty(jnp.int32),
        jnp.concatenate(floats) if floats else empty(jnp.float32),
    )


def fetch_pytree(tree):
    """Return the same pytree with every leaf as a host numpy array of the
    ORIGINAL shape and dtype, using at most three device→host transfers."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if len(leaves) <= 1:
        # one leaf is one transfer either way — skip the pack program (and
        # its per-structure jit cache entry; the planner's batched host
        # views hand in many distinct small dict shapes)
        return jax.tree_util.tree_unflatten(
            treedef, [np.asarray(jax.device_get(x)) for x in leaves])
    b, i, f = jax.device_get(_packed(tree))
    offs = {"b": 0, "i": 0, "f": 0}
    out = []
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.ndim else 1
        dt = np.dtype(leaf.dtype)
        if dt == np.bool_:
            chunk, key = b, "b"
        elif np.issubdtype(dt, np.floating):
            chunk, key = f, "f"
        else:
            chunk, key = i, "i"
        out.append(chunk[offs[key]:offs[key] + n]
                   .reshape(leaf.shape).astype(dt))
        offs[key] += n
    return jax.tree_util.tree_unflatten(treedef, out)
