"""First-fit packing primitive: place pod equivalence groups onto node bins.

This is the vectorized replacement for the reference's one-pod-at-a-time
SchedulePod loop (estimator/binpacking_estimator.go:163-238 and the
HintingSimulator's TrySchedulePods, simulator/scheduling/hinting_simulator.go:53).
Instead of scheduling pod-by-pod with fork/revert, a whole equivalence group is
placed in one step: per node, `how many exemplars still fit` is an integer
divide over the free-resource vector, and first-fit order becomes a cumulative
sum — pods spill across nodes in index order exactly as a serial first-fit
would, but with no inner loop.

The outer loop over groups is a `lax.scan` carrying the free-capacity tensor:
binpacking is inherently sequential across groups (SURVEY.md §7 hard part),
but each scan step does all-nodes work on the VPU, so the serial depth is G
(≈ distinct pod shapes), not P (pods).

Wavefront packing (`pack_groups_wavefront`) cuts that serial depth further:
groups whose feasibility masks touch DISJOINT node sets cannot interact
through the free-capacity carry, so they can be placed in one scan step
without changing first-fit results. A host-side precedence-respecting
coloring of the G×G mask-overlap graph (`compute_wavefronts`) batches the
scan into W ≤ G wavefronts; `WavefrontCache` memoizes the coloring across
control loops keyed by a mask fingerprint (the planner's `_marshal_artifacts`
idiom). When masks overlap heavily W ≈ G and callers keep the serial scan.

Tie-break/ordering contract: nodes are filled in ascending index order; callers
control placement preference by passing a node permutation (the reference's
pluggable NodeOrdering, plugin_runner.go:89-131, becomes "sort the axis").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# shard_map compatibility: the public `jax.shard_map` (with its `check_vma`
# kwarg) only exists on newer JAX; older releases ship it under
# jax.experimental with `check_rep` instead. Resolved once at import so the
# sharded packer runs on both.
try:
    _shard_map = jax.shard_map
    _SHARD_MAP_KW = {"check_vma": False}
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_KW = {"check_rep": False}


class PackResult(struct.PyTreeNode):
    free_after: jax.Array   # i32[N, R] remaining capacity after placement
    placed: jax.Array       # i32[G, N] pods of group g placed on node n
    scheduled: jax.Array    # i32[G] total pods placed per group (≤ count)


def fit_count(free: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """i32[N]: how many pods with request vector `req` fit into `free` rows.

    Resource slots with req==0 impose no constraint. Negative free → 0."""
    big = jnp.int32(1 << 30)
    safe = jnp.maximum(req, 1)[None, :]                  # avoid /0
    per_r = jnp.where(req[None, :] > 0, jnp.clip(free, 0) // safe, big)
    return jnp.min(per_r, axis=-1)


def pack_groups(
    free: jnp.ndarray,       # i32[N, R]
    mask: jnp.ndarray,       # bool[G, N] placement-independent feasibility
    req: jnp.ndarray,        # i32[G, R]
    count: jnp.ndarray,      # i32[G] pods wanted per group
    order: jnp.ndarray,      # i32[G] group processing order (e.g. FFD by size)
    limit_one: jnp.ndarray,  # bool[G] cap placement at 1/node (self-anti-affinity)
) -> PackResult:
    """First-fit-decreasing placement of all groups onto the node bins."""
    free = jnp.asarray(free)
    mask = jnp.asarray(mask)
    req = jnp.asarray(req)
    count = jnp.asarray(count)
    order = jnp.asarray(order)
    limit_one = jnp.asarray(limit_one)

    def step(free_c, g):
        reqg = req[g]
        c = fit_count(free_c, reqg)
        c = jnp.where(mask[g], c, 0)
        c = jnp.where(limit_one[g], jnp.minimum(c, 1), c)
        # Clamp to the group's pod count: semantics-neutral (placement is
        # capped by count anyway) and keeps the prefix sum away from i32
        # overflow when a zero-request pod makes fit_count() huge.
        c = jnp.minimum(c, count[g])
        cum = jnp.cumsum(c)
        place = jnp.clip(count[g] - (cum - c), 0, c)
        free_c = free_c - place[:, None] * reqg[None, :]
        return free_c, place

    free_after, placed_in_order = jax.lax.scan(step, free, order)
    placed = jnp.zeros_like(placed_in_order).at[order].set(placed_in_order)
    return PackResult(free_after=free_after, placed=placed, scheduled=placed.sum(axis=-1))


# Standalone dispatch entry for ONE-SHOT host callers outside a larger jit:
# the free-capacity input is DONATED, so XLA reuses its buffer for
# free_after instead of allocating a second [N, R] plane per call. The
# caller must not touch `free` afterwards (donation invalidates the device
# buffer; passing a host array is always safe — each call uploads a fresh
# one). Inside scale_up_sim the scan carry already aliases.
pack_groups_jit = jax.jit(pack_groups, donate_argnums=(0,))


def pack_groups_sharded(
    mesh,
    free: jnp.ndarray,       # i32[N, R]  N divisible by the nodes-axis size
    mask: jnp.ndarray,       # bool[G, N]
    req: jnp.ndarray,        # i32[G, R]
    count: jnp.ndarray,      # i32[G]
    order: jnp.ndarray,      # i32[G]
    limit_one: jnp.ndarray,  # bool[G]
) -> PackResult:
    """First-fit pack with the NODES axis sharded over the device mesh.

    The distributed form of SURVEY.md §2.9's mapping: the reference's
    goroutine node scan becomes per-shard vector work plus two ICI
    collectives per group step — an all_gather of the per-shard fit totals
    (turning local prefix sums into the global first-fit order: shard s's
    offset is the sum of earlier shards' totals, a hierarchical scan) and a
    psum of per-group placements. Bit-identical to pack_groups on one
    device; scales the N axis across chips/hosts (ICI then DCN) the way the
    scaling-book recipe shards a sequence axis.
    """
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from kubernetes_autoscaler_tpu.parallel.mesh import NODES_AXIS

    n_shards = mesh.shape[NODES_AXIS]

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(NODES_AXIS, None), P(None, NODES_AXIS), P(None, None),
                  P(None), P(None), P(None)),
        out_specs=(P(NODES_AXIS, None), P(None, NODES_AXIS), P(None)),
        **_SHARD_MAP_KW,
    )
    def run(free_l, mask_l, req_r, count_r, order_r, limone_r):
        shard = jax.lax.axis_index(NODES_AXIS)

        def step(free_c, g):
            reqg = req_r[g]
            c = fit_count(free_c, reqg)
            c = jnp.where(mask_l[g], c, 0)
            c = jnp.where(limone_r[g], jnp.minimum(c, 1), c)
            c = jnp.minimum(c, count_r[g])
            totals = jax.lax.all_gather(c.sum(), NODES_AXIS)      # i32[S]
            offset = jnp.sum(
                jnp.where(jnp.arange(n_shards) < shard, totals, 0))
            cum = jnp.cumsum(c) + offset
            place = jnp.clip(count_r[g] - (cum - c), 0, c)
            free_c = free_c - place[:, None] * reqg[None, :]
            return free_c, place

        free_after, placed_in_order = jax.lax.scan(step, free_l, order_r)
        placed = jnp.zeros_like(placed_in_order).at[order_r].set(placed_in_order)
        scheduled = jax.lax.psum(placed.sum(axis=-1), NODES_AXIS)
        return free_after, placed, scheduled

    free_after, placed, scheduled = run(
        jnp.asarray(free), jnp.asarray(mask), jnp.asarray(req),
        jnp.asarray(count), jnp.asarray(order), jnp.asarray(limit_one))
    return PackResult(free_after=free_after, placed=placed, scheduled=scheduled)


class WavefrontPlan(struct.PyTreeNode):
    """Conflict-free batching of the group scan into W wavefronts.

    `waves[w]` holds the group indices placed in step w (-1 = padding). Within
    one wavefront all pairwise feasibility masks are disjoint, so placements
    commute; across wavefronts every conflicting pair keeps its first-fit
    order (the coloring is precedence-respecting, see compute_wavefronts).
    Static fields key the jit cache: a plan reshape recompiles, a same-shape
    re-coloring does not.
    """

    waves: jax.Array  # i32[W, S] group ids per wavefront, -1-padded
    n_waves: int = struct.field(pytree_node=False, default=0)      # real W
    n_active: int = struct.field(pytree_node=False, default=0)     # groups colored

    @property
    def worthwhile(self) -> bool:
        """True when batching actually shortens the scan (W < active groups)."""
        return self.n_waves < self.n_active


def compute_wavefronts(mask: np.ndarray, order: np.ndarray,
                       active: np.ndarray | None = None) -> list[list[int]]:
    """Precedence-respecting coloring of the mask-overlap graph (host-side).

    layer(g) = 1 + max(layer(h)) over groups h EARLIER in `order` whose masks
    intersect g's — the longest-conflict-chain layering. Two invariants make
    the wavefront pack byte-identical to the serial scan:
      * within a layer, masks are pairwise disjoint (a conflicting earlier
        group forces a later layer), so placements touch disjoint node sets
        and commute;
      * across layers, every conflicting pair keeps its `order` sequence, so
        the free-capacity carry on shared nodes evolves exactly as serially.
    Plain greedy smallest-color would violate the second invariant (a group
    could be colored BEFORE an earlier conflicting group's color).

    `active` masks out groups that cannot place anything (invalid / count 0);
    they are appended to wavefront 0 — their placement rows are all-zero
    either way, and keeping them out of the conflict graph stops an
    everything-overlapping dead group from serializing live ones.
    """
    mask = np.asarray(mask, bool)
    order = np.asarray(order)
    g = mask.shape[0]
    if active is None:
        active = mask.any(axis=1)
    else:
        active = np.asarray(active, bool) & mask.any(axis=1)
    conflict = (mask.astype(np.int32) @ mask.astype(np.int32).T) > 0
    layer = np.zeros((g,), np.int64)
    seen: list[int] = []
    for gi in order.tolist():
        if not active[gi]:
            continue
        prev = [h for h in seen if conflict[gi, h]]
        layer[gi] = (max(layer[h] for h in prev) + 1) if prev else 0
        seen.append(gi)
    n_waves = int(layer[seen].max()) + 1 if seen else 1
    waves: list[list[int]] = [[] for _ in range(n_waves)]
    for gi in order.tolist():          # deterministic: order position within wave
        if active[gi]:
            waves[int(layer[gi])].append(int(gi))
        else:
            waves[0].append(int(gi))   # dead group: zero placement, any step
    return waves


def build_wavefront_plan(mask: np.ndarray, order: np.ndarray,
                         active: np.ndarray | None = None,
                         pad_w: int = 4, pad_s: int = 8) -> WavefrontPlan:
    """compute_wavefronts + padding to shape buckets (bounded recompiles)."""
    waves = compute_wavefronts(mask, order, active=active)
    w = len(waves)
    s = max(max((len(wv) for wv in waves), default=1), 1)
    w_pad = ((w + pad_w - 1) // pad_w) * pad_w
    s_pad = ((s + pad_s - 1) // pad_s) * pad_s
    arr = np.full((w_pad, s_pad), -1, np.int32)
    for i, wv in enumerate(waves):
        arr[i, : len(wv)] = wv
    n_active = int(np.asarray(mask, bool).any(axis=1).sum()) \
        if active is None else int(np.count_nonzero(active))
    return WavefrontPlan(waves=jnp.asarray(arr), n_waves=w,
                         n_active=max(n_active, 1))


class WavefrontCache:
    """Single-entry plan cache keyed by the (mask, order) byte fingerprint.

    The planner's `_marshal_artifacts` idiom: the coloring is host work that
    only changes when group COMPOSITION changes; count-only churn between
    control loops is a hit. Counters feed PhaseStats.events / test assertions.
    """

    def __init__(self, pad_w: int = 4, pad_s: int = 8):
        self._entry: tuple | None = None
        self.pad_w = pad_w
        self.pad_s = pad_s
        self.hits = 0
        self.misses = 0

    def plan(self, mask: np.ndarray, order: np.ndarray,
             active: np.ndarray | None = None,
             phases=None) -> WavefrontPlan:
        mask = np.asarray(mask, bool)
        order = np.asarray(order)
        act = None if active is None else np.asarray(active, bool)
        # bit-packed fingerprint: the retained key is G×N/8 bytes, not G×N
        # (the ops/bitplane idea applied to the cache's memory footprint)
        fp = (mask.shape, np.packbits(mask).tobytes(), order.tobytes(),
              None if act is None else np.packbits(act).tobytes())
        if self._entry is not None and self._entry[0] == fp:
            self.hits += 1
            if phases is not None:
                phases.bump("wavefront_cache_hit")
            return self._entry[1]
        self.misses += 1
        if phases is not None:
            phases.bump("wavefront_cache_miss")
        plan = build_wavefront_plan(mask, order, active=act,
                                    pad_w=self.pad_w, pad_s=self.pad_s)
        self._entry = (fp, plan)
        return plan


def pack_groups_wavefront(
    free: jnp.ndarray,       # i32[N, R]
    mask: jnp.ndarray,       # bool[G, N]
    req: jnp.ndarray,        # i32[G, R]
    count: jnp.ndarray,      # i32[G]
    limit_one: jnp.ndarray,  # bool[G]
    plan: WavefrontPlan,
) -> PackResult:
    """First-fit pack with the group scan batched into the plan's wavefronts.

    Byte-identical to pack_groups(free, mask, req, count, order, limit_one)
    when `plan` was built from (a superset of) `mask` in the same `order`:
    each scan step performs segmented placement arithmetic for a whole
    wavefront — per-slot fit counts, per-slot node-prefix sums, one fused
    carry update — so the serial depth is W, not G. A plan mask that is a
    SUPERSET of the runtime mask is safe (conflicts only shrink; disjointness
    and precedence both survive), which is why callers may build the plan
    from placement-independent predicates and still apply runtime-only
    restrictions (e.g. resident self-anti-affinity) in `mask`.
    """
    free = jnp.asarray(free)
    mask = jnp.asarray(mask)
    req = jnp.asarray(req)
    count = jnp.asarray(count)
    limit_one = jnp.asarray(limit_one)
    g_total, n = mask.shape

    def step(free_c, wave):                     # wave: i32[S]
        slot_ok = wave >= 0
        gid = jnp.maximum(wave, 0)
        reqw = req[gid]                         # i32[S, R]
        cntw = jnp.where(slot_ok, count[gid], 0)
        c = jax.vmap(fit_count, in_axes=(None, 0))(free_c, reqw)   # [S, N]
        c = jnp.where(mask[gid] & slot_ok[:, None], c, 0)
        c = jnp.where(limit_one[gid][:, None], jnp.minimum(c, 1), c)
        c = jnp.minimum(c, cntw[:, None])
        cum = jnp.cumsum(c, axis=1)
        place = jnp.clip(cntw[:, None] - (cum - c), 0, c)          # [S, N]
        # disjoint masks ⇒ each node is touched by ≤ 1 slot: the summed
        # update equals the serial per-group subtraction
        free_c = free_c - (place[:, :, None] * reqw[:, None, :]).sum(axis=0)
        return free_c, place

    free_after, placed_w = jax.lax.scan(step, free, plan.waves)    # [W, S, N]
    flat_ids = plan.waves.reshape(-1)
    flat_place = placed_w.reshape(-1, n)
    # pad slots carry all-zero rows (slot_ok masking) → .add is a scatter-set
    placed = jnp.zeros((g_total, n), placed_w.dtype).at[
        jnp.maximum(flat_ids, 0)].add(flat_place)
    return PackResult(free_after=free_after, placed=placed,
                      scheduled=placed.sum(axis=-1))


def ffd_order(req: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Decreasing-size group order (reference: estimator/decreasing_pod_orderer.go —
    exemplar score over cpu+memory). Invalid rows sort last."""
    score = req[:, 0].astype(jnp.float32) + req[:, 1].astype(jnp.float32) / 1024.0
    score = jnp.where(valid, score, -1.0)
    return jnp.argsort(-score).astype(jnp.int32)
