"""First-fit packing primitive: place pod equivalence groups onto node bins.

This is the vectorized replacement for the reference's one-pod-at-a-time
SchedulePod loop (estimator/binpacking_estimator.go:163-238 and the
HintingSimulator's TrySchedulePods, simulator/scheduling/hinting_simulator.go:53).
Instead of scheduling pod-by-pod with fork/revert, a whole equivalence group is
placed in one step: per node, `how many exemplars still fit` is an integer
divide over the free-resource vector, and first-fit order becomes a cumulative
sum — pods spill across nodes in index order exactly as a serial first-fit
would, but with no inner loop.

The outer loop over groups is a `lax.scan` carrying the free-capacity tensor:
binpacking is inherently sequential across groups (SURVEY.md §7 hard part),
but each scan step does all-nodes work on the VPU, so the serial depth is G
(≈ distinct pod shapes), not P (pods).

Tie-break/ordering contract: nodes are filled in ascending index order; callers
control placement preference by passing a node permutation (the reference's
pluggable NodeOrdering, plugin_runner.go:89-131, becomes "sort the axis").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

# shard_map compatibility: the public `jax.shard_map` (with its `check_vma`
# kwarg) only exists on newer JAX; older releases ship it under
# jax.experimental with `check_rep` instead. Resolved once at import so the
# sharded packer runs on both.
try:
    _shard_map = jax.shard_map
    _SHARD_MAP_KW = {"check_vma": False}
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_KW = {"check_rep": False}


class PackResult(struct.PyTreeNode):
    free_after: jax.Array   # i32[N, R] remaining capacity after placement
    placed: jax.Array       # i32[G, N] pods of group g placed on node n
    scheduled: jax.Array    # i32[G] total pods placed per group (≤ count)


def fit_count(free: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """i32[N]: how many pods with request vector `req` fit into `free` rows.

    Resource slots with req==0 impose no constraint. Negative free → 0."""
    big = jnp.int32(1 << 30)
    safe = jnp.maximum(req, 1)[None, :]                  # avoid /0
    per_r = jnp.where(req[None, :] > 0, jnp.clip(free, 0) // safe, big)
    return jnp.min(per_r, axis=-1)


def pack_groups(
    free: jnp.ndarray,       # i32[N, R]
    mask: jnp.ndarray,       # bool[G, N] placement-independent feasibility
    req: jnp.ndarray,        # i32[G, R]
    count: jnp.ndarray,      # i32[G] pods wanted per group
    order: jnp.ndarray,      # i32[G] group processing order (e.g. FFD by size)
    limit_one: jnp.ndarray,  # bool[G] cap placement at 1/node (self-anti-affinity)
) -> PackResult:
    """First-fit-decreasing placement of all groups onto the node bins."""
    free = jnp.asarray(free)
    mask = jnp.asarray(mask)
    req = jnp.asarray(req)
    count = jnp.asarray(count)
    order = jnp.asarray(order)
    limit_one = jnp.asarray(limit_one)

    def step(free_c, g):
        reqg = req[g]
        c = fit_count(free_c, reqg)
        c = jnp.where(mask[g], c, 0)
        c = jnp.where(limit_one[g], jnp.minimum(c, 1), c)
        # Clamp to the group's pod count: semantics-neutral (placement is
        # capped by count anyway) and keeps the prefix sum away from i32
        # overflow when a zero-request pod makes fit_count() huge.
        c = jnp.minimum(c, count[g])
        cum = jnp.cumsum(c)
        place = jnp.clip(count[g] - (cum - c), 0, c)
        free_c = free_c - place[:, None] * reqg[None, :]
        return free_c, place

    free_after, placed_in_order = jax.lax.scan(step, free, order)
    placed = jnp.zeros_like(placed_in_order).at[order].set(placed_in_order)
    return PackResult(free_after=free_after, placed=placed, scheduled=placed.sum(axis=-1))


def pack_groups_sharded(
    mesh,
    free: jnp.ndarray,       # i32[N, R]  N divisible by the nodes-axis size
    mask: jnp.ndarray,       # bool[G, N]
    req: jnp.ndarray,        # i32[G, R]
    count: jnp.ndarray,      # i32[G]
    order: jnp.ndarray,      # i32[G]
    limit_one: jnp.ndarray,  # bool[G]
) -> PackResult:
    """First-fit pack with the NODES axis sharded over the device mesh.

    The distributed form of SURVEY.md §2.9's mapping: the reference's
    goroutine node scan becomes per-shard vector work plus two ICI
    collectives per group step — an all_gather of the per-shard fit totals
    (turning local prefix sums into the global first-fit order: shard s's
    offset is the sum of earlier shards' totals, a hierarchical scan) and a
    psum of per-group placements. Bit-identical to pack_groups on one
    device; scales the N axis across chips/hosts (ICI then DCN) the way the
    scaling-book recipe shards a sequence axis.
    """
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from kubernetes_autoscaler_tpu.parallel.mesh import NODES_AXIS

    n_shards = mesh.shape[NODES_AXIS]

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(NODES_AXIS, None), P(None, NODES_AXIS), P(None, None),
                  P(None), P(None), P(None)),
        out_specs=(P(NODES_AXIS, None), P(None, NODES_AXIS), P(None)),
        **_SHARD_MAP_KW,
    )
    def run(free_l, mask_l, req_r, count_r, order_r, limone_r):
        shard = jax.lax.axis_index(NODES_AXIS)

        def step(free_c, g):
            reqg = req_r[g]
            c = fit_count(free_c, reqg)
            c = jnp.where(mask_l[g], c, 0)
            c = jnp.where(limone_r[g], jnp.minimum(c, 1), c)
            c = jnp.minimum(c, count_r[g])
            totals = jax.lax.all_gather(c.sum(), NODES_AXIS)      # i32[S]
            offset = jnp.sum(
                jnp.where(jnp.arange(n_shards) < shard, totals, 0))
            cum = jnp.cumsum(c) + offset
            place = jnp.clip(count_r[g] - (cum - c), 0, c)
            free_c = free_c - place[:, None] * reqg[None, :]
            return free_c, place

        free_after, placed_in_order = jax.lax.scan(step, free_l, order_r)
        placed = jnp.zeros_like(placed_in_order).at[order_r].set(placed_in_order)
        scheduled = jax.lax.psum(placed.sum(axis=-1), NODES_AXIS)
        return free_after, placed, scheduled

    free_after, placed, scheduled = run(
        jnp.asarray(free), jnp.asarray(mask), jnp.asarray(req),
        jnp.asarray(count), jnp.asarray(order), jnp.asarray(limit_one))
    return PackResult(free_after=free_after, placed=placed, scheduled=scheduled)


def ffd_order(req: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Decreasing-size group order (reference: estimator/decreasing_pod_orderer.go —
    exemplar score over cpu+memory). Invalid rows sort last."""
    score = req[:, 0].astype(jnp.float32) + req[:, 1].astype(jnp.float32) / 1024.0
    score = jnp.where(valid, score, -1.0)
    return jnp.argsort(-score).astype(jnp.int32)
