"""Pallas TPU kernel for the first-fit-decreasing pack scan.

This is the Pallas tier of the hot op named in SURVEY.md §7 ("the
scatter-heavy incremental node_alloc update and the first-fit argmin with
tie-break ordering"). The XLA tier (ops/pack.py pack_groups) expresses the
FFD pass as a `lax.scan` over pod equivalence groups whose carry — the
free-capacity tensor — round-trips through the scan machinery every step.
Here the whole pass is ONE kernel launch:

  * grid = (batch, node-tiles); the TPU grid is sequential, so tiles see
    free capacity exactly as a serial first-fit would,
  * the free tensor lives in VMEM for the whole group loop (read-modify-
    write on the output block, no HBM traffic per group),
  * per-group remaining pod counts persist across node tiles in SMEM
    scratch — the cross-tile spill carry of first-fit,
  * group metadata (requests, counts, FFD order, one-per-node flags) ride
    the scalar-prefetch channel into SMEM.

Semantics are bit-identical to ops/pack.pack_groups (property-tested in
tests/test_pallas_pack.py): nodes fill in ascending index order, groups in
the caller-supplied order, placement capped by per-node fit counts and the
group's remaining pod count.

Reference counterpart (behavior, not design): the serial per-pod
SchedulePod loop in estimator/binpacking_estimator.go:163-238 and
simulator/scheduling/hinting_simulator.go:53.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetes_autoscaler_tpu.ops.pack import PackResult

_BIG = 1 << 30  # Python int: jnp scalars would be captured tracer constants


def _cumsum_lanes(x: jnp.ndarray, tile: int) -> jnp.ndarray:
    """Inclusive prefix sum along the lane axis of i32[1, T] (Hillis–Steele).

    log2(T) shift-and-add steps; jnp.roll wraps, the iota mask zeroes the
    wrapped lanes."""
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    s = 1
    while s < tile:
        x = x + jnp.where(idx >= s, jnp.roll(x, s, axis=1), 0)
        s *= 2
    return x


def _pack_kernel(
    # scalar prefetch (SMEM)
    req_ref,      # i32[G, R]
    count_ref,    # i32[G]
    order_ref,    # i32[G]
    limone_ref,   # i32[G]
    # VMEM blocks
    free_ref,     # i32[1, R, T] this tile's starting free capacity
    mask_ref,     # i32[1, G, T] feasibility (already includes bin_open/validity)
    placed_ref,   # i32[1, G, T] out
    freeout_ref,  # i32[1, R, T] out
    # scratch
    rem_ref,      # SMEM i32[G] pods still wanted per group (carries across tiles)
    *,
    n_groups: int,
    n_res: int,
    tile: int,
):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init_remaining():
        def init(i, _):
            rem_ref[i] = count_ref[i]
            return 0
        jax.lax.fori_loop(0, n_groups, init, 0)

    freeout_ref[...] = free_ref[...]

    def body(i, _):
        g = order_ref[i]
        cnt = rem_ref[g]
        lim = limone_ref[g]

        fit = jnp.full((1, tile), _BIG, jnp.int32)
        for r in range(n_res):
            rv = req_ref[g, r]
            fr = jnp.maximum(freeout_ref[0, r : r + 1, :], 0)
            q = fr // jnp.maximum(rv, 1)
            fit = jnp.minimum(fit, jnp.where(rv > 0, q, _BIG))

        m = mask_ref[0, pl.ds(g, 1), :]
        fit = jnp.where(m > 0, fit, 0)
        fit = jnp.where(lim > 0, jnp.minimum(fit, 1), fit)
        # Clamp to the remaining count: semantics-neutral, and keeps the
        # prefix sum far from i32 overflow (50k pods × 8k lanes < 2^31).
        fit = jnp.minimum(fit, cnt)

        cum = _cumsum_lanes(fit, tile)
        place = jnp.clip(cnt - (cum - fit), 0, fit)

        for r in range(n_res):
            rv = req_ref[g, r]
            freeout_ref[0, r : r + 1, :] = freeout_ref[0, r : r + 1, :] - place * rv
        placed_ref[0, pl.ds(g, 1), :] = place
        rem_ref[g] = cnt - jnp.sum(place)
        return 0

    jax.lax.fori_loop(0, n_groups, body, 0)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def pack_groups_batched(
    free: jnp.ndarray,       # i32[B, N, R] starting free capacity per batch row
    mask: jnp.ndarray,       # bool[B, G, N] placement-independent feasibility
    req: jnp.ndarray,        # i32[G, R]
    count: jnp.ndarray,      # i32[G]
    order: jnp.ndarray,      # i32[G]
    limit_one: jnp.ndarray,  # bool[G]
    tile: int = 512,
    interpret: bool | None = None,
) -> PackResult:
    """Batched FFD pack as one Pallas launch; batch rows are independent.

    Returns a PackResult with a leading batch axis on every field."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, n, r = free.shape
    g = req.shape[0]
    tile = min(tile, max(128, n))
    n_pad = ((n + tile - 1) // tile) * tile
    nt = n_pad // tile

    free_t = jnp.swapaxes(free.astype(jnp.int32), 1, 2)          # [B, R, N]
    if n_pad != n:
        free_t = jnp.pad(free_t, ((0, 0), (0, 0), (0, n_pad - n)))
    mask_i = jnp.pad(mask.astype(jnp.int32), ((0, 0), (0, 0), (0, n_pad - n)))

    kernel = functools.partial(_pack_kernel, n_groups=g, n_res=r, tile=tile)
    placed, free_out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(b, nt),
            in_specs=[
                pl.BlockSpec((1, r, tile), lambda bi, t, *_: (bi, 0, t)),
                pl.BlockSpec((1, g, tile), lambda bi, t, *_: (bi, 0, t)),
            ],
            out_specs=[
                pl.BlockSpec((1, g, tile), lambda bi, t, *_: (bi, 0, t)),
                pl.BlockSpec((1, r, tile), lambda bi, t, *_: (bi, 0, t)),
            ],
            scratch_shapes=[pltpu.SMEM((g,), jnp.int32)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, g, n_pad), jnp.int32),
            jax.ShapeDtypeStruct((b, r, n_pad), jnp.int32),
        ],
        interpret=interpret,
    )(
        req.astype(jnp.int32),
        count.astype(jnp.int32),
        order.astype(jnp.int32),
        limit_one.astype(jnp.int32),
        free_t,
        mask_i,
    )

    placed = placed[:, :, :n]
    free_after = jnp.swapaxes(free_out, 1, 2)[:, :n, :]
    return PackResult(
        free_after=free_after,
        placed=placed,
        scheduled=placed.sum(axis=-1),
    )


def pack_groups_pallas(
    free: jnp.ndarray,       # i32[N, R]
    mask: jnp.ndarray,       # bool[G, N]
    req: jnp.ndarray,
    count: jnp.ndarray,
    order: jnp.ndarray,
    limit_one: jnp.ndarray,
    tile: int = 512,
    interpret: bool | None = None,
) -> PackResult:
    """Drop-in Pallas replacement for ops/pack.pack_groups (unbatched)."""
    res = pack_groups_batched(
        free[None], mask[None], req, count, order, limit_one,
        tile=tile, interpret=interpret,
    )
    return PackResult(
        free_after=res.free_after[0],
        placed=res.placed[0],
        scheduled=res.scheduled[0],
    )
