"""Pallas TPU kernels for the first-fit-decreasing pack scan.

This is the Pallas tier of the hot op named in SURVEY.md §7 ("the
scatter-heavy incremental node_alloc update and the first-fit argmin with
tie-break ordering"). The XLA tier (ops/pack.py pack_groups) expresses the
FFD pass as a `lax.scan` over pod equivalence groups whose carry — the
free-capacity tensor — round-trips through the scan machinery every step.
Here the whole pass is ONE kernel launch:

  * grid = (batch, node-tiles); the TPU grid is sequential, so tiles see
    free capacity exactly as a serial first-fit would,
  * the free tensor lives in VMEM for the whole group loop (read-modify-
    write on the output block, no HBM traffic per group),
  * per-group remaining pod counts persist across node tiles in SMEM
    scratch — the cross-tile spill carry of first-fit,
  * group metadata (requests, counts, FFD order, one-per-node flags) ride
    the scalar-prefetch channel into SMEM,
  * the feasibility mask is BIT-PACKED along the group axis
    (ops/bitplane.pack_group_bits): the VMEM mask block is
    int32[ceil(G/32), tile] instead of int32[G, tile] — 32× less mask
    VMEM — and the kernel resolves group g with one dynamic-uniform
    logical shift (word row g//32, bit g%32), no gather.

Two kernels share that layout:

  `pack_groups_batched`   the serial-order pack (group loop in FFD order),
                          batched over independent free-capacity rows —
                          the estimate_all expansion-option shape. Runs
                          unchanged INSIDE shard_map (no collectives per
                          batch row), which is how the mesh-sharded
                          estimator keeps the fused kernel per shard.
  `pack_groups_wavefront_pallas`
                          the segmented per-wavefront pack: the Pallas
                          analog of ops/pack.pack_groups_wavefront's
                          segmented scan step. Each wavefront's slots are
                          placed against the WAVE-START free capacity and
                          applied as one fused carry update — legal
                          because in-wave masks are pairwise disjoint
                          (see compute_wavefronts), byte-identical to the
                          serial pack by the same argument, and
                          property-tested against both formulations.

Semantics are bit-identical to ops/pack.pack_groups (property-tested in
tests/test_pallas_pack.py): nodes fill in ascending index order, groups in
the caller-supplied order, placement capped by per-node fit counts and the
group's remaining pod count.

Reference counterpart (behavior, not design): the serial per-pod
SchedulePod loop in estimator/binpacking_estimator.go:163-238 and
simulator/scheduling/hinting_simulator.go:53.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetes_autoscaler_tpu.ops.bitplane import pack_group_bits, words_for
from kubernetes_autoscaler_tpu.ops.pack import PackResult, WavefrontPlan

_BIG = 1 << 30  # Python int: jnp scalars would be captured tracer constants


def _cumsum_lanes(x: jnp.ndarray, tile: int) -> jnp.ndarray:
    """Inclusive prefix sum along the lane axis of i32[1, T] (Hillis–Steele).

    log2(T) shift-and-add steps; jnp.roll wraps, the iota mask zeroes the
    wrapped lanes."""
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    s = 1
    while s < tile:
        x = x + jnp.where(idx >= s, jnp.roll(x, s, axis=1), 0)
        s *= 2
    return x


def _mask_row(mask_ref, g, lead=None):
    """bool-ish i32[1, T] feasibility row for group g from the bit-packed
    mask block: word row g//32, logical shift by g%32. Both index and shift
    amount are SMEM scalars — a dynamic sublane slice plus a uniform
    vector-scalar shift, the whole point of the group-axis packing."""
    gw = g // 32
    gb = g % 32
    if lead is None:
        word = mask_ref[pl.ds(gw, 1), :]
    else:
        word = mask_ref[lead, pl.ds(gw, 1), :]
    return jax.lax.shift_right_logical(word, gb) & 1


def _fit_row(freeout_ref, req_ref, g, n_res, tile, lead=None):
    """i32[1, T]: how many group-g pods fit each node lane right now."""
    fit = jnp.full((1, tile), _BIG, jnp.int32)
    for r in range(n_res):
        rv = req_ref[g, r]
        if lead is None:
            fr = jnp.maximum(freeout_ref[r: r + 1, :], 0)
        else:
            fr = jnp.maximum(freeout_ref[lead, r: r + 1, :], 0)
        q = fr // jnp.maximum(rv, 1)
        fit = jnp.minimum(fit, jnp.where(rv > 0, q, _BIG))
    return fit


def _pack_kernel(
    # scalar prefetch (SMEM)
    req_ref,      # i32[G, R]
    count_ref,    # i32[G]
    order_ref,    # i32[G]
    limone_ref,   # i32[G]
    # VMEM blocks
    free_ref,     # i32[1, R, T] this tile's starting free capacity
    mask_ref,     # i32[1, Gw, T] BIT-PACKED feasibility (incl. bin_open/validity)
    placed_ref,   # i32[1, G, T] out
    freeout_ref,  # i32[1, R, T] out
    # scratch
    rem_ref,      # SMEM i32[G] pods still wanted per group (carries across tiles)
    *,
    n_groups: int,
    n_res: int,
    tile: int,
):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init_remaining():
        def init(i, _):
            rem_ref[i] = count_ref[i]
            return 0
        jax.lax.fori_loop(0, n_groups, init, 0)

    freeout_ref[...] = free_ref[...]

    def body(i, _):
        g = order_ref[i]
        cnt = rem_ref[g]
        lim = limone_ref[g]

        fit = _fit_row(freeout_ref, req_ref, g, n_res, tile, lead=0)
        m = _mask_row(mask_ref, g, lead=0)
        fit = jnp.where(m > 0, fit, 0)
        fit = jnp.where(lim > 0, jnp.minimum(fit, 1), fit)
        # Clamp to the remaining count: semantics-neutral, and keeps the
        # prefix sum far from i32 overflow (50k pods × 8k lanes < 2^31).
        fit = jnp.minimum(fit, cnt)

        cum = _cumsum_lanes(fit, tile)
        place = jnp.clip(cnt - (cum - fit), 0, fit)

        for r in range(n_res):
            rv = req_ref[g, r]
            freeout_ref[0, r : r + 1, :] = freeout_ref[0, r : r + 1, :] - place * rv
        placed_ref[0, pl.ds(g, 1), :] = place
        rem_ref[g] = cnt - jnp.sum(place)
        return 0

    jax.lax.fori_loop(0, n_groups, body, 0)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def pack_groups_batched(
    free: jnp.ndarray,       # i32[B, N, R] starting free capacity per batch row
    mask: jnp.ndarray,       # bool[B, G, N] placement-independent feasibility
    req: jnp.ndarray,        # i32[G, R]
    count: jnp.ndarray,      # i32[G]
    order: jnp.ndarray,      # i32[G]
    limit_one: jnp.ndarray,  # bool[G]
    tile: int = 512,
    interpret: bool | None = None,
) -> PackResult:
    """Batched FFD pack as one Pallas launch; batch rows are independent.

    The bool mask is bit-packed along the group axis before the launch, so
    the kernel's VMEM mask blocks are Gw = ceil(G/32) words deep. Safe to
    call inside shard_map (no collectives; the grid is per-shard).

    Returns a PackResult with a leading batch axis on every field."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, n, r = free.shape
    g = req.shape[0]
    gw = words_for(g)
    tile = min(tile, max(128, n))
    n_pad = ((n + tile - 1) // tile) * tile
    nt = n_pad // tile

    free_t = jnp.swapaxes(free.astype(jnp.int32), 1, 2)          # [B, R, N]
    if n_pad != n:
        free_t = jnp.pad(free_t, ((0, 0), (0, 0), (0, n_pad - n)))
    mask_bits = pack_group_bits(
        jnp.pad(jnp.asarray(mask, bool), ((0, 0), (0, 0), (0, n_pad - n))))

    kernel = functools.partial(_pack_kernel, n_groups=g, n_res=r, tile=tile)
    placed, free_out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(b, nt),
            in_specs=[
                pl.BlockSpec((1, r, tile), lambda bi, t, *_: (bi, 0, t)),
                pl.BlockSpec((1, gw, tile), lambda bi, t, *_: (bi, 0, t)),
            ],
            out_specs=[
                pl.BlockSpec((1, g, tile), lambda bi, t, *_: (bi, 0, t)),
                pl.BlockSpec((1, r, tile), lambda bi, t, *_: (bi, 0, t)),
            ],
            scratch_shapes=[pltpu.SMEM((g,), jnp.int32)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, g, n_pad), jnp.int32),
            jax.ShapeDtypeStruct((b, r, n_pad), jnp.int32),
        ],
        interpret=interpret,
    )(
        req.astype(jnp.int32),
        count.astype(jnp.int32),
        order.astype(jnp.int32),
        limit_one.astype(jnp.int32),
        free_t,
        mask_bits,
    )

    placed = placed[:, :, :n]
    free_after = jnp.swapaxes(free_out, 1, 2)[:, :n, :]
    return PackResult(
        free_after=free_after,
        placed=placed,
        scheduled=placed.sum(axis=-1),
    )


def pack_groups_pallas(
    free: jnp.ndarray,       # i32[N, R]
    mask: jnp.ndarray,       # bool[G, N]
    req: jnp.ndarray,
    count: jnp.ndarray,
    order: jnp.ndarray,
    limit_one: jnp.ndarray,
    tile: int = 512,
    interpret: bool | None = None,
) -> PackResult:
    """Drop-in Pallas replacement for ops/pack.pack_groups (unbatched)."""
    res = pack_groups_batched(
        free[None], mask[None], req, count, order, limit_one,
        tile=tile, interpret=interpret,
    )
    return PackResult(
        free_after=res.free_after[0],
        placed=res.placed[0],
        scheduled=res.scheduled[0],
    )


def _wavefront_kernel(
    # scalar prefetch (SMEM)
    req_ref,      # i32[G, R]
    count_ref,    # i32[G]
    limone_ref,   # i32[G]
    waves_ref,    # i32[W, S] group ids per wavefront, -1 = padding slot
    # VMEM blocks
    free_ref,     # i32[R, T]
    mask_ref,     # i32[Gw, T] bit-packed feasibility
    placed_ref,   # i32[G, T] out
    freeout_ref,  # i32[R, T] out
    # scratch
    rem_ref,      # SMEM i32[G] remaining pods (cross-tile carry)
    delta_ref,    # VMEM i32[R, T] this wave's fused capacity update
    *,
    n_groups: int,
    n_res: int,
    n_waves: int,
    n_slots: int,
    tile: int,
):
    """Segmented per-wavefront placement: every slot of a wave reads the
    WAVE-START free capacity (freeout_ref is only updated once per wave,
    by the accumulated delta), mirroring the XLA wavefront scan step.
    Disjoint in-wave masks make the fused update equal the serial
    subtraction; the property tests pin byte-equality against BOTH
    pack_groups and pack_groups_wavefront."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init_remaining():
        def init(i, _):
            rem_ref[i] = count_ref[i]
            return 0
        jax.lax.fori_loop(0, n_groups, init, 0)

    freeout_ref[...] = free_ref[...]
    placed_ref[...] = jnp.zeros_like(placed_ref)

    def wave(w, _):
        delta_ref[...] = jnp.zeros_like(delta_ref)
        # slots unroll at trace time (S is static); the wave index stays
        # dynamic — one fori iteration per wavefront, W total
        for s in range(n_slots):
            g = waves_ref[w, s]

            @pl.when(g >= 0)
            def _slot(g=g):
                cnt = rem_ref[g]
                lim = limone_ref[g]
                fit = _fit_row(freeout_ref, req_ref, g, n_res, tile)
                m = _mask_row(mask_ref, g)
                fit = jnp.where(m > 0, fit, 0)
                fit = jnp.where(lim > 0, jnp.minimum(fit, 1), fit)
                fit = jnp.minimum(fit, cnt)
                cum = _cumsum_lanes(fit, tile)
                place = jnp.clip(cnt - (cum - fit), 0, fit)
                for r in range(n_res):
                    rv = req_ref[g, r]
                    delta_ref[r : r + 1, :] = delta_ref[r : r + 1, :] + place * rv
                placed_ref[pl.ds(g, 1), :] = place
                rem_ref[g] = cnt - jnp.sum(place)

        freeout_ref[...] = freeout_ref[...] - delta_ref[...]
        return 0

    jax.lax.fori_loop(0, n_waves, wave, 0)


@functools.partial(jax.jit,
                   static_argnames=("n_waves", "n_slots", "tile", "interpret"))
def _wavefront_call(free, mask, req, count, limit_one, waves,
                    n_waves: int, n_slots: int,
                    tile: int, interpret: bool) -> PackResult:
    n, r = free.shape
    g = req.shape[0]
    gw = words_for(g)
    tile = min(tile, max(128, n))
    n_pad = ((n + tile - 1) // tile) * tile
    nt = n_pad // tile

    free_t = jnp.swapaxes(free.astype(jnp.int32), 0, 1)          # [R, N]
    if n_pad != n:
        free_t = jnp.pad(free_t, ((0, 0), (0, n_pad - n)))
    mask_bits = pack_group_bits(
        jnp.pad(jnp.asarray(mask, bool), ((0, 0), (0, n_pad - n))))

    kernel = functools.partial(_wavefront_kernel, n_groups=g, n_res=r,
                               n_waves=n_waves, n_slots=n_slots, tile=tile)
    placed, free_out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(nt,),
            in_specs=[
                pl.BlockSpec((r, tile), lambda t, *_: (0, t)),
                pl.BlockSpec((gw, tile), lambda t, *_: (0, t)),
            ],
            out_specs=[
                pl.BlockSpec((g, tile), lambda t, *_: (0, t)),
                pl.BlockSpec((r, tile), lambda t, *_: (0, t)),
            ],
            scratch_shapes=[
                pltpu.SMEM((g,), jnp.int32),
                pltpu.VMEM((r, tile), jnp.int32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((g, n_pad), jnp.int32),
            jax.ShapeDtypeStruct((r, n_pad), jnp.int32),
        ],
        interpret=interpret,
    )(
        req.astype(jnp.int32),
        count.astype(jnp.int32),
        limit_one.astype(jnp.int32),
        waves.astype(jnp.int32),
        free_t,
        mask_bits,
    )

    placed = placed[:, :n]
    free_after = jnp.swapaxes(free_out, 0, 1)[:n, :]
    return PackResult(
        free_after=free_after,
        placed=placed,
        scheduled=placed.sum(axis=-1),
    )


def pack_groups_wavefront_pallas(
    free: jnp.ndarray,       # i32[N, R]
    mask: jnp.ndarray,       # bool[G, N]
    req: jnp.ndarray,        # i32[G, R]
    count: jnp.ndarray,      # i32[G]
    limit_one: jnp.ndarray,  # bool[G]
    plan: WavefrontPlan,
    tile: int = 512,
    interpret: bool | None = None,
) -> PackResult:
    """Drop-in Pallas replacement for ops/pack.pack_groups_wavefront.

    Same superset-mask contract: a `plan` built from a SUPERSET of `mask`
    in the same order stays byte-identical (conflicts only shrink). Safe
    inside shard_map for batch-style axes; the node axis must be whole per
    shard (the in-tile prefix sum is local, like the XLA wavefront)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    w, s = plan.waves.shape
    return _wavefront_call(
        jnp.asarray(free), jnp.asarray(mask), jnp.asarray(req),
        jnp.asarray(count), jnp.asarray(limit_one), plan.waves,
        n_waves=w, n_slots=s, tile=tile, interpret=interpret)
