"""Batched scheduler-predicate evaluation: the pods×nodes feasibility mask.

Reference counterpart: SchedulerPluginRunner.RunFiltersUntilPassingNode /
RunFiltersOnNode (simulator/clustersnapshot/predicate/plugin_runner.go:54-182),
which runs the vendored kube-scheduler Filter plugins serially per pod with a
goroutine-parallel node scan (plugin_runner.go:135, √n chunking). Here the
entire (pod-group × node) plane is evaluated as one fused tensor expression —
the per-pair cost is a handful of int32 compares, so the TPU evaluates the
whole plane exhaustively instead of early-exiting per pod.

Implemented filter semantics (the simulable subset, SURVEY.md §7):
  * NodeResourcesFit     — dense int32 resource vectors (models/resources.py)
  * NodeUnschedulable    — `schedulable` gate (spec.unschedulable + ToBeDeleted taint)
  * NodeAffinity + nodeSelector — AND-of-OR hash requirements + negatives
  * TaintToleration      — exact/key hash coverage per taint
  * NodePorts            — occupied-port hash intersection
  * readiness/validity gates

Inter-pod (anti-)affinity and topology spread have cross-pod coupling and are
handled at the packing layer (ops/binpack.py caps per-node placement for
self-anti-affinity groups) and the host-check tier for richer terms.

All loops below are over *static padding dims* (unrolled at trace time into a
fused XLA graph); no data-dependent Python control flow.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_autoscaler_tpu.models.cluster_state import NodeTensors, PodGroupTensors
from kubernetes_autoscaler_tpu.models.resources import (
    CPU,
    EPHEMERAL,
    MEMORY,
    NUM_STANDARD,
    PODS,
)

# ---- the REASON plane: packed per-(pod-group × node) refusal bits ----
#
# Each bit names the Filter that refused the pair; 0 ⇔ feasible (the
# invariant `feasibility_mask == (reason_mask == 0)` is property-tested in
# tests/test_predicate_fuzz.py). uint16 keeps the whole G×N plane one quarter
# the size of the int32 predicate inputs. The taxonomy follows the reference's
# NoScaleUp/event reasons (estimator skip reasons + the per-filter verdicts
# its scheduler framework reports) — see docs/OBSERVABILITY.md for the table.
REASON_CPU = 1 << 0           # NodeResourcesFit: cpu request > free
REASON_MEMORY = 1 << 1        # NodeResourcesFit: memory
REASON_EPHEMERAL = 1 << 2     # NodeResourcesFit: ephemeral-storage
REASON_PODS = 1 << 3          # NodeResourcesFit: pod-capacity slot
REASON_EXTENDED = 1 << 4      # NodeResourcesFit: any extended resource (GPU…)
REASON_SELECTOR = 1 << 5      # NodeAffinity / nodeSelector mismatch
REASON_TAINT = 1 << 6         # TaintToleration: uncovered NoSchedule/NoExecute
REASON_PORTS = 1 << 7         # NodePorts: hostPort collision
REASON_NODE_UNAVAILABLE = 1 << 8  # invalid / unready / unschedulable node row
REASON_GROUP_INVALID = 1 << 9     # padding pod-group row (specs.valid False)

# ordered: the first set bit in this order is the headline reason
REASON_BITS = (
    (REASON_CPU, "cpu"),
    (REASON_MEMORY, "memory"),
    (REASON_EPHEMERAL, "ephemeral-storage"),
    (REASON_PODS, "pod-capacity"),
    (REASON_EXTENDED, "extended-resource"),
    (REASON_SELECTOR, "selector"),
    (REASON_TAINT, "taint"),
    (REASON_PORTS, "ports"),
    (REASON_NODE_UNAVAILABLE, "node-unavailable"),
    (REASON_GROUP_INVALID, "invalid-group"),
)
REASON_NAMES = {bit: name for bit, name in REASON_BITS}

# host-level summary reasons (not kernel bits):
# - a refused group with no valid node/template column at all (reference:
#   the NoScaleUp "no node group can help" event)
NO_NODE_IN_GROUP = "no-node-in-group"
# - a refused group with at least one fully-feasible column: the constraint
#   planes admit it somewhere, so the refusal came from option capping
#   (max_new / limiter stack / bins crowded out by earlier FFD groups) —
#   the reference's "max node group size reached"-family skip reasons
CAPPED_BY_LIMITS = "capped-by-limits"


def _any_eq(table: jnp.ndarray, h: jnp.ndarray) -> jnp.ndarray:
    """table: i32[N, K] hash slots, h: i32[G] probes → bool[G, N] membership.

    0 probes never match (0 is the padding sentinel and never a valid hash)."""
    hit = (table[None, :, :] == h[:, None, None]).any(axis=-1)
    return hit & (h != 0)[:, None]


def resources_fit(nodes: NodeTensors, specs: PodGroupTensors) -> jnp.ndarray:
    """bool[G, N]: req <= cap - alloc on every resource slot."""
    free = nodes.free()  # i32[N, R]
    return (specs.req[:, None, :] <= free[None, :, :]).all(axis=-1)


def selector_match(node_labels: jnp.ndarray, specs: PodGroupTensors) -> jnp.ndarray:
    """bool[G, N]: every ANDed requirement has ≥1 alternative present, and no
    must-be-absent hash is present. node_labels: i32[N, L]."""
    g = specs.sel_req.shape[0]
    n = node_labels.shape[0]
    ok = jnp.ones((g, n), dtype=bool)
    s_terms, s_alts = specs.sel_req.shape[1], specs.sel_req.shape[2]
    for s in range(s_terms):
        term = specs.sel_req[:, s, :]                      # i32[G, A]
        term_active = (term != 0).any(axis=-1)             # bool[G]
        sat = jnp.zeros((g, n), dtype=bool)
        for a in range(s_alts):
            sat = sat | _any_eq(node_labels, term[:, a])
        ok = ok & (~term_active[:, None] | sat)
    for s in range(specs.sel_neg.shape[1]):
        ok = ok & ~_any_eq(node_labels, specs.sel_neg[:, s])
    return ok


def taints_tolerated(
    taint_exact: jnp.ndarray, taint_key: jnp.ndarray, specs: PodGroupTensors
) -> jnp.ndarray:
    """bool[G, N]: every NoSchedule/NoExecute taint is covered by a toleration.

    Coverage = exact (key,value,effect) hash match (Equal operator), or
    (key,effect) hash match (Exists operator), or the tolerate-everything flag.
    taint_exact/taint_key: i32[N, T]."""
    g = specs.tol_exact.shape[0]
    n = taint_exact.shape[0]
    ok = jnp.ones((g, n), dtype=bool)
    for t in range(taint_exact.shape[1]):
        te = taint_exact[:, t]                              # i32[N]
        tk = taint_key[:, t]
        active = te != 0                                    # bool[N]
        covered = jnp.broadcast_to(specs.tolerate_all[:, None], (g, n))
        for tl in range(specs.tol_exact.shape[1]):
            covered = covered | (specs.tol_exact[:, tl][:, None] == te[None, :]) & active[None, :]
            covered = covered | (specs.tol_key[:, tl][:, None] == tk[None, :]) & (tk != 0)[None, :]
        ok = ok & (~active[None, :] | covered)
    return ok


def ports_free(used_ports: jnp.ndarray, specs: PodGroupTensors) -> jnp.ndarray:
    """bool[G, N]: none of the pod's hostPorts collide with occupied ports."""
    g = specs.port_hash.shape[0]
    n = used_ports.shape[0]
    conflict = jnp.zeros((g, n), dtype=bool)
    for pp in range(specs.port_hash.shape[1]):
        conflict = conflict | _any_eq(used_ports, specs.port_hash[:, pp])
    return ~conflict


def host_predicate_row(label_hash: np.ndarray, taint_exact: np.ndarray,
                       taint_key: np.ndarray, spec) -> np.ndarray:
    """Host-side (numpy) selector + taint feasibility row for ONE encoded pod
    spec against the node planes: bool[N].

    The single-pod mirror of `selector_match` and `taints_tolerated` above,
    evaluated on the encoder's host mirrors with no device dispatch — the
    scale-down planner's phantom-injection prefilter runs it per evicted pod
    so the exact oracle only sees the surviving nodes. Exact for non-lossy
    specs (same hash-equality contract as the device planes); callers must
    not prefilter with it when `spec.lossy` is set, because a lossy encoding
    may under-admit and the prefilter must never exclude a node the oracle
    would accept.

    `spec` is a models.encode._PodSpecEncoding (numpy fields)."""
    n = label_hash.shape[0]
    ok = np.ones((n,), dtype=bool)
    # selector: every active AND-term needs >= 1 alternative hash present
    for s in range(spec.sel_req.shape[0]):
        alts = spec.sel_req[s]
        alts = alts[alts != 0]
        if alts.size == 0:
            continue
        ok &= np.isin(label_hash, alts).any(axis=1)
    negs = spec.sel_neg[spec.sel_neg != 0]
    if negs.size:
        ok &= ~np.isin(label_hash, negs).any(axis=1)
    # taints: every active NoSchedule/NoExecute taint must be covered by an
    # exact (key,value,effect) or key-scoped (key,effect) toleration hash
    if not spec.tolerate_all:
        tol_ex = spec.tol_exact[spec.tol_exact != 0]
        tol_ky = spec.tol_key[spec.tol_key != 0]
        active = taint_exact != 0                       # bool[N, T]
        covered = np.isin(taint_exact, tol_ex) | np.isin(taint_key, tol_ky)
        ok &= (~active | covered).all(axis=1)
    return ok


def host_reason_row(planes: dict, gi: int,
                    check_resources: bool = True) -> np.ndarray:
    """Host-side (numpy) twin of ONE `reason_mask` row: uint16[N] packed
    refusal bits for pod-group `gi` against every node, computed from the
    incremental encoder's host mirrors with no device dispatch.

    This is the shadow-audit oracle (audit/shadow.py): the device evaluates
    `reason_mask` over its resident planes, this recomputes the same bits
    from the same logical inputs on the host — bit-for-bit equal on a
    healthy backend (pinned by tests/test_shadow_audit.py the same way the
    fuzz suite pins `feasible ⇔ reason_bits == 0`). A silently miscompiled
    predicate kernel, a corrupted resident plane, or a bad fetch shows up
    as a per-bit diff the audit can name.

    `planes` is the mirror dict (models/incremental.IncrementalEncoder._m
    keys: "nodes.*" / "specs.*"). Same hash-equality contract as
    `host_predicate_row` — the comparison is at the ENCODING level, so it
    is exact for lossy specs too (both sides see the same hashes)."""
    lh = planes["nodes.label_hash"]
    te = planes["nodes.taint_exact"]
    tk = planes["nodes.taint_key"]
    up = planes["nodes.used_ports"]
    n = lh.shape[0]
    bits = np.zeros((n,), dtype=np.uint16)
    # selector: every active AND-term needs >= 1 alternative present, and
    # no must-be-absent hash present (host_predicate_row's contract)
    sel_ok = np.ones((n,), dtype=bool)
    sel_req = planes["specs.sel_req"][gi]
    for s in range(sel_req.shape[0]):
        alts = sel_req[s]
        alts = alts[alts != 0]
        if alts.size:
            sel_ok &= np.isin(lh, alts).any(axis=1)
    negs = planes["specs.sel_neg"][gi]
    negs = negs[negs != 0]
    if negs.size:
        sel_ok &= ~np.isin(lh, negs).any(axis=1)
    bits |= np.where(~sel_ok, np.uint16(REASON_SELECTOR), np.uint16(0))
    # taints: every active taint covered by an exact or key-scoped hash
    if bool(planes["specs.tolerate_all"][gi]):
        t_ok = np.ones((n,), dtype=bool)
    else:
        tol_ex = planes["specs.tol_exact"][gi]
        tol_ex = tol_ex[tol_ex != 0]
        tol_ky = planes["specs.tol_key"][gi]
        tol_ky = tol_ky[tol_ky != 0]
        active = te != 0
        covered = np.isin(te, tol_ex) | np.isin(tk, tol_ky)
        t_ok = (~active | covered).all(axis=1)
    bits |= np.where(~t_ok, np.uint16(REASON_TAINT), np.uint16(0))
    # ports: any of the spec's hostPort hashes already occupied
    ph = planes["specs.port_hash"][gi]
    ph = ph[ph != 0]
    if ph.size:
        conflict = np.isin(up, ph).any(axis=1)
        bits |= np.where(conflict, np.uint16(REASON_PORTS), np.uint16(0))
    if check_resources:
        free = (planes["nodes.cap"].astype(np.int64)
                - planes["nodes.alloc"].astype(np.int64))
        lack = planes["specs.req"][gi].astype(np.int64)[None, :] > free
        bits |= np.where(lack[:, CPU], np.uint16(REASON_CPU), np.uint16(0))
        bits |= np.where(lack[:, MEMORY], np.uint16(REASON_MEMORY),
                         np.uint16(0))
        bits |= np.where(lack[:, EPHEMERAL], np.uint16(REASON_EPHEMERAL),
                         np.uint16(0))
        bits |= np.where(lack[:, PODS], np.uint16(REASON_PODS), np.uint16(0))
        bits |= np.where(lack[:, NUM_STANDARD:].any(axis=-1),
                         np.uint16(REASON_EXTENDED), np.uint16(0))
    gate = (planes["nodes.valid"].astype(bool)
            & planes["nodes.ready"].astype(bool)
            & planes["nodes.schedulable"].astype(bool))
    bits |= np.where(~gate, np.uint16(REASON_NODE_UNAVAILABLE), np.uint16(0))
    if not bool(planes["specs.valid"][gi]):
        bits |= np.uint16(REASON_GROUP_INVALID)
    return bits


def feasibility_mask(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    check_resources: bool = True,
) -> jnp.ndarray:
    """The full predicate plane: bool[G, N].

    One entry per (pod-equivalence-group, node): True iff the group's exemplar
    pod passes every implemented Filter on that node given current allocations.
    `check_resources=False` yields the placement-independent mask (template
    matching, where capacity is checked separately by the packer)."""
    mask = selector_match(nodes.label_hash, specs)
    mask = mask & taints_tolerated(nodes.taint_exact, nodes.taint_key, specs)
    mask = mask & ports_free(nodes.used_ports, specs)
    if check_resources:
        mask = mask & resources_fit(nodes, specs)
    gate = nodes.valid & nodes.ready & nodes.schedulable
    mask = mask & gate[None, :]
    return mask & specs.valid[:, None]


def _bit(fail: jnp.ndarray, b: int) -> jnp.ndarray:
    return jnp.where(fail, jnp.uint16(b), jnp.uint16(0))


def reason_mask(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    check_resources: bool = True,
) -> jnp.ndarray:
    """The reason variant of `feasibility_mask`: uint16[G, N] packed refusal
    bits, one per (pod-equivalence-group, node). 0 ⇔ the pair is feasible —
    bit-for-bit `feasibility_mask(...) == (reason_mask(...) == 0)` for the
    same `check_resources` (the property tests pin this).

    Same trace-time cost shape as the boolean plane (each constraint plane is
    evaluated once and mapped to its bit), but it is NOT on the hot path: the
    normal pack/sim runs the boolean plane unchanged, and callers dispatch
    this only over already-refused groups / failed candidates (the lazy
    second-dispatch contract — `reason_mask_for_groups` below)."""
    bits = _bit(~selector_match(nodes.label_hash, specs), REASON_SELECTOR)
    bits |= _bit(~taints_tolerated(nodes.taint_exact, nodes.taint_key, specs),
                 REASON_TAINT)
    bits |= _bit(~ports_free(nodes.used_ports, specs), REASON_PORTS)
    if check_resources:
        free = nodes.free()
        lack = specs.req[:, None, :] > free[None, :, :]     # bool[G, N, R]
        bits |= _bit(lack[..., CPU], REASON_CPU)
        bits |= _bit(lack[..., MEMORY], REASON_MEMORY)
        bits |= _bit(lack[..., EPHEMERAL], REASON_EPHEMERAL)
        bits |= _bit(lack[..., PODS], REASON_PODS)
        bits |= _bit(lack[..., NUM_STANDARD:].any(axis=-1), REASON_EXTENDED)
    gate = nodes.valid & nodes.ready & nodes.schedulable
    bits |= _bit(~gate, REASON_NODE_UNAVAILABLE)[None, :]
    bits |= _bit(~specs.valid, REASON_GROUP_INVALID)[:, None]
    return bits


@partial(jax.jit, static_argnames=("check_resources",))
def reason_mask_for_groups(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    group_mask: jnp.ndarray,       # bool[G] — only these rows carry bits
    check_resources: bool = True,
) -> jnp.ndarray:
    """The lazy masked dispatch: reason bits for the refused groups only
    (other rows zeroed so host consumers can't misread padding). One device
    program + one batched fetch per *refused* loop; a fully-schedulable loop
    never dispatches it — callers count dispatches under
    `reason_extraction_dispatches`."""
    bits = reason_mask(nodes, specs, check_resources=check_resources)
    return jnp.where(group_mask[:, None], bits, jnp.uint16(0))


def reason_bit_names(bits: int) -> list[str]:
    """Decode one packed value into its ordered reason names."""
    return [name for bit, name in REASON_BITS if bits & bit]


def summarize_reason_row(row: np.ndarray, col_valid: np.ndarray
                         ) -> tuple[str, dict[str, int]]:
    """Host-side summary of ONE refused group's reason row: the headline
    reason plus per-constraint refused-column counts.

    `col_valid` masks real columns (live nodes, or `groups.valid` when the
    row came from the template plane). Headline selection: no valid column
    at all means nothing could ever host the group ("no-node-in-group"); a
    fully-feasible column (bits == 0) means the constraint planes admit the
    group somewhere and the refusal came from option capping
    ("capped-by-limits"); a constraint refusing on EVERY valid column
    (bitwise AND) is the single blocking reason; otherwise no one constraint
    explains the refusal alone — "multiple-constraints"."""
    cols = np.asarray(row)[np.asarray(col_valid, bool)]
    if cols.size == 0:
        return NO_NODE_IN_GROUP, {}
    counts = {
        name: int(n)
        for bit, name in REASON_BITS
        if (n := int((cols & bit != 0).sum()))
    }
    if (cols == 0).any():
        return CAPPED_BY_LIMITS, counts
    common = int(np.bitwise_and.reduce(cols))
    for bit, name in REASON_BITS:
        if common & bit:
            return name, counts
    return "multiple-constraints", counts
