"""Scheduling pending pods onto the existing cluster (filter-out-schedulable).

Reference counterpart: the filter-out-schedulable pod-list processor
(core/podlistprocessor/filter_out_schedulable.go:103) driving
HintingSimulator.TrySchedulePods (simulator/scheduling/hinting_simulator.go:53)
— a serial per-pod loop with a hint cache (pod→last node) and a negative cache
of failed equivalence classes (similar_pods.go). The TPU plane needs neither
cache: equivalence grouping is the negative cache (one predicate row per
shape), and the full pods×nodes evaluation replaces hint lookups.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_autoscaler_tpu.models.cluster_state import (
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
)
from kubernetes_autoscaler_tpu.ops import predicates
from kubernetes_autoscaler_tpu.ops.pack import (
    PackResult,
    WavefrontPlan,
    ffd_order,
    pack_groups,
    pack_groups_sharded,
    pack_groups_wavefront,
)


def resident_group_counts(
    scheduled: ScheduledPodTensors, g: int, n: int
) -> jnp.ndarray:
    """i32[G, N]: how many resident pods of each equivalence group sit on each
    node. Feeds self-anti-affinity masking: a group with hostname
    anti-affinity on its own labels cannot land where a sibling already runs."""
    ok = scheduled.valid & (scheduled.node_idx >= 0)
    gr = jnp.where(ok, scheduled.group_ref, 0)
    ni = jnp.where(ok, scheduled.node_idx, 0)
    return (
        jnp.zeros((g, n), jnp.int32).at[gr, ni].add(ok.astype(jnp.int32))
    )


def schedule_pending_on_existing(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors | None = None,
    planes=None,
    max_zones: int = 16,
    with_constraints: bool = False,
    mesh=None,
    wavefront_plan: WavefrontPlan | None = None,
) -> PackResult:
    """First-fit all pending groups onto current free capacity.

    Returns a PackResult whose `scheduled` says how many pods of each group fit
    the existing cluster — those are removed from the scale-up problem, exactly
    the role of filter-out-schedulable in RunOnce (static_autoscaler.go:530).

    `with_constraints` (STATIC) selects the topology-coupled pack
    (ops/constrained.py) when the snapshot carries spread/affinity groups.

    `mesh` shards the N axis over NODES_AXIS (pack_groups_sharded); a
    `wavefront_plan` (built from the placement-independent feasibility mask —
    see plan_wavefronts) batches the group scan to depth W. The two are
    mutually exclusive (sharded wins): the wavefront segmented arithmetic is
    single-program, the sharded scan is per-group collective."""
    mask = predicates.feasibility_mask(nodes, specs, check_resources=False)
    if scheduled is not None:
        resident = resident_group_counts(scheduled, specs.g, nodes.n)
        mask = mask & ~(specs.anti_affinity_self[:, None] & (resident > 0))
    order = ffd_order(specs.req, specs.valid & (specs.count > 0))
    count = jnp.where(specs.valid, specs.count, 0)
    if with_constraints and planes is not None:
        from kubernetes_autoscaler_tpu.ops import constrained

        mask = mask & constrained.planes_static_mask(
            specs, planes, nodes.zone_id, max_zones)
        cons = constrained.constraints_for_nodes(specs, planes, nodes, max_zones)
        return constrained.pack_groups_constrained(
            nodes.free(), mask, specs.req, count, order,
            specs.one_per_node(), cons, max_zones)
    if mesh is not None:
        from kubernetes_autoscaler_tpu.parallel.mesh import NODES_AXIS

        if nodes.n % mesh.shape[NODES_AXIS] == 0:
            return pack_groups_sharded(
                mesh, nodes.free(), mask, specs.req, count, order,
                specs.one_per_node())
    if wavefront_plan is not None and wavefront_plan.worthwhile:
        # the plan mask is a SUPERSET of the runtime mask (it omits the
        # resident anti-affinity subtraction) — safe, see pack_groups_wavefront
        from kubernetes_autoscaler_tpu.ops.binpack import pack_backend

        if pack_backend() == "pallas":
            # the segmented Mosaic kernel (same wave plan, same superset
            # contract): one launch, bit-packed mask blocks in VMEM
            from kubernetes_autoscaler_tpu.ops.pallas.pack_kernel import (
                pack_groups_wavefront_pallas,
            )

            return pack_groups_wavefront_pallas(
                nodes.free(), mask, specs.req, count, specs.one_per_node(),
                wavefront_plan)
        return pack_groups_wavefront(
            nodes.free(), mask, specs.req, count, specs.one_per_node(),
            wavefront_plan)
    return pack_groups(
        nodes.free(), mask, specs.req, count, order, specs.one_per_node()
    )


def plan_wavefronts(nodes: NodeTensors, specs: PodGroupTensors,
                    cache, phases=None) -> WavefrontPlan:
    """Host-side wavefront planning for the existing-nodes pack.

    Evaluates the placement-independent feasibility mask (one small device
    program), fetches it, and asks the cache for a coloring. The mask comes
    home through ops/hostfetch.fetch_pytree, which BIT-PACKS boolean leaves
    (ops/bitplane): the predicate-plane fetch moves ~G×N/8 bytes instead of
    G×N, counted under `batched_fetch_bytes_moved`/`_logical` on `phases` —
    the counters bench.py's smoke mode asserts a ≥4× reduction on.

    The plan deliberately SKIPS the resident self-anti-affinity subtraction
    the kernel applies at
    runtime: the plan mask must be a superset of every runtime mask so that
    resident churn between control loops cannot invalidate the coloring —
    only composition changes (selectors/taints/labels) miss the cache. For
    the same reason every count-dependence is kept out of the fingerprint:
    `active` is `valid` alone, and the layering order is
    `ffd_order(req, valid)` rather than the runtime's
    `ffd_order(req, valid & count>0)`. The two orders differ only in where
    count-0 groups sit, and a count-0 group places nothing wherever it
    sits (its placement row is all-zero and the carry is untouched), while
    the relative order of count>0 groups is identical under the stable
    sort — so the pack stays byte-identical and count churn (including a
    group's count crossing zero) is always a cache hit, never a
    plan-reshape recompile of the jitted sim."""
    import numpy as np

    from kubernetes_autoscaler_tpu.ops.hostfetch import fetch_pytree

    mask = predicates.feasibility_mask(nodes, specs, check_resources=False)
    order = ffd_order(specs.req, specs.valid)
    host = fetch_pytree((mask, order, specs.valid), phases=phases)
    mask_h, order_h, active_h = (np.asarray(host[0]), np.asarray(host[1]),
                                 np.asarray(host[2]))
    return cache.plan(mask_h, order_h, active=active_h, phases=phases)
