"""Scheduling pending pods onto the existing cluster (filter-out-schedulable).

Reference counterpart: the filter-out-schedulable pod-list processor
(core/podlistprocessor/filter_out_schedulable.go:103) driving
HintingSimulator.TrySchedulePods (simulator/scheduling/hinting_simulator.go:53)
— a serial per-pod loop with a hint cache (pod→last node) and a negative cache
of failed equivalence classes (similar_pods.go). The TPU plane needs neither
cache: equivalence grouping is the negative cache (one predicate row per
shape), and the full pods×nodes evaluation replaces hint lookups.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_autoscaler_tpu.models.cluster_state import (
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
)
from kubernetes_autoscaler_tpu.ops import predicates
from kubernetes_autoscaler_tpu.ops.pack import PackResult, ffd_order, pack_groups


def resident_group_counts(
    scheduled: ScheduledPodTensors, g: int, n: int
) -> jnp.ndarray:
    """i32[G, N]: how many resident pods of each equivalence group sit on each
    node. Feeds self-anti-affinity masking: a group with hostname
    anti-affinity on its own labels cannot land where a sibling already runs."""
    ok = scheduled.valid & (scheduled.node_idx >= 0)
    gr = jnp.where(ok, scheduled.group_ref, 0)
    ni = jnp.where(ok, scheduled.node_idx, 0)
    return (
        jnp.zeros((g, n), jnp.int32).at[gr, ni].add(ok.astype(jnp.int32))
    )


def schedule_pending_on_existing(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors | None = None,
    planes=None,
    max_zones: int = 16,
    with_constraints: bool = False,
) -> PackResult:
    """First-fit all pending groups onto current free capacity.

    Returns a PackResult whose `scheduled` says how many pods of each group fit
    the existing cluster — those are removed from the scale-up problem, exactly
    the role of filter-out-schedulable in RunOnce (static_autoscaler.go:530).

    `with_constraints` (STATIC) selects the topology-coupled pack
    (ops/constrained.py) when the snapshot carries spread/affinity groups."""
    mask = predicates.feasibility_mask(nodes, specs, check_resources=False)
    if scheduled is not None:
        resident = resident_group_counts(scheduled, specs.g, nodes.n)
        mask = mask & ~(specs.anti_affinity_self[:, None] & (resident > 0))
    order = ffd_order(specs.req, specs.valid & (specs.count > 0))
    count = jnp.where(specs.valid, specs.count, 0)
    if with_constraints and planes is not None:
        from kubernetes_autoscaler_tpu.ops import constrained

        mask = mask & constrained.planes_static_mask(
            specs, planes, nodes.zone_id, max_zones)
        cons = constrained.constraints_for_nodes(specs, planes, nodes, max_zones)
        return constrained.pack_groups_constrained(
            nodes.free(), mask, specs.req, count, order,
            specs.one_per_node(), cons, max_zones)
    return pack_groups(
        nodes.free(), mask, specs.req, count, order, specs.one_per_node()
    )
