"""Expander scoring: node-group choice as on-device reductions.

Reference counterpart: expander.Strategy.BestOption (expander/expander.go:55)
with the strategy zoo under expander/{random,mostpods,waste,leastnodes,price}.
Those strategies iterate Go maps over the already-computed expansion options;
here every score is a reduction over the EstimateResult tensors, so all
strategies are computed for all node groups in one pass and the strategy
*chain* (expander/factory/chain.go) becomes successive masked argmin/argmax.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_autoscaler_tpu.models.cluster_state import NodeGroupTensors
from kubernetes_autoscaler_tpu.models.resources import CPU, MEMORY
from kubernetes_autoscaler_tpu.ops.binpack import EstimateResult

_INF = jnp.float32(3.0e38)


class OptionScores(struct.PyTreeNode):
    valid: jax.Array        # bool[NG] option schedules ≥1 pod with ≥1 node
    pods: jax.Array         # i32[NG] pods helped (most-pods maximizes)
    nodes: jax.Array        # i32[NG] new nodes (least-nodes minimizes)
    waste: jax.Array        # f32[NG] leftover cpu+mem fraction (least-waste minimizes)
    price: jax.Array        # f32[NG] node_count × price_per_node (price minimizes)
    helped_req: jax.Array | None = None  # f32[NG, R] Σ_g scheduled × req — the
                                         # price expander's pod-cost input


def fetch_scores(sc: "OptionScores", phases=None) -> "OptionScores":
    """Device→host with at most three transfers (ops/hostfetch) — the host
    consumes these values element-wise, and each lazy scalar read would be
    its own round trip. `phases` turns on the moved/logical byte counters."""
    from kubernetes_autoscaler_tpu.ops.hostfetch import fetch_pytree

    return fetch_pytree(sc, phases=phases)


def score_options(est: EstimateResult, groups: NodeGroupTensors,
                  specs=None) -> OptionScores:
    pods = est.scheduled.sum(axis=-1)
    nodes = est.node_count
    valid = groups.valid & (nodes > 0) & (pods > 0)
    helped_req = None
    if specs is not None:
        helped_req = (est.scheduled.astype(jnp.float32)
                      @ specs.req.astype(jnp.float32))        # [NG, R]

    used = (est.pods_per_node > 0).astype(jnp.float32)            # f32[NG, M]
    cap_cpu = groups.cap[:, CPU].astype(jnp.float32)
    cap_mem = groups.cap[:, MEMORY].astype(jnp.float32)
    total_cpu = used.sum(-1) * cap_cpu
    total_mem = used.sum(-1) * cap_mem
    free_cpu = (est.free_after[:, :, CPU].astype(jnp.float32) * used).sum(-1)
    free_mem = (est.free_after[:, :, MEMORY].astype(jnp.float32) * used).sum(-1)
    waste = jnp.where(total_cpu > 0, free_cpu / jnp.maximum(total_cpu, 1.0), 1.0)
    waste = waste + jnp.where(total_mem > 0, free_mem / jnp.maximum(total_mem, 1.0), 1.0)

    price = nodes.astype(jnp.float32) * groups.price_per_node
    return OptionScores(valid=valid, pods=pods, nodes=nodes, waste=waste,
                        price=price, helped_req=helped_req)


def best_option(scores: OptionScores, strategy: str = "least-waste") -> jax.Array:
    """i32 scalar: index of the winning node group (-1 if no valid option).

    Ties break toward the lowest index — a fixed, documented order (the
    reference breaks ties randomly, expander/random; determinism here is a
    feature for testability, SURVEY.md §7 'determinism/tie-breaks')."""
    if strategy == "most-pods":
        key = -scores.pods.astype(jnp.float32)
    elif strategy == "least-nodes":
        key = scores.nodes.astype(jnp.float32)
    elif strategy == "price":
        key = scores.price
    elif strategy in ("least-waste", "waste"):
        key = scores.waste
    elif strategy == "random":
        # Deterministic stand-in: first valid option. The host-side expander
        # package provides true randomness (expander/random.py).
        key = jnp.zeros_like(scores.waste)
    else:
        raise ValueError(f"unknown expander strategy {strategy!r}")
    key = jnp.where(scores.valid, key, _INF)
    idx = jnp.argmin(key).astype(jnp.int32)
    return jnp.where(scores.valid.any(), idx, -1)
