"""Per-node utilization for scale-down eligibility.

Reference counterpart: simulator/utilization/info.go:50-58 — dominant-resource
utilization (max of cpu, memory; GPU-only on GPU nodes), consumed by the
eligibility filter (core/scaledown/eligibility/eligibility.go) against
per-nodegroup thresholds.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_autoscaler_tpu.models.cluster_state import NodeTensors
from kubernetes_autoscaler_tpu.models.resources import CPU, MEMORY


def node_utilization(nodes: NodeTensors, gpu_slot: jnp.ndarray | None = None) -> jnp.ndarray:
    """f32[N] dominant-resource utilization in [0, 1].

    gpu_slot: optional i32 scalar — when a node has capacity in that extended
    slot, its utilization is that slot's ratio alone (reference GPU rule:
    utilization/info.go gpu branch)."""
    cap = nodes.cap.astype(jnp.float32)
    alloc = nodes.alloc.astype(jnp.float32)
    ratio = alloc / jnp.maximum(cap, 1.0)
    util = jnp.maximum(ratio[:, CPU], ratio[:, MEMORY])
    if gpu_slot is not None:
        gpu_cap = jnp.take_along_axis(cap, gpu_slot[None, None].repeat(cap.shape[0], 0), axis=1)[:, 0]
        gpu_ratio = jnp.take_along_axis(ratio, gpu_slot[None, None].repeat(cap.shape[0], 0), axis=1)[:, 0]
        util = jnp.where(gpu_cap > 0, gpu_ratio, util)
    return jnp.where(nodes.valid, util, 0.0)


def eligible_for_scale_down(
    nodes: NodeTensors,
    threshold: float | jnp.ndarray,
    gpu_slot: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """bool[N]: utilization below threshold and node is a live candidate.

    threshold may be a scalar or f32[N] (per-nodegroup overrides, reference
    NodeGroupConfigProcessor → ScaleDownUtilizationThreshold)."""
    util = node_utilization(nodes, gpu_slot)
    return nodes.valid & nodes.ready & (util < threshold)
