"""Device-mesh sharding of the simulation tensors.

The reference's intra-process parallelism is goroutine fan-out over the node
scan (plugin_runner.go:135 `workqueue.ParallelizeUntil`, √n chunking) and
per-nodegroup scale-up goroutines (executor.go:96-143). The TPU equivalent
(SURVEY.md §2.9 mapping) shards the *axes of the simulation tensors* over a
`jax.sharding.Mesh`:

  * `nodes` axis  — the N dimension of NodeTensors and of every pods×nodes
    plane (the TP-analog: the predicate mask's contraction axis). Collectives:
    per-group `any`/`sum` over node shards ride the ICI.
  * `pods`  axis  — the G dimension of PodGroupTensors (the DP-analog): whole
    pod-groups evaluated independently per shard.

Multi-host deployments initialize jax.distributed (parallel/multihost.py) and
the same named shardings span DCN automatically — there is no NCCL/MPI-style
explicit backend to port (reference has none either; §2.9).

The packing scan's carry (free capacity) is replicated: each scan step reduces
over the sharded node axis (cumsum) — XLA inserts the collectives. For the
estimator, node *groups* are independent → sharded over `pods` too.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODES_AXIS = "nodes"
PODS_AXIS = "pods"


def make_mesh(
    n_devices: int | None = None,
    nodes_parallel: int | None = None,
    devices=None,
) -> Mesh:
    """Build a (pods, nodes) mesh over the available devices.

    Default factorization puts all devices on the nodes axis (the dominant
    dimension at reference scale: 5k nodes vs ~hundreds of pod groups)."""
    devices = devices if devices is not None else jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    npar = nodes_parallel or n
    assert n % npar == 0, f"{n} devices not divisible by nodes_parallel={npar}"
    arr = np.asarray(devices).reshape(n // npar, npar)
    return Mesh(arr, (PODS_AXIS, NODES_AXIS))


def cluster_shardings(mesh: Mesh):
    """NamedShardings for (NodeTensors, PodGroupTensors, ScheduledPodTensors,
    NodeGroupTensors) — leading axis of node tensors over NODES_AXIS, leading
    axis of pod/group tensors over PODS_AXIS, templates replicated."""

    def node_spec(ndim):
        return NamedSharding(mesh, P(NODES_AXIS, *([None] * (ndim - 1))))

    def pod_spec(ndim):
        return NamedSharding(mesh, P(PODS_AXIS, *([None] * (ndim - 1))))

    repl = NamedSharding(mesh, P())
    return node_spec, pod_spec, repl


def shard_cluster(cluster, mesh: Mesh):
    """Place a ClusterTensors pytree according to cluster_shardings.

    Shapes must be divisible by the axis sizes — encode.py's bucket padding
    (pad_to) guarantees this for bucket ≥ mesh axis size."""
    node_spec, pod_spec, repl = cluster_shardings(mesh)

    def place(path_leaf):
        kind, leaf = path_leaf
        if kind == "node":
            return jax.device_put(leaf, node_spec(leaf.ndim))
        if kind == "pod":
            return jax.device_put(leaf, pod_spec(leaf.ndim))
        return jax.device_put(leaf, repl)

    nodes = jax.tree_util.tree_map(lambda x: place(("node", x)), cluster.nodes)
    pending = jax.tree_util.tree_map(lambda x: place(("pod", x)), cluster.pending)
    # scheduled pods index into nodes/groups arbitrarily → replicate for now
    scheduled = jax.tree_util.tree_map(lambda x: place(("repl", x)), cluster.scheduled)
    groups = jax.tree_util.tree_map(lambda x: place(("repl", x)), cluster.groups)
    out = cluster.replace(nodes=nodes, pending=pending, scheduled=scheduled,
                          groups=groups)
    if getattr(cluster, "planes", None) is not None:
        # constraint planes are small ([G, N] counts) and indexed by both
        # axes inside the wave placer — replicate
        planes = jax.tree_util.tree_map(lambda x: place(("repl", x)),
                                        cluster.planes)
        out = out.replace(planes=planes)
    return out
