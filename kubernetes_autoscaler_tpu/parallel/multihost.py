"""Multi-host initialization: the DCN leg of the distributed design.

Reference counterpart (SURVEY.md §2.9/§5.8): the reference has no NCCL/MPI
backend — its cross-process edges are kube-apiserver + gRPC. Here the
simulation tensors shard over a Mesh whose inner axis rides ICI within a
host; spanning hosts only requires initializing the JAX distributed runtime
so `jax.devices()` becomes the global device set — the SAME named shardings
(parallel/mesh.py) then place collectives on ICI within a slice and DCN
across slices. No explicit communication backend to port.

`initialize()` is idempotent and a no-op in single-process settings, so the
process entry can call it unconditionally.
"""

from __future__ import annotations

import os


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join the multi-host cluster if configured; returns True if distributed.

    Configuration precedence: explicit args, then the standard JAX env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID), then the
    TPU-pod auto-detection built into jax.distributed.initialize."""
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        return False  # single process; nothing to join

    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError:
        # already initialized — idempotent by contract
        pass
    return True


def global_mesh(nodes_parallel: int | None = None):
    """Mesh over ALL processes' devices (ICI inner, DCN outer)."""
    from kubernetes_autoscaler_tpu.parallel.mesh import make_mesh

    return make_mesh(nodes_parallel=nodes_parallel)
