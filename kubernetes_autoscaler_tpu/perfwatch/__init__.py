"""Perf observatory: the longitudinal layer over bench.py's evidence.

bench.py made every round emit a measured JSON line (docs/BENCH.md, the
never-null contract) — but until this package the lines were write-only:
printed, maybe eyeballed, discarded. perfwatch banks them:

  * `history`  — PerfHistory, an append-only chain-sealed JSONL store
    (one row per bench mode per run) with rotation, drop accounting and
    STRICT lineage separation: a cpu-floor row can never become a tpu
    baseline, closing the PR 7 "cpu recorded as tpu evidence" bug class
    structurally rather than by reviewer vigilance.
  * `detect`   — a noise-robust regression detector: per (metric,
    lineage, shape) rolling median + MAD bands, min-samples warmup, and
    a direction-policy table (latency/bytes/recompiles up = bad;
    speedup/hit-rate down = bad).
  * `triage`   — every confirmed regression emits a self-contained
    evidence bundle (the shadow-audit pattern): metric delta + baseline
    window, compile-census variant diff, per-phase span diffs, counter
    diffs, trace id / journal cursor when present.
  * `report`   — terminal trajectory table + markdown report + the
    bench --all per-mode summary table.
  * `cli`      — `python -m kubernetes_autoscaler_tpu.perfwatch
    {log,check,report,gate,seed}`; `gate` exits nonzero on confirmed
    regressions (advisory mode reports only).

Registry families (`bench_runs_total{mode,backend}`,
`perf_regressions_total{metric,severity}`,
`perf_history_dropped_total{reason}`) ride the normal exposition path and
are served identically by /metrics and Metricz (PARITY.md).
"""

from kubernetes_autoscaler_tpu.perfwatch.history import (  # noqa: F401
    HISTORY_VERSION,
    SCHEMA_VERSION,
    HistoryTamperError,
    PerfHistory,
    flatten_metrics,
    lineage_of,
    shape_signature,
)
