import sys

from kubernetes_autoscaler_tpu.perfwatch.cli import main

sys.exit(main())
