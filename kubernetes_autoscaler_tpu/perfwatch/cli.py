"""`python -m kubernetes_autoscaler_tpu.perfwatch` — the operator surface.

  log     append bench JSON lines (files or stdin) to a history store
  check   judge a run against its lineage baselines; print every verdict
          (including observe-class context); always exits 0
  report  render the markdown trajectory + verdict report
  gate    the CI teeth: judge the newest run, write triage bundles for
          confirmed regressions, exit 2 when any gating verdict
          regressed (0 in --advisory mode, which still writes the
          report — the cpu-floor lineage runs advisory in tier1 until
          enough TPU rows bank to make the band meaningful)
  seed    migrate the orphaned BENCH_r0*.json / MULTICHIP_r0*.json
          round-evidence files into the store as the seed lineage

Store-level failures (tamper, unreadable files) exit 3 — distinct from
exit 2 (regression) so CI can tell "the build got slower" from "the
history is broken".
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

from kubernetes_autoscaler_tpu.perfwatch.detect import (
    RegressionDetector,
    gating_regressions,
)
from kubernetes_autoscaler_tpu.perfwatch.history import (
    HistoryTamperError,
    PerfHistory,
    git_commit,
)
from kubernetes_autoscaler_tpu.perfwatch.report import (
    markdown_report,
    trajectory_lines,
    verdict_lines,
)
from kubernetes_autoscaler_tpu.perfwatch.triage import (
    build_bundle,
    write_bundle,
)

_TS_RE = re.compile(r"\d{4}-\d{2}-\d{2}[ T]\d{2}:\d{2}:\d{2}")


def _add_store(sp) -> None:
    sp.add_argument("--history", required=True, metavar="DIR",
                    help="history store directory")
    sp.add_argument("--max-mb", type=float, default=16.0)
    sp.add_argument("--keep-files", type=int, default=8)


def _add_detect(sp) -> None:
    sp.add_argument("--run", default="",
                    help="run id to judge (default: newest in store)")
    sp.add_argument("--lineage", default="",
                    help="restrict judging to one lineage bucket "
                         "(e.g. cpu-floor)")
    sp.add_argument("--min-samples", type=int, default=3)
    sp.add_argument("--window", type=int, default=12)
    sp.add_argument("--k-mad", type=float, default=4.0)


def _open(args) -> PerfHistory:
    return PerfHistory(args.history, max_mb=args.max_mb,
                       keep_files=args.keep_files)


def _verdicts(hist: PerfHistory, args, include_observe: bool):
    rows = hist.load()
    lineage = args.lineage or None
    run = args.run or hist.last_run_id(lineage=lineage)
    det = RegressionDetector(min_samples=args.min_samples,
                             window=args.window, k_mad=args.k_mad,
                             include_observe=include_observe)
    return rows, run, det, det.check_run(rows, run, lineage=lineage)


# ---- subcommands ----

def cmd_log(args) -> int:
    hist = _open(args)
    run_id = args.run_id or os.environ.get("KA_BENCH_RUN_ID", "")
    commit = args.commit if args.commit is not None else git_commit()
    sources = args.files or ["-"]
    appended = 0
    for src in sources:
        fh = sys.stdin if src == "-" else open(src, encoding="utf-8")
        try:
            for line in fh:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(obj, dict) or not obj.get("metric") \
                        or obj["metric"] in ("bench_all_combined",
                                             "perfwatch_log"):
                    continue
                hist.append_bench_record(obj, run_id=run_id, commit=commit,
                                         ts=args.ts)
                appended += 1
        finally:
            if fh is not sys.stdin:
                fh.close()
    print(f"[perfwatch] appended {appended} rows to {hist.root} "
          f"(run={run_id or '<from records>'})")
    return 0


def cmd_check(args) -> int:
    hist = _open(args)
    rows, run, _, verdicts = _verdicts(hist, args, include_observe=True)
    print(f"[perfwatch] store {hist.root}: {len(rows)} rows; "
          f"judging run={run or '<none>'}")
    for line in trajectory_lines(rows, lineage=args.lineage or None):
        print("  " + line)
    for line in verdict_lines(verdicts):
        print(line)
    regressed = gating_regressions(verdicts)
    print(f"[perfwatch] {len(verdicts)} verdicts, "
          f"{len(regressed)} gating regressions")
    return 0


def cmd_report(args) -> int:
    hist = _open(args)
    rows, run, _, verdicts = _verdicts(hist, args, include_observe=False)
    md = markdown_report(rows, verdicts, stats=hist.stats(),
                         title=f"Perf trajectory — run {run or 'n/a'}")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(md)
        print(f"[perfwatch] report -> {args.out}")
    else:
        print(md)
    return 0


def cmd_gate(args) -> int:
    hist = _open(args)
    rows, run, det, verdicts = _verdicts(hist, args, include_observe=False)
    if not run:
        print("[perfwatch] gate: no judged run in store "
              "(empty or all dropped) — nothing to gate")
        return 0
    for line in verdict_lines(verdicts):
        print(line)
    regressed = gating_regressions(verdicts)
    bundles = []
    if regressed and args.bundle_dir:
        by_id = {(r.get("run"), r.get("metric"), r.get("shape_sig")): r
                 for r in rows if not r.get("dropped")}
        for v in regressed:
            row = by_id.get((v.run, v.metric, v.shape_sig))
            if row is None:
                continue
            path = write_bundle(
                build_bundle(v, row, det.baselines_for(rows, row)),
                args.bundle_dir)
            if path:
                bundles.append(path)
                print(f"[perfwatch] triage bundle -> {path}")
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            f.write(markdown_report(rows, verdicts, stats=hist.stats(),
                                    title=f"Perf gate — run {run}"))
    if regressed:
        print(f"[perfwatch] gate: {len(regressed)} confirmed "
              f"regression(s) in run {run}"
              + (f" ({len(bundles)} bundle(s))" if bundles else ""))
        return 0 if args.advisory else 2
    print(f"[perfwatch] gate: run {run} clean "
          f"({len(verdicts)} gating verdicts, 0 regressed)")
    return 0


# ---- seed migration ----

def _seed_bench_file(hist: PerfHistory, path: str, default_metric: str,
                     ts_by_round: dict[int, float]) -> int:
    with open(path, encoding="utf-8") as f:
        o = json.load(f)
    n = int(o.get("n", 0))
    tail = o.get("tail", "") or ""
    stamps = _TS_RE.findall(tail)
    ts = (time.mktime(time.strptime(stamps[-1], "%Y-%m-%d %H:%M:%S"))
          if stamps else ts_by_round.get(n) or os.path.getmtime(path))
    ts_by_round.setdefault(n, ts)
    parsed = o.get("parsed")
    if not isinstance(parsed, dict):
        # pre-never-null round: the process died before emitting any JSON
        parsed = {"metric": default_metric, "value": None, "unit": "ms",
                  "error": "round crashed before emitting a record "
                           "(pre-never-null era)"}
    rec = dict(parsed)
    if rec.get("value") is not None and not rec.get("backend"):
        # r02-era records predate the provenance field; a measured
        # full-shape headline from those rounds is the real-TPU number
        rec["backend"] = "tpu"
    hist.append_bench_record(
        rec, run_id=f"seed-{os.path.basename(path).split('.')[0]}",
        commit="", ts=ts,
        fingerprint={"platform": "seed-evidence", "jax": "", "pack": ""},
        notes=f"migrated from {os.path.basename(path)} (rc={o.get('rc')})")
    return 1


def _seed_multichip_file(hist: PerfHistory, path: str,
                         ts_by_round: dict[int, float]) -> int:
    with open(path, encoding="utf-8") as f:
        o = json.load(f)
    n = int(re.search(r"r(\d+)", os.path.basename(path)).group(1)) \
        if re.search(r"r(\d+)", os.path.basename(path)) else 0
    # the dryrun rode the same round as BENCH_r0N — reuse its stamp
    ts = ts_by_round.get(n) or os.path.getmtime(path)
    rec = {
        "metric": "multichip_dryrun",
        "value": (1.0 if o.get("ok") else None),
        "unit": "ok",
        # its own lineage bucket: a virtual-mesh dryrun is neither tpu
        # evidence nor a cpu floor measurement, and must baseline neither
        "backend": f"dryrun-{int(o.get('n_devices', 0))}dev",
        "n_devices": int(o.get("n_devices", 0)),
        "rc": int(o.get("rc", 0)),
    }
    if o.get("skipped"):
        rec["value"] = None
        rec["error"] = "dryrun skipped"
    hist.append_bench_record(
        rec, run_id=f"seed-{os.path.basename(path).split('.')[0]}",
        commit="", ts=ts,
        fingerprint={"platform": "seed-evidence", "jax": "", "pack": ""},
        notes=f"migrated from {os.path.basename(path)}")
    return 1


def cmd_seed(args) -> int:
    hist = _open(args)
    ts_by_round: dict[int, float] = {}
    bench = sorted(p for p in args.files
                   if os.path.basename(p).startswith("BENCH_"))
    multi = sorted(p for p in args.files
                   if os.path.basename(p).startswith("MULTICHIP_"))
    other = [p for p in args.files if p not in bench and p not in multi]
    if other:
        print(f"[perfwatch] seed: skipping unrecognized files: {other}",
              file=sys.stderr)
    appended = 0
    for p in bench:
        appended += _seed_bench_file(hist, p, args.default_metric,
                                     ts_by_round)
    for p in multi:
        appended += _seed_multichip_file(hist, p, ts_by_round)
    st = hist.stats()
    print(f"[perfwatch] seeded {appended} rows "
          f"({st['dropped_rows']} dropped) into {hist.root}; "
          f"lineages: {st['lineages']}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_autoscaler_tpu.perfwatch",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("log", help="append bench JSON lines to the store")
    _add_store(sp)
    sp.add_argument("files", nargs="*",
                    help="files of bench JSON lines ('-' or none = stdin)")
    sp.add_argument("--run-id", default="")
    sp.add_argument("--commit", default=None)
    sp.add_argument("--ts", type=float, default=None)
    sp.set_defaults(fn=cmd_log)

    sp = sub.add_parser("check", help="judge + print all verdicts (exit 0)")
    _add_store(sp)
    _add_detect(sp)
    sp.set_defaults(fn=cmd_check)

    sp = sub.add_parser("report", help="markdown trajectory report")
    _add_store(sp)
    _add_detect(sp)
    sp.add_argument("--out", default="", help="write to file, not stdout")
    sp.set_defaults(fn=cmd_report)

    sp = sub.add_parser("gate",
                        help="exit 2 on confirmed regressions "
                             "(0 with --advisory)")
    _add_store(sp)
    _add_detect(sp)
    sp.add_argument("--advisory", action="store_true",
                    help="report-only: never exit nonzero on regressions")
    sp.add_argument("--bundle-dir", default="",
                    help="write a triage bundle per confirmed regression")
    sp.add_argument("--report", default="",
                    help="also write the markdown report here")
    sp.set_defaults(fn=cmd_gate)

    sp = sub.add_parser("seed",
                        help="migrate BENCH_r0*/MULTICHIP_r0* round "
                             "evidence into the store")
    _add_store(sp)
    sp.add_argument("files", nargs="+")
    sp.add_argument("--default-metric",
                    default="scaleup_sim_p50_ms_50kpods_5knodes_20ng",
                    help="metric for rounds that died before emitting JSON")
    sp.set_defaults(fn=cmd_seed)

    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except HistoryTamperError as e:
        print(f"[perfwatch] HISTORY TAMPER: {e}", file=sys.stderr)
        return 3
    except OSError as e:
        print(f"[perfwatch] store error: {e}", file=sys.stderr)
        return 3


if __name__ == "__main__":
    sys.exit(main())
