"""Noise-robust regression detection over PerfHistory rows.

Baselines are per (metric, lineage, shape_sig) — the lineage axis is
structural (history.py): a cpu-floor row is never compared against tpu
rows, and vice versa, no matter how the store was assembled.

Statistics: rolling median + MAD over the last `window` baseline values.
MAD (scaled by 1.4826 to estimate sigma under normality) is robust to the
occasional outlier run that would wreck a mean/stddev band; bench
latencies on shared CI runners jitter by tens of percent, so the band is
additionally floored at `rel_floor × |median|` — a constant series
(MAD = 0) does not produce a zero-width band that flags the next run's
scheduler noise. Below `min_samples` baselines a key yields `no-baseline`
(warmup), never a regression.

Direction policy: a table keyed on the metric/key NAME decides which way
is bad (latency/bytes/recompiles up = bad; speedup/hit-rate down = bad)
and which class the key gates under:

  * gate    — statistical band on the headline `value`s; a confirmed
    breach fails `perfwatch gate`.
  * exact   — invariant counters (steady_state_recompiles,
    loop_device_round_trips, driver_deaths, ...): ANY bad-direction move
    past the baseline extremum is a regression — these are contracts the
    repo CI already asserts pointwise; the history makes drift across
    runs visible too.
  * observe — everything else numeric (phase spans, census figures,
    intermediate ratios): verdicts are computed and reported for triage
    context but never fail the gate — one flaky sub-span must not turn
    the gate into a coin flip.
"""

from __future__ import annotations

import dataclasses
import re
import statistics

GATE = "gate"
EXACT = "exact"
OBSERVE = "observe"

UP_BAD = "up-bad"
DOWN_BAD = "down-bad"

_REGRESSIONS_HELP = "Confirmed perf regressions, by metric and severity"

# MAD → sigma under a normal noise model
_MAD_SIGMA = 1.4826


@dataclasses.dataclass(frozen=True)
class Policy:
    direction: str          # UP_BAD | DOWN_BAD
    klass: str              # GATE | EXACT | OBSERVE
    rel_floor: float = 0.30  # minimum band half-width as fraction of |median|


# Two ordered rule tables, first match wins.
#
# _METRIC_RULES judge the headline key `value` by the METRIC name (the
# semantics live there — a *_p50_ms value is latency, a
# *_clusters_per_sec value is throughput). A mode's headline gates BY
# DEFAULT: a headline metric with no explicit rule still gets a
# direction-inferred GATE policy (rel_floor 0.40) — new bench modes are
# born gated, and opting a headline out of the gate takes an explicit
# OBSERVE rule here, visible in review.
_METRIC_RULES: list[tuple[re.Pattern, Policy]] = [
    (re.compile(p), pol) for p, pol in [
        (r"^scaleup_sim_p50_ms_", Policy(UP_BAD, GATE, rel_floor=0.35)),
        (r"^runonce_e2e_p50_ms", Policy(UP_BAD, GATE, rel_floor=0.35)),
        (r"^fused_loop_e2e", Policy(UP_BAD, GATE, rel_floor=0.35)),
        (r"^multi_tenant_clusters_per_sec$",
         Policy(DOWN_BAD, GATE, rel_floor=0.35)),
        (r"^whatif_multiverse$", Policy(UP_BAD, GATE, rel_floor=0.40)),
        (r"^world_store_churn$", Policy(UP_BAD, GATE, rel_floor=0.40)),
        (r"^local_chaos_control_loop$",
         Policy(UP_BAD, GATE, rel_floor=0.45)),
        (r"^journal_record_replay_smoke$",
         Policy(UP_BAD, GATE, rel_floor=0.45)),
        (r"^shadow_audit_smoke$", Policy(UP_BAD, GATE, rel_floor=0.50)),
        (r"^device_stats$", Policy(UP_BAD, GATE, rel_floor=0.40)),
        # a virtual-mesh dryrun's value is an ok-flag, not a measurement
        (r"^multichip_dryrun$", Policy(DOWN_BAD, OBSERVE)),
    ]]

# _KEY_RULES judge every other flattened key by the KEY name.
_KEY_RULES: list[tuple[re.Pattern, Policy]] = [
    (re.compile(p), pol) for p, pol in [
        # ---- exact invariant counters: the repo's pointwise CI contracts
        (r"(^|\.)steady_state_recompiles$", Policy(UP_BAD, EXACT)),
        (r"(^|\.)recompiles_per_new_tenant$", Policy(UP_BAD, EXACT)),
        (r"(^|\.)loop_device_round_trips", Policy(UP_BAD, EXACT)),
        (r"(^|\.)driver_deaths$", Policy(UP_BAD, EXACT)),
        (r"(^|\.)(zero_drift|null_lane_identical|verdicts_identical"
         r"|identical_to_cold_encode|decisions_identical)$",
         Policy(DOWN_BAD, EXACT)),
        # ---- observed families: direction matters for the report ----
        # bigger-is-better ratios first: h2d_reduction_vs_full is a
        # REDUCTION factor, not a byte count — it must not fall into the
        # bytes rule below
        (r"(per_sec|speedup|hit_rate|reduction|occupancy|retained"
         r"|vs_baseline)", Policy(DOWN_BAD, OBSERVE)),
        (r"(^|\.)(h2d|d2h|bytes|_mb$|_mib$)", Policy(UP_BAD, OBSERVE)),
        (r"(_ms|_ns|_s)$", Policy(UP_BAD, OBSERVE)),
        (r"(^|\.)(p50|p95|p99|mean|max)$", Policy(UP_BAD, OBSERVE)),
        (r"overhead", Policy(UP_BAD, OBSERVE)),
        (r"(dispatches|recompiles|drops|deferrals|resends|evictions)",
         Policy(UP_BAD, OBSERVE)),
    ]]

_FALLBACK = Policy(UP_BAD, OBSERVE)


def _first_match(rules, subject: str) -> Policy | None:
    for pat, pol in rules:
        if pat.search(subject):
            return pol
    return None


def policy_for(metric: str, key: str) -> Policy:
    """Never returns None. Headline `value`s gate (direction from the
    metric name, throughput-style names flip to down-bad); every other
    unknown key falls back to observe/up-bad — a number we cannot
    interpret is reported, never gated."""
    if key == "value":
        pol = _first_match(_METRIC_RULES, metric)
        if pol is not None:
            return pol
        inferred = _first_match(_KEY_RULES, metric) or _FALLBACK
        return Policy(inferred.direction, GATE, rel_floor=0.40)
    return _first_match(_KEY_RULES, key) or _FALLBACK


@dataclasses.dataclass
class Verdict:
    metric: str
    key: str
    lineage: str
    shape_sig: str
    status: str              # stable | improved | regressed | no-baseline
    severity: str            # none | minor | major | critical
    value: float | None
    baseline_median: float | None
    baseline_mad: float | None
    baseline_n: int
    window: list[float]
    delta: float | None
    delta_frac: float | None
    threshold: float | None
    direction: str
    klass: str
    run: str = ""
    baseline_runs: list[str] = dataclasses.field(default_factory=list)

    @property
    def gates(self) -> bool:
        return self.klass in (GATE, EXACT)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class RegressionDetector:
    """`check_row` judges one history row against its exact-lineage
    baselines; `check_run` fans that over every row of one run id.
    `registry` (optional) gets `perf_regressions_total{metric,severity}`
    bumped once per confirmed gating regression."""

    def __init__(self, min_samples: int = 3, window: int = 12,
                 k_mad: float = 4.0, registry=None,
                 include_observe: bool = False):
        self.min_samples = max(1, int(min_samples))
        self.window = max(2, int(window))
        self.k_mad = float(k_mad)
        self.registry = registry
        self.include_observe = include_observe

    # ---- plumbing ----

    def baselines_for(self, all_rows: list[dict], row: dict) -> list[dict]:
        """The rolling window: same metric, SAME lineage, same shape
        signature; never dropped rows, never rows of the judged run, only
        rows sealed earlier."""
        seq = row.get("seq", 1 << 62)
        run = row.get("run", "")
        base = [r for r in all_rows
                if r.get("metric") == row.get("metric")
                and r.get("lineage") == row.get("lineage")
                and r.get("shape_sig") == row.get("shape_sig")
                and not r.get("dropped")
                and r.get("seq", -1) < seq
                and (not run or r.get("run") != run)]
        return base[-self.window:]

    def check_run(self, all_rows: list[dict], run_id: str,
                  lineage: str | None = None) -> list[Verdict]:
        out: list[Verdict] = []
        for row in all_rows:
            if row.get("run") != run_id or row.get("dropped"):
                continue
            if lineage is not None and row.get("lineage") != lineage:
                continue
            out.extend(self.check_row(all_rows, row))
        return out

    def check_row(self, all_rows: list[dict], row: dict) -> list[Verdict]:
        base = self.baselines_for(all_rows, row)
        out: list[Verdict] = []
        metric = str(row.get("metric") or "")
        for key, value in (row.get("metrics") or {}).items():
            pol = policy_for(metric, key)
            if pol.klass == OBSERVE and not self.include_observe:
                continue
            pairs = [(str(r.get("run") or ""), float(r["metrics"][key]))
                     for r in base
                     if isinstance(r.get("metrics", {}).get(key),
                                   (int, float))]
            v = self._judge(metric, key, row, pol, pairs, float(value))
            if v is not None:
                out.append(v)
        return out

    # ---- the statistics ----

    def _judge(self, metric: str, key: str, row: dict, pol: Policy,
               pairs: list[tuple[str, float]], value: float
               ) -> Verdict | None:
        series = [v for _, v in pairs]
        common = dict(metric=metric, key=key,
                      lineage=str(row.get("lineage") or ""),
                      shape_sig=str(row.get("shape_sig") or ""),
                      run=str(row.get("run") or ""),
                      direction=pol.direction, klass=pol.klass,
                      window=list(series), baseline_n=len(series),
                      baseline_runs=[r for r, _ in pairs])
        if len(series) < self.min_samples:
            return Verdict(status="no-baseline", severity="none",
                           value=value, baseline_median=None,
                           baseline_mad=None, delta=None, delta_frac=None,
                           threshold=None, **common)
        med = float(statistics.median(series))
        mad = float(statistics.median([abs(s - med) for s in series]))
        if pol.klass == EXACT:
            return self._judge_exact(pol, series, value, med, mad, common)
        thr = max(self.k_mad * _MAD_SIGMA * mad,
                  pol.rel_floor * abs(med), 1e-9)
        delta = value - med
        bad = delta if pol.direction == UP_BAD else -delta
        if bad > thr:
            status = "regressed"
            ratio = bad / thr
            severity = ("minor" if ratio <= 2.0
                        else "major" if ratio <= 5.0 else "critical")
            self._count(metric, severity)
        elif bad < -thr:
            status, severity = "improved", "none"
        else:
            status, severity = "stable", "none"
        return Verdict(status=status, severity=severity, value=value,
                       baseline_median=med, baseline_mad=mad, delta=delta,
                       delta_frac=(delta / med if med else None),
                       threshold=thr, **common)

    def _judge_exact(self, pol: Policy, series: list[float], value: float,
                     med: float, mad: float, common: dict) -> Verdict:
        """Invariant counters: ANY bad-direction move past the baseline
        extremum regresses, at critical severity — one steady-state
        recompile is a broken contract, not noise."""
        if pol.direction == UP_BAD:
            bound = max(series)
            regressed, improved = value > bound, value < min(series)
        else:
            bound = min(series)
            regressed, improved = value < bound, value > max(series)
        status = ("regressed" if regressed
                  else "improved" if improved else "stable")
        severity = "critical" if regressed else "none"
        if regressed:
            self._count(common["metric"], severity)
        delta = value - bound
        return Verdict(status=status, severity=severity, value=value,
                       baseline_median=med, baseline_mad=mad, delta=delta,
                       delta_frac=(delta / bound if bound else None),
                       threshold=0.0, **common)

    def _count(self, metric: str, severity: str) -> None:
        if self.registry is not None:
            self.registry.counter(
                "perf_regressions_total", help=_REGRESSIONS_HELP,
            ).inc(metric=metric, severity=severity)


def gating_regressions(verdicts: list[Verdict]) -> list[Verdict]:
    return [v for v in verdicts if v.status == "regressed" and v.gates]
