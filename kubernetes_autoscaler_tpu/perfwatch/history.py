"""PerfHistory: append-only, chain-sealed JSONL store of bench evidence.

One row per bench mode per run. Each row carries the run id, the commit,
an INJECTED timestamp (the store never reads a clock behind the caller's
back — tests and seed migration stamp historical times), the backend
lineage, a shape/mesh signature, the jax + device fingerprint, the mode's
flattened numeric metrics, and the full original record (so triage can
diff compile-census variants and phase spans without chasing artifacts).

Sealing is the flight journal's pattern (replay/journal.py): every row
carries `parent` (the previous row's digest) and `digest`
(sha256/16hex over the canonical body), so any in-place edit, deletion or
reorder breaks the chain structurally — `load(verify=True)` raises
HistoryTamperError instead of silently serving doctored baselines. Files
rotate at max_bytes/keep_files; each file opens with a meta line whose
`parentDigest` anchors the first row, so a retained file verifies on its
own even after older files are pruned (pruned rows are counted, never
silently vanished: `perf_history_dropped_total{reason}`).

Lineage separation is the load-bearing rule: `lineage_of(backend)` maps
the record's provenance field (docs/BENCH.md "The backend field") to the
baseline bucket, and every query filters on EXACT lineage — the floor
child emits the tpu headline metric NAME with `backend: cpu-floor`, and
that row lands in the cpu-floor bucket, never under a tpu baseline.
Dropped rows (null-valued error records) are banked for the trajectory's
honesty but excluded from baselines unless explicitly requested.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import time

from kubernetes_autoscaler_tpu.utils.canonical import digest_of

HISTORY_VERSION = 1
# bench JSON record schema: v2 added schema_version + the propagated run_id
# (the floor child and parent used to emit unversioned, uncorrelated lines)
SCHEMA_VERSION = 2

_FILE_PREFIX = "perf-"
_FILE_SUFFIX = ".jsonl"
_FILE_RE = re.compile(r"perf-(\d{6})\.jsonl$")

_RUNS_HELP = "Bench rows appended to the perf history store"
_DROPPED_HELP = "Perf-history rows dropped, by reason"

# bookkeeping/identity fields of a bench record that are not metrics
_NON_METRIC_KEYS = frozenset({
    "metric", "unit", "backend", "mode", "error", "run_id",
    "schema_version", "floor_shapes", "device", "trace", "journal",
    "modes", "results",
})
_MAX_FLAT_KEYS = 512


class HistoryTamperError(RuntimeError):
    """The chain seal failed: a row's digest or parent link does not match
    what is on disk — the history was edited, truncated mid-row, or
    reordered. Structural, not a verdict: a legitimately slower build
    changes METRICS; it cannot change an already-sealed row."""


def lineage_of(backend) -> str:
    """Map a bench record's `backend` provenance field to its baseline
    bucket. tpu | cpu-floor | any explicit platform string; records with
    no backend (old null-value error lines) bucket as `unknown` and are
    never anyone's baseline."""
    b = str(backend or "").strip()
    return b if b else "unknown"


def flatten_metrics(obj: dict, prefix: str = "", out: dict | None = None
                    ) -> dict[str, float]:
    """Flatten a bench record's numeric leaves to dotted keys
    (`phases.encode_ms`, `world_store_churn` fields, ...). Bools flatten
    to 0/1 (identity predicates like `verdicts_identical` are evidence
    too); strings, nulls and lists are not metrics and are skipped."""
    if out is None:
        out = {}
    for k, v in obj.items():
        if not prefix and k in _NON_METRIC_KEYS:
            continue
        if len(out) >= _MAX_FLAT_KEYS:
            break
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            out[key] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict):
            flatten_metrics(v, prefix=f"{key}.", out=out)
    return out


_SHAPE_KEYS = ("floor_shapes", "mesh_devices", "wavefronts", "tenants",
               "loops", "lanes", "steps", "rollout_steps", "n_devices",
               "mode")


def shape_signature(obj: dict) -> tuple[dict, str]:
    """The shape/mesh identity of a record: the metric name (headline
    names encode pods/nodes/ng) plus any explicit shape fields —
    `floor_shapes` makes a degraded child's signature differ from a true
    full-shape run even though both carry the headline metric name, a
    second fence under the lineage rule."""
    shape = {"metric": obj.get("metric", "")}
    for k in _SHAPE_KEYS:
        if obj.get(k) is not None:
            shape[k] = obj[k]
    return shape, digest_of(shape)


def git_commit(cwd: str | None = None) -> str:
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        if proc.returncode == 0:
            return proc.stdout.strip()
    except Exception:  # noqa: BLE001 — provenance is best-effort, never fatal
        pass
    return ""


def runtime_fingerprint() -> dict:
    """jax + device identity for the row (journal backend_identity's
    shape, without forcing a backend touch when jax was never imported —
    appending history must not initialize a TPU tunnel)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return {"platform": "uninitialized", "jax": "",
                "pack": os.environ.get("KA_TPU_PACK", "")}
    try:
        platform, ver = jax.default_backend(), jax.__version__
    except Exception:  # noqa: BLE001
        platform, ver = "error", ""
    return {"platform": platform, "jax": ver,
            "pack": os.environ.get("KA_TPU_PACK", "")}


def seal_row(row: dict) -> dict:
    body = {k: v for k, v in row.items() if k != "digest"}
    row["digest"] = digest_of(body)
    return row


class PerfHistory:
    """The store. Construction scans the newest file's tail to resume the
    chain; appends are O(1) in history size. `registry` (optional) gets
    `bench_runs_total{mode,backend}` + `perf_history_dropped_total{reason}`
    on the normal exposition path; `clock` is injectable for tests."""

    def __init__(self, root: str, max_mb: float = 16.0, keep_files: int = 8,
                 registry=None, clock=time.time):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.keep_files = max(2, int(keep_files))
        self.rotate_bytes = max(4096, int(max_mb * 1e6) // self.keep_files)
        self.registry = registry
        self.clock = clock
        self.drops: dict[str, int] = {}
        self._seq = 0
        self._last_digest = ""
        self._file_index = -1
        self._cur_bytes = 0
        self._load_tail()

    # ---- file plumbing ----

    def files(self) -> list[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = [n for n in names if _FILE_RE.match(n)]
        return [os.path.join(self.root, n) for n in sorted(out)]

    def _path(self, index: int) -> str:
        return os.path.join(self.root,
                            f"{_FILE_PREFIX}{index:06d}{_FILE_SUFFIX}")

    def _load_tail(self) -> None:
        files = self.files()
        if not files:
            return
        last = files[-1]
        m = _FILE_RE.search(last)
        self._file_index = int(m.group(1)) if m else len(files) - 1
        self._cur_bytes = os.path.getsize(last)
        with open(last, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError as e:
                    raise HistoryTamperError(
                        f"{last}: unparseable tail line ({e})") from e
                if obj.get("kind") == "meta":
                    # an empty freshly-rotated file still anchors the chain
                    self._last_digest = obj.get("parentDigest", "")
                    self._seq = int(obj.get("nextSeq", self._seq))
                    continue
                self._seq = int(obj.get("seq", self._seq - 1)) + 1
                self._last_digest = obj.get("digest", "")

    def _open_next(self) -> None:
        self._file_index += 1
        path = self._path(self._file_index)
        meta = {"kind": "meta", "v": HISTORY_VERSION,
                "file": self._file_index, "nextSeq": self._seq,
                "parentDigest": self._last_digest}
        line = json.dumps(meta, separators=(",", ":")) + "\n"
        with open(path, "w", encoding="utf-8") as f:
            f.write(line)
        self._cur_bytes = len(line.encode())
        self._prune()

    def _prune(self) -> None:
        files = self.files()
        while len(files) > self.keep_files:
            victim = files.pop(0)
            dropped = 0
            try:
                with open(victim, encoding="utf-8") as f:
                    for line in f:
                        if line.strip() and '"kind":"meta"' not in line:
                            dropped += 1
                os.remove(victim)
            except OSError:
                break
            if dropped:
                self._drop("rotated", dropped)

    def _drop(self, reason: str, n: int = 1) -> None:
        self.drops[reason] = self.drops.get(reason, 0) + n
        if self.registry is not None:
            self.registry.counter(
                "perf_history_dropped_total", help=_DROPPED_HELP,
            ).inc(n, reason=reason)

    # ---- append ----

    def append(self, row: dict) -> dict:
        """Seal and append one row (already shaped by
        `append_bench_record`, or hand-built by tests). Assigns seq +
        parent, writes, rotates, returns the sealed row."""
        if self._file_index < 0:
            self._open_next()
        row = dict(row)
        row["v"] = HISTORY_VERSION
        row["seq"] = self._seq
        row["parent"] = self._last_digest
        seal_row(row)
        line = json.dumps(row, separators=(",", ":"), default=str) + "\n"
        path = self._path(self._file_index)
        with open(path, "a", encoding="utf-8") as f:
            f.write(line)
        self._cur_bytes += len(line.encode())
        self._seq += 1
        self._last_digest = row["digest"]
        if self.registry is not None:
            self.registry.counter("bench_runs_total", help=_RUNS_HELP).inc(
                mode=str(row.get("mode") or "unknown"),
                backend=str(row.get("lineage") or "unknown"))
        if row.get("dropped"):
            self._drop(str(row["dropped"]))
        if self._cur_bytes >= self.rotate_bytes:
            self._open_next()
        return row

    def append_bench_record(self, obj: dict, run_id: str = "",
                            commit: str = "", ts: float | None = None,
                            fingerprint: dict | None = None,
                            notes: str = "") -> dict:
        """Bank one bench JSON record (one mode's line). Null-valued
        error records are banked as DROPPED rows — visible in the
        trajectory, never a baseline."""
        metric = obj.get("metric")
        if not metric:
            raise ValueError("bench record has no 'metric' field")
        if metric == "bench_all_combined":
            raise ValueError("bench_all_combined is an envelope, not a "
                             "mode record — append the per-mode lines")
        shape, shape_sig = shape_signature(obj)
        row = {
            "kind": "row",
            "ts": float(self.clock() if ts is None else ts),
            "run": run_id or str(obj.get("run_id") or ""),
            "commit": commit,
            "metric": metric,
            "mode": obj.get("mode") or "",
            "backend": obj.get("backend"),
            "lineage": lineage_of(obj.get("backend")),
            "shape": shape,
            "shape_sig": shape_sig,
            "fingerprint": fingerprint if fingerprint is not None
            else runtime_fingerprint(),
            "metrics": flatten_metrics(obj),
            "record": obj,
        }
        if notes:
            row["notes"] = notes
        if obj.get("value") is None and "value" in obj:
            row["dropped"] = ("null-value: " + str(obj.get("error") or
                                                   "no error recorded"))[:200]
        return self.append(row)

    # ---- read side ----

    def load(self, verify: bool = True) -> list[dict]:
        """Read every retained row in order; with verify (the default)
        re-derive the chain and raise HistoryTamperError on any digest,
        parent-link or seq break."""
        rows: list[dict] = []
        for path in self.files():
            parent = None
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except ValueError as e:
                        raise HistoryTamperError(
                            f"{path}:{i + 1}: unparseable line ({e})") from e
                    if obj.get("kind") == "meta":
                        if parent is not None:
                            raise HistoryTamperError(
                                f"{path}:{i + 1}: meta line mid-file")
                        parent = obj.get("parentDigest", "")
                        continue
                    if verify:
                        if parent is None:
                            raise HistoryTamperError(
                                f"{path}:{i + 1}: row before meta line")
                        body = {k: v for k, v in obj.items()
                                if k != "digest"}
                        if digest_of(body) != obj.get("digest"):
                            raise HistoryTamperError(
                                f"{path}:{i + 1}: digest mismatch — row "
                                f"edited after sealing")
                        if obj.get("parent") != parent:
                            raise HistoryTamperError(
                                f"{path}:{i + 1}: parent-link break — row "
                                f"deleted, reordered or spliced")
                        if rows and obj.get("seq") != rows[-1]["seq"] + 1:
                            raise HistoryTamperError(
                                f"{path}:{i + 1}: seq gap "
                                f"{rows[-1]['seq']} -> {obj.get('seq')}")
                        parent = obj["digest"]
                    rows.append(obj)
        return rows

    def verify(self) -> int:
        return len(self.load(verify=True))

    def rows(self, metric: str | None = None, lineage: str | None = None,
             shape_sig: str | None = None, include_dropped: bool = False,
             verify: bool = True) -> list[dict]:
        """Filtered view. `lineage` filtering is EXACT — this is the
        never-cross rule; there is deliberately no 'any lineage'
        baseline helper."""
        out = []
        for r in self.load(verify=verify):
            if metric is not None and r.get("metric") != metric:
                continue
            if lineage is not None and r.get("lineage") != lineage:
                continue
            if shape_sig is not None and r.get("shape_sig") != shape_sig:
                continue
            if r.get("dropped") and not include_dropped:
                continue
            out.append(r)
        return out

    def last_run_id(self, lineage: str | None = None) -> str:
        """The run id of the newest non-dropped row (optionally within a
        lineage) — what `gate` targets by default."""
        for r in reversed(self.load(verify=False)):
            if r.get("dropped"):
                continue
            if lineage is not None and r.get("lineage") != lineage:
                continue
            if r.get("run"):
                return str(r["run"])
        return ""

    def stats(self) -> dict:
        rows = self.load(verify=False)
        lineages: dict[str, int] = {}
        dropped = 0
        for r in rows:
            if r.get("dropped"):
                dropped += 1
                continue
            lin = str(r.get("lineage") or "unknown")
            lineages[lin] = lineages.get(lin, 0) + 1
        return {"files": len(self.files()), "rows": len(rows),
                "dropped_rows": dropped, "lineages": lineages,
                "drops": dict(self.drops), "next_seq": self._seq}
