"""Rendering: terminal trajectory table, markdown report, verdict lines,
and the bench --all per-mode summary table.

Everything here is pure text over already-loaded rows/verdicts — no
store access, no clock, no registry — so the sidecar Statusz page and
the CLI share one implementation.
"""

from __future__ import annotations


def fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1000:
            return f"{v:,.0f}"
        if abs(v) >= 1:
            return f"{v:.3g}"
        return f"{v:.3g}"
    return str(v)


def _series_key(row: dict) -> tuple:
    return (str(row.get("metric") or ""), str(row.get("lineage") or ""),
            str(row.get("shape_sig") or ""))


def group_series(rows: list[dict]) -> dict[tuple, list[dict]]:
    """(metric, lineage, shape_sig) → chronological non-dropped rows."""
    out: dict[tuple, list[dict]] = {}
    for r in rows:
        if r.get("dropped"):
            continue
        out.setdefault(_series_key(r), []).append(r)
    return out


def trajectory_lines(rows: list[dict], lineage: str | None = None,
                     last: int = 8) -> list[str]:
    """One line per (metric, lineage, shape) series: the last `last`
    headline values, oldest → newest."""
    out = []
    for (metric, lin, sig), series in sorted(group_series(rows).items()):
        if lineage is not None and lin != lineage:
            continue
        vals = [(r.get("metrics") or {}).get("value") for r in series]
        vals = [v for v in vals if isinstance(v, (int, float))]
        if not vals:
            continue
        unit = ""
        rec = series[-1].get("record") or {}
        if rec.get("unit"):
            unit = f" {rec['unit']}"
        tail = " ".join(fmt(v) for v in vals[-last:])
        out.append(f"{metric} [{lin}] shape={sig[:8]} n={len(vals)}: "
                   f"{tail} ->{fmt(vals[-1])}{unit}")
    return out


def verdict_lines(verdicts) -> list[str]:
    out = []
    for v in verdicts:
        flag = {"regressed": "FAIL", "improved": "good",
                "no-baseline": "warm", "stable": "ok  "}.get(v.status,
                                                            "????")
        extra = ""
        if v.baseline_median is not None:
            extra = (f" value={fmt(v.value)} baseline={fmt(v.baseline_median)}"
                     f" delta={fmt(v.delta)}"
                     f" band=±{fmt(v.threshold)} (n={v.baseline_n})")
        elif v.value is not None:
            extra = f" value={fmt(v.value)} (n={v.baseline_n}, warming up)"
        sev = f" severity={v.severity}" if v.status == "regressed" else ""
        out.append(f"[{flag}] {v.metric}/{v.key} [{v.lineage}] "
                   f"{v.status}{sev}{extra}")
    return out


def markdown_report(rows: list[dict], verdicts, stats: dict | None = None,
                    title: str = "Perf trajectory") -> str:
    lines = [f"# {title}", ""]
    if stats:
        lines.append(
            f"{stats.get('rows', 0)} rows "
            f"({stats.get('dropped_rows', 0)} dropped) across "
            f"{stats.get('files', 0)} files; lineages: "
            + (", ".join(f"{k}={v}" for k, v in
                         sorted(stats.get("lineages", {}).items()))
               or "none"))
        lines.append("")
    lines += ["## Trajectories (headline `value`, oldest -> newest)", ""]
    lines.append("| metric | lineage | shape | n | recent values | latest |")
    lines.append("|---|---|---|---|---|---|")
    for (metric, lin, sig), series in sorted(group_series(rows).items()):
        vals = [(r.get("metrics") or {}).get("value") for r in series]
        vals = [v for v in vals if isinstance(v, (int, float))]
        if not vals:
            continue
        lines.append(f"| `{metric}` | {lin} | `{sig[:8]}` | {len(vals)} | "
                     f"{' '.join(fmt(v) for v in vals[-8:])} | "
                     f"{fmt(vals[-1])} |")
    lines += ["", "## Verdicts (latest run)", ""]
    if verdicts:
        lines.append("| metric/key | lineage | status | severity | value |"
                     " baseline | delta | band |")
        lines.append("|---|---|---|---|---|---|---|---|")
        for v in verdicts:
            lines.append(
                f"| `{v.metric}/{v.key}` | {v.lineage} | {v.status} "
                f"| {v.severity} | {fmt(v.value)} "
                f"| {fmt(v.baseline_median)} | {fmt(v.delta)} "
                f"| ±{fmt(v.threshold)} |")
    else:
        lines.append("_no verdicts (empty run or store)_")
    lines.append("")
    return "\n".join(lines)


def mode_summary_table(results: dict[str, dict],
                       verdicts=None) -> str:
    """bench --all's final table: one line per mode — mode, headline
    metric value, producing backend, gate verdict. Text, to stderr-able
    width; the JSON stays the machine artifact."""
    gate: dict[str, str] = {}
    for v in verdicts or []:
        if v.key != "value":
            continue
        prev = gate.get(v.metric)
        order = {"regressed": 3, "no-baseline": 2, "improved": 1,
                 "stable": 0}
        if prev is None or order.get(v.status, 0) > order.get(prev, 0):
            gate[v.metric] = v.status
    rows = []
    for metric in sorted(results):
        if metric == "bench_all_combined":
            continue
        rec = results[metric]
        value, unit = rec.get("value"), rec.get("unit", "")
        headline = f"{fmt(value)} {unit}".strip() if value is not None \
            else "null"
        rows.append((str(rec.get("mode") or "full"), metric, headline,
                     str(rec.get("backend") or "?"),
                     gate.get(metric, "-")))
    widths = [max([len(h)] + [len(r[i]) for r in rows])
              for i, h in enumerate(("mode", "metric", "headline",
                                     "backend", "gate"))]
    header = ("mode", "metric", "headline", "backend", "gate")
    fmt_row = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [fmt_row.format(*header), fmt_row.format(*("-" * w
                                                     for w in widths))]
    out += [fmt_row.format(*r) for r in rows]
    return "\n".join(out)
