"""Auto-triage: every confirmed regression becomes ONE self-contained
evidence bundle (the shadow-audit bundle discipline, audit/shadow.py):
everything a human needs to start bisecting, in one JSON file — no
chasing CI logs that will have rotated away by the time anyone looks.

Bundle anatomy (docs/BENCH.md "Trajectory & regression gate"):

  * the verdict — metric/key, lineage, direction, delta vs the rolling
    baseline median, band width, severity;
  * the baseline window — (run, commit, ts, value) per baseline row, so
    "regressed against WHAT" is answerable offline;
  * censusDiff — compile-census variant diff vs the newest baseline row
    (added/removed variants, compile-count or cost drift): a new jit
    variant appearing alongside a latency regression is usually the
    whole story;
  * phaseDiff — per-phase / per-span deltas (baseline median vs current)
    from the flattened `phases.*` / `spans.*` keys: says WHERE in
    encode → compile → dispatch → fetch the time went;
  * counterDiff — movement counters (h2d bytes, steady_state_recompiles,
    loop_device_round_trips, dispatches, drops);
  * traceId / journalCursor when the record carries them — the handles
    into the Perfetto dump and the flight journal for full replay.

Writes are atomic (tmp + os.replace) and an OSError never sinks the
caller — triage is evidence, not control flow.
"""

from __future__ import annotations

import json
import os
import re
import statistics

_COUNTER_RE = re.compile(
    r"(bytes|recompile|round_trips|dispatch|drops|deaths|deferrals"
    r"|resends|h2d|d2h)", re.IGNORECASE)
_PHASE_RE = re.compile(r"^(phases\.|spans\.)")

_BUNDLES_HELP = "Perf-regression triage bundles written"


def _census_variants(record: dict) -> dict[str, dict]:
    """Normalize the record's compile-census evidence to a map keyed by
    `fn@shape_sig`. bench's primary line carries one census record dict;
    the device-stats line carries a list; tolerate both plus fn-keyed
    maps."""
    census = record.get("compile_census")
    if census is None and isinstance(record.get("device"), dict):
        census = record["device"].get("compile_census")
    out: dict[str, dict] = {}
    if isinstance(census, dict) and "fn" in census:
        census = [census]
    if isinstance(census, dict):
        census = list(census.values())
    if not isinstance(census, list):
        return out
    for rec in census:
        if isinstance(rec, dict) and rec.get("fn"):
            out[f"{rec.get('fn')}@{rec.get('shape_sig', '')}"] = rec
    return out


def census_diff(current: dict, baseline: dict) -> dict:
    """Variant-level diff of two records' compile censuses."""
    cur = _census_variants(current)
    base = _census_variants(baseline)
    changed = {}
    for k in sorted(cur.keys() & base.keys()):
        delta = {}
        for field in ("compiles", "flops", "bytes_accessed", "temp_bytes",
                      "tenants"):
            a, b = base[k].get(field), cur[k].get(field)
            if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                    and a != b:
                delta[field] = {"baseline": a, "current": b}
        if delta:
            changed[k] = delta
    return {
        "added": sorted(cur.keys() - base.keys()),
        "removed": sorted(base.keys() - cur.keys()),
        "changed": changed,
    }


def _metric_deltas(pattern: re.Pattern, row: dict,
                   baselines: list[dict]) -> dict:
    """baseline-median vs current for every flattened key matching
    `pattern` — shared shape of phaseDiff and counterDiff."""
    out = {}
    cur = row.get("metrics") or {}
    for key in sorted(cur):
        if not pattern.search(key):
            continue
        series = [r["metrics"][key] for r in baselines
                  if isinstance(r.get("metrics", {}).get(key),
                                (int, float))]
        if not series:
            out[key] = {"current": cur[key], "baseline_median": None,
                        "delta": None}
            continue
        med = float(statistics.median(series))
        out[key] = {"current": cur[key], "baseline_median": med,
                    "delta": cur[key] - med}
    return out


def build_bundle(verdict, row: dict, baselines: list[dict]) -> dict:
    record = row.get("record") or {}
    newest_base = baselines[-1] if baselines else {}
    bundle = {
        "kind": "perf-regression",
        "v": 1,
        "metric": verdict.metric,
        "key": verdict.key,
        "lineage": verdict.lineage,
        "shapeSig": verdict.shape_sig,
        "run": row.get("run", ""),
        "commit": row.get("commit", ""),
        "ts": row.get("ts"),
        "backend": row.get("backend"),
        "fingerprint": row.get("fingerprint"),
        "verdict": verdict.to_dict(),
        "baselineWindow": [
            {"run": r.get("run", ""), "commit": r.get("commit", ""),
             "ts": r.get("ts"), "seq": r.get("seq"),
             "value": (r.get("metrics") or {}).get(verdict.key)}
            for r in baselines
        ],
        "censusDiff": census_diff(record,
                                  (newest_base.get("record") or {})),
        "phaseDiff": _metric_deltas(_PHASE_RE, row, baselines),
        "counterDiff": _metric_deltas(_COUNTER_RE, row, baselines),
    }
    # the replay handles, when the run carried them
    for src_key, dst_key in (("trace_id", "traceId"),
                             ("traceId", "traceId"),
                             ("journal_cursor", "journalCursor"),
                             ("journalCursor", "journalCursor"),
                             ("journal", "journalDir")):
        if record.get(src_key) is not None and dst_key not in bundle:
            bundle[dst_key] = record[src_key]
    return bundle


def write_bundle(bundle: dict, out_dir: str, registry=None) -> str:
    """Atomic write; returns the path, or '' when the filesystem refused
    (evidence best-effort, never fatal)."""
    name = re.sub(r"[^A-Za-z0-9._-]", "_",
                  f"perf-{bundle.get('metric', 'unknown')}"
                  f"-{bundle.get('key', '')}-{bundle.get('run', '')}")
    path = os.path.join(out_dir, name + ".json")
    try:
        os.makedirs(out_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(bundle, f, indent=1, default=str)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        return ""
    if registry is not None:
        registry.counter("perf_triage_bundles_total",
                         help=_BUNDLES_HELP).inc(
            metric=str(bundle.get("metric", "unknown")))
    return path
