"""Node-group list processing and auto-provisioning lifecycle.

Reference counterparts (SURVEY.md §2.6): `NodeGroupListProcessor` (identity
default, autoprovisioning variant under processors/nodegroups/) which extends
the candidate node-group list before expansion options are computed, and
`NodeGroupManager` which owns create/delete of autoprovisioned groups
(creation of the expander's winner before IncreaseSize; deletion of empty
autoprovisioned groups each loop).
"""

from __future__ import annotations

from typing import Protocol

from kubernetes_autoscaler_tpu.cloudprovider.provider import (
    CloudProvider,
    NodeGroup,
    NodeGroupError,
)
from kubernetes_autoscaler_tpu.models.api import Pod


class NodeGroupListProcessor(Protocol):
    def process(self, provider: CloudProvider, groups: list[NodeGroup],
                pending: list[Pod]) -> list[NodeGroup]: ...


class IdentityNodeGroupListProcessor:
    """Default: candidates are exactly the provider's existing groups."""

    def process(self, provider, groups, pending):
        return groups


class AutoprovisioningNodeGroupListProcessor:
    """Extend candidates with not-yet-existing groups built from the cloud's
    machine catalog (reference: processors/nodegroups autoprovisioning — one
    candidate per available machine type, capped by
    --max-autoprovisioned-node-group-count)."""

    def __init__(self, max_autoprovisioned_groups: int = 15):
        self.max_autoprovisioned_groups = max_autoprovisioned_groups

    def process(self, provider, groups, pending):
        get_types = getattr(provider, "get_available_machine_types", None)
        new_group = getattr(provider, "new_node_group", None)
        if get_types is None or new_group is None or not pending:
            return groups
        # dedup/count against the provider's FULL registry, not the filtered
        # candidate list — a registered group excluded by validity filters
        # (max size, backoff) must not get a duplicate candidate that would
        # bypass those gates
        registered = list(provider.node_groups())
        existing_ids = {g.id() for g in registered} | {g.id() for g in groups}
        autoprovisioned_count = sum(1 for g in registered if g.autoprovisioned())
        out = list(groups)
        for mt in get_types():
            if autoprovisioned_count >= self.max_autoprovisioned_groups:
                break
            try:
                cand = new_group(mt)
            except NodeGroupError:
                continue
            if cand.id() in existing_ids:
                continue
            out.append(cand)
            autoprovisioned_count += 1
        return out


class NodeGroupManager:
    """Auto-provisioned group lifecycle (reference: the default
    NodeGroupManager processors row, §2.6)."""

    def create_node_group(self, group: NodeGroup) -> NodeGroup:
        if group.exist():
            return group
        created = group.create()
        from kubernetes_autoscaler_tpu.metrics.metrics import default_registry

        default_registry.counter("created_node_groups_total").inc()
        return created

    def remove_unneeded_node_groups(self, provider: CloudProvider) -> list[str]:
        """Delete empty autoprovisioned groups (no nodes, target 0)."""
        removed = []
        for g in list(provider.node_groups()):
            if not g.autoprovisioned() or not g.exist():
                continue
            if g.target_size() == 0 and not any(
                i.state != "Deleting" for i in g.nodes()
            ):
                try:
                    g.delete()
                    removed.append(g.id())
                    from kubernetes_autoscaler_tpu.metrics.metrics import (
                        default_registry,
                    )

                    default_registry.counter("deleted_node_groups_total").inc()
                except NodeGroupError:
                    pass
        return removed
