"""Pod injection: pre-scale for pods controllers have not created yet.

Reference counterpart: processors/podinjection/ (SURVEY.md §2.6) — for each
Deployment/Job/ReplicaSet, compare desired replicas against the pods that
actually exist (scheduled or pending) and inject fake pending pods for the
gap, so scale-up provisions capacity before the workload controller finishes
creating its pods (useful for large Jobs rolling out faster than kubelet
registration).
"""

from __future__ import annotations

import copy

from kubernetes_autoscaler_tpu.models.api import OwnerRef, Pod, Workload

FAKE_POD_ANNOTATION = "autoscaler.x-k8s.io/injected-pod"

_SUPPORTED_KINDS = {"Deployment", "ReplicaSet", "Job"}


def injected_pods_for(workload: Workload, existing: list[Pod]) -> list[Pod]:
    if workload.kind not in _SUPPORTED_KINDS or workload.template is None:
        return []
    owned = sum(
        1 for p in existing
        if p.owner is not None
        and (p.owner.uid == workload.uid
             or (p.owner.kind == workload.kind and p.owner.name == workload.name))
        and p.phase not in ("Succeeded", "Failed")
    )
    gap = workload.replicas - owned
    out = []
    for i in range(max(gap, 0)):
        p = copy.deepcopy(workload.template)
        p.name = f"injected-{workload.kind.lower()}-{workload.name}-{i}"
        p.namespace = workload.namespace
        p.node_name = ""
        p.phase = "Pending"
        p.annotations[FAKE_POD_ANNOTATION] = workload.name
        p.owner = OwnerRef(kind=workload.kind, name=workload.name,
                           uid=workload.uid)
        out.append(p)
    return out


class PodInjectionProcessor:
    """PodListProcessor appending the injection gap for every workload the
    source exposes (reference: podinjection processor in the default chain).

    `list_workloads` comes from the data source when it supports it (the
    FakeCluster does; a real deployment feeds Deployments/Jobs/ReplicaSets
    through the sidecar wire)."""

    def process(self, pods: list[Pod], ctx) -> list[Pod]:
        list_workloads = getattr(ctx, "list_workloads", None)
        if list_workloads is None:
            return pods
        # total injected fake pods per loop are capped (reference:
        # --pod-injection-limit, default 5000)
        limit = getattr(getattr(ctx, "options", None), "pod_injection_limit", 5000)
        out = list(pods)
        injected = 0
        for w in list_workloads():
            fakes = injected_pods_for(w, pods)
            if limit > 0 and injected + len(fakes) > limit:
                fakes = fakes[: max(limit - injected, 0)]
            out.extend(fakes)
            injected += len(fakes)
            if limit > 0 and injected >= limit:
                break
        return out
