"""Processors: the pluggable policy hook points around the control loop.

Reference counterpart: processors/processors.go:38-79 — the
AutoscalingProcessors struct with 18 hooks and defaults at :82+. The hooks
kept here are the ones with behavioral force in the simulation loop; the
event/status observers are callback lists. Host-side pod-list hooks run
before tensor encoding; the filter-out-schedulable step itself is a device
kernel invoked by StaticAutoscaler (it needs the snapshot), mirroring how the
reference's combined pod-list processor consults the ClusterSnapshot
(core/podlistprocessor/filter_out_schedulable.go:103).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Protocol

from kubernetes_autoscaler_tpu.cloudprovider.provider import CloudProvider, NodeGroup
from kubernetes_autoscaler_tpu.config.options import AutoscalingOptions
from kubernetes_autoscaler_tpu.models.api import Node, Pod


class PodListProcessor(Protocol):
    """Mutate the pending-pod list before scale-up (reference:
    NewDefaultPodListProcessor chain, core/podlistprocessor/)."""

    def process(self, pods: list[Pod], ctx: "ProcessorContext") -> list[Pod]: ...


@dataclass
class ProcessorContext:
    options: AutoscalingOptions
    provider: CloudProvider
    now: float = field(default_factory=time.time)
    # workload lister for pod injection (reference: podinjection reads
    # Deployments/Jobs/ReplicaSets via listers); None = feature off
    list_workloads: Callable[[], list] | None = None


class ClearTpuRequestsProcessor:
    """reference: core/podlistprocessor/clear_tpu_request.go — strip GKE TPU
    requests so they don't block simulated scheduling (utils/tpu/tpu.go:17-35).
    Amusingly load-bearing for a TPU-native framework: google.com/tpu requests
    are handled by device plugins, not the scheduler's resource math."""

    TPU_RESOURCE = "google.com/tpu"

    def process(self, pods, ctx):
        for p in pods:
            p.requests.pop(self.TPU_RESOURCE, None)
        return pods


class CurrentlyDrainedNodesProcessor:
    """reference: core/podlistprocessor/currently_drained_nodes.go — pods on
    nodes whose DRAIN is still in flight join the pending list (node name
    cleared) so scale-up provisions their replacement capacity before the
    node disappears. Matters most with --async-node-deletion, where drains
    span loops and the capacity is leaving while the pods still show as
    scheduled.

    The injected objects are COPIES (the live pods stay bound to the draining
    node — the reference likewise keeps the originals in the snapshot, where
    the ToBeDeleted taint stops duplicates landing back on the leaving node),
    cached by identity across loops so the incremental encoder sees a stable
    pending set while a drain is in progress. Copies are renamed
    "drained::<name>" — ':' cannot appear in real pod names, so the encoder's
    (namespace, name) keyspace stays collision-free while the original is
    still listed.

    A cached copy is INVALIDATED when the live pod is replaced (object
    identity change — the encoder's replace-on-update contract) or its
    request vector mutates in place, so scale-up never keeps provisioning
    for a stale spec while the drain is in flight (ADVICE r5)."""

    def __init__(self, deletion_tracker):
        self.tracker = deletion_tracker          # actuator's NodeDeletionTracker
        # key -> (live source pod, request signature, injected copy)
        self._copies: dict[tuple[str, str], tuple[Pod, tuple, Pod]] = {}

    @staticmethod
    def _req_sig(p: Pod) -> tuple:
        return (tuple(sorted(p.requests.items())),
                tuple(sorted(p.overhead.items())))

    def process(self, pods, ctx):
        from kubernetes_autoscaler_tpu.models.api import is_recreatable

        draining = set(self.tracker.drain_deletions_in_progress())
        if not draining:
            self._copies.clear()
            return pods
        injected: list[Pod] = []
        live_keys: set[tuple[str, str]] = set()
        for p in pods:
            if p.node_name not in draining:
                continue
            # deletion already under way -> the eviction/recreation path
            # owns it (currently_drained_nodes.go:57 skips these)
            if p.deletion_timestamp is not None:
                continue
            if not is_recreatable(p):
                continue
            key = (p.namespace, p.name)
            live_keys.add(key)
            sig = self._req_sig(p)
            entry = self._copies.get(key)
            if entry is not None:
                src, old_sig, cp = entry
                if src is not p or old_sig != sig:
                    entry = None    # live pod replaced/resized mid-drain
            if entry is None:
                import copy as _copy

                cp = _copy.copy(p)
                cp.name = f"drained::{p.name}"
                cp.uid = f"drained::{p.uid}"
                cp.node_name = ""                # ClearPodNodeNames
                cp.phase = "Pending"
                self._copies[key] = (p, sig, cp)
            injected.append(cp)
        for key in list(self._copies):
            if key not in live_keys:
                del self._copies[key]
        return pods + injected


class FilterExpendableProcessor:
    """reference: filter_out_expendable.go — drop pods below the priority
    cutoff (--expendable-pods-priority-cutoff)."""

    def process(self, pods, ctx):
        cut = ctx.options.expendable_pods_priority_cutoff
        return [p for p in pods if p.node_name or p.priority >= cut]


class FilterDaemonSetPodsProcessor:
    """reference: filter_out_daemon_sets.go — pending DS pods never trigger
    node-count scale-up (the DS controller owns them)."""

    def process(self, pods, ctx):
        return [p for p in pods if p.node_name or not p.is_daemonset()]


class FilterRecentPodsProcessor:
    """reference: --new-pod-scale-up-delay handling in listPods — very young
    pods wait a beat before triggering scale-up."""

    def __init__(self, creation_time: Callable[[Pod], float] | None = None):
        self.creation_time = creation_time

    def process(self, pods, ctx):
        delay = ctx.options.new_pod_scale_up_delay_s
        if delay <= 0 or self.creation_time is None:
            return pods
        return [
            p for p in pods
            if p.node_name or ctx.now - self.creation_time(p) >= delay
        ]


class TemplateNodeInfoProvider:
    """reference: MixedTemplateNodeInfoProvider (processors/nodeinfosprovider)
    — template from a real ready node exemplar when one exists (sanitized),
    else NodeGroup.TemplateNodeInfo()."""

    def template_for(self, group: NodeGroup, real_nodes: list[Node]) -> Node:
        for nd in real_nodes:
            if nd.ready:
                return self.sanitize(nd, group.id())
        return group.template_node_info()

    @staticmethod
    def sanitize(node: Node, group_id: str) -> Node:
        """reference: simulator/node_info_utils.go SanitizedNodeInfo — fresh
        identity, churn taints cleared."""
        from kubernetes_autoscaler_tpu.models.api import (
            DELETION_CANDIDATE_TAINT,
            TO_BE_DELETED_TAINT,
        )

        labels = dict(node.labels)
        labels.pop("kubernetes.io/hostname", None)
        return Node(
            name=f"template-{group_id}",
            labels=labels,
            capacity=dict(node.capacity),
            allocatable=dict(node.allocatable),
            taints=[t for t in node.taints
                    if t.key not in (TO_BE_DELETED_TAINT, DELETION_CANDIDATE_TAINT)],
            ready=True,
        )


class CustomResourcesProcessor:
    """reference: processors/customresources/ — GPU nodes whose accelerator
    allocatable has not appeared yet count as unready (prevents premature
    scale-down/up decisions on booting GPU nodes)."""

    def __init__(self, gpu_label: str = "cloud.google.com/gke-accelerator",
                 gpu_resource: str = "nvidia.com/gpu"):
        self.gpu_label = gpu_label
        self.gpu_resource = gpu_resource

    def filter_ready(self, nodes: list[Node]) -> list[Node]:
        for nd in nodes:
            if nd.ready and self.gpu_label in nd.labels:
                if not nd.alloc_or_cap().get(self.gpu_resource):
                    nd.ready = False
        return nodes


class ActionableClusterProcessor:
    """reference: processors/actionablecluster — abort the loop early when the
    cluster has nothing to act on. Scale-from-zero with configured node groups
    is actionable (the reference supports 0-sized groups via templates)."""

    def should_abort(self, nodes: list[Node], node_groups: list[NodeGroup]) -> bool:
        return len(nodes) == 0 and len(node_groups) == 0


@dataclass
class AutoscalingProcessors:
    """The hook bundle threaded through RunOnce (reference:
    processors.AutoscalingProcessors, built by DefaultProcessors)."""

    pod_list_processors: list = field(default_factory=list)
    template_node_info_provider: TemplateNodeInfoProvider = field(
        default_factory=TemplateNodeInfoProvider
    )
    custom_resources: CustomResourcesProcessor = field(
        default_factory=CustomResourcesProcessor
    )
    actionable_cluster: ActionableClusterProcessor = field(
        default_factory=ActionableClusterProcessor
    )
    # observer callbacks (reference: ScaleUpStatusProcessor / ScaleDownStatusProcessor /
    # AutoscalingStatusProcessor / nodegroupchange observers)
    on_scale_up_status: list = field(default_factory=list)
    on_scale_down_status: list = field(default_factory=list)
    on_loop_start: list = field(default_factory=list)

    @classmethod
    def default(cls) -> "AutoscalingProcessors":
        return cls(
            pod_list_processors=[
                ClearTpuRequestsProcessor(),
                FilterExpendableProcessor(),
                FilterDaemonSetPodsProcessor(),
                FilterRecentPodsProcessor(),
            ]
        )

    def run_pod_list(self, pods: list[Pod], ctx: ProcessorContext) -> list[Pod]:
        for p in self.pod_list_processors:
            pods = p.process(pods, ctx)
        return pods
