from kubernetes_autoscaler_tpu.provisioningrequest.api import (
    BEST_EFFORT_ATOMIC_CLASS,
    CHECK_CAPACITY_CLASS,
    PodSet,
    ProvisioningRequest,
)
from kubernetes_autoscaler_tpu.provisioningrequest.orchestrator import (
    ProvReqOrchestrator,
    WrapperOrchestrator,
)

__all__ = [
    "BEST_EFFORT_ATOMIC_CLASS",
    "CHECK_CAPACITY_CLASS",
    "PodSet",
    "ProvisioningRequest",
    "ProvReqOrchestrator",
    "WrapperOrchestrator",
]
