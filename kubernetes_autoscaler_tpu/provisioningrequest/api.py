"""ProvisioningRequest API: atomic capacity reservations.

Reference counterpart: cluster-autoscaler/apis/provisioningrequest/.../v1/
types.go:77-97 and provisioningrequest/ (SURVEY.md §2.7) — a request names a
provisioning class and a list of pod sets (template × count); the autoscaler
answers by either verifying capacity exists now (check-capacity) or scaling
up all-or-nothing (best-effort-atomic-scale-up), then books the capacity for
a TTL by injecting the request's pods into every loop until the booking
expires.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_autoscaler_tpu.models.api import OwnerRef, Pod

# Supported classes (reference: provisioningrequest/supported_classes.go).
CHECK_CAPACITY_CLASS = "check-capacity.autoscaling.x-k8s.io"
BEST_EFFORT_ATOMIC_CLASS = "best-effort-atomic-scale-up.autoscaling.x-k8s.io"
SUPPORTED_CLASSES = (CHECK_CAPACITY_CLASS, BEST_EFFORT_ATOMIC_CLASS)

# Condition types (reference: v1 conditions).
PROVISIONED = "Provisioned"
FAILED = "Failed"
ACCEPTED = "Accepted"
BOOKING_EXPIRED = "BookingExpired"

# Booked capacity is held this long after Provisioned=True (reference:
# provreq booking expiry; checkcapacity pods injection window).
DEFAULT_BOOKING_TTL_S = 10 * 60.0

FAKE_POD_ANNOTATION = "autoscaler.x-k8s.io/provisioning-request-pod"


@dataclass
class PodSet:
    template: Pod
    count: int


@dataclass
class ProvisioningRequest:
    name: str
    namespace: str = "default"
    class_name: str = CHECK_CAPACITY_CLASS
    pod_sets: list[PodSet] = field(default_factory=list)
    conditions: dict[str, tuple[str, str]] = field(default_factory=dict)  # type -> (status, reason)
    creation_time: float = 0.0
    provisioned_time: Optional[float] = None
    booking_ttl_s: float = DEFAULT_BOOKING_TTL_S

    # ---- condition helpers (reference: provreqwrapper) ----

    def set_condition(self, cond: str, status: bool, reason: str = "",
                      now: float | None = None) -> None:
        self.conditions[cond] = ("True" if status else "False", reason)
        if cond == PROVISIONED and status and self.provisioned_time is None:
            self.provisioned_time = now

    def has(self, cond: str) -> bool:
        return self.conditions.get(cond, ("False", ""))[0] == "True"

    def terminal(self) -> bool:
        return self.has(FAILED) or self.has(BOOKING_EXPIRED)

    def booked(self, now: float) -> bool:
        """Capacity is held: Provisioned and the booking TTL has not lapsed."""
        if not self.has(PROVISIONED) or self.terminal():
            return False
        if self.provisioned_time is None:
            return True
        return now - self.provisioned_time < self.booking_ttl_s

    def expire_booking(self, now: float) -> bool:
        """Flip to BookingExpired once the TTL lapses (reference: the provreq
        processor marking BookingExpired); returns True when flipped."""
        if self.has(PROVISIONED) and not self.terminal() \
                and self.provisioned_time is not None \
                and now - self.provisioned_time >= self.booking_ttl_s:
            self.set_condition(BOOKING_EXPIRED, True, "BookingTTLLapsed")
            return True
        return False

    def total_pods(self) -> int:
        return sum(ps.count for ps in self.pod_sets)

    def pods(self) -> list[Pod]:
        """Materialize the request's pods (reference: provreqwrapper builds
        fake pods per pod set for injection/simulation).

        Cached per pod-set identity: booked requests re-inject every loop,
        and stable object identity lets the incremental encoder skip
        re-lowering them (a ProvisioningRequest whose spec changes is a new
        object in the k8s model, so identity-keying is sound)."""
        # key holds the TEMPLATE REFERENCES (not bare ids): retaining them
        # both prevents id reuse after GC and makes identity comparison sound
        key = tuple((ps.template, ps.count) for ps in self.pod_sets)
        cached = getattr(self, "_pods_cache", None)
        if cached is not None and len(cached[0]) == len(key) and all(
                a[0] is b[0] and a[1] == b[1]
                for a, b in zip(cached[0], key)):
            return list(cached[1])
        out: list[Pod] = []
        for si, ps in enumerate(self.pod_sets):
            for i in range(ps.count):
                p = copy.deepcopy(ps.template)
                p.name = f"provreq-{self.name}-{si}-{i}"
                p.namespace = self.namespace
                p.node_name = ""
                p.phase = "Pending"
                p.annotations[FAKE_POD_ANNOTATION] = self.name
                p.owner = OwnerRef(kind="ProvisioningRequest", name=self.name,
                                   uid=f"provreq-{self.namespace}-{self.name}")
                out.append(p)
        self._pods_cache = (key, out)
        return list(out)
