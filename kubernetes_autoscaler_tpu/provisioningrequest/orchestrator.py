"""ProvisioningRequest orchestration: check-capacity and best-effort-atomic.

Reference counterpart: provisioningrequest/orchestrator/ — the
WrapperOrchestrator (wrapper_orchestrator.go) alternates loops between
ProvisioningRequest handling and regular pending pods; checkcapacity/ runs a
booking simulation only (no cloud calls); besteffortatomic/ uses
NodeGroup.AtomicIncreaseSize for all-or-nothing scale-up
(cloud_provider.go:198-204).

TPU re-design: check-capacity is a pure device query — encode the request's
pods against the current node tensors and run the batched pack kernel; a
request fits iff every pod places. Best-effort-atomic reuses the batched
all-groups binpacking estimate and requires some group to absorb the WHOLE
request within its remaining headroom.
"""

from __future__ import annotations

import time

import numpy as np

from kubernetes_autoscaler_tpu.cloudprovider.provider import (
    CloudProvider,
    NodeGroupError,
)
from kubernetes_autoscaler_tpu.estimator.estimator import BinpackingEstimator
from kubernetes_autoscaler_tpu.models.api import Node, Pod
from kubernetes_autoscaler_tpu.models.cluster_state import DEFAULT_DIMS
from kubernetes_autoscaler_tpu.models.encode import (
    encode_cluster,
    encode_node_groups,
)
from kubernetes_autoscaler_tpu.ops.schedule import schedule_pending_on_existing
from kubernetes_autoscaler_tpu.provisioningrequest.api import (
    ACCEPTED,
    BEST_EFFORT_ATOMIC_CLASS,
    CHECK_CAPACITY_CLASS,
    FAILED,
    PROVISIONED,
    SUPPORTED_CLASSES,
    ProvisioningRequest,
)


class ProvReqOrchestrator:
    """Processes all actionable ProvisioningRequests in one pass."""

    def __init__(self, provider: CloudProvider, dims=DEFAULT_DIMS,
                 node_bucket: int = 64, group_bucket: int = 64,
                 max_new_nodes_static: int = 256):
        self.provider = provider
        self.dims = dims
        self.node_bucket = node_bucket
        self.group_bucket = group_bucket
        self.max_new_nodes_static = max_new_nodes_static

    def run(self, provreqs: list[ProvisioningRequest], nodes: list[Node],
            scheduled_pods: list[Pod], now: float | None = None) -> list[str]:
        """Handle every pending supported request; returns names acted on.
        Expired bookings are flipped first (reference: provreq processors)."""
        now = time.time() if now is None else now
        acted = []
        for pr in provreqs:
            pr.expire_booking(now)
        pending = [
            pr for pr in provreqs
            if pr.class_name in SUPPORTED_CLASSES
            and not pr.has(PROVISIONED) and not pr.terminal()
        ]
        for pr in pending:
            pr.set_condition(ACCEPTED, True, "Supported", now)
            if pr.class_name == CHECK_CAPACITY_CLASS:
                ok = self.check_capacity(pr, nodes, scheduled_pods)
            else:
                ok = self.best_effort_atomic(pr, nodes, scheduled_pods, now)
            acted.append(pr.name)
            if ok:
                pr.set_condition(PROVISIONED, True, "CapacityAvailable", now)
            else:
                # check-capacity failure is terminal for this attempt window;
                # atomic failure is retried next loop (reference: checkcapacity
                # sets Failed, besteffortatomic keeps retrying under backoff)
                if pr.class_name == CHECK_CAPACITY_CLASS:
                    pr.set_condition(FAILED, True, "NotEnoughCapacity", now)
        return acted

    # ---- check-capacity (reference: checkcapacity/ — simulation only) ----

    def check_capacity(self, pr: ProvisioningRequest, nodes: list[Node],
                       scheduled_pods: list[Pod]) -> bool:
        enc = encode_cluster(
            nodes, scheduled_pods + pr.pods(), dims=self.dims,
            node_bucket=self.node_bucket, group_bucket=self.group_bucket,
        )
        packed = schedule_pending_on_existing(enc.nodes, enc.specs, enc.scheduled)
        total_pending = int(np.asarray(enc.specs.count).sum())
        return int(np.asarray(packed.scheduled).sum()) >= total_pending

    # ---- best-effort-atomic (reference: besteffortatomic/) ----

    def best_effort_atomic(self, pr: ProvisioningRequest, nodes: list[Node],
                           scheduled_pods: list[Pod], now: float) -> bool:
        # capacity may already exist — atomic requests first try to book it
        if self.check_capacity(pr, nodes, scheduled_pods):
            return True
        enc = encode_cluster(
            nodes, scheduled_pods + pr.pods(), dims=self.dims,
            node_bucket=self.node_bucket, group_bucket=self.group_bucket,
        )
        groups = [g for g in self.provider.node_groups() if g.exist()]
        if not groups:
            return False
        templates = [
            (g.template_node_info(), g.max_size() - g.target_size(),
             getattr(g, "price_per_node", 1.0))
            for g in groups
        ]
        group_tensors = encode_node_groups(
            templates, enc.registry, enc.zone_table, enc.dims,
            daemonsets=getattr(self, "daemonsets", None),
        )
        estimator = BinpackingEstimator(
            enc.dims, max_new_nodes_static=self.max_new_nodes_static
        )
        est = estimator.estimate_all_groups(enc.specs, group_tensors, len(nodes))
        total = int(np.asarray(enc.specs.count).sum())
        # group tensors are padded to the shape bucket; only real rows count
        scheduled = np.asarray(est.scheduled).sum(axis=1)[:len(groups)]
        node_count = np.asarray(est.node_count)[:len(groups)]
        for gi in np.argsort(node_count):                   # cheapest option first
            g = groups[int(gi)]
            if scheduled[gi] < total or node_count[gi] <= 0:
                continue
            if node_count[gi] > g.max_size() - g.target_size():
                continue
            try:
                g.atomic_increase_size(int(node_count[gi]))
                return True
            except NodeGroupError:
                continue
        return False


class ProvReqPodListProcessor:
    """Inject booked requests' pods into the pending list each loop so the
    reserved capacity is held until booking expiry (reference: the provreq
    injector turning accepted ProvReqs into fake pod lists)."""

    def __init__(self, list_provreqs):
        self.list_provreqs = list_provreqs

    def process(self, pods: list[Pod], ctx) -> list[Pod]:
        out = list(pods)
        for pr in self.list_provreqs():
            if pr.booked(ctx.now):
                out.extend(pr.pods())
        return out


class WrapperOrchestrator:
    """Alternate RunOnce loops between ProvisioningRequests and regular pods
    (reference: wrapper_orchestrator.go — the two-population split keeps a
    storm of ProvReqs from starving regular pending pods and vice versa)."""

    def __init__(self, provreq_orchestrator: ProvReqOrchestrator, list_provreqs):
        self.provreq = provreq_orchestrator
        self.list_provreqs = list_provreqs
        self._provreq_turn = False

    def maybe_run(self, nodes: list[Node], scheduled_pods: list[Pod],
                  now: float) -> list[str]:
        """Called once per loop; handles ProvReqs on alternating turns.
        Skips its turn (and keeps it) when there is nothing actionable.
        Booking expiry is checked EVERY loop — a lapsed booking must stop
        holding capacity immediately, not when the turn comes around."""
        reqs = self.list_provreqs()
        for r in reqs:
            r.expire_booking(now)
        self._provreq_turn = not self._provreq_turn
        if not self._provreq_turn:
            return []
        actionable = [
            r for r in reqs
            if r.class_name in SUPPORTED_CLASSES
            and not r.has(PROVISIONED) and not r.terminal()
        ]
        if not actionable:
            self._provreq_turn = False   # don't burn the next turn
            return []
        return self.provreq.run(reqs, nodes, scheduled_pods, now)
