"""Deterministic flight journal: record/replay provenance for every
simulated decision.

`journal.py` writes the append-only record stream (full world snapshot on
the first loop, compact deltas after — pods added/deleted, node/taint/
occupancy changes — plus config/backend identity and digests of every
verdict surface); `harness.py` reconstructs worlds from snapshot+deltas,
re-executes the recorded loops bit-for-bit and emits a drift report;
`python -m kubernetes_autoscaler_tpu.replay <journal>` is the CLI.

docs/REPLAY.md documents the record format and the cross-backend
divergence oracle.
"""

from kubernetes_autoscaler_tpu.replay.journal import (  # noqa: F401
    JournalWriter,
    TenantJournal,
    backend_identity,
    canonical,
    collect_outputs,
    digest_of,
    groups_state,
    surface_digests,
)
