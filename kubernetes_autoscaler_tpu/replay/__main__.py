"""CLI: python -m kubernetes_autoscaler_tpu.replay <journal> [--loop K]
[--backend cpu|tpu] [--diff] [--out PATH]

Replays a flight journal recorded by --journal-dir (StaticAutoscaler),
bench.py --journal, or the tests, and prints the drift report as JSON.
Exit codes: 0 zero drift, 2 drift detected, 1 structural journal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_autoscaler_tpu.replay",
        description="Replay a deterministic flight journal and report drift")
    ap.add_argument("journal", help="journal directory or a single "
                                    "journal-*.jsonl file")
    ap.add_argument("--loop", type=int, default=None,
                    help="replay up to (and report through) this loop index "
                         "(earlier loops still execute — cross-loop state)")
    ap.add_argument("--backend", choices=("cpu", "tpu"), default="",
                    help="force the jax platform before replaying — the "
                         "cross-backend divergence oracle (record on one "
                         "backend, replay on the other)")
    ap.add_argument("--diff", action="store_true",
                    help="include the reason-plane (uint16 bits per "
                         "pod-group × node) localization even for clean "
                         "loops")
    ap.add_argument("--out", default="",
                    help="also write the report JSON to this path")
    args = ap.parse_args(argv)

    if args.backend:
        # must land before anything imports jax
        os.environ["JAX_PLATFORMS"] = args.backend

    from kubernetes_autoscaler_tpu.replay.harness import (
        JournalError,
        replay_journal,
    )

    try:
        report = replay_journal(args.journal, upto=args.loop, diff=args.diff)
    except JournalError as e:
        print(json.dumps({"error": str(e)}), file=sys.stderr)
        return 1
    doc = json.dumps(report, indent=2)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    return 0 if report["zeroDrift"] else 2


if __name__ == "__main__":
    sys.exit(main())
