"""Replay harness: reconstruct journaled worlds and re-execute the loops.

Three stages, each checkable on its own:

  load_journal()       parse + INTEGRITY-check the record stream (every
                       record's seal recomputed; parent-chain breaks
                       collected, not fatal — a rotated journal legally
                       starts mid-history at a snapshot record).
  reconstruct_worlds() apply snapshot+deltas forward, verifying each
                       record's `worldDigest` against the reconstruction
                       (the round-trip contract the writer enforced at
                       record time, re-proven at read time).
  replay_journal()     drive a fresh StaticAutoscaler through the recorded
                       loops — recorded options, recorded `now`s, recorded
                       worlds presented with the recorded object-churn
                       pattern (only changed objects are replaced, so the
                       incremental encoder sees the same delta sequence the
                       recorder saw) — and compare every output surface's
                       digest. The drift report localizes: per-group
                       verdict byte diffs, and a reason-plane pass (uint16
                       refusal bits per pod-group × node, ops/predicates.
                       reason_mask) naming exactly which bits flipped.

Cross-backend divergence mode: record on one backend, replay on another
(`--backend`, or KA_TPU_PACK for the pack-kernel choice). Digest equality
then proves the TPU path and the CPU floor compute identical verdicts —
the correctness oracle docs/REPLAY.md describes.
"""

from __future__ import annotations

import json
import os

import numpy as np

from kubernetes_autoscaler_tpu.replay import journal as rj


class JournalError(ValueError):
    """Structural journal failure (unparseable, bad seal, bad round-trip)."""


def _journal_files(path: str) -> list[str]:
    if os.path.isfile(path):
        return [path]
    if not os.path.isdir(path):
        raise JournalError(f"no journal at {path!r}")
    files = sorted(
        os.path.join(path, f) for f in os.listdir(path)
        if f.startswith("journal-") and f.endswith(".jsonl"))
    if not files:
        raise JournalError(f"no journal-*.jsonl files under {path!r}")
    return files


def load_journal(path: str, run: str | None = None
                 ) -> tuple[dict, list[dict], list[dict]]:
    """→ (meta, records, problems). Seals are recomputed for every record;
    a mismatch is fatal (the file is corrupt, not merely drifted). Parent
    chain breaks (rotation pruning) are collected as problems.

    A journal DIRECTORY may hold several RUNS: each autoscaler process
    starts a fresh chain (first record: a snapshot with parent="" at loop
    0) and never deletes a predecessor's files at startup — they are
    evidence (only the rotation size bound may later prune them,
    oldest-first, with drop accounting). Stitching runs into one stream
    would replay run 2
    under run 1's accumulated cross-loop state (timers, backoffs) the
    recorder never had, reporting spurious drift — so only ONE run is
    loaded. `run` selects it by chain head: a digest prefix of any run's
    FIRST record (the heads the `previous-runs` problem lists); None keeps
    the historical default, the LAST run. The other runs are surfaced as a
    `previous-runs` problem either way (count/loops plus a per-run `runs`
    list of head digests and loop ranges), and `meta` is the meta line
    governing the loaded run."""
    runs: list[tuple[dict, list[dict], list[dict]]] = []
    meta: dict = {}
    records: list[dict] = []
    problems: list[dict] = []
    last_meta: dict = {}
    files = _journal_files(path)
    for fp in files:
        with open(fp) as f:
            lines = [(ln, line.strip()) for ln, line in enumerate(f)
                     if line.strip()]
        for i, (ln, line) in enumerate(lines):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                if fp == files[-1] and i == len(lines) - 1:
                    # a torn TRAILING line (writer killed mid-append /
                    # ENOSPC on an old build): the records before it are
                    # intact evidence — surface, don't destroy
                    problems.append({"kind": "torn-tail", "file": fp,
                                     "line": ln + 1})
                    break
                raise JournalError(f"{fp}:{ln + 1}: not JSON ({e})")
            if rec.get("kind") == "meta":
                last_meta = rec
                if records and rec.get("config") != meta.get("config"):
                    problems.append({"kind": "config-change",
                                     "file": fp, "line": ln + 1})
                continue
            sealed = rec.get("digest", "")
            if rj.seal_record(dict(rec))["digest"] != sealed:
                raise JournalError(
                    f"{fp}:{ln + 1}: record seal mismatch (loop "
                    f"{rec.get('loop')}) — journal is corrupt")
            if records and rec.get("kind") == "snapshot" \
                    and rec.get("parent") == "":
                # a fresh process re-journaled into the same dir: run
                # boundary (rotation keeps the parent chain; only a new
                # writer starts from parent="")
                runs.append((meta, records, problems))
                meta, records, problems = {}, [], []
            if not records:
                meta = last_meta
            if records and rec.get("kind") == "delta" \
                    and rec.get("parent") != records[-1]["digest"]:
                raise JournalError(
                    f"{fp}:{ln + 1}: delta record's parent does not "
                    f"match the previous record")
            if records and rec.get("kind") == "snapshot" \
                    and rec.get("parent") != records[-1]["digest"]:
                # legal after rotation pruned the ancestor files
                problems.append({"kind": "chain-break", "file": fp,
                                 "loop": rec.get("loop")})
            records.append(rec)
    if not records:
        raise JournalError(f"journal at {path!r} holds no records")
    runs.append((meta, records, problems))
    if run is not None:
        matches = [r for r in runs
                   if r[1] and r[1][0].get("digest", "").startswith(run)]
        if not matches:
            heads = [r[1][0].get("digest", "")[:16] for r in runs if r[1]]
            raise JournalError(
                f"no run with chain head {run!r} in {path!r} "
                f"(heads: {', '.join(heads) or 'none'})")
        if len(matches) > 1:
            raise JournalError(
                f"chain-head prefix {run!r} is ambiguous in {path!r}")
        meta, records, problems = matches[0]
    else:
        meta, records, problems = runs[-1]
    if records[0].get("kind") != "snapshot":
        raise JournalError("journal starts with a delta record (its "
                           "snapshot base was pruned past keep_files?)")
    others = [r for r in runs if r[1] is not records]
    if others:
        problems.append({
            "kind": "previous-runs", "count": len(others),
            "loops": sum(len(r[1]) for r in others),
            # selectable chain heads for load_journal(run=...) / the
            # lineage CLI's --run
            "runs": [{"head": r[1][0].get("digest", ""),
                      "firstLoop": r[1][0].get("loop"),
                      "lastLoop": r[1][-1].get("loop"),
                      "records": len(r[1])} for r in others],
        })
    return meta, records, problems


def reconstruct_worlds(records: list[dict]):
    """Yield (record, world_index) applying snapshot+deltas forward, each
    step digest-verified against the record's `worldDigest`."""
    idx = None
    for rec in records:
        if rec["kind"] == "snapshot":
            idx = rj.index_from_snapshot(rec["world"])
        else:
            if idx is None:
                raise JournalError(f"loop {rec['loop']}: delta without a "
                                   f"preceding snapshot")
            idx = rj.apply_world_delta(idx, rec.get("delta", {}))
        got = idx.digest()
        if got != rec["worldDigest"]:
            raise JournalError(
                f"loop {rec['loop']}: reconstructed world digest {got} != "
                f"recorded {rec['worldDigest']} (round-trip check failed)")
        yield rec, idx


def options_from_meta(meta: dict, neutralize: bool = True):
    """Rebuild the recorded AutoscalingOptions (unknown/renamed fields are
    dropped — forward compatibility over strictness).

    With `neutralize` (the replay path), side-effecting fields are cleared:
    no journaling of the replay itself, no flight-recorder dumps into the
    RECORDER's evidence directory, no SLO-breach accounting from a slower
    replay machine. The report's config fingerprint is computed with
    neutralize=False so a faithful replay matches the recorded one."""
    import dataclasses

    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )

    d = dict(meta.get("options") or {})
    ngd = d.pop("node_group_defaults", None)
    known = {f.name for f in dataclasses.fields(AutoscalingOptions)}
    opts = AutoscalingOptions(**{k: v for k, v in d.items() if k in known})
    if isinstance(ngd, dict):
        kn = {f.name for f in dataclasses.fields(NodeGroupDefaults)}
        opts.node_group_defaults = NodeGroupDefaults(
            **{k: v for k, v in ngd.items() if k in kn})
    if neutralize:
        opts.journal_dir = ""
        opts.flight_recorder_dir = ""
        opts.loop_wallclock_budget_s = 0.0
        # the replay's shadow audit re-runs the recorded sampling (same
        # cursor seeds via parent_override) but must not write divergence
        # bundles into the RECORDER's evidence directory
        opts.shadow_audit_dir = ""
    return opts


class ReplaySource:
    """ClusterDataSource over reconstructed worlds. Object identity follows
    the recorded churn: only added/modified entries get fresh objects, so
    the incremental encoder's replace-on-update contract sees the same
    delta sequence the recorder's source produced."""

    def __init__(self):
        self._nodes: dict[str, tuple[str, object]] = {}   # name -> (canon, Node)
        self._pods: dict[str, tuple[str, object]] = {}    # ns/name -> (canon, Pod)

    def set_world(self, idx: "rj._WorldIndex") -> None:
        self._nodes = self._sync(self._nodes, idx.nodes, rj.node_from_dict)
        self._pods = self._sync(self._pods, idx.pods, rj.pod_from_dict)

    @staticmethod
    def _sync(store: dict, canon_map: dict[str, str], build):
        out = {}
        for key, canon in canon_map.items():
            held = store.get(key)
            if held is not None and held[0] == canon:
                out[key] = held
            else:
                out[key] = (canon, build(json.loads(canon)))
        return out

    def list_nodes(self):
        return [obj for _, obj in self._nodes.values()]

    def list_pods(self):
        return [obj for _, obj in self._pods.values()]

    # EvictionSink: actuation during replay must not touch anything real
    def evict(self, pod, node, grace_period_s=None) -> None:
        pass


def _sync_provider(provider, groups: list[dict], template_cache: dict) -> None:
    """Force the in-memory provider to the recorded node-group states
    (sizes, template, price, node membership). Reaches into the test
    provider's internals on purpose — replay owns this provider outright."""
    seen = set()
    for gs in groups:
        canon = rj.canonical(gs["template"])
        cached = template_cache.get(gs["id"])
        if cached is None or cached[0] != canon:
            cached = (canon, rj.node_from_dict(gs["template"]))
            template_cache[gs["id"]] = cached
        tmpl = cached[1]
        g = provider._groups.get(gs["id"])
        if g is None:
            g = provider.add_node_group(
                gs["id"], tmpl, min_size=gs["min"], max_size=gs["max"],
                target=gs["target"], price_per_node=gs["price"])
        else:
            g._min, g._max = gs["min"], gs["max"]
            g._target = gs["target"]
            g._template = tmpl
            g.price_per_node = gs["price"]
            g._instances = []
        seen.add(gs["id"])
    for gid in list(provider._groups):
        if gid not in seen:
            del provider._groups[gid]
    provider._node_to_group = {
        name: gs["id"] for gs in groups for name in gs.get("members", [])}


def _reason_plane_diff(rec: dict, world: "rj._WorldIndex",
                       drifted_groups: set[int] | None = None) -> list[dict]:
    """Reason-plane localization for a drifted loop: encode the record's
    world fresh and dispatch `reason_mask` — uint16 refusal bits per
    (pod-group × node). The recorded baseline per pair is derived from the
    recorded outputs (a group the recorder scheduled carried zero bits; a
    refused group carries its recorded constraint names), so each entry
    names the pod-group (exemplar pod), the node, and WHICH bits flipped."""
    from kubernetes_autoscaler_tpu.models.encode import encode_cluster
    from kubernetes_autoscaler_tpu.ops import predicates as preds

    snap = rj.snapshot_from_index(world)
    nodes = [rj.node_from_dict(d) for d in snap["nodes"]]
    pods = [rj.pod_from_dict(d) for d in snap["pods"]]
    enc = encode_cluster(nodes, pods)
    bits = np.asarray(preds.reason_mask(enc.nodes, enc.specs))
    counts = np.asarray(enc.specs.count)
    recorded = rec["outputs"]
    rec_sched = rj.decode_verdict_plane(recorded["verdict"])
    rec_reasons = {g["group"]: g for g in recorded["reasons"]["groups"]}
    out: list[dict] = []
    pending_rows = [gi for gi in range(len(enc.group_pods))
                    if counts[gi] > 0 or (drifted_groups and gi in drifted_groups)]
    for gi in pending_rows:
        if drifted_groups is not None and gi not in drifted_groups:
            continue
        exemplar = ""
        if gi < len(enc.group_pods) and enc.group_pods[gi]:
            exemplar = enc.pending_pods[enc.group_pods[gi][0]].name
        rec_row = rec_reasons.get(gi)
        rec_bits = set(rec_row["constraints"]) if rec_row else set()
        if gi < rec_sched.shape[0] and rec_sched[gi] > 0:
            rec_bits = set()          # the recorder scheduled this group
        for ni, name in enumerate(enc.node_names):
            names = set(preds.reason_bit_names(int(bits[gi, ni])))
            flipped = sorted(names ^ rec_bits)
            if not names and not flipped:
                continue
            out.append({"group": int(gi), "exemplarPod": exemplar,
                        "node": name,
                        "replayedBits": sorted(names),
                        "recordedBits": sorted(rec_bits),
                        "flipped": flipped})
    return out


def _verdict_diff(rec: dict, outputs: dict) -> list[dict]:
    a = rj.decode_verdict_plane(rec["outputs"]["verdict"])
    b = rj.decode_verdict_plane(outputs["verdict"])
    n = max(a.shape[0], b.shape[0])
    out = []
    for gi in range(n):
        ra = int(a[gi]) if gi < a.shape[0] else None
        rb = int(b[gi]) if gi < b.shape[0] else None
        if ra != rb:
            out.append({"group": gi, "recorded": ra, "replayed": rb})
    return out


def replay_journal(path: str, upto: int | None = None, diff: bool = False,
                   keep_autoscaler: bool = False,
                   options_override: dict | None = None) -> dict:
    """Re-execute a journal; → drift report. `upto` stops after that loop
    index (earlier loops still replay — the autoscaler's cross-loop state
    is part of the recorded history). `diff=True` adds the reason-plane
    localization even for clean loops' drifted groups (drifted loops always
    get it). `options_override` force-sets option fields AFTER the recorded
    options are rebuilt — the fused-loop cross-oracle records with
    --fused-loop and replays with {"fused_loop": False} (or vice versa) to
    prove the two execution modes make bit-identical decisions
    (docs/FUSED_LOOP.md)."""
    from kubernetes_autoscaler_tpu.cloudprovider.test_provider import (
        TestCloudProvider,
    )
    from kubernetes_autoscaler_tpu.core.static_autoscaler import (
        StaticAutoscaler,
    )

    meta, records, problems = load_journal(path)
    options = options_from_meta(meta)
    for k, v in (options_override or {}).items():
        setattr(options, k, v)
    provider = TestCloudProvider()
    src = ReplaySource()
    clock = {"now": 0.0}
    autoscaler = StaticAutoscaler(provider, src, options=options,
                                  eviction_sink=src,
                                  walltime=lambda: clock["now"])
    autoscaler.capture_verdicts = True
    template_cache: dict = {}
    drift_loops: list[int] = []
    loops: list[dict] = []
    for rec, world in reconstruct_worlds(records):
        if upto is not None and rec["loop"] > upto:
            break
        clock["now"] = rec["now"]
        if getattr(autoscaler, "shadow_auditor", None) is not None:
            # cursor-seeding contract (docs/REPLAY.md): the recorder
            # seeded loop k's sample with record k-1's digest; the record
            # carries exactly that as `parent`, so the replayed audit
            # draws the SAME cells without a live journal
            autoscaler.shadow_auditor.parent_override = rec.get(
                "parent", "")
        src.set_world(world)
        # groups-only parse: snapshot_from_index would json-parse every
        # node/pod canon per loop just to discard them (ReplaySource
        # already syncs those churn-only)
        _sync_provider(provider,
                       [json.loads(c) for c in world.groups.values()],
                       template_cache)
        status = autoscaler.run_once(now=rec["now"])
        outputs = rj.collect_outputs(autoscaler, status)
        digests = rj.surface_digests(outputs)
        drifted = sorted(k for k in rec["digests"]
                         if digests.get(k) != rec["digests"][k])
        entry: dict = {"loop": rec["loop"], "record": rec["digest"],
                       "kind": rec["kind"], "surfaces": digests,
                       "drift": drifted,
                       # execution-mode provenance, recorded vs replayed
                       # (docs/FUSED_LOOP.md): surface digests are mode-
                       # independent, so a fusedMode mismatch here is
                       # informational, never drift — it also lets the
                       # report verify the phased twin saw identical worlds
                       # even when the recorder harvested a SPECULATIVE
                       # result for the loop
                       "fusedMode": {"recorded": rec.get("fusedMode", ""),
                                     "replayed": status.fused_mode},
                       "loopDeviceRoundTrips": {
                           "recorded": rec.get("loopDeviceRoundTrips"),
                           "replayed": status.loop_device_round_trips},
                       "speculation": {"recorded": rec.get("speculation",
                                                           ""),
                                       "replayed": status.speculation}}
        if drifted:
            drift_loops.append(rec["loop"])
            vdiff = _verdict_diff(rec, outputs)
            entry["verdictDiff"] = vdiff
            entry["scaleUpDiff"] = {
                "recorded": rec["outputs"]["scaleUp"],
                "replayed": outputs["scaleUp"],
            } if "scaleUp" in drifted else None
            entry["drainDiff"] = {
                "recorded": rec["outputs"]["drain"],
                "replayed": outputs["drain"],
            } if "drain" in drifted else None
            groups = {d["group"] for d in vdiff} or None
            entry["reasonDiff"] = _reason_plane_diff(rec, world, groups)
        elif diff:
            # clean loop under --diff: localize over ALL pending rows
            # (None — an empty set would filter every group out)
            entry["reasonDiff"] = _reason_plane_diff(rec, world, None)
        loops.append(entry)
    report = {
        "journal": path,
        "loops": len(loops),
        "firstLoop": records[0]["loop"],
        "driftLoops": drift_loops,
        "zeroDrift": not drift_loops,
        "problems": problems,
        # fingerprinted WITHOUT the replay-side neutralizations (journal/
        # flight-recorder paths, wallclock budget) — those are replay
        # hygiene, not config drift; a faithful same-version replay matches
        "config": {"recorded": meta.get("config", ""),
                   "replayed": rj.options_fingerprint(
                       options_from_meta(meta, neutralize=False))},
        "backend": {"recorded": records[-1].get("backend", {}),
                    "replayed": rj.backend_identity(
                        options.node_shape_bucket,
                        options.group_shape_bucket)},
        "records": loops,
    }
    if records[0]["loop"] != 0:
        # rotation pruned the journal's origin: cross-loop autoscaler state
        # (unneeded clocks, backoffs) could not be rebuilt from loop 0 —
        # stateful surfaces (drain) may legitimately differ
        report["stateHorizon"] = records[0]["loop"]
    lossy = sorted({s for rec in records
                    for s in (rec.get("fidelity") or {}).get(
                        "unrecordedSources", [])})
    if lossy:
        # the recorder's source exposed surfaces the v1 record format does
        # not carry (PDBs, workloads, DRA/CSI…) — replay may legitimately
        # drift on loops where they influenced a decision
        report["fidelity"] = {"unrecordedSources": lossy}
    aud = getattr(autoscaler, "shadow_auditor", None)
    if aud is not None:
        # the replayed audit's sample provenance: loop-for-loop equal to
        # the recorder's sample_log when the journal is faithful (the
        # determinism pin in tests/test_shadow_audit.py)
        report["audit"] = {
            "samples": list(aud.sample_log),
            "checks": {s: dict(c) for s, c in aud.checks.items()},
            "divergences": aud.divergences,
        }
    if keep_autoscaler:
        report["_autoscaler"] = autoscaler
    return report
