"""JournalWriter: every RunOnce as a self-contained, replayable record.

The journal is the provenance layer under the trace/reason/metric surfaces
(PRs 3/4/8): those say what a loop LOOKED like; the journal lets you take
the loop offline and re-execute it. Record format (JSONL, one object per
line, one file per rotation window):

  meta line   {"kind": "meta", "options": {...}, "config": fp, ...}
              — first line of every file; carries the full
              AutoscalingOptions so a replay runs under the recorded
              config, not whatever the harness defaults to.
  record      {"v": 1, "loop": k, "kind": "snapshot" | "delta",
               "parent": <digest of record k-1>, "now": <loop now>,
               "config": <options fingerprint>, "backend": {...},
               "world" | "delta": {...}, "worldDigest": <digest>,
               "outputs": {...}, "digests": {verdict, scaleUp, reasons,
               drain}, "digest": <record digest>}

World encoding: the source view at the TOP of the loop (nodes, pods as
listed, node-group states incl. membership), serialized object-per-object
in listing order. A delta carries only added/deleted/modified objects
against the previous record; the writer REPLAYS its own delta before
committing and falls back to a full snapshot if the reconstruction is not
digest-identical — every committed record reconstructs exactly, by
construction. Digests are sha256/16hex over a canonical JSON encoding, so
they are process- and platform-independent.

Bounded by --journal-max-mb with rotation (each file re-opens with a meta
line + full snapshot, so any retained file is independently replayable)
and drop accounting (`journal_dropped_total{reason}`).

`TenantJournal` is the sidecar's per-tenant analog: a bounded in-memory
ring of delta/verdict provenance records, persisted only on an SLO breach
or backpressure (the TailSampler retention pattern), capped like the
tenant table.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import deque

import numpy as np

from kubernetes_autoscaler_tpu.models.api import (
    AffinityTerm,
    Node,
    NodeSelectorRequirement,
    OwnerRef,
    Pod,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)

JOURNAL_VERSION = 1
_FILE_PREFIX = "journal-"
_FILE_SUFFIX = ".jsonl"

_RECORDS_HELP = "Flight-journal records committed"
_BYTES_HELP = "Flight-journal bytes appended"
_ROTATIONS_HELP = "Flight-journal file rotations"
_DROPPED_HELP = "Flight-journal records dropped, by reason"


# ---- canonical encoding + digests ----
#
# One shared implementation (utils/canonical.py) for the journal AND the
# device-resident WorldStore (models/world_store.py): both must agree on
# what "changed" means, by construction — re-exported here because the
# journal is the historical home these names are imported from.

from kubernetes_autoscaler_tpu.utils.canonical import (  # noqa: F401
    canon_map as _canon_map,
    canonical,
    digest_of,
    digest_strs as _digest_strs,
)


def backend_identity(node_bucket: int | None = None,
                     group_bucket: int | None = None) -> dict:
    """Backend + shape-class identity stamped into every record — the
    cross-backend divergence oracle compares records ACROSS these."""
    try:
        import jax

        platform, jax_ver = jax.default_backend(), jax.__version__
    except Exception:  # pragma: no cover — jax always importable in-repo
        platform, jax_ver = "none", ""
    out = {"platform": platform, "jax": jax_ver,
           "pack": os.environ.get("KA_TPU_PACK", "")}
    if node_bucket is not None:
        out["shape"] = {"nodeBucket": int(node_bucket),
                        "groupBucket": int(group_bucket or 0)}
    return out


def options_fingerprint(options) -> str:
    return digest_of(dataclasses.asdict(options))


# ---- world serialization (object boundary ↔ JSON) ----

def node_to_dict(nd: Node) -> dict:
    return dataclasses.asdict(nd)


def node_from_dict(d: dict) -> Node:
    d = dict(d)
    d["taints"] = [Taint(**t) for t in d.get("taints", [])]
    return Node(**d)


def pod_to_dict(p: Pod) -> dict:
    return dataclasses.asdict(p)


def _nsr(d: dict) -> NodeSelectorRequirement:
    d = dict(d)
    d["values"] = tuple(d.get("values", ()))
    return NodeSelectorRequirement(**d)


def _aff_term(d: dict) -> AffinityTerm:
    d = dict(d)
    d["namespaces"] = tuple(d.get("namespaces", ()))
    return AffinityTerm(**d)


def pod_from_dict(d: dict) -> Pod:
    d = dict(d)
    d["required_node_affinity"] = [_nsr(x)
                                   for x in d.get("required_node_affinity", [])]
    d["node_affinity_terms"] = [[_nsr(x) for x in term]
                                for term in d.get("node_affinity_terms", [])]
    d["tolerations"] = [Toleration(**t) for t in d.get("tolerations", [])]
    d["host_ports"] = tuple((int(p), proto)
                            for p, proto in d.get("host_ports", ()))
    d["anti_affinity"] = [_aff_term(t) for t in d.get("anti_affinity", [])]
    d["pod_affinity"] = [_aff_term(t) for t in d.get("pod_affinity", [])]
    spreads = []
    for c in d.get("topology_spread", []):
        c = dict(c)
        c["match_label_keys"] = tuple(c.get("match_label_keys", ()))
        spreads.append(TopologySpreadConstraint(**c))
    d["topology_spread"] = spreads
    owner = d.get("owner")
    d["owner"] = OwnerRef(**owner) if owner else None
    d["pvc_refs"] = tuple(d.get("pvc_refs", ()))
    d["resource_claims"] = tuple(d.get("resource_claims", ()))
    return Pod(**d)


def groups_state(provider, nodes: list[Node]) -> list[dict]:
    """Node-group states at the top of the loop: sizes, template, price and
    node membership (replay needs membership to rebuild
    node_group_for_node)."""
    members: dict[str, list[str]] = {}
    for nd in nodes:
        g = provider.node_group_for_node(nd)
        if g is not None:
            members.setdefault(g.id(), []).append(nd.name)
    out = []
    for g in provider.node_groups():
        if not g.exist():
            continue
        out.append({
            "id": g.id(),
            "min": int(g.min_size()),
            "max": int(g.max_size()),
            "target": int(g.target_size()),
            "price": float(getattr(g, "price_per_node", 1.0)),
            "template": node_to_dict(g.template_node_info()),
            "members": members.get(g.id(), []),
        })
    return out


# ---- the outputs surface (shared verbatim by recorder and replayer) ----

def collect_outputs(autoscaler, status) -> dict:
    """One loop's decision surfaces, exactly as the loop computed them:
    the filter-out-schedulable verdict plane (per-group scheduled counts,
    byte-preserved), the scale-up verdict incl. the chosen expansion
    option, the reason plane (NoScaleUp groups with constraint bits,
    unremovable nodes, drain-failure attribution) and the drain decisions.
    The recorder digests this dict; the replay harness rebuilds it from the
    re-executed loop with THIS SAME function, so digest equality means the
    decisions match byte for byte."""
    plane = getattr(autoscaler, "last_verdict_plane", None)
    verdict = {
        "pending": int(status.pending_pods),
        "groups": int(plane.shape[0]) if plane is not None else 0,
        "scheduledHex": (plane.astype("<i4").tobytes().hex()
                         if plane is not None else ""),
    }
    su = status.scale_up
    scale_up = None
    if su is not None:
        best = None
        if su.best is not None:
            best = {"group": su.best.group_id,
                    "nodes": int(su.best.node_count),
                    "pods": int(su.best.pod_count),
                    "waste": float(su.best.waste),
                    "price": float(su.best.price)}
        scale_up = {"scaledUp": bool(su.scaled_up),
                    "increases": dict(sorted(su.increases.items())),
                    "errors": dict(sorted(su.errors.items())),
                    "podsHelped": int(su.pods_helped),
                    "podsRemaining": int(su.pods_remaining),
                    "best": best}
    orch = autoscaler.scale_up_orchestrator
    planner = autoscaler.planner
    reasons = {
        "noScaleUp": dict(sorted(orch.last_noscaleup.items())),
        "groups": [
            {"group": int(g["group"]), "exemplarPod": g["exemplarPod"],
             "pods": int(g["pods"]), "reason": g["reason"],
             "constraints": dict(sorted(g["constraints"].items()))}
            for g in orch.last_noscaleup_groups
        ],
        "unremovable": {n: e[1] for n, e in
                        sorted(planner.unremovable.entries.items())},
        "drainFail": dict(sorted(planner.state.drain_fail_detail.items())),
    }
    drain = {"unneeded": sorted(status.unneeded_nodes),
             "deleted": sorted(status.scale_down_deleted)}
    return {"ran": bool(status.ran), "aborted": status.aborted_reason,
            "verdict": verdict, "scaleUp": scale_up, "reasons": reasons,
            "drain": drain}


def surface_digests(outputs: dict) -> dict:
    return {
        "verdict": digest_of(outputs["verdict"]),
        "scaleUp": digest_of(outputs["scaleUp"]),
        "reasons": digest_of(outputs["reasons"]),
        "drain": digest_of(outputs["drain"]),
    }


def decode_verdict_plane(verdict: dict) -> np.ndarray:
    """The byte-preserved per-group scheduled counts back as int32[G]."""
    raw = bytes.fromhex(verdict.get("scheduledHex", ""))
    return np.frombuffer(raw, dtype="<i4").copy()


def seal_record(rec: dict) -> dict:
    """(Re)compute a record's digest over everything but the seal itself.
    Exposed so tests/tools can perturb a record and keep it structurally
    valid — the drift then shows up in the OUTPUT digests, where it
    belongs, not as a corrupted file."""
    body = {k: v for k, v in rec.items() if k != "digest"}
    rec["digest"] = digest_of(body)
    return rec


def world_digest(node_canons: list[str], pod_canons: list[str],
                 group_canons: list[str]) -> str:
    """Order-sensitive digest of the full world: listing order is part of
    the contract (the incremental encoder's row/slot assignment follows
    arrival order, so replay must present objects in the recorded order)."""
    return _digest_strs(["N", *node_canons, "P", *pod_canons,
                         "G", *group_canons])


class _WorldIndex:
    """Ordered name → canonical-JSON maps for one world (the delta base)."""

    __slots__ = ("nodes", "pods", "groups")

    def __init__(self, nodes: dict[str, str], pods: dict[str, str],
                 groups: dict[str, str]):
        self.nodes = nodes
        self.pods = pods
        self.groups = groups

    def digest(self) -> str:
        return world_digest(list(self.nodes.values()),
                            list(self.pods.values()),
                            list(self.groups.values()))


def _section_delta(prev: dict[str, str], cur: dict[str, str]
                   ) -> tuple[list, list, list]:
    """(added canon-parsed dicts, deleted keys, modified canon-parsed dicts)."""
    add, mod = [], []
    for k, c in cur.items():
        p = prev.get(k)
        if p is None:
            add.append(json.loads(c))
        elif p != c:
            mod.append(json.loads(c))
    dele = [k for k in prev if k not in cur]
    return add, dele, mod


def apply_section_delta(prev: dict[str, str], delta: dict, key_of,
                        section: str) -> dict[str, str]:
    """Rebuild one ordered section map from its predecessor + delta. Order
    contract: surviving entries keep their relative order, modified entries
    stay in place, added entries append in recorded order."""
    dele = set(delta.get(f"{section}Del", []))
    mods = {key_of(d): canonical(d) for d in delta.get(f"{section}Mod", [])}
    out: dict[str, str] = {}
    for k, c in prev.items():
        if k in dele:
            continue
        out[k] = mods.pop(k, c)
    if mods:
        # a "modified" key the base does not carry — structurally invalid
        raise ValueError(f"delta modifies unknown {section} keys: "
                         f"{sorted(mods)}")
    for d in delta.get(f"{section}Add", []):
        out[key_of(d)] = canonical(d)
    return out


def _node_key(d: dict) -> str:
    return d["name"]


def _pod_key(d: dict) -> str:
    return f"{d['namespace']}/{d['name']}"


def _group_key(d: dict) -> str:
    return d["id"]


def apply_world_delta(prev: _WorldIndex, delta: dict) -> _WorldIndex:
    return _WorldIndex(
        apply_section_delta(prev.nodes, delta, _node_key, "nodes"),
        apply_section_delta(prev.pods, delta, _pod_key, "pods"),
        apply_section_delta(prev.groups, delta, _group_key, "groups"),
    )


def snapshot_from_index(idx: _WorldIndex) -> dict:
    return {"nodes": [json.loads(c) for c in idx.nodes.values()],
            "pods": [json.loads(c) for c in idx.pods.values()],
            "groups": [json.loads(c) for c in idx.groups.values()]}


def index_from_snapshot(world: dict) -> _WorldIndex:
    return _WorldIndex(
        {_node_key(d): canonical(d) for d in world.get("nodes", [])},
        {_pod_key(d): canonical(d) for d in world.get("pods", [])},
        {_group_key(d): canonical(d) for d in world.get("groups", [])},
    )


# ---- the writer ----

class JournalWriter:
    """Append-only, size-bounded, rotating flight journal.

    Not thread-safe by design: it is owned by the control-loop thread the
    way the FlightRecorder's tracer is (one record per RunOnce, begun and
    committed on the loop)."""

    def __init__(self, dir: str, max_mb: float = 64.0, keep_files: int = 4,
                 registry=None, options=None, meta: dict | None = None):
        self.dir = dir
        self.max_bytes = max(int(max_mb * 1_000_000), 10_000)
        self.keep_files = max(int(keep_files), 1)
        # each file is bounded so the RETAINED set (keep_files files)
        # respects --journal-max-mb in total
        self.rotate_bytes = max(self.max_bytes // self.keep_files, 5_000)
        self.registry = registry
        self._options = options
        self.config_fp = options_fingerprint(options) if options else ""
        self._meta_extra = meta or {}
        self._node_bucket = getattr(options, "node_shape_bucket", None)
        self._group_bucket = getattr(options, "group_shape_bucket", None)
        self.loop = 0
        self.records = 0
        self.bytes = 0
        self.rotations = 0
        self.snapshot_fallbacks = 0
        self.drops: dict[str, int] = {}
        self.overhead_ns = 0
        self._prev: _WorldIndex | None = None
        self._last_digest = ""
        self._staged: dict | None = None
        self._staged_index: _WorldIndex | None = None
        # per-loop top-level record annotations, set by the autoscaler
        # before commit() and cleared after each sealed record
        self.loop_annotations: dict = {}
        # canonical-form cache keyed by OBJECT IDENTITY (value holds the
        # object reference, so a freed id can never alias — the
        # host_mirror_token pattern). Valid under the repo-wide
        # replace-on-update contract the incremental encoder already
        # rides: a changed object is a NEW object. This turns the per-loop
        # serialization cost from O(world) to O(churn).
        self._canon_nodes: dict[int, tuple] = {}
        self._canon_pods: dict[int, tuple] = {}
        self._file = None
        self._file_seq = -1
        self._file_bytes = 0
        self._file_records: dict[str, int] = {}
        os.makedirs(self.dir, exist_ok=True)

    # -- record lifecycle (begin at the top of RunOnce, commit at the end) --

    def begin(self, nodes: list[Node], pods: list[Pod], groups: list[dict],
              now: float, fidelity: dict | None = None) -> None:
        """Stage this loop's input world. Serialization happens HERE — before
        the loop body mutates anything in place (soft taints, lowering
        passes), so the record is the world the loop actually consumed."""
        t0 = time.perf_counter_ns()
        try:
            self._canon_nodes, node_map = _canon_map(
                nodes, lambda nd: nd.name, node_to_dict, self._canon_nodes)
            self._canon_pods, pod_map = _canon_map(
                pods, lambda p: f"{p.namespace}/{p.name}", pod_to_dict,
                self._canon_pods)
            cur = _WorldIndex(node_map, pod_map,
                              {g["id"]: canonical(g) for g in groups})
            wd = cur.digest()
            kind = "snapshot" if (self._prev is None or self._file is None) \
                else "delta"
            body: dict = {}
            if kind == "delta":
                delta: dict = {}
                for section, prev_m, cur_m, key_of in (
                        ("nodes", self._prev.nodes, cur.nodes, _node_key),
                        ("pods", self._prev.pods, cur.pods, _pod_key),
                        ("groups", self._prev.groups, cur.groups, _group_key)):
                    add, dele, mod = _section_delta(prev_m, cur_m)
                    if add:
                        delta[f"{section}Add"] = add
                    if dele:
                        delta[f"{section}Del"] = dele
                    if mod:
                        delta[f"{section}Mod"] = mod
                # the round-trip guarantee is enforced at WRITE time: replay
                # the delta against the previous index; any reconstruction
                # mismatch (e.g. a source re-ordering its listing) falls
                # back to a full snapshot instead of committing a record
                # that cannot reproduce its own world
                if apply_world_delta(self._prev, delta).digest() == wd:
                    body["delta"] = delta
                else:
                    kind = "snapshot"
                    self.snapshot_fallbacks += 1
            if kind == "snapshot":
                body["world"] = snapshot_from_index(cur)
            self._staged = {
                "v": JOURNAL_VERSION, "loop": self.loop, "kind": kind,
                "parent": self._last_digest, "now": float(now),
                "config": self.config_fp,
                "backend": backend_identity(self._node_bucket,
                                            self._group_bucket),
                **body,
                "worldDigest": wd,
                **({"fidelity": fidelity} if fidelity else {}),
            }
            self._staged_index = cur
        finally:
            self.overhead_ns += time.perf_counter_ns() - t0

    def commit(self, outputs: dict) -> tuple[int, str] | None:
        """Attach the loop's outputs + digests, seal, append. Returns the
        journal cursor (loop, record digest) the observability surfaces
        stamp — None when the append failed and the record was dropped."""
        t0 = time.perf_counter_ns()
        try:
            rec = self._staged
            if rec is None:
                raise RuntimeError("commit without begin")
            self._staged = None
            rec["outputs"] = outputs
            rec["digests"] = surface_digests(outputs)
            # loop-scoped annotations (fused-loop provenance: fusedMode /
            # loopDeviceRoundTrips / speculation — docs/FUSED_LOOP.md) ride
            # the record TOP LEVEL, not `outputs`: the surface digests the
            # replay drift comparison checks stay mode-independent, so a
            # record written fused replays clean on the phased oracle
            if self.loop_annotations:
                for k, v in self.loop_annotations.items():
                    rec.setdefault(k, v)
                self.loop_annotations = {}
            seal_record(rec)
            line = canonical(rec) + "\n"
            try:
                self._append(line)
            except OSError:
                # a full/readonly disk must never sink the loop — but the
                # dropped record exists in no file, so it gets NO cursor
                # (stamping its digest onto /snapshotz or the trace would
                # name provenance nothing can ever resolve)
                self._drop("io-error")
                return None
            self._prev = self._staged_index
            self._last_digest = rec["digest"]
            self.loop += 1
            self.records += 1
            nbytes = len(line)
            self.bytes += nbytes
            if self.registry is not None:
                self.registry.counter("journal_records_total",
                                      help=_RECORDS_HELP).inc()
                self.registry.counter("journal_bytes_total",
                                      help=_BYTES_HELP).inc(nbytes)
            if self._file_bytes >= self.rotate_bytes:
                self._rotate()
            return (rec["loop"], rec["digest"])
        finally:
            self.overhead_ns += time.perf_counter_ns() - t0

    def abort(self, reason: str = "aborted-loop") -> None:
        """Discard a staged record (the loop raised or returned before its
        outputs existed) — counted, never silently lost."""
        if self._staged is None:
            return
        self._staged = None
        self._drop(reason)

    def cursor(self) -> tuple[int, str] | None:
        """(loop, digest) of the last committed record."""
        if not self._last_digest:
            return None
        return (self.loop - 1, self._last_digest)

    def overhead_ms(self) -> float:
        return self.overhead_ns / 1e6

    def stats(self) -> dict:
        return {"records": self.records, "bytes": self.bytes,
                "rotations": self.rotations,
                "snapshotFallbacks": self.snapshot_fallbacks,
                "drops": dict(self.drops),
                "files": sorted(self._file_records),
                "overheadMs": round(self.overhead_ms(), 3)}

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    # -- file management --

    def _path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{_FILE_PREFIX}{seq:06d}{_FILE_SUFFIX}")

    def _append(self, line: str) -> None:
        if self._file is None:
            self._open_next()
        pos = self._file.tell()
        try:
            self._file.write(line)
            self._file.flush()
        except OSError:
            # roll the file back to the pre-write offset: a torn trailing
            # fragment (ENOSPC mid-line) would otherwise concatenate with
            # the next successful record and render the WHOLE journal
            # unparseable — destroying the evidence exactly under the
            # disk-pressure conditions it must survive
            try:
                self._file.seek(pos)
                self._file.truncate()
            except OSError:
                pass
            raise
        self._file_bytes += len(line)
        path = self._path(self._file_seq)
        self._file_records[path] = self._file_records.get(path, 0) + 1

    def _open_next(self) -> None:
        existing = [f for f in os.listdir(self.dir)
                    if f.startswith(_FILE_PREFIX) and f.endswith(_FILE_SUFFIX)]
        if self._file_seq < 0 and existing:
            last = max(int(f[len(_FILE_PREFIX):-len(_FILE_SUFFIX)])
                       for f in existing)
            self._file_seq = last
        self._file_seq += 1
        path = self._path(self._file_seq)
        self._file = open(path, "w")
        meta = {
            "kind": "meta", "v": JOURNAL_VERSION,
            "config": self.config_fp,
            "backend": backend_identity(self._node_bucket, self._group_bucket),
            "createdLoop": self.loop,
            **({"options": dataclasses.asdict(self._options)}
               if self._options is not None else {}),
            **self._meta_extra,
        }
        line = canonical(meta) + "\n"
        self._file.write(line)
        self._file.flush()
        self._file_bytes = len(line)
        self.bytes += len(line)
        if self.registry is not None:
            self.registry.counter("journal_bytes_total",
                                  help=_BYTES_HELP).inc(len(line))

    def _rotate(self) -> None:
        self.close()
        self.rotations += 1
        # a rotated-into file must be independently replayable: its first
        # record re-snapshots the world
        self._prev = None
        if self.registry is not None:
            self.registry.counter("journal_rotations_total",
                                  help=_ROTATIONS_HELP).inc()
        files = sorted(
            f for f in os.listdir(self.dir)
            if f.startswith(_FILE_PREFIX) and f.endswith(_FILE_SUFFIX))
        while len(files) >= self.keep_files:
            victim = os.path.join(self.dir, files.pop(0))
            dropped = self._file_records.pop(victim, None)
            if dropped is None:
                # a predecessor run's file (reused --journal-dir): count
                # its records before pruning — the size bound applies
                # across runs, but drops are NEVER silently unaccounted.
                # The raw substring is unambiguous: canonical JSON escapes
                # quotes inside string values, so '"kind":"meta"' can only
                # be the meta line's own key.
                try:
                    with open(victim) as f:
                        dropped = sum(1 for ln in f
                                      if ln.strip()
                                      and '"kind":"meta"' not in ln)
                except OSError:
                    dropped = 0
            try:
                os.remove(victim)
            except OSError:
                break
            if dropped:
                self._drop("rotated", dropped)

    def _drop(self, reason: str, n: int = 1) -> None:
        self.drops[reason] = self.drops.get(reason, 0) + n
        if self.registry is not None:
            self.registry.counter("journal_dropped_total",
                                  help=_DROPPED_HELP).inc(n, reason=reason)


# ---- sidecar per-tenant journal ----

class TenantJournal:
    """Bounded in-memory provenance ring for one sidecar tenant: every
    ApplyDelta (the tenant's world delta stream is the KAD1 wire payload
    itself) and every sim verdict digest, chained like the on-disk journal.
    Retention follows the TailSampler pattern: nothing touches disk until a
    breach/backpressure event `persist()`s the ring next to the trace dump."""

    def __init__(self, tenant: str = "", capacity: int = 256, registry=None):
        import threading

        self.tenant = tenant or "default"
        self.capacity = max(int(capacity), 1)
        self.registry = registry
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()   # gRPC handlers + batch scheduler
        self.seq = 0
        self.records = 0
        self.bytes = 0
        self.dropped = 0
        self.persisted = 0
        self._last_digest = ""
        # maybe_persist dedup watermark: the seq already on disk
        self._persisted_seq = -1

    def record(self, kind: str, version: int, nbytes: int = 0,
               digest: str = "", extra: dict | None = None) -> tuple[int, str]:
        with self._lock:
            rec = {"seq": self.seq, "kind": kind, "version": int(version),
                   "parent": self._last_digest,
                   **({"bytes": int(nbytes)} if nbytes else {}),
                   **({"payload": digest} if digest else {}),
                   **(extra or {})}
            seal_record(rec)
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
                if self.registry is not None:
                    self.registry.counter(
                        "journal_dropped_total", help=_DROPPED_HELP,
                    ).inc(reason="evicted", tenant=self.tenant)
            self._ring.append(rec)
            self._last_digest = rec["digest"]
            self.seq += 1
            self.records += 1
            nb = len(canonical(rec))
            self.bytes += nb
        if self.registry is not None:
            self.registry.counter("journal_records_total",
                                  help=_RECORDS_HELP).inc(tenant=self.tenant)
            self.registry.counter("journal_bytes_total",
                                  help=_BYTES_HELP).inc(nb,
                                                        tenant=self.tenant)
        return (rec["seq"], rec["digest"])

    def cursor(self) -> tuple[int, str] | None:
        with self._lock:
            if not self._last_digest:
                return None
            return (self.seq - 1, self._last_digest)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def stats(self) -> dict:
        with self._lock:
            return {"tenant": self.tenant, "records": self.records,
                    "bytes": self.bytes, "held": len(self._ring),
                    "dropped": self.dropped, "persisted": self.persisted}

    def persist(self, path: str, reason: str = "") -> str:
        """Write the retained ring as JSONL (meta line first, like the main
        journal). Atomic replace; OSError propagates to the caller, which
        treats a full disk as non-fatal."""
        snaps = self.snapshot()
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(canonical({"kind": "meta", "v": JOURNAL_VERSION,
                               "tenant": self.tenant, "reason": reason,
                               "backend": backend_identity()}) + "\n")
            for rec in snaps:
                f.write(canonical(rec) + "\n")
        os.replace(tmp, path)
        with self._lock:
            self.persisted += 1
        return path

    def maybe_persist(self, dir_path: str, reason: str = "") -> str | None:
        """Persist the ring IF it grew since the last persist — the
        retention trigger (breach/backpressure) fires per REQUEST, and
        backpressure fires exactly when the server is saturated: without
        the watermark, an overload storm would write one full ring copy
        per rejected RPC. The file is keyed by (tenant, ring seq), so a
        re-persist of the same history overwrites instead of accreting."""
        with self._lock:
            seq = self.seq - 1
            if seq < 0 or seq == self._persisted_seq:
                return None
            self._persisted_seq = seq
        path = os.path.join(
            dir_path, f"journal-{self.tenant}-seq{seq:08d}.jsonl")
        return self.persist(path, reason=reason)
