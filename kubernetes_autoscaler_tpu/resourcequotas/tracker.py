"""Resource quotas: cluster-wide cores/memory/custom caps on scaling.

Reference counterpart: resourcequotas/ (tracker.go CheckDelta capping
scale-ups at orchestrator applyLimits :205-217; min-quota tracker gating
scale-down at planner.go:160; default provider wrapping the cloudprovider
ResourceLimiter).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from kubernetes_autoscaler_tpu.cloudprovider.provider import ResourceLimiter
from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.models.api import Node
from kubernetes_autoscaler_tpu.models.encode import node_capacity_vector

CORES = "cpu"
MEMORY = "memory"


def merge_flag_limits(limiter: ResourceLimiter, options) -> ResourceLimiter:
    """Fold --cores-total/--memory-total/--gpu-total caps into the provider's
    ResourceLimiter (reference: resourcequotas default provider wraps the flag
    limits; flags.go --cores-total et al.)."""
    max_limits = dict(limiter.max_limits)

    def cap(name: str, value: float) -> None:
        if value > 0:
            max_limits[name] = min(max_limits.get(name, 1 << 60), value)

    cap(CORES, options.max_cores_total)
    cap(MEMORY, options.max_memory_total_mib)
    cap("nvidia.com/gpu", options.max_gpu_total)
    return ResourceLimiter(min_limits=dict(limiter.min_limits),
                           max_limits=max_limits)


@dataclass
class QuotaStatus:
    """Current cluster totals in limiter units (cores, MiB, custom counts)."""

    totals: dict[str, float]


class QuotaTracker:
    """Tracks totals and answers 'how many nodes of this template may I add /
    remove' (reference: resourcequotas.Tracker)."""

    def __init__(self, limiter: ResourceLimiter, registry: res.ExtendedResourceRegistry):
        self.limiter = limiter
        self.registry = registry

    def status(self, nodes: list[Node]) -> QuotaStatus:
        totals = {CORES: 0.0, MEMORY: 0.0}
        for nd in nodes:
            v = node_capacity_vector(nd, self.registry)
            totals[CORES] += v[res.CPU] / 1000.0
            totals[MEMORY] += float(v[res.MEMORY])
            for name, slot in self.registry.slots.items():
                totals[name] = totals.get(name, 0.0) + float(v[slot])
        return QuotaStatus(totals)

    def status_from_encoded(self, enc) -> QuotaStatus:
        """Vectorized totals straight off the encoded snapshot — one masked sum
        over enc.nodes.cap instead of a per-node Python loop (hot path: called
        from the orchestrator and planner every loop)."""
        cap = np.asarray(enc.nodes.cap, dtype=np.int64)
        valid = np.asarray(enc.nodes.valid)
        sums = cap[valid].sum(axis=0)
        totals = {
            CORES: float(sums[res.CPU]) / 1000.0,
            MEMORY: float(sums[res.MEMORY]),
        }
        for name, slot in self.registry.slots.items():
            totals[name] = float(sums[slot])
        return QuotaStatus(totals)

    def max_nodes_addable(self, status: QuotaStatus, template: Node,
                          wanted: int) -> int:
        """Cap a scale-up delta so no max-limit is exceeded (reference:
        orchestrator applyLimits → ComputeDelta/CheckDelta)."""
        v = node_capacity_vector(template, self.registry)
        per_node = {
            CORES: v[res.CPU] / 1000.0,
            MEMORY: float(v[res.MEMORY]),
        }
        for name, slot in self.registry.slots.items():
            per_node[name] = float(v[slot])
        allowed = wanted
        for name, per in per_node.items():
            if per <= 0:
                continue
            headroom = self.limiter.max_for(name) - status.totals.get(name, 0.0)
            allowed = min(allowed, int(max(headroom, 0) // per))
        return max(allowed, 0)

    def deduct(self, status: QuotaStatus, node: Node) -> None:
        """Subtract one node's capacity from the running totals."""
        v = node_capacity_vector(node, self.registry)
        status.totals[CORES] = status.totals.get(CORES, 0.0) - v[res.CPU] / 1000.0
        status.totals[MEMORY] = status.totals.get(MEMORY, 0.0) - float(v[res.MEMORY])
        for name, slot in self.registry.slots.items():
            status.totals[name] = status.totals.get(name, 0.0) - float(v[slot])

    def nodes_removable(self, status: QuotaStatus, node: Node) -> bool:
        """Would removing `node` violate a min-limit? (reference: min-quota
        tracker gating planner.go:160)."""
        v = node_capacity_vector(node, self.registry)
        checks = {CORES: v[res.CPU] / 1000.0, MEMORY: float(v[res.MEMORY])}
        for name, slot in self.registry.slots.items():
            checks[name] = float(v[slot])
        for name, per in checks.items():
            if status.totals.get(name, 0.0) - per < self.limiter.min_for(name):
                return False
        return True
