"""Admission control for the multi-tenant sidecar: bounded queue, per-tenant
fairness, and the pipelined coalescing-window scheduler.

Three pieces (docs/SERVING.md):

  AdmissionQueue   a bounded, tenant-aware queue. `submit` raises QueueFull
                   (→ gRPC RESOURCE_EXHAUSTED + retry-after) once the depth
                   bound is hit — the service sheds load explicitly instead
                   of wedging behind an unbounded backlog. Window formation
                   is ROUND-ROBIN ACROSS TENANTS, not FIFO across all
                   requests: each cycle takes at most one ticket per tenant,
                   so a chatty tenant fills only the lanes quiet tenants
                   left unused and can never starve them
                   (tests/test_admission.py pins this).
  Ticket           one queued simulation request: the prepared per-lane
                   payload, a completion event the handler thread waits on,
                   and the batch_info the observability layer turns into a
                   `batch` span.
  BatchScheduler   the single dispatch thread. Collects a coalescing window
                   (first arrival, then up to `window_s` for concurrent
                   requests to join), splits it by batch-compatibility key,
                   and PIPELINES windows: window k's device results are
                   harvested (ops/hostfetch.AsyncFetch.get) only after
                   window k+1's upload+dispatch is in flight, so the
                   device→host fetch of one window hides under the next
                   window's encode/dispatch — the serving-side double
                   buffer, same mechanism as PR 6's bench loop.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.sidecar import faults
from kubernetes_autoscaler_tpu.sidecar.lifecycle import Stamps


class WorldValidationError(ValueError):
    """A structurally invalid tenant world or request, rejected BEFORE it
    reaches a coalescing window (docs/ROBUSTNESS.md): mapped to gRPC
    INVALID_ARGUMENT, counted by `world_validation_rejects_total{reason}`.
    Reasons form a small fixed taxonomy pinned by tests/test_quarantine.py:
    `nan` (NaN/inf in request params or template capacities),
    `negative-request` (negative resource requests in the world or params),
    `section-version-mismatch` (a delta built against a different snapshot
    version than the server holds — the post-restart full-resend signal),
    `oversize-world` (counts past the configured world caps), and
    `rehydration-pending` (a checkpoint-restored tenant hit a path that
    needs the native world re-sent)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"invalid world/request [{reason}]"
                         + (f": {detail}" if detail else ""))
        self.reason = reason


class Quarantined(Exception):
    """The tenant is serving a quarantine sentence (a window failure was
    bisected down to it): rejected at the admission edge with gRPC
    FAILED_PRECONDITION + the parole time as a retry-after hint. Auto-
    parole: the next request after the TTL elapses is admitted (and a
    successful ApplyDelta paroles early — a new world is a new chance)."""

    def __init__(self, tenant: str, reason: str, retry_after_ms: int):
        super().__init__(
            f"tenant {tenant or 'default'!r} quarantined [{reason}]; "
            f"parole in {retry_after_ms}ms")
        self.tenant = tenant
        self.reason = reason
        self.retry_after_ms = retry_after_ms


class SchedulerDown(RuntimeError):
    """The batch scheduler thread is dead: nothing drains the admission
    queue, so accepting the request would wedge it until its deadline.
    Mapped to gRPC UNAVAILABLE — the client's retry ladder / circuit
    breaker / local fallback takes over (the Health RPC reports
    NOT_SERVING so orchestration restarts the sidecar)."""


class QueueFull(Exception):
    """Admission bound hit: reject now, retry after `retry_after_ms`.

    Mapped to gRPC RESOURCE_EXHAUSTED by the server handler. The request was
    NOT enqueued — retrying it later is always safe (nothing partial
    happened), which tests/test_admission.py proves end to end.

    `reason` distinguishes WHY the reject fired — `queue-full` (admission
    depth bound) vs `tenant-cap` (resident-world table bound) — so the
    server's `admission_rejects_total{reason}` and the event sink can tell
    an overloaded queue (transient; retry helps) from a full tenant table
    (structural; retry alone never helps, an operator must drop_tenant or
    run a bigger sidecar)."""

    def __init__(self, depth: int | None, retry_after_ms: int,
                 what: str = "admission queue",
                 reason: str = "queue-full"):
        where = (f"{depth} queued" if isinstance(depth, int)
                 else "server backpressure")
        super().__init__(
            f"{what} full ({where}); retry in {retry_after_ms}ms")
        self.depth = depth
        self.retry_after_ms = retry_after_ms
        self.reason = reason


@dataclass
class Ticket:
    tenant: str
    kind: str                    # "up" | "down"
    key: tuple                   # batch-compatibility key (shape class + statics)
    lane: object                 # prepared per-lane input (sidecar/batch.py)
    fp: tuple | None = None      # world fingerprint (stack-cache key part)
    trace_id: str | None = None
    result: object = None
    error: Exception | None = None
    batch_info: dict | None = None
    done: threading.Event = field(default_factory=threading.Event)
    enqueued_ns: int = field(default_factory=time.perf_counter_ns)
    # request-lifecycle marks (sidecar/lifecycle.py): the queue stamps
    # `enqueue`/`collected`; the dispatch path stamps the batch-level marks
    stamps: Stamps = field(default_factory=Stamps)

    def wait(self, timeout_s: float = 60.0):
        if not self.done.wait(timeout_s):
            raise TimeoutError(
                f"{self.kind} ticket for tenant {self.tenant!r} not served "
                f"within {timeout_s:.0f}s (scheduler wedged?)")
        if self.error is not None:
            raise self.error
        return self.result

    def resolve(self, result=None, error: Exception | None = None,
                batch_info: dict | None = None) -> None:
        self.result = result
        self.error = error
        self.batch_info = batch_info
        self.done.set()


class AdmissionQueue:
    """Bounded queue with per-tenant sub-queues and a persistent round-robin
    cursor (fairness holds ACROSS windows too: the tenant served last in one
    window is first only when its turn comes around again)."""

    def __init__(self, max_depth: int = 128, retry_after_ms: int = 20):
        self.max_depth = max_depth
        self.retry_after_ms = retry_after_ms
        self._cond = threading.Condition()
        self._by_tenant: dict[str, deque[Ticket]] = {}
        self._ring: list[str] = []       # tenant round-robin order
        self._cursor = 0
        self.depth = 0
        self.submitted = 0
        self.rejected = 0
        self._closed: Exception | None = None

    def close(self, error: Exception) -> None:
        """Fail-fast mode after a scheduler crash: every future submit
        raises SchedulerDown instead of enqueuing into a queue nobody
        drains (the supervision contract, tests/test_fault_injection.py)."""
        with self._cond:
            self._closed = error
            self._cond.notify_all()

    def submit(self, t: Ticket) -> None:
        with self._cond:
            if self._closed is not None:
                raise SchedulerDown(
                    f"admission queue closed: {self._closed}"
                ) from self._closed
            if self.depth >= self.max_depth:
                self.rejected += 1
                raise QueueFull(self.depth, self.retry_after_ms)
            dq = self._by_tenant.get(t.tenant)
            if dq is None:
                dq = deque()
                self._by_tenant[t.tenant] = dq
                self._ring.append(t.tenant)
            if not t.stamps.enqueue:
                t.stamps.enqueue = time.perf_counter_ns()
            dq.append(t)
            self.depth += 1
            self.submitted += 1
            self._cond.notify_all()

    def collect(self, max_lanes: int, wait_s: float,
                coalesce_s: float) -> list[Ticket]:
        """One coalescing window: block up to `wait_s` for a first ticket,
        then hold the window open `coalesce_s` (or until `max_lanes` tickets
        are waiting) so concurrent in-flight requests coalesce, then pop
        round-robin. Empty list = idle timeout."""
        with self._cond:
            deadline = time.monotonic() + wait_s
            while self.depth == 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)
            cdeadline = time.monotonic() + coalesce_s
            while self.depth < max_lanes:
                remaining = cdeadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            return self._pop_round_robin(max_lanes)

    def _pop_round_robin(self, max_lanes: int) -> list[Ticket]:
        out: list[Ticket] = []
        collected_ns = time.perf_counter_ns()
        while len(out) < max_lanes and self.depth > 0:
            # one full cycle over the ring = at most one ticket per tenant
            took_any = False
            n = len(self._ring)
            for _ in range(n):
                if len(out) >= max_lanes:
                    break
                tenant = self._ring[self._cursor % len(self._ring)]
                self._cursor = (self._cursor + 1) % len(self._ring)
                dq = self._by_tenant.get(tenant)
                if dq:
                    t = dq.popleft()
                    t.stamps.collected = collected_ns
                    out.append(t)
                    self.depth -= 1
                    took_any = True
            if not took_any:
                break
        # prune empty tenants so the ring stays proportional to ACTIVE
        # tenants (the cursor re-anchors; fairness is per-cycle, unaffected)
        if any(not dq for dq in self._by_tenant.values()):
            live = [t for t in self._ring if self._by_tenant.get(t)]
            for t in list(self._by_tenant):
                if not self._by_tenant[t]:
                    del self._by_tenant[t]
            self._cursor = 0 if not live else self._cursor % len(live)
            self._ring = live
        return out

    def drain(self) -> list[Ticket]:
        with self._cond:
            out = [t for dq in self._by_tenant.values() for t in dq]
            self._by_tenant.clear()
            self._ring.clear()
            self.depth = 0
            return out


def split_by_key(window: list[Ticket]) -> list[list[Ticket]]:
    """Group a window's tickets into batch-compatible runs (same vmapped
    program: kind + shape class + static params), preserving first-seen
    order so fairness inside the window survives the split."""
    groups: dict[tuple, list[Ticket]] = {}
    order: list[tuple] = []
    for t in window:
        if t.key not in groups:
            groups[t.key] = []
            order.append(t.key)
        groups[t.key].append(t)
    return [groups[k] for k in order]


class BatchScheduler:
    """The dispatch thread. `dispatch(batch)` (the service's stacked-vmap
    issue path) must return an in-flight handle with a `.harvest()` method
    that blocks for the device→host fetch and resolves every ticket; the
    scheduler calls it one window LATE to overlap fetch with the next
    window's dispatch."""

    def __init__(self, queue: AdmissionQueue, dispatch, lanes: int,
                 window_s: float = 0.002, idle_wait_s: float = 0.05,
                 window_max: int | None = None, gap_cb=None,
                 on_batch_failure=None, on_crash=None):
        self.queue = queue
        self.dispatch = dispatch
        self.lanes = max(int(lanes), 1)
        # the window collects MORE than one dispatch's lanes (a window mixes
        # batch keys; each key run then chunks into lane-width dispatches) —
        # decoupling the coalescing cap from the compiled lane width lets the
        # lane width stay small (padding is wasted compute on lane-serial
        # backends) without shrinking the coalescing opportunity
        self.window_max = max(int(window_max or 4 * self.lanes), self.lanes)
        self.window_s = window_s
        self.idle_wait_s = idle_wait_s
        self.windows = 0
        self.batches = 0
        # device-utilization accounting: `gap_cb(gap_seconds, cause)` fires
        # per dispatch with the estimated device idle since the previous
        # batch's results were ready. Causes:
        #   pipelined  an unharvested batch was still in flight when this
        #              dispatch launched — the device had queued work, so
        #              the gap is 0 BY CONSTRUCTION (the pipelining
        #              contract, CI-asserted ≈0 under load)
        #   stall      the previous harvest completed WITH work already
        #              waiting in the queue, yet the device sat idle until
        #              this dispatch — a genuine pipeline failure
        #   idle       the previous harvest completed with an empty queue;
        #              the gap is arrival-bound (no work to run), reported
        #              separately so idle fleets don't read as stalls
        self.gap_cb = gap_cb
        # isolation hooks (docs/ROBUSTNESS.md): on_batch_failure(batch,
        # error) — a failed dispatch is handed to the service's bisection
        # re-dispatcher instead of blanket-failing every member;
        # on_crash(error) — the supervision escalation when the serve loop
        # itself dies (the service flips Health to NOT_SERVING)
        self.on_batch_failure = on_batch_failure
        self.on_crash = on_crash
        self.crashed: Exception | None = None
        self._last_harvest_done_ns: int | None = None
        self._work_waiting_at_harvest = False
        self._pending = None   # previous batch, fetch still in flight
        self._window: list[Ticket] = []   # collected, not yet all dispatched
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="katpu-batch-scheduler", daemon=True)

    def start(self) -> "BatchScheduler":
        self._thread.start()
        return self

    @property
    def alive(self) -> bool:
        return self.crashed is None and self._thread.is_alive()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout_s)
        err = RuntimeError("sidecar batch scheduler stopped")
        for t in self.queue.drain():
            t.resolve(error=err)

    def _serve(self) -> None:
        """Supervised serve loop: an unhandled exception (anything outside
        the per-batch dispatch guard — queue plumbing, window forming, an
        injected scheduler_loop fault) must NOT die silently with requests
        queued behind a drain that will never come. The crash path closes
        the queue (future submits raise SchedulerDown), fails every queued
        and in-flight ticket, and escalates through on_crash so the service
        flips Health to NOT_SERVING."""
        try:
            self._serve_inner()
        except Exception as e:  # noqa: BLE001 — the supervision contract
            self.crashed = e
            err = SchedulerDown(f"batch scheduler crashed: {e!r}")
            err.__cause__ = e
            self.queue.close(err)
            for t in self.queue.drain():
                t.resolve(error=err)
            # tickets already COLLECTED into the current window live in
            # neither the queue nor _pending — without this they would
            # block their clients until the gRPC deadline, the exact hang
            # the supervision contract exists to prevent
            for t in self._window:
                if not t.done.is_set():
                    t.resolve(error=err)
            self._window = []
            if self._pending is not None:
                for t in getattr(self._pending, "tickets", ()):
                    if not t.done.is_set():
                        t.resolve(error=err)
                self._pending = None
            if self.on_crash is not None:
                try:
                    self.on_crash(e)
                except Exception:  # noqa: BLE001 — escalation is best-effort
                    pass

    def _serve_inner(self) -> None:
        while not self._stop.is_set():
            if faults.PLAN is not None:
                faults.PLAN.fire("scheduler_loop")
            # with a fetch in flight, poll instead of sleeping: an empty
            # queue means there is nothing to overlap the fetch with, and
            # the waiters of the pending batch may be exactly what the next
            # request is blocked on (request-response clients) — sleeping
            # idle_wait_s here adds a dead stall to every round trip
            window = self.queue.collect(
                self.window_max,
                wait_s=0.0 if self._pending is not None else self.idle_wait_s,
                coalesce_s=self.window_s)
            if not window:
                # idle: nothing to overlap the pending fetch with — harvest
                if self._pending is not None:
                    self._harvest(self._pending)
                    self._pending = None
                continue
            self._window = window
            self.windows += 1
            for run in split_by_key(window):
                # canonical member order: the round-robin cursor rotates the
                # pop order window to window, but lane order is irrelevant to
                # latency (a batch completes together) and a STABLE order
                # keys the server's stacked-pytree cache — steady-state
                # windows with the same members must re-hit, not restack
                run.sort(key=lambda t: (t.tenant, t.enqueued_ns))
                for lo in range(0, len(run), self.lanes):
                    batch = run[lo:lo + self.lanes]
                    self.batches += 1
                    self._note_gap(self._pending is not None)
                    try:
                        inflight = self.dispatch(batch)
                    except Exception as e:  # noqa: BLE001 — fail the batch, keep serving
                        # a failed dispatch is handed to the service's
                        # bisection re-dispatcher when one is wired: split
                        # lanes, retry halves, isolate the poison member —
                        # healthy co-batched tenants still get results
                        if self.on_batch_failure is not None:
                            try:
                                self.on_batch_failure(batch, e)
                            except Exception as e2:  # noqa: BLE001
                                for t in batch:
                                    if not t.done.is_set():
                                        t.resolve(error=e2)
                        else:
                            for t in batch:
                                t.resolve(error=e)
                        continue
                    # pipeline point: THIS batch's upload+dispatch is now in
                    # flight; only now pay the previous batch's fetch wait
                    if self._pending is not None:
                        self._harvest(self._pending)
                    self._pending = inflight
            self._window = []
        if self._pending is not None:
            self._harvest(self._pending)
            self._pending = None

    def _note_gap(self, pipelined: bool) -> None:
        """Estimated device idle before the dispatch about to launch (see
        gap_cb causes above). Host-side estimator: the device's results-ready
        time is observed as the previous harvest's completion."""
        if self.gap_cb is None:
            return
        if pipelined:
            self.gap_cb(0.0, "pipelined")
            return
        if self._last_harvest_done_ns is None:
            return   # first dispatch ever: no previous batch to idle after
        gap_s = (time.perf_counter_ns() - self._last_harvest_done_ns) / 1e9
        self.gap_cb(gap_s,
                    "stall" if self._work_waiting_at_harvest else "idle")

    def _harvest(self, inflight) -> None:
        try:
            inflight.harvest()
        except Exception:  # noqa: BLE001 — harvest resolves tickets itself
            pass
        finally:
            self._last_harvest_done_ns = time.perf_counter_ns()
            self._work_waiting_at_harvest = self.queue.depth > 0
