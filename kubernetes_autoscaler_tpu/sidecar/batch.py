"""Stacked-world plumbing for batched multi-tenant dispatch.

The sidecar's batching layer (docs/SERVING.md) turns one coalescing window's
tickets into ONE vmapped device program per shape class
(ops/autoscale_step.scale_up_sim_batch / scale_down_sim_batch). This module
owns the data movement around that dispatch:

  * converters from the native codec's numpy export (NativeSnapshotState
    .export layout) to the flax tensor structs — shared by single worlds
    and lane-stacked worlds (the casts are elementwise, so a leading tenant
    axis rides through);
  * lane stacking with occupancy padding: a window of M tenants pads to the
    service's FIXED lane count B by repeating lane 0 — lane count is part of
    the compiled shape, so padding (instead of a per-occupancy program)
    makes "new tenant ⇒ 0 recompiles" hold even for a tenant that arrives
    alone in its window;
  * a bounded stack cache: steady-state traffic (same members, unchanged
    world versions) reuses the stacked device pytree instead of re-stacking
    and re-uploading every window;
  * InFlightBatch: the dispatched batch + its async result fetch
    (ops/hostfetch.fetch_pytree_async). `harvest()` blocks for the fetch,
    assembles every member's response (identical JSON to the serial path —
    the bit-identity contract of tests/test_batched_sim.py extends through
    assembly), and resolves the tickets. The scheduler harvests one window
    late, overlapping fetch with the next window's dispatch.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from kubernetes_autoscaler_tpu.models.cluster_state import (
    NodeGroupTensors,
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
)
from kubernetes_autoscaler_tpu.metrics import device
from kubernetes_autoscaler_tpu.sidecar import faults


class MemberFault(Exception):
    """One member's result is poisoned (NaN in its lane's outputs, or its
    per-member assembly raised): ONLY that member's ticket errors — the
    batch's other members are assembled and resolved normally, because
    vmapped lanes are computationally independent (docs/ROBUSTNESS.md)."""

    def __init__(self, tenant: str, message: str):
        super().__init__(f"member {tenant or 'default'!r}: {message}")
        self.tenant = tenant


@dataclass
class UpLane:
    """Prepared scale-up input for one tenant: class-shaped world sections
    (the tenant's RESIDENT device arrays from server._export_dev — numpy
    also accepted for tests/tools) + the request's encoded node-group
    template fields."""

    nodes: dict
    groups: dict
    pods: dict
    ng: dict
    ids: list[str]


@dataclass
class DownLane:
    nodes: dict
    groups: dict
    pods: dict
    threshold: float
    # host copy of the nodes-section valid mask for response assembly —
    # device lanes must not force a d2h round trip per member just to
    # index the fetched results
    valid_np: np.ndarray | None = None


# ---- numpy export → tensor structs (single or lane-stacked) ----

def node_tensors(a: dict) -> NodeTensors:
    import jax.numpy as jnp

    return NodeTensors(
        cap=jnp.asarray(a["cap"]), alloc=jnp.asarray(a["alloc"]),
        label_hash=jnp.asarray(a["label_hash"]),
        taint_exact=jnp.asarray(a["taint_exact"]),
        taint_key=jnp.asarray(a["taint_key"]),
        used_ports=jnp.asarray(a["used_ports"]),
        zone_id=jnp.asarray(a["zone_id"]),
        group_id=jnp.asarray(a["group_id"]),
        ready=jnp.asarray(a["ready"].astype(bool)),
        schedulable=jnp.asarray(a["schedulable"].astype(bool)),
        valid=jnp.asarray(a["valid"].astype(bool)),
    )


def podgroup_tensors(a: dict) -> PodGroupTensors:
    import jax.numpy as jnp

    return PodGroupTensors(
        req=jnp.asarray(a["req"]), count=jnp.asarray(a["count"]),
        sel_req=jnp.asarray(a["sel_req"]), sel_neg=jnp.asarray(a["sel_neg"]),
        tol_exact=jnp.asarray(a["tol_exact"]),
        tol_key=jnp.asarray(a["tol_key"]),
        tolerate_all=jnp.asarray(a["tolerate_all"].astype(bool)),
        port_hash=jnp.asarray(a["port_hash"]),
        anti_affinity_self=jnp.asarray(a["anti_self"].astype(bool)),
        valid=jnp.asarray(a["valid"].astype(bool)),
        needs_host_check=jnp.asarray(a["lossy"].astype(bool)),
    )


def sched_tensors(a: dict) -> ScheduledPodTensors:
    import jax.numpy as jnp

    return ScheduledPodTensors(
        req=jnp.asarray(a["req"]), node_idx=jnp.asarray(a["node_idx"]),
        group_ref=jnp.asarray(a["group_ref"]),
        movable=jnp.asarray(a["movable"].astype(bool)),
        blocks=jnp.asarray(a["blocks"].astype(bool)),
        valid=jnp.asarray(a["valid"].astype(bool)),
    )


def nodegroup_tensors(a: dict) -> NodeGroupTensors:
    import jax.numpy as jnp

    return NodeGroupTensors(
        cap=jnp.asarray(a["cap"]), label_hash=jnp.asarray(a["label_hash"]),
        taint_exact=jnp.asarray(a["taint_exact"]),
        taint_key=jnp.asarray(a["taint_key"]),
        zone_id=jnp.asarray(a["zone_id"]), max_new=jnp.asarray(a["max_new"]),
        price_per_node=jnp.asarray(a["price_per_node"]),
        valid=jnp.asarray(a["valid"].astype(bool)),
    )


def nodegroup_np(t: NodeGroupTensors) -> dict:
    """Host mirror of an encoded NodeGroupTensors (encode_node_groups
    uploads; batching stacks on the host first, so pull it back once and
    cache)."""
    return {
        "cap": np.asarray(t.cap), "label_hash": np.asarray(t.label_hash),
        "taint_exact": np.asarray(t.taint_exact),
        "taint_key": np.asarray(t.taint_key),
        "zone_id": np.asarray(t.zone_id), "max_new": np.asarray(t.max_new),
        "price_per_node": np.asarray(t.price_per_node),
        "valid": np.asarray(t.valid).astype(np.uint8),
    }


def stack_fields(dicts: list[dict]) -> dict:
    """Stack each field over a new leading lane axis. Device lanes (the
    resident per-tenant arrays) stack ON-DEVICE via jnp.stack — zero h2d
    world bytes per window; numpy lanes keep the host np.stack (uploaded
    once by the tensor-struct casts), preserving the legacy path for
    tests/tools that build lanes from numpy exports."""
    first = dicts[0]
    if any(not isinstance(v, np.ndarray) for v in first.values()):
        import jax.numpy as jnp

        return {k: jnp.stack([d[k] for d in dicts]) for k in first}
    return {k: np.stack([d[k] for d in dicts]) for k in first}


def pad_lanes(items: list, lanes: int) -> list:
    """Occupancy padding: repeat lane 0 up to the fixed lane count. The
    padded lanes compute a real (duplicate) world and their outputs are
    simply not delivered — masking by duplication keeps every lane's inputs
    well-formed (no all-zero worlds hitting div-by-zero style edges)."""
    if len(items) > lanes:
        raise ValueError(f"{len(items)} lanes exceed the batch width {lanes}")
    return items + [items[0]] * (lanes - len(items))


class StackCache:
    """Bounded LRU of stacked device pytrees keyed by (batch key + member
    world fingerprints). Steady-state windows — same members, unchanged
    versions — skip restack + re-upload entirely, so a served window costs
    one vmapped dispatch plus one batched fetch."""

    def __init__(self, capacity: int = 32):
        self.capacity = capacity
        self._d: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key, build):
        hit = self._d.get(key)
        if hit is not None:
            self._d.move_to_end(key)
            self.hits += 1
            return hit
        self.misses += 1
        val = build()
        self._d[key] = val
        if device.LEDGER is not None:
            # HBM residency ledger: stacked pytrees are device arrays held
            # across windows; key by insertion identity so an evicted
            # entry's registration is dropped with it
            device.LEDGER.track("stack_cache", self._ledger_key(key), val)
        while len(self._d) > self.capacity:
            old_key, _old = self._d.popitem(last=False)
            if device.LEDGER is not None:
                device.LEDGER.release(owner="stack_cache",
                                      key=self._ledger_key(old_key))
        return val

    @staticmethod
    def _ledger_key(key) -> str:
        import hashlib

        return hashlib.sha1(repr(key).encode()).hexdigest()[:12]


class InFlightBatch:
    """One dispatched window batch: resolve tickets at harvest time.

    Failure contract (docs/ROBUSTNESS.md): NO ticket may be left pending —
    a client blocked on an unresolved ticket waits out its full gRPC
    deadline for nothing. A batch-level failure (fetch raised, assembly
    length mismatch) is delegated to `on_failure` (the service's bisection
    re-dispatcher) when wired, else fails every still-pending member with
    the error; a PER-member failure (MemberFault in the assembled results)
    errors only that member and reports it through `on_member_fault` (the
    quarantine hook) while co-members resolve normally."""

    def __init__(self, tickets, fetch, assemble, batch_info: dict,
                 on_done=None, on_failure=None, on_member_fault=None):
        self.tickets = tickets
        self.fetch = fetch
        self.assemble = assemble          # host pytree -> list of responses
        self.batch_info = batch_info
        self.on_done = on_done
        self.on_failure = on_failure
        self.on_member_fault = on_member_fault

    def harvest(self) -> None:
        try:
            if faults.PLAN is not None:
                faults.PLAN.fire(
                    "harvest", tenants=[t.tenant for t in self.tickets])
            host = self.fetch.get()
            harvested_ns = time.perf_counter_ns()
            results = self.assemble(host)
            if len(results) != len(self.tickets):
                # zip would silently truncate and leave the surplus tickets
                # blocked until their deadline — the exact hang this layer
                # exists to prevent (tests/test_fault_injection.py)
                raise RuntimeError(
                    f"assembly returned {len(results)} results for "
                    f"{len(self.tickets)} members")
            self.batch_info["dur_ns"] = (
                time.perf_counter_ns() - self.batch_info["t0_ns"])
            for t, r in zip(self.tickets, results):
                t.stamps.harvested = harvested_ns
                t.stamps.resolved = time.perf_counter_ns()
                if isinstance(r, Exception):
                    t.resolve(error=r, batch_info=self.batch_info)
                    if self.on_member_fault is not None:
                        try:
                            self.on_member_fault(t, r)
                        except Exception:  # noqa: BLE001 — best-effort hook
                            pass
                else:
                    t.resolve(result=r, batch_info=self.batch_info)
            if self.on_done is not None:
                self.on_done(self)
        except Exception as e:  # noqa: BLE001 — every ticket must resolve
            live = [t for t in self.tickets if not t.done.is_set()]
            if self.on_failure is not None and live:
                try:
                    self.on_failure(live, e)
                    return
                except Exception as e2:  # noqa: BLE001 — bisection failed
                    e = e2
            for t in live:
                if not t.done.is_set():
                    t.resolve(error=e)


def stack_up_lanes(lanes_list: list[UpLane]):
    """Stacked device inputs for scale_up_sim_batch."""
    return (
        node_tensors(stack_fields([ln.nodes for ln in lanes_list])),
        podgroup_tensors(stack_fields([ln.groups for ln in lanes_list])),
        sched_tensors(stack_fields([ln.pods for ln in lanes_list])),
        nodegroup_tensors(stack_fields([ln.ng for ln in lanes_list])),
    )


def stack_down_lanes(lanes_list: list[DownLane]):
    """Stacked device inputs for scale_down_sim_batch (thresholds ride as a
    traced f32[B] — mixed per-tenant thresholds share one program)."""
    import jax.numpy as jnp

    return (
        node_tensors(stack_fields([ln.nodes for ln in lanes_list])),
        podgroup_tensors(stack_fields([ln.groups for ln in lanes_list])),
        sched_tensors(stack_fields([ln.pods for ln in lanes_list])),
        jnp.asarray([ln.threshold for ln in lanes_list], jnp.float32),
    )


def assemble_up_one(host: dict, ln: UpLane, i: int) -> dict:
    """One member's scale-up response from the batched fetch —
    field-for-field the serial handler's JSON (ids mapping, option list,
    fits/remaining)."""
    best = int(host["best"][i])
    return {
        "best": ln.ids[best] if 0 <= best < len(ln.ids) else "",
        "options": [
            {
                "id": ln.ids[j],
                "node_count": int(host["node_count"][i, j]),
                "pods": int(host["pods"][i, j]),
                "waste": float(host["waste"][i, j]),
                "price": float(host["price"][i, j]),
                "valid": bool(host["valid"][i, j]),
            }
            for j in range(len(ln.ids))
        ],
        "fits_existing": int(host["fits"][i]),
        "remaining": int(host["remaining"][i]),
    }


def assemble_down_one(host: dict, ln: DownLane, i: int) -> dict:
    # device lanes carry a host copy of the valid mask (valid_np) so
    # assembly never round-trips to the device
    valid_src = ln.valid_np if ln.valid_np is not None else ln.nodes["valid"]
    valid = np.asarray(valid_src).astype(bool)
    return {
        "eligible": np.nonzero(host["eligible"][i] & valid)[0].tolist(),
        "drainable": np.nonzero(host["drainable"][i] & valid)[0].tolist(),
        "utilization": [round(float(u), 4)
                        for u in host["util"][i][valid]],
    }


def _result_poisoned(r, path="") -> str:
    """Name the first non-finite float in an assembled response ('' when
    clean): a poisoned lane (corrupted inputs, device fault) surfaces as
    NaN/inf in ITS outputs only — lanes are vmap-independent — so the check
    isolates the offender without failing the batch."""
    import math

    if isinstance(r, dict):
        for k, v in r.items():
            bad = _result_poisoned(v, f"{path}.{k}" if path else k)
            if bad:
                return bad
    elif isinstance(r, (list, tuple)):
        for j, v in enumerate(r):
            bad = _result_poisoned(v, f"{path}[{j}]")
            if bad:
                return bad
    elif isinstance(r, float) and not math.isfinite(r):
        return f"{path}={r}"
    return ""


def assemble_members(host: dict, members: list, tenants: list[str],
                     assemble_one) -> list:
    """Fault-isolated per-member assembly: each member assembles inside its
    own guard (assembly fault hook, NaN screen, exception fence), so one
    poisoned lane yields one MemberFault entry in the result list while its
    co-members' responses stay bit-identical to a fault-free run."""
    out: list = []
    for i, ln in enumerate(members):
        tenant = tenants[i] if i < len(tenants) else ""
        try:
            if faults.PLAN is not None:
                faults.PLAN.fire("assembly", tenant=tenant)
            r = assemble_one(host, ln, i)
            bad = _result_poisoned(r)
            if bad:
                raise MemberFault(
                    tenant, f"non-finite result plane ({bad}) — "
                            f"poisoned lane quarantined")
            out.append(r)
        except MemberFault as e:
            out.append(e)
        except Exception as e:  # noqa: BLE001 — isolate to this member
            out.append(MemberFault(tenant, f"assembly failed: {e!r}"))
    return out


def assemble_up(host: dict, members: list[UpLane]) -> list[dict]:
    """Whole-batch scale-up assembly (tests/tools; the server uses the
    fault-isolated assemble_members wrapper)."""
    return [assemble_up_one(host, ln, i) for i, ln in enumerate(members)]


def assemble_down(host: dict, members: list[DownLane]) -> list[dict]:
    return [assemble_down_one(host, ln, i) for i, ln in enumerate(members)]
