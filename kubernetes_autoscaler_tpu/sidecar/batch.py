"""Stacked-world plumbing for batched multi-tenant dispatch.

The sidecar's batching layer (docs/SERVING.md) turns one coalescing window's
tickets into ONE vmapped device program per shape class
(ops/autoscale_step.scale_up_sim_batch / scale_down_sim_batch). This module
owns the data movement around that dispatch:

  * converters from the native codec's numpy export (NativeSnapshotState
    .export layout) to the flax tensor structs — shared by single worlds
    and lane-stacked worlds (the casts are elementwise, so a leading tenant
    axis rides through);
  * lane stacking with occupancy padding: a window of M tenants pads to the
    service's FIXED lane count B by repeating lane 0 — lane count is part of
    the compiled shape, so padding (instead of a per-occupancy program)
    makes "new tenant ⇒ 0 recompiles" hold even for a tenant that arrives
    alone in its window;
  * a bounded stack cache: steady-state traffic (same members, unchanged
    world versions) reuses the stacked device pytree instead of re-stacking
    and re-uploading every window;
  * InFlightBatch: the dispatched batch + its async result fetch
    (ops/hostfetch.fetch_pytree_async). `harvest()` blocks for the fetch,
    assembles every member's response (identical JSON to the serial path —
    the bit-identity contract of tests/test_batched_sim.py extends through
    assembly), and resolves the tickets. The scheduler harvests one window
    late, overlapping fetch with the next window's dispatch.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from kubernetes_autoscaler_tpu.models.cluster_state import (
    NodeGroupTensors,
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
)


@dataclass
class UpLane:
    """Prepared scale-up input for one tenant: class-shaped world sections
    (the tenant's RESIDENT device arrays from server._export_dev — numpy
    also accepted for tests/tools) + the request's encoded node-group
    template fields."""

    nodes: dict
    groups: dict
    pods: dict
    ng: dict
    ids: list[str]


@dataclass
class DownLane:
    nodes: dict
    groups: dict
    pods: dict
    threshold: float
    # host copy of the nodes-section valid mask for response assembly —
    # device lanes must not force a d2h round trip per member just to
    # index the fetched results
    valid_np: np.ndarray | None = None


# ---- numpy export → tensor structs (single or lane-stacked) ----

def node_tensors(a: dict) -> NodeTensors:
    import jax.numpy as jnp

    return NodeTensors(
        cap=jnp.asarray(a["cap"]), alloc=jnp.asarray(a["alloc"]),
        label_hash=jnp.asarray(a["label_hash"]),
        taint_exact=jnp.asarray(a["taint_exact"]),
        taint_key=jnp.asarray(a["taint_key"]),
        used_ports=jnp.asarray(a["used_ports"]),
        zone_id=jnp.asarray(a["zone_id"]),
        group_id=jnp.asarray(a["group_id"]),
        ready=jnp.asarray(a["ready"].astype(bool)),
        schedulable=jnp.asarray(a["schedulable"].astype(bool)),
        valid=jnp.asarray(a["valid"].astype(bool)),
    )


def podgroup_tensors(a: dict) -> PodGroupTensors:
    import jax.numpy as jnp

    return PodGroupTensors(
        req=jnp.asarray(a["req"]), count=jnp.asarray(a["count"]),
        sel_req=jnp.asarray(a["sel_req"]), sel_neg=jnp.asarray(a["sel_neg"]),
        tol_exact=jnp.asarray(a["tol_exact"]),
        tol_key=jnp.asarray(a["tol_key"]),
        tolerate_all=jnp.asarray(a["tolerate_all"].astype(bool)),
        port_hash=jnp.asarray(a["port_hash"]),
        anti_affinity_self=jnp.asarray(a["anti_self"].astype(bool)),
        valid=jnp.asarray(a["valid"].astype(bool)),
        needs_host_check=jnp.asarray(a["lossy"].astype(bool)),
    )


def sched_tensors(a: dict) -> ScheduledPodTensors:
    import jax.numpy as jnp

    return ScheduledPodTensors(
        req=jnp.asarray(a["req"]), node_idx=jnp.asarray(a["node_idx"]),
        group_ref=jnp.asarray(a["group_ref"]),
        movable=jnp.asarray(a["movable"].astype(bool)),
        blocks=jnp.asarray(a["blocks"].astype(bool)),
        valid=jnp.asarray(a["valid"].astype(bool)),
    )


def nodegroup_tensors(a: dict) -> NodeGroupTensors:
    import jax.numpy as jnp

    return NodeGroupTensors(
        cap=jnp.asarray(a["cap"]), label_hash=jnp.asarray(a["label_hash"]),
        taint_exact=jnp.asarray(a["taint_exact"]),
        taint_key=jnp.asarray(a["taint_key"]),
        zone_id=jnp.asarray(a["zone_id"]), max_new=jnp.asarray(a["max_new"]),
        price_per_node=jnp.asarray(a["price_per_node"]),
        valid=jnp.asarray(a["valid"].astype(bool)),
    )


def nodegroup_np(t: NodeGroupTensors) -> dict:
    """Host mirror of an encoded NodeGroupTensors (encode_node_groups
    uploads; batching stacks on the host first, so pull it back once and
    cache)."""
    return {
        "cap": np.asarray(t.cap), "label_hash": np.asarray(t.label_hash),
        "taint_exact": np.asarray(t.taint_exact),
        "taint_key": np.asarray(t.taint_key),
        "zone_id": np.asarray(t.zone_id), "max_new": np.asarray(t.max_new),
        "price_per_node": np.asarray(t.price_per_node),
        "valid": np.asarray(t.valid).astype(np.uint8),
    }


def stack_fields(dicts: list[dict]) -> dict:
    """Stack each field over a new leading lane axis. Device lanes (the
    resident per-tenant arrays) stack ON-DEVICE via jnp.stack — zero h2d
    world bytes per window; numpy lanes keep the host np.stack (uploaded
    once by the tensor-struct casts), preserving the legacy path for
    tests/tools that build lanes from numpy exports."""
    first = dicts[0]
    if any(not isinstance(v, np.ndarray) for v in first.values()):
        import jax.numpy as jnp

        return {k: jnp.stack([d[k] for d in dicts]) for k in first}
    return {k: np.stack([d[k] for d in dicts]) for k in first}


def pad_lanes(items: list, lanes: int) -> list:
    """Occupancy padding: repeat lane 0 up to the fixed lane count. The
    padded lanes compute a real (duplicate) world and their outputs are
    simply not delivered — masking by duplication keeps every lane's inputs
    well-formed (no all-zero worlds hitting div-by-zero style edges)."""
    if len(items) > lanes:
        raise ValueError(f"{len(items)} lanes exceed the batch width {lanes}")
    return items + [items[0]] * (lanes - len(items))


class StackCache:
    """Bounded LRU of stacked device pytrees keyed by (batch key + member
    world fingerprints). Steady-state windows — same members, unchanged
    versions — skip restack + re-upload entirely, so a served window costs
    one vmapped dispatch plus one batched fetch."""

    def __init__(self, capacity: int = 32):
        self.capacity = capacity
        self._d: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key, build):
        hit = self._d.get(key)
        if hit is not None:
            self._d.move_to_end(key)
            self.hits += 1
            return hit
        self.misses += 1
        val = build()
        self._d[key] = val
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)
        return val


class InFlightBatch:
    """One dispatched window batch: resolve tickets at harvest time."""

    def __init__(self, tickets, fetch, assemble, batch_info: dict,
                 on_done=None):
        self.tickets = tickets
        self.fetch = fetch
        self.assemble = assemble          # host pytree -> list of responses
        self.batch_info = batch_info
        self.on_done = on_done

    def harvest(self) -> None:
        try:
            host = self.fetch.get()
            harvested_ns = time.perf_counter_ns()
            results = self.assemble(host)
            self.batch_info["dur_ns"] = (
                time.perf_counter_ns() - self.batch_info["t0_ns"])
            for t, r in zip(self.tickets, results):
                t.stamps.harvested = harvested_ns
                t.stamps.resolved = time.perf_counter_ns()
                t.resolve(result=r, batch_info=self.batch_info)
            if self.on_done is not None:
                self.on_done(self)
        except Exception as e:  # noqa: BLE001 — every ticket must resolve
            for t in self.tickets:
                if not t.done.is_set():
                    t.resolve(error=e)


def stack_up_lanes(lanes_list: list[UpLane]):
    """Stacked device inputs for scale_up_sim_batch."""
    return (
        node_tensors(stack_fields([ln.nodes for ln in lanes_list])),
        podgroup_tensors(stack_fields([ln.groups for ln in lanes_list])),
        sched_tensors(stack_fields([ln.pods for ln in lanes_list])),
        nodegroup_tensors(stack_fields([ln.ng for ln in lanes_list])),
    )


def stack_down_lanes(lanes_list: list[DownLane]):
    """Stacked device inputs for scale_down_sim_batch (thresholds ride as a
    traced f32[B] — mixed per-tenant thresholds share one program)."""
    import jax.numpy as jnp

    return (
        node_tensors(stack_fields([ln.nodes for ln in lanes_list])),
        podgroup_tensors(stack_fields([ln.groups for ln in lanes_list])),
        sched_tensors(stack_fields([ln.pods for ln in lanes_list])),
        jnp.asarray([ln.threshold for ln in lanes_list], jnp.float32),
    )


def assemble_up(host: dict, members: list[UpLane]) -> list[dict]:
    """Per-member scale-up responses from the batched fetch — field-for-field
    the serial handler's JSON (ids mapping, option list, fits/remaining)."""
    out = []
    for i, ln in enumerate(members):
        best = int(host["best"][i])
        out.append({
            "best": ln.ids[best] if 0 <= best < len(ln.ids) else "",
            "options": [
                {
                    "id": ln.ids[j],
                    "node_count": int(host["node_count"][i, j]),
                    "pods": int(host["pods"][i, j]),
                    "waste": float(host["waste"][i, j]),
                    "price": float(host["price"][i, j]),
                    "valid": bool(host["valid"][i, j]),
                }
                for j in range(len(ln.ids))
            ],
            "fits_existing": int(host["fits"][i]),
            "remaining": int(host["remaining"][i]),
        })
    return out


def assemble_down(host: dict, members: list[DownLane]) -> list[dict]:
    out = []
    for i, ln in enumerate(members):
        # device lanes carry a host copy of the valid mask (valid_np) so
        # assembly never round-trips to the device
        valid_src = ln.valid_np if ln.valid_np is not None else ln.nodes["valid"]
        valid = np.asarray(valid_src).astype(bool)
        out.append({
            "eligible": np.nonzero(host["eligible"][i] & valid)[0].tolist(),
            "drainable": np.nonzero(host["drainable"][i] & valid)[0].tolist(),
            "utilization": [round(float(u), 4)
                            for u in host["util"][i][valid]],
        })
    return out
