"""KAD1/KAUX wire-format conformance kit.

The sidecar boundary's contract artifacts (round-3 review item #5: "the Go
half of the sidecar boundary" — no Go toolchain exists in this image, so the
deliverable is golden fixtures a Go encoder builds against, the shape
precedent being expander/grpcplugin/protos/expander.proto:25-28):

  * `scenarios()` — deterministic builders covering the whole format surface
    (every op code, every field, the KAUX constraint trailer, multi-delta
    incremental sequences);
  * `write_goldens(dir)` — for each scenario, the exact payload bytes plus
    the tensors the native codec (sidecar/native/kacodec.cc) must decode
    them into, saved as one .npz; `manifest.json` records the semantic
    inputs so an independent (Go) encoder can reproduce the byte stream and
    byte-compare;
  * `replay(payloads)` — run payloads through the C++ codec and export.

tests/test_wire_conformance.py replays the COMMITTED goldens through the
live codec every CI run — the wire format cannot drift silently.
"""

from __future__ import annotations

import json
import os

import numpy as np

from kubernetes_autoscaler_tpu.models.api import (
    AffinityTerm,
    Node,
    OwnerRef,
    Pod,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from kubernetes_autoscaler_tpu.sidecar.wire import DeltaWriter, split_aux

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "goldens")


def _node(name, cpu=8.0, mem_gib=16, pods=64, labels=None, taints=None,
          zone="", gpus=0, ready=True, unschedulable=False):
    lbl = {"kubernetes.io/hostname": name}
    if zone:
        lbl["topology.kubernetes.io/zone"] = zone
    lbl.update(labels or {})
    cap = {"cpu": cpu, "memory": mem_gib * (1 << 30), "pods": pods}
    if gpus:
        cap["nvidia.com/gpu"] = gpus
    return Node(name=name, labels=lbl, capacity=dict(cap),
                allocatable=dict(cap), taints=list(taints or []),
                ready=ready, unschedulable=unschedulable)


def _pod(name, cpu=0.5, mem_mib=512, node="", uid="", **kw):
    p = Pod(name=name, uid=uid or f"uid-{name}",
            requests={"cpu": cpu, "memory": mem_mib * (1 << 20)},
            node_name=node, **kw)
    return p


def scenarios() -> list[tuple[str, list[DeltaWriter], str]]:
    """(name, delta writers in apply order, description)."""
    out = []

    # -- 1: node field coverage ------------------------------------------
    w = DeltaWriter()
    w.upsert_node(_node("plain"), group_id=0)
    w.upsert_node(_node(
        "full", cpu=16.0, mem_gib=64, pods=110,
        labels={"pool": "a", "disk": "ssd"},
        taints=[Taint("dedicated", "infra", "NoSchedule"),
                Taint("flaky", "", "NoExecute"),
                Taint("soft", "x", "PreferNoSchedule")],  # effect=2 (other)
        zone="us-a", gpus=4), group_id=1)
    w.upsert_node(_node("cordoned", unschedulable=True), group_id=0)
    w.upsert_node(_node("unready", ready=False), group_id=-1)
    out.append(("nodes_fields", [w],
                "every UPSERT_NODE field: labels, the three taint-effect "
                "encodings, zone, extended resource, flags byte, group_id"))

    # -- 2: pod field coverage -------------------------------------------
    w = DeltaWriter()
    w.upsert_node(_node("host-1", zone="us-a"), group_id=0)
    w.upsert_pod(_pod("resident", node="host-1"), movable=True)
    w.upsert_pod(_pod("blocker", node="host-1"), blocks=True)
    w.upsert_pod(_pod(
        "selective", node_selector={"disk": "ssd", "pool": "a"},
        tolerations=[Toleration("dedicated", "Equal", "infra", "NoSchedule"),
                     Toleration("any", "Exists", "", ""),
                     Toleration("", "Exists", "", "")],  # tolerate-everything
        host_ports=((8080, "TCP"), (53, "UDP"))))
    anti = _pod("anti-self", labels={"app": "web"})
    anti.anti_affinity = [AffinityTerm(match_labels={"app": "web"},
                                       topology_key="kubernetes.io/hostname")]
    w.upsert_pod(anti)
    out.append(("pods_fields", [w],
                "UPSERT_POD fields: resident vs pending, movable/blocks "
                "flags, selectors, the three toleration encodings, TCP/UDP "
                "hostPorts, the anti_affinity_self + lossy flag bits, and "
                "the KAUX trailer the labeled pods produce"))

    # -- 3: equivalence groups + alloc charging ---------------------------
    w = DeltaWriter()
    w.upsert_node(_node("h1"), group_id=0)
    w.upsert_node(_node("h2"), group_id=0)
    rs = OwnerRef(kind="ReplicaSet", name="rs-twins", uid="uid-rs-twins")
    for i in range(3):
        w.upsert_pod(_pod(f"twin-{i}", uid=f"uid-twin-{i}", owner=rs),
                     movable=True)
    # same spec → same eqkey string → one group row, count 3
    for i in range(2):
        w.upsert_pod(_pod(f"res-{i}", cpu=1.0, mem_mib=1024,
                          node=f"h{i + 1}"))
    out.append(("equivalence_and_alloc", [w],
                "identical pending specs share one equivalence row "
                "(count=3); resident pods charge node alloc"))

    # -- 4: incremental delta sequence ------------------------------------
    w1 = DeltaWriter()
    w1.upsert_node(_node("n1", zone="us-a"), group_id=0)
    w1.upsert_node(_node("n2", zone="us-b"), group_id=0)
    w1.upsert_pod(_pod("p1", node="n1"), movable=True)
    w1.upsert_pod(_pod("p2"))
    w2 = DeltaWriter()
    w2.upsert_pod(_pod("p2", node="n2"), movable=True)   # pending → bound
    w2.delete_pod("uid-p1")
    w2.upsert_node(_node("n1", zone="us-a", unschedulable=True),
                   group_id=0)                            # cordon in place
    w3 = DeltaWriter()
    w3.delete_node("n2")                                  # residents released
    out.append(("incremental_sequence", [w1, w2, w3],
                "three deltas: bind, delete-pod, node update in place, "
                "node delete (its resident pod returns to pending)"))

    # -- 5: KAUX constraint records (incl. round-4 fields) ----------------
    w = DeltaWriter()
    w.upsert_node(_node("z1", zone="us-a"), group_id=0)
    spread = _pod("spreader", labels={"app": "web", "rev": "r1"})
    spread.topology_spread = [TopologySpreadConstraint(
        max_skew=2, topology_key="topology.kubernetes.io/zone",
        match_labels={"app": "web"}, match_label_keys=("rev",))]
    w.upsert_pod(spread)
    exotic = _pod("exotic", labels={"app": "api"})
    exotic.topology_spread = [TopologySpreadConstraint(
        max_skew=1, topology_key="topology.kubernetes.io/zone",
        match_labels={"app": "api"}, min_domains=3,
        node_taints_policy="Honor")]
    w.upsert_pod(exotic)
    nsaff = _pod("nsaff", labels={"app": "db"})
    nsaff.pod_affinity = [AffinityTerm(
        match_labels={"app": "web"},
        topology_key="topology.kubernetes.io/zone",
        namespace_selector={"tier": "prod"})]
    w.upsert_pod(nsaff)
    out.append(("aux_constraints", [w],
                "KAUX trailer: merged matchLabelKeys selector, md/nap/ntp "
                "fields, namespace_selector (nssel) on an affinity term"))
    return out


def _writer_manifest(w: DeltaWriter) -> dict:
    """Human/Go-readable digest of one delta: op count + aux doc."""
    payload = w.payload()
    body, aux = split_aux(payload)
    return {
        "bytes": len(payload),
        "kad1_bytes": len(body),
        "records": int.from_bytes(body[4:8], "little"),
        "aux": aux,
    }


def replay(payloads: list[bytes], dims=None):
    """Apply payloads through the native codec; return (state, exports)."""
    from kubernetes_autoscaler_tpu.models.cluster_state import DEFAULT_DIMS
    from kubernetes_autoscaler_tpu.sidecar.native_api import (
        NativeSnapshotState,
    )

    st = NativeSnapshotState(dims or DEFAULT_DIMS)
    for p in payloads:
        body, _aux = split_aux(p)
        st.apply_delta(body)
    nodes, groups, pods = st.export(node_bucket=16, group_bucket=8,
                                    pod_bucket=16)
    return st, (nodes, groups, pods)


def write_goldens(directory: str = GOLDEN_DIR) -> list[str]:
    os.makedirs(directory, exist_ok=True)
    manifest = {}
    names = []
    for name, writers, desc in scenarios():
        payloads = [w.payload() for w in writers]
        st, (nodes, groups, pods) = replay(payloads)
        arrays = {f"payload_{i}": np.frombuffer(p, np.uint8)
                  for i, p in enumerate(payloads)}
        arrays.update({f"nodes.{k}": v for k, v in nodes.items()})
        arrays.update({f"groups.{k}": v for k, v in groups.items()})
        arrays.update({f"pods.{k}": v for k, v in pods.items()})
        n, p, g = st.counts()
        arrays["counts"] = np.array([n, p, g, st.version], np.int64)
        np.savez(os.path.join(directory, f"{name}.npz"), **arrays)
        manifest[name] = {
            "description": desc,
            "deltas": [_writer_manifest(w) for w in writers],
            "counts": {"nodes": n, "pods": p, "groups": g,
                       "version": st.version},
        }
        names.append(name)
    with open(os.path.join(directory, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    return names


if __name__ == "__main__":  # regenerate: python -m kubernetes_autoscaler_tpu.sidecar.conformance
    for n in write_goldens():
        print(f"wrote {os.path.join(GOLDEN_DIR, n)}.npz")
