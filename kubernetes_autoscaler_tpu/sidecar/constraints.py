"""Constraint overlay for sidecar-fed snapshots.

The KAD1 dense rows (C++ codec) cannot carry topology-coupled specs; the wire
ships them on the KAUX trailer (`sidecar/wire.py`). This module rebuilds what
`models/encode.encode_cluster` derives natively — per-group constraint
scalars + resident-count AffinityPlanes — on top of the C++-exported tensors,
so a Go-fed cluster gets the device constrained tier (ops/constrained.py)
instead of blanket host-checking every constrained pod.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from kubernetes_autoscaler_tpu.models.api import (
    HOSTNAME_KEY,
    ZONE_KEY,
    ZONE_KEY_BETA,
    labels_match,
)
from kubernetes_autoscaler_tpu.models.cluster_state import AffinityPlanes


def _kind(topology_key: str) -> int:
    if topology_key == HOSTNAME_KEY:
        return 1
    if topology_key in (ZONE_KEY, ZONE_KEY_BETA):
        return 2
    return 0


def _term_matches(sel: dict, namespaces: list[str], own_ns: str,
                  other_ns: str, other_labels: dict) -> bool:
    nss = namespaces or [own_ns]
    return other_ns in nss and labels_match(sel, other_labels)


def attach_constraints(state, specs, n_nodes: int, aux: dict[str, dict],
                       max_zones: int = 16):
    """(specs', planes, has_constraints) from the aux records.

    `state` is a NativeSnapshotState (needs group_key(row) and node_row(name));
    `specs` the exported PodGroupTensors; aux maps pod uid -> wire record.
    """
    # zones_fit guard (mirrors encode_cluster): the codec's zone ids are
    # unbounded; when they exceed the static Z dim the kernels would ALIAS
    # distinct zones, so zone-kind constraints must fall back to host-check
    zones_fit = state.num_zones() + 1 <= max_zones
    g_pad = specs.g
    row_of: dict[str, int] = {}
    for r in range(g_pad):
        key = state.group_key(r)
        if key:
            row_of[key] = r

    spread_kind = np.zeros((g_pad,), np.int32)
    max_skew = np.zeros((g_pad,), np.int32)
    spread_self = np.zeros((g_pad,), bool)
    aff_kind = np.zeros((g_pad,), np.int32)
    aff_self = np.zeros((g_pad,), bool)
    anti_self_zone = np.zeros((g_pad,), bool)
    anti_self_host = np.asarray(specs.anti_affinity_self).copy()
    lossy = np.asarray(specs.needs_host_check).copy()

    # exemplar constraint specs per row (first constrained record wins)
    row_spec: dict[int, dict] = {}
    constrained = False
    for rec in aux.values():
        if not (rec.get("s") or rec.get("a") or rec.get("x")):
            continue
        row = row_of.get(rec.get("k", ""))
        if row is None or row in row_spec:
            continue
        row_spec[row] = rec
        exotic = False
        s = rec.get("s")
        if s:
            k = _kind(s["key"])
            if k == 2 and not zones_fit:
                k = 0
            # the dense tier models the DEFAULT inclusion policies and
            # minDomains=1 only; non-defaults go to the exact host tier
            # (mirrors models/encode._encode_pod_spec)
            nondefault = (int(s.get("md", 1)) > 1
                          or s.get("nap", "Honor") == "Ignore"
                          or s.get("ntp", "Ignore") == "Honor")
            if k and not s.get("extra") and not nondefault:
                spread_kind[row] = k
                max_skew[row] = max(int(s["w"]), 1)
                spread_self[row] = labels_match(s["sel"], rec["l"])
            else:
                exotic = True
        a = rec.get("a")
        if a:
            k = _kind(a["key"])
            if k == 2 and not zones_fit:
                k = 0
            if a.get("nssel") is not None:
                k = 0  # namespace-by-labels scoping → exact host tier
            if k and not a.get("extra"):
                aff_kind[row] = k
                aff_self[row] = _term_matches(
                    a["sel"], a.get("nss", []), rec["ns"], rec["ns"], rec["l"])
            else:
                exotic = True
        for t in rec.get("x", []):
            k = _kind(t["key"])
            if k == 2 and not zones_fit:
                k = 0
            if t.get("nssel") is not None:
                k = 0  # namespace-by-labels scoping → exact host tier
            if k == 0:
                exotic = True
                continue
            self_m = _term_matches(t["sel"], t.get("nss", []), rec["ns"],
                                   rec["ns"], rec["l"])
            if k == 1:
                anti_self_host[row] |= self_m
            else:
                anti_self_zone[row] |= self_m
        if exotic:
            lossy[row] = True
        else:
            constrained = True
            if rec.get("dok"):
                # topology was the only reason the wire flagged lossy, and
                # the overlay now models it — the device tier is exact here
                # (cross-group coupling may re-flag below)
                lossy[row] = False

    if not row_spec:
        return specs, None, False

    # cross-group coupling (mirror encode_cluster): a constrained PENDING
    # row whose selector matches another pending record stays host-checked
    pending = [r for r in aux.values() if not r.get("n")]
    for row, rec in row_spec.items():
        sels: list[tuple[dict, list[str]]] = []
        if rec.get("s") and spread_kind[row]:
            sels.append((rec["s"]["sel"], [rec["ns"]]))
        for t in rec.get("x", []):
            sels.append((t["sel"], t.get("nss", []) or [rec["ns"]]))
        a = rec.get("a")
        if a and aff_kind[row] and not aff_self[row]:
            sels.append((a["sel"], a.get("nss", []) or [rec["ns"]]))
        for other in pending:
            # siblings of the SAME equivalence group are the group's own
            # placements — modeled on device (spread_self/anti caps), not a
            # cross-group coupling (mirrors encode_cluster's hrow != grow)
            if other is rec or other.get("k") == rec.get("k"):
                continue
            if any(other["ns"] in nss and labels_match(sel, other["l"])
                   for sel, nss in sels):
                lossy[row] = True
                break

    # resident-count planes
    p_aff = np.zeros((g_pad, n_nodes), np.int32)
    p_anti_h = np.zeros((g_pad, n_nodes), np.int32)
    p_anti_z = np.zeros((g_pad, n_nodes), np.int32)
    p_spread = np.zeros((g_pad, n_nodes), np.int32)
    for rec in aux.values():
        name = rec.get("n")
        if not name:
            continue
        ni = state.node_row(name)
        if ni < 0 or ni >= n_nodes:
            continue
        for row, spec in row_spec.items():
            a = spec.get("a")
            if a and aff_kind[row] and _term_matches(
                    a["sel"], a.get("nss", []), spec["ns"], rec["ns"], rec["l"]):
                p_aff[row, ni] += 1
            for t in spec.get("x", []):
                k = _kind(t["key"])
                if k and _term_matches(t["sel"], t.get("nss", []), spec["ns"],
                                       rec["ns"], rec["l"]):
                    if k == 1:
                        p_anti_h[row, ni] += 1
                    else:
                        p_anti_z[row, ni] += 1
            s = spec.get("s")
            if (s and spread_kind[row] and rec["ns"] == spec["ns"]
                    and labels_match(s["sel"], rec["l"])):
                p_spread[row, ni] += 1

    specs = specs.replace(
        spread_kind=jnp.asarray(spread_kind),
        max_skew=jnp.asarray(max_skew),
        spread_self=jnp.asarray(spread_self),
        aff_kind=jnp.asarray(aff_kind),
        aff_self=jnp.asarray(aff_self),
        aff_match_any=jnp.asarray(p_aff.sum(axis=1) > 0),
        anti_self_zone=jnp.asarray(anti_self_zone),
        anti_affinity_self=jnp.asarray(anti_self_host),
        needs_host_check=jnp.asarray(lossy),
    )
    planes = AffinityPlanes(
        aff_cnt=jnp.asarray(p_aff),
        anti_host_cnt=jnp.asarray(p_anti_h),
        anti_zone_cnt=jnp.asarray(p_anti_z),
        spread_cnt=jnp.asarray(p_spread),
    )
    return specs, planes, constrained
