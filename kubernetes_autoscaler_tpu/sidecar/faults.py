"""Deterministic fault injection for the serving path (docs/ROBUSTNESS.md).

Chaos that is replayable evidence, not flakes: a FaultPlan is a SEEDED,
declaratively-configured schedule of faults bound to NAMED hook points at
every pipeline hand-off of the sidecar —

  codec_decode     ApplyDelta payload entering the C++ codec
  classify         shape-ladder classification of a tenant's world
  stack            member lanes stacking into one batched pytree
  h2d              a tenant's resident device lanes uploading
  dispatch         the vmapped sim program launching
  harvest          the async device→host result fetch completing
  assembly         one member's response assembling from the fetched pytree
  grpc_reply       the response leaving the gRPC handler
  scheduler_loop   the BatchScheduler's serve loop (thread-death chaos)

— and, since the control loop grew its own survival layer
(core/supervisor.py, docs/ROBUSTNESS.md "Control loop"), at the LOCAL
guarded phases of StaticAutoscaler.run_once:

  local_encode     the world encode / delta program building
  local_dispatch   the filter-out-schedulable + sim dispatch
  local_fetch      the device→host verdict fetch
  local_probe      the supervisor's recovery probe

Specs fire on deterministic match-hit counters (`after` skips the first N
matching invocations, `times` caps total fires; a tenant-scoped spec counts
only that tenant's invocations, so its schedule is independent of co-tenant
interleaving), and probabilistic specs draw from a per-spec `random.Random`
seeded by (plan seed, spec id) — the same plan over the same request
sequence injects the same faults.

Zero overhead when disabled is a CONTRACT, not an aspiration: the module
global `PLAN` is None unless a plan is installed, and every hook site guards
with `if faults.PLAN is not None` — one global load + identity test, no
function call, no dict lookup (the chaos bench measures the guard at
single-digit ns/op and CI asserts it stays that way).

Every fired fault is stamped three ways so a chaos run leaves evidence:
`faults_injected_total{hook,kind}` on the service registry, a closed
`fault/<hook>` span on the active tracer (when the hook runs on a traced
handler thread), and an entry in the plan's bounded fire log (sequence,
hook, kind, spec, tenant) — the log is what the bench's `chaos` block and
the Statusz faults section print.

Config: programmatic `install(specs, seed=...)` (tests, bench) or the
`KATPU_FAULTS` env var — a JSON document `{"seed": 7, "specs": [...]}` or
`@/path/to/plan.json` — read once by the first SimulatorService that
starts while no plan is installed.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass

HOOKS = ("codec_decode", "classify", "stack", "h2d", "dispatch",
         "harvest", "assembly", "grpc_reply", "scheduler_loop",
         # the local control loop's guarded phases (core/supervisor.py)
         "local_encode", "local_dispatch", "local_fetch", "local_probe",
         # the fetched filter-out-schedulable verdict plane, right after
         # its device→host copy (core/static_autoscaler.py) — the
         # shadow-audit-visible corruption point: a flip_bit spec here
         # corrupts what every downstream consumer reads while the device
         # buffer keeps the truth (audit/shadow.py detects the split)
         "verdict_plane")

# raise: typed InjectedFault; delay/hang: sleep delay_ms (hang is the same
# mechanism with an alarming name — a bounded stall, so tests can assert
# deadline behavior without wedging the suite); truncate: cut a bytes
# payload in half (a torn KAD1 section); nan: NaN every float plane of a
# dict-of-arrays payload (a poisoned world/result); flip_bit: XOR one bit
# of one element of an integer ndarray payload (single-bit silent data
# corruption — the canonical SDC shape the online shadow audit must
# detect within one loop; element/bit picked by the spec's seeded RNG,
# overridable via `index`/`bit`).
KINDS = ("raise", "delay", "hang", "truncate", "nan", "flip_bit")

ENV_VAR = "KATPU_FAULTS"


class InjectedFault(RuntimeError):
    """The typed error a `raise`-kind spec throws: carries its hook + spec
    id so the isolation layer can attribute a window failure (and the
    quarantine reason) to the exact injection point."""

    def __init__(self, hook: str, spec_id: str, message: str = ""):
        super().__init__(message or f"injected fault at {hook} [{spec_id}]")
        self.hook = hook
        self.spec_id = spec_id


@dataclass
class FaultSpec:
    """One declarative fault: where (hook, optional tenant), what (kind),
    and when (after/times/prob) it fires."""

    hook: str
    kind: str = "raise"
    tenant: str = ""        # exact tenant match; "" = any request
    after: int = 0          # skip the first N matching invocations
    times: int = 1          # fire at most N times; 0 = unlimited
    prob: float = 1.0       # seeded Bernoulli per eligible invocation
    delay_ms: float = 0.0   # delay/hang sleep
    index: int = -1         # flip_bit: element index (-1 = seeded pick)
    bit: int = -1           # flip_bit: bit position (-1 = seeded pick)
    message: str = ""
    id: str = ""

    def __post_init__(self):
        if self.hook not in HOOKS:
            raise ValueError(f"unknown fault hook {self.hook!r}; "
                             f"hooks are {', '.join(HOOKS)}")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"kinds are {', '.join(KINDS)}")
        if not self.id:
            self.id = f"{self.hook}/{self.kind}" + (
                f"@{self.tenant}" if self.tenant else "")


class FaultPlan:
    """A seeded spec set + per-spec fire state + the bounded fire log."""

    def __init__(self, specs, seed: int = 0, registry=None,
                 log_capacity: int = 512):
        self.specs: list[FaultSpec] = [
            s if isinstance(s, FaultSpec) else FaultSpec(**s) for s in specs]
        self.seed = int(seed)
        # default registry for hook sites that have no handle (batch.py,
        # admission.py); server.py sites pass their service registry
        self.registry = registry
        self._lock = threading.Lock()
        self._hits = [0] * len(self.specs)
        self._fired = [0] * len(self.specs)
        self._rng = [random.Random(f"{self.seed}:{i}:{s.id}")
                     for i, s in enumerate(self.specs)]
        self.log: deque[dict] = deque(maxlen=log_capacity)
        self.seq = 0

    # ---- the hook-site entry ----

    def fire(self, hook: str, tenant: str = "", tenants=(),
             payload=None, registry=None):
        """Evaluate every spec against one hook invocation. Returns the
        (possibly corrupted) payload; raises InjectedFault for `raise`
        specs. `tenant` is the single-request identity, `tenants` the
        member set of a batched hand-off — a tenant-scoped spec matches
        either way, so a window fails exactly when the poison member is
        co-batched."""
        for i, s in enumerate(self.specs):
            if s.hook != hook:
                continue
            if s.tenant and s.tenant != tenant \
                    and s.tenant not in (tenants or ()):
                continue
            with self._lock:
                self._hits[i] += 1
                if self._hits[i] <= s.after:
                    continue
                if s.times and self._fired[i] >= s.times:
                    continue
                if s.prob < 1.0 and self._rng[i].random() >= s.prob:
                    continue
                self._fired[i] += 1
                seq = self.seq
                self.seq += 1
                self.log.append({
                    "seq": seq, "hook": hook, "kind": s.kind, "spec": s.id,
                    "tenant": s.tenant or tenant or ""})
            payload = self._act(s, hook, s.tenant or tenant,
                                payload, registry or self.registry,
                                rng=self._rng[i])
        return payload

    def _act(self, s: FaultSpec, hook: str, tenant: str, payload, registry,
             rng=None):
        self._stamp(s, hook, tenant, registry)
        if s.kind in ("delay", "hang"):
            time.sleep(max(s.delay_ms, 0.0) / 1000.0)
            return payload
        if s.kind == "raise":
            raise InjectedFault(hook, s.id, s.message)
        if s.kind == "truncate":
            if isinstance(payload, (bytes, bytearray)):
                return bytes(payload)[: max(len(payload) // 2 - 1, 0)]
            return payload
        if s.kind == "nan":
            return _nan_corrupt(payload)
        if s.kind == "flip_bit":
            return _flip_bit(payload, s, rng)
        return payload  # pragma: no cover — KINDS is exhaustive

    @staticmethod
    def _stamp(s: FaultSpec, hook: str, tenant: str, registry) -> None:
        """Every injected fault is accounted evidence: a labelled counter
        on the registry and a closed span on the active tracer (handler
        threads run under `traced_call`, so payload/classify/reply faults
        land on the request's own timeline)."""
        if registry is not None:
            registry.counter(
                "faults_injected_total",
                help="Faults injected by the deterministic chaos plane "
                     "(sidecar/faults.py), by hook point and kind",
            ).inc(hook=hook, kind=s.kind)
        from kubernetes_autoscaler_tpu.metrics import trace as _trace

        tr = _trace.current_tracer()
        if tr is not None:
            tr.add_span(f"fault/{hook}", cat="fault", kind=s.kind,
                        spec=s.id, **({"tenant": tenant} if tenant else {}))

    # ---- accounting ----

    def fired_total(self) -> int:
        with self._lock:
            return sum(self._fired)

    def stats(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "specs": [
                    {"id": s.id, "hook": s.hook, "kind": s.kind,
                     "tenant": s.tenant, "hits": self._hits[i],
                     "fired": self._fired[i]}
                    for i, s in enumerate(self.specs)],
                "fired_total": sum(self._fired),
                "log_tail": list(self.log)[-8:],
            }


def _nan_corrupt(payload):
    """NaN every float plane of a dict-of-arrays payload (int planes are
    left alone — NaN has no int encoding; the validation layer catches
    negative/oversize int corruption separately)."""
    import numpy as np

    if not isinstance(payload, dict):
        return payload
    out = {}
    for k, v in payload.items():
        if isinstance(v, np.ndarray) and np.issubdtype(v.dtype, np.floating):
            v = np.full_like(v, np.nan)
        out[k] = v
    return out


def _flip_bit(payload, s: FaultSpec, rng):
    """XOR one bit of one element of an integer ndarray (a COPY — the
    caller's array may be a host mirror shared with other readers). The
    single-bit-flip is the canonical silent-data-corruption shape: the
    payload stays structurally valid, finite, plausible — only a
    golden-output check (the shadow audit) can tell."""
    import numpy as np

    if not isinstance(payload, np.ndarray) or payload.size == 0 \
            or not np.issubdtype(payload.dtype, np.integer):
        return payload
    out = payload.copy()
    flat = out.reshape(-1)
    idx = s.index if 0 <= s.index < flat.size else \
        (rng.randrange(flat.size) if rng is not None else 0)
    nbits = out.dtype.itemsize * 8 - 1   # spare the sign bit
    bit = s.bit if 0 <= s.bit < nbits else \
        (rng.randrange(nbits) if rng is not None else 0)
    flat[idx] = int(flat[idx]) ^ (1 << bit)
    return out


# ---- module-level plan (the zero-overhead guard reads this) ----

PLAN: FaultPlan | None = None


def install(specs, seed: int = 0, registry=None) -> FaultPlan:
    """Install a plan as the process's active fault plane (tests/bench)."""
    global PLAN
    PLAN = specs if isinstance(specs, FaultPlan) else FaultPlan(
        specs, seed=seed, registry=registry)
    return PLAN


def clear() -> None:
    global PLAN
    PLAN = None


def from_env(registry=None) -> FaultPlan | None:
    """Install from KATPU_FAULTS (JSON, or @path) — no-op when unset or a
    plan is already installed (programmatic install wins)."""
    if PLAN is not None:
        return PLAN
    raw = os.environ.get(ENV_VAR, "")
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    doc = json.loads(raw)
    if not isinstance(doc, dict):
        raise ValueError(f"{ENV_VAR} must be a JSON object "
                         f"{{'seed': ..., 'specs': [...]}}")
    return install(doc.get("specs", []), seed=doc.get("seed", 0),
                   registry=registry)
