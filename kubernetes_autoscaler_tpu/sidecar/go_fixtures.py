"""Go-test fixture exporter: golden scenarios as stdlib-readable files.

The Go encoder (`go/katpusim/kad1.go`) must produce byte-identical KAD1
bodies and semantically-equal KAUX trailers for the conformance scenarios
(docs/SIDECAR_WIRE.md §Conformance). This image ships no Go toolchain (r4
verdict Missing #3), so the fixtures are exported in forms `go test` can
consume with the standard library alone:

  go/katpusim/testdata/<scenario>.json        — per-delta writer-call records
  go/katpusim/testdata/<scenario>_<i>.bin     — the committed payload bytes

The records are DECODED BACK from the Python writer's own bytes (not
re-lowered), so exporter drift is impossible: whatever the Python encoder
wrote is exactly what the Go replay is asked to reproduce.

Regenerate after a wire change:  python -m kubernetes_autoscaler_tpu.sidecar.go_fixtures
"""

from __future__ import annotations

import json
import os
import struct

from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.sidecar.wire import (
    DELETE_NODE,
    DELETE_POD,
    MAGIC,
    UPSERT_NODE,
    UPSERT_POD,
)

GO_TESTDATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "go", "katpusim", "testdata")


def split_payload(payload: bytes) -> tuple[int, bytes, dict | None]:
    """(record count, KAD1 body, aux doc or None)."""
    assert payload[:4] == MAGIC
    count = struct.unpack_from("<I", payload, 4)[0]
    rest = payload[8:]
    aux = None
    if rest.endswith(b"KAUX"):
        doc_len, _crc = struct.unpack_from("<II", rest, len(rest) - 12)
        doc = rest[len(rest) - 12 - doc_len: len(rest) - 12]
        aux = json.loads(doc.decode())
        rest = rest[: len(rest) - 12 - doc_len]
    return count, rest, aux


def _rstr(b: bytes, o: int) -> tuple[str, int]:
    n = struct.unpack_from("<H", b, o)[0]
    return b[o + 2: o + 2 + n].decode(), o + 2 + n


def decode_records(body: bytes, count: int) -> list[dict]:
    """KAD1 body → writer-call records (the Go test's replay inputs)."""
    out: list[dict] = []
    o = 0
    r = res.NUM_RESOURCES
    for _ in range(count):
        op = body[o]
        o += 1
        if op == UPSERT_NODE:
            name, o = _rstr(body, o)
            n_lbl = struct.unpack_from("<H", body, o)[0]
            o += 2
            labels = []
            for _i in range(n_lbl):
                k, o = _rstr(body, o)
                v, o = _rstr(body, o)
                labels.append([k, v])
            n_taints = body[o]
            o += 1
            taints = []
            for _i in range(n_taints):
                k, o = _rstr(body, o)
                v, o = _rstr(body, o)
                taints.append({"key": k, "value": v, "effect": body[o]})
                o += 1
            cap = list(struct.unpack_from(f"<{r}i", body, o))
            o += 4 * r
            flags = body[o]
            o += 1
            group_id = struct.unpack_from("<i", body, o)[0]
            o += 4
            zone, o = _rstr(body, o)
            out.append({"op": "upsert_node", "name": name, "labels": labels,
                        "taints": taints, "cap": cap,
                        "ready": bool(flags & 1),
                        "unschedulable": bool(flags & 2),
                        "group_id": group_id, "zone": zone})
        elif op == DELETE_NODE:
            name, o = _rstr(body, o)
            out.append({"op": "delete_node", "name": name})
        elif op == UPSERT_POD:
            uid, o = _rstr(body, o)
            node, o = _rstr(body, o)
            req = list(struct.unpack_from(f"<{r}i", body, o))
            o += 4 * r
            n_sel = struct.unpack_from("<H", body, o)[0]
            o += 2
            sel = []
            for _i in range(n_sel):
                k, o = _rstr(body, o)
                v, o = _rstr(body, o)
                sel.append([k, v])
            n_tol = body[o]
            o += 1
            tols = []
            for _i in range(n_tol):
                k, o = _rstr(body, o)
                exists = bool(body[o])
                o += 1
                v, o = _rstr(body, o)
                tols.append({"key": k, "exists": exists, "value": v,
                             "effect": body[o]})
                o += 1
            n_ports = body[o]
            o += 1
            ports = []
            for _i in range(n_ports):
                port = struct.unpack_from("<H", body, o)[0]
                o += 2
                ports.append({"port": port, "udp": bool(body[o])})
                o += 1
            flags = body[o]
            o += 1
            eqkey, o = _rstr(body, o)
            out.append({"op": "upsert_pod", "uid": uid, "node": node,
                        "req": req, "selector": sel, "tolerations": tols,
                        "ports": ports,
                        "movable": bool(flags & 1), "blocks": bool(flags & 2),
                        "anti_self": bool(flags & 4),
                        "lossy": bool(flags & 8), "eqkey": eqkey})
        elif op == DELETE_POD:
            uid, o = _rstr(body, o)
            out.append({"op": "delete_pod", "uid": uid})
        else:
            raise ValueError(f"unknown op {op} at offset {o - 1}")
    assert o == len(body), (o, len(body))
    return out


def export(directory: str = GO_TESTDATA) -> list[str]:
    from kubernetes_autoscaler_tpu.sidecar.conformance import scenarios

    os.makedirs(directory, exist_ok=True)
    written = []
    for name, writers, _desc in scenarios():
        deltas = []
        for i, w in enumerate(writers):
            payload = w.payload()
            count, body, aux = split_payload(payload)
            records = decode_records(body, count)
            # per-pod aux records keyed by uid, so the Go replay can hand
            # each UpsertPod its AuxRecord (shape = AuxRecord json tags)
            aux_up = (aux or {}).get("up", {})
            for rec in records:
                if rec["op"] == "upsert_pod":
                    rec["aux"] = aux_up.get(rec["uid"])
            bin_name = f"{name}_{i}.bin"
            with open(os.path.join(directory, bin_name), "wb") as f:
                f.write(payload)
            deltas.append({"payload": bin_name, "records": records,
                           "aux_deletes": (aux or {}).get("del", []),
                           "has_aux": aux is not None})
        path = os.path.join(directory, f"{name}.json")
        with open(path, "w") as f:
            json.dump({"scenario": name, "deltas": deltas}, f, indent=1,
                      sort_keys=True)
        written.append(path)
    return written


if __name__ == "__main__":
    for p in export():
        print(p)
