"""Request-lifecycle decomposition + per-tenant SLO budgets for the sidecar.

`rpc_duration_seconds{tenant}` says a request took N ms; this module says
WHERE those ms went. Every admitted request is stamped with monotonic
`perf_counter_ns` marks at each hand-off of the serving pipeline and the
decomposition is derived as CONTIGUOUS intervals, so the phases sum to the
end-to-end latency by construction (CI asserts the sum within tolerance —
a drifting sum means a stamp went missing, not that clocks skewed):

  encode    RPC entry → ticket enqueued: world export at class shape,
            node-group template lowering, lane build (under ts.lock)
  queue     enqueued → popped into a window: admission-queue wait PLUS the
            coalescing window the scheduler held open for joiners
  form      window popped → stack start: split-by-key, canonical member
            sort, chunking, and any wait behind earlier chunks' dispatches
  stack     member numpy worlds → one stacked device pytree (0 on a stack
            cache hit — steady windows re-hit instead of re-uploading)
  dispatch  the vmapped sim call: program launch (async backends return
            before compute finishes) + issuing the async result fetch
  harvest   fetch issued → results on host. Includes the deliberate
            pipeline delay (window k is harvested only after window k+1's
            dispatch is in flight) — from the REQUEST's view all of it is
            waiting for results
  assembly  host pytree → this member's JSON response
  reply     ticket resolved → the handler thread actually woke and took
            the response (scheduler→handler hand-off latency)

The serial (non-batched / constrained) path stamps the subset that exists
there: encode, dispatch, harvest (response build including device→host
reads); queue/form/stack/reply are structurally zero and omitted.

The decomposition rides three surfaces at once (docs/OBSERVABILITY.md):
per-tenant histograms `request_phase_seconds{phase,tenant}` (stale-zeroed
on drop_tenant), a closed `lifecycle` span tree on the request's trace, and
a `lifecycle` block in the gRPC response JSON so the CLIENT's RunOnce trace
can show server-side queue time distinct from network time (client-observed
RPC wall minus server e2e ≈ wire + serialization).

`SloBudgets` is the per-tenant latency budget table: a tenant class (or the
client itself, via `wire.SLO_BUDGET_MS_HEADER`) declares how slow is too
slow; a breach bumps `tenant_slo_breaches_total{tenant}` and triggers a
TENANT-SCOPED tail-sampler dump (only that tenant's retained request
traces, never the whole ring — see server._on_complete).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

# canonical phase order (batched path); the serial path uses the subset
# (encode, dispatch, harvest)
LIFECYCLE_PHASES = ("encode", "queue", "form", "stack", "dispatch",
                    "harvest", "assembly", "reply")

# request phases span ~10 µs (assembly) to ~100 ms (a cold-compile
# dispatch); the registry's default 5ms-start buckets would flatten them
REQUEST_PHASE_BUCKETS = (0.00001, 0.000025, 0.00005, 0.0001, 0.00025,
                         0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                         0.1, 0.25, 0.5, 1.0, 2.5, 10.0)


@dataclass
class Stamps:
    """Monotonic `perf_counter_ns` marks along one request's pipeline.
    Batch-level marks (stack/dispatch/harvest/assembly) are shared by every
    member of the batch — the hand-offs happen once per batch."""

    entry: int = 0          # RPC body entry (before world export)
    enqueue: int = 0        # ticket submitted to the admission queue
    collected: int = 0      # popped into a coalescing window
    stack0: int = 0         # batch stacking began
    stack1: int = 0         # stacked device pytree ready
    dispatched: int = 0     # vmapped sim launched + async fetch issued
    harvested: int = 0      # results on host
    resolved: int = 0       # this member's response assembled + resolved
    woke: int = 0           # handler thread woke with the response

    def phases_ns(self) -> dict[str, int]:
        """Contiguous decomposition; only phases whose both endpoints were
        stamped appear (the serial path stamps a subset). Negative clamps
        guard perf-counter reads racing across threads (sub-µs skew)."""
        marks = [("encode", self.entry, self.enqueue),
                 ("queue", self.enqueue, self.collected),
                 ("form", self.collected, self.stack0),
                 ("stack", self.stack0, self.stack1),
                 ("dispatch", self.stack1, self.dispatched),
                 ("harvest", self.dispatched, self.harvested),
                 ("assembly", self.harvested, self.resolved),
                 ("reply", self.resolved, self.woke)]
        out: dict[str, int] = {}
        prev_end = 0
        for name, a, b in marks:
            if a and b:
                out[name] = max(b - a, 0)
            elif b and prev_end:
                # a stamp is missing upstream (serial path): charge from the
                # last stamped mark so the chain stays contiguous
                out[name] = max(b - prev_end, 0)
            prev_end = b or prev_end
        return out

    def e2e_ns(self) -> int:
        last = self.woke or self.resolved or self.harvested
        return max(last - self.entry, 0) if self.entry and last else 0


def lifecycle_block(stamps: Stamps, batch_id: str | None = None,
                    trace_id: str | None = None) -> dict:
    """The `lifecycle` block a gRPC response carries: phase milliseconds +
    e2e, so the client sees server-side time decomposed and can derive
    network time as (client rpc wall − e2e_ms)."""
    phases = {k: round(v / 1e6, 4) for k, v in stamps.phases_ns().items()}
    block = {"phases_ms": phases,
             "e2e_ms": round(stamps.e2e_ns() / 1e6, 4)}
    if batch_id:
        block["batch_id"] = batch_id
    if trace_id:
        block["trace_id"] = trace_id
    return block


def add_lifecycle_spans(tracer, stamps: Stamps, cat: str = "lifecycle",
                        **root_args) -> None:
    """Emit the decomposition as a CLOSED `lifecycle` span tree on
    `tracer`: one parent spanning e2e, one child per phase, all from the
    absolute perf-counter stamps (Tracer.add_span rebases them), so the
    Perfetto dump shows queue vs dispatch vs harvest as nested intervals
    without any live begin/end bracketing."""
    if tracer is None or not stamps.entry:
        return
    tracer.add_span("lifecycle", cat=cat, begin_abs_ns=stamps.entry,
                    dur_ns=stamps.e2e_ns(), **root_args)
    t = stamps.entry
    for name, dur in stamps.phases_ns().items():
        tracer.add_span(f"lifecycle/{name}", cat=cat, begin_abs_ns=t,
                        dur_ns=dur)
        t += dur


class SloBudgets:
    """Per-tenant latency budgets (milliseconds). A tenant without an
    explicit budget uses the default; a default of 0 disables breach
    detection for unconfigured tenants. Budgets may be set server-side
    (config) or declared by the client per request via
    `wire.SLO_BUDGET_MS_HEADER` (last write wins — the client knows its
    own loop deadline best)."""

    def __init__(self, default_ms: float = 0.0,
                 budgets: dict[str, float] | None = None):
        self.default_ms = float(default_ms)
        self._budgets: dict[str, float] = dict(budgets or {})
        self._lock = threading.Lock()

    def set(self, tenant: str, budget_ms: float) -> None:
        with self._lock:
            self._budgets[tenant] = float(budget_ms)

    def get(self, tenant: str) -> float:
        with self._lock:
            return self._budgets.get(tenant, self.default_ms)

    def drop(self, tenant: str) -> None:
        with self._lock:
            self._budgets.pop(tenant, None)

    def breached(self, tenant: str, e2e_s: float) -> bool:
        budget = self.get(tenant)
        return budget > 0 and e2e_s * 1000.0 > budget

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._budgets)
