// kacodec: native snapshot-delta codec for the TPU autoscaling sidecar.
//
// Role (SURVEY.md §7 "Components in C++"): the latency-critical host-side
// boundary — decoding versioned snapshot deltas from the control plane
// (the reference's DeltaSnapshotStore idea moved onto the wire,
// cluster-autoscaler/simulator/clustersnapshot/store/delta.go:33-54) and
// lowering the string world (labels/taints/selectors/ports) into the dense
// int32 hash tables the TPU kernels consume, directly into caller-provided
// (pinned) buffers. The Python encoder (models/encode.py) is the semantics
// oracle; this codec must produce bit-identical tables (tests/test_sidecar.py).
//
// Wire format "KAD1" (little-endian):
//   header:  'K''A''D''1'  u32 record_count
//   str:     u16 len, bytes (utf-8)
//   record:  u8 op
//     op=1 UPSERT_NODE: str name, u16 n_labels ×{str k, str v},
//          u8 n_taints ×{str key, str value, u8 effect(0=NoSchedule,1=NoExecute,2=other)},
//          i32 cap[R], u8 flags (bit0 ready, bit1 unschedulable), i32 group_id,
//          str zone
//     op=2 DELETE_NODE: str name
//     op=3 UPSERT_POD: str uid, str node_name (empty ⇒ pending), i32 req[R],
//          u16 n_sel ×{str k, str v},
//          u8 n_tol ×{str key, u8 tolop(0=Equal,1=Exists), str value,
//                     u8 effect(0=NoSchedule,1=NoExecute,2=all)},
//          u8 n_ports ×{u16 port, u8 proto(0=TCP,1=UDP)},
//          u8 flags (bit0 movable, bit1 blocks, bit2 anti_affinity_self),
//          str eqkey (equivalence-group key, '' ⇒ uid)
//     op=4 DELETE_POD: str uid
//
// Build: make -C kubernetes_autoscaler_tpu/sidecar  (→ libkacodec.so)

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint64_t kFnvOffset = 0xCBF29CE484222325ULL;
constexpr uint64_t kFnvPrime = 0x100000001B3ULL;
constexpr int R = 8;  // resource slots; must match models/resources.NUM_RESOURCES

uint64_t fnv1a64(const char* data, size_t n) {
  uint64_t h = kFnvOffset;
  for (size_t i = 0; i < n; i++) {
    h ^= static_cast<uint8_t>(data[i]);
    h *= kFnvPrime;
  }
  return h;
}

// fold32: must mirror utils/hashing.py (nonzero signed int32).
int32_t fold32(const std::string& s) {
  uint64_t h = fnv1a64(s.data(), s.size());
  uint32_t h32 = static_cast<uint32_t>(h ^ (h >> 32));
  if (h32 == 0) h32 = 1;
  return static_cast<int32_t>(h32);
}

const char kKeyMark = '\x01';
const char* kNoSchedule = "NoSchedule";
const char* kNoExecute = "NoExecute";

struct Dims {
  int max_labels, max_taints, max_tolerations, max_sel_terms, max_sel_alts,
      max_neg_terms, max_pod_ports, max_node_ports;
};

struct NodeRow {
  std::string name;
  int32_t cap[R] = {0};
  std::vector<int32_t> label_hash;
  std::vector<int32_t> taint_exact, taint_key;
  std::vector<int32_t> used_ports;  // rebuilt from resident pods on export
  int32_t zone_id = 0, group_id = -1;
  bool ready = true, schedulable = true, valid = true;
};

struct GroupRow {
  std::string eqkey;
  int32_t req[R] = {0};
  std::vector<int32_t> sel_req;  // [S*A]
  std::vector<int32_t> sel_neg;
  std::vector<int32_t> tol_exact, tol_key;
  bool tolerate_all = false;
  std::vector<int32_t> port_hash;
  bool anti_self = false;
  bool lossy = false;
};

struct PodRow {
  std::string uid;
  int32_t req[R] = {0};
  int32_t node_idx = -1;  // -1 = pending
  int32_t group_ref = 0;
  std::vector<int32_t> port_hash;
  bool movable = false, blocks = false, valid = true;
};

// Export-section dirtiness bits: which of the three export surfaces
// (ka_export_nodes / ka_export_groups / ka_export_pods) a delta op can
// change. Node ops touch the node tensors only; a PENDING pod touches the
// group tensors only (its row spec + the pending count); a RESIDENT pod
// touches the scheduled-pod tensors AND the node tensors (alloc/used_ports
// are derived from resident pods at export time).
constexpr unsigned kSecNodes = 1u << 0;
constexpr unsigned kSecGroups = 1u << 1;
constexpr unsigned kSecPods = 1u << 2;

struct State {
  Dims dims;
  std::vector<NodeRow> nodes;
  std::vector<PodRow> pods;
  std::vector<GroupRow> groups;
  std::unordered_map<std::string, int> node_index;   // name -> row
  std::unordered_map<std::string, int> pod_index;    // uid -> row
  std::unordered_map<std::string, int> group_index;  // eqkey -> row
  std::unordered_map<std::string, int32_t> zone_ids;
  std::vector<int> free_node_rows, free_pod_rows;
  uint64_t version = 0;
  // per-export-section versions (0 = nodes, 1 = groups, 2 = pods): bumped
  // once per apply_delta for each section the delta's ops could change —
  // the python sidecar keys its plane-granular export/device caches on
  // these so a single-pod delta never re-materializes untouched planes
  // (ISSUE 11 satellite; ka_section_version).
  uint64_t section_versions[3] = {0, 0, 0};
  std::string error;
};

class Reader {
 public:
  Reader(const uint8_t* buf, size_t len) : p_(buf), end_(buf + len) {}
  bool ok() const { return ok_; }
  uint8_t u8() { return static_cast<uint8_t>(byte()); }
  uint16_t u16() {
    uint16_t lo = u8(), hi = u8();
    return static_cast<uint16_t>(lo | (hi << 8));
  }
  uint32_t u32() {
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) v |= static_cast<uint32_t>(u8()) << (8 * i);
    return v;
  }
  int32_t i32() { return static_cast<int32_t>(u32()); }
  std::string str() {
    uint16_t n = u16();
    if (p_ + n > end_) {
      ok_ = false;
      return std::string();
    }
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += n;
    return s;
  }

 private:
  uint8_t byte() {
    if (p_ >= end_) {
      ok_ = false;
      return 0;
    }
    return *p_++;
  }
  const uint8_t* p_;
  const uint8_t* end_;
  bool ok_ = true;
};

const char* effect_name(uint8_t e) {
  return e == 0 ? kNoSchedule : (e == 1 ? kNoExecute : "");
}

void fill(std::vector<int32_t>& dst, size_t cap, const std::vector<int32_t>& src,
          bool* overflow) {
  dst.assign(cap, 0);
  if (src.size() > cap && overflow) *overflow = true;
  size_t n = src.size() < cap ? src.size() : cap;
  for (size_t i = 0; i < n; i++) dst[i] = src[i];
}

int32_t zone_id_for(State* st, const std::string& zone) {
  if (zone.empty()) return 0;
  auto it = st->zone_ids.find(zone);
  if (it != st->zone_ids.end()) return it->second;
  int32_t id = static_cast<int32_t>(st->zone_ids.size()) + 1;
  st->zone_ids.emplace(zone, id);
  return id;
}

bool parse_node(State* st, Reader* r) {
  NodeRow row;
  row.name = r->str();
  std::vector<int32_t> labels;
  uint16_t nl = r->u16();
  for (int i = 0; i < nl; i++) {
    std::string k = r->str(), v = r->str();
    labels.push_back(fold32(k + "=" + v));
    labels.push_back(fold32(k + kKeyMark));
  }
  std::vector<int32_t> tx, tk;
  bool blocked = false;
  uint8_t nt = r->u8();
  for (int i = 0; i < nt; i++) {
    std::string key = r->str(), value = r->str();
    uint8_t eff = r->u8();
    if (eff > 1) continue;  // PreferNoSchedule etc: score-only
    if (key == "ToBeDeletedByClusterAutoscaler") blocked = true;
    std::string e = effect_name(eff);
    tx.push_back(fold32(key + '\0' + value + '\0' + e));
    tk.push_back(fold32(key + '\0' + e));
  }
  for (int i = 0; i < R; i++) row.cap[i] = r->i32();
  uint8_t flags = r->u8();
  row.group_id = r->i32();
  std::string zone = r->str();
  if (!r->ok()) return false;

  bool overflow = false;
  fill(row.label_hash, st->dims.max_labels, labels, &overflow);
  fill(row.taint_exact, st->dims.max_taints, tx, &overflow);
  fill(row.taint_key, st->dims.max_taints, tk, &overflow);
  if (overflow) {
    st->error = "node table overflow: " + row.name;
    return false;  // mirror encode.py fail-fast semantics
  }
  row.used_ports.assign(st->dims.max_node_ports, 0);
  row.zone_id = zone_id_for(st, zone);
  row.ready = flags & 1;
  row.schedulable = !(flags & 2) && !blocked;

  auto it = st->node_index.find(row.name);
  if (it != st->node_index.end()) {
    st->nodes[it->second] = row;
  } else if (!st->free_node_rows.empty()) {
    int slot = st->free_node_rows.back();
    st->free_node_rows.pop_back();
    st->nodes[slot] = row;
    st->node_index[row.name] = slot;
  } else {
    st->node_index[row.name] = static_cast<int>(st->nodes.size());
    st->nodes.push_back(std::move(row));
  }
  return true;
}

bool parse_pod(State* st, Reader* r, unsigned* mask) {
  PodRow pod;
  GroupRow g;
  pod.uid = r->str();
  std::string node_name = r->str();
  for (int i = 0; i < R; i++) {
    pod.req[i] = r->i32();
    g.req[i] = pod.req[i];
  }
  std::vector<int32_t> sel_flat;
  uint16_t ns = r->u16();
  for (int i = 0; i < ns; i++) {
    std::string k = r->str(), v = r->str();
    sel_flat.push_back(fold32(k + "=" + v));
  }
  std::vector<int32_t> tex, tky;
  uint8_t ntl = r->u8();
  for (int i = 0; i < ntl; i++) {
    std::string key = r->str();
    uint8_t op = r->u8();
    std::string value = r->str();
    uint8_t eff = r->u8();
    std::vector<uint8_t> effects;
    if (eff == 2) {
      effects = {0, 1};
    } else {
      effects = {eff};
    }
    if (op == 1) {  // Exists
      if (key.empty()) {
        g.tolerate_all = true;
        continue;
      }
      for (uint8_t e : effects) tky.push_back(fold32(key + '\0' + effect_name(e)));
    } else {
      for (uint8_t e : effects)
        tex.push_back(fold32(key + '\0' + value + '\0' + effect_name(e)));
    }
  }
  std::vector<int32_t> ports;
  uint8_t np = r->u8();
  for (int i = 0; i < np; i++) {
    uint16_t port = r->u16();
    uint8_t proto = r->u8();
    ports.push_back(
        fold32(std::to_string(port) + "/" + (proto == 1 ? "UDP" : "TCP")));
  }
  uint8_t flags = r->u8();
  std::string eqkey = r->str();
  if (!r->ok()) return false;
  if (eqkey.empty()) eqkey = pod.uid;

  pod.movable = flags & 1;
  pod.blocks = flags & 2;
  g.anti_self = flags & 4;

  // group row (selector terms: single-alt per nodeSelector pair; richer
  // affinity shapes arrive pre-flagged via the lossy bit on the wire — the
  // control plane computes them, mirroring _encode_pod_spec)
  const Dims& d = st->dims;
  g.sel_req.assign(d.max_sel_terms * d.max_sel_alts, 0);
  bool lossy = flags & 8;
  if (static_cast<int>(sel_flat.size()) > d.max_sel_terms) lossy = true;
  for (size_t i = 0;
       i < sel_flat.size() && i < static_cast<size_t>(d.max_sel_terms); i++) {
    g.sel_req[i * d.max_sel_alts] = sel_flat[i];
  }
  g.sel_neg.assign(d.max_neg_terms, 0);
  bool overflow = false;
  fill(g.tol_exact, d.max_tolerations, tex, &overflow);
  fill(g.tol_key, d.max_tolerations, tky, &overflow);
  fill(g.port_hash, d.max_pod_ports, ports, &overflow);
  if (overflow) lossy = true;
  g.lossy = lossy;
  g.eqkey = eqkey;
  pod.port_hash = g.port_hash;

  auto git = st->group_index.find(eqkey);
  if (git == st->group_index.end()) {
    *mask |= kSecGroups;  // fresh equivalence row enters the group export
    st->group_index[eqkey] = static_cast<int>(st->groups.size());
    st->groups.push_back(std::move(g));
    git = st->group_index.find(eqkey);
  }
  pod.group_ref = git->second;

  if (!node_name.empty()) {
    auto nit = st->node_index.find(node_name);
    if (nit == st->node_index.end()) {
      st->error = "pod " + pod.uid + ": unknown node " + node_name;
      return false;
    }
    pod.node_idx = nit->second;
  }
  // new residency decides the sections this op changes; a replaced pod's
  // OLD residency changes them too (a bind moves a pod from the pending
  // count into alloc/scheduled rows: groups AND pods+nodes are dirty)
  *mask |= pod.node_idx >= 0 ? (kSecPods | kSecNodes) : kSecGroups;

  auto pit = st->pod_index.find(pod.uid);
  if (pit != st->pod_index.end()) {
    const PodRow& old = st->pods[pit->second];
    *mask |= old.node_idx >= 0 ? (kSecPods | kSecNodes) : kSecGroups;
    st->pods[pit->second] = pod;
  } else if (!st->free_pod_rows.empty()) {
    int slot = st->free_pod_rows.back();
    st->free_pod_rows.pop_back();
    st->pods[slot] = pod;
    st->pod_index[pod.uid] = slot;
  } else {
    st->pod_index[pod.uid] = static_cast<int>(st->pods.size());
    st->pods.push_back(std::move(pod));
  }
  return true;
}

}  // namespace

extern "C" {

void* ka_state_new(int max_labels, int max_taints, int max_tolerations,
                   int max_sel_terms, int max_sel_alts, int max_neg_terms,
                   int max_pod_ports, int max_node_ports) {
  State* st = new State();
  st->dims = Dims{max_labels, max_taints,   max_tolerations, max_sel_terms,
                  max_sel_alts, max_neg_terms, max_pod_ports,   max_node_ports};
  return st;
}

void ka_state_free(void* handle) { delete static_cast<State*>(handle); }

const char* ka_last_error(void* handle) {
  return static_cast<State*>(handle)->error.c_str();
}

// Returns 0 on success; <0 on malformed input (state unchanged semantics are
// NOT transactional — callers should rebuild on error, like the reference
// falls back to a full SetClusterState).
int ka_apply_delta(void* handle, const uint8_t* buf, uint64_t len) {
  State* st = static_cast<State*>(handle);
  st->error.clear();
  Reader r(buf, len);
  if (len < 8 || r.u8() != 'K' || r.u8() != 'A' || r.u8() != 'D' ||
      r.u8() != '1') {
    st->error = "bad magic";
    return -1;
  }
  uint32_t count = r.u32();
  unsigned mask = 0;
  for (uint32_t i = 0; i < count; i++) {
    uint8_t op = r.u8();
    if (!r.ok()) {
      st->error = "truncated";
      return -2;
    }
    switch (op) {
      case 1:
        if (!parse_node(st, &r)) return -3;
        mask |= kSecNodes;
        break;
      case 2: {
        std::string name = r.str();
        auto it = st->node_index.find(name);
        if (it != st->node_index.end()) {
          st->nodes[it->second].valid = false;
          st->free_node_rows.push_back(it->second);
          st->node_index.erase(it);
          mask |= kSecNodes;
        }
        break;
      }
      case 3:
        if (!parse_pod(st, &r, &mask)) return -4;
        break;
      case 4: {
        std::string uid = r.str();
        auto it = st->pod_index.find(uid);
        if (it != st->pod_index.end()) {
          // a removed RESIDENT pod uncharges alloc/ports and drops a
          // scheduled row; a removed PENDING pod drops a group count
          mask |= st->pods[it->second].node_idx >= 0
                      ? (kSecPods | kSecNodes)
                      : kSecGroups;
          st->pods[it->second].valid = false;
          st->free_pod_rows.push_back(it->second);
          st->pod_index.erase(it);
        }
        break;
      }
      default:
        st->error = "unknown op";
        return -5;
    }
  }
  st->version++;
  if (mask & kSecNodes) st->section_versions[0]++;
  if (mask & kSecGroups) st->section_versions[1]++;
  if (mask & kSecPods) st->section_versions[2]++;
  return 0;
}

uint64_t ka_version(void* handle) { return static_cast<State*>(handle)->version; }

// Per-export-section version (0 = nodes, 1 = groups, 2 = pods) — the
// python sidecar's plane-granular export/device caches key on these
// (ISSUE 11: a single-pod delta must not re-materialize untouched planes).
uint64_t ka_section_version(void* handle, int section) {
  State* st = static_cast<State*>(handle);
  if (section < 0 || section > 2) return 0;
  return st->section_versions[section];
}

// Group row -> its equivalence key (for the python-side constraint
// side-channel to map aux pod records onto exported rows). Returns the key
// length, or -1 when out of range; truncates to cap.
int ka_group_key(void* handle, int row, char* buf, int cap) {
  State* st = static_cast<State*>(handle);
  if (row < 0 || row >= static_cast<int>(st->groups.size())) return -1;
  const std::string& k = st->groups[row].eqkey;
  int n = static_cast<int>(k.size());
  int c = n < cap ? n : cap;
  std::memcpy(buf, k.data(), c);
  return n;
}

// Node name -> row index (-1 when absent).
int ka_node_row(void* handle, const char* name) {
  State* st = static_cast<State*>(handle);
  auto it = st->node_index.find(name);
  return it == st->node_index.end() ? -1 : it->second;
}

// Zone string -> the codec's interned id (-1 when the zone is unknown; 0 is
// the reserved "no zone" id). Lets the python side encode TEMPLATES in the
// same zone-id space as the exported node tensors.
int ka_zone_id(void* handle, const char* zone) {
  State* st = static_cast<State*>(handle);
  if (zone == nullptr || *zone == '\0') return 0;
  auto it = st->zone_ids.find(zone);
  return it == st->zone_ids.end() ? -1 : it->second;
}

int ka_num_zones(void* handle) {
  return static_cast<int>(static_cast<State*>(handle)->zone_ids.size());
}
int ka_num_nodes(void* handle) {
  return static_cast<int>(static_cast<State*>(handle)->nodes.size());
}
int ka_num_pods(void* handle) {
  return static_cast<int>(static_cast<State*>(handle)->pods.size());
}
int ka_num_groups(void* handle) {
  return static_cast<int>(static_cast<State*>(handle)->groups.size());
}

// Export node tensors into caller buffers (padded to n_pad rows, zeroed by
// caller). alloc and used_ports are derived from resident pods here — the
// aggregation loop the Python encoder runs per SetClusterState.
int ka_export_nodes(void* handle, int n_pad, int32_t* cap, int32_t* alloc,
                    int32_t* label_hash, int32_t* taint_exact,
                    int32_t* taint_key, int32_t* used_ports, int32_t* zone_id,
                    int32_t* group_id, uint8_t* ready, uint8_t* schedulable,
                    uint8_t* valid) {
  State* st = static_cast<State*>(handle);
  const Dims& d = st->dims;
  int n = static_cast<int>(st->nodes.size());
  if (n > n_pad) return -1;
  std::vector<int> port_fill(n, 0);
  for (int i = 0; i < n; i++) {
    const NodeRow& row = st->nodes[i];
    if (!row.valid) continue;
    std::memcpy(cap + i * R, row.cap, sizeof(row.cap));
    std::memcpy(label_hash + i * d.max_labels, row.label_hash.data(),
                d.max_labels * 4);
    std::memcpy(taint_exact + i * d.max_taints, row.taint_exact.data(),
                d.max_taints * 4);
    std::memcpy(taint_key + i * d.max_taints, row.taint_key.data(),
                d.max_taints * 4);
    zone_id[i] = row.zone_id;
    group_id[i] = row.group_id;
    ready[i] = row.ready;
    schedulable[i] = row.schedulable;
    valid[i] = 1;
  }
  for (const PodRow& pod : st->pods) {
    if (!pod.valid || pod.node_idx < 0) continue;
    for (int rix = 0; rix < R; rix++)
      alloc[pod.node_idx * R + rix] += pod.req[rix];
    for (int32_t ph : pod.port_hash) {
      if (ph == 0) continue;
      if (port_fill[pod.node_idx] >= d.max_node_ports) return -2;  // fail fast
      used_ports[pod.node_idx * d.max_node_ports + port_fill[pod.node_idx]++] =
          ph;
    }
  }
  return n;
}

int ka_export_groups(void* handle, int g_pad, int32_t* req, int32_t* count,
                     int32_t* sel_req, int32_t* sel_neg, int32_t* tol_exact,
                     int32_t* tol_key, uint8_t* tolerate_all, int32_t* port_hash,
                     uint8_t* anti_self, uint8_t* valid, uint8_t* lossy) {
  State* st = static_cast<State*>(handle);
  const Dims& d = st->dims;
  int g = static_cast<int>(st->groups.size());
  if (g > g_pad) return -1;
  for (int i = 0; i < g; i++) {
    const GroupRow& row = st->groups[i];
    std::memcpy(req + i * R, row.req, sizeof(row.req));
    std::memcpy(sel_req + i * d.max_sel_terms * d.max_sel_alts,
                row.sel_req.data(), d.max_sel_terms * d.max_sel_alts * 4);
    std::memcpy(sel_neg + i * d.max_neg_terms, row.sel_neg.data(),
                d.max_neg_terms * 4);
    std::memcpy(tol_exact + i * d.max_tolerations, row.tol_exact.data(),
                d.max_tolerations * 4);
    std::memcpy(tol_key + i * d.max_tolerations, row.tol_key.data(),
                d.max_tolerations * 4);
    tolerate_all[i] = row.tolerate_all;
    std::memcpy(port_hash + i * d.max_pod_ports, row.port_hash.data(),
                d.max_pod_ports * 4);
    anti_self[i] = row.anti_self;
    valid[i] = 1;
    lossy[i] = row.lossy;
  }
  // pending counts
  for (const PodRow& pod : st->pods) {
    if (pod.valid && pod.node_idx < 0) count[pod.group_ref]++;
  }
  return g;
}

int ka_export_pods(void* handle, int p_pad, int32_t* req, int32_t* node_idx,
                   int32_t* group_ref, uint8_t* movable, uint8_t* blocks,
                   uint8_t* valid) {
  State* st = static_cast<State*>(handle);
  int scheduled = 0;
  for (const PodRow& pod : st->pods) {
    if (!pod.valid || pod.node_idx < 0) continue;
    if (scheduled >= p_pad) return -1;
    std::memcpy(req + scheduled * R, pod.req, sizeof(pod.req));
    node_idx[scheduled] = pod.node_idx;
    group_ref[scheduled] = pod.group_ref;
    movable[scheduled] = pod.movable;
    blocks[scheduled] = pod.blocks;
    valid[scheduled] = 1;
    scheduled++;
  }
  return scheduled;
}

// Batch hashing for the Python encoder's hot path: n strings packed in `data`
// with offsets[n+1]; writes fold32 hashes to out.
void ka_fold32_batch(const char* data, const int64_t* offsets, int n,
                     int32_t* out) {
  for (int i = 0; i < n; i++) {
    uint64_t h = fnv1a64(data + offsets[i],
                         static_cast<size_t>(offsets[i + 1] - offsets[i]));
    uint32_t h32 = static_cast<uint32_t>(h ^ (h >> 32));
    if (h32 == 0) h32 = 1;
    out[i] = static_cast<int32_t>(h32);
  }
}

void ka_fnv64_batch(const char* data, const int64_t* offsets, int n,
                    int64_t* out) {
  for (int i = 0; i < n; i++) {
    out[i] = static_cast<int64_t>(fnv1a64(
        data + offsets[i], static_cast<size_t>(offsets[i + 1] - offsets[i])));
  }
}

}  // extern "C"
