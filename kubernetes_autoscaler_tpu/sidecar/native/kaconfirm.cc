// Native sequential confirmation pass for scale-down.
//
// Reference counterpart: the commit-on-success ordering of
// simulator/cluster.go:174-188 driven by core/scaledown/planner NodesToDelete —
// the one latency-critical HOST-side loop in the framework (SURVEY.md §0:
// "the single latency-critical host-side component ... is C++ where Go/Python
// would be too slow"). Python/numpy does this pass in seconds at 5k nodes /
// 50k pods; this kernel does the identical algorithm in milliseconds.
//
// Semantics (mirrors core/scaledown/planner.py attempt(), fast-path subset —
// no exact-oracle groups, no one-per-node groups, no atomic groups; the
// Python loop remains the fallback for those. PDB budgets ARE handled:
// up to 64 PodDisruptionBudgets ride as a per-slot membership bitmask +
// a remaining-budget vector, gating candidates over their ORIGINAL
// resident slots exactly as the Python pass's can_remove_pods +
// accumulated reservation do — round-3 review Weak #3/#6, the all-PDB
// cluster previously fell back to the seconds-long Python pass):
//   * candidates processed in the given order (oldest unneeded clock first)
//   * per candidate: its victim slots (original residents + pods RECEIVED
//     from earlier accepted drains) re-place group-by-group, first feasible
//     node in index order, against live free capacity
//   * all-or-nothing: failure reverts the candidate's placements
//   * group min-size room, empty/drain/total budgets, and min-quota gates
//     applied exactly as the Python pass does
//
// Build: part of libkacodec.so (see ../Makefile).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Move {
  int slot;
  int node;
  int group;
};

}  // namespace

extern "C" {

// Returns the number of accepted candidates, or -1 on bad arguments.
// reason_out: 0 accepted, 1 no-place, 2 group-room, 3 quota, 4 budget-skip,
//             5 pdb-budget.
int ka_confirm(
    int n, int r, int g,
    int64_t* free_io,            // [n*r] free capacity, mutated in place
    const uint8_t* feas,         // [g*n] predicate plane (pre-capacity)
    const uint8_t* node_valid,   // [n] valid & ready & schedulable
    const int32_t* greq,         // [g*r] per-group request vectors
    int n_cand,
    const int32_t* cand_node,    // [n_cand]
    const int32_t* slot_ids,     // [total_slots] scheduled-pod slot ids
    const int32_t* slot_group,   // [total_slots] group per slot
    const int32_t* slot_off,     // [n_cand+1] per-candidate ranges
    const int32_t* cand_group_idx,  // [n_cand] index into group_room
    int n_room,
    int32_t* group_room,         // [n_room] remaining deletions per node group
    int64_t* quota_totals,       // [r] running cluster totals (or null)
    const int64_t* quota_min,    // [r] min limits (or null)
    const int64_t* node_cap,     // [n*r] per-node capacity (for quota deduct)
    int empty_budget, int drain_budget, int total_budget,
    int n_pdbs,                  // 0..64 (0 = no PDB gating)
    const uint64_t* slot_pdb,    // [max_slot_id+1] membership bitmask, or null
    int64_t* pdb_remaining,      // [n_pdbs] budgets, deducted on accept
    uint8_t* accept_out,         // [n_cand]
    uint8_t* reason_out,         // [n_cand]
    int32_t* dest_out)           // slot id -> destination (indexed by slot id;
                                 // caller sizes it max_slot_id+1, fills -1)
{
  if (n <= 0 || r <= 0 || g <= 0 || n_cand < 0) return -1;
  if (n_pdbs < 0 || n_pdbs > 64) return -1;
  if (n_pdbs > 0 && (slot_pdb == nullptr || pdb_remaining == nullptr))
    return -1;
  std::vector<uint8_t> deleted(n, 0);
  // pods moved ONTO a node (re-placed again if that node later drains)
  std::vector<std::vector<Move>> received(n);
  // first-fit frontier hint per group: nodes before the hint are known full
  // for that group's request (capacity only shrinks; reverts rewind the hint)
  std::vector<int> hint(g, 0);
  int accepted = 0;

  for (int c = 0; c < n_cand; ++c) {
    accept_out[c] = 0;
    reason_out[c] = 4;
    if (accepted >= total_budget) continue;
    const int cand = cand_node[c];
    if (cand < 0 || cand >= n) continue;

    const int gi_room = cand_group_idx[c];
    if (gi_room < 0 || gi_room >= n_room || group_room[gi_room] <= 0) {
      reason_out[c] = 2;
      continue;
    }
    if (quota_totals && quota_min) {
      bool quota_ok = true;
      for (int k = 0; k < r; ++k) {
        if (quota_totals[k] - node_cap[(int64_t)cand * r + k] < quota_min[k]) {
          quota_ok = false;
          break;
        }
      }
      if (!quota_ok) {
        reason_out[c] = 3;
        continue;
      }
    }

    // victim set: original slots + received pods
    std::vector<Move> victims;
    for (int s = slot_off[c]; s < slot_off[c + 1]; ++s)
      victims.push_back({slot_ids[s], -1, slot_group[s]});
    const size_t n_orig = victims.size();
    for (const Move& m : received[cand]) victims.push_back(m);
    const bool is_empty = victims.empty();
    if (is_empty) {
      if (empty_budget <= 0) continue;
    } else {
      if (drain_budget <= 0) continue;
    }

    // PDB gate over the ORIGINAL resident slots only (received pods were
    // accounted when their own node was confirmed — planner.py comment)
    int64_t pdb_need[64];
    if (n_pdbs > 0) {
      for (int p = 0; p < n_pdbs; ++p) pdb_need[p] = 0;
      for (int s = slot_off[c]; s < slot_off[c + 1]; ++s) {
        uint64_t mask = slot_pdb[slot_ids[s]];
        while (mask) {
          int p = __builtin_ctzll(mask);
          mask &= mask - 1;
          ++pdb_need[p];
        }
      }
      bool pdb_ok = true;
      for (int p = 0; p < n_pdbs; ++p) {
        if (pdb_need[p] > pdb_remaining[p]) {
          pdb_ok = false;
          break;
        }
      }
      if (!pdb_ok) {
        reason_out[c] = 5;
        continue;
      }
    }

    // place group-by-group (stable-sorted so equal groups are consecutive),
    // first-fit in node index order
    std::stable_sort(victims.begin(), victims.end(),
                     [](const Move& a, const Move& b) { return a.group < b.group; });
    std::vector<Move> placed;
    placed.reserve(victims.size());
    bool ok = true;
    size_t v = 0;
    while (v < victims.size() && ok) {
      const int gg = victims[v].group;
      size_t v_end = v;
      while (v_end < victims.size() && victims[v_end].group == gg) ++v_end;
      int want = (int)(v_end - v);
      const int32_t* req = greq + (int64_t)gg * r;
      const uint8_t* fg = feas + (int64_t)gg * n;
      int node = hint[gg];
      bool advancing_frontier = true;
      while (want > 0 && node < n) {
        if (node == cand) {
          // the candidate itself is only transiently excluded — never
          // advance the persistent frontier past it
          advancing_frontier = false;
          ++node;
          continue;
        }
        if (deleted[node] || !node_valid[node] || !fg[node]) {
          if (advancing_frontier && node == hint[gg]) ++hint[gg];
          ++node;
          continue;
        }
        int64_t* fr = free_io + (int64_t)node * r;
        int64_t fits = INT64_MAX;
        for (int k = 0; k < r; ++k) {
          if (req[k] > 0) {
            int64_t f = fr[k] / req[k];
            if (f < fits) fits = f;
          }
        }
        if (fits <= 0) {
          if (advancing_frontier && node == hint[gg]) ++hint[gg];
          ++node;
          continue;
        }
        advancing_frontier = false;
        int take = (int)(fits < want ? fits : want);
        for (int t = 0; t < take; ++t) {
          placed.push_back({victims[v + (v_end - v - want) + t].slot, node, gg});
        }
        for (int k = 0; k < r; ++k) fr[k] -= (int64_t)req[k] * take;
        want -= take;
        ++node;
      }
      if (want > 0) ok = false;
      v = v_end;
    }

    if (!ok) {
      int min_reverted = n;
      for (const Move& m : placed) {
        const int32_t* req = greq + (int64_t)m.group * r;
        int64_t* fr = free_io + (int64_t)m.node * r;
        for (int k = 0; k < r; ++k) fr[k] += req[k];
        if (m.node < min_reverted) min_reverted = m.node;
      }
      // Restoring capacity can re-open a node that ANOTHER group's frontier
      // already skipped as full while this candidate was being placed, so
      // every group's hint must rewind to the earliest reverted destination —
      // not just the placing group's. (Hints are pure optimization: rewinding
      // too far only costs a rescan of permanently-bad nodes.)
      if (min_reverted < n)
        for (int gg2 = 0; gg2 < g; ++gg2)
          if (min_reverted < hint[gg2]) hint[gg2] = min_reverted;
      reason_out[c] = 1;
      continue;
    }

    // accept
    accept_out[c] = 1;
    reason_out[c] = 0;
    ++accepted;
    if (n_pdbs > 0)
      for (int p = 0; p < n_pdbs; ++p) pdb_remaining[p] -= pdb_need[p];
    deleted[cand] = 1;
    group_room[gi_room] -= 1;
    if (is_empty) --empty_budget; else --drain_budget;
    if (quota_totals) {
      for (int k = 0; k < r; ++k)
        quota_totals[k] -= node_cap[(int64_t)cand * r + k];
    }
    received[cand].clear();
    for (const Move& m : placed) {
      dest_out[m.slot] = m.node;
      received[m.node].push_back(m);
    }
    (void)n_orig;
  }
  return accepted;
}

}  // extern "C"
