// Native sequential confirmation pass for scale-down.
//
// Reference counterpart: the commit-on-success ordering of
// simulator/cluster.go:174-188 driven by core/scaledown/planner NodesToDelete —
// the one latency-critical HOST-side loop in the framework (SURVEY.md §0:
// "the single latency-critical host-side component ... is C++ where Go/Python
// would be too slow"). Python/numpy does this pass in seconds at 5k nodes /
// 50k pods; this kernel does the identical algorithm in milliseconds.
//
// Semantics (mirrors core/scaledown/planner.py attempt()):
//   * candidates processed in the given order (oldest unneeded clock first)
//   * per candidate: its victim slots (original residents + pods RECEIVED
//     from earlier accepted drains) re-place group-by-group, first feasible
//     node in index order, against live free capacity
//   * all-or-nothing: failure reverts the candidate's placements
//   * group min-size room, empty/drain/total budgets, and min-quota gates
//     applied exactly as the Python pass does
//   * ANY number of PodDisruptionBudgets ride as a per-slot MULTI-WORD
//     membership bitmask ([pdb_words] u64 per slot; round-4 review Weak #3
//     lifted the old single-word 64-budget cap)
//   * CONSTRAINED TIER (round-4 verdict item 4 — the all-constrained confirm
//     took ~37 s host-side at 5k nodes / 50k pods): zone- and host-scope
//     topology spread and host/zone-scope required anti-affinity evaluate natively
//     against incrementally-maintained count planes, mirroring the Python
//     pass's ConfirmOracle verdicts (utils/oracle.py spread_ok /
//     anti_affinity_ok): domain counts over ELIGIBLE nodes, global minimum
//     over eligible domains, self-match term, per-pod re-evaluation as
//     counts shift; host-kind spread maintains its global minimum O(1)
//     through a per-group count histogram over eligible nodes. Groups
//     needing more (pod affinity, lossy
//     encodings, min_domains/policies, host ports) stay on the Python pass —
//     the planner's gate routes them there.
//
// Build: part of libkacodec.so (see ../Makefile).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Move {
  int slot;
  int node;
  int group;
};

// Constrained-tier state: per-group count planes + zone aggregates.
// Aggregation convention follows the Python oracle: spread counts aggregate
// over ELIGIBLE nodes only and zones are domains only while they still hold
// at least one eligible node; anti counts aggregate over all nodes.
struct ConState {
  int n = 0, g = 0, nz = 0;
  const int32_t* zone_id = nullptr;       // [n]; 0 = no zone
  const uint8_t* spread_kind = nullptr;   // [g]; 0 none, 1 host, 2 zone
  const int32_t* max_skew = nullptr;      // [g]
  const uint8_t* spread_self = nullptr;   // [g]
  const uint8_t* has_anti_host = nullptr; // [g]
  const uint8_t* has_anti_zone = nullptr; // [g]
  const uint8_t* aff_kind = nullptr;      // [g]; 0 none, 1 host, 2 zone
  const uint8_t* aff_self = nullptr;      // [g] pod matches its own term
  const uint8_t* one_per_node = nullptr;  // [g] limit_g: anti-self | ports
  // python's exact path ORACLE-MOVES only need_exact groups; pods of
  // limit-only (pure port) groups leave the count planes stale there —
  // mirror that staleness or plans diverge
  const uint8_t* oracle_moved = nullptr;  // [g] = need_exact
  const uint8_t* elig = nullptr;          // [g*n] spread domain eligibility
  int32_t* cnt_node = nullptr;            // [g*n] spread matches per node
  int32_t* anti_host_node = nullptr;      // [g*n]
  int32_t* anti_zone_node = nullptr;      // [g*n]
  int32_t* aff_node = nullptr;            // [g*n]
  const uint8_t* m_spread = nullptr;      // [g*g]: pod of b counts for a
  const uint8_t* m_anti_h = nullptr;      // [g*g]
  const uint8_t* m_anti_z = nullptr;      // [g*g]
  const uint8_t* m_aff = nullptr;         // [g*g]
  const uint8_t* con_path = nullptr;      // [g] group places via this tier
  std::vector<int64_t> cnt_zone, anti_zone, elig_zone;  // [g*nz]
  // one-per-node marks, mirroring the Python pass's moved_marks EXACTLY:
  // a destination a limit_g group placed on stays excluded for that group
  // for the rest of the pass (STICKY — python never clears marks, even
  // when the pod later cascades away); local marks vanish on candidate
  // revert, committed marks persist
  std::vector<uint8_t> marks_committed, marks_local;  // [g*n]
  std::vector<int64_t> aff_zone;          // [g*nz]
  std::vector<int64_t> aff_total;         // [g] matches anywhere alive
  std::vector<int> con_groups;            // groups with any constraint rows
  // host-kind spread (kind 1): every ELIGIBLE node is a domain; the global
  // minimum is maintained O(1) via a per-group count histogram over
  // eligible nodes (counts clamp at kHistMax; a min that large means the
  // skew check can never bind for realistic max_skew values)
  static constexpr int kHistMax = 1023;
  // packed: one (kHistMax+1)-bucket row PER HOST-SPREAD GROUP only (zero
  // allocation when no group has kind 1)
  std::vector<int64_t> hist;
  std::vector<int> hist_row;              // [g] packed row index or -1
  std::vector<int> hist_min;              // [g] current minimum count
  std::vector<int64_t> elig_alive;        // [g] eligible nodes still alive

  static int clampc(int64_t c) {
    return c < 0 ? 0 : (c > kHistMax ? kHistMax : (int)c);
  }

  void hist_move(int a, int from, int to) {
    int64_t* h = hist.data() + (size_t)hist_row[a] * (kHistMax + 1);
    h[clampc(from)] -= 1;
    h[clampc(to)] += 1;
    if (to < hist_min[a]) {
      hist_min[a] = clampc(to);
    } else if (from == hist_min[a] && h[clampc(from)] == 0) {
      int m = hist_min[a];
      while (m <= kHistMax && h[m] == 0) ++m;
      hist_min[a] = m > kHistMax ? 0 : m;  // no eligible nodes left -> min 0
    }
  }

  bool active() const { return zone_id != nullptr; }

  void init() {
    cnt_zone.assign((size_t)g * nz, 0);
    anti_zone.assign((size_t)g * nz, 0);
    elig_zone.assign((size_t)g * nz, 0);
    aff_zone.assign((size_t)g * nz, 0);
    aff_total.assign(g, 0);
    marks_committed.assign((size_t)g * n, 0);
    marks_local.assign((size_t)g * n, 0);
    hist_row.assign(g, -1);
    hist_min.assign(g, 0);
    elig_alive.assign(g, 0);
    int n_host = 0;
    for (int a = 0; a < g; ++a)
      if (spread_kind[a] == 1) hist_row[a] = n_host++;
    hist.assign((size_t)n_host * (kHistMax + 1), 0);
    for (int a = 0; a < g; ++a) {
      // marks work without con_groups membership: pure one-per-node
      // (port-only) groups stay OUT so apply()/remove_node() never iterate
      // their all-zero count-plane rows
      const bool any = spread_kind[a] != 0 || has_anti_host[a] ||
                       has_anti_zone[a] || aff_kind[a] != 0;
      if (any) con_groups.push_back(a);
      const bool host_spread = spread_kind[a] == 1;
      int64_t* h = host_spread
          ? hist.data() + (size_t)hist_row[a] * (kHistMax + 1) : nullptr;
      int mn = kHistMax + 1;
      for (int i = 0; i < n; ++i) {
        const bool el = elig[(size_t)a * n + i];
        if (host_spread && el) {
          const int c = clampc(cnt_node[(size_t)a * n + i]);
          h[c] += 1;
          elig_alive[a] += 1;
          if (c < mn) mn = c;
        }
        aff_total[a] += aff_node[(size_t)a * n + i];
        const int z = zone_id[i];
        if (z <= 0 || z >= nz) continue;
        if (el) {
          elig_zone[(size_t)a * nz + z] += 1;
          cnt_zone[(size_t)a * nz + z] += cnt_node[(size_t)a * n + i];
        }
        anti_zone[(size_t)a * nz + z] += anti_zone_node[(size_t)a * n + i];
        aff_zone[(size_t)a * nz + z] += aff_node[(size_t)a * n + i];
      }
      hist_min[a] = mn > kHistMax ? 0 : mn;
    }
  }

  // one pod of group b lands on (+1) / leaves (-1) node i, `count` at a time
  void apply(int b, int i, int sign, int count = 1) {
    const int z = zone_id[i];
    for (int a : con_groups) {
      const size_t an = (size_t)a * n + i;
      if (m_spread[(size_t)a * g + b]) {
        const int64_t before = cnt_node[an];
        cnt_node[an] += sign * count;
        if (z > 0 && z < nz && elig[an])
          cnt_zone[(size_t)a * nz + z] += sign * count;
        if (spread_kind[a] == 1 && elig[an])
          hist_move(a, (int)before, (int)cnt_node[an]);
      }
      if (m_anti_h[(size_t)a * g + b]) anti_host_node[an] += sign * count;
      if (m_anti_z[(size_t)a * g + b]) {
        anti_zone_node[an] += sign * count;
        if (z > 0 && z < nz) anti_zone[(size_t)a * nz + z] += sign * count;
      }
      if (m_aff[(size_t)a * g + b]) {
        aff_node[an] += sign * count;
        aff_total[a] += sign * count;
        if (z > 0 && z < nz) aff_zone[(size_t)a * nz + z] += sign * count;
      }
    }
  }

  // can one pod of group a land on node i right now?
  bool ok(int a, int i) const {
    const int z = zone_id[i];
    if (one_per_node[a]) {
      const size_t an = (size_t)a * n + i;
      if (marks_committed[an] || marks_local[an]) return false;
    }
    if (has_anti_host[a] && anti_host_node[(size_t)a * n + i] > 0)
      return false;
    if (has_anti_zone[a] && z > 0 && z < nz &&
        anti_zone[(size_t)a * nz + z] > 0)
      return false;
    if (aff_kind[a] != 0) {
      int64_t here = 0;
      if (aff_kind[a] == 1) {
        here = aff_node[(size_t)a * n + i];
      } else if (z > 0 && z < nz) {
        here = aff_zone[(size_t)a * nz + z];
      } else {
        return false;  // zone term, node without the key
      }
      if (here <= 0 && !(aff_total[a] == 0 && aff_self[a])) return false;
    }
    if (spread_kind[a] == 1) {
      // every eligible alive node is a domain; min over them is hist_min
      const int64_t minc = elig_alive[a] > 0 ? hist_min[a] : 0;
      const int64_t here =
          elig[(size_t)a * n + i] ? cnt_node[(size_t)a * n + i] : 0;
      if (here + (spread_self[a] ? 1 : 0) - minc > max_skew[a]) return false;
    }
    if (spread_kind[a] == 2) {
      if (z <= 0 || z >= nz) return false;  // no key -> cannot satisfy
      int64_t minc = INT64_MAX;
      bool any = false;
      for (int zz = 1; zz < nz; ++zz) {
        if (elig_zone[(size_t)a * nz + zz] > 0) {
          any = true;
          const int64_t cc = cnt_zone[(size_t)a * nz + zz];
          if (cc < minc) minc = cc;
        }
      }
      if (!any) minc = 0;
      const int64_t here =
          elig_zone[(size_t)a * nz + z] > 0 ? cnt_zone[(size_t)a * nz + z] : 0;
      if (here + (spread_self[a] ? 1 : 0) - minc > max_skew[a]) return false;
    }
    return true;
  }

  // candidate node removed from the world: residual (non-moved) pods vanish
  // with it and it stops being an eligible domain member (the Python pass's
  // oracle remove_node)
  void remove_node(int i) {
    const int z = zone_id[i];
    for (int a : con_groups) {
      const size_t an = (size_t)a * n + i;
      if (spread_kind[a] == 1 && elig[an]) {
        // the node stops being a domain: drop its histogram bucket and
        // recompute the min if it owned it
        int64_t* h = hist.data() + (size_t)hist_row[a] * (kHistMax + 1);
        const int c = clampc(cnt_node[an]);
        h[c] -= 1;
        elig_alive[a] -= 1;
        if (c == hist_min[a] && h[c] == 0) {
          int m = hist_min[a];
          while (m <= kHistMax && h[m] == 0) ++m;
          hist_min[a] = m > kHistMax ? 0 : m;
        }
      }
      aff_total[a] -= aff_node[an];
      if (z > 0 && z < nz) {
        if (elig[an]) {
          cnt_zone[(size_t)a * nz + z] -= cnt_node[an];
          elig_zone[(size_t)a * nz + z] -= 1;
        }
        anti_zone[(size_t)a * nz + z] -= anti_zone_node[an];
        aff_zone[(size_t)a * nz + z] -= aff_node[an];
      }
      cnt_node[an] = 0;
      anti_zone_node[an] = 0;
      anti_host_node[an] = 0;
      aff_node[an] = 0;
    }
  }
};

}  // namespace

extern "C" {

// Returns the number of accepted candidates, or -1 on bad arguments.
// reason_out: 0 accepted, 1 no-place, 2 group-room, 3 quota, 4 budget-skip,
//             5 pdb-budget.
// The con_* block is the constrained tier; pass con_zone_id = null to
// disable it (plain capacity-first-fit semantics).
int ka_confirm_c(
    int n, int r, int g,
    int64_t* free_io,            // [n*r] free capacity, mutated in place
    const uint8_t* feas,         // [g*n] predicate plane (pre-capacity)
    const uint8_t* node_valid,   // [n] valid & ready & schedulable
    const int32_t* greq,         // [g*r] per-group request vectors
    int n_cand,
    const int32_t* cand_node,    // [n_cand]
    const int32_t* slot_ids,     // [total_slots] scheduled-pod slot ids
    const int32_t* slot_group,   // [total_slots] group per slot
    const int32_t* slot_off,     // [n_cand+1] per-candidate ranges
    const int32_t* cand_group_idx,  // [n_cand] index into group_room
    int n_room,
    int32_t* group_room,         // [n_room] remaining deletions per node group
    int64_t* quota_totals,       // [r] running cluster totals (or null)
    const int64_t* quota_min,    // [r] min limits (or null)
    const int64_t* node_cap,     // [n*r] per-node capacity (for quota deduct)
    int empty_budget, int drain_budget, int total_budget,
    int n_pdbs,                  // >= 0 (0 = no PDB gating)
    int pdb_words,               // words per slot = ceil(n_pdbs / 64)
    const uint64_t* slot_pdb,    // [(max_slot_id+1) * pdb_words] bitmask rows
    int64_t* pdb_remaining,      // [n_pdbs] budgets, deducted on accept
    // ---- constrained tier (all null/0 to disable) ----
    int n_zones,
    const int32_t* con_zone_id,
    const uint8_t* con_spread_kind,
    const int32_t* con_max_skew,
    const uint8_t* con_spread_self,
    const uint8_t* con_has_anti_host,
    const uint8_t* con_has_anti_zone,
    const uint8_t* con_aff_kind,
    const uint8_t* con_aff_self,
    const uint8_t* con_one_per_node,
    const uint8_t* con_oracle_moved,
    const uint8_t* con_elig,
    int32_t* con_cnt_node,
    int32_t* con_anti_host_node,
    int32_t* con_anti_zone_node,
    int32_t* con_aff_node,
    const uint8_t* con_m_spread,
    const uint8_t* con_m_anti_h,
    const uint8_t* con_m_anti_z,
    const uint8_t* con_m_aff,
    const uint8_t* con_path_flag,  // [g] group routes through the tier
    // ---- outputs ----
    uint8_t* accept_out,         // [n_cand]
    uint8_t* reason_out,         // [n_cand]
    int32_t* dest_out)           // slot id -> destination (indexed by slot id;
                                 // caller sizes it max_slot_id+1, fills -1)
{
  if (n <= 0 || r <= 0 || g <= 0 || n_cand < 0) return -1;
  if (n_pdbs < 0) return -1;
  if (n_pdbs > 0 && (slot_pdb == nullptr || pdb_remaining == nullptr ||
                     pdb_words != (n_pdbs + 63) / 64))
    return -1;
  ConState con;
  if (con_zone_id != nullptr) {
    if (n_zones <= 0 || con_spread_kind == nullptr ||
        con_max_skew == nullptr || con_spread_self == nullptr ||
        con_has_anti_host == nullptr || con_has_anti_zone == nullptr ||
        con_aff_kind == nullptr || con_aff_self == nullptr ||
        con_one_per_node == nullptr || con_oracle_moved == nullptr ||
        con_elig == nullptr || con_cnt_node == nullptr ||
        con_anti_host_node == nullptr || con_anti_zone_node == nullptr ||
        con_aff_node == nullptr || con_m_spread == nullptr ||
        con_m_anti_h == nullptr || con_m_anti_z == nullptr ||
        con_m_aff == nullptr || con_path_flag == nullptr)
      return -1;
    con.n = n;
    con.g = g;
    con.nz = n_zones;
    con.zone_id = con_zone_id;
    con.spread_kind = con_spread_kind;
    con.max_skew = con_max_skew;
    con.spread_self = con_spread_self;
    con.has_anti_host = con_has_anti_host;
    con.has_anti_zone = con_has_anti_zone;
    con.aff_kind = con_aff_kind;
    con.aff_self = con_aff_self;
    con.one_per_node = con_one_per_node;
    con.oracle_moved = con_oracle_moved;
    con.elig = con_elig;
    con.cnt_node = con_cnt_node;
    con.anti_host_node = con_anti_host_node;
    con.anti_zone_node = con_anti_zone_node;
    con.aff_node = con_aff_node;
    con.m_spread = con_m_spread;
    con.m_anti_h = con_m_anti_h;
    con.m_anti_z = con_m_anti_z;
    con.m_aff = con_m_aff;
    con.con_path = con_path_flag;
    con.init();
  }
  // KA_CONFIRM_TRACE=1: per-placement records on stderr, for diffing the
  // native pass against the Python pass when chasing plan-equality bugs
  static const bool trace = std::getenv("KA_CONFIRM_TRACE") != nullptr;
  std::vector<uint8_t> deleted(n, 0);
  // pods moved ONTO a node (re-placed again if that node later drains)
  std::vector<std::vector<Move>> received(n);
  // first-fit frontier hint per group: nodes before the hint are known full
  // for that group's request (capacity only shrinks; reverts rewind the hint)
  std::vector<int> hint(g, 0);
  // per-candidate scratch, hoisted out of the hot loop (no per-candidate
  // heap traffic)
  std::vector<int64_t> pdb_need(n_pdbs > 0 ? n_pdbs : 0);
  int accepted = 0;

  for (int c = 0; c < n_cand; ++c) {
    accept_out[c] = 0;
    reason_out[c] = 4;
    if (accepted >= total_budget) continue;
    const int cand = cand_node[c];
    if (cand < 0 || cand >= n) continue;

    const int gi_room = cand_group_idx[c];
    if (gi_room < 0 || gi_room >= n_room || group_room[gi_room] <= 0) {
      reason_out[c] = 2;
      continue;
    }
    if (quota_totals && quota_min) {
      bool quota_ok = true;
      for (int k = 0; k < r; ++k) {
        if (quota_totals[k] - node_cap[(int64_t)cand * r + k] < quota_min[k]) {
          quota_ok = false;
          break;
        }
      }
      if (!quota_ok) {
        reason_out[c] = 3;
        continue;
      }
    }

    // victim set: original slots + received pods
    std::vector<Move> victims;
    for (int s = slot_off[c]; s < slot_off[c + 1]; ++s)
      victims.push_back({slot_ids[s], -1, slot_group[s]});
    for (const Move& m : received[cand]) victims.push_back(m);
    const bool is_empty = victims.empty();
    if (is_empty) {
      if (empty_budget <= 0) continue;
    } else {
      if (drain_budget <= 0) continue;
    }

    // PDB gate over the ORIGINAL resident slots only (received pods were
    // accounted when their own node was confirmed — planner.py comment)
    if (n_pdbs > 0) {
      std::fill(pdb_need.begin(), pdb_need.end(), 0);
      for (int s = slot_off[c]; s < slot_off[c + 1]; ++s) {
        const uint64_t* row = slot_pdb + (int64_t)slot_ids[s] * pdb_words;
        for (int w = 0; w < pdb_words; ++w) {
          uint64_t mask = row[w];
          while (mask) {
            int p = (w << 6) + __builtin_ctzll(mask);
            mask &= mask - 1;
            ++pdb_need[p];
          }
        }
      }
      bool pdb_ok = true;
      for (int p = 0; p < n_pdbs; ++p) {
        if (pdb_need[p] > pdb_remaining[p]) {
          pdb_ok = false;
          break;
        }
      }
      if (!pdb_ok) {
        reason_out[c] = 5;
        continue;
      }
    }

    // place group-by-group (stable-sorted so equal groups are consecutive),
    // first-fit in node index order
    std::stable_sort(victims.begin(), victims.end(),
                     [](const Move& a, const Move& b) { return a.group < b.group; });
    std::vector<Move> placed;
    placed.reserve(victims.size());
    // constrained-tier pods whose contribution left `cand` but found no
    // destination yet (revert must re-add them)
    int out_unplaced_group = -1;
    bool ok = true;
    size_t v = 0;
    while (v < victims.size() && ok) {
      const int gg = victims[v].group;
      size_t v_end = v;
      while (v_end < victims.size() && victims[v_end].group == gg) ++v_end;
      int want = (int)(v_end - v);
      const int32_t* req = greq + (int64_t)gg * r;
      const uint8_t* fg = feas + (int64_t)gg * n;
      const bool con_gg = con.active() && con.con_path[gg];

      if (con_gg) {
        // per-pod path, mirroring the Python exact path: move the pod's
        // contribution off the candidate, then scan destinations re-checking
        // the constraint as counts shift (pure-limit groups skip the count
        // planes exactly as python skips their oracle moves)
        const bool track = con.oracle_moved[gg] != 0;
        for (int t = 0; t < want && ok; ++t) {
          if (track) con.apply(gg, cand, -1);
          int d_found = -1;
          for (int node = 0; node < n; ++node) {
            if (node == cand || deleted[node] || !node_valid[node] ||
                !fg[node])
              continue;
            int64_t* fr = free_io + (int64_t)node * r;
            bool fits = true;
            for (int k = 0; k < r; ++k) {
              if (req[k] > 0 && fr[k] < req[k]) {
                fits = false;
                break;
              }
            }
            if (!fits) continue;
            if (!con.ok(gg, node)) continue;
            d_found = node;
            break;
          }
          if (d_found < 0) {
            ok = false;
            out_unplaced_group = gg;
            break;
          }
          int64_t* fr = free_io + (int64_t)d_found * r;
          for (int k = 0; k < r; ++k) fr[k] -= req[k];
          if (track) con.apply(gg, d_found, +1);
          if (con.one_per_node[gg])
            con.marks_local[(size_t)gg * n + d_found] = 1;
          if (trace)
            fprintf(stderr, "[kaconfirm] cand=%d con slot=%d g=%d -> %d\n",
                    cand, victims[v + t].slot, gg, d_found);
          placed.push_back({victims[v + t].slot, d_found, gg});
        }
        v = v_end;
        continue;
      }

      int node = hint[gg];
      bool advancing_frontier = true;
      while (want > 0 && node < n) {
        if (node == cand) {
          // the candidate itself is only transiently excluded — never
          // advance the persistent frontier past it
          advancing_frontier = false;
          ++node;
          continue;
        }
        if (deleted[node] || !node_valid[node] || !fg[node]) {
          if (advancing_frontier && node == hint[gg]) ++hint[gg];
          ++node;
          continue;
        }
        int64_t* fr = free_io + (int64_t)node * r;
        int64_t fits = INT64_MAX;
        for (int k = 0; k < r; ++k) {
          if (req[k] > 0) {
            int64_t f = fr[k] / req[k];
            if (f < fits) fits = f;
          }
        }
        if (fits <= 0) {
          if (advancing_frontier && node == hint[gg]) ++hint[gg];
          ++node;
          continue;
        }
        advancing_frontier = false;
        int take = (int)(fits < want ? fits : want);
        for (int t = 0; t < take; ++t) {
          if (trace)
            fprintf(stderr, "[kaconfirm] cand=%d blk slot=%d g=%d -> %d\n",
                    cand, victims[v + (v_end - v - want) + t].slot, gg, node);
          placed.push_back({victims[v + (v_end - v - want) + t].slot, node, gg});
        }
        for (int k = 0; k < r; ++k) fr[k] -= (int64_t)req[k] * take;
        want -= take;
        ++node;
      }
      if (want > 0) ok = false;
      v = v_end;
    }

    if (!ok) {
      if (trace) fprintf(stderr, "[kaconfirm] cand=%d REVERT\n", cand);
      int min_reverted = n;
      for (const Move& m : placed) {
        const int32_t* req = greq + (int64_t)m.group * r;
        int64_t* fr = free_io + (int64_t)m.node * r;
        for (int k = 0; k < r; ++k) fr[k] += req[k];
        if (m.node < min_reverted) min_reverted = m.node;
        if (con.active() && con.con_path[m.group]) {
          if (con.oracle_moved[m.group]) {
            con.apply(m.group, m.node, -1);
            con.apply(m.group, cand, +1);
          }
          con.marks_local[(size_t)m.group * n + m.node] = 0;
        }
      }
      if (out_unplaced_group >= 0 && con.oracle_moved[out_unplaced_group])
        con.apply(out_unplaced_group, cand, +1);
      // Restoring capacity can re-open a node that ANOTHER group's frontier
      // already skipped as full while this candidate was being placed, so
      // every group's hint must rewind to the earliest reverted destination —
      // not just the placing group's. (Hints are pure optimization: rewinding
      // too far only costs a rescan of permanently-bad nodes.)
      if (min_reverted < n)
        for (int gg2 = 0; gg2 < g; ++gg2)
          if (min_reverted < hint[gg2]) hint[gg2] = min_reverted;
      reason_out[c] = 1;
      continue;
    }

    // accept
    accept_out[c] = 1;
    reason_out[c] = 0;
    ++accepted;
    if (n_pdbs > 0)
      for (int p = 0; p < n_pdbs; ++p) pdb_remaining[p] -= pdb_need[p];
    deleted[cand] = 1;
    if (con.active()) {
      for (const Move& m : placed) {
        const size_t mi = (size_t)m.group * n + m.node;
        if (con.marks_local[mi]) {
          con.marks_local[mi] = 0;
          con.marks_committed[mi] = 1;
        }
      }
      con.remove_node(cand);
    }
    group_room[gi_room] -= 1;
    if (is_empty) --empty_budget; else --drain_budget;
    if (quota_totals) {
      for (int k = 0; k < r; ++k)
        quota_totals[k] -= node_cap[(int64_t)cand * r + k];
    }
    received[cand].clear();
    for (const Move& m : placed) {
      dest_out[m.slot] = m.node;
      received[m.node].push_back(m);
    }
  }
  return accepted;
}

}  // extern "C"
