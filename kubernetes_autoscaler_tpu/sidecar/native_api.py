"""ctypes bindings for the native snapshot-delta codec (libkacodec.so).

Builds lazily via `make` on first use if the shared library is missing
(g++ is part of the baked toolchain; no pip deps). Falls back to raising a
clear error when no compiler exists — callers gate on `available()`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.models.cluster_state import DEFAULT_DIMS, Dims

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libkacodec.so")
_lib = None


def _build() -> None:
    subprocess.run(["make", "-C", _DIR, "-s"], check=True)


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        _build()
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        # stale binary from a different toolchain (loader version mismatch):
        # force-rebuild with the local compiler, then load for real
        subprocess.run(["make", "-C", _DIR, "-s", "-B"], check=True)
        lib = ctypes.CDLL(_LIB_PATH)
    lib.ka_state_new.restype = ctypes.c_void_p
    lib.ka_state_new.argtypes = [ctypes.c_int] * 8
    lib.ka_state_free.argtypes = [ctypes.c_void_p]
    lib.ka_last_error.restype = ctypes.c_char_p
    lib.ka_last_error.argtypes = [ctypes.c_void_p]
    lib.ka_apply_delta.restype = ctypes.c_int
    lib.ka_apply_delta.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
    lib.ka_version.restype = ctypes.c_uint64
    lib.ka_version.argtypes = [ctypes.c_void_p]
    try:
        # per-export-section versions (plane-granular cache keys); absent
        # on a stale pre-ISSUE-11 binary — section_versions() degrades to
        # the whole-state version, which only costs cache granularity
        lib.ka_section_version.restype = ctypes.c_uint64
        lib.ka_section_version.argtypes = [ctypes.c_void_p, ctypes.c_int]
    except AttributeError:  # pragma: no cover — repo ships the new binary
        pass
    for f in (lib.ka_num_nodes, lib.ka_num_pods, lib.ka_num_groups):
        f.restype = ctypes.c_int
        f.argtypes = [ctypes.c_void_p]
    lib.ka_export_nodes.restype = ctypes.c_int
    lib.ka_export_groups.restype = ctypes.c_int
    lib.ka_export_pods.restype = ctypes.c_int
    lib.ka_group_key.restype = ctypes.c_int
    lib.ka_group_key.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                 ctypes.c_char_p, ctypes.c_int]
    lib.ka_node_row.restype = ctypes.c_int
    lib.ka_node_row.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ka_zone_id.restype = ctypes.c_int
    lib.ka_zone_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ka_num_zones.restype = ctypes.c_int
    lib.ka_num_zones.argtypes = [ctypes.c_void_p]
    lib.ka_fold32_batch.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64), ctypes.c_int,
        np.ctypeslib.ndpointer(np.int32),
    ]
    lib.ka_fnv64_batch.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64), ctypes.c_int,
        np.ctypeslib.ndpointer(np.int64),
    ]
    _lib = lib
    return lib


def available() -> bool:
    try:
        load()
        return True
    except Exception:
        return False


def fold32_batch(strings: list[bytes]) -> np.ndarray:
    """Native batch hashing (hot path of models/encode for big clusters)."""
    lib = load()
    data = b"".join(strings)
    offsets = np.zeros(len(strings) + 1, np.int64)
    np.cumsum([len(s) for s in strings], out=offsets[1:])
    out = np.zeros(len(strings), np.int32)
    lib.ka_fold32_batch(data, offsets, len(strings), out)
    return out


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


class NativeSnapshotState:
    """Server-side incremental cluster state (the sidecar's resident model)."""

    def __init__(self, dims: Dims = DEFAULT_DIMS):
        self.lib = load()
        self.dims = dims
        self.handle = ctypes.c_void_p(self.lib.ka_state_new(
            dims.max_labels, dims.max_taints, dims.max_tolerations,
            dims.max_sel_terms, dims.max_sel_alts, dims.max_neg_terms,
            dims.max_pod_ports, dims.max_node_ports,
        ))

    def __del__(self):
        if getattr(self, "handle", None):
            self.lib.ka_state_free(self.handle)
            self.handle = None

    def apply_delta(self, payload: bytes) -> None:
        rc = self.lib.ka_apply_delta(self.handle, payload, len(payload))
        if rc != 0:
            err = self.lib.ka_last_error(self.handle).decode()
            raise ValueError(f"apply_delta failed rc={rc}: {err}")

    @property
    def version(self) -> int:
        return int(self.lib.ka_version(self.handle))

    def counts(self) -> tuple[int, int, int]:
        return (self.lib.ka_num_nodes(self.handle),
                self.lib.ka_num_pods(self.handle),
                self.lib.ka_num_groups(self.handle))

    def group_key(self, row: int) -> str:
        """Equivalence key of a group row ('' when out of range) — the join
        key for the KAUX constraint side-channel (sidecar/constraints.py)."""
        buf = ctypes.create_string_buffer(256)
        n = self.lib.ka_group_key(self.handle, row, buf, 256)
        if n < 0:
            return ""
        return buf.raw[: min(n, 256)].decode()

    def node_row(self, name: str) -> int:
        return int(self.lib.ka_node_row(self.handle, name.encode()))

    def zone_id(self, zone: str) -> int:
        """Codec-interned id for a zone string (-1 = unknown, 0 = none)."""
        return int(self.lib.ka_zone_id(self.handle, zone.encode()))

    def num_zones(self) -> int:
        return int(self.lib.ka_num_zones(self.handle))

    def zone_table_for_templates(self, zones: list[str]):
        """A ZoneTable aligned with the codec's zone-id space: known zones
        reuse the codec's ids; unknown template zones get fresh ids beyond
        them (review finding: a fresh ZoneTable would intern template zones
        in a DIFFERENT id space than the exported node tensors)."""
        from kubernetes_autoscaler_tpu.models.encode import ZoneTable

        ids: dict[str, int] = {}
        next_id = self.num_zones() + 1
        for z in zones:
            if not z or z in ids:
                continue
            known = self.zone_id(z)
            if known > 0:
                ids[z] = known
            else:
                ids[z] = next_id
                next_id += 1
        return ZoneTable(ids=ids)

    def section_versions(self) -> tuple[int, int, int]:
        """(nodes, groups, pods) export-section versions — the codec bumps
        exactly the sections a delta's ops could change, so these are the
        plane-granular cache keys (server._Tenant export/device caches). A
        stale binary without the symbol degrades to the whole-state version
        on every axis (correct, just coarser caching)."""
        fn = getattr(self.lib, "ka_section_version", None)
        if fn is None:  # pragma: no cover — repo ships the new binary
            v = self.version
            return (v, v, v)
        return (int(fn(self.handle, 0)), int(fn(self.handle, 1)),
                int(fn(self.handle, 2)))

    def export_nodes(self, node_bucket: int = 64) -> dict:
        """Node tensor section at `pad_to(n, node_bucket)` rows (numpy)."""
        from kubernetes_autoscaler_tpu.models.cluster_state import pad_to

        d = self.dims
        n, _, _ = self.counts()
        n_pad = pad_to(n, node_bucket)
        r = res.NUM_RESOURCES
        nodes = {
            "cap": np.zeros((n_pad, r), np.int32),
            "alloc": np.zeros((n_pad, r), np.int32),
            "label_hash": np.zeros((n_pad, d.max_labels), np.int32),
            "taint_exact": np.zeros((n_pad, d.max_taints), np.int32),
            "taint_key": np.zeros((n_pad, d.max_taints), np.int32),
            "used_ports": np.zeros((n_pad, d.max_node_ports), np.int32),
            "zone_id": np.zeros((n_pad,), np.int32),
            "group_id": np.full((n_pad,), -1, np.int32),
            "ready": np.zeros((n_pad,), np.uint8),
            "schedulable": np.zeros((n_pad,), np.uint8),
            "valid": np.zeros((n_pad,), np.uint8),
        }
        rc = self.lib.ka_export_nodes(
            self.handle, n_pad, _ptr(nodes["cap"]), _ptr(nodes["alloc"]),
            _ptr(nodes["label_hash"]), _ptr(nodes["taint_exact"]),
            _ptr(nodes["taint_key"]), _ptr(nodes["used_ports"]),
            _ptr(nodes["zone_id"]), _ptr(nodes["group_id"]),
            _ptr(nodes["ready"]), _ptr(nodes["schedulable"]),
            _ptr(nodes["valid"]))
        if rc < 0:
            raise ValueError(f"export_nodes failed rc={rc}")
        return nodes

    def export_groups(self, group_bucket: int = 64) -> dict:
        """Pod-group tensor section at `pad_to(max(g, 1), group_bucket)`."""
        from kubernetes_autoscaler_tpu.models.cluster_state import pad_to

        d = self.dims
        _, _, g = self.counts()
        g_pad = pad_to(max(g, 1), group_bucket)
        r = res.NUM_RESOURCES
        groups = {
            "req": np.zeros((g_pad, r), np.int32),
            "count": np.zeros((g_pad,), np.int32),
            "sel_req": np.zeros((g_pad, d.max_sel_terms, d.max_sel_alts), np.int32),
            "sel_neg": np.zeros((g_pad, d.max_neg_terms), np.int32),
            "tol_exact": np.zeros((g_pad, d.max_tolerations), np.int32),
            "tol_key": np.zeros((g_pad, d.max_tolerations), np.int32),
            "tolerate_all": np.zeros((g_pad,), np.uint8),
            "port_hash": np.zeros((g_pad, d.max_pod_ports), np.int32),
            "anti_self": np.zeros((g_pad,), np.uint8),
            "valid": np.zeros((g_pad,), np.uint8),
            "lossy": np.zeros((g_pad,), np.uint8),
        }
        rc = self.lib.ka_export_groups(
            self.handle, g_pad, _ptr(groups["req"]), _ptr(groups["count"]),
            _ptr(groups["sel_req"]), _ptr(groups["sel_neg"]),
            _ptr(groups["tol_exact"]), _ptr(groups["tol_key"]),
            _ptr(groups["tolerate_all"]), _ptr(groups["port_hash"]),
            _ptr(groups["anti_self"]), _ptr(groups["valid"]),
            _ptr(groups["lossy"]))
        if rc < 0:
            raise ValueError(f"export_groups failed rc={rc}")
        return groups

    def export_pods(self, pod_bucket: int = 256) -> dict:
        """Scheduled-pod tensor section at `pad_to(p, pod_bucket)`."""
        from kubernetes_autoscaler_tpu.models.cluster_state import pad_to

        _, p, _ = self.counts()
        p_pad = pad_to(p, pod_bucket)
        r = res.NUM_RESOURCES
        pods = {
            "req": np.zeros((p_pad, r), np.int32),
            "node_idx": np.full((p_pad,), -1, np.int32),
            "group_ref": np.zeros((p_pad,), np.int32),
            "movable": np.zeros((p_pad,), np.uint8),
            "blocks": np.zeros((p_pad,), np.uint8),
            "valid": np.zeros((p_pad,), np.uint8),
        }
        rc = self.lib.ka_export_pods(
            self.handle, p_pad, _ptr(pods["req"]), _ptr(pods["node_idx"]),
            _ptr(pods["group_ref"]), _ptr(pods["movable"]),
            _ptr(pods["blocks"]), _ptr(pods["valid"]))
        if rc < 0:
            raise ValueError(f"export_pods failed rc={rc}")
        return pods

    def export(self, node_bucket: int = 64, group_bucket: int = 64,
               pod_bucket: int = 256):
        """Materialize tensors (numpy; caller ships to device). Mirrors the
        EncodedCluster tensor layout exactly. Per-section callers (the
        plane-granular export cache) use export_nodes/export_groups/
        export_pods directly."""
        return (self.export_nodes(node_bucket),
                self.export_groups(group_bucket),
                self.export_pods(pod_bucket))

    def to_tensors(self, node_bucket: int = 64, group_bucket: int = 64,
                   pod_bucket: int = 256):
        """Export as device-resident NodeTensors/PodGroupTensors/ScheduledPodTensors."""
        import jax.numpy as jnp

        from kubernetes_autoscaler_tpu.models.cluster_state import (
            NodeTensors,
            PodGroupTensors,
            ScheduledPodTensors,
        )

        nodes, groups, pods = self.export(node_bucket, group_bucket, pod_bucket)
        nt = NodeTensors(
            cap=jnp.asarray(nodes["cap"]), alloc=jnp.asarray(nodes["alloc"]),
            label_hash=jnp.asarray(nodes["label_hash"]),
            taint_exact=jnp.asarray(nodes["taint_exact"]),
            taint_key=jnp.asarray(nodes["taint_key"]),
            used_ports=jnp.asarray(nodes["used_ports"]),
            zone_id=jnp.asarray(nodes["zone_id"]),
            group_id=jnp.asarray(nodes["group_id"]),
            ready=jnp.asarray(nodes["ready"].astype(bool)),
            schedulable=jnp.asarray(nodes["schedulable"].astype(bool)),
            valid=jnp.asarray(nodes["valid"].astype(bool)),
        )
        gt = PodGroupTensors(
            req=jnp.asarray(groups["req"]), count=jnp.asarray(groups["count"]),
            sel_req=jnp.asarray(groups["sel_req"]),
            sel_neg=jnp.asarray(groups["sel_neg"]),
            tol_exact=jnp.asarray(groups["tol_exact"]),
            tol_key=jnp.asarray(groups["tol_key"]),
            tolerate_all=jnp.asarray(groups["tolerate_all"].astype(bool)),
            port_hash=jnp.asarray(groups["port_hash"]),
            anti_affinity_self=jnp.asarray(groups["anti_self"].astype(bool)),
            valid=jnp.asarray(groups["valid"].astype(bool)),
            needs_host_check=jnp.asarray(groups["lossy"].astype(bool)),
        )
        pt = ScheduledPodTensors(
            req=jnp.asarray(pods["req"]),
            node_idx=jnp.asarray(pods["node_idx"]),
            group_ref=jnp.asarray(pods["group_ref"]),
            movable=jnp.asarray(pods["movable"].astype(bool)),
            blocks=jnp.asarray(pods["blocks"].astype(bool)),
            valid=jnp.asarray(pods["valid"].astype(bool)),
        )
        return nt, gt, pt
