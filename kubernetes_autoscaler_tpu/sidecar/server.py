"""The TPU simulation sidecar: gRPC service over the native snapshot state.

Deployment shape (SURVEY.md north star): the Go Cluster Autoscaler keeps its
control loop and cloud actuation; behind the estimator/expander/processor
seams it dials this sidecar — pushing KAD1 snapshot deltas (decoded by the C++
codec into pinned buffers) and asking for scale-up/scale-down simulations,
which run as the fused device kernels (ops/autoscale_step).

Transport: grpcio generic handlers speaking the rpc shape documented in
protos/simulator.proto (bytes payloads; no codegen dependency). The same
Service object also backs in-process use (tests, the Python control plane).
"""

from __future__ import annotations

import json
import threading
import time as _time
from dataclasses import dataclass

import numpy as np

from kubernetes_autoscaler_tpu.metrics import trace
from kubernetes_autoscaler_tpu.metrics.metrics import Registry
from kubernetes_autoscaler_tpu.metrics.phases import PHASE_BUCKETS
from kubernetes_autoscaler_tpu.models.cluster_state import DEFAULT_DIMS, Dims
from kubernetes_autoscaler_tpu.sidecar.native_api import NativeSnapshotState
from kubernetes_autoscaler_tpu.sidecar.wire import TRACE_ID_HEADER, DeltaWriter

_SERVICE = "katpu.simulator.v1.TpuSimulator"


@dataclass
class SimParams:
    max_new_nodes: int = 256
    strategy: str = "least-waste"
    threshold: float = 0.5
    node_groups: list | None = None


class SimulatorService:
    """Transport-independent service core."""

    def __init__(self, dims: Dims = DEFAULT_DIMS,
                 node_bucket: int = 256, group_bucket: int = 64):
        self.dims = dims
        self.state = NativeSnapshotState(dims)
        self.node_bucket = node_bucket
        self.group_bucket = group_bucket
        self._lock = threading.Lock()
        # KAUX constraint side-channel store (uid -> wire record)
        self._aux: dict[str, dict] = {}
        # per-RPC metrics, exposed in prometheus text by the Metricz rpc
        # (the sidecar's /metricz analog — it has no HTTP mux of its own)
        self.registry = Registry(prefix="katpu_sidecar")

    # ---- rpc: ApplyDelta ----

    def apply_delta(self, payload: bytes) -> dict:
        from kubernetes_autoscaler_tpu.sidecar.wire import split_aux

        with self._lock:
            try:
                # split INSIDE the guarded region: any malformed trailer must
                # surface as an error dict, never an uncaught exception
                dense, aux = split_aux(payload)
                self.state.apply_delta(dense)
                if aux is not None:
                    self._aux.update(aux.get("up", {}))
                    for uid in aux.get("del", []):
                        self._aux.pop(uid, None)
                return {"version": self.state.version, "error": ""}
            except (ValueError, TypeError) as e:
                return {"version": self.state.version, "error": str(e)}

    def _tensors_with_constraints(self):
        """Exported tensors + the constraint overlay (side-channel specs +
        resident planes) — what encode_cluster produces natively."""
        from kubernetes_autoscaler_tpu.sidecar.constraints import (
            attach_constraints,
        )

        nt, gt, pt = self.state.to_tensors(self.node_bucket, self.group_bucket)
        planes, has_c = None, False
        if self._aux:
            gt, planes, has_c = attach_constraints(
                self.state, gt, nt.n, self._aux,
                max_zones=self.dims.max_zones)
        return nt, gt, pt, planes, has_c

    # ---- rpc: ScaleUpSim ----

    def scale_up_sim(self, params: SimParams) -> dict:
        from kubernetes_autoscaler_tpu.models.api import Node, Taint
        from kubernetes_autoscaler_tpu.models.encode import (
            ZoneTable,
            encode_node_groups,
        )
        from kubernetes_autoscaler_tpu.models.resources import (
            ExtendedResourceRegistry,
        )
        from kubernetes_autoscaler_tpu.ops.autoscale_step import scale_up_sim

        with self._lock:
            nt, gt, pt, planes, has_c = self._tensors_with_constraints()
        templates = []
        ids = []
        for g in params.node_groups or []:
            t = g["template"]
            node = Node(
                name=t.get("name", g["id"]),
                labels=t.get("labels", {}),
                capacity=t.get("capacity", {}),
                allocatable=t.get("allocatable", t.get("capacity", {})),
                taints=[Taint(**x) for x in t.get("taints", [])],
            )
            templates.append((node, g.get("max_new", 1000), g.get("price", 1.0)))
            ids.append(g["id"])
        groups = encode_node_groups(
            templates, ExtendedResourceRegistry(),
            # align template zone ids with the codec's interning so the
            # constrained tier compares zones in ONE id space
            self.state.zone_table_for_templates(
                [t.zone() for t, _, _ in templates]),
            self.dims
        )
        out = scale_up_sim(nt, gt, pt, groups, self.dims,
                           params.max_new_nodes, params.strategy,
                           planes=planes, with_constraints=has_c)
        best = int(out.best)
        return {
            "best": ids[best] if 0 <= best < len(ids) else "",
            "options": [
                {
                    "id": ids[i],
                    "node_count": int(out.estimate.node_count[i]),
                    "pods": int(out.scores.pods[i]),
                    "waste": float(out.scores.waste[i]),
                    "price": float(out.scores.price[i]),
                    "valid": bool(out.scores.valid[i]),
                }
                for i in range(len(ids))
            ],
            "fits_existing": int(np.asarray(out.fits_existing).sum()),
            "remaining": int(np.asarray(out.remaining).sum()),
        }

    # ---- rpc: ScaleDownSim ----

    def scale_down_sim(self, params: SimParams) -> dict:
        from kubernetes_autoscaler_tpu.ops.autoscale_step import scale_down_sim

        with self._lock:
            nt, gt, pt, planes, has_c = self._tensors_with_constraints()
        out = scale_down_sim(nt, gt, pt, params.threshold,
                             planes=planes, max_zones=self.dims.max_zones,
                             with_constraints=has_c)
        valid = np.asarray(nt.valid)
        return {
            "eligible": np.nonzero(np.asarray(out.eligible) & valid)[0].tolist(),
            "drainable": np.nonzero(
                np.asarray(out.removal.drainable) & valid)[0].tolist(),
            "utilization": [round(float(u), 4)
                            for u in np.asarray(out.utilization)[valid]],
        }

    def health(self) -> dict:
        return {"version": self.state.version, "error": ""}

    # ---- rpc: Metricz ----

    def metricz(self) -> str:
        """The sidecar's /metricz analog: its own Registry (per-RPC counters
        and duration histograms, `katpu_sidecar_*`) FOLLOWED BY the
        process-wide default registry (`cluster_autoscaler_*`, including
        `# HELP` lines and the reason-labelled families) in prometheus
        exposition text. Serving both means the main-process `/metrics` mux
        and this RPC expose the same autoscaler families — a scrape of
        either surface sees the reason plane (asserted by
        tests/test_reason_plane.py). Plain text on the wire, not JSON —
        scrapeable as-is."""
        from kubernetes_autoscaler_tpu.metrics.metrics import default_registry

        return self.registry.expose_text() + default_registry.expose_text()


def traced_call(service: SimulatorService, method: str, fn,
                trace_id: str | None = None):
    """Run one RPC body under the sidecar's observability contract: RPC
    count/duration always land in `service.registry`; when the caller
    stamped a trace id into the request metadata, the body runs under a
    child Tracer with the SAME id and the closed spans come back as the
    `(result, trace_group)` second element — the shape
    `metrics/trace.Tracer.add_remote_spans` merges client-side, so one
    trace covers both processes."""
    tracer = (trace.Tracer(trace_id=trace_id, process="sidecar")
              if trace_id else None)
    prev = trace.activate(tracer) if tracer is not None else None
    t0 = _time.perf_counter()
    try:
        if tracer is not None:
            idx = tracer.begin(f"sidecar/{method}", cat="sidecar")
            try:
                out = fn()
            finally:
                tracer.end(idx, version=service.state.version)
        else:
            out = fn()
    finally:
        if tracer is not None:
            trace.activate(prev)
        dt = _time.perf_counter() - t0
        service.registry.counter(
            "rpc_total", help="RPCs served, by method").inc(method=method)
        service.registry.histogram(
            "rpc_duration_seconds", help="Server-side RPC wall clock",
            buckets=PHASE_BUCKETS).observe(dt, method=method)
    group = None
    if tracer is not None:
        snap = tracer.snapshot()
        group = {"trace_id": snap["trace_id"], "process": "sidecar",
                 "spans": snap["spans"]}
    return out, group


def make_grpc_server(service: SimulatorService, port: int = 50151,
                     cert_file: str | None = None,
                     key_file: str | None = None,
                     client_ca_file: str | None = None,
                     host: str = "127.0.0.1"):
    """Wire the service into a grpc.Server with generic bytes handlers.

    TLS: pass cert_file/key_file to serve over TLS (mirrors the reference's
    --grpc-expander-cert precedent for out-of-process plugins; round-3 review
    item #7 — the simulator service previously bound insecure only).
    client_ca_file additionally requires and verifies client certificates
    (mTLS). Without certs the server binds insecure on localhost."""
    import grpc

    def _trace_id_of(context) -> str | None:
        md = getattr(context, "invocation_metadata", None)
        if md is None:
            return None
        for k, v in md() or ():
            if k == TRACE_ID_HEADER:
                return v
        return None

    def _json_method(name: str, fn, parse_params: bool):
        def handler(request: bytes, context):
            try:
                if parse_params:
                    raw = json.loads(request.decode() or "{}")
                    params = SimParams(
                        max_new_nodes=raw.get("max_new_nodes", 256),
                        strategy=raw.get("strategy", "least-waste"),
                        threshold=raw.get("threshold", 0.5),
                        node_groups=raw.get("node_groups"),
                    )
                    body = lambda: fn(params)  # noqa: E731
                else:
                    body = lambda: fn(request)  # noqa: E731
                resp, group = traced_call(service, name, body,
                                          trace_id=_trace_id_of(context))
                if group is not None and isinstance(resp, dict):
                    resp["trace"] = group
                return json.dumps(resp).encode()
            except Exception as e:  # fail-closed with the error on the wire
                return json.dumps({"error": str(e)}).encode()

        return handler

    def _metricz(request: bytes, context):
        text, _ = traced_call(service, "Metricz", service.metricz,
                              trace_id=_trace_id_of(context))
        return text.encode()

    ident = lambda b: b

    method_handlers = {
        "ApplyDelta": grpc.unary_unary_rpc_method_handler(
            _json_method("ApplyDelta", service.apply_delta, False),
            request_deserializer=ident, response_serializer=ident),
        "ScaleUpSim": grpc.unary_unary_rpc_method_handler(
            _json_method("ScaleUpSim", service.scale_up_sim, True),
            request_deserializer=ident, response_serializer=ident),
        "ScaleDownSim": grpc.unary_unary_rpc_method_handler(
            _json_method("ScaleDownSim", service.scale_down_sim, True),
            request_deserializer=ident, response_serializer=ident),
        "Health": grpc.unary_unary_rpc_method_handler(
            _json_method("Health", lambda _b: service.health(), False),
            request_deserializer=ident, response_serializer=ident),
        "Metricz": grpc.unary_unary_rpc_method_handler(
            _metricz, request_deserializer=ident, response_serializer=ident),
    }
    from concurrent.futures import ThreadPoolExecutor

    server = grpc.server(ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE, method_handlers),)
    )
    if client_ca_file and not (cert_file and key_file):
        raise ValueError(
            "client_ca_file (mTLS) requires a serving cert_file/key_file — "
            "refusing to bind insecure while client verification was asked")
    if cert_file and key_file:
        with open(key_file, "rb") as f:
            key = f.read()
        with open(cert_file, "rb") as f:
            crt = f.read()
        root = None
        if client_ca_file:
            with open(client_ca_file, "rb") as f:
                root = f.read()
        creds = grpc.ssl_server_credentials(
            [(key, crt)], root_certificates=root,
            require_client_auth=bool(client_ca_file))
        bound = server.add_secure_port(f"{host}:{port}", creds)
    else:
        bound = server.add_insecure_port(f"{host}:{port}")
    return server, bound


class SimulatorClient:
    """Thin client mirroring the Go side's calls (tests + examples)."""

    def __init__(self, port: int, cert_file: str | None = None,
                 host: str = "127.0.0.1",
                 client_cert_file: str | None = None,
                 client_key_file: str | None = None):
        import grpc

        if cert_file:
            with open(cert_file, "rb") as f:
                root = f.read()
            ck = cc = None
            if client_cert_file and client_key_file:
                with open(client_key_file, "rb") as f:
                    ck = f.read()
                with open(client_cert_file, "rb") as f:
                    cc = f.read()
            creds = grpc.ssl_channel_credentials(
                root_certificates=root, private_key=ck, certificate_chain=cc)
            # loopback targets verify against the self-signed pair's
            # "localhost" SAN; real hosts verify their own names — never
            # weaken verification for them
            opts = ([("grpc.ssl_target_name_override", "localhost")]
                    if host in ("127.0.0.1", "::1", "localhost") else [])
            self.channel = grpc.secure_channel(
                f"{host}:{port}", creds, options=opts)
        else:
            self.channel = grpc.insecure_channel(f"{host}:{port}")

    def _call(self, method: str, payload: bytes) -> bytes:
        rpc = self.channel.unary_unary(
            f"/{_SERVICE}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        # trace propagation: the ACTIVE tracer's id rides request metadata
        # (never the payload bytes — the KAD1 wire contract stays trace-free)
        # and the rpc itself is a client-side span on the same timeline
        tracer = trace.current_tracer()
        if tracer is None:
            return rpc(payload)
        with tracer.span(f"rpc/{method}", cat="rpc", bytes=len(payload)):
            return rpc(payload,
                       metadata=((TRACE_ID_HEADER, tracer.trace_id),))

    def _call_json(self, method: str, payload: bytes) -> dict:
        resp = json.loads(self._call(method, payload))
        # the server reports its child spans back in the response; merge
        # them so ONE trace covers both processes
        tracer = trace.current_tracer()
        group = resp.pop("trace", None) if isinstance(resp, dict) else None
        if tracer is not None and group is not None:
            tracer.add_remote_spans(group)
        return resp

    def apply_delta(self, writer: DeltaWriter) -> dict:
        return self._call_json("ApplyDelta", writer.payload())

    def scale_up_sim(self, **params) -> dict:
        return self._call_json("ScaleUpSim", json.dumps(params).encode())

    def scale_down_sim(self, **params) -> dict:
        return self._call_json("ScaleDownSim", json.dumps(params).encode())

    def health(self) -> dict:
        return self._call_json("Health", b"")

    def metricz(self) -> str:
        """Prometheus text of the sidecar's Registry (rpc counters etc.)."""
        return self._call("Metricz", b"").decode()


def main(argv=None):
    """Standalone sidecar: python -m kubernetes_autoscaler_tpu.sidecar.server
    --port 50151 [--grpc-cert C --grpc-key K [--grpc-client-ca CA]]
    [--self-signed-cert-dir DIR]."""
    import argparse
    import time

    ap = argparse.ArgumentParser(prog="katpu-sidecar")
    ap.add_argument("--port", type=int, default=50151)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--grpc-cert", default="")
    ap.add_argument("--grpc-key", default="")
    ap.add_argument("--grpc-client-ca", default="")
    ap.add_argument("--self-signed-cert-dir", default="",
                    help="generate+rotate a serving cert here when no "
                         "--grpc-cert is given (rotation rebinds the gRPC "
                         "listener — grpc credentials hold the PEM bytes)")
    args = ap.parse_args(argv)
    cm = None
    cert, key = args.grpc_cert, args.grpc_key
    if not cert and args.self_signed_cert_dir:
        from kubernetes_autoscaler_tpu.utils.certs import CertManager

        cm = CertManager(args.self_signed_cert_dir, common_name="localhost")
        cert, key = cm.cert_path, cm.key_path
    service = SimulatorService()

    def bind():
        srv, bound = make_grpc_server(
            service, args.port, cert_file=cert or None, key_file=key or None,
            client_ca_file=args.grpc_client_ca or None, host=args.host)
        srv.start()
        return srv, bound

    server, bound = bind()
    print(f"katpu-sidecar listening on {args.host}:{bound} "
          f"({'tls' if cert else 'insecure'})", flush=True)
    try:
        while True:
            time.sleep(3600)
            if cm is not None and cm.ensure():
                # rotated: grpc server credentials are immutable — rebind
                # with the fresh pair (the snapshot state lives in `service`
                # and survives the rebind)
                server.stop(5.0).wait()
                server, bound = bind()
                print(f"katpu-sidecar rotated serving cert; rebound on "
                      f"{args.host}:{bound}", flush=True)
    except KeyboardInterrupt:
        server.stop(2.0)


if __name__ == "__main__":
    main()
