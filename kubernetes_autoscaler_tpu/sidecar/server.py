"""The TPU simulation sidecar: multi-tenant gRPC service over native snapshot state.

Deployment shape (SURVEY.md north star): ONE sidecar serves a FLEET of
autoscalers behind the reference's `externalgrpc` extension point. Each Go
control plane keeps its loop and cloud actuation; behind the estimator/
expander/processor seams it dials this sidecar — pushing KAD1 snapshot deltas
(decoded by the C++ codec into pinned buffers) under its tenant id and asking
for scale-up/scale-down simulations.

Multi-tenant serving (docs/SERVING.md): every tenant's world is bucketed into
a padded shape class (sidecar/shapes.py); concurrent requests coalesce in a
short admission window (sidecar/admission.py) and dispatch as ONE vmapped
device program per class (ops/autoscale_step.scale_up_sim_batch), so
simulation throughput scales with batch occupancy, not tenant count, and a
new tenant joining an existing class compiles NOTHING
(`recompiles_per_new_tenant` gauge, CI-asserted). The admission queue is
bounded — overload rejects with RESOURCE_EXHAUSTED + retry-after instead of
wedging — and fair: windows form round-robin across tenants, never FIFO
across all requests.

Transport: grpcio generic handlers speaking the rpc shape documented in
protos/simulator.proto (bytes payloads; no codegen dependency). Tenant
identity rides request metadata (wire.TENANT_ID_HEADER); no header = the
default tenant = the exact pre-multi-tenant behavior. The same Service
object also backs in-process use (tests, the Python control plane, bench).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import queue as _queue
import threading
import time as _time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass, field

import numpy as np

from kubernetes_autoscaler_tpu.events import EventSink
from kubernetes_autoscaler_tpu.metrics import device
from kubernetes_autoscaler_tpu.metrics import trace
from kubernetes_autoscaler_tpu.metrics.metrics import (
    Registry,
    register_exposition,
    unregister_exposition,
)
from kubernetes_autoscaler_tpu.metrics.phases import PHASE_BUCKETS, PhaseStats
from kubernetes_autoscaler_tpu.models.cluster_state import DEFAULT_DIMS, Dims
from kubernetes_autoscaler_tpu.sidecar import faults
from kubernetes_autoscaler_tpu.sidecar.admission import (
    AdmissionQueue,
    BatchScheduler,
    Quarantined,
    QueueFull,
    SchedulerDown,
    Ticket,
    WorldValidationError,
)
from kubernetes_autoscaler_tpu.sidecar.lifecycle import (
    REQUEST_PHASE_BUCKETS,
    SloBudgets,
    Stamps,
    add_lifecycle_spans,
    lifecycle_block,
)
from kubernetes_autoscaler_tpu.sidecar.native_api import NativeSnapshotState
from kubernetes_autoscaler_tpu.sidecar.shapes import ShapeClass, ShapeLadder, rung
from kubernetes_autoscaler_tpu.replay.journal import TenantJournal
from kubernetes_autoscaler_tpu.sidecar.wire import (
    BASE_VERSION_HEADER,
    RETRY_AFTER_MS_HEADER,
    SLO_BUDGET_MS_HEADER,
    TENANT_ID_HEADER,
    TRACE_ID_HEADER,
    DeltaWriter,
)

_SERVICE = "katpu.simulator.v1.TpuSimulator"

# node-group template count quantization (requests carry their own template
# ladder; NG is small, so a fine-grained geometric base keeps padding waste low)
_NG_RUNG_BASE = 4


@dataclass
class SimParams:
    max_new_nodes: int = 256
    strategy: str = "least-waste"
    threshold: float = 0.5
    node_groups: list | None = None


@dataclass
class _Tenant:
    """One tenant's server-resident world + caches."""

    tid: str
    state: NativeSnapshotState
    aux: dict = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock, repr=False)
    shape_class: ShapeClass | None = None
    # plane-granular export caches (ISSUE 11): keyed PER SECTION by the
    # codec's section version + the class axis rung — a single-pod delta
    # bumps only the sections its ops touched, so untouched planes are
    # never re-materialized (numpy) or re-uploaded (device). The device
    # tier is the tenant's RESIDENT world: steady windows stack these
    # arrays on-device and move zero world h2d bytes.
    export_keys: dict = field(default_factory=dict)  # section -> (sv, rung)
    export_np: dict = field(default_factory=dict)    # section -> numpy dict
    dev_keys: dict = field(default_factory=dict)     # section -> (sv, rung)
    dev_np: dict = field(default_factory=dict)       # section -> device dict
    # serial-path residency: version-keyed cache of the fully-assembled
    # (tensors + constraint overlay) world, so constrained/serial tenants
    # stop re-uploading per RPC too
    serial_cache: tuple | None = None
    # encode-mode accounting mirrored per tenant for Statusz
    encode_modes: dict = field(default_factory=dict)
    # request node-group digest -> (ng numpy tensors, ids, ng_rung, digest)
    ng_cache: OrderedDict = field(default_factory=OrderedDict)
    dispatched: bool = False     # has served ≥1 sim (new-tenant accounting)
    # serving observability: recent e2e latencies (statusz percentiles),
    # SLO breach count and the last breach's retained exemplar trace id
    lat_ms: deque = field(default_factory=lambda: deque(maxlen=512))
    slo_breaches: int = 0
    last_breach_trace: str = ""
    # per-tenant flight journal (replay/journal.TenantJournal): bounded
    # in-memory provenance ring, persisted on breach/backpressure
    journal: TenantJournal | None = None
    # pre-admission validation cache: the section-version tuple the last
    # clean validation ran against (unchanged sections re-validate free)
    validated_key: tuple | None = None
    # warm restart (docs/ROBUSTNESS.md): True while serving from a
    # checkpoint-restored export (native codec state still empty); the
    # first ApplyDelta full-resend exits rehydration
    rehydrated: bool = False
    rehydrated_meta: dict | None = None


class SimulatorService:
    """Transport-independent service core (multi-tenant)."""

    def __init__(self, dims: Dims = DEFAULT_DIMS,
                 node_bucket: int = 256, group_bucket: int = 64,
                 pod_bucket: int = 256,
                 batch_lanes: int = 0, batch_window_ms: float = 2.0,
                 batch_window_max: int | None = None,
                 queue_depth: int = 128, ticket_timeout_s: float = 60.0,
                 max_tenants: int = 256,
                 slo_default_budget_ms: float = 0.0,
                 slo_budgets: dict | None = None,
                 slo_dump_dir: str = "",
                 tail_sample_capacity: int = 64,
                 tail_slow_quantile: float = 0.95,
                 journal_capacity: int = 256,
                 quarantine_ttl_s: float = 30.0,
                 max_world: tuple | None = None,
                 rehydrate_dir: str = "",
                 hbm_budget_frac: float = 0.0,
                 hbm_limit_bytes: int = 0,
                 device_profile_dir: str = "",
                 profile_min_interval_s: float = 30.0,
                 profile_max_captures: int = 8,
                 shadow_audit: bool = False):
        self.dims = dims
        self.max_tenants = int(max_tenants)
        # fault-domain isolation (docs/ROBUSTNESS.md): quarantine TTL and
        # the structural world caps the pre-admission validator enforces
        # ((nodes, groups, pods); defaults are generous — they bound abuse,
        # not legitimate scale)
        self.quarantine_ttl_s = float(quarantine_ttl_s)
        self.max_world = tuple(max_world) if max_world \
            else (1 << 20, 1 << 16, 1 << 21)
        self._quarantine: dict[str, dict] = {}
        self._quarantine_lock = threading.Lock()
        self._not_serving = ""      # non-empty = Health reports NOT_SERVING
        self.node_bucket = node_bucket
        self.group_bucket = group_bucket
        self.pod_bucket = pod_bucket
        # per-RPC metrics, exposed in prometheus text by the Metricz rpc
        # (the sidecar's /metricz analog — it has no HTTP mux of its own).
        # Registered with the process /metrics exposition too, so an
        # in-process sidecar's series appear identically on both surfaces.
        self.registry = Registry(prefix="katpu_sidecar")
        register_exposition(self.registry)
        # device-side observability (metrics/device.py): the HBM residency
        # ledger (owner/tenant-tagged census of the resident device arrays —
        # tenant export tiers, stack cache, world-store planes), the compile
        # census (which shape signature compiled for which tenant, at what
        # flop/temp-HBM cost), and the breach-armed device profiler.
        # `hbm_budget_frac` > 0 turns residency into an ADMISSION dimension:
        # a new tenant whose projected class-shaped residency would push
        # tagged bytes past frac·limit is rejected with the `hbm-budget`
        # validation reason instead of OOMing the window it joined.
        device.enable_ledger()
        self.hbm_budget_frac = float(hbm_budget_frac)
        self.hbm_limit_bytes = int(hbm_limit_bytes)
        self._hbm_limit_cache: int | None = None
        # async analysis: the mode-"full" AOT compile for memory figures
        # must not run inside the dispatch that just paid a real compile
        self.census = device.CompileCensus(registry=self.registry,
                                           sync_analysis=False)
        if device_profile_dir:
            device.install_profiler(
                device_profile_dir,
                min_interval_s=profile_min_interval_s,
                max_captures=profile_max_captures,
                registry=self.registry)
        # activate a chaos plan declared in the environment (KATPU_FAULTS);
        # a programmatically installed plan wins, absence costs one env
        # read. The registry rides as the plan's default so hook sites
        # WITHOUT a handle (batch.py / admission.py) still count their
        # fires into faults_injected_total — the "stamped 3 ways" contract
        faults.from_env(registry=self.registry)
        self.phases = PhaseStats(owner="sidecar", registry=self.registry)
        self.ladder = ShapeLadder(node_bucket, group_bucket, pod_bucket,
                                  registry=self.registry)
        # serving-grade observability (docs/OBSERVABILITY.md "Serving
        # surfaces"): per-tenant latency budgets, tail-sampled request
        # traces with exemplar linkage, admission-reject events
        self.slo = SloBudgets(slo_default_budget_ms, slo_budgets)
        self.slo_dump_dir = slo_dump_dir
        self.tail = trace.TailSampler(capacity=tail_sample_capacity,
                                      slow_quantile=tail_slow_quantile)
        # per-tenant journal ring size; the tenant table cap bounds how many
        # rings exist, this bounds each ring's records
        self.journal_capacity = int(journal_capacity)
        self.events = EventSink(registry=self.registry)
        self._events_lock = threading.Lock()   # EventSink isn't thread-safe
        self._tenants: dict[str, _Tenant] = {}
        self._tenants_lock = threading.Lock()
        # serializes the (cache-size, dispatch, cache-size) window that
        # charges recompiles_per_new_tenant: the jit caches are process
        # global, so a concurrent dispatch on another thread (scheduler vs
        # a constrained tenant's serial handler) would otherwise have its
        # compiles attributed to whichever tenant measured last
        self._account_lock = threading.Lock()
        self._tenant("")     # the default tenant: pre-multi-tenant behavior
        # ---- batching (0 lanes = serial dispatch per RPC, the legacy path)
        self.batch_lanes = int(batch_lanes)
        self.ticket_timeout_s = ticket_timeout_s
        self.occupancies: deque[int] = deque(maxlen=1024)
        self._queue: AdmissionQueue | None = None
        self._scheduler: BatchScheduler | None = None
        # device-utilization accounting: recent (gap_seconds, cause) pairs
        # from the scheduler's dispatch-gap estimator (bench percentiles)
        self.gaps: deque[tuple] = deque(maxlen=4096)
        if self.batch_lanes > 0:
            from kubernetes_autoscaler_tpu.sidecar.batch import StackCache

            self._stack_cache = StackCache()
            self._queue = AdmissionQueue(
                max_depth=queue_depth,
                retry_after_ms=max(int(batch_window_ms * 10), 20))
            self._scheduler = BatchScheduler(
                self._queue, self._dispatch_batch, lanes=self.batch_lanes,
                window_s=batch_window_ms / 1000.0,
                window_max=batch_window_max,
                gap_cb=self._note_gap,
                on_batch_failure=self._batch_failure,
                on_crash=self._scheduler_crash).start()
        # online shadow audit, serving edition (docs/OBSERVABILITY.md
        # "Shadow audit"): one ROUND-ROBIN member lane per batched window
        # is re-simulated through the serial (unbatched) reference program
        # and its assembled response compared bit-for-bit — the online form
        # of test_batched_sim's serial≡batched identity. A divergence is a
        # DEVICE/BACKEND fault by construction (same inputs, independent
        # executable), so it rides the supervisor/backend evidence path —
        # counter + AuditDivergence event + tail-retained trace
        # (reason=audit) + tenant-journal persist — and NEVER convicts the
        # tenant (contrast: PR 12's poison-member quarantine, which fires
        # on per-member validation/NaN faults, i.e. BAD INPUTS).
        self.shadow_audit = bool(shadow_audit)
        self._audit_rr = 0
        self.audit_divergences = 0
        self.audit_last: dict | None = None
        self.audit_overhead_ns = 0
        # the audit runs on its OWN worker, never the scheduler thread:
        # the reference re-sim (and its first-window compile, seconds)
        # must not stall the next coalescing window's dispatch. Bounded
        # queue; a full queue drops the window's audit (counted skipped).
        self._audit_q: "_queue.Queue | None" = None
        self._audit_stop = threading.Event()
        self._audit_worker: threading.Thread | None = None
        # batch-compat keys whose serial reference variant has already
        # compiled: later audits at the key dispatch lock-free
        self._audit_warmed: set = set()
        if self.shadow_audit:
            self._audit_q = _queue.Queue(maxsize=4)
            self._audit_worker = threading.Thread(
                target=self._audit_loop, daemon=True, name="ka-shadow-audit")
            self._audit_worker.start()
        # warm restart: rehydrate per-tenant serving records persisted by
        # checkpoint() — steady tenants serve batched sims again without a
        # full world re-send (docs/ROBUSTNESS.md)
        self.rehydration = {"restored": 0, "digest_mismatch": 0, "error": 0}
        if rehydrate_dir:
            self._rehydrate(rehydrate_dir)

    def close(self) -> None:
        if self._scheduler is not None:
            self._scheduler.stop()
            self._scheduler = None
        if self._audit_worker is not None:
            self._audit_stop.set()
            self._audit_worker.join(timeout=2.0)
            self._audit_worker = None
        unregister_exposition(self.registry)

    def _note_gap(self, gap_s: float, cause: str) -> None:
        """Dispatch-gap accounting (BatchScheduler.gap_cb): `pipelined` and
        `stall` gaps measure device idle while work existed — the pipelining
        contract says their distribution sits at ≈0; `idle` gaps are
        arrival-bound and ride a separate counter so an idle fleet does not
        read as a pipeline failure."""
        self.gaps.append((gap_s, cause))
        if cause == "idle":
            self.registry.counter(
                "device_idle_seconds_total",
                help="Device idle while the admission queue was empty "
                     "(arrival-bound, not a pipeline stall)").inc(gap_s)
            return
        self.registry.histogram(
            "dispatch_gap_seconds",
            help="Estimated device idle between one batch's results being "
                 "ready and the next dispatch launching, while work "
                 "existed — ≈0 under pipelining (CI-asserted)",
            buckets=REQUEST_PHASE_BUCKETS).observe(gap_s, cause=cause)

    # ---- tenants ----

    def _tenant(self, tid: str) -> _Tenant:
        with self._tenants_lock:
            ts = self._tenants.get(tid)
            if ts is None:
                if len(self._tenants) >= self.max_tenants:
                    # tenant ids arrive on unauthenticated request metadata:
                    # without a cap, a client stamping fresh ids allocates
                    # one world each until OOM. RESOURCE_EXHAUSTED, like the
                    # admission bound — the operator frees slots with
                    # drop_tenant (or runs a bigger sidecar).
                    e = QueueFull(None, retry_after_ms=1000,
                                  what=f"tenant table "
                                       f"({self.max_tenants} worlds)",
                                  reason="tenant-cap")
                    self._note_reject(tid, e)
                    raise e
                ts = _Tenant(tid=tid, state=NativeSnapshotState(self.dims))
                ts.journal = TenantJournal(tenant=tid,
                                           capacity=self.journal_capacity,
                                           registry=self.registry)
                self._tenants[tid] = ts
                self.registry.gauge(
                    "tenants_active",
                    help="Tenant worlds resident in this sidecar",
                ).set(float(len(self._tenants)))
            return ts

    def _tenant_peek(self, tid: str) -> "_Tenant | None":
        """Read-only lookup: never allocates a world (observability paths
        must not mint tenants from a stray metadata header)."""
        with self._tenants_lock:
            return self._tenants.get(tid)

    def drop_tenant(self, tid: str) -> bool:
        """Evict a tenant's world and ZERO its labelled series (the
        stale-label convention: a dropped tenant must not keep claiming
        traffic — or classification history, phase time, or SLO breaches —
        in the exposition)."""
        with self._tenants_lock:
            ts = self._tenants.pop(tid, None)
            self.registry.gauge("tenants_active").set(
                float(len(self._tenants)))
        if ts is None:
            return False
        self.registry.counter("rpc_total").zero_matching(tenant=tid)
        self.registry.histogram(
            "rpc_duration_seconds").zero_matching(tenant=tid)
        # the same sweep for every tenant-labelled family the serving layer
        # grew: shape-class classification history (ISSUE 8 fix — these
        # lingered forever before), lifecycle phase histograms, SLO breaches
        self.registry.counter("shape_class_hit_total").zero_matching(
            tenant=tid)
        self.registry.counter("shape_class_miss_total").zero_matching(
            tenant=tid)
        self._phase_hist().zero_matching(tenant=tid)
        self.registry.counter("tenant_slo_breaches_total").zero_matching(
            tenant=tid)
        # world-store families are tenant-labelled too: a dropped tenant's
        # resident lanes died with the _Tenant object, so its encode-mode
        # history and h2d byte series must not linger in the exposition
        self.registry.counter("encoder_encodes_total").zero_matching(
            tenant=tid)
        self.registry.counter("world_store_h2d_bytes_total").zero_matching(
            tenant=tid)
        # device-residency families: the tenant's resident lanes die with
        # the _Tenant object, so its HBM gauges zero NOW (not at the next
        # ledger reconcile) and its census charge attribution is removed —
        # the same zero_matching contract as the serving families above
        self.registry.gauge("tenant_hbm_bytes").zero_matching(tenant=tid)
        self.registry.gauge("resident_bytes").zero_matching(tenant=tid)
        self.registry.counter("compile_census_total").zero_matching(
            tenant=tid)
        self.census.zero_tenant(tid)
        if device.LEDGER is not None:
            # owner-scoped: tenant="" is also how the NON-tenant owners
            # (world_store / stack_cache / marshal) are tagged — dropping
            # the default tenant must not deflate their census
            device.LEDGER.release(owner="tenant_export", tenant=tid)
        # per-tenant shadow-audit families: the audited lanes died with the
        # tenant; its check/divergence series must not linger either
        self.registry.counter("shadow_audit_checks_total").zero_matching(
            tenant=tid)
        # journal families are tenant-labelled too (TenantJournal); its ring
        # died with the _Tenant object, so its series must zero as well
        jt = tid or "default"
        self.registry.counter("journal_records_total").zero_matching(
            tenant=jt)
        self.registry.counter("journal_bytes_total").zero_matching(tenant=jt)
        self.registry.counter("journal_dropped_total").zero_matching(
            tenant=jt)
        self.slo.drop(tid)
        return True

    def tenants(self) -> list[str]:
        with self._tenants_lock:
            return sorted(self._tenants)

    # ---- fault-domain isolation (docs/ROBUSTNESS.md) ----

    def _check_quarantine(self, tenant: str) -> None:
        """Admission edge: a quarantined tenant's sims are rejected with
        FAILED_PRECONDITION until its TTL elapses (auto-parole — the first
        request after the TTL is admitted and the entry cleared)."""
        with self._quarantine_lock:
            q = self._quarantine.get(tenant)
            if q is None:
                return
            now = _time.monotonic()
            if now < q["until"]:
                raise Quarantined(tenant, q["reason"],
                                  retry_after_ms=max(
                                      int((q["until"] - now) * 1000), 1))
            del self._quarantine[tenant]
        self._note_parole(tenant, "ttl")

    def _note_parole(self, tenant: str, how: str) -> None:
        self.registry.counter(
            "tenant_paroled_total",
            help="Quarantined tenants re-admitted, by parole path (ttl = "
                 "sentence elapsed; new-world = the tenant re-sent its "
                 "world via ApplyDelta)").inc(how=how)
        with self._events_lock:
            self.events.emit("QuarantineParole", tenant or "default", how,
                             now=_time.time())

    def _quarantine_tenant(self, tenant: str, reason: str,
                           error: Exception | None = None) -> None:
        """Isolate the offender: further sims reject until the TTL parole
        (or an ApplyDelta re-send). Counted per reason, evidenced on the
        event sink and the Statusz quarantine table."""
        now = _time.monotonic()
        with self._quarantine_lock:
            q = self._quarantine.get(tenant)
            if q is None:
                q = self._quarantine[tenant] = {
                    "since": _time.time(), "count": 0}
            q["count"] += 1
            q["reason"] = reason
            q["until"] = now + self.quarantine_ttl_s
            q["error"] = repr(error) if error is not None else ""
        self.registry.counter(
            "tenant_quarantined_total",
            help="Tenants quarantined after a window failure bisected down "
                 "to them, by fault reason").inc(reason=reason)
        with self._events_lock:
            self.events.emit("TenantQuarantined", tenant or "default",
                             reason, message=repr(error) if error else "",
                             now=_time.time())

    def _parole_on_new_world(self, tenant: str) -> None:
        """A successful ApplyDelta paroles early: the quarantined world was
        the evidence, and the tenant just replaced it."""
        with self._quarantine_lock:
            if self._quarantine.pop(tenant, None) is None:
                return
        self._note_parole(tenant, "new-world")

    def quarantine_stats(self) -> dict:
        """tenant -> {reason, count, remaining_s, since} (statusz/bench)."""
        now = _time.monotonic()
        with self._quarantine_lock:
            return {
                t or "default": {
                    "reason": q["reason"], "count": q["count"],
                    "since": q["since"],
                    "remaining_s": round(max(q["until"] - now, 0.0), 3),
                    "error": q["error"],
                }
                for t, q in self._quarantine.items()}

    @staticmethod
    def _fault_reason(error: Exception) -> str:
        if isinstance(error, faults.InjectedFault):
            return f"injected-{error.hook}"
        from kubernetes_autoscaler_tpu.sidecar.batch import MemberFault

        if isinstance(error, MemberFault):
            return "poison-result"
        return f"window-{type(error).__name__}"

    def _batch_failure(self, tickets: list[Ticket], error: Exception) -> None:
        """Entry point for a FAILED window batch (BatchScheduler
        .on_batch_failure / InFlightBatch.on_failure): start a bounded
        bisection re-dispatch. The budget caps TOTAL re-dispatches for the
        whole failure tree — a genuine device/infra failure (every half
        keeps failing) degrades the window with per-member errors instead
        of looping, while a single poison member costs ~2·log2(B)
        re-dispatches to isolate."""
        budget = [max(4, 2 * max(len(tickets), 1).bit_length() + 2)]
        self.registry.counter(
            "window_failures_total",
            help="Batched dispatch windows that failed at dispatch or "
                 "harvest and entered bisection re-dispatch").inc()
        if device.is_oom(error) and self.slo_dump_dir:
            # a device OOM is an allocator post-mortem, not a poison world:
            # persist the per-allocation pprof snapshot BEFORE bisection
            # churns the heap, next to the SLO/backpressure evidence
            path = device.dump_memory_profile(
                self.slo_dump_dir, tag="window-oom", registry=self.registry)
            if path:
                with self._events_lock:
                    self.events.emit("HbmOomDump", "sidecar", "window-oom",
                                     message=path, now=_time.time())
        self._bisect(tickets, error, budget)

    def _bisect(self, tickets: list[Ticket], error: Exception,
                budget: list[int], tried: set | None = None) -> None:
        tried = tried if tried is not None else set()
        live = [t for t in tickets if not t.done.is_set()]
        if not live:
            return
        if len(live) == 1:
            t = live[0]
            if id(t) not in tried and budget[0] > 0:
                # one retry before conviction: a singleton that failed may
                # have hit a TRANSIENT fault, not be poison — multi-member
                # windows implicitly get this via their half re-dispatches,
                # a lone member (low traffic, lanes=1) must get it too
                tried.add(id(t))
                budget[0] -= 1
                self.registry.counter("window_redispatches_total").inc()
                try:
                    inflight = self._dispatch_batch(
                        live, bisect_budget=budget, bisect_tried=tried)
                except Exception as e:  # noqa: BLE001 — recurse: now convict
                    self._bisect(live, e, budget, tried)
                    return
                inflight.harvest()
                return
            # isolated AND retried: the poison member. Quarantine + error
            # THIS ticket; every healthy co-member was already served
            # bit-identically by its own half re-dispatch (vmap lanes are
            # independent).
            self._quarantine_tenant(t.tenant, self._fault_reason(error),
                                    error=error)
            t.resolve(error=error)
            return
        if budget[0] <= 0:
            self.registry.counter(
                "bisect_budget_exhausted_total",
                help="Bisection re-dispatch trees cut short by the retry "
                     "budget (a whole-device/infra failure pattern, not a "
                     "poison member) — remaining members degrade with "
                     "per-member errors").inc()
            for t in live:
                t.resolve(error=error)
            return
        mid = (len(live) + 1) // 2
        for half in (live[:mid], live[mid:]):
            budget[0] -= 1
            self.registry.counter(
                "window_redispatches_total",
                help="Half-window re-dispatches issued by bisection").inc()
            try:
                inflight = self._dispatch_batch(half, bisect_budget=budget,
                                                bisect_tried=tried)
            except Exception as e:  # noqa: BLE001 — recurse on this half
                self._bisect(half, e, budget, tried)
                continue
            # synchronous harvest: the failure path trades the pipeline
            # overlap for bounded isolation latency; a harvest failure
            # recurses through the InFlightBatch's on_failure (same budget)
            inflight.harvest()

    def _scheduler_crash(self, error: Exception) -> None:
        """Supervision escalation (BatchScheduler.on_crash): the dispatch
        thread died, so the serving path is gone — flip Health to
        NOT_SERVING (orchestration restarts the sidecar) and leave the
        evidence on metrics + events. Queued tickets were already failed
        and the admission queue closed by the scheduler's crash handler."""
        self._not_serving = f"batch scheduler crashed: {error!r}"
        self.registry.counter(
            "scheduler_crashes_total",
            help="Batch-scheduler serve-loop deaths (Health flips to "
                 "NOT_SERVING; queued tickets failed fast)").inc()
        with self._events_lock:
            self.events.emit("SchedulerCrash", "sidecar",
                             type(error).__name__, message=str(error),
                             now=_time.time())

    # ---- pre-admission validation (docs/ROBUSTNESS.md) ----

    def _note_validation_reject(self, tenant: str,
                                e: WorldValidationError) -> None:
        self.registry.counter(
            "world_validation_rejects_total",
            help="Requests rejected INVALID_ARGUMENT by pre-admission "
                 "world/param validation, by taxonomy reason",
        ).inc(reason=e.reason)
        with self._events_lock:
            self.events.emit("WorldValidationReject", tenant or "default",
                             e.reason, message=str(e), now=_time.time())

    def _validate_params(self, params: SimParams, kind: str) -> None:
        """Request-side structural screen (cheap scalar checks, every
        request): NaN/inf and negative values in the simulation parameters
        — a NaN threshold or template capacity would poison every lane of
        the window it joined."""
        import math

        def _bad_float(v) -> bool:
            return isinstance(v, float) and not math.isfinite(v)

        if kind == "down":
            th = params.threshold
            if not isinstance(th, (int, float)) or _bad_float(float(th)):
                raise WorldValidationError("nan", f"threshold={th!r}")
            if th < 0:
                raise WorldValidationError("negative-request",
                                           f"threshold={th!r}")
            return
        if params.max_new_nodes < 0:
            raise WorldValidationError(
                "negative-request", f"max_new_nodes={params.max_new_nodes}")
        for g in params.node_groups or []:
            tpl = (g or {}).get("template") or {}
            for field_name in ("capacity", "allocatable"):
                for k, v in (tpl.get(field_name) or {}).items():
                    if _bad_float(v):
                        raise WorldValidationError(
                            "nan", f"node group {g.get('id')!r}: "
                                   f"{field_name}[{k}]={v}")
                    if isinstance(v, (int, float)) and v < 0:
                        raise WorldValidationError(
                            "negative-request",
                            f"node group {g.get('id')!r}: "
                            f"{field_name}[{k}]={v}")

    def _validate_world(self, ts: _Tenant) -> None:
        """World-side structural screen, run BEFORE the world reaches a
        coalescing window; caller holds ts.lock. Cached per section-version
        tuple, so steady tenants re-validate for one tuple compare — the
        scan only runs when a delta actually changed a section. Rehydrated
        tenants were validated before their checkpoint."""
        if ts.rehydrated:
            return
        n, p, g = ts.state.counts()
        mn, mg, mp = self.max_world
        if n > mn or g > mg or p > mp:
            raise WorldValidationError(
                "oversize-world",
                f"counts nodes={n} groups={g} pods={p} exceed caps "
                f"nodes={mn} groups={mg} pods={mp}")
        key = ts.state.section_versions()
        if ts.validated_key == key:
            return
        groups_np = ts.export_np.get("groups")
        pods_np = ts.export_np.get("pods")
        for section, arr in (("groups", groups_np), ("pods", pods_np)):
            if arr is not None and int(arr["req"].min(initial=0)) < 0:
                raise WorldValidationError(
                    "negative-request",
                    f"{section} section carries a negative resource "
                    f"request (min={int(arr['req'].min())})")
        ts.validated_key = key

    # ---- HBM budget admission (docs/OBSERVABILITY.md "Device surfaces") ----

    def _hbm_limit(self) -> int:
        """The budget denominator: the configured override, else the
        device's own bytes_limit (probed once — memory_stats is a device
        call). 0 = unknown (CPU floor without an override) = budget off."""
        if self.hbm_limit_bytes:
            return self.hbm_limit_bytes
        if self._hbm_limit_cache is None:
            ms = device.memory_stats()
            self._hbm_limit_cache = int((ms or {}).get("bytes_limit") or 0)
        return self._hbm_limit_cache

    def _check_hbm_budget(self, ts: _Tenant) -> None:
        """Projected-residency admission gate; caller holds ts.lock with
        export_np fresh at class shape. A tenant whose lanes are already
        resident at the current keys re-admits free (steady path: two dict
        probes); a tenant about to upload projects its class-shaped export
        bytes on top of everyone else's live tagged bytes and is rejected
        with the `hbm-budget` validation reason when the total would breach
        frac·limit — a loud structured reject instead of an OOM that would
        take the whole coalescing window (and its innocent co-tenants)
        down."""
        if self.hbm_budget_frac <= 0 or device.LEDGER is None:
            return
        if ts.dev_keys and all(
                ts.dev_keys.get(s) == ts.export_keys.get(s)
                for s in ("nodes", "groups", "pods")):
            return      # resident at current keys: nothing new to admit
        limit = self._hbm_limit()
        if limit <= 0:
            return      # no denominator (CPU floor, no override): gate off
        projected = sum(
            int(v.nbytes)
            for s in ("nodes", "groups", "pods")
            for v in ts.export_np.get(s, {}).values())
        self._hbm_budget_screen(ts.tid, projected, limit)

    def _hbm_budget_screen(self, tid: str, projected: int,
                           limit: int) -> None:
        """The shared core: reject when `projected` bytes for `tid` on top
        of everyone ELSE's live tagged bytes would breach frac·limit."""
        own = device.LEDGER.tenant_bytes(tid)
        others = device.LEDGER.tagged_bytes() - own
        budget = self.hbm_budget_frac * limit
        if others + projected > budget:
            raise WorldValidationError(
                "hbm-budget",
                f"projected residency {projected}b for tenant "
                f"{tid or 'default'!r} on top of {others}b already "
                f"tagged would breach the HBM budget "
                f"({self.hbm_budget_frac:.2f} x {limit}b = {budget:.0f}b)")

    # ---- warm restart: checkpoint + rehydration (docs/ROBUSTNESS.md) ----

    @staticmethod
    def _export_digest(arrays: dict) -> str:
        """Canonical digest over a tenant's class-shaped export planes:
        name, dtype, shape and raw bytes of every section field in sorted
        order — the journal-style content digest a rehydrating sidecar
        verifies before trusting a record."""
        h = hashlib.sha256()
        for name in sorted(arrays):
            a = np.ascontiguousarray(arrays[name])
            h.update(name.encode())
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        return h.hexdigest()[:16]

    def checkpoint(self, dir_path: str) -> dict:
        """Persist per-tenant rehydration records (graceful shutdown /
        periodic checkpoint): class-shaped native export planes + metadata
        (world version, section versions, shape class, SLO budget, journal
        cursor, content digest). A restarted sidecar pointed at the same
        directory serves these tenants' batched sims again WITHOUT a full
        world re-send; constrained (aux-overlay) and empty tenants are
        skipped — they fall back to the existing full-encode re-send path."""
        import os

        os.makedirs(dir_path, exist_ok=True)
        written = []
        for tid in self.tenants():
            ts = self._tenant_peek(tid)
            if ts is None:
                continue
            with ts.lock:
                if ts.aux:
                    continue    # constrained tier: needs the native world
                if ts.state.version == 0 and not ts.rehydrated:
                    continue    # empty world: nothing to restore
                if not ts.rehydrated:
                    self._export_np(ts)     # refresh sections at class shape
                if np.any(ts.export_np["nodes"]["zone_id"] > 0):
                    # zoned worlds restart cold by design: the codec's
                    # zone-id interning is not recoverable from the export
                    # planes, and a rehydrated tenant's templates would be
                    # lowered against a FRESH id space — silently wrong
                    # multi-zone sims instead of a re-send
                    continue
                arrays = {f"{sec}:{k}": v
                          for sec in ("nodes", "groups", "pods")
                          for k, v in ts.export_np[sec].items()}
                n, p, g = (tuple(ts.rehydrated_meta["counts"])
                           if ts.rehydrated else ts.state.counts())
                cursor = (ts.journal.cursor()
                          if ts.journal is not None else None)
                meta = {
                    "tenant": tid,
                    "version": (ts.rehydrated_meta["version"]
                                if ts.rehydrated else ts.state.version),
                    "counts": [n, p, g],
                    "sections": {s: list(ts.export_keys[s])
                                 for s in ("nodes", "groups", "pods")},
                    "shape_class": ts.shape_class.key if ts.shape_class
                    else "",
                    "slo_budget_ms": self.slo.get(tid) or 0.0,
                    "journal_cursor": list(cursor) if cursor else None,
                    "digest": self._export_digest(arrays),
                }
            fname = ("rehydrate-"
                     + hashlib.sha1((tid or "default").encode())
                     .hexdigest()[:12] + ".npz")
            path = os.path.join(dir_path, fname)
            tmp = path + ".tmp.npz"
            with open(tmp, "wb") as f:
                np.savez(f, __meta__=np.frombuffer(
                    json.dumps(meta).encode(), dtype=np.uint8), **arrays)
            os.replace(tmp, path)
            written.append(tid)
        return {"dir": dir_path, "tenants": len(written), "ids": written}

    def _rehydrated_total(self):
        """The one accessor for `tenant_rehydrated_total` — whichever
        outcome fires first creates the family with its help text (the
        _phase_hist convention)."""
        return self.registry.counter(
            "tenant_rehydrated_total",
            help="Warm-restart rehydration outcomes per checkpoint record "
                 "(restored / digest-mismatch / error)")

    def _rehydrate(self, dir_path: str) -> None:
        """Load rehydration records written by checkpoint(). Every record
        is digest-verified before its planes are trusted; a mismatch (torn
        write, tampering, version skew) drops the record — that tenant is
        simply cold and re-sends its world like any new tenant."""
        import glob
        import os

        for path in sorted(glob.glob(os.path.join(dir_path,
                                                  "rehydrate-*.npz"))):
            try:
                with np.load(path) as z:
                    meta = json.loads(bytes(z["__meta__"].tobytes()))
                    arrays = {k: z[k] for k in z.files if k != "__meta__"}
                if self._export_digest(arrays) != meta["digest"]:
                    self.rehydration["digest_mismatch"] += 1
                    self._rehydrated_total().inc(outcome="digest-mismatch")
                    continue
                tid = meta["tenant"]
                ts = self._tenant(tid)
                with ts.lock:
                    ts.export_np = {"nodes": {}, "groups": {}, "pods": {}}
                    for k, v in arrays.items():
                        sec, field_name = k.split(":", 1)
                        ts.export_np[sec][field_name] = v
                    ts.export_keys = {s: tuple(v) for s, v in
                                      meta["sections"].items()}
                    ts.rehydrated = True
                    ts.rehydrated_meta = {"version": meta["version"],
                                          "counts": meta["counts"],
                                          "digest": meta["digest"]}
                    n, p, g = meta["counts"]
                    ts.shape_class = self.ladder.classify(n, g, p,
                                                          tenant=tid)
                    if meta.get("slo_budget_ms"):
                        self.slo.set(tid, float(meta["slo_budget_ms"]))
                    if ts.journal is not None:
                        ts.journal.record("rehydrate", meta["version"],
                                          digest=meta["digest"])
                self.rehydration["restored"] += 1
                self._rehydrated_total().inc(outcome="restored")
            except Exception:  # noqa: BLE001 — a bad record = a cold tenant
                self.rehydration["error"] += 1
                self._rehydrated_total().inc(outcome="error")

    def _exit_rehydration(self, ts: _Tenant) -> None:
        """First ApplyDelta after a warm restart: the native codec state is
        authoritative again — drop the restored planes and every cache
        keyed by the OLD process's section versions. Caller holds ts.lock."""
        ts.rehydrated = False
        ts.rehydrated_meta = None
        ts.export_keys = {}
        ts.export_np = {}
        ts.dev_keys = {}
        ts.dev_np = {}
        ts.serial_cache = None
        ts.validated_key = None

    # legacy single-tenant accessors (tests, conformance tooling)
    @property
    def state(self) -> NativeSnapshotState:
        return self._tenant("").state

    @property
    def _aux(self) -> dict:
        return self._tenant("").aux

    # ---- rpc: ApplyDelta ----

    def apply_delta(self, payload: bytes, tenant: str = "",
                    base_version: int | None = None) -> dict:
        from kubernetes_autoscaler_tpu.sidecar.wire import split_aux

        ts = self._tenant(tenant)
        with ts.lock:
            # snapshot-version pinning (wire.BASE_VERSION_HEADER): a delta
            # built against a version the server does not hold — most
            # importantly after a restart, when the codec is empty or the
            # tenant is serving a rehydrated export — must reject loudly
            # (INVALID_ARGUMENT, reason pinned by tests) instead of
            # silently applying against the wrong base snapshot
            if base_version is not None \
                    and int(base_version) != ts.state.version:
                e = WorldValidationError(
                    "section-version-mismatch",
                    f"delta built against version {base_version}, server "
                    f"holds {ts.state.version}"
                    + (" (rehydrated world — full re-send required)"
                       if ts.rehydrated else ""))
                self._note_validation_reject(tenant, e)
                raise e
            try:
                if faults.PLAN is not None:
                    payload = faults.PLAN.fire(
                        "codec_decode", tenant=tenant, payload=payload,
                        registry=self.registry)
                # split INSIDE the guarded region: any malformed trailer must
                # surface as an error dict, never an uncaught exception
                dense, aux = split_aux(payload)
                ts.state.apply_delta(dense)
                if ts.rehydrated:
                    # the codec state is authoritative again: drop the
                    # restored planes + the old process's cache keys
                    self._exit_rehydration(ts)
                if aux is not None:
                    ts.aux.update(aux.get("up", {}))
                    for uid in aux.get("del", []):
                        ts.aux.pop(uid, None)
                self._classify(ts)
                # provenance: the KAD1 payload IS the tenant's world delta —
                # journal its digest against the post-apply version
                if ts.journal is not None:
                    ts.journal.record(
                        "delta", ts.state.version, nbytes=len(payload),
                        digest=hashlib.sha256(payload).hexdigest()[:16])
                # the ack version is read UNDER ts.lock: a concurrent delta
                # for this tenant must not make the ack report a version
                # whose contents this caller never sent (clients pin
                # BASE_VERSION_HEADER from exactly this value)
                acked_version = ts.state.version
            except (ValueError, TypeError) as e:
                # codec rejections ride the error-dict contract (committed
                # goldens / Go shim compatibility) but still count into the
                # validation taxonomy — a chaos-truncated section lands here
                self._note_validation_reject(
                    tenant, WorldValidationError("codec", str(e)))
                return {"version": ts.state.version, "error": str(e)}
        # a successful re-send paroles a quarantined tenant early: the
        # quarantined world is gone, the tenant brought a new one
        self._parole_on_new_world(tenant)
        return {"version": acked_version, "error": ""}

    def _classify(self, ts: _Tenant) -> ShapeClass:
        """(Re)bucket a tenant's world; caller holds ts.lock. Counts within
        the current rungs keep the class — the hit counters measure exactly
        the "no new padded shape" guarantee."""
        if ts.rehydrated:
            # the class was restored (and re-seen on the ladder) at
            # rehydration time; the empty codec counts would misclassify
            return ts.shape_class
        if faults.PLAN is not None:
            faults.PLAN.fire("classify", tenant=ts.tid,
                             registry=self.registry)
        n, p, g = ts.state.counts()
        ts.shape_class = self.ladder.classify(n, g, p, tenant=ts.tid)
        return ts.shape_class

    # ---- serial world assembly (legacy + constrained + no-batching path) ----

    def _tensors_with_constraints(self, ts: _Tenant | None = None):
        """Exported tensors + the constraint overlay (side-channel specs +
        resident planes) — what encode_cluster produces natively. `ts`
        defaults to the default tenant (single-tenant callers)."""
        from kubernetes_autoscaler_tpu.sidecar.constraints import (
            attach_constraints,
        )

        if ts is None:
            ts = self._tenant("")
        if ts.rehydrated:
            # the serial/constrained tier assembles from the NATIVE world,
            # which a warm restart does not restore — the client must
            # re-send before this path can serve (FAILED cold fallback)
            raise WorldValidationError(
                "rehydration-pending",
                "tenant restored from checkpoint serves batched sims only; "
                "the serial/constrained path requires an ApplyDelta "
                "world re-send")
        # serial-path residency: the assembled world is immutable once
        # built, and every ApplyDelta bumps the codec version (aux rides
        # the same payload) — so (version, buckets) keys a safe cache and
        # steady serial/constrained tenants stop re-uploading per RPC
        key = (ts.state.version, self.node_bucket, self.group_bucket)
        if ts.serial_cache is not None and ts.serial_cache[0] == key:
            return ts.serial_cache[1]
        nt, gt, pt = ts.state.to_tensors(self.node_bucket, self.group_bucket)
        planes, has_c = None, False
        if ts.aux:
            gt, planes, has_c = attach_constraints(
                ts.state, gt, nt.n, ts.aux,
                max_zones=self.dims.max_zones)
        out = (nt, gt, pt, planes, has_c)
        if self.hbm_budget_frac > 0 and device.LEDGER is not None:
            # the serial/constrained tier passes the SAME admission gate as
            # the batched path. The check is post-assembly (the tier has no
            # class-shaped projection to price beforehand), so it refuses
            # RESIDENCY — the over-budget world is neither cached nor
            # tagged, and the transient arrays die with this call
            limit = self._hbm_limit()
            if limit > 0:
                self._hbm_budget_screen(
                    ts.tid, device.device_bytes((nt, gt, pt)), limit)
        ts.serial_cache = (key, out)
        if device.LEDGER is not None:
            # serial/constrained tenants hold their assembled world
            # resident too (the version-keyed cache above) — same owner
            # tag as the batched lanes, so the census sees every tier
            device.LEDGER.track("tenant_export",
                                f"{ts.tid or 'default'}/serial",
                                (nt, gt, pt), tenant=ts.tid)
        return out

    def _encode_groups(self, ts: _Tenant, params: SimParams, bucket: int = 8):
        """Lower a request's node-group templates against the tenant's zone
        interning. Returns (NodeGroupTensors, ids)."""
        from kubernetes_autoscaler_tpu.models.api import Node, Taint
        from kubernetes_autoscaler_tpu.models.encode import encode_node_groups
        from kubernetes_autoscaler_tpu.models.resources import (
            ExtendedResourceRegistry,
        )

        templates = []
        ids = []
        for g in params.node_groups or []:
            t = g["template"]
            node = Node(
                name=t.get("name", g["id"]),
                labels=t.get("labels", {}),
                capacity=t.get("capacity", {}),
                allocatable=t.get("allocatable", t.get("capacity", {})),
                taints=[Taint(**x) for x in t.get("taints", [])],
            )
            templates.append((node, g.get("max_new", 1000), g.get("price", 1.0)))
            ids.append(g["id"])
        groups = encode_node_groups(
            templates, ExtendedResourceRegistry(),
            # align template zone ids with the codec's interning so the
            # constrained tier compares zones in ONE id space
            ts.state.zone_table_for_templates(
                [t.zone() for t, _, _ in templates]),
            self.dims, bucket=bucket,
        )
        return groups, ids

    # ---- rpc: ScaleUpSim ----

    def _admit_sim(self, tenant: str, params: SimParams, kind: str) -> None:
        """The admission edge every sim passes BEFORE a ticket exists:
        dead-scheduler fail-fast (UNAVAILABLE — nothing would drain the
        queue), quarantine sentence check (FAILED_PRECONDITION), and the
        request-side structural validation (INVALID_ARGUMENT)."""
        if self._not_serving:
            raise SchedulerDown(self._not_serving)
        self._check_quarantine(tenant)
        try:
            self._validate_params(params, kind)
        except WorldValidationError as e:
            self._note_validation_reject(tenant, e)
            raise

    def scale_up_sim(self, params: SimParams, tenant: str = "") -> dict:
        entry_ns = _time.perf_counter_ns()
        self._admit_sim(tenant, params, "up")
        ts = self._tenant(tenant)
        if self._batchable(ts):
            return self._submit("up", ts, params, entry_ns)
        try:
            return self._scale_up_serial(ts, params, entry_ns)
        except WorldValidationError as e:
            self._note_validation_reject(tenant, e)
            raise

    def _scale_up_serial(self, ts: _Tenant, params: SimParams,
                         entry_ns: int = 0) -> dict:
        from kubernetes_autoscaler_tpu.ops.autoscale_step import scale_up_sim

        stamps = Stamps(entry=entry_ns or _time.perf_counter_ns())
        with ts.lock:
            self._classify(ts)
            nt, gt, pt, planes, has_c = self._tensors_with_constraints(ts)
            groups, ids = self._encode_groups(ts, params)
        stamps.enqueue = _time.perf_counter_ns()   # encode done
        with self._recompile_charge([ts]):
            out = self._timed_sim(
                lambda: scale_up_sim(nt, gt, pt, groups, self.dims,
                                     params.max_new_nodes, params.strategy,
                                     planes=planes, with_constraints=has_c),
                census=("scale_up_sim", scale_up_sim,
                        (nt, gt, pt, groups, self.dims,
                         params.max_new_nodes, params.strategy),
                        {"planes": planes, "with_constraints": has_c}),
                tenant=ts.tid if not ts.dispatched else "")
        stamps.dispatched = _time.perf_counter_ns()
        best = int(out.best)
        resp = {
            "best": ids[best] if 0 <= best < len(ids) else "",
            "options": [
                {
                    "id": ids[i],
                    "node_count": int(out.estimate.node_count[i]),
                    "pods": int(out.scores.pods[i]),
                    "waste": float(out.scores.waste[i]),
                    "price": float(out.scores.price[i]),
                    "valid": bool(out.scores.valid[i]),
                }
                for i in range(len(ids))
            ],
            "fits_existing": int(np.asarray(out.fits_existing).sum()),
            "remaining": int(np.asarray(out.remaining).sum()),
        }
        stamps.harvested = _time.perf_counter_ns()
        return self._finish_lifecycle(ts, stamps, resp)

    # ---- rpc: ScaleDownSim ----

    def scale_down_sim(self, params: SimParams, tenant: str = "") -> dict:
        entry_ns = _time.perf_counter_ns()
        self._admit_sim(tenant, params, "down")
        ts = self._tenant(tenant)
        if self._batchable(ts):
            return self._submit("down", ts, params, entry_ns)
        try:
            return self._scale_down_serial(ts, params, entry_ns)
        except WorldValidationError as e:
            self._note_validation_reject(tenant, e)
            raise

    def _scale_down_serial(self, ts: _Tenant, params: SimParams,
                           entry_ns: int = 0) -> dict:
        from kubernetes_autoscaler_tpu.ops.autoscale_step import scale_down_sim

        stamps = Stamps(entry=entry_ns or _time.perf_counter_ns())
        with ts.lock:
            self._classify(ts)
            nt, gt, pt, planes, has_c = self._tensors_with_constraints(ts)
        stamps.enqueue = _time.perf_counter_ns()   # encode done
        with self._recompile_charge([ts]):
            out = self._timed_sim(
                lambda: scale_down_sim(nt, gt, pt, params.threshold,
                                       planes=planes,
                                       max_zones=self.dims.max_zones,
                                       with_constraints=has_c),
                census=("scale_down_sim", scale_down_sim,
                        (nt, gt, pt, params.threshold),
                        {"planes": planes, "max_zones": self.dims.max_zones,
                         "with_constraints": has_c}),
                tenant=ts.tid if not ts.dispatched else "")
        stamps.dispatched = _time.perf_counter_ns()
        valid = np.asarray(nt.valid)
        resp = {
            "eligible": np.nonzero(np.asarray(out.eligible) & valid)[0].tolist(),
            "drainable": np.nonzero(
                np.asarray(out.removal.drainable) & valid)[0].tolist(),
            "utilization": [round(float(u), 4)
                            for u in np.asarray(out.utilization)[valid]],
        }
        stamps.harvested = _time.perf_counter_ns()
        return self._finish_lifecycle(ts, stamps, resp)

    # ---- rpc: WhatIf (counterfactual multiverse, docs/WHATIF.md) ----

    def what_if(self, request: bytes, tenant: str = "") -> dict:
        """Batched what-if evaluation over the tenant's resident world:
        B hypothesis lanes (lane 0 = the null hypothesis, bit-identical to
        a plain fused step on the unperturbed world) through ONE vmapped
        fused dispatch, optionally time-compressed over T rollout loops.

        The lane count is quantized up to a shape-class rung (padding with
        null lanes, masked out of the report), so variant-count churn rides
        the SAME compiled program — B lanes cost 0 steady-state recompiles,
        the same admission contract the tenant batcher gives worlds."""
        entry_ns = _time.perf_counter_ns()
        raw = json.loads(request.decode() or "{}")
        params = SimParams(
            max_new_nodes=raw.get("max_new_nodes", 256),
            strategy=raw.get("strategy", "least-waste"),
            threshold=raw.get("threshold", 0.5),
            node_groups=raw.get("node_groups"),
        )
        self._admit_sim(tenant, params, "up")
        ts = self._tenant(tenant)
        try:
            return self._what_if_serial(ts, raw, params, entry_ns)
        except WorldValidationError as e:
            self._note_validation_reject(tenant, e)
            raise

    def _what_if_serial(self, ts: _Tenant, raw: dict, params: SimParams,
                        entry_ns: int = 0) -> dict:
        from kubernetes_autoscaler_tpu.whatif import (
            generator as wgen,
            kernel as wkernel,
            report as wreport,
            variants as wvariants,
        )

        stamps = Stamps(entry=entry_ns or _time.perf_counter_ns())
        vs = [wvariants.VariantSpec.from_dict(d)
              for d in raw.get("variants", [])]
        rollout_t = int(raw.get("rollout", 0))
        with ts.lock:
            self._classify(ts)
            nt, gt, pt, planes, has_c = self._tensors_with_constraints(ts)
            groups, ids = self._encode_groups(ts, params)
        if has_c:
            # the multiverse lanes run the unconstrained fused body — same
            # split the tenant batcher makes (constraint overlays stay on
            # the serial planes-attached tier)
            raise WorldValidationError(
                "whatif-constrained",
                "what-if lanes do not carry constraint overlays; drop the "
                "aux constraints or use the serial sims")
        branch = wvariants.Branch(
            nodes=nt, specs=gt, scheduled=pt, groups=groups,
            limit_cap=np.minimum(
                np.asarray(groups.max_new, np.int64),
                np.int64(params.max_new_nodes)).astype(np.int32),
            statics={
                "dims": self.dims,
                "max_new_nodes": params.max_new_nodes,
                "max_pods_per_node": 128,
                "chunk": 32,
                "with_constraints": False,
            },
            meta={"source": "tenant", "tenant": ts.tid, "groups": ids},
        )
        # lane-count admission: pad B up to a rung so variant churn never
        # changes the dispatch shape (counted on the shape-class counters)
        want = len(vs) + (0 if vs and vs[0].is_null() else 1)
        lanes = wvariants.build_lanes(branch, vs, pad_to=rung(want, 4))
        stamps.enqueue = _time.perf_counter_ns()   # encode done

        st = lanes.statics
        kw = dict(dims=st["dims"], max_new_nodes=st["max_new_nodes"],
                  max_pods_per_node=st["max_pods_per_node"],
                  chunk=st["chunk"], strategy=params.strategy)
        margs = (lanes.nodes, lanes.specs, lanes.scheduled, lanes.groups,
                 lanes.limit_cap)
        with self._recompile_charge([ts]):
            decision, summary = self._timed_sim(
                lambda: wkernel.multiverse_step(*margs, **kw),
                census=("multiverse_step", wkernel.multiverse_step,
                        margs, kw),
                tenant=ts.tid if not ts.dispatched else "")
            traj = wl = None
            if rollout_t > 0:
                wl = wgen.WorkloadSpec.from_record(
                    raw.get("workload") or {"kind": "quiet"})
                g = int(np.asarray(lanes.specs.count).shape[1])
                n = int(np.asarray(lanes.nodes.valid).shape[1])
                adds, fails = wgen.generate_workload(wl, rollout_t, g, n)
                adds_b, fails_b = wgen.lane_workloads(
                    lanes.variants, adds, fails)
                rargs = margs + (lanes.thresholds, adds_b, fails_b)
                traj = self._timed_sim(
                    lambda: wkernel.rollout_multiverse(*rargs, **kw),
                    census=("rollout_multiverse",
                            wkernel.rollout_multiverse, rargs, kw),
                    tenant="")
        stamps.dispatched = _time.perf_counter_ns()
        resp = wreport.build_report(lanes, summary=summary,
                                    decision=decision, traj=traj,
                                    workload=wl)
        stamps.harvested = _time.perf_counter_ns()
        return self._finish_lifecycle(ts, stamps, resp)

    # ---- batched dispatch path ----

    def _batchable(self, ts: _Tenant) -> bool:
        # tenants with a constraint overlay need the planes-attached serial
        # tier; everyone else rides the vmapped batch (docs/SERVING.md)
        return self._scheduler is not None and not ts.aux

    def _export_np(self, ts: _Tenant):
        """Class-shaped numpy export, cached PLANE-GRANULARLY: each section
        (nodes/groups/pods) is keyed by its own codec section version + its
        class axis rung, so a delta that touched one section re-exports
        exactly that section (ISSUE 11 fix — the old (version, class) key
        re-materialized the whole export on any single-pod delta). Caller
        holds ts.lock. The geometric rungs make `pad_to(n, rung) == rung`,
        so every tenant of a class exports identical tensor shapes."""
        if ts.rehydrated:
            # warm restart: serve the checkpoint-restored planes as-is —
            # the empty codec must not overwrite them; the first ApplyDelta
            # re-send exits this mode (_exit_rehydration)
            return ts.export_np["nodes"], ts.export_np["groups"], \
                ts.export_np["pods"]
        sc = self._classify(ts)
        sv = ts.state.section_versions()
        refreshed = []
        grew = False
        for section, svi, rung_n, exporter in (
                ("nodes", sv[0], sc.nodes, ts.state.export_nodes),
                ("groups", sv[1], sc.groups, ts.state.export_groups),
                ("pods", sv[2], sc.pods, ts.state.export_pods)):
            key = (svi, rung_n)
            prev = ts.export_keys.get(section)
            if prev != key:
                ts.export_np[section] = exporter(rung_n)
                ts.export_keys[section] = key
                refreshed.append(section)
                grew = grew or (prev is not None and prev[1] != rung_n)
        if refreshed:
            self._note_encode(ts, refreshed, grew)
        return ts.export_np["nodes"], ts.export_np["groups"], \
            ts.export_np["pods"]

    def _note_encode(self, ts: _Tenant, refreshed: list[str],
                     grew: bool) -> None:
        """The reasoned encode counter, sidecar edition: mode=delta when
        the plane-granular cache reused ≥1 resident section, mode=full when
        every section re-materialized (cause=initial on the first export,
        shape_overflow when an axis crossed its rung — a new padded shape —
        churn otherwise). Tenant-labelled; stale-zeroed by drop_tenant."""
        from kubernetes_autoscaler_tpu.models.world_store import ENCODES_HELP

        first = len(ts.encode_modes) == 0
        mode = "full" if len(refreshed) == 3 else "delta"
        cause = ("initial" if first
                 else "shape_overflow" if grew else "churn")
        key = f"{mode}/{cause}"
        ts.encode_modes[key] = ts.encode_modes.get(key, 0) + 1
        labels = {"tenant": ts.tid} if ts.tid else {}
        self.registry.counter("encoder_encodes_total",
                              help=ENCODES_HELP).inc(mode=mode, cause=cause,
                                                     **labels)

    def _export_dev(self, ts: _Tenant):
        """The tenant's RESIDENT device lanes: per-section device arrays
        refreshed only when that section's numpy export refreshed. The
        upload is the ONLY h2d movement on the batched path — stacking
        happens on-device (batch.stack_fields uses jnp.stack for device
        lanes) — so a steady window moves zero world bytes, and a one-pod
        delta uploads one tenant's dirty sections, not the whole stack.
        Caller holds ts.lock."""
        import jax.numpy as jnp

        from kubernetes_autoscaler_tpu.models.world_store import H2D_HELP

        self._export_np(ts)
        if faults.PLAN is not None:
            faults.PLAN.fire("h2d", tenant=ts.tid, registry=self.registry)
        uploaded = 0
        for section in ("nodes", "groups", "pods"):
            key = ts.export_keys[section]
            if ts.dev_keys.get(section) != key:
                np_dict = ts.export_np[section]
                ts.dev_np[section] = {k: jnp.asarray(v)
                                      for k, v in np_dict.items()}
                ts.dev_keys[section] = key
                uploaded += sum(int(v.nbytes) for v in np_dict.values())
                if device.LEDGER is not None:
                    # HBM residency ledger: the tenant's resident lanes,
                    # per section (a refreshed section re-registers; the
                    # old arrays expire from the census by weakref)
                    device.LEDGER.track(
                        "tenant_export",
                        f"{ts.tid or 'default'}/{section}",
                        ts.dev_np[section], tenant=ts.tid)
        if uploaded:
            labels = {"tenant": ts.tid} if ts.tid else {}
            self.registry.counter("world_store_h2d_bytes_total",
                                  help=H2D_HELP).inc(uploaded, **labels)
            self.registry.counter(
                "device_transfer_bytes_total",
                help="Host↔device bytes moved by the serving path, by "
                     "direction (h2d = resident-lane section uploads; "
                     "d2h = batched result fetches)",
            ).inc(uploaded, direction="h2d")
        return ts.dev_np["nodes"], ts.dev_np["groups"], ts.dev_np["pods"]

    def _ng_np(self, ts: _Tenant, params: SimParams):
        """Per-tenant cache of lowered request templates (ids + numpy AND
        device NodeGroupTensors fields at the NG rung): steady-state
        tenants re-send the same node-group ladder every loop, and the
        device field map lets the batched path stack template lanes
        on-device with zero re-upload (encode_node_groups already uploaded
        them once — the map just re-exposes those arrays per field)."""
        from kubernetes_autoscaler_tpu.sidecar.batch import nodegroup_np

        ng_rung = rung(max(len(params.node_groups or []), 1), _NG_RUNG_BASE)
        digest = hashlib.sha1(json.dumps(
            params.node_groups or [], sort_keys=True).encode()).hexdigest()
        key = (digest, ng_rung, ts.state.num_zones())
        hit = ts.ng_cache.get(key)
        if hit is not None:
            ts.ng_cache.move_to_end(key)
            return hit
        groups, ids = self._encode_groups(ts, params, bucket=ng_rung)
        ng_dev = {
            "cap": groups.cap, "label_hash": groups.label_hash,
            "taint_exact": groups.taint_exact, "taint_key": groups.taint_key,
            "zone_id": groups.zone_id, "max_new": groups.max_new,
            "price_per_node": groups.price_per_node, "valid": groups.valid,
        }
        val = (nodegroup_np(groups), ids, ng_rung, digest, ng_dev)
        ts.ng_cache[key] = val
        while len(ts.ng_cache) > 8:
            ts.ng_cache.popitem(last=False)
        return val

    def _submit(self, kind: str, ts: _Tenant, params: SimParams,
                entry_ns: int = 0) -> dict:
        from kubernetes_autoscaler_tpu.sidecar import batch as b

        stamps = Stamps(entry=entry_ns or _time.perf_counter_ns())
        with ts.lock:
            # pre-admission world validation: a structurally bad world
            # (negative requests, oversize counts) never reaches a
            # coalescing window where it could take co-tenants down
            self._export_np(ts)
            try:
                self._validate_world(ts)
                # projected-residency screen rides the same taxonomy: a
                # world too big for the HBM budget must never reach a
                # window where its upload OOMs innocent co-tenants
                self._check_hbm_budget(ts)
            except WorldValidationError as e:
                self._note_validation_reject(ts.tid, e)
                raise
            # the RESIDENT device lanes: dirty sections upload here (the
            # only world h2d on the batched path); untouched sections and
            # steady tenants reuse their device arrays as-is
            nodes, groups, pods = self._export_dev(ts)
            sc = ts.shape_class
            if kind == "up":
                _ng, ids, ng_rung, ng_digest, ng_dev = self._ng_np(ts, params)
                lane = b.UpLane(nodes=nodes, groups=groups, pods=pods,
                                ng=ng_dev, ids=ids)
                fp = (ts.tid, ts.state.version, ng_rung, ng_digest)
                key = ("up", sc, ng_rung, params.max_new_nodes,
                       params.strategy)
            else:
                lane = b.DownLane(nodes=nodes, groups=groups, pods=pods,
                                  threshold=float(params.threshold),
                                  valid_np=ts.export_np["nodes"]["valid"])
                fp = (ts.tid, ts.state.version)
                key = ("down", sc, self.dims.max_zones)
        tracer = trace.current_tracer()
        ticket = Ticket(tenant=ts.tid, kind=kind, key=key, lane=lane, fp=fp,
                        trace_id=tracer.trace_id if tracer else None,
                        stamps=stamps)
        try:
            self._queue.submit(ticket)      # raises QueueFull on overload
        except QueueFull as e:
            self._note_reject(ts.tid, e)
            raise
        resp = ticket.wait(self.ticket_timeout_s)
        stamps.woke = _time.perf_counter_ns()
        bi = ticket.batch_info
        if tracer is not None and bi is not None:
            # the coalescing window on the member's own timeline: one
            # `batch` span carrying class/occupancy/member ids, and the RPC
            # span annotated with the batch id so the Perfetto dump links
            # member ↔ batch both ways
            tracer.add_span(
                "batch", cat="sidecar", begin_abs_ns=bi["t0_ns"],
                dur_ns=bi.get("dur_ns", 0), batch_id=bi["batch_id"],
                shape_class=bi["shape_class"], occupancy=bi["occupancy"],
                lanes=bi["lanes"], members=bi["members"])
            tracer.annotate(batch=bi["batch_id"])
        return self._finish_lifecycle(
            ts, stamps, resp, batch_id=bi["batch_id"] if bi else None)

    def _phase_hist(self):
        """The one accessor for `request_phase_seconds` — every touch
        (observe OR a drop_tenant sweep) passes the sub-10µs bucket ladder,
        so whichever call creates the family creates it right (Registry
        only honors buckets on first touch)."""
        return self.registry.histogram(
            "request_phase_seconds",
            help="Per-request serving-lifecycle phase wall clock "
                 "(encode/queue/form/stack/dispatch/harvest/assembly/"
                 "reply — contiguous, sums to e2e)",
            buckets=REQUEST_PHASE_BUCKETS)

    def _finish_lifecycle(self, ts: _Tenant, stamps: Stamps, resp: dict,
                          batch_id: str | None = None) -> dict:
        """One completed request's lifecycle → three surfaces at once:
        per-tenant `request_phase_seconds{phase,tenant}` histograms, a
        closed `lifecycle` span tree on the request's trace, and the
        `lifecycle` block in the response JSON (so the CLIENT can show
        server-side queue time distinct from network time). The phases are
        contiguous intervals — they sum to e2e by construction, which CI
        asserts within tolerance on the bench smoke."""
        labels = {"tenant": ts.tid} if ts.tid else {}
        for name, dur_ns in stamps.phases_ns().items():
            self._phase_hist().observe(dur_ns / 1e9, phase=name, **labels)
        ts.lat_ms.append(stamps.e2e_ns() / 1e6)
        # verdict provenance: digest the response BEFORE the lifecycle block
        # rides in (timings are observation, not decision)
        from kubernetes_autoscaler_tpu.replay.journal import digest_of

        if ts.journal is not None:
            ts.journal.record("verdict", ts.state.version,
                              digest=digest_of(resp))
        tracer = trace.current_tracer()
        if isinstance(resp, dict):
            resp["lifecycle"] = lifecycle_block(
                stamps, batch_id=batch_id,
                trace_id=tracer.trace_id if tracer else None)
        add_lifecycle_spans(tracer, stamps, tenant=ts.tid or "default",
                            **({"batch_id": batch_id} if batch_id else {}))
        return resp

    def _timed_sim(self, fn, census=None, tenant: str = ""):
        """Run one sim dispatch with compile accounting: when the call grew
        a jit cache, its wall clock is (almost entirely) XLA compilation —
        counted as `sim_compiles_total` / `sim_compile_seconds_total` so
        compile stalls on the serving path are a first-class series, not a
        mystery latency spike.

        `census` = (label, jit_fn, args, kwargs): on a compile, the
        compile CENSUS records the variant — which entry point, which shape
        signature, charged to which (fresh) tenant, at what flop/temp-HBM
        cost — so the bare counters resolve to named executables
        (metrics/device.CompileCensus; Statusz + /metrics).

        An ARMED device profiler (breach-triggered or Profilez-armed) wraps
        exactly this dispatch in a bounded jax.profiler.trace session;
        disarmed costs two loads (the PR 12 guard contract)."""
        prof = device.PROFILER
        run = (lambda: prof.capture(fn)[0]) \
            if prof is not None and prof.armed else fn
        c0 = self._sim_cache_size()
        t0 = _time.perf_counter()
        out = run()
        grew = self._sim_cache_size() - c0
        if grew > 0:
            self.registry.counter(
                "sim_compiles_total",
                help="XLA programs compiled by serving dispatches").inc(grew)
            self.registry.counter(
                "sim_compile_seconds_total",
                help="Wall clock of serving dispatches that compiled "
                     "(≈ compile time)").inc(_time.perf_counter() - t0)
            if census is not None:
                label, jfn, cargs, ckw = census
                self.census.record(label, jfn, cargs, ckw, tenant=tenant)
        return out

    def _note_reject(self, tenant: str, e: QueueFull) -> None:
        """Admission-reject accounting, split by WHY (ISSUE 8 fix: a
        RESOURCE_EXHAUSTED previously carried retry-after but no metric
        distinguishing queue overload from a full tenant table)."""
        self.registry.counter(
            "admission_rejects_total",
            help="Requests rejected RESOURCE_EXHAUSTED, by reason "
                 "(queue-full = transient overload; tenant-cap = resident "
                 "world table full, retry alone never helps)",
        ).inc(reason=e.reason)
        with self._events_lock:
            self.events.emit("AdmissionReject", tenant or "default",
                             e.reason, message=str(e), now=_time.time())

    def _sim_cache_size(self) -> int:
        from kubernetes_autoscaler_tpu.ops import autoscale_step as a
        from kubernetes_autoscaler_tpu.whatif import kernel as w

        return sum(f._cache_size() for f in (
            a.scale_up_sim, a.scale_down_sim,
            a.scale_up_sim_batch, a.scale_down_sim_batch,
            w.multiverse_step, w.rollout_fused, w.rollout_multiverse))

    def _account_new_tenant(self, tenants: list[_Tenant],
                            recompiles: int) -> None:
        """`recompiles_per_new_tenant`: XLA programs compiled by the first
        dispatch that served each newly admitted tenant. A tenant landing in
        a warm shape class costs 0 — the observable form of the ≈0-recompile
        guarantee (CI-asserted, like PR 2's steady_state_recompiles)."""
        fresh = [t for t in tenants if not t.dispatched]
        for t in tenants:
            t.dispatched = True
        if fresh:
            self.registry.gauge(
                "recompiles_per_new_tenant",
                help="XLA compiles triggered by the dispatch that first "
                     "served a newly admitted tenant (0 = it joined a warm "
                     "shape class)",
            ).set(recompiles / len(fresh))

    @contextlib.contextmanager
    def _recompile_charge(self, tenants: list[_Tenant]):
        """Wrap a dispatch that first serves a fresh tenant in the
        (cache-size, dispatch, cache-size) charge window, under
        _account_lock — the jit caches are process global, so a concurrent
        dispatch on another thread would otherwise have its compiles
        attributed to whichever tenant measured last. Steady dispatches
        (every member already served) skip the lock AND the cache walks:
        nothing to charge, no serialization on the hot path."""
        if all(t.dispatched for t in tenants):
            yield
            return
        with self._account_lock:
            before = self._sim_cache_size()
            yield
            self._account_new_tenant(
                tenants, self._sim_cache_size() - before)

    def _dispatch_batch(self, tickets: list[Ticket],
                        bisect_budget: list | None = None,
                        bisect_tried: set | None = None):
        """Scheduler-thread entry: stack one batch-compatible ticket run,
        dispatch the vmapped program, issue the async result fetch. Returns
        the in-flight handle the scheduler harvests one window later.

        `bisect_budget`/`bisect_tried` are set on bisection re-dispatches:
        the in-flight handle's failure path then recurses into `_bisect`
        with the SAME bounded budget (and singleton-retry history) instead
        of starting a fresh tree."""
        import jax.numpy as jnp

        from kubernetes_autoscaler_tpu.ops import autoscale_step as a
        from kubernetes_autoscaler_tpu.sidecar import batch as b
        from kubernetes_autoscaler_tpu.ops.hostfetch import fetch_pytree_async

        kind = tickets[0].kind
        key = tickets[0].key
        tenants = [t.tenant for t in tickets]
        t0 = _time.perf_counter_ns()
        for t in tickets:
            t.stamps.stack0 = t0
        members = [t.lane for t in tickets]
        if faults.PLAN is not None:
            faults.PLAN.fire("stack", tenants=tenants,
                             registry=self.registry)
        lanes_list = b.pad_lanes(members, self.batch_lanes)
        stack_key = (key, tuple(t.fp for t in tickets))

        # NOTE on h2d accounting: the lanes are the tenants' RESIDENT
        # device arrays (_export_dev), so a stack-cache miss re-stacks
        # on-device and moves no world bytes — uploads were already
        # charged, per dirty section, when the lanes refreshed.
        tenant_objs = [self._tenant(t.tenant) for t in tickets]
        # census attribution: a compile in this window is charged to the
        # fresh tenant it first serves (the recompiles_per_new_tenant
        # contract, now carrying a NAME); steady windows charge nobody
        fresh_tenant = next(
            (o.tid for o in tenant_objs if not o.dispatched), "")
        with self._recompile_charge(tenant_objs):
            if faults.PLAN is not None:
                faults.PLAN.fire("dispatch", tenants=tenants,
                                 registry=self.registry)
            if kind == "up":
                nt, gt, pt, gr = self._stack_cache.get(
                    stack_key, lambda: b.stack_up_lanes(lanes_list))
                stack1 = _time.perf_counter_ns()
                _, _, _, max_new_nodes, strategy = key
                out = self._timed_sim(
                    lambda: a.scale_up_sim_batch(nt, gt, pt, gr, self.dims,
                                                 max_new_nodes, strategy),
                    census=("scale_up_sim_batch", a.scale_up_sim_batch,
                            (nt, gt, pt, gr, self.dims, max_new_nodes,
                             strategy), {}),
                    tenant=fresh_tenant)
                fetch_tree = {
                    "best": out.best,
                    "node_count": out.estimate.node_count,
                    "pods": out.scores.pods,
                    "waste": out.scores.waste,
                    "price": out.scores.price,
                    "valid": out.scores.valid,
                    "fits": out.fits_existing.sum(-1),
                    "remaining": out.remaining.sum(-1),
                }
                assemble = lambda host: b.assemble_members(  # noqa: E731
                    host, members, tenants, b.assemble_up_one)
            else:
                nt, gt, pt = self._stack_cache.get(
                    stack_key, lambda: b.stack_down_lanes(lanes_list)[:3])
                stack1 = _time.perf_counter_ns()
                th = jnp.asarray(
                    [ln.threshold for ln in lanes_list], jnp.float32)
                out = self._timed_sim(
                    lambda: a.scale_down_sim_batch(
                        nt, gt, pt, th, max_zones=self.dims.max_zones),
                    census=("scale_down_sim_batch", a.scale_down_sim_batch,
                            (nt, gt, pt, th),
                            {"max_zones": self.dims.max_zones}),
                    tenant=fresh_tenant)
                fetch_tree = {
                    "eligible": out.eligible,
                    "drainable": out.removal.drainable,
                    "util": out.utilization,
                }
                assemble = lambda host: b.assemble_members(  # noqa: E731
                    host, members, tenants, b.assemble_down_one)
        occupancy = len(tickets)
        self.occupancies.append(occupancy)
        self.registry.counter(
            "batched_dispatches_total",
            help="Coalesced vmapped sim dispatches, by kind").inc(kind=kind)
        self.registry.histogram(
            "batch_occupancy",
            help="Member tenants per coalesced dispatch (lanes minus "
                 "padding)",
            buckets=tuple(float(x) for x in range(1, 33)),
        ).observe(float(occupancy), kind=kind)
        # occupancy over time as a scrapeable gauge (device-utilization
        # accounting): what fraction of the compiled lane width carried
        # real member tenants on the latest dispatch
        self.registry.gauge(
            "batch_occupancy_ratio",
            help="Members / compiled lanes of the latest coalesced "
                 "dispatch (1.0 = no padding waste)",
        ).set(occupancy / self.batch_lanes, kind=kind)
        d2h0 = self.phases.events.get("batched_fetch_bytes_moved", 0)
        fetch = fetch_pytree_async(fetch_tree, phases=self.phases)
        self.registry.counter("device_transfer_bytes_total").inc(
            self.phases.events.get("batched_fetch_bytes_moved", 0) - d2h0,
            direction="d2h")
        dispatched_ns = _time.perf_counter_ns()
        for t in tickets:
            t.stamps.stack1 = stack1
            t.stamps.dispatched = dispatched_ns
        batch_info = {
            "batch_id": uuid.uuid4().hex[:8],
            "kind": kind,
            "shape_class": tickets[0].key[1].key,
            "occupancy": occupancy,
            "lanes": self.batch_lanes,
            "members": [{"tenant": t.tenant, "trace_id": t.trace_id}
                        for t in tickets],
            "t0_ns": t0,
        }
        # failure wiring (docs/ROBUSTNESS.md): a batch-level harvest
        # failure enters (or continues) the bounded bisection tree; a
        # per-member poison result quarantines exactly that tenant
        on_failure = ((lambda tks, e: self._bisect(
            tks, e, bisect_budget, bisect_tried))
            if bisect_budget is not None else self._batch_failure)
        # shadow audit rides on_done (post-harvest, every member already
        # resolved — audit latency never sits on a client's critical
        # path); bisection re-dispatches are excluded: their windows exist
        # to LOCALIZE a failure, not to re-verify healthy lanes
        on_done = (self._shadow_audit_window
                   if self.shadow_audit and bisect_budget is None else None)
        return b.InFlightBatch(
            tickets, fetch, assemble, batch_info,
            on_done=on_done,
            on_failure=on_failure,
            on_member_fault=lambda t, e: self._quarantine_tenant(
                t.tenant, self._fault_reason(e), error=e))

    # ---- online shadow audit (one round-robin lane per window) ----------

    def _audit_reference(self, t: "Ticket") -> dict:
        """The independent reference verdict for one member: the SERIAL
        (unbatched) sim program over the member's own lane tensors,
        assembled into the same JSON shape the batched path produced.
        Different compiled executable, same inputs — bit-identical by the
        serial≡batched contract (tests/test_batched_sim.py), so any
        difference is backend corruption, not modeling."""
        from kubernetes_autoscaler_tpu.ops import autoscale_step as a
        from kubernetes_autoscaler_tpu.sidecar import batch as b

        ln = t.lane
        nt = b.node_tensors(ln.nodes)
        gt = b.podgroup_tensors(ln.groups)
        pt = b.sched_tensors(ln.pods)
        if t.kind == "up":
            gr = b.nodegroup_tensors(ln.ng)
            _, _, _, max_new, strategy = t.key
            out = a.scale_up_sim(nt, gt, pt, gr, self.dims, max_new,
                                 strategy)
            host = {
                "best": np.asarray(out.best)[None],
                "node_count": np.asarray(out.estimate.node_count)[None],
                "pods": np.asarray(out.scores.pods)[None],
                "waste": np.asarray(out.scores.waste)[None],
                "price": np.asarray(out.scores.price)[None],
                "valid": np.asarray(out.scores.valid)[None],
                "fits": np.asarray(out.fits_existing.sum(-1))[None],
                "remaining": np.asarray(out.remaining.sum(-1))[None],
            }
            return b.assemble_up_one(host, ln, 0)
        out = a.scale_down_sim(nt, gt, pt, ln.threshold,
                               max_zones=self.dims.max_zones)
        host = {
            "eligible": np.asarray(out.eligible)[None],
            "drainable": np.asarray(out.removal.drainable)[None],
            "util": np.asarray(out.utilization)[None],
        }
        return b.assemble_down_one(host, ln, 0)

    def _shadow_audit_window(self, batch) -> None:
        """InFlightBatch.on_done hook (scheduler thread): pick ONE resolved
        member of this window (round-robin over members, so every tenant's
        lane is audited over time), snapshot its verdict, and hand it to
        the audit worker — the scheduler thread pays a dict copy, never a
        reference re-sim. Best-effort by contract."""
        from kubernetes_autoscaler_tpu.audit.shadow import AUDIT_CHECKS_HELP

        try:
            tickets = [t for t in batch.tickets
                       if isinstance(t.result, dict)]
            if not tickets or self._audit_q is None:
                return
            t = tickets[self._audit_rr % len(tickets)]
            self._audit_rr += 1
            # verdict snapshot, taken NOW: the handler thread this ticket
            # woke is concurrently annotating the same dict in place with
            # per-request metadata (`lifecycle` stamps) the reference path
            # never computes — retry the copy across that single-key
            # insert instead of letting RuntimeError eat the audit
            got = None
            for _attempt in range(3):
                try:
                    got = {k: v for k, v in (t.result or {}).items()
                           if k != "lifecycle"}
                    break
                except RuntimeError:
                    continue
            counter = self.registry.counter("shadow_audit_checks_total",
                                            help=AUDIT_CHECKS_HELP)
            if got is None:
                counter.inc(surface=f"sidecar-{t.kind}",
                            outcome="skipped", tenant=t.tenant)
                return
            try:
                self._audit_q.put_nowait(
                    (t, got, dict(batch.batch_info)))
            except _queue.Full:
                # the worker is behind (a reference compile in flight):
                # drop THIS window's audit, accounted — never block the
                # scheduler loop on verification
                counter.inc(surface=f"sidecar-{t.kind}",
                            outcome="skipped", tenant=t.tenant)
        except Exception:  # noqa: BLE001 — best-effort evidence path
            pass

    def _audit_loop(self) -> None:
        while not self._audit_stop.is_set():
            try:
                item = self._audit_q.get(timeout=0.2)
            except _queue.Empty:
                continue
            t0 = _time.perf_counter_ns()
            try:
                self._audit_one(*item)
            except Exception:  # noqa: BLE001 — the worker must survive
                pass
            finally:
                self.audit_overhead_ns += _time.perf_counter_ns() - t0
                self._audit_q.task_done()

    def audit_quiesce(self, timeout_s: float = 30.0) -> bool:
        """Wait for every enqueued audit to finish (tests/bench — audits
        run async on the worker; asserting counters right after a window
        resolves would race it). True when the queue drained."""
        if self._audit_q is None:
            return True
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if self._audit_q.unfinished_tasks == 0:
                return True
            _time.sleep(0.02)
        return False

    def _audit_one(self, t: "Ticket", got: dict, batch_info: dict) -> None:
        """Worker-thread body: the serial reference re-sim + compare for
        one snapshotted member verdict."""
        from kubernetes_autoscaler_tpu.audit.shadow import AUDIT_CHECKS_HELP

        surface = f"sidecar-{t.kind}"
        # compile-attribution hygiene: only the FIRST audit at a given
        # batch-compat key can compile the serial reference variant — that
        # one runs under _account_lock so the new-tenant charge window
        # never sees audit-grown jit cache; every later audit at the key
        # is a cache hit by construction and runs lock-free (the audit
        # worker must never stall the scheduler's fresh-tenant windows in
        # steady state)
        if t.key in self._audit_warmed:
            ref = self._audit_reference(t)
        else:
            with self._account_lock:
                ref = self._audit_reference(t)
            self._audit_warmed.add(t.key)
        if ref == got:
            self.registry.counter(
                "shadow_audit_checks_total",
                help=AUDIT_CHECKS_HELP).inc(
                surface=surface, outcome="ok", tenant=t.tenant)
            return
        self.audit_divergences += 1
        diff = sorted(k for k in set(ref) | set(got)
                      if ref.get(k) != got.get(k))
        self.audit_last = {
            "tenant": t.tenant or "default", "kind": t.kind,
            "batch": batch_info.get("batch_id", ""),
            "fields": diff, "trace": t.trace_id or "",
        }
        self.registry.counter(
            "shadow_audit_checks_total", help=AUDIT_CHECKS_HELP).inc(
            surface=surface, outcome="divergent", tenant=t.tenant)
        with self._events_lock:
            self.events.emit(
                "AuditDivergence", obj=t.tenant or "default",
                reason=surface,
                message=(f"batched verdict diverged from the serial "
                         f"reference (fields: {', '.join(diff)}; "
                         f"batch {self.audit_last['batch']}) — "
                         f"backend fault, tenant NOT quarantined"))
        # evidence: a retained trace (reason=audit) + the tenant's
        # provenance ring persisted next to the SLO dumps
        tr = trace.Tracer(trace_id=t.trace_id or None)
        tr.add_span("shadow_audit_divergence", cat="audit",
                    tenant=t.tenant or "default", kind=t.kind,
                    batch=self.audit_last["batch"],
                    fields=diff)
        snap = tr.snapshot()
        snap["tenant"] = t.tenant
        self.tail.offer(snap, 0.0, reason="audit")
        if self.slo_dump_dir:
            ts = self._tenant_peek(t.tenant)
            if ts is not None and ts.journal is not None:
                try:
                    os.makedirs(self.slo_dump_dir, exist_ok=True)
                    ts.journal.maybe_persist(self.slo_dump_dir,
                                             reason="audit_divergence")
                except OSError:
                    pass

    def audit_stats(self) -> dict:
        checks: dict[str, float] = {}
        for key, v in self.registry.counter(
                "shadow_audit_checks_total").items():
            d = dict(key)
            k = f"{d.get('surface', '?')}/{d.get('outcome', '?')}"
            checks[k] = checks.get(k, 0.0) + v
        return {
            "enabled": self.shadow_audit,
            "checks": checks,
            "divergences": self.audit_divergences,
            "last": self.audit_last,
            "overhead_ms": round(self.audit_overhead_ns / 1e6, 3),
        }

    def hbm_stats(self) -> dict:
        """The residency-ledger reconciliation, published into this
        service's registry: tagged census per (owner, tenant), the device's
        own totals (hbm_bytes_in_use/limit/headroom) — or the host-RSS
        fallback with `source: host-fallback` on backends without
        memory_stats. Never null (the bench --device-stats contract)."""
        if device.LEDGER is None:
            return {"source": "disabled"}
        return device.LEDGER.reconcile(registry=self.registry,
                                       hbm_limit_bytes=self.hbm_limit_bytes)

    def profilez(self, payload: bytes = b"") -> dict:
        """The armed-handle device-profiler RPC (the /snapshotz pattern):
        body `{"arm": true, "reason": "..."}` arms the profiler — the NEXT
        sim dispatch is captured into a trace-id-stamped directory; an
        empty body just reports state. Rate limits apply to manual arms
        exactly like breach arms."""
        req = {}
        if payload:
            try:
                req = json.loads(payload.decode() or "{}")
            except ValueError:
                return {"enabled": device.PROFILER is not None,
                        "error": "malformed Profilez body (want JSON)"}
        prof = device.PROFILER
        if prof is None:
            return {"enabled": False,
                    "error": "no device profiler installed "
                             "(--device-profile-dir)"}
        out = {"enabled": True}
        if req.get("arm"):
            tracer = trace.current_tracer()
            out["armed_now"] = prof.arm(
                str(req.get("reason") or "manual"),
                trace_id=tracer.trace_id if tracer else "")
        out.update(prof.stats())
        return out

    def batch_stats(self) -> dict:
        """Bench/ops view of the batching layer."""
        occ = list(self.occupancies)
        return {
            "windows": self._scheduler.windows if self._scheduler else 0,
            "batches": self._scheduler.batches if self._scheduler else 0,
            "occupancy_p50": (float(np.percentile(occ, 50)) if occ else None),
            "stack_cache": (
                {"hits": self._stack_cache.hits,
                 "misses": self._stack_cache.misses}
                if self._scheduler else None),
            "shape_class_hits": self.ladder.hits,
            "shape_class_misses": self.ladder.misses,
            "queue_rejected": self._queue.rejected if self._queue else 0,
            "recompiles_per_new_tenant": self.registry.gauge(
                "recompiles_per_new_tenant").value(),
            "dispatch_gap": self.gap_stats(),
            "tail_sampler": self.tail.stats(),
        }

    def gap_stats(self) -> dict:
        """Dispatch-gap summary: the `pipelined`+`stall` population is the
        device-idle-while-work-existed distribution (≈0 under pipelining);
        `idle` is arrival-bound and summarized separately."""
        gaps = list(self.gaps)
        busy = [g for g, c in gaps if c in ("pipelined", "stall")]
        idle = [g for g, c in gaps if c == "idle"]
        stalls = sum(1 for _, c in gaps if c == "stall")
        return {
            "dispatches": len(gaps),
            "p50_ms": (round(float(np.percentile(busy, 50)) * 1000, 4)
                       if busy else None),
            "p99_ms": (round(float(np.percentile(busy, 99)) * 1000, 4)
                       if busy else None),
            "stalls": stalls,
            "idle_s_total": round(sum(idle), 4),
        }

    def tenant_stats(self, tid: str) -> dict:
        """One tenant's serving view (statusz row)."""
        ts = self._tenant_peek(tid)
        if ts is None:
            return {}
        lat = list(ts.lat_ms)
        pct = (lambda q: round(float(np.percentile(lat, q)), 3)) \
            if lat else (lambda q: None)
        return {
            "tenant": tid or "default",
            "shape_class": ts.shape_class.key if ts.shape_class else "-",
            "version": ts.state.version,
            "requests": len(lat),
            "p50_ms": pct(50), "p95_ms": pct(95), "p99_ms": pct(99),
            "slo_budget_ms": self.slo.get(tid) or None,
            "slo_breaches": ts.slo_breaches,
            "last_breach_trace": ts.last_breach_trace or None,
            "journal": ts.journal.stats() if ts.journal is not None else None,
            # plane-granular export accounting (ISSUE 11): how this
            # tenant's world reached the device, by mode/cause. Copied
            # under ts.lock — _note_encode inserts keys under it on
            # handler threads, and iterating a mutating dict RuntimeErrors
            "encodes": self._encode_modes(ts),
        }

    @staticmethod
    def _encode_modes(ts: _Tenant) -> dict:
        with ts.lock:
            return dict(ts.encode_modes)

    def statusz(self) -> str:
        """Human-readable serving snapshot (the sidecar's /statusz analog,
        served by the Statusz RPC): tenant table with latency percentiles
        and SLO state, queue + reject accounting, shape-class hit rates,
        batching/occupancy/dispatch-gap figures, tail-sampler budget, and
        the last-breach exemplar trace ids — the one-page view an operator
        reads before opening /metrics or a Perfetto dump."""
        lines = [f"katpu-sidecar statusz @ {_time.strftime('%Y-%m-%dT%H:%M:%SZ', _time.gmtime())}"]
        with self._tenants_lock:
            tids = sorted(self._tenants)
        lines.append(f"tenants: {len(tids)} active (cap {self.max_tenants})")
        lines.append("  tenant          class            ver   reqs   "
                     "p50ms    p95ms    p99ms  slo_ms  breaches  last_breach")
        for tid in tids:
            st = self.tenant_stats(tid)
            if not st:
                continue
            lines.append(
                f"  {st['tenant']:<15} {st['shape_class']:<16} "
                f"{st['version']:>4}  {st['requests']:>5}  "
                f"{st['p50_ms'] if st['p50_ms'] is not None else '-':>7}  "
                f"{st['p95_ms'] if st['p95_ms'] is not None else '-':>7}  "
                f"{st['p99_ms'] if st['p99_ms'] is not None else '-':>7}  "
                f"{st['slo_budget_ms'] or '-':>6}  {st['slo_breaches']:>8}  "
                f"{st['last_breach_trace'] or '-'}")
        q = self._queue
        rej = self.registry.counter("admission_rejects_total")
        lines.append(
            f"queue: depth={q.depth if q else 0} "
            f"submitted={q.submitted if q else 0} "
            f"rejected=[queue-full={rej.value(reason='queue-full'):.0f} "
            f"tenant-cap={rej.value(reason='tenant-cap'):.0f}]")
        lines.append(
            f"shape classes: {len(self.ladder.seen())} seen, "
            f"hits={self.ladder.hits} misses={self.ladder.misses} "
            f"hit_rate={self.ladder.hit_rate():.3f}")
        gs = self.gap_stats()
        occ = list(self.occupancies)
        lines.append(
            f"batching: lanes={self.batch_lanes} "
            f"windows={self._scheduler.windows if self._scheduler else 0} "
            f"batches={self._scheduler.batches if self._scheduler else 0} "
            f"occupancy_p50={float(np.percentile(occ, 50)) if occ else '-'} "
            f"dispatch_gap_p50_ms={gs['p50_ms'] if gs['p50_ms'] is not None else '-'} "
            f"stalls={gs['stalls']} idle_s={gs['idle_s_total']}")
        tstats = self.tail.stats()
        lines.append(
            f"tail sampler: offered={tstats['offered']} "
            f"retained={tstats['retained']} evicted={tstats['evicted']} "
            f"held={tstats['held']} reasons={json.dumps(tstats['reasons'], sort_keys=True)}")
        # shadow audit (docs/OBSERVABILITY.md "Shadow audit"): one
        # round-robin lane per window re-verified against the serial
        # reference — divergence is a backend fault, never a quarantine
        au = self.audit_stats()
        if au["enabled"]:
            lines.append(
                f"shadow audit: checks={json.dumps(au['checks'], sort_keys=True)} "
                f"divergences={au['divergences']} "
                f"overhead_ms={au['overhead_ms']}")
            if au["last"]:
                la = au["last"]
                lines.append(
                    f"  last divergence: tenant={la['tenant']} "
                    f"kind={la['kind']} batch={la['batch']} "
                    f"fields={','.join(la['fields'])} "
                    f"trace={la['trace'] or '-'}")
        else:
            lines.append("shadow audit: disabled")
        # fault-domain isolation (docs/ROBUSTNESS.md): quarantine table,
        # window-failure/bisection accounting, rehydration + chaos plane
        qs = self.quarantine_stats()
        wrej = self.registry.counter("world_validation_rejects_total")
        lines.append(
            f"quarantine: {len(qs)} tenants (ttl {self.quarantine_ttl_s}s) "
            f"quarantined_total={self.registry.counter('tenant_quarantined_total').total():.0f} "
            f"paroled_total={self.registry.counter('tenant_paroled_total').total():.0f} "
            f"window_failures={self.registry.counter('window_failures_total').total():.0f} "
            f"redispatches={self.registry.counter('window_redispatches_total').total():.0f} "
            f"validation_rejects={wrej.total():.0f}")
        for t in sorted(qs):
            q = qs[t]
            lines.append(f"  {t:<15} reason={q['reason']} "
                         f"count={q['count']} "
                         f"parole_in={q['remaining_s']}s")
        rh = self.rehydration
        lines.append(
            f"warm restart: restored={rh['restored']} "
            f"digest_mismatch={rh['digest_mismatch']} "
            f"errors={rh['error']} "
            f"scheduler={'NOT_SERVING (' + self._not_serving + ')' if self._not_serving else 'serving'}")
        if faults.PLAN is not None:
            fs = faults.PLAN.stats()
            lines.append(
                f"faults: ACTIVE seed={fs['seed']} "
                f"specs={len(fs['specs'])} fired={fs['fired_total']}")
            for ent in fs["log_tail"]:
                lines.append(f"  #{ent['seq']} {ent['hook']}/{ent['kind']} "
                             f"spec={ent['spec']} tenant={ent['tenant'] or '-'}")
        else:
            lines.append("faults: disabled")
        # flight-journal section: per-tenant provenance ring accounting
        # (records/bytes/held/drops/persists), capped like the tenant table
        jrows = []
        jtot = {"records": 0, "bytes": 0, "dropped": 0, "persisted": 0}
        for tid in tids:
            ts = self._tenant_peek(tid)
            if ts is None or ts.journal is None:
                continue
            js = ts.journal.stats()
            for k in jtot:
                jtot[k] += js[k]
            jrows.append(
                f"  {js['tenant']:<15} records={js['records']:>6} "
                f"bytes={js['bytes']:>8} held={js['held']:>4} "
                f"dropped={js['dropped']} persisted={js['persisted']}")
        lines.append(
            f"journal: tenants={len(jrows)} cap={self.journal_capacity}/tenant "
            f"records={jtot['records']} bytes={jtot['bytes']} "
            f"dropped={jtot['dropped']} persisted={jtot['persisted']}")
        lines.extend(jrows)
        comp = self.registry.counter("sim_compiles_total")
        xfer = self.registry.counter("device_transfer_bytes_total")
        lines.append(
            f"device: compiles={comp.value():.0f} "
            f"compile_s={self.registry.counter('sim_compile_seconds_total').value():.3f} "
            f"h2d_bytes={xfer.value(direction='h2d'):.0f} "
            f"d2h_bytes={xfer.value(direction='d2h'):.0f}")
        # HBM residency ledger: tagged census vs device totals, per owner
        # component and tenant (docs/OBSERVABILITY.md "Device surfaces")
        hs = self.hbm_stats()
        if hs.get("source") == "disabled":
            lines.append("hbm: ledger disabled")
        else:
            head = hs.get("headroom_ratio")
            lines.append(
                f"hbm: source={hs['source']} in_use={hs['bytes_in_use']} "
                f"limit={hs['bytes_limit']} tagged={hs['tagged_bytes']} "
                f"untagged={hs['untagged_bytes']} "
                f"headroom={f'{head:.3f}' if head is not None else '-'} "
                f"budget_frac={self.hbm_budget_frac or '-'} "
                f"budget_rejects={self.registry.counter('world_validation_rejects_total').value(reason='hbm-budget'):.0f}")
            for k, v in hs.get("by_owner_tenant", {}).items():
                lines.append(f"  {k:<28} {v} bytes")
        # compile census: named variants instead of a bare compile count
        variants = self.census.variants()
        lines.append(f"compile census: {len(variants)} variants "
                     f"(mode={self.census.mode})")
        for e in variants[:16]:
            lines.append(
                f"  {e['fn']:<22} sig={e['shape_sig']:<22} "
                f"compiles={e['compiles']} "
                f"tenants={','.join(e['tenants']) or '-'}"
                + (f" flops={e['flops']:.3g}" if "flops" in e else "")
                + (f" temp_b={e['temp_bytes']}" if "temp_bytes" in e else ""))
        prof = device.PROFILER
        if prof is not None:
            ps = prof.stats()
            lines.append(
                f"profiler: dir={ps['dir']} armed={ps['armed']} "
                f"captures={ps['captures']}/{ps['max_captures']} "
                f"throttled={ps['throttled']}"
                + (f" last={ps['last']['path']}" if ps["last"] else ""))
        else:
            lines.append("profiler: disabled")
        # world-store section: encode modes aggregated across resident
        # tenants (delta = plane-granular refresh reused resident sections)
        emodes: dict[str, int] = {}
        for tid in tids:
            ets = self._tenant_peek(tid)
            if ets is not None:
                for k, v in self._encode_modes(ets).items():
                    emodes[k] = emodes.get(k, 0) + v
        wsb_total = self.registry.counter(
            "world_store_h2d_bytes_total").total()
        lines.append(
            "world store: encodes="
            + json.dumps(emodes, sort_keys=True)
            + f" h2d_world_bytes={wsb_total:.0f}")
        # EventSink isn't thread-safe: the reject path emits under
        # _events_lock on handler threads, so the statusz read takes it too
        with self._events_lock:
            events = self.events.snapshot()
        if events:
            lines.append(f"events ({len(events)} stored, newest last):")
            for ev in events[-8:]:
                lines.append(f"  {ev['kind']} {ev['object']}: "
                             f"{ev['reason']} x{ev['count']}")
        hist_dir = os.environ.get("KATPU_PERF_HISTORY")
        if hist_dir:
            # perf trajectory tail: a sidecar pointed at a perfwatch store
            # serves the recent bench series so fleet perf is inspectable
            # without pulling artifacts (docs/BENCH.md "Trajectory &
            # regression gate")
            try:
                from ..perfwatch.history import PerfHistory
                from ..perfwatch.report import trajectory_lines
                if not os.path.isdir(hist_dir):
                    # a status read must not mkdir a mistyped store path
                    raise FileNotFoundError(hist_dir)
                hist = PerfHistory(hist_dir)
                st = hist.stats()
                lines.append(
                    f"perf history: dir={hist_dir} rows={st['rows']} "
                    f"dropped={st['dropped_rows']} "
                    f"lineages={json.dumps(st['lineages'], sort_keys=True)}")
                for ln in trajectory_lines(hist.load(), last=5):
                    lines.append("  " + ln)
            except Exception as exc:  # tampered/unreadable store: surface it
                lines.append(f"perf history: unreadable ({exc})")
        return "\n".join(lines) + "\n"

    def _on_complete(self, method: str, tenant: str, dt_s: float,
                     tracer: "trace.Tracer | None",
                     error: Exception | None = None) -> str | None:
        """Per-request completion hook (traced_call): feed the tail
        sampler, check the tenant's SLO budget, and return the retained
        exemplar trace id (if any) for the latency histogram bucket.

        A breach bumps `tenant_slo_breaches_total{tenant}` and persists a
        TENANT-SCOPED dump: only this tenant's retained request traces
        (TailSampler.tenant_traces), never the whole ring — the serving
        analog of the FlightRecorder's loop-scoped breach dump."""
        ts = self._tenant_peek(tenant)
        breached = self.slo.breached(tenant, dt_s)
        reason = None
        if error is not None:
            reason = ("backpressure" if isinstance(error, QueueFull)
                      else "failed")
        elif breached:
            reason = "slo_breach"
        exemplar = None
        if tracer is not None:
            snap = tracer.snapshot()
            snap["tenant"] = tenant
            snap["method"] = method
            # a retained trace names its replayable provenance: the
            # tenant-journal cursor at completion time
            if ts is not None and ts.journal is not None:
                cur = ts.journal.cursor()
                if cur is not None:
                    snap["journal_seq"], snap["journal_digest"] = cur
            exemplar = self.tail.offer(snap, dt_s, reason)
            if exemplar and device.PROFILER is not None:
                # tail retention arms the device profiler: the NEXT sim
                # dispatch runs under a bounded jax.profiler.trace session
                # whose capture dir is stamped with THIS retained trace id
                # + journal cursor (rate-limited inside arm(); a throttled
                # arm is a counter bump, not a capture)
                cur = (snap.get("journal_seq"), snap.get("journal_digest"))
                device.PROFILER.arm(
                    reason or "slow", trace_id=exemplar,
                    journal_cursor=cur if cur[0] is not None else None)
        else:
            self.tail.observe_latency(dt_s)
        if reason in ("slo_breach", "backpressure") and self.slo_dump_dir \
                and ts is not None and ts.journal is not None:
            # breach/backpressure-triggered retention (the TailSampler
            # pattern): the in-memory provenance ring hits disk only now —
            # deduped by ring watermark, because backpressure fires exactly
            # when the server is saturated and the reject path must stay a
            # cheap fast-reject (maybe_persist writes once per NEW history,
            # an overload storm re-persists nothing)
            try:
                import os

                os.makedirs(self.slo_dump_dir, exist_ok=True)
                ts.journal.maybe_persist(self.slo_dump_dir, reason=reason)
            except OSError:
                pass   # a full disk must never sink the RPC
        if breached:
            self.registry.counter(
                "tenant_slo_breaches_total",
                help="Requests exceeding their tenant's latency budget "
                     "(sidecar/lifecycle.SloBudgets)",
            ).inc(tenant=tenant or "default")
            if ts is not None:
                ts.slo_breaches += 1
                if exemplar:
                    ts.last_breach_trace = exemplar
            if self.slo_dump_dir and tracer is not None:
                try:
                    import os

                    os.makedirs(self.slo_dump_dir, exist_ok=True)
                    self.tail.dump(
                        os.path.join(
                            self.slo_dump_dir,
                            f"slo-{tenant or 'default'}-{tracer.trace_id}"
                            f".trace.json"),
                        self.tail.tenant_traces(tenant))
                except OSError:
                    pass   # a full disk must never sink the RPC
        return exemplar

    def health(self) -> dict:
        """SERVING, or NOT_SERVING once the batch scheduler crashed (the
        supervision contract: a sidecar whose dispatch thread is dead must
        not look healthy to orchestration OR to client half-open probes)."""
        if self._not_serving:
            return {"version": self.state.version, "status": "NOT_SERVING",
                    "error": self._not_serving,
                    "tenants": len(self._tenants)}
        return {"version": self.state.version, "status": "SERVING",
                "error": "", "tenants": len(self._tenants)}

    # ---- rpc: Metricz ----

    def metricz(self) -> str:
        """The sidecar's /metricz analog: its own Registry (per-RPC counters
        and duration histograms, `katpu_sidecar_*`) FOLLOWED BY the
        process-wide default registry (`cluster_autoscaler_*`, including
        `# HELP` lines and the reason-labelled families) in prometheus
        exposition text. Serving both means the main-process `/metrics` mux
        and this RPC expose the same autoscaler families — a scrape of
        either surface sees the reason plane (asserted by
        tests/test_reason_plane.py). Plain text on the wire, not JSON —
        scrapeable as-is."""
        from kubernetes_autoscaler_tpu.metrics.metrics import default_registry

        return self.registry.expose_text() + default_registry.expose_text()

    # ---- rpc: Explain ----

    def explain(self, payload: bytes = b"", tenant: str = "") -> dict:
        """The sidecar's per-tenant lineage surface (docs/LINEAGE.md): the
        tenant's TenantJournal ring — every ApplyDelta and sim verdict
        digest, chained — returned ROW-FOR-ROW (the Metricz ≡ /metrics
        parity contract: tests pin Explain records == ts.journal.snapshot()
        exactly). Optional body `{"kinds": [...], "limit": N}` filters by
        record kind / keeps the newest N rows, with `held`/`returned`
        accounting so a filtered reply never masquerades as the full ring.
        Pure read under the ring's own lock — no dispatch, no encode."""
        req = {}
        if payload:
            try:
                req = json.loads(payload.decode() or "{}")
            except ValueError:
                return {"error": "malformed Explain body (want JSON)"}
        self.registry.counter(
            "lineage_queries_total",
            help="Lineage queries served, by surface").inc(surface="explain")
        ts = self._tenant_peek(tenant)
        if ts is None:
            return {"tenant": tenant or "default", "found": False,
                    "records": [], "held": 0, "returned": 0}
        if ts.journal is None:
            return {"tenant": tenant or "default", "found": True,
                    "journal": None, "records": [], "held": 0,
                    "returned": 0}
        rows = ts.journal.snapshot()
        held = len(rows)
        kinds = req.get("kinds")
        if kinds:
            rows = [r for r in rows if r.get("kind") in set(kinds)]
        limit = req.get("limit")
        if isinstance(limit, int) and limit >= 0:
            rows = rows[-limit:] if limit else []
        return {"tenant": tenant or "default", "found": True,
                "records": rows, "held": held, "returned": len(rows),
                "cursor": list(ts.journal.cursor() or ()) or None,
                "stats": ts.journal.stats()}


def traced_call(service: SimulatorService, method: str, fn,
                trace_id: str | None = None, tenant: str = "",
                sample: bool = True):
    """Run one RPC body under the sidecar's observability contract: RPC
    count/duration always land in `service.registry` (labelled with the
    tenant when one was identified — stale tenant labels are zeroed by
    drop_tenant); when the caller stamped a trace id into the request
    metadata, the body runs under a child Tracer with the SAME id and the
    closed spans come back as the `(result, trace_group)` second element —
    the shape `metrics/trace.Tracer.add_remote_spans` merges client-side,
    so one trace covers both processes.

    With `sample` (simulation RPCs), the body ALWAYS runs under a tracer —
    a fresh server-side id when the client stamped none — and the completed
    trace is OFFERED to the tail sampler (service._on_complete): slow /
    failed / backpressured / SLO-breaching requests are retained with their
    full lifecycle span tree, and the retained trace id lands as the
    latency histogram bucket's exemplar. Unsampled requests cost one
    snapshot + a reservoir append."""
    own_id = sample and trace_id is None
    tracer = (trace.Tracer(trace_id=trace_id, process="sidecar")
              if (trace_id or sample) else None)
    prev = trace.activate(tracer) if tracer is not None else None
    t0 = _time.perf_counter()
    error: Exception | None = None
    try:
        if tracer is not None:
            idx = tracer.begin(f"sidecar/{method}", cat="sidecar",
                               **({"tenant": tenant} if tenant else {}))
            try:
                out = fn()
            finally:
                ts = service._tenant_peek(tenant)
                tracer.end(
                    idx, version=ts.state.version if ts is not None else 0)
        else:
            out = fn()
    except Exception as e:
        error = e
        raise
    finally:
        if tracer is not None:
            trace.activate(prev)
        dt = _time.perf_counter() - t0
        exemplar = (service._on_complete(method, tenant, dt, tracer,
                                         error=error)
                    if sample else None)
        labels = {"method": method}
        if tenant:
            labels["tenant"] = tenant
        service.registry.counter(
            "rpc_total", help="RPCs served, by method").inc(**labels)
        service.registry.histogram(
            "rpc_duration_seconds", help="Server-side RPC wall clock",
            buckets=PHASE_BUCKETS).observe(dt, exemplar=exemplar, **labels)
    group = None
    if tracer is not None and not own_id:
        # span report-back only when the CLIENT is tracing (it stamped the
        # id); a server-side sampling tracer stays server-side
        snap = tracer.snapshot()
        group = {"trace_id": snap["trace_id"], "process": "sidecar",
                 "spans": snap["spans"]}
    return out, group


def make_grpc_server(service: SimulatorService, port: int = 50151,
                     cert_file: str | None = None,
                     key_file: str | None = None,
                     client_ca_file: str | None = None,
                     host: str = "127.0.0.1",
                     max_workers: int = 16):
    """Wire the service into a grpc.Server with generic bytes handlers.

    TLS: pass cert_file/key_file to serve over TLS (mirrors the reference's
    --grpc-expander-cert precedent for out-of-process plugins; round-3 review
    item #7 — the simulator service previously bound insecure only).
    client_ca_file additionally requires and verifies client certificates
    (mTLS). Without certs the server binds insecure on localhost.

    `max_workers` bounds concurrently blocked handler threads — it must
    comfortably exceed the batch lane count or the coalescing window can
    never fill (handlers park on their tickets while a window forms)."""
    import grpc

    def _meta_of(context, key: str) -> str | None:
        md = getattr(context, "invocation_metadata", None)
        if md is None:
            return None
        for k, v in md() or ():
            if k == key:
                return v
        return None

    def _reject(context, e: Exception, code, code_name: str,
                retry_after_ms: int | None = None,
                reason: str | None = None) -> bytes:
        # explicit structured rejection: the caller sees a REAL status code
        # (RESOURCE_EXHAUSTED backpressure / FAILED_PRECONDITION quarantine
        # / INVALID_ARGUMENT validation / UNAVAILABLE dead scheduler)
        # instead of a wedged RPC or an anonymous error string
        try:
            if retry_after_ms is not None:
                context.set_trailing_metadata(
                    ((RETRY_AFTER_MS_HEADER, str(retry_after_ms)),))
            context.set_code(code)
            context.set_details(str(e))
        except Exception:  # noqa: BLE001 — non-grpc contexts in tests
            pass
        body = {"error": str(e), "code": code_name}
        if retry_after_ms is not None:
            body["retry_after_ms"] = retry_after_ms
        if reason is not None:
            body["reason"] = reason
        return json.dumps(body).encode()

    def _json_method(name: str, fn, parse_params: bool, sample: bool = True):
        def handler(request: bytes, context):
            tenant = _meta_of(context, TENANT_ID_HEADER) or ""
            budget = _meta_of(context, SLO_BUDGET_MS_HEADER)
            if budget:
                # the client declares its own loop deadline as the tenant's
                # latency budget (last write wins)
                try:
                    service.slo.set(tenant, float(budget))
                except ValueError:
                    pass
            try:
                if parse_params:
                    raw = json.loads(request.decode() or "{}")
                    params = SimParams(
                        max_new_nodes=raw.get("max_new_nodes", 256),
                        strategy=raw.get("strategy", "least-waste"),
                        threshold=raw.get("threshold", 0.5),
                        node_groups=raw.get("node_groups"),
                    )
                    body = lambda: fn(params, tenant=tenant)  # noqa: E731
                elif name == "ApplyDelta":
                    base = _meta_of(context, BASE_VERSION_HEADER)
                    kw = ({"base_version": int(base)}
                          if base not in (None, "") else {})
                    body = lambda: fn(request, tenant=tenant, **kw)  # noqa: E731
                else:
                    body = lambda: fn(request, tenant=tenant)  # noqa: E731
                resp, group = traced_call(
                    service, name, body,
                    trace_id=_meta_of(context, TRACE_ID_HEADER),
                    tenant=tenant, sample=sample)
                if group is not None and isinstance(resp, dict):
                    resp["trace"] = group
                if faults.PLAN is not None:
                    faults.PLAN.fire("grpc_reply", tenant=tenant,
                                     registry=service.registry)
                return json.dumps(resp).encode()
            except QueueFull as e:
                return _reject(context, e, grpc.StatusCode.RESOURCE_EXHAUSTED,
                               "RESOURCE_EXHAUSTED",
                               retry_after_ms=e.retry_after_ms)
            except Quarantined as e:
                return _reject(context, e, grpc.StatusCode.FAILED_PRECONDITION,
                               "FAILED_PRECONDITION",
                               retry_after_ms=e.retry_after_ms,
                               reason=e.reason)
            except WorldValidationError as e:
                return _reject(context, e, grpc.StatusCode.INVALID_ARGUMENT,
                               "INVALID_ARGUMENT", reason=e.reason)
            except SchedulerDown as e:
                return _reject(context, e, grpc.StatusCode.UNAVAILABLE,
                               "UNAVAILABLE")
            except Exception as e:  # fail-closed with the error on the wire
                return json.dumps({"error": str(e)}).encode()

        return handler

    def _metricz(request: bytes, context):
        text, _ = traced_call(service, "Metricz", service.metricz,
                              trace_id=_meta_of(context, TRACE_ID_HEADER),
                              sample=False)
        return text.encode()

    def _statusz(request: bytes, context):
        text, _ = traced_call(service, "Statusz", service.statusz,
                              trace_id=_meta_of(context, TRACE_ID_HEADER),
                              sample=False)
        return text.encode()

    def _profilez(request: bytes, context):
        resp, _ = traced_call(service, "Profilez",
                              lambda: service.profilez(request),
                              trace_id=_meta_of(context, TRACE_ID_HEADER),
                              sample=False)
        return json.dumps(resp).encode()

    ident = lambda b: b

    method_handlers = {
        "ApplyDelta": grpc.unary_unary_rpc_method_handler(
            _json_method("ApplyDelta", service.apply_delta, False,
                         sample=False),
            request_deserializer=ident, response_serializer=ident),
        "ScaleUpSim": grpc.unary_unary_rpc_method_handler(
            _json_method("ScaleUpSim", service.scale_up_sim, True),
            request_deserializer=ident, response_serializer=ident),
        "ScaleDownSim": grpc.unary_unary_rpc_method_handler(
            _json_method("ScaleDownSim", service.scale_down_sim, True),
            request_deserializer=ident, response_serializer=ident),
        "WhatIf": grpc.unary_unary_rpc_method_handler(
            _json_method("WhatIf", service.what_if, False),
            request_deserializer=ident, response_serializer=ident),
        "Health": grpc.unary_unary_rpc_method_handler(
            _json_method("Health", lambda _b, tenant="": service.health(),
                         False, sample=False),
            request_deserializer=ident, response_serializer=ident),
        "Explain": grpc.unary_unary_rpc_method_handler(
            _json_method("Explain", service.explain, False, sample=False),
            request_deserializer=ident, response_serializer=ident),
        "Metricz": grpc.unary_unary_rpc_method_handler(
            _metricz, request_deserializer=ident, response_serializer=ident),
        "Statusz": grpc.unary_unary_rpc_method_handler(
            _statusz, request_deserializer=ident, response_serializer=ident),
        "Profilez": grpc.unary_unary_rpc_method_handler(
            _profilez, request_deserializer=ident,
            response_serializer=ident),
    }
    from concurrent.futures import ThreadPoolExecutor

    server = grpc.server(ThreadPoolExecutor(
        max_workers=max(max_workers, 2 * service.batch_lanes or 4)))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE, method_handlers),)
    )
    if client_ca_file and not (cert_file and key_file):
        raise ValueError(
            "client_ca_file (mTLS) requires a serving cert_file/key_file — "
            "refusing to bind insecure while client verification was asked")
    if cert_file and key_file:
        with open(key_file, "rb") as f:
            key = f.read()
        with open(cert_file, "rb") as f:
            crt = f.read()
        root = None
        if client_ca_file:
            with open(client_ca_file, "rb") as f:
                root = f.read()
        creds = grpc.ssl_server_credentials(
            [(key, crt)], root_certificates=root,
            require_client_auth=bool(client_ca_file))
        bound = server.add_secure_port(f"{host}:{port}", creds)
    else:
        bound = server.add_insecure_port(f"{host}:{port}")
    return server, bound


class CircuitOpen(ConnectionError):
    """Fast-fail from an OPEN client circuit: the sidecar kept failing, so
    this call never touched the wire — one exception per loop instead of a
    full retry ladder per RPC against a flapping server. Carries the error
    that opened the circuit and the time until the next half-open probe."""

    def __init__(self, retry_in_s: float, last_error: Exception | None):
        super().__init__(
            f"sidecar circuit open (half-open probe in {retry_in_s:.2f}s); "
            f"last error: {last_error!r}")
        self.retry_in_s = retry_in_s
        self.last_error = last_error


class CircuitBreaker:
    """closed → open → half-open client circuit (docs/ROBUSTNESS.md).

    closed: calls flow; `threshold` CONSECUTIVE transport failures
    (UNAVAILABLE after the retry ladder, deadline exceeded) open it.
    open: calls fast-fail with CircuitOpen until `cooldown_s` elapses.
    half-open: exactly one probe (the client uses the cheap Health RPC) is
    allowed through; success closes the circuit, failure re-opens it for
    another cooldown. Responses that prove the server ALIVE — including
    backpressure rejections — reset the failure streak.

    State changes land on the default metrics registry
    (`sidecar_breaker_state{target}` 0/1/2 and
    `sidecar_breaker_transitions_total{target,to}`) so a flapping sidecar
    is visible from the control plane's own /metrics. `clock` is
    injectable for fake-clock tests."""

    STATES = {"closed": 0, "open": 1, "half-open": 2}

    def __init__(self, threshold: int = 5, cooldown_s: float = 1.0,
                 clock=_time.monotonic, target: str = ""):
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.target = target
        self.state = "closed"
        self.failures = 0
        self.last_error: Exception | None = None
        self._opened_at = 0.0
        self._lock = threading.Lock()

    def _to(self, state: str) -> None:
        if state == self.state:
            return
        self.state = state
        from kubernetes_autoscaler_tpu.metrics.metrics import default_registry

        labels = {"target": self.target} if self.target else {}
        default_registry.gauge(
            "sidecar_breaker_state",
            help="Client circuit-breaker state per sidecar target "
                 "(0=closed 1=open 2=half-open)",
        ).set(float(self.STATES[state]), **labels)
        default_registry.counter(
            "sidecar_breaker_transitions_total",
            help="Client circuit-breaker state transitions",
        ).inc(to=state, **labels)

    def gate(self) -> str:
        """'ok' to call through, 'probe' to health-check first (half-open);
        raises CircuitOpen while the circuit is open and cooling."""
        with self._lock:
            if self.state == "closed":
                return "ok"
            elapsed = self._clock() - self._opened_at
            if self.state == "open" and elapsed < self.cooldown_s:
                raise CircuitOpen(self.cooldown_s - elapsed, self.last_error)
            self._to("half-open")
            return "probe"

    def ok(self) -> None:
        """The server answered (a sim result, or even a structured
        rejection): the streak resets and a half-open circuit closes."""
        with self._lock:
            self.failures = 0
            if self.state != "closed":
                self._to("closed")

    def fail(self, error: Exception) -> None:
        """A transport-level failure: half-open re-opens immediately, a
        closed circuit opens once the consecutive streak hits threshold."""
        with self._lock:
            self.last_error = error
            self.failures += 1
            if self.state == "half-open" or self.failures >= self.threshold:
                self._to("open")
                self._opened_at = self._clock()


class SimulatorClient:
    """Thin client mirroring the Go side's calls (tests + examples).

    Resilience contract (ISSUE 7 small fix): every RPC carries a per-call
    DEADLINE (`rpc_timeout_s`) and UNAVAILABLE errors — the sidecar
    restarting or the channel flapping — are retried with exponential
    backoff, capped BOTH by attempts (`retry_attempts`) and by a TOTAL
    wall-clock budget (`retry_budget_s`, the InitBudget pattern: the ladder
    never sleeps past the deadline; a persistently refused connection fails
    in under a second). When the cap is hit the last error raises promptly,
    so a control loop using the sidecar degrades to its LOCAL simulation
    fallback instead of hanging a RunOnce forever.

    Backpressure (RESOURCE_EXHAUSTED) now honors the server's
    `katpu-retry-after-ms` hint (ISSUE 12 small fix): up to
    `queue_retry_attempts` jittered, capped sleeps before surfacing
    admission.QueueFull — the hint is what the server computed the queue
    needs, so blind-fast retry (hammering a saturated server) and
    terminal-give-up (shedding load the queue would have absorbed in 20ms)
    are both wrong. Deliberate immediate shedding is still available with
    `queue_retry_attempts=0`.

    On top of the per-RPC ladder sits a real CIRCUIT BREAKER
    (docs/ROBUSTNESS.md): `breaker_threshold` consecutive transport
    failures open it, after which calls fast-fail with CircuitOpen (no
    wire touch) until `breaker_cooldown_s` elapses; the half-open probe is
    the cheap Health RPC, so a flapping sidecar costs one fast exception
    per loop instead of a full retry ladder per RPC. `clock`/`sleep` are
    injectable for fake-clock tests."""

    def __init__(self, port: int, cert_file: str | None = None,
                 host: str = "127.0.0.1",
                 client_cert_file: str | None = None,
                 client_key_file: str | None = None,
                 tenant: str = "",
                 rpc_timeout_s: float = 30.0,
                 retry_budget_s: float = 10.0,
                 retry_attempts: int = 5,
                 slo_budget_ms: float = 0.0,
                 queue_retry_attempts: int = 3,
                 queue_retry_cap_ms: float = 2000.0,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 1.0,
                 clock=_time.monotonic,
                 sleep=_time.sleep):
        import grpc
        import random as _random

        self.tenant = tenant
        self.rpc_timeout_s = rpc_timeout_s
        self.retry_budget_s = retry_budget_s
        self.retry_attempts = retry_attempts
        self.queue_retry_attempts = max(int(queue_retry_attempts), 0)
        self.queue_retry_cap_ms = float(queue_retry_cap_ms)
        self._clock = clock
        self._sleep = sleep
        # full jitter over the server hint: a deterministic per-client seed
        # keeps chaos runs replayable (the jitter exists to decorrelate a
        # HERD of clients, not to randomize one client's evidence)
        self._rng = _random.Random(0x5EED)
        # breaker_threshold=0 disables the breaker (raw ladder semantics)
        self.breaker = (CircuitBreaker(
            threshold=breaker_threshold, cooldown_s=breaker_cooldown_s,
            clock=clock, target=f"{host}:{port}")
            if breaker_threshold > 0 else None)
        # declared per-tenant latency budget (wire.SLO_BUDGET_MS_HEADER):
        # the server counts tenant_slo_breaches_total against it and keeps
        # tenant-scoped breach dumps
        self.slo_budget_ms = float(slo_budget_ms)
        # server-side lifecycle block of the most recent sim RPC (queue vs
        # dispatch vs harvest decomposition; RunOnce consumers read it to
        # separate server time from network time)
        self.last_lifecycle: dict | None = None
        if cert_file:
            with open(cert_file, "rb") as f:
                root = f.read()
            ck = cc = None
            if client_cert_file and client_key_file:
                with open(client_key_file, "rb") as f:
                    ck = f.read()
                with open(client_cert_file, "rb") as f:
                    cc = f.read()
            creds = grpc.ssl_channel_credentials(
                root_certificates=root, private_key=ck, certificate_chain=cc)
            # loopback targets verify against the self-signed pair's
            # "localhost" SAN; real hosts verify their own names — never
            # weaken verification for them
            opts = ([("grpc.ssl_target_name_override", "localhost")]
                    if host in ("127.0.0.1", "::1", "localhost") else [])
            self.channel = grpc.secure_channel(
                f"{host}:{port}", creds, options=opts)
        else:
            self.channel = grpc.insecure_channel(f"{host}:{port}")

    @staticmethod
    def _retry_after_ms(err) -> int:
        try:
            for k, v in err.trailing_metadata() or ():
                if k == RETRY_AFTER_MS_HEADER:
                    return int(v)
        except Exception:  # noqa: BLE001
            pass
        return 20

    def _probe_health(self) -> None:
        """The half-open probe: ONE cheap Health RPC, no retry ladder. A
        SERVING answer closes the breaker and lets the real call proceed; a
        failure (or NOT_SERVING) re-opens it for another cooldown and
        fast-fails the caller."""
        rpc = self.channel.unary_unary(
            f"/{_SERVICE}/Health",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        try:
            resp = json.loads(rpc(b"", timeout=min(self.rpc_timeout_s, 2.0)))
            if resp.get("status", "SERVING") == "NOT_SERVING":
                raise ConnectionError(
                    f"sidecar NOT_SERVING: {resp.get('error')}")
            self.breaker.ok()
        except Exception as e:  # noqa: BLE001 — any probe failure re-opens
            self.breaker.fail(e)
            raise CircuitOpen(self.breaker.cooldown_s, e) from e

    def _call(self, method: str, payload: bytes, metadata=()) -> bytes:
        import grpc

        rpc = self.channel.unary_unary(
            f"/{_SERVICE}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        # circuit gate BEFORE any wire touch: open = one fast exception
        # (the caller's local-fallback signal), half-open = Health probe
        if self.breaker is not None:
            tracer0 = trace.current_tracer()
            try:
                if self.breaker.gate() == "probe":
                    self._probe_health()
            except CircuitOpen:
                if tracer0 is not None:
                    tracer0.annotate(breaker="open")
                raise
        # trace propagation: the ACTIVE tracer's id rides request metadata
        # (never the payload bytes — the KAD1 wire contract stays trace-free)
        # and the rpc itself is a client-side span on the same timeline;
        # tenant identity rides the same way (wire.TENANT_ID_HEADER)
        tracer = trace.current_tracer()
        md = list(metadata)
        if tracer is not None:
            md.append((TRACE_ID_HEADER, tracer.trace_id))
        if self.tenant:
            md.append((TENANT_ID_HEADER, self.tenant))
        if self.slo_budget_ms > 0:
            md.append((SLO_BUDGET_MS_HEADER, str(self.slo_budget_ms)))

        def invoke():
            deadline = self._clock() + self.retry_budget_s
            delay = 0.05
            for attempt in range(max(self.retry_attempts, 1)):
                try:
                    return rpc(payload, timeout=self.rpc_timeout_s,
                               metadata=tuple(md) or None)
                except grpc.RpcError as e:
                    code = e.code()
                    if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                        raise QueueFull(None, self._retry_after_ms(e)) from e
                    if (code != grpc.StatusCode.UNAVAILABLE
                            or attempt + 1 >= self.retry_attempts
                            or self._clock() + delay >= deadline):
                        raise   # cap hit: degrade, don't hang
                    self._sleep(delay)
                    delay = min(delay * 2, 1.0)

        def attempt():
            """invoke() + breaker accounting + the retry-after contract:
            backpressure sleeps the server's hint (full jitter, capped)
            up to queue_retry_attempts times — neither terminal nor blind."""
            import grpc as _grpc

            for qa in range(self.queue_retry_attempts + 1):
                try:
                    out = invoke()
                    if self.breaker is not None:
                        self.breaker.ok()
                    return out
                except QueueFull as e:
                    if self.breaker is not None:
                        self.breaker.ok()   # the server ANSWERED: alive
                    if qa >= self.queue_retry_attempts:
                        raise
                    hint_ms = max(e.retry_after_ms, 1)
                    wait_ms = min(hint_ms * (1.0 + self._rng.random()),
                                  self.queue_retry_cap_ms)
                    if tracer is not None:
                        tracer.bump("queue_retries")
                    self._sleep(wait_ms / 1000.0)
                except _grpc.RpcError as e:
                    if self.breaker is not None and e.code() in (
                            _grpc.StatusCode.UNAVAILABLE,
                            _grpc.StatusCode.DEADLINE_EXCEEDED):
                        self.breaker.fail(e)
                    raise

        if tracer is None:
            return attempt()
        with tracer.span(f"rpc/{method}", cat="rpc", bytes=len(payload)):
            return attempt()

    def _call_json(self, method: str, payload: bytes, metadata=()) -> dict:
        t0 = _time.perf_counter()
        resp = json.loads(self._call(method, payload, metadata=metadata))
        rpc_wall_ms = (_time.perf_counter() - t0) * 1000.0
        # the server reports its child spans back in the response; merge
        # them so ONE trace covers both processes
        tracer = trace.current_tracer()
        group = resp.pop("trace", None) if isinstance(resp, dict) else None
        if tracer is not None and group is not None:
            tracer.add_remote_spans(group)
        # the server's lifecycle decomposition: annotate the caller's trace
        # so a RunOnce timeline shows server-side queue time DISTINCT from
        # network time (client rpc wall minus server e2e ≈ wire +
        # serialization). Kept off the returned payload — consumers read
        # `last_lifecycle`, response dicts stay sim results only.
        lc = resp.pop("lifecycle", None) if isinstance(resp, dict) else None
        if lc is not None:
            lc["net_ms"] = round(max(rpc_wall_ms - lc.get("e2e_ms", 0.0), 0.0), 4)
            self.last_lifecycle = lc
            if tracer is not None:
                tracer.annotate(
                    server_e2e_ms=lc.get("e2e_ms"), net_ms=lc["net_ms"],
                    server_queue_ms=lc.get("phases_ms", {}).get("queue"))
        return resp

    def apply_delta(self, writer: DeltaWriter,
                    base_version: int | None = None) -> dict:
        """`base_version` pins the snapshot version this delta was built
        against (wire.BASE_VERSION_HEADER): a restarted/rehydrated server
        holding a different version rejects INVALID_ARGUMENT
        (section-version-mismatch) — the full-resend signal — instead of
        applying the delta to the wrong base."""
        md = (((BASE_VERSION_HEADER, str(int(base_version))),)
              if base_version is not None else ())
        return self._call_json("ApplyDelta", writer.payload(), metadata=md)

    def scale_up_sim(self, **params) -> dict:
        return self._call_json("ScaleUpSim", json.dumps(params).encode())

    def scale_down_sim(self, **params) -> dict:
        return self._call_json("ScaleDownSim", json.dumps(params).encode())

    def what_if(self, variants=(), rollout: int = 0, workload=None,
                **params) -> dict:
        """Counterfactual multiverse over the tenant's resident world
        (docs/WHATIF.md): `variants` is a list of variant dicts (lane 0
        null hypothesis is always prepended server-side), `rollout` a
        simulated loop count (0 = single step), `workload` a
        WorkloadSpec record dict for the rollout's synthetic traffic."""
        body = dict(params)
        body["variants"] = [v.to_dict() if hasattr(v, "to_dict") else v
                            for v in variants]
        body["rollout"] = rollout
        if workload is not None:
            body["workload"] = (workload.to_record()
                                if hasattr(workload, "to_record")
                                else workload)
        return self._call_json("WhatIf", json.dumps(body).encode())

    def health(self) -> dict:
        return self._call_json("Health", b"")

    def explain(self, kinds=None, limit: int | None = None) -> dict:
        """This tenant's TenantJournal lineage rows (the Explain RPC;
        row-for-row the server ring's snapshot unless filtered)."""
        body = {}
        if kinds:
            body["kinds"] = list(kinds)
        if limit is not None:
            body["limit"] = int(limit)
        return self._call_json("Explain",
                               json.dumps(body).encode() if body else b"")

    def metricz(self) -> str:
        """Prometheus text of the sidecar's Registry (rpc counters etc.)."""
        return self._call("Metricz", b"").decode()

    def statusz(self) -> str:
        """Human-readable serving snapshot (tenant table, queue, shape
        classes, dispatch gaps, tail-sampler budget)."""
        return self._call("Statusz", b"").decode()

    def profilez(self, arm: bool = False, reason: str = "manual") -> dict:
        """Device-profiler state; `arm=True` arms a capture of the NEXT
        sim dispatch (the /snapshotz armed-handle pattern, rate-limited)."""
        return self._call_json(
            "Profilez", json.dumps({"arm": arm, "reason": reason}).encode())


def main(argv=None):
    """Standalone sidecar: python -m kubernetes_autoscaler_tpu.sidecar.server
    --port 50151 [--batch-lanes 8 --batch-window-ms 2 --queue-depth 128]
    [--grpc-cert C --grpc-key K [--grpc-client-ca CA]]
    [--self-signed-cert-dir DIR]."""
    import argparse
    import time

    ap = argparse.ArgumentParser(prog="katpu-sidecar")
    ap.add_argument("--port", type=int, default=50151)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--batch-lanes", type=int, default=8,
                    help="multi-tenant coalesced dispatch width (0 = serial "
                         "per-RPC dispatch)")
    ap.add_argument("--batch-window-ms", type=float, default=2.0,
                    help="coalescing window: how long a dispatch waits for "
                         "concurrent requests to join its batch")
    ap.add_argument("--batch-window-max", type=int, default=0,
                    help="coalescing cap: tickets collected per window "
                         "before it closes early (0 = 4x batch-lanes); each "
                         "window then chunks into lane-width dispatches")
    ap.add_argument("--queue-depth", type=int, default=128,
                    help="admission bound; beyond it requests are rejected "
                         "with RESOURCE_EXHAUSTED + retry-after")
    ap.add_argument("--checkpoint-dir", default="",
                    help="warm-restart state dir: rehydrate per-tenant "
                         "serving records from here at startup and persist "
                         "them on graceful shutdown (SIGTERM/SIGINT) — a "
                         "restarted sidecar serves steady tenants without "
                         "full world re-sends (docs/ROBUSTNESS.md)")
    ap.add_argument("--quarantine-ttl-s", type=float, default=30.0,
                    help="poison-tenant quarantine sentence before "
                         "auto-parole")
    ap.add_argument("--hbm-budget-frac", type=float, default=0.0,
                    help="HBM admission budget as a fraction of the device "
                         "memory limit: a new tenant whose projected "
                         "residency would push tagged bytes past it is "
                         "rejected with the hbm-budget validation reason "
                         "(0 = gate off)")
    ap.add_argument("--hbm-limit-bytes", type=int, default=0,
                    help="budget denominator override for backends without "
                         "memory_stats (0 = use the device's bytes_limit)")
    ap.add_argument("--device-profile-dir", default="",
                    help="enable breach-triggered device profiling: SLO-"
                         "breach/tail-retained requests (or the Profilez "
                         "RPC) arm a bounded, rate-limited "
                         "jax.profiler.trace capture into this directory, "
                         "stamped with the retained trace id + journal "
                         "cursor")
    ap.add_argument("--shadow-audit", action="store_true",
                    help="online shadow audit: one round-robin member "
                         "lane per batched window is re-simulated through "
                         "the serial reference program on a dedicated "
                         "worker and compared bit-for-bit — divergence is "
                         "surfaced as a backend fault (counter + event + "
                         "retained trace + tenant-journal persist), never "
                         "a tenant quarantine (docs/OBSERVABILITY.md "
                         "\"Shadow audit\")")
    ap.add_argument("--grpc-cert", default="")
    ap.add_argument("--grpc-key", default="")
    ap.add_argument("--grpc-client-ca", default="")
    ap.add_argument("--self-signed-cert-dir", default="",
                    help="generate+rotate a serving cert here when no "
                         "--grpc-cert is given (rotation rebinds the gRPC "
                         "listener — grpc credentials hold the PEM bytes)")
    args = ap.parse_args(argv)
    cm = None
    cert, key = args.grpc_cert, args.grpc_key
    if not cert and args.self_signed_cert_dir:
        from kubernetes_autoscaler_tpu.utils.certs import CertManager

        cm = CertManager(args.self_signed_cert_dir, common_name="localhost")
        cert, key = cm.cert_path, cm.key_path
    service = SimulatorService(batch_lanes=args.batch_lanes,
                               batch_window_ms=args.batch_window_ms,
                               batch_window_max=args.batch_window_max or None,
                               queue_depth=args.queue_depth,
                               quarantine_ttl_s=args.quarantine_ttl_s,
                               rehydrate_dir=args.checkpoint_dir,
                               hbm_budget_frac=args.hbm_budget_frac,
                               hbm_limit_bytes=args.hbm_limit_bytes,
                               device_profile_dir=args.device_profile_dir,
                               shadow_audit=args.shadow_audit)
    if args.checkpoint_dir and service.rehydration["restored"]:
        print(f"katpu-sidecar rehydrated "
              f"{service.rehydration['restored']} tenants from "
              f"{args.checkpoint_dir} "
              f"(digest_mismatch={service.rehydration['digest_mismatch']})",
              flush=True)
    # graceful termination checkpoints the tenant table: SIGTERM (the
    # orchestrated shutdown path) raises into the KeyboardInterrupt branch
    import signal

    def _term(_sig, _frm):
        raise KeyboardInterrupt

    try:
        signal.signal(signal.SIGTERM, _term)
    except ValueError:   # pragma: no cover — non-main-thread embedding
        pass

    def bind():
        srv, bound = make_grpc_server(
            service, args.port, cert_file=cert or None, key_file=key or None,
            client_ca_file=args.grpc_client_ca or None, host=args.host)
        srv.start()
        return srv, bound

    server, bound = bind()
    print(f"katpu-sidecar listening on {args.host}:{bound} "
          f"({'tls' if cert else 'insecure'}; "
          f"batch_lanes={args.batch_lanes})", flush=True)
    try:
        while True:
            time.sleep(3600)
            if cm is not None and cm.ensure():
                # rotated: grpc server credentials are immutable — rebind
                # with the fresh pair (the snapshot state lives in `service`
                # and survives the rebind)
                server.stop(5.0).wait()
                server, bound = bind()
                print(f"katpu-sidecar rotated serving cert; rebound on "
                      f"{args.host}:{bound}", flush=True)
    except KeyboardInterrupt:
        server.stop(2.0)
        if args.checkpoint_dir:
            ck = service.checkpoint(args.checkpoint_dir)
            print(f"katpu-sidecar checkpointed {ck['tenants']} tenants to "
                  f"{args.checkpoint_dir}", flush=True)
        service.close()


if __name__ == "__main__":
    main()
