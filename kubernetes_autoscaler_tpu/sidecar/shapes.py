"""Shape classes: the padded (nodes × groups × pods) ladder tenants bucket into.

Multi-tenant serving (docs/SERVING.md) batches many tenants' simulations into
one vmapped dispatch — which requires their worlds to share ONE padded tensor
shape, because a fresh shape is a fresh XLA program (~seconds of compile on
the serving path). This module owns that quantization: a small fixed ladder
of geometric rungs per axis, seeded from the same node/group/pod bucket
bases `models/incremental.py` uses for its delta-scatter padding, so the
sidecar's shape discipline matches the in-process encoder's.

A rung is `base * 2^k`, so the whole ladder for a 64-base axis serving up to
1M rows is 15 classes — new tenants land in an existing class with
probability ≈ 1, which is what makes the "≈0 recompiles for a new tenant"
guarantee (`recompiles_per_new_tenant` gauge, CI-asserted like PR 2's
`steady_state_recompiles`) achievable at all.

Counters: `shape_class_hit_total` / `shape_class_miss_total` count
classifications against the set of classes already seen — a miss means a new
padded shape entered the ladder and the next dispatch at that shape will
compile. The hit RATE over a traffic window is the bench's
`shape_class_hit_rate` (1.0 after warmup, asserted in CI).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class ShapeClass:
    """One padded world shape: every tenant in the class exports tensors at
    exactly these leading dims (pad rows invalid-masked), so their worlds
    stack into one pytree and share one compiled batched program."""

    nodes: int
    groups: int
    pods: int

    @property
    def key(self) -> str:
        return f"n{self.nodes}g{self.groups}p{self.pods}"


def rung(n: int, base: int) -> int:
    """Smallest base*2^k ≥ n (n ≤ 0 → base). Geometric, unlike the linear
    `pad_to` multiples: a ladder of multiples would mint a distinct class
    per bucket increment and compile-store one program per tenant size."""
    if base <= 0:
        raise ValueError(f"rung base must be positive, got {base}")
    r = base
    while r < n:
        r *= 2
    return r


class ShapeLadder:
    """Classifier + seen-set + hit/miss accounting. Thread-safe: the gRPC
    pool classifies concurrently."""

    def __init__(self, node_bucket: int = 64, group_bucket: int = 64,
                 pod_bucket: int = 256, registry=None):
        self.node_bucket = node_bucket
        self.group_bucket = group_bucket
        self.pod_bucket = pod_bucket
        self._seen: set[ShapeClass] = set()
        self._lock = threading.Lock()
        self._registry = registry
        self.hits = 0
        self.misses = 0

    def classify(self, n_nodes: int, n_groups: int, n_pods: int,
                 tenant: str = "") -> ShapeClass:
        """Assign counts to a class and account the hit/miss. Counts within
        a rung re-classify to the SAME class — count churn (pods added or
        removed inside the rung) is always a hit, never a recompile, the
        same stability contract as the delta-scatter buckets.

        `tenant` additionally labels the registry series so a departed
        tenant's classification history can be stale-zeroed by
        `drop_tenant` (the rpc_total convention); the default tenant keeps
        label-free series (it is never dropped)."""
        sc = ShapeClass(
            nodes=rung(n_nodes, self.node_bucket),
            groups=rung(max(n_groups, 1), self.group_bucket),
            pods=rung(n_pods, self.pod_bucket),
        )
        with self._lock:
            hit = sc in self._seen
            if hit:
                self.hits += 1
            else:
                self._seen.add(sc)
                self.misses += 1
        if self._registry is not None:
            name = ("shape_class_hit_total" if hit
                    else "shape_class_miss_total")
            labels = {"shape_class": sc.key}
            if tenant:
                labels["tenant"] = tenant
            self._registry.counter(
                name,
                help="World classifications landing in an already-seen "
                     "(hit) vs a brand-new (miss) padded shape class — a "
                     "miss precedes exactly one batched-program compile",
            ).inc(**labels)
        return sc

    def seen(self) -> frozenset[ShapeClass]:
        with self._lock:
            return frozenset(self._seen)

    def hit_rate(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return (self.hits / total) if total else 1.0
