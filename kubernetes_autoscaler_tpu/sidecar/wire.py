"""KAD1 wire serialization: api objects → snapshot-delta bytes.

The client half of the sidecar boundary (a Go control plane implements the
same trivial format; see native/kacodec.cc header for the byte layout). This
is the versioned snapshot-diff protocol SURVEY.md §7 calls for — per loop the
control plane sends only changed nodes/pods instead of re-uploading the world
(the reference's DeltaSnapshotStore idea, delta.go:33-54, moved to the wire).
"""

from __future__ import annotations

import struct

from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.models.api import (
    NO_EXECUTE,
    NO_SCHEDULE,
    Node,
    Pod,
)
from kubernetes_autoscaler_tpu.models.encode import (
    equivalence_key,
    node_capacity_vector,
    pod_request_vector,
)

MAGIC = b"KAD1"

# Trace context rides gRPC request metadata under this key — NEVER the KAD1
# body or KAUX trailer. The dense bytes stay trace-free so committed goldens
# (tests/test_wire_conformance.py) and independent Go encoders are untouched
# by whether the caller happens to be tracing; the server echoes its child
# spans back in the RESPONSE json ("trace" field), also off-wire-format.
TRACE_ID_HEADER = "katpu-trace-id"

# Tenant identity for the multi-tenant serving sidecar (docs/SERVING.md)
# rides request metadata exactly like the trace id — NEVER the KAD1 bytes,
# so single-tenant encoders (the committed goldens, the Go shim) are
# untouched. Absent/empty header = the default tenant: the pre-multi-tenant
# wire behavior, byte-for-byte.
TENANT_ID_HEADER = "katpu-tenant-id"

# Backpressure: a RESOURCE_EXHAUSTED rejection carries its retry hint in
# trailing metadata under this key (milliseconds, decimal string).
RETRY_AFTER_MS_HEADER = "katpu-retry-after-ms"

# Snapshot-version pinning for ApplyDelta (decimal string): a client that
# tracks its server-side world version stamps the version its delta was
# built AGAINST here; a mismatch (most importantly: the server restarted
# and holds version 0 or a rehydrated world) rejects INVALID_ARGUMENT with
# reason `section-version-mismatch` instead of silently applying a delta to
# the wrong base snapshot — the client's signal to full-resend
# (docs/ROBUSTNESS.md, warm restart).
BASE_VERSION_HEADER = "katpu-base-version"

# Per-tenant SLO budget declaration (milliseconds, decimal string): a client
# that knows its own loop deadline stamps it here; the server registers it
# as the tenant's latency budget (sidecar/lifecycle.SloBudgets) and counts
# `tenant_slo_breaches_total{tenant}` against it — metadata only, the KAD1
# bytes stay SLO-free like trace/tenant identity above.
SLO_BUDGET_MS_HEADER = "katpu-slo-budget-ms"

UPSERT_NODE, DELETE_NODE, UPSERT_POD, DELETE_POD = 1, 2, 3, 4

_EFFECTS = {NO_SCHEDULE: 0, NO_EXECUTE: 1}


def _s(out: bytearray, s: str) -> None:
    b = s.encode("utf-8")
    out += struct.pack("<H", len(b))
    out += b


AUX_MAGIC = b"KAUX"


class DeltaWriter:
    def __init__(self, registry: res.ExtendedResourceRegistry | None = None):
        self.registry = registry or res.ExtendedResourceRegistry()
        self._body = bytearray()
        self._count = 0
        # constraint side-channel (v1.1): topology-coupled pod specs the dense
        # KAD1 rows cannot carry ride a trailer the C++ codec skips (it reads
        # exactly `count` ops) and the PYTHON sidecar consumes — so sidecar-fed
        # clusters get the device constrained tier instead of blanket
        # host-checking. Labels ship for every labeled pod: they are the
        # TARGETS of other pods' selectors (plane counting).
        self._aux_upserts: dict[str, dict] = {}
        self._aux_deletes: list[str] = []

    def upsert_node(self, node: Node, group_id: int = -1) -> "DeltaWriter":
        b = self._body
        b.append(UPSERT_NODE)
        _s(b, node.name)
        b += struct.pack("<H", len(node.labels))
        for k, v in node.labels.items():
            _s(b, k)
            _s(b, v)
        taints = node.taints
        b.append(len(taints))
        for t in taints:
            _s(b, t.key)
            _s(b, t.value)
            b.append(_EFFECTS.get(t.effect, 2))
        cap = node_capacity_vector(node, self.registry)
        b += struct.pack(f"<{res.NUM_RESOURCES}i", *cap.tolist())
        b.append((1 if node.ready else 0) | (2 if node.unschedulable else 0))
        b += struct.pack("<i", group_id)
        _s(b, node.zone())
        self._count += 1
        return self

    def delete_node(self, name: str) -> "DeltaWriter":
        self._body.append(DELETE_NODE)
        _s(self._body, name)
        self._count += 1
        return self

    def upsert_pod(self, pod: Pod, movable: bool = False,
                   blocks: bool = False) -> "DeltaWriter":
        b = self._body
        b.append(UPSERT_POD)
        _s(b, pod.uid or f"{pod.namespace}/{pod.name}")
        _s(b, pod.node_name)
        req, req_lossy = pod_request_vector(pod, self.registry)
        b += struct.pack(f"<{res.NUM_RESOURCES}i", *req.tolist())
        sel = sorted(pod.node_selector.items())
        b += struct.pack("<H", len(sel))
        for k, v in sel:
            _s(b, k)
            _s(b, v)
        b.append(len(pod.tolerations))
        for t in pod.tolerations:
            _s(b, t.key)
            b.append(1 if t.operator == "Exists" else 0)
            _s(b, t.value)
            b.append(_EFFECTS.get(t.effect, 2) if t.effect else 2)
        b.append(len(pod.host_ports))
        for port, proto in pod.host_ports:
            b += struct.pack("<H", port)
            b.append(1 if proto == "UDP" else 0)
        anti_self = any(
            term.topology_key == "kubernetes.io/hostname"
            and term.match_labels
            and all(pod.labels.get(k) == v for k, v in term.match_labels.items())
            for term in pod.anti_affinity
        )
        # lossy mirrors _encode_pod_spec: shapes the dense wire can't express.
        # Uses the ACCESSORS so both the legacy sugar fields and the full
        # list forms (topology_spread, node_affinity_terms, resource_claims)
        # route to the host-check tier rather than silently dropping.
        lossy = bool(
            req_lossy
            or pod.affinity_node_terms()
            or pod.pod_affinity
            or pod.spread_constraints()
            or pod.resource_claims
            or any(not (t.topology_key == "kubernetes.io/hostname"
                        and t.match_labels
                        and all(pod.labels.get(k) == v
                                for k, v in t.match_labels.items()))
                   for t in pod.anti_affinity)
        )
        b.append((1 if movable else 0) | (2 if blocks else 0)
                 | (4 if anti_self else 0) | (8 if lossy else 0))
        eqkey = str(equivalence_key(pod))
        _s(b, eqkey)
        self._maybe_aux(pod, eqkey)
        self._count += 1
        return self

    def _maybe_aux(self, pod: Pod, eqkey: str) -> None:
        uid = pod.uid or f"{pod.namespace}/{pod.name}"
        has_topology = bool(pod.pod_affinity or pod.anti_affinity
                            or pod.spread_constraints())
        if not (has_topology or pod.labels):
            # a re-upsert that no longer qualifies must CLEAR any earlier
            # record on the server, or stale labels keep feeding the planes
            self._aux_upserts.pop(uid, None)
            if uid not in self._aux_deletes:
                self._aux_deletes.append(uid)
            return
        rec: dict = {
            "k": eqkey, "ns": pod.namespace, "l": dict(pod.labels),
            "n": pod.node_name,
            # the wire's lossy bit is CONSERVATIVE (set for any topology
            # constraint so aux-unaware servers host-check); "dok" tells the
            # overlay whether topology was the ONLY cause — i.e. the bit may
            # be cleared once the overlay models the constraints
            "dok": not (pod_request_vector(pod, self.registry)[1]
                        or pod.affinity_node_terms()
                        or pod.resource_claims),
        }
        cons = pod.spread_constraints()
        if cons:
            c = cons[0]
            # matchLabelKeys merges into "sel" AT THE ENCODER (the Go shim
            # does the same — common.go:96-104 is a static per-pod merge);
            # minDomains / non-default inclusion policies ride as fields the
            # overlay routes to the exact host-check tier
            rec["s"] = {"key": c.topology_key, "w": int(c.max_skew),
                        "sel": dict(c.merged_selector(pod.labels)),
                        "extra": len(cons) > 1,
                        "md": int(c.min_domains),
                        "nap": c.node_affinity_policy,
                        "ntp": c.node_taints_policy}
        if pod.pod_affinity:
            t = pod.pod_affinity[0]
            rec["a"] = {"key": t.topology_key, "sel": dict(t.match_labels),
                        "nss": list(t.namespaces),
                        "nssel": (dict(t.namespace_selector)
                                  if t.namespace_selector is not None
                                  else None),
                        "extra": len(pod.pod_affinity) > 1}
        if pod.anti_affinity:
            rec["x"] = [{"key": t.topology_key, "sel": dict(t.match_labels),
                         "nss": list(t.namespaces),
                         "nssel": (dict(t.namespace_selector)
                                   if t.namespace_selector is not None
                                   else None)}
                        for t in pod.anti_affinity]
        # within-payload coherence: a uid lives in exactly ONE list, with the
        # LAST op winning (the server applies upserts then deletes, so mixed
        # membership would net to deletion regardless of op order)
        if uid in self._aux_deletes:
            self._aux_deletes.remove(uid)
        self._aux_upserts[uid] = rec

    def delete_pod(self, uid: str) -> "DeltaWriter":
        self._body.append(DELETE_POD)
        _s(self._body, uid)
        self._aux_upserts.pop(uid, None)
        if uid not in self._aux_deletes:
            self._aux_deletes.append(uid)
        self._count += 1
        return self

    def payload(self) -> bytes:
        import json
        import zlib

        out = MAGIC + struct.pack("<I", self._count) + bytes(self._body)
        if self._aux_upserts or self._aux_deletes:
            doc = json.dumps({"up": self._aux_upserts,
                              "del": self._aux_deletes}).encode()
            # reverse-parsable trailer: [json][u32 len][u32 crc32][KAUX];
            # the crc makes a coincidental 'KAUX' suffix in a plain payload
            # statistically impossible to mis-split
            out += (doc + struct.pack("<I", len(doc))
                    + struct.pack("<I", zlib.crc32(doc)) + AUX_MAGIC)
        return out


def split_aux(payload: bytes) -> tuple[bytes, dict | None]:
    """(KAD1 bytes for the C++ codec, parsed aux doc or None). A malformed or
    coincidental trailer (bad length / crc / json shape) yields the payload
    unchanged — never a truncated dense body."""
    import json
    import zlib

    if len(payload) < 12 or payload[-4:] != AUX_MAGIC:
        return payload, None
    (crc,) = struct.unpack("<I", payload[-8:-4])
    (n,) = struct.unpack("<I", payload[-12:-8])
    if n > len(payload) - 12:
        return payload, None
    doc_bytes = payload[-12 - n:-12]
    if zlib.crc32(doc_bytes) != crc:
        return payload, None
    try:
        doc = json.loads(doc_bytes)
    except ValueError:
        return payload, None
    if not isinstance(doc, dict) or not set(doc) <= {"up", "del"}:
        return payload, None
    return payload[: len(payload) - 12 - n], doc
