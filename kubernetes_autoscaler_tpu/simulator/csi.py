"""CSI volume-limit tracking on the tensor plane.

Reference counterpart: simulator/csi/ (269 LoC, flag-gated — SURVEY.md §2.3):
a fork/commit/revert snapshot of CSINode objects so the scheduler's volume-
limits filter sees simulated attach counts.

TPU re-design: same lowering pattern as DRA — each CSI driver's attachable
volume limit becomes an extended-resource slot ("csi/<driver>"): node
capacity = the driver's allocatable count from CSINode, pod request = how
many of the pod's PVCs that driver serves. The volume-limits predicate then
IS the resource-fit comparison; fork/commit/revert ride the pytree snapshot.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.models.api import Node, Pod

CSI_RESOURCE_PREFIX = "csi/"


@dataclass
class CSINodeDriver:
    name: str
    allocatable_count: int = 0      # max attachable volumes (0 = unlimited)


@dataclass
class CSINode:
    """reference: storage.k8s.io CSINode, joined into framework.NodeInfo
    (infos.go:57-68)."""

    node_name: str
    drivers: list[CSINodeDriver] = field(default_factory=list)


@dataclass
class CsiSnapshot:
    csi_nodes: dict[str, CSINode] = field(default_factory=dict)
    # pvc (namespace/name) -> driver name, from PV/StorageClass resolution
    pvc_driver: dict[str, str] = field(default_factory=dict)

    def add(self, csi_node: CSINode) -> None:
        self.csi_nodes[csi_node.node_name] = csi_node

def apply_csi(nodes: list[Node], pods: list[Pod], csi: CsiSnapshot):
    """Lower volume limits into the resource axis before encode_cluster.

    Like apply_dra, previously-lowered state is CLEARED first so removed
    CSINodes/PVC mappings leave no phantom limits on the persistent
    objects."""
    clear_csi_lowering(nodes, pods)
    drivers_seen: set[str] = set()
    for nd in nodes:
        cn = csi.csi_nodes.get(nd.name)
        if cn is None:
            continue
        for d in cn.drivers:
            if d.allocatable_count <= 0:
                continue
            key = CSI_RESOURCE_PREFIX + d.name
            nd.capacity[key] = d.allocatable_count
            if nd.allocatable:
                nd.allocatable[key] = d.allocatable_count
            drivers_seen.add(d.name)

    # A PVC mounted by several pods occupies ONE attachment on a node, not
    # one per pod (the scheduler's volume-limits filter counts unique
    # volumes). The dense per-pod lowering can't express sharing, so the
    # FIRST referencing pod carries the charge and the rest go through the
    # host-check tier (the same exactness pattern as shared DRA claims).
    from kubernetes_autoscaler_tpu.models.api import HOST_CHECK_ANNOTATION

    pvc_owners: dict[str, str] = {}
    pvc_refcount: dict[str, int] = {}
    for pod in pods:
        for ref in pod.pvc_refs:
            key = ref if "/" in ref else f"{pod.namespace}/{ref}"
            pvc_refcount[key] = pvc_refcount.get(key, 0) + 1
            pvc_owners.setdefault(key, pod.name)

    for pod in pods:
        per_driver: dict[str, int] = {}
        lossy = False
        for ref in pod.pvc_refs:
            key = ref if "/" in ref else f"{pod.namespace}/{ref}"
            driver = csi.pvc_driver.get(key)
            if not driver:
                continue
            if pvc_refcount.get(key, 1) > 1:
                lossy = True
                if pvc_owners.get(key) != pod.name:
                    continue  # a sibling already carries the attachment
            per_driver[driver] = per_driver.get(driver, 0) + 1
        # overwrite, not accumulate — the loop re-lists the same Pod objects
        # every tick and this pass must be idempotent
        for driver, n in per_driver.items():
            if driver in drivers_seen:
                pod.requests[CSI_RESOURCE_PREFIX + driver] = n
        if lossy:
            from kubernetes_autoscaler_tpu.models.api import (
                CSI_LOSSY_ANNOTATION,
            )

            pod.annotations[HOST_CHECK_ANNOTATION] = "true"
            pod.annotations[CSI_LOSSY_ANNOTATION] = "true"


    from kubernetes_autoscaler_tpu.models.api import CSI_LOSSY_ANNOTATION
    from kubernetes_autoscaler_tpu.simulator.dynamicresources import (
        lowering_fingerprint,
    )

    return lowering_fingerprint(nodes, pods, CSI_RESOURCE_PREFIX,
                                (CSI_LOSSY_ANNOTATION,))


def clear_csi_lowering(nodes: list[Node], pods: list[Pod]) -> None:
    """Remove everything a previous apply_csi pass wrote."""
    from kubernetes_autoscaler_tpu.models.api import (
        CSI_LOSSY_ANNOTATION,
        DRA_LOSSY_ANNOTATION,
        HOST_CHECK_ANNOTATION,
    )
    from kubernetes_autoscaler_tpu.simulator.dynamicresources import (
        clear_prefixed_resources,
    )

    clear_prefixed_resources(nodes, pods, CSI_RESOURCE_PREFIX)
    for p in pods:
        if p.annotations.pop(CSI_LOSSY_ANNOTATION, None) is not None \
                and DRA_LOSSY_ANNOTATION not in p.annotations:
            p.annotations.pop(HOST_CHECK_ANNOTATION, None)
