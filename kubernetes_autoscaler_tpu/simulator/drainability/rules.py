"""Drainability rules: classify every resident pod for scale-down.

Reference counterpart: simulator/drain.go:49-86 GetPodsToMove running the
ordered rule chain in simulator/drainability/rules/ (one subdir per rule:
mirror, longterminating, terminal, daemonset, safetoevict, notsafetoevict,
replicated, system, localstorage, pdb — rules.Default in rules/rules.go).

Verdicts map onto the tensor plane (ScheduledPodTensors):
  SKIP  — pod neither blocks nor needs rescheduling (mirror/daemonset/terminal:
          the kubelet or controller handles it; reference returns them in
          nothing-to-do lists)
  DRAIN — pod is evictable and must find a new home (movable=True)
  BLOCK — pod forbids removing its node (blocks=True)

PDB accounting is a separate tracker (core/scaledown/pdb.py) consulted at
selection time, mirroring the reference's RemainingPdbTracker split.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from enum import Enum

from kubernetes_autoscaler_tpu.models.api import SAFE_TO_EVICT_KEY, Pod

# reference: drainability/rules/longterminating uses an extended grace period
LONG_TERMINATING_THRESHOLD_S = 6 * 60.0


class Verdict(Enum):
    SKIP = "skip"
    DRAIN = "drain"
    BLOCK = "block"


@dataclass(frozen=True)
class DrainOptions:
    """Mirrors the drain-related AutoscalingOptions flags
    (config/autoscaling_options.go: SkipNodesWithSystemPods,
    SkipNodesWithLocalStorage, SkipNodesWithCustomControllerPods)."""

    skip_nodes_with_system_pods: bool = True
    skip_nodes_with_local_storage: bool = True
    skip_nodes_with_custom_controller_pods: bool = False
    # reference: rules/replicacount — a replicated pod whose controller runs
    # fewer than this many replicas blocks the drain (--min-replica-count)
    min_replica_count: int = 0

    # namespaces whose pods are "system" for the system rule
    system_namespace: str = "kube-system"


_REPLICATED_KINDS = {"ReplicaSet", "ReplicationController", "Job", "StatefulSet"}


def classify_pod(
    pod: Pod,
    opts: DrainOptions = DrainOptions(),
    now: float | None = None,
    has_pdb: bool = False,
    owner_replicas: int | None = None,
) -> Verdict:
    """Ordered rule chain; first decisive rule wins (reference rules.go order)."""
    now = time.time() if now is None else now

    # mirror (static kubelet pods): stay with the node, never block
    if pod.is_mirror():
        return Verdict.SKIP
    # long-terminating: already going away
    if pod.deletion_timestamp is not None and (
        now - pod.deletion_timestamp > LONG_TERMINATING_THRESHOLD_S
    ):
        return Verdict.SKIP
    # terminal: Succeeded/Failed never reschedule
    if pod.phase in ("Succeeded", "Failed"):
        return Verdict.SKIP
    # daemonset: the DS controller re-creates on remaining nodes; not our problem
    if pod.is_daemonset():
        return Verdict.SKIP

    safe = pod.annotations.get(SAFE_TO_EVICT_KEY)
    if safe == "false":
        return Verdict.BLOCK
    if safe == "true":
        return Verdict.DRAIN

    # replicated rule: a pod nobody would re-create blocks the drain
    controlled = pod.owner is not None and pod.owner.controller
    if not controlled:
        return Verdict.BLOCK
    if (
        pod.owner.kind not in _REPLICATED_KINDS
        and not opts.skip_nodes_with_custom_controller_pods
    ):
        # custom-controller pods block unless the operator opted out
        return Verdict.BLOCK

    # replicacount rule: a controller running below --min-replica-count
    # cannot spare a disruption (reference: rules/replicacount/rule.go —
    # desired replicas approximated by the controller's live pod count)
    if (opts.min_replica_count > 0 and owner_replicas is not None
            and owner_replicas < opts.min_replica_count):
        return Verdict.BLOCK

    # system rule: kube-system pods without a PDB block (reference: rules/system)
    if (
        opts.skip_nodes_with_system_pods
        and pod.namespace == opts.system_namespace
        and not has_pdb
    ):
        return Verdict.BLOCK

    # local storage rule
    if opts.skip_nodes_with_local_storage and pod.volumes_with_local_storage > 0:
        return Verdict.BLOCK

    return Verdict.DRAIN


def owner_replica_counts(*pod_lists) -> dict[str, int]:
    """Live pod count per controller uid (the observed stand-in for the
    controller's desired replicas, reference rules/replicacount)."""
    counts: dict[str, int] = {}
    for pods in pod_lists:
        for p in pods:
            if p is None or p.owner is None or p.phase in ("Succeeded",
                                                           "Failed"):
                continue
            counts[p.owner.uid] = counts.get(p.owner.uid, 0) + 1
    return counts


def apply_drainability(enc, opts: DrainOptions = DrainOptions(),
                       now: float | None = None, pdb_namespaced_names=frozenset()):
    """Populate ScheduledPodTensors.movable/blocks on an EncodedCluster in place."""
    import jax.numpy as jnp
    import numpy as np

    movable = np.zeros((enc.scheduled.p,), bool)
    blocks = np.zeros((enc.scheduled.p,), bool)
    owner_counts = owner_replica_counts(
        enc.scheduled_pods, enc.pending_pods) \
        if opts.min_replica_count > 0 else {}
    for j, pod in enumerate(enc.scheduled_pods):
        v = classify_pod(
            pod, opts, now=now,
            has_pdb=f"{pod.namespace}/{pod.name}" in pdb_namespaced_names,
            owner_replicas=(owner_counts.get(pod.owner.uid)
                            if pod.owner is not None else None),
        )
        movable[j] = v is Verdict.DRAIN
        blocks[j] = v is Verdict.BLOCK
    enc.scheduled = enc.scheduled.replace(
        movable=jnp.asarray(movable), blocks=jnp.asarray(blocks)
    )
    if enc.host_arrays is not None:  # keep the host mirror coherent
        enc.host_arrays["scheduled.movable"] = movable
        enc.host_arrays["scheduled.blocks"] = blocks
        if enc.host_mirror_token is not None:
            # the replaced device arrays ARE mirrored by the new host arrays
            enc.host_mirror_token["scheduled.movable"] = enc.scheduled.movable
            enc.host_mirror_token["scheduled.blocks"] = enc.scheduled.blocks
    return enc
