"""Dynamic Resource Allocation (DRA): device claims on the tensor plane.

Reference counterpart: simulator/dynamicresources/ (2679 LoC — SURVEY.md
§2.3): a fork/commit/revert patchset store of ResourceClaims / ResourceSlices
/ DeviceClasses, with claim allocation and reservation performed during
simulated scheduling, plus eager joining of slices into NodeInfos
(predicate_snapshot.go:72-120).

TPU re-design: the pointer-graph store disappears. Devices are counted per
(node, device-class) and LOWERED INTO THE RESOURCE AXIS before encoding:
each device class maps to an extended-resource slot ("dra/<class>"), node
device counts become capacity, per-pod claims become requests. Feasibility,
allocation charging, and fork/commit/revert then ride the existing
int32 resource tensors for free — one comparison per class on the VPU
instead of per-device object matching.

Exactness tiering (the framework-wide pattern): what the dense encoding
cannot express — CEL-style device attribute selectors, shared multi-pod
claims (ReservedFor), partitionable devices — sets `needs_host_check`, and
the winner-verification tier re-checks with `claim_fits_exact` before
actuation (same contract as oracle.check_pod_on_node for affinity).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.models.api import (
    HOST_CHECK_ANNOTATION,
    Node,
    Pod,
)

DRA_RESOURCE_PREFIX = "dra/"
# markers recording what apply_dra wrote onto the persistent objects, so the
# next pass can CLEAR residue when claims/slices disappear (the loop
# re-lists the same Node/Pod objects every tick)
DRA_PIN_ANNOTATION = "autoscaler.x-k8s.io/dra-pinned-host"
# the USER's own hostname selector value the pin overwrote (restored on clear)
DRA_PIN_PREV_ANNOTATION = "autoscaler.x-k8s.io/dra-pinned-host-prev"
from kubernetes_autoscaler_tpu.models.api import (  # noqa: E402
    CSI_LOSSY_ANNOTATION,
    DRA_LOSSY_ANNOTATION,
)


@dataclass
class DeviceClass:
    """reference: resource.k8s.io DeviceClass (simulator/dynamicresources
    snapshot stores these verbatim)."""

    name: str
    # class-level required attributes (every device of the class has them)
    attributes: dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceSlice:
    """A pool of identical devices one node publishes (reference:
    ResourceSlice; LocalResourceSlices joined into NodeInfo at
    framework/infos.go:57)."""

    node_name: str
    device_class: str
    count: int
    # per-device attributes for selector matching (uniform within a slice)
    attributes: dict[str, str] = field(default_factory=dict)


@dataclass
class ClaimRequest:
    """One request inside a claim: N devices of a class, optionally
    attribute-constrained (the simulable subset of CEL selectors:
    attribute equality)."""

    device_class: str
    count: int = 1
    selector: dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceClaim:
    """reference: ResourceClaim/ResourceClaimTemplate. `owner_pod` empty means
    a shared claim (multiple pods reserve it) — host-check tier."""

    name: str
    namespace: str = "default"
    requests: list[ClaimRequest] = field(default_factory=list)
    owner_pod: str = ""               # pod name for per-pod (template) claims
    allocated_node: str = ""          # "" = unallocated
    reserved_for: list[str] = field(default_factory=list)


# ReservedFor list cap (reference: resourceapi.ResourceClaimReservedForMaxSize)
RESERVED_FOR_MAX = 32


@dataclass
class DraSnapshot:
    """The queryable DRA world handed to the lowering pass (reference:
    DraProvider.Snapshot() at static_autoscaler.go:313).

    Fork/commit/revert mirror the reference's patchset store
    (simulator/dynamicresources/snapshot + simulator/common/patchset.go):
    the mutable claim state (allocation + reservations) is checkpointed as an
    overlay stack; slices and classes are immutable within a loop."""

    classes: dict[str, DeviceClass] = field(default_factory=dict)
    slices: list[ResourceSlice] = field(default_factory=list)
    claims: list[ResourceClaim] = field(default_factory=list)
    _stack: list[dict[str, tuple[str, tuple[str, ...]]]] = field(
        default_factory=list, repr=False)

    # ---- fork/commit/revert (reference: patchset Fork/Commit/Revert) ----

    def fork(self) -> None:
        self._stack.append({
            c.name: (c.allocated_node, tuple(c.reserved_for))
            for c in self.claims
        })

    def revert(self) -> None:
        if not self._stack:
            raise RuntimeError("revert without fork")
        saved = self._stack.pop()
        for c in self.claims:
            if c.name in saved:
                node, reserved = saved[c.name]
                c.allocated_node = node
                c.reserved_for = list(reserved)

    def commit(self) -> None:
        if not self._stack:
            raise RuntimeError("commit without fork")
        self._stack.pop()  # keep the current (child) state

    # ---- queries ----

    def claim_by_name(self, name: str, namespace: str = "default"
                      ) -> ResourceClaim | None:
        for c in self.claims:
            if c.name == name and c.namespace == namespace:
                return c
        return None

    def claims_for_pod(self, pod: Pod) -> list[ResourceClaim]:
        """Owned (template) claims plus referenced shared claims."""
        out = [c for c in self.claims
               if c.owner_pod == pod.name and c.namespace == pod.namespace]
        for name in pod.resource_claims:
            c = self.claim_by_name(name, pod.namespace)
            if c is not None and c not in out:
                out.append(c)
        return out

    def sharers_of(self, claim: ResourceClaim, pods: list[Pod]) -> list[Pod]:
        return [p for p in pods
                if p.namespace == claim.namespace
                and (claim.name in p.resource_claims
                     or claim.owner_pod == p.name)]

    def device_capacity(self) -> dict[str, dict[str, int]]:
        """node -> class -> device count. Global slices (node_name == "")
        are pool devices not tied to any node and impose no node constraint."""
        out: dict[str, dict[str, int]] = {}
        for s in self.slices:
            if not s.node_name:
                continue
            per = out.setdefault(s.node_name, {})
            per[s.device_class] = per.get(s.device_class, 0) + s.count
        return out

    # ---- reservation (reference: claim reservation in RunReserve) ----

    def reserve(self, claim: ResourceClaim, pod: Pod, node_name: str) -> bool:
        """Allocate (if needed) and add the pod to ReservedFor. False when
        the claim is bound elsewhere or the ReservedFor list is full."""
        if claim.allocated_node and claim.allocated_node != node_name:
            if self._is_node_local(claim):
                return False
        if len(claim.reserved_for) >= RESERVED_FOR_MAX:
            return False
        if not claim.allocated_node and self._is_node_local(claim):
            claim.allocated_node = node_name
        ref = f"{pod.namespace}/{pod.name}"
        if ref not in claim.reserved_for:
            claim.reserved_for.append(ref)
        return True

    def release(self, pod: Pod) -> None:
        """Drop the pod's reservations; deallocate claims nobody holds
        (reference: unreserve + deallocation on drain/unschedule)."""
        ref = f"{pod.namespace}/{pod.name}"
        for c in self.claims_for_pod(pod):
            if ref in c.reserved_for:
                c.reserved_for.remove(ref)
            if not c.reserved_for:
                c.allocated_node = ""

    def _is_node_local(self, claim: ResourceClaim) -> bool:
        """A claim binds to one node unless EVERY request's class is served
        by a global pool (node_name == "" slices). Classes with no slices at
        all — e.g. scale-from-zero, where only templates advertise devices —
        are node-local (the conservative and correct default)."""
        for req in claim.requests:
            has_global = any(not s.node_name
                             and s.device_class == req.device_class
                             for s in self.slices)
            if not has_global:
                return True
        return False


def slice_matches(s: ResourceSlice, req: ClaimRequest,
                  classes: dict[str, DeviceClass]) -> bool:
    if s.device_class != req.device_class:
        return False
    attrs = dict(classes.get(req.device_class, DeviceClass(req.device_class)).attributes)
    attrs.update(s.attributes)
    return all(attrs.get(k) == v for k, v in req.selector.items())


def claim_fits_exact(claim: ResourceClaim, node: Node, dra: DraSnapshot,
                     allocated: dict[tuple[str, str], int] | None = None) -> bool:
    """Host-side exact check: every request satisfiable from the node's
    matching slices minus what's already allocated (the winner-verification
    tier for selectored/shared claims)."""
    allocated = allocated or {}
    for req in claim.requests:
        avail = 0
        for s in dra.slices:
            if s.node_name != node.name:
                continue
            if slice_matches(s, req, dra.classes):
                avail += s.count
        avail -= allocated.get((node.name, req.device_class), 0)
        if avail < req.count:
            return False
    return True


DRA_SHARED_LABEL_PREFIX = "dra.claim/"


def apply_dra(nodes: list[Node], pods: list[Pod], dra: DraSnapshot):
    """The lowering pass: fold device counts into node capacity and claim
    counts into pod requests as 'dra/<class>' extended resources, BEFORE
    encode_cluster.

    Shared claims (multiple sharers, reference: ReservedFor) lower to:
      * allocated node-local claim  → every PENDING sharer gets a hostname
        nodeSelector to the allocated node (dense); devices are charged to
        that node once by subtracting from its published capacity.
      * unallocated node-local claim → one REPRESENTATIVE sharer carries the
        device request; all sharers get a synthetic self pod-affinity on
        hostname (the gang shape the wave placer handles exactly, including
        the first-pod exception) so they co-locate where the devices are.
      * global-pool claims (only global slices provide the class) impose no
        node constraint and charge nothing node-local.
    Pods with selectored claims or other inexpressible shapes get the
    host-check annotation (claim_fits_exact is the exact tier).

    Totals are recomputed and OVERWRITTEN each pass — the loop re-lists the
    same Pod objects every tick, so += would compound across loops. Every
    DRA-owned mutation is CLEARED up front so deleted claims/slices leave no
    residue (requests/capacity keys, hostname pins, gang labels/affinity,
    the host-check mark) — without this, a removed claim left its pod
    demanding phantom devices forever."""
    clear_dra_lowering(nodes, pods)
    cap = dra.device_capacity()
    # devices held by allocated claims of NON-resident owners (shared claims
    # or claims of departed pods) reduce the node's free devices; resident
    # owners are charged through their own pod requests at encode time
    pods_by_ref = {f"{p.namespace}/{p.name}": p for p in pods}
    held: dict[str, dict[str, int]] = {}
    for claim in dra.claims:
        if not claim.allocated_node:
            continue
        resident_owner = any(
            pods_by_ref.get(ref) is not None
            and pods_by_ref[ref].node_name == claim.allocated_node
            and pods_by_ref[ref].name == claim.owner_pod
            for ref in claim.reserved_for
        )
        if claim.owner_pod and resident_owner:
            continue  # charged via the owner pod's lowered requests
        per = held.setdefault(claim.allocated_node, {})
        for req in claim.requests:
            per[req.device_class] = per.get(req.device_class, 0) + req.count
    for nd in nodes:
        for cls, count in cap.get(nd.name, {}).items():
            key = DRA_RESOURCE_PREFIX + cls
            free = count - held.get(nd.name, {}).get(cls, 0)
            nd.capacity[key] = max(free, 0)
            if nd.allocatable:
                nd.allocatable[key] = max(free, 0)

    shared_rep: dict[str, str] = {}   # claim key -> representative pod name
    for claim in dra.claims:
        sharers = dra.sharers_of(claim, pods)
        if len(sharers) <= 1 or not dra._is_node_local(claim):
            continue
        ckey = f"{claim.namespace}/{claim.name}"
        pending = [p for p in sharers if not p.node_name]
        if claim.allocated_node:
            # bound claim: pending sharers can only go where the devices are
            for p in pending:
                _pin_host(p, claim.allocated_node)
        elif pending:
            shared_rep[ckey] = pending[0].name
            from kubernetes_autoscaler_tpu.models.api import AffinityTerm

            gang_label = DRA_SHARED_LABEL_PREFIX + claim.name
            for p in pending:
                p.labels[gang_label] = "y"
                if not any(t.match_labels == {gang_label: "y"}
                           for t in p.pod_affinity):
                    p.pod_affinity.append(AffinityTerm(
                        match_labels={gang_label: "y"}))

    for pod in pods:
        totals: dict[str, int] = {}
        lossy = False
        for claim in dra.claims_for_pod(pod):
            sharers = dra.sharers_of(claim, pods)
            shared = len(sharers) > 1 or not claim.owner_pod
            if (claim.allocated_node and not pod.node_name
                    and claim.owner_pod == pod.name):
                # owned claim already bound: the pod must follow its devices,
                # which `held` charged to the node (no double charge)
                _pin_host(pod, claim.allocated_node)
                continue
            for req in claim.requests:
                if req.selector:
                    lossy = True
                if not dra._is_node_local(claim):
                    continue  # global pool: no node-local charge
                key = DRA_RESOURCE_PREFIX + req.device_class
                if shared:
                    ckey = f"{claim.namespace}/{claim.name}"
                    if shared_rep.get(ckey) == pod.name:
                        totals[key] = totals.get(key, 0) + req.count
                        lossy = True  # exact tier re-checks the gang charge
                else:
                    totals[key] = totals.get(key, 0) + req.count
        for key, total in totals.items():
            pod.requests[key] = total
        if lossy:
            pod.annotations[HOST_CHECK_ANNOTATION] = "true"
            pod.annotations[DRA_LOSSY_ANNOTATION] = "true"
    return lowering_fingerprint(nodes, pods, DRA_RESOURCE_PREFIX,
                                (DRA_PIN_ANNOTATION, DRA_LOSSY_ANNOTATION))


def lowering_fingerprint(nodes, pods, prefix: str,
                         annotations: tuple[str, ...]) -> int:
    """Hash of everything a lowering pass WROTE onto the live objects.

    The control plane compares this per loop to decide whether the
    incremental encoder must rebuild: the lowered OUTPUT depends on the pod
    set (claim residency, PVC sharing), not just the DRA/CSI snapshots, so
    fingerprinting the inputs is not enough. Only prefixed keys and the
    pass's own annotations contribute — O(touched objects), not O(world)."""
    acc = hash(prefix)
    for nd in nodes:
        for k, v in nd.capacity.items():
            if k.startswith(prefix):
                acc = hash((acc, nd.name, k, v))
    for p in pods:
        for k, v in p.requests.items():
            if k.startswith(prefix):
                acc = hash((acc, p.namespace, p.name, k, v))
        for k in p.labels:
            if k.startswith(DRA_SHARED_LABEL_PREFIX):
                acc = hash((acc, p.namespace, p.name, k))
        for a in annotations:
            v = p.annotations.get(a)
            if v is not None:
                acc = hash((acc, p.namespace, p.name, a, v))
    return acc


def _pin_host(p: Pod, node_name: str) -> None:
    """Overwrite the hostname selector with the claim's node, stashing any
    USER-authored value so clear_dra_lowering can restore (not delete) it —
    the clear runs first each pass, so the current selector here IS the
    user's state. A SECOND pin in the same pass must not re-stash (it would
    capture the first pin as if it were user state)."""
    prev = p.node_selector.get("kubernetes.io/hostname")
    if DRA_PIN_ANNOTATION not in p.annotations and prev is not None:
        p.annotations[DRA_PIN_PREV_ANNOTATION] = prev
    p.annotations[DRA_PIN_ANNOTATION] = node_name
    p.node_selector["kubernetes.io/hostname"] = node_name


def clear_prefixed_resources(nodes: list[Node], pods: list[Pod],
                             prefix: str) -> None:
    """Purge a lowering pass's resource-key namespace from the live objects
    (shared by the DRA and CSI clears)."""
    for nd in nodes:
        for store in (nd.capacity, nd.allocatable):
            if not store:
                continue
            for k in [k for k in store if k.startswith(prefix)]:
                del store[k]
    for p in pods:
        for k in [k for k in p.requests if k.startswith(prefix)]:
            del p.requests[k]


def clear_dra_lowering(nodes: list[Node], pods: list[Pod]) -> None:
    """Remove everything a previous apply_dra pass wrote (see its docstring)."""
    clear_prefixed_resources(nodes, pods, DRA_RESOURCE_PREFIX)
    for p in pods:
        gang = [k for k in p.labels if k.startswith(DRA_SHARED_LABEL_PREFIX)]
        for k in gang:
            del p.labels[k]
        if p.pod_affinity:
            p.pod_affinity = [
                t for t in p.pod_affinity
                if not (len(t.match_labels) == 1 and next(
                    iter(t.match_labels)).startswith(DRA_SHARED_LABEL_PREFIX))]
        pin = p.annotations.pop(DRA_PIN_ANNOTATION, None)
        prev = p.annotations.pop(DRA_PIN_PREV_ANNOTATION, None)
        if pin is not None \
                and p.node_selector.get("kubernetes.io/hostname") == pin:
            if prev is not None:
                p.node_selector["kubernetes.io/hostname"] = prev
            else:
                del p.node_selector["kubernetes.io/hostname"]
        if p.annotations.pop(DRA_LOSSY_ANNOTATION, None) is not None \
                and CSI_LOSSY_ANNOTATION not in p.annotations:
            p.annotations.pop(HOST_CHECK_ANNOTATION, None)


def allocate_claim(claim: ResourceClaim, node: Node, pod: Pod) -> None:
    """Actuation-time bookkeeping (reference: RunReserve during SchedulePod,
    predicate_snapshot.go SchedulePod → DRA claim reservation)."""
    claim.allocated_node = node.name
    ref = f"{pod.namespace}/{pod.name}"
    if ref not in claim.reserved_for:
        claim.reserved_for.append(ref)


def deallocate_claim(claim: ResourceClaim, pod: Pod) -> None:
    ref = f"{pod.namespace}/{pod.name}"
    if ref in claim.reserved_for:
        claim.reserved_for.remove(ref)
    if not claim.reserved_for:
        claim.allocated_node = ""
