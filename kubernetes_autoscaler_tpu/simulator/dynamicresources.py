"""Dynamic Resource Allocation (DRA): device claims on the tensor plane.

Reference counterpart: simulator/dynamicresources/ (2679 LoC — SURVEY.md
§2.3): a fork/commit/revert patchset store of ResourceClaims / ResourceSlices
/ DeviceClasses, with claim allocation and reservation performed during
simulated scheduling, plus eager joining of slices into NodeInfos
(predicate_snapshot.go:72-120).

TPU re-design: the pointer-graph store disappears. Devices are counted per
(node, device-class) and LOWERED INTO THE RESOURCE AXIS before encoding:
each device class maps to an extended-resource slot ("dra/<class>"), node
device counts become capacity, per-pod claims become requests. Feasibility,
allocation charging, and fork/commit/revert then ride the existing
int32 resource tensors for free — one comparison per class on the VPU
instead of per-device object matching.

Exactness tiering (the framework-wide pattern): what the dense encoding
cannot express — CEL-style device attribute selectors, shared multi-pod
claims (ReservedFor), partitionable devices — sets `needs_host_check`, and
the winner-verification tier re-checks with `claim_fits_exact` before
actuation (same contract as oracle.check_pod_on_node for affinity).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.models.api import (
    HOST_CHECK_ANNOTATION,
    Node,
    Pod,
)

DRA_RESOURCE_PREFIX = "dra/"


@dataclass
class DeviceClass:
    """reference: resource.k8s.io DeviceClass (simulator/dynamicresources
    snapshot stores these verbatim)."""

    name: str
    # class-level required attributes (every device of the class has them)
    attributes: dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceSlice:
    """A pool of identical devices one node publishes (reference:
    ResourceSlice; LocalResourceSlices joined into NodeInfo at
    framework/infos.go:57)."""

    node_name: str
    device_class: str
    count: int
    # per-device attributes for selector matching (uniform within a slice)
    attributes: dict[str, str] = field(default_factory=dict)


@dataclass
class ClaimRequest:
    """One request inside a claim: N devices of a class, optionally
    attribute-constrained (the simulable subset of CEL selectors:
    attribute equality)."""

    device_class: str
    count: int = 1
    selector: dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceClaim:
    """reference: ResourceClaim/ResourceClaimTemplate. `owner_pod` empty means
    a shared claim (multiple pods reserve it) — host-check tier."""

    name: str
    namespace: str = "default"
    requests: list[ClaimRequest] = field(default_factory=list)
    owner_pod: str = ""               # pod name for per-pod (template) claims
    allocated_node: str = ""          # "" = unallocated
    reserved_for: list[str] = field(default_factory=list)


@dataclass
class DraSnapshot:
    """The queryable DRA world handed to the lowering pass (reference:
    DraProvider.Snapshot() at static_autoscaler.go:313)."""

    classes: dict[str, DeviceClass] = field(default_factory=dict)
    slices: list[ResourceSlice] = field(default_factory=list)
    claims: list[ResourceClaim] = field(default_factory=list)

    def claims_for_pod(self, pod: Pod) -> list[ResourceClaim]:
        return [c for c in self.claims
                if c.owner_pod == pod.name and c.namespace == pod.namespace]

    def device_capacity(self) -> dict[str, dict[str, int]]:
        """node -> class -> device count."""
        out: dict[str, dict[str, int]] = {}
        for s in self.slices:
            per = out.setdefault(s.node_name, {})
            per[s.device_class] = per.get(s.device_class, 0) + s.count
        return out


def slice_matches(s: ResourceSlice, req: ClaimRequest,
                  classes: dict[str, DeviceClass]) -> bool:
    if s.device_class != req.device_class:
        return False
    attrs = dict(classes.get(req.device_class, DeviceClass(req.device_class)).attributes)
    attrs.update(s.attributes)
    return all(attrs.get(k) == v for k, v in req.selector.items())


def claim_fits_exact(claim: ResourceClaim, node: Node, dra: DraSnapshot,
                     allocated: dict[tuple[str, str], int] | None = None) -> bool:
    """Host-side exact check: every request satisfiable from the node's
    matching slices minus what's already allocated (the winner-verification
    tier for selectored/shared claims)."""
    allocated = allocated or {}
    for req in claim.requests:
        avail = 0
        for s in dra.slices:
            if s.node_name != node.name:
                continue
            if slice_matches(s, req, dra.classes):
                avail += s.count
        avail -= allocated.get((node.name, req.device_class), 0)
        if avail < req.count:
            return False
    return True


def apply_dra(nodes: list[Node], pods: list[Pod], dra: DraSnapshot) -> None:
    """The lowering pass: fold device counts into node capacity and claim
    counts into pod requests as 'dra/<class>' extended resources, BEFORE
    encode_cluster. Pods with selectored or shared claims additionally get
    the host-check annotation (consumed by models/encode)."""
    cap = dra.device_capacity()
    for nd in nodes:
        for cls, count in cap.get(nd.name, {}).items():
            key = DRA_RESOURCE_PREFIX + cls
            nd.capacity[key] = count
            if nd.allocatable:
                nd.allocatable[key] = count

    # allocated claims on live nodes consume device capacity exactly like
    # resident pods consume cpu/mem (encode charges scheduled pods' requests).
    # Totals are recomputed and OVERWRITTEN each pass — the loop re-lists the
    # same Pod objects every tick, so += would compound across loops.
    for pod in pods:
        totals: dict[str, int] = {}
        lossy = False
        for claim in dra.claims_for_pod(pod):
            if len(claim.reserved_for) > 1:
                lossy = True
            for req in claim.requests:
                key = DRA_RESOURCE_PREFIX + req.device_class
                totals[key] = totals.get(key, 0) + req.count
                if req.selector:
                    lossy = True
        for key, total in totals.items():
            pod.requests[key] = total
        if lossy:
            pod.annotations[HOST_CHECK_ANNOTATION] = "true"


def allocate_claim(claim: ResourceClaim, node: Node, pod: Pod) -> None:
    """Actuation-time bookkeeping (reference: RunReserve during SchedulePod,
    predicate_snapshot.go SchedulePod → DRA claim reservation)."""
    claim.allocated_node = node.name
    ref = f"{pod.namespace}/{pod.name}"
    if ref not in claim.reserved_for:
        claim.reserved_for.append(ref)


def deallocate_claim(claim: ResourceClaim, pod: Pod) -> None:
    ref = f"{pod.namespace}/{pod.name}"
    if ref in claim.reserved_for:
        claim.reserved_for.remove(ref)
    if not claim.reserved_for:
        claim.allocated_node = ""
