"""TensorClusterSnapshot: the ClusterSnapshot contract on immutable pytrees.

Reference counterpart: simulator/clustersnapshot/clustersnapshot.go:43-105 —
the five mutating/query verbs plus Fork/Commit/Revert — implemented there by
the DeltaSnapshotStore's layered deltas (store/delta.go:33-54, an O(1)-fork
design motivated by Go pointer graphs). Here the whole cluster is one
immutable pytree, so:

  Fork   = push a reference onto a stack        (O(1), no copy)
  Revert = pop                                   (O(1))
  Commit = collapse the top into its parent      (O(1) pointer swap)

The entire delta-store complexity disappears by construction (SURVEY.md §7
step 3). Mutation verbs return *new* pytrees via `.at[...]` updates; XLA turns
these into in-place buffer donation where safe.

Verbs are batch-first (whole equivalence groups / candidate sets per call) —
the serial per-pod verbs exist for parity and for the sidecar wire protocol,
implemented as batch calls of size 1.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from kubernetes_autoscaler_tpu.models.api import Node, Pod
from kubernetes_autoscaler_tpu.models.cluster_state import (
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
)
from kubernetes_autoscaler_tpu.models.encode import (
    EncodedCluster,
    encode_cluster,
    encode_node_row,
)


@dataclass
class _State:
    nodes: NodeTensors
    specs: PodGroupTensors
    scheduled: ScheduledPodTensors
    node_names: list[str]
    node_index: dict[str, int]
    n_valid: int
    planes: object = None   # AffinityPlanes | None — per-fork so growth
                            # padding cannot leak across revert


class SnapshotError(Exception):
    pass


class TensorClusterSnapshot:
    """Forkable cluster snapshot over device tensors."""

    def __init__(self, enc: EncodedCluster):
        self.enc = enc
        self._stack: list[_State] = [
            _State(
                nodes=enc.nodes,
                specs=enc.specs,
                scheduled=enc.scheduled,
                node_names=list(enc.node_names),
                node_index=dict(enc.node_index),
                n_valid=len(enc.node_names),
                planes=enc.planes,
            )
        ]

    # ---- construction ----

    @classmethod
    def from_objects(cls, nodes: list[Node], pods: list[Pod], **encode_kw):
        return cls(encode_cluster(nodes, pods, **encode_kw))

    # ---- fork/commit/revert (reference clustersnapshot.go:43-105) ----

    @property
    def state(self) -> _State:
        return self._stack[-1]

    def fork(self) -> None:
        s = self.state
        self._stack.append(
            _State(s.nodes, s.specs, s.scheduled, list(s.node_names),
                   dict(s.node_index), s.n_valid, s.planes)
        )

    def revert(self) -> None:
        if len(self._stack) == 1:
            raise SnapshotError("revert without fork")
        self._stack.pop()

    def commit(self) -> None:
        if len(self._stack) == 1:
            raise SnapshotError("commit without fork")
        top = self._stack.pop()
        self._stack[-1] = top

    def with_forked(self, fn):
        """reference: WithForkedSnapshot (clustersnapshot.go:135) — run fn on a
        fork; commit when it returns True, revert otherwise or on error."""
        self.fork()
        try:
            keep = fn()
        except Exception:
            self.revert()
            raise
        if keep:
            self.commit()
        else:
            self.revert()
        return keep

    # ---- node mutation (reference AddNodeInfo/RemoveNodeInfo) ----

    def add_node(self, node: Node, group_id: int = -1,
                 alloc_row=None) -> int:
        """Add a (template-instantiated) node; grows padded space if needed.
        Reference analog: estimator adding template nodes
        (binpacking_estimator.go:330 via SanitizedNodeInfo). `alloc_row`
        pre-charges the fresh node (DaemonSet overhead — the reference's
        template NodeInfos carry their DS pods, node_info_utils.go:45)."""
        s = self.state
        if node.name in s.node_index:
            raise SnapshotError(f"node {node.name} already in snapshot")
        i = s.n_valid
        if i >= s.nodes.n:
            s.nodes = _grow_nodes(s.nodes)
            if s.planes is not None:
                # constraint planes are [G, N]: keep the node axis in step
                # (new columns are zero — fresh nodes carry no residents);
                # per-FORK so a reverted growth cannot leak wider planes
                s.planes = jax.tree_util.tree_map(
                    lambda x: jnp.pad(x, ((0, 0), (0, x.shape[1]))),
                    s.planes)
        row = encode_node_row(node, self.enc.registry, self.enc.zone_table, self.enc.dims)
        nt = s.nodes
        s.nodes = nt.replace(
            cap=nt.cap.at[i].set(jnp.asarray(row["cap"])),
            alloc=nt.alloc.at[i].set(
                0 if alloc_row is None else jnp.asarray(alloc_row)),
            label_hash=nt.label_hash.at[i].set(jnp.asarray(row["label_hash"])),
            taint_exact=nt.taint_exact.at[i].set(jnp.asarray(row["taint_exact"])),
            taint_key=nt.taint_key.at[i].set(jnp.asarray(row["taint_key"])),
            used_ports=nt.used_ports.at[i].set(0),
            zone_id=nt.zone_id.at[i].set(row["zone_id"]),
            group_id=nt.group_id.at[i].set(group_id),
            ready=nt.ready.at[i].set(bool(row["ready"])),
            schedulable=nt.schedulable.at[i].set(bool(row["schedulable"])),
            valid=nt.valid.at[i].set(True),
        )
        s.node_names.append(node.name)
        s.node_index[node.name] = i
        s.n_valid += 1
        return i

    def remove_node(self, name: str) -> None:
        s = self.state
        if name not in s.node_index:
            raise SnapshotError(f"node {name} not in snapshot")
        i = s.node_index[name]
        s.nodes = s.nodes.replace(valid=s.nodes.valid.at[i].set(False))
        # names keep their slots; index drops the mapping (ghost row)
        del s.node_index[name]

    def set_unschedulable(self, name: str, unschedulable: bool = True) -> None:
        s = self.state
        i = s.node_index[name]
        s.nodes = s.nodes.replace(
            schedulable=s.nodes.schedulable.at[i].set(not unschedulable)
        )

    # ---- batch verbs (delegate to ops/) ----

    def schedule_pending_on_existing(self):
        from kubernetes_autoscaler_tpu.ops.schedule import schedule_pending_on_existing

        s = self.state
        return schedule_pending_on_existing(
            s.nodes, s.specs, s.scheduled,
            planes=s.planes,
            max_zones=self.enc.dims.max_zones,
            with_constraints=self.enc.has_constraints,
        )

    def apply_placement(self, placed: jnp.ndarray) -> None:
        """Charge a PackResult.placed (i32[G, N]) onto node allocations and
        decrement pending counts — the batch SchedulePod."""
        s = self.state
        add = jnp.einsum("gn,gr->nr", placed.astype(jnp.int32), s.specs.req)
        new_count = jnp.maximum(s.specs.count - placed.sum(axis=1), 0)
        s.nodes = s.nodes.replace(alloc=s.nodes.alloc + add)
        s.specs = s.specs.replace(count=new_count)

    def check_predicates(self):
        from kubernetes_autoscaler_tpu.ops.predicates import feasibility_mask

        s = self.state
        return feasibility_mask(s.nodes, s.specs)

    def simulate_removals(self, candidate_indices, dest_allowed=None,
                          max_pods_per_node: int = 128, chunk: int = 32):
        from kubernetes_autoscaler_tpu.ops.drain import simulate_removals

        s = self.state
        if dest_allowed is None:
            dest_allowed = jnp.ones((s.nodes.n,), bool)
        return simulate_removals(
            s.nodes, s.specs, s.scheduled,
            jnp.asarray(candidate_indices, jnp.int32), dest_allowed,
            max_pods_per_node=max_pods_per_node, chunk=chunk,
            planes=s.planes,
            max_zones=self.enc.dims.max_zones,
            with_constraints=self.enc.has_constraints,
        )


def _grow_nodes(nt: NodeTensors) -> NodeTensors:
    """Double the padded node capacity (rare; keeps shape buckets coarse)."""
    n = nt.n

    def pad(x):
        pad_width = [(0, n)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, pad_width)

    grown = NodeTensors(
        cap=pad(nt.cap), alloc=pad(nt.alloc), label_hash=pad(nt.label_hash),
        taint_exact=pad(nt.taint_exact), taint_key=pad(nt.taint_key),
        used_ports=pad(nt.used_ports), zone_id=pad(nt.zone_id),
        group_id=jnp.pad(nt.group_id, (0, n), constant_values=-1),
        ready=pad(nt.ready), schedulable=pad(nt.schedulable), valid=pad(nt.valid),
    )
    return grown
