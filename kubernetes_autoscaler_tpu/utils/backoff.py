"""Exponential per-node-group backoff after failed scale-ups.

Reference counterpart: utils/backoff/exponential_backoff.go (174 LoC) —
duration doubles per failure up to a cap, resets after a quiet period.

Memory: entries are pruned by an amortized sweep. An entry past its
`backoff_until` whose last failure is also older than `reset_timeout_s` can
never influence a future verdict (`is_backed_off` is False and the next
`backoff()` would start the ladder fresh), so it is garbage. The sweep runs
from `backoff()` whenever the dict crosses a watermark set to 2× the live
count after the previous sweep — O(1) amortized per call, and the dict stays
bounded by ~2× the number of groups that failed within the reset window,
instead of growing without bound under node-group churn on long runs
(autoprovisioned groups mint fresh ids forever).
"""

from __future__ import annotations

from dataclasses import dataclass, field

_SWEEP_FLOOR = 64


@dataclass
class _Entry:
    duration: float
    backoff_until: float
    last_failure: float


@dataclass
class ExponentialBackoff:
    initial_s: float = 300.0
    max_s: float = 1800.0
    reset_timeout_s: float = 3 * 3600.0
    _entries: dict[str, _Entry] = field(default_factory=dict)
    _sweep_watermark: int = _SWEEP_FLOOR

    def backoff(self, group_id: str, now: float) -> float:
        """Record a failure; returns the until-timestamp."""
        e = self._entries.get(group_id)
        if e is not None and now - e.last_failure < self.reset_timeout_s:
            duration = min(e.duration * 2, self.max_s)
        else:
            duration = self.initial_s
        self._entries[group_id] = _Entry(duration, now + duration, now)
        if len(self._entries) >= self._sweep_watermark:
            self.sweep(now)
        return now + duration

    def is_backed_off(self, group_id: str, now: float) -> bool:
        e = self._entries.get(group_id)
        return e is not None and now < e.backoff_until

    def remove_backoff(self, group_id: str) -> None:
        self._entries.pop(group_id, None)

    def sweep(self, now: float) -> None:
        """Drop entries that can no longer affect any verdict (backoff
        elapsed AND quiet past the reset window) and re-arm the watermark
        at 2× the surviving population."""
        self._entries = {
            g: e for g, e in self._entries.items()
            if now < e.backoff_until
            or now - e.last_failure < self.reset_timeout_s
        }
        self._sweep_watermark = max(_SWEEP_FLOOR, 2 * len(self._entries))
