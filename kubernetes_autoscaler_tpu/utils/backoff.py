"""Exponential per-node-group backoff after failed scale-ups.

Reference counterpart: utils/backoff/exponential_backoff.go (174 LoC) —
duration doubles per failure up to a cap, resets after a quiet period.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class _Entry:
    duration: float
    backoff_until: float
    last_failure: float


@dataclass
class ExponentialBackoff:
    initial_s: float = 300.0
    max_s: float = 1800.0
    reset_timeout_s: float = 3 * 3600.0
    _entries: dict[str, _Entry] = field(default_factory=dict)

    def backoff(self, group_id: str, now: float) -> float:
        """Record a failure; returns the until-timestamp."""
        e = self._entries.get(group_id)
        if e is not None and now - e.last_failure < self.reset_timeout_s:
            duration = min(e.duration * 2, self.max_s)
        else:
            duration = self.initial_s
        self._entries[group_id] = _Entry(duration, now + duration, now)
        return now + duration

    def is_backed_off(self, group_id: str, now: float) -> bool:
        e = self._entries.get(group_id)
        return e is not None and now < e.backoff_until

    def remove_backoff(self, group_id: str) -> None:
        self._entries.pop(group_id, None)
