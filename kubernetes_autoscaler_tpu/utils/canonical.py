"""Shared canonical encoding + object-identity change detection.

One vocabulary of "changed" for the two consumers that must agree on it BY
CONSTRUCTION:

  * the flight journal (replay/journal.py) — serializes each loop's world
    and commits listing-order add/del/mod delta records against the
    previous loop;
  * the device-resident WorldStore (models/world_store.py) — keeps the
    encoded planes resident on the device and applies a per-loop delta
    program derived from the same loop-to-loop object diff.

Both ride the repo-wide replace-on-update contract (a changed k8s object is
a NEW object; informer-fed sources and FakeCluster honor it, and the
incremental encoder's id()-based fingerprints already depend on it). The
helpers here are the single implementation of that contract:

  * `canonical` / `digest_of` / `digest_strs` — deterministic JSON + sha256/16
    digests, process- and platform-independent (journal record seals, world
    digests, composition fingerprints);
  * `canon_map` — ordered key → canonical-JSON maps with an object-IDENTITY
    cache, turning per-loop serialization cost from O(world) to O(churn);
  * `IdentityMemo` — the same identity-caching pattern for arbitrary derived
    values (marshal-cache exemplar signatures, template fingerprints), so
    every fingerprint on the encode path is O(churn) too;
  * `node_fp` — the cheap in-place-mutation fingerprint for Node objects
    (the one k8s object the control plane itself mutates in place).

If the journal says an object changed, the WorldStore's delta program
re-lowers it, and vice versa — there is no second, subtly different notion
of equality to drift.
"""

from __future__ import annotations

import hashlib
import json


def canonical(obj) -> str:
    """Deterministic JSON: sorted keys, no whitespace, default=str for the
    rare non-JSON leaf. Tuples and lists both serialize as arrays, so a
    live-object encoding and its JSON round trip share one canonical form."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"), default=str)


def digest_of(obj) -> str:
    return hashlib.sha256(canonical(obj).encode()).hexdigest()[:16]


def digest_strs(parts: list[str]) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode())
        h.update(b"\n")
    return h.hexdigest()[:16]


def canon_map(objs, key_of, to_dict, cache: dict
              ) -> tuple[dict, dict[str, str]]:
    """Ordered key → canonical map, reusing cached canonical forms for
    objects whose IDENTITY is unchanged (replace-on-update contract).
    Returns (new cache holding only live objects, the map). The cache value
    holds the object reference, so a freed id can never alias — the
    host_mirror_token pattern."""
    new_cache: dict[int, tuple] = {}
    out: dict[str, str] = {}
    for obj in objs:
        hit = cache.get(id(obj))
        canon = hit[1] if hit is not None and hit[0] is obj \
            else canonical(to_dict(obj))
        new_cache[id(obj)] = (obj, canon)
        out[key_of(obj)] = canon
    return new_cache, out


class IdentityMemo:
    """Memoize `fn(obj)` by object identity across refresh rounds.

    `refresh(objs)` computes (or reuses) the value for every listed object
    and DROPS entries for objects no longer listed — the cache never grows
    past the live set, and holding the object reference pins its id against
    reuse. The derived value must be a pure function of the object's
    content, which the replace-on-update contract makes equivalent to a
    function of its identity between replacements."""

    __slots__ = ("fn", "_cache", "hits", "misses")

    def __init__(self, fn):
        self.fn = fn
        self._cache: dict[int, tuple] = {}
        self.hits = 0
        self.misses = 0

    def get(self, obj):
        """One lookup WITHOUT lifecycle management (caller sweeps via
        refresh, or accepts growth bounded by its own call pattern)."""
        hit = self._cache.get(id(obj))
        if hit is not None and hit[0] is obj:
            self.hits += 1
            return hit[1]
        self.misses += 1
        val = self.fn(obj)
        self._cache[id(obj)] = (obj, val)
        return val

    def refresh(self, objs) -> list:
        new_cache: dict[int, tuple] = {}
        out = []
        for obj in objs:
            hit = self._cache.get(id(obj))
            if hit is not None and hit[0] is obj:
                self.hits += 1
                val = hit[1]
            else:
                self.misses += 1
                val = self.fn(obj)
            new_cache[id(obj)] = (obj, val)
            out.append(val)
        self._cache = new_cache
        return out


def node_fp(nd) -> tuple:
    """Cheap change fingerprint for a Node. Catches the in-place mutations
    the control plane itself performs (ready flips, cordons, taint sync);
    label/capacity map REPLACEMENT is caught via id() — in-place mutation of
    those dicts is outside the source contract (k8s replaces objects on
    update)."""
    return (
        nd.ready, nd.unschedulable,
        tuple((t.key, t.value, t.effect) for t in nd.taints),
        id(nd.labels), id(nd.allocatable), id(nd.capacity),
        id(nd.annotations),
    )
