"""Self-signed serving-certificate management.

Reference counterpart: vertical-pod-autoscaler/pkg/admission-controller's
cert self-management (certs/ — the webhook generates and rotates its own
serving certificate instead of requiring one to be provisioned). Used by the
VPA admission webhook server and available to the sidecar gRPC service.

`CertManager` keeps a cert/key pair under a directory, regenerating when
absent or within `rotate_before_s` of expiry; `reload()` hooks let a live
listener swap chains without rebinding (ssl.SSLContext.load_cert_chain may
be called again on a serving context — new handshakes pick up the new pair).
"""

from __future__ import annotations

import datetime as _dt
import ipaddress
import os
import threading


def generate_self_signed(
    common_name: str,
    sans: list[str] | None = None,
    valid_days: float = 365.0,
) -> tuple[bytes, bytes]:
    """(cert_pem, key_pem) for a self-signed serving certificate."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    alt_names: list[x509.GeneralName] = []
    for san in sans or [common_name, "localhost", "127.0.0.1"]:
        try:
            alt_names.append(x509.IPAddress(ipaddress.ip_address(san)))
        except ValueError:
            alt_names.append(x509.DNSName(san))
    now = _dt.datetime.now(_dt.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _dt.timedelta(minutes=5))
        .not_valid_after(now + _dt.timedelta(days=valid_days))
        .add_extension(x509.SubjectAlternativeName(alt_names), critical=False)
        .add_extension(
            x509.BasicConstraints(ca=False, path_length=None), critical=True)
        .sign(key, hashes.SHA256())
    )
    return (
        cert.public_bytes(serialization.Encoding.PEM),
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ),
    )


class CertManager:
    """Keeps `<dir>/tls.crt` + `<dir>/tls.key` present and fresh."""

    def __init__(
        self,
        cert_dir: str,
        common_name: str = "localhost",
        sans: list[str] | None = None,
        valid_days: float = 365.0,
        rotate_before_s: float = 30 * 24 * 3600.0,
    ):
        self.cert_dir = cert_dir
        self.common_name = common_name
        self.sans = sans
        self.valid_days = valid_days
        self.rotate_before_s = rotate_before_s
        self.rotations = 0
        self._lock = threading.Lock()
        self._reload_hooks: list = []
        os.makedirs(cert_dir, exist_ok=True)
        self.ensure()

    @property
    def cert_path(self) -> str:
        return os.path.join(self.cert_dir, "tls.crt")

    @property
    def key_path(self) -> str:
        return os.path.join(self.cert_dir, "tls.key")

    def on_reload(self, hook) -> None:
        """hook(cert_path, key_path) runs after every (re)generation."""
        self._reload_hooks.append(hook)

    def _expires_at(self) -> float | None:
        from cryptography import x509

        try:
            with open(self.cert_path, "rb") as f:
                cert = x509.load_pem_x509_certificate(f.read())
        except (OSError, ValueError):
            return None
        try:
            return cert.not_valid_after_utc.timestamp()
        except AttributeError:  # cryptography < 42
            import datetime

            return cert.not_valid_after.replace(
                tzinfo=datetime.timezone.utc).timestamp()

    def ensure(self, now: float | None = None) -> bool:
        """Generate/rotate when absent or expiring soon; True if rotated."""
        import time

        now = time.time() if now is None else now
        with self._lock:
            exp = self._expires_at()
            if exp is not None and exp - now > self.rotate_before_s:
                return False
            cert_pem, key_pem = generate_self_signed(
                self.common_name, self.sans, self.valid_days)
            tmp_c, tmp_k = self.cert_path + ".tmp", self.key_path + ".tmp"
            with open(tmp_c, "wb") as f:
                f.write(cert_pem)
            # the private key must never be world-readable (0600, like the
            # k8s cert managers write theirs). Unlink first: os.open's mode
            # applies only on CREATION — a leftover tmp from a crashed run
            # would keep its old permissions
            try:
                os.unlink(tmp_k)
            except FileNotFoundError:
                pass
            fd = os.open(tmp_k, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(key_pem)
            os.replace(tmp_c, self.cert_path)
            os.replace(tmp_k, self.key_path)
            self.rotations += 1
            hooks = list(self._reload_hooks)
        # hooks run OUTSIDE the non-reentrant lock — a hook calling back
        # into ensure() must not deadlock (r4 advisor)
        for hook in hooks:
            hook(self.cert_path, self.key_path)
        return True
