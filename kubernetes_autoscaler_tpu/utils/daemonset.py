"""DaemonSet overhead for simulated new nodes.

Reference counterpart: utils/daemonset/daemonset.go:39
GetDaemonSetPodsForNode — template NodeInfos are built WITH their matching
DaemonSet pods (simulator/node_info_utils.go:45,63 threads `daemonsets`
into every sanitized template), so binpacking charges DS cpu/mem on every
simulated new node. Without this, a cluster whose nodes each run 10-20% of
logging/monitoring agents over-estimates fresh-node capacity and
systematically under-provisions (round-4 verdict Missing #2).

DaemonSets ride the Workload seam (kind == "DaemonSet", template = the DS
pod spec) — the same lister-shaped source podinjection already consumes.
"""

from __future__ import annotations

import numpy as np

from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.models.api import Node, Pod


def daemonset_pods_for_node(node: Node, workloads: list) -> list[Pod]:
    """The DS pods that would run on `node` (reference:
    daemon.NodeShouldRunDaemonPod via GetDaemonSetPodsForNode): node
    selector/affinity must match and the node's hard taints must be
    tolerated. The DS controller itself schedules regardless of free
    capacity (it uses its own tolerations for unschedulable/not-ready), so
    no resource-fit gate here — the charge is what the pod REQUESTS."""
    from kubernetes_autoscaler_tpu.utils import oracle

    out: list[Pod] = []
    for w in workloads:
        if getattr(w, "kind", "") != "DaemonSet" or w.template is None:
            continue
        p = w.template
        if not oracle.selector_matches(p, node):
            continue
        if not oracle.taints_tolerated(p, node):
            continue
        out.append(p)
    return out


def daemonset_overhead(
    template: Node,
    workloads: list,
    registry: res.ExtendedResourceRegistry,
) -> np.ndarray:
    """Summed request vector (int32[R]) of the DS pods a fresh node stamped
    from `template` would immediately carry. Subtracted from the group
    capacity row at encode time (models/encode.encode_node_groups) and
    charged as initial allocation on injected template nodes."""
    from kubernetes_autoscaler_tpu.models.encode import pod_request_vector

    total = np.zeros((res.NUM_RESOURCES,), np.int32)
    for p in daemonset_pods_for_node(template, workloads):
        req, _lossy = pod_request_vector(p, registry)
        total += req
    return total
