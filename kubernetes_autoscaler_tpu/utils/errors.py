"""Typed autoscaler errors.

Reference counterpart: cluster-autoscaler/utils/errors — AutoscalerError with
an error-type discriminant (CloudProviderError, ApiCallError, InternalError,
TransientError, ConfigurationError) so callers can decide between backoff,
retry, and abort without string matching.
"""

from __future__ import annotations

from enum import Enum


class ErrorType(Enum):
    CLOUD_PROVIDER = "cloudProviderError"
    API_CALL = "apiCallError"
    INTERNAL = "internalError"
    TRANSIENT = "transientError"
    CONFIGURATION = "configurationError"


class AutoscalerError(Exception):
    def __init__(self, error_type: ErrorType, msg: str):
        super().__init__(msg)
        self.error_type = error_type

    def prefixed(self, prefix: str) -> "AutoscalerError":
        """Wrap with context, keeping the type (reference: AddPrefix)."""
        return AutoscalerError(self.error_type, f"{prefix}{self}")

    @property
    def retriable(self) -> bool:
        return self.error_type in (ErrorType.TRANSIENT, ErrorType.API_CALL)


def to_autoscaler_error(default_type: ErrorType, err: Exception) -> AutoscalerError:
    """reference: errors.ToAutoscalerError — idempotent wrapping."""
    if isinstance(err, AutoscalerError):
        return err
    return AutoscalerError(default_type, str(err))
