"""FakeCluster: in-memory kube-world for integration tests.

Reference counterpart: test/integration/utils.go:58-88 FakeSet — bundles a
fake clientset, fake cloud provider and pod observer so a whole
StaticAutoscaler.RunOnce runs against memory. Here the fake wires the
TestCloudProvider callbacks to node lifecycle: increase_size materializes
ready nodes from the group template after `provision_delay_s`; delete removes
them; evictions unbind pods.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from kubernetes_autoscaler_tpu.models.api import Node, Pod


@dataclass
class _PendingProvision:
    group_id: str
    count: int
    at: float


class FakeCluster:
    """ClusterDataSource + EvictionSink + cloud-side node lifecycle."""

    def __init__(self, provision_delay_s: float = 0.0):
        self.provider = TestCloudProvider(
            on_scale_up=self._on_scale_up,
            on_scale_down=self._on_scale_down,
        )
        self.nodes: dict[str, Node] = {}
        self.pods: dict[str, Pod] = {}
        self.pdbs: list = []
        self.workloads: list = []
        self.provreqs: list = []
        self.capacity_buffers: list = []
        self._dra = None
        self._csi = None
        self.provision_delay_s = provision_delay_s
        self.evicted: list[str] = []
        self.eviction_graces: dict[str, float | None] = {}
        self.namespace_labels: dict[str, dict[str, str]] = {}
        self._pending: list[_PendingProvision] = []
        self._seq = itertools.count()
        self._now = 0.0

    # ---- time control ----

    def advance_to(self, now: float) -> None:
        self._now = now
        still = []
        for p in self._pending:
            if now >= p.at:
                self._materialize(p.group_id, p.count)
            else:
                still.append(p)
        self._pending = still

    # ---- cloud callbacks ----

    def _on_scale_up(self, gid: str, delta: int) -> None:
        if self.provision_delay_s <= 0:
            self._materialize(gid, delta)
        else:
            self._pending.append(
                _PendingProvision(gid, delta, self._now + self.provision_delay_s)
            )

    def _materialize(self, gid: str, count: int) -> None:
        g = next(x for x in self.provider.node_groups() if x.id() == gid)
        for _ in range(count):
            t = g.template_node_info()
            name = f"{gid}-node-{next(self._seq)}"
            nd = Node(
                name=name,
                labels={**t.labels, "kubernetes.io/hostname": name},
                capacity=dict(t.capacity),
                allocatable=dict(t.allocatable),
                taints=list(t.taints),
                ready=True,
            )
            self.nodes[name] = nd
            self.provider.add_node(gid, nd)

    def _on_scale_down(self, gid: str, node_name: str) -> None:
        self.nodes.pop(node_name, None)
        for p in self.pods.values():
            if p.node_name == node_name:
                p.node_name = ""
                p.phase = "Pending"

    # ---- ClusterDataSource ----

    def list_nodes(self) -> list[Node]:
        return list(self.nodes.values())

    def list_pods(self) -> list[Pod]:
        return list(self.pods.values())

    def list_namespaces(self) -> dict[str, dict[str, str]]:
        """Namespace name -> labels (affinity namespaceSelector support)."""
        return dict(self.namespace_labels)

    def list_pdbs(self) -> list:
        """Effective budgets, the way the API server maintains
        status.disruptionsAllowed: the configured allowance minus matching
        pods currently disrupted (evicted and not yet Running again)."""
        from dataclasses import replace

        out = []
        for pdb in self.pdbs:
            disrupted = sum(
                1 for p in self.pods.values()
                if pdb.matches(p) and p.phase != "Running"
            )
            out.append(replace(
                pdb,
                disruptions_allowed=max(pdb.disruptions_allowed - disrupted, 0),
            ))
        return out

    def add_pdb(self, pdb) -> None:
        self.pdbs.append(pdb)

    def list_workloads(self) -> list:
        return list(self.workloads)

    def add_workload(self, workload) -> None:
        self.workloads.append(workload)

    def list_capacity_buffers(self) -> list:
        return list(self.capacity_buffers)

    def add_capacity_buffer(self, buf) -> None:
        self.capacity_buffers.append(buf)

    def list_provisioning_requests(self) -> list:
        return list(self.provreqs)

    def add_provisioning_request(self, pr) -> None:
        self.provreqs.append(pr)

    def dra_snapshot(self):
        from kubernetes_autoscaler_tpu.simulator.dynamicresources import (
            DraSnapshot,
        )

        if self._dra is None:
            self._dra = DraSnapshot()
        return self._dra

    def csi_snapshot(self):
        from kubernetes_autoscaler_tpu.simulator.csi import CsiSnapshot

        if self._csi is None:
            self._csi = CsiSnapshot()
        return self._csi

    # ---- EvictionSink ----

    def evict(self, pod: Pod, node: Node,
              grace_period_s: float | None = None) -> None:
        self.evicted.append(pod.name)
        self.eviction_graces[pod.name] = grace_period_s
        live = self.pods.get(f"{pod.namespace}/{pod.name}")
        if live is not None:
            live.node_name = ""
            live.phase = "Pending"
        # eviction releases DRA claim reservations; claims nobody holds
        # deallocate so their devices free up (reference: drain path
        # unreserving claims through the DRA snapshot)
        if self._dra is not None:
            self._dra.release(pod)

    # ---- fixture helpers ----

    def add_node_group(self, gid: str, template: Node, **kw):
        return self.provider.add_node_group(gid, template, **kw)

    def add_existing_node(self, gid: str, node: Node) -> None:
        self.nodes[node.name] = node
        self.provider.add_node(gid, node)
        g = next(x for x in self.provider.node_groups() if x.id() == gid)
        g._target = max(g._target, len(self.provider.nodes_of(gid)))

    def add_pod(self, pod: Pod) -> None:
        self.pods[f"{pod.namespace}/{pod.name}"] = pod

    def remove_pod(self, name: str, namespace: str = "default") -> None:
        self.pods.pop(f"{namespace}/{name}", None)

    def bind(self, pod_name: str, node_name: str, namespace: str = "default") -> None:
        p = self.pods[f"{namespace}/{pod_name}"]
        p.node_name = node_name
        p.phase = "Running"
