"""Deterministic 64-bit hashing and bloom-mask encoding.

The reference operates on strings (labels, taints, selectors) via Go map lookups
per pod×node pair (vendored kube-scheduler plugins, e.g. TaintToleration / NodeAffinity
filters invoked from plugin_runner.go:146). The TPU plane cannot chase strings, so the
string world is lowered once on the host into fixed-width bloom bitmasks and the
per-pair checks become bitwise superset tests (see ops/predicates.py).

Bloom membership is probabilistic; the framework's contract (mirroring the reference's
own split between simulated scheduling and real kubelet admission) is:
  * the dense pods×nodes fast path may produce rare false "fits" (never false "does
    not fit" for the subset-encoded predicates — a missing required bit always rejects),
  * every *selected* assignment is re-verified exactly on the host before actuation
    (core/scaleup/orchestrator.py), so no incorrect action is ever taken.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a64(data: str | bytes) -> int:
    """Stable FNV-1a 64-bit hash (process-independent, unlike Python's hash())."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK64
    return h


@lru_cache(maxsize=1 << 18)
def fold32(data: str | bytes) -> int:
    """64-bit FNV-1a folded to a nonzero signed int32 (0 is the padding sentinel).

    TPUs run with 32-bit integers (JAX x64 disabled); a 32-bit hash over the
    few-thousand distinct strings of one cluster snapshot collides with
    probability ~1e-3 per snapshot, and any collision can only *relax* a
    predicate — the host-side winner verification (exact string semantics)
    catches it before actuation.

    Memoized: snapshot encoding re-hashes the same label/taint strings for
    every node row (5k nodes × ~dozens of strings per loop, heavily repeated)
    — the cache turns the per-byte Python FNV loop into a dict hit.
    """
    h = fnv1a64(data)
    h32 = (h ^ (h >> 32)) & 0xFFFFFFFF
    if h32 == 0:
        h32 = 1
    if h32 >= 1 << 31:
        h32 -= 1 << 32
    return h32


# Bloom geometry: BLOOM_WORDS uint32 words, K bit positions per element.
BLOOM_WORDS = 8          # 256 bits
BLOOM_BITS = BLOOM_WORDS * 32
BLOOM_K = 2


def bloom_bit_positions(item: str, nbits: int = BLOOM_BITS, k: int = BLOOM_K) -> list[int]:
    """Double-hashing scheme: positions h1 + i*h2 mod nbits."""
    h = fnv1a64(item)
    h1 = h & 0xFFFFFFFF
    h2 = (h >> 32) | 1  # odd => full-period stepping
    return [(h1 + i * h2) % nbits for i in range(k)]


def bloom_insert(words: np.ndarray, item: str) -> None:
    """Set the bits for `item` in a uint32[BLOOM_WORDS] array, in place."""
    for pos in bloom_bit_positions(item, nbits=words.shape[-1] * 32):
        words[pos // 32] |= np.uint32(1 << (pos % 32))


def bloom_from_items(items, nwords: int = BLOOM_WORDS) -> np.ndarray:
    words = np.zeros((nwords,), dtype=np.uint32)
    for it in items:
        bloom_insert(words, it)
    return words


def bloom_might_contain(words: np.ndarray, item: str) -> bool:
    for pos in bloom_bit_positions(item, nbits=words.shape[-1] * 32):
        if not (int(words[pos // 32]) >> (pos % 32)) & 1:
            return False
    return True


def bloom_is_superset(sup: np.ndarray, sub: np.ndarray) -> bool:
    """True iff every bit of `sub` is set in `sup` (host-side mirror of the device test)."""
    return bool(np.all((sup & sub) == sub))
