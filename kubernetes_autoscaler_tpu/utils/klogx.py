"""Quota-limited logging: cap per-loop log spam from per-pod/per-node paths.

Reference counterpart: cluster-autoscaler/utils/klogx — a logging quota
(`klogx.NewLoggingQuota(N)`) consumed by hot loops (e.g.
hinting_simulator.go:57 logs the first N unschedulable pods, then one
"...and M more" summary). Same shape here over the stdlib logger.
"""

from __future__ import annotations

import logging

logger = logging.getLogger("kubernetes_autoscaler_tpu")


class LoggingQuota:
    def __init__(self, limit: int):
        self.limit = limit
        self.left = limit

    def reset(self) -> None:
        self.left = self.limit


def v(quota: LoggingQuota, msg: str, *args, level: int = logging.INFO) -> None:
    """Log while the quota lasts; overflow is counted, not printed."""
    quota.left -= 1
    if quota.left >= 0:
        logger.log(level, msg, *args)


def frame_up(quota: LoggingQuota, what: str, level: int = logging.INFO) -> None:
    """Emit the '... and N more' summary and reset (reference: klogx.V(...).
    Over() + the summary line after the loop)."""
    if quota.left < 0:
        logger.log(level, "... and %d other %s", -quota.left, what)
    quota.reset()
