"""Leader election: active/passive HA for the control loop.

Reference counterpart: main.go:271-319 — leaderelection.RunOrDie over a
kube Lease lock; only the leader runs the loop, replicas block. Standalone
equivalent: an OS-level advisory file lock (flock) with the same contract —
`run_or_die(fn)` blocks until leadership is acquired, runs fn, and releases
on exit. Works across processes on one host; multi-host deployments point
the lease file at shared storage or swap in a Lease-based implementation
behind the same interface.
"""

from __future__ import annotations

import fcntl
import os
import time


class FileLeaderElector:
    def __init__(self, lease_file: str, retry_period_s: float = 2.0):
        self.lease_file = lease_file
        self.retry_period_s = retry_period_s
        self._fd: int | None = None

    def try_acquire(self) -> bool:
        fd = os.open(self.lease_file, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        os.ftruncate(fd, 0)
        os.write(fd, str(os.getpid()).encode())
        self._fd = fd
        return True

    def acquire(self, timeout_s: float | None = None, stop=None) -> bool:
        """Block for leadership. `stop` (threading.Event) aborts the wait —
        a passive replica must stay killable by SIGTERM while standing by."""
        deadline = None if timeout_s is None else time.time() + timeout_s
        while True:
            if stop is not None and stop.is_set():
                return False
            if self.try_acquire():
                return True
            if deadline is not None and time.time() >= deadline:
                return False
            if stop is not None:
                stop.wait(self.retry_period_s)
            else:
                time.sleep(self.retry_period_s)

    def release(self) -> None:
        if self._fd is not None:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
            self._fd = None

    def is_leader(self) -> bool:
        return self._fd is not None

    def run_or_die(self, fn, timeout_s: float | None = None, stop=None):
        """reference: leaderelection.RunOrDie — block for leadership, run.
        Returns None without running fn when `stop` fires during the wait."""
        if not self.acquire(timeout_s, stop=stop):
            if stop is not None and stop.is_set():
                return None
            raise TimeoutError("could not acquire leadership")
        try:
            return fn()
        finally:
            self.release()
