"""Serial exact predicate oracle — the test-time ground truth.

Plays the role the reference's Go path plays for its TPU sidecar (SURVEY.md §4
'oracle-checked against a serial reference implementation'): a direct,
unvectorized implementation of the simulable Filter subset with full string
semantics. The device kernels (ops/predicates.py) are property-tested against
this module; the control plane also uses it to exactly verify selected
winners before actuation (the host-check tier for lossy encodings).

Semantics distilled from the vendored kube-scheduler plugins the reference
runs (simulator/framework/handle.go:84-89 builds the in-tree registry):
NodeResourcesFit, NodeAffinity, TaintToleration, NodePorts, NodeUnschedulable.
"""

from __future__ import annotations

from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.models.api import (
    NO_EXECUTE,
    NO_SCHEDULE,
    TO_BE_DELETED_TAINT,
    Node,
    Pod,
)
from kubernetes_autoscaler_tpu.models.encode import (
    node_capacity_vector,
    pod_request_vector,
)


def resources_fit(pod: Pod, node: Node,
                  registry: res.ExtendedResourceRegistry | None = None) -> bool:
    """Fit vs an empty node (resident-pod usage is handled in check_pod_on_node)."""
    registry = registry or res.ExtendedResourceRegistry()
    cap = node_capacity_vector(node, registry).astype(int)
    req, _ = pod_request_vector(pod, registry)
    return bool((req.astype(int) <= cap).all())


def selector_matches(pod: Pod, node: Node) -> bool:
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    for r in pod.required_node_affinity:
        if r.operator == "In":
            if node.labels.get(r.key) not in r.values:
                return False
        elif r.operator == "NotIn":
            if node.labels.get(r.key) in r.values:
                return False
        elif r.operator == "Exists":
            if r.key not in node.labels:
                return False
        elif r.operator == "DoesNotExist":
            if r.key in node.labels:
                return False
        else:
            raise NotImplementedError(f"operator {r.operator}")
    return True


def taints_tolerated(pod: Pod, node: Node) -> bool:
    for t in node.taints:
        if t.effect not in (NO_SCHEDULE, NO_EXECUTE):
            continue
        tolerated = False
        for tol in pod.tolerations:
            if tol.effect and tol.effect != t.effect:
                continue
            if tol.operator == "Exists":
                if not tol.key or tol.key == t.key:
                    tolerated = True
                    break
            else:
                if tol.key == t.key and tol.value == t.value:
                    tolerated = True
                    break
        if not tolerated:
            return False
    return True


def ports_free(pod: Pod, pods_on_node: list[Pod]) -> bool:
    wanted = {(p, proto or "TCP") for p, proto in pod.host_ports}
    if not wanted:
        return True
    used = set()
    for q in pods_on_node:
        used.update((p, proto or "TCP") for p, proto in q.host_ports)
    return not (wanted & used)


def node_schedulable(node: Node) -> bool:
    if node.unschedulable or not node.ready:
        return False
    return all(t.key != TO_BE_DELETED_TAINT for t in node.taints)


def check_pod_on_node(
    pod: Pod,
    node: Node,
    pods_on_node: list[Pod],
    registry: res.ExtendedResourceRegistry | None = None,
) -> bool:
    """Exact verdict: can `pod` schedule on `node` given its resident pods?"""
    registry = registry or res.ExtendedResourceRegistry()
    if not node_schedulable(node):
        return False
    if not selector_matches(pod, node):
        return False
    if not taints_tolerated(pod, node):
        return False
    if not ports_free(pod, pods_on_node):
        return False
    cap = node_capacity_vector(node, registry).astype(int)
    used = sum(
        (pod_request_vector(q, registry)[0].astype(int) for q in pods_on_node),
        start=cap * 0,
    )
    req, _ = pod_request_vector(pod, registry)
    if not bool((req.astype(int) <= cap - used).all()):
        return False
    for term in pod.anti_affinity:
        if term.topology_key == "kubernetes.io/hostname":
            for q in pods_on_node:
                if all(q.labels.get(k) == v for k, v in term.match_labels.items()):
                    return False
    return True
