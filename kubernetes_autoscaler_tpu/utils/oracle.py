"""Serial exact predicate oracle — the test-time and host-check ground truth.

Plays the role the reference's Go path plays for its TPU sidecar (SURVEY.md §4
'oracle-checked against a serial reference implementation'): a direct,
unvectorized implementation of the simulable Filter subset with full string
semantics. The device kernels (ops/predicates.py, ops/constrained.py) are
property-tested against this module; the control plane also uses it to exactly
verify selected winners before actuation (the host-check tier for lossy
encodings).

Semantics distilled from the vendored kube-scheduler plugins the reference
runs (simulator/framework/handle.go:84-89 builds the in-tree registry):
NodeResourcesFit, NodeAffinity (full OR-of-terms + Gt/Lt), TaintToleration,
NodePorts, NodeUnschedulable, InterPodAffinity (required affinity and
anti-affinity, any topology key, first-pod exception), PodTopologySpread
(DoNotSchedule constraints).

Cluster-wide constraints (spread, inter-pod affinity) need the whole snapshot;
`check_pod_in_cluster` is the full-context entry. `check_pod_on_node` keeps
the single-node view for the plain predicates.
"""

from __future__ import annotations

from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.models.api import (
    HOSTNAME_KEY,
    NO_EXECUTE,
    NO_SCHEDULE,
    TO_BE_DELETED_TAINT,
    ZONE_KEY,
    ZONE_KEY_BETA,
    AffinityTerm,
    Node,
    Pod,
    labels_match,
    term_matches_pod,
)
from kubernetes_autoscaler_tpu.models.encode import (
    node_capacity_vector,
    pod_request_vector,
)


def resources_fit(pod: Pod, node: Node,
                  registry: res.ExtendedResourceRegistry | None = None) -> bool:
    """Fit vs an empty node (resident-pod usage is handled in check_pod_on_node)."""
    registry = registry or res.ExtendedResourceRegistry()
    cap = node_capacity_vector(node, registry).astype(int)
    req, _ = pod_request_vector(pod, registry)
    return bool((req.astype(int) <= cap).all())


def _as_int(s: str) -> int | None:
    try:
        return int(s)
    except (TypeError, ValueError):
        return None


def requirement_matches(req, labels: dict[str, str]) -> bool:
    """One NodeSelectorRequirement vs a label map (k8s v1.NodeSelectorRequirement
    semantics, Gt/Lt included: both sides must parse as integers)."""
    val = labels.get(req.key)
    if req.operator == "In":
        return val is not None and val in req.values
    if req.operator == "NotIn":
        return val not in req.values
    if req.operator == "Exists":
        return req.key in labels
    if req.operator == "DoesNotExist":
        return req.key not in labels
    if req.operator in ("Gt", "Lt"):
        lhs = _as_int(val) if val is not None else None
        rhs = _as_int(req.values[0]) if req.values else None
        if lhs is None or rhs is None:
            return False
        return lhs > rhs if req.operator == "Gt" else lhs < rhs
    raise NotImplementedError(f"operator {req.operator}")


def selector_matches(pod: Pod, node: Node) -> bool:
    """nodeSelector AND required node affinity (OR over nodeSelectorTerms,
    AND within a term — k8s NodeAffinity semantics)."""
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    terms = pod.affinity_node_terms()
    if not terms:
        return True
    return any(
        all(requirement_matches(r, node.labels) for r in term) for term in terms
    )


def taints_tolerated(pod: Pod, node: Node) -> bool:
    for t in node.taints:
        if t.effect not in (NO_SCHEDULE, NO_EXECUTE):
            continue
        tolerated = False
        for tol in pod.tolerations:
            if tol.effect and tol.effect != t.effect:
                continue
            if tol.operator == "Exists":
                if not tol.key or tol.key == t.key:
                    tolerated = True
                    break
            else:
                if tol.key == t.key and tol.value == t.value:
                    tolerated = True
                    break
        if not tolerated:
            return False
    return True


def ports_free(pod: Pod, pods_on_node: list[Pod]) -> bool:
    wanted = {(p, proto or "TCP") for p, proto in pod.host_ports}
    if not wanted:
        return True
    used = set()
    for q in pods_on_node:
        used.update((p, proto or "TCP") for p, proto in q.host_ports)
    return not (wanted & used)


def node_schedulable(node: Node) -> bool:
    if node.unschedulable or not node.ready:
        return False
    return all(t.key != TO_BE_DELETED_TAINT for t in node.taints)


# ---- topology helpers ----------------------------------------------------


def topology_value(node: Node, key: str) -> str | None:
    """The node's domain value for a topology key (None = key absent).

    The GA zone key falls back to the beta key; the hostname key falls back to
    the node name (kubelet always sets it; lightweight fixtures may not)."""
    if key == ZONE_KEY:
        return node.labels.get(ZONE_KEY, node.labels.get(ZONE_KEY_BETA))
    if key == HOSTNAME_KEY:
        return node.labels.get(HOSTNAME_KEY, node.name)
    return node.labels.get(key)


_term_matches_pod = term_matches_pod  # canonical impl lives in models/api.py


# ---- cluster-wide constraints -------------------------------------------


def spread_ok(
    pod: Pod,
    node: Node,
    nodes: list[Node],
    pods_by_node: dict[str, list[Pod]],
) -> bool:
    """PodTopologySpread DoNotSchedule check (vendored plugin semantics,
    podtopologyspread/{common,filtering}.go):

      * the constraint selector is match_labels merged with the pod's values
        for match_label_keys (common.go:96-104);
      * domains and their match counts are computed over nodes passing the
        node INCLUSION POLICIES — nodeAffinityPolicy=Honor (default) keeps
        only nodes matching the pod's nodeSelector/affinity,
        nodeTaintsPolicy=Honor keeps only nodes whose DoNotSchedule taints
        the pod tolerates (common.go:42-56);
      * global minimum = min match count over those domains, treated as 0
        while fewer domains exist than min_domains (filtering.go:54-67);
      * verdict: count(candidate's domain) + selfMatchNum - min <= max_skew,
        selfMatchNum = 1 iff the pod matches the (merged) selector
        (filtering.go:337-351). A candidate node without the topology key
        can never satisfy the constraint (filtering.go:330-335)."""
    for c in pod.spread_constraints():
        v_here = topology_value(node, c.topology_key)
        if v_here is None:
            return False  # node without the key cannot satisfy the constraint
        sel = c.merged_selector(pod.labels)
        counts: dict[str, int] = {}
        for nd in nodes:
            v = topology_value(nd, c.topology_key)
            if v is None:
                continue
            if c.node_affinity_policy != "Ignore" and not selector_matches(pod, nd):
                continue
            if c.node_taints_policy == "Honor" and not taints_tolerated(pod, nd):
                continue
            counts.setdefault(v, 0)
            for q in pods_by_node.get(nd.name, []):
                if q.namespace == pod.namespace and labels_match(sel, q.labels):
                    counts[v] += 1
        min_count = min(counts.values(), default=0)
        if len(counts) < max(int(c.min_domains), 1):
            min_count = 0  # not enough eligible domains yet (filtering.go:61)
        self_match = 1 if labels_match(sel, pod.labels) else 0
        if counts.get(v_here, 0) + self_match - min_count > c.max_skew:
            return False
    return True


def pod_affinity_ok(
    pod: Pod,
    node: Node,
    nodes: list[Node],
    pods_by_node: dict[str, list[Pod]],
    namespaces: dict[str, dict[str, str]] | None = None,
) -> bool:
    """Required inter-pod affinity: each term needs >=1 matching pod in the
    candidate node's topology domain. First-pod exception (vendored
    InterPodAffinity): a term with NO matching pod anywhere is satisfied if
    the incoming pod matches its own selector+namespaces."""
    for term in pod.pod_affinity:
        v_here = topology_value(node, term.topology_key)
        if v_here is None:
            return False
        matched_here = False
        matched_anywhere = False
        for nd in nodes:
            v = topology_value(nd, term.topology_key)
            for q in pods_by_node.get(nd.name, []):
                if _term_matches_pod(term, pod, q, namespaces):
                    matched_anywhere = True
                    if v == v_here:
                        matched_here = True
        if matched_here:
            continue
        if not matched_anywhere and _term_matches_pod(term, pod, pod,
                                                      namespaces):
            continue  # first-pod exception
        return False
    return True


def anti_affinity_ok(
    pod: Pod,
    node: Node,
    nodes: list[Node],
    pods_by_node: dict[str, list[Pod]],
    namespaces: dict[str, dict[str, str]] | None = None,
) -> bool:
    """Required inter-pod anti-affinity: no matching pod may share the
    candidate node's topology domain. A node without the key has no domain,
    so the term cannot be violated there (vendored plugin behavior)."""
    for term in pod.anti_affinity:
        v_here = topology_value(node, term.topology_key)
        if v_here is None:
            continue
        for nd in nodes:
            if topology_value(nd, term.topology_key) != v_here:
                continue
            for q in pods_by_node.get(nd.name, []):
                if _term_matches_pod(term, pod, q, namespaces):
                    return False
    return True


# ---- verdict entries -----------------------------------------------------


def group_pods_by_node(pods: list[Pod]) -> dict[str, list[Pod]]:
    by_node: dict[str, list[Pod]] = {}
    for p in pods:
        if p.node_name and p.phase not in ("Succeeded", "Failed"):
            by_node.setdefault(p.node_name, []).append(p)
    return by_node


def check_pod_on_node(
    pod: Pod,
    node: Node,
    pods_on_node: list[Pod],
    registry: res.ExtendedResourceRegistry | None = None,
) -> bool:
    """Single-node verdict: plain predicates plus the cluster constraints
    evaluated in a one-node world (exact when the pod has no cluster-wide
    constraints; call check_pod_in_cluster when it does)."""
    return check_pod_in_cluster(
        pod, node, [node], {node.name: list(pods_on_node)}, registry
    )


def check_pod_in_cluster(
    pod: Pod,
    node: Node,
    nodes: list[Node],
    pods_by_node: dict[str, list[Pod]],
    registry: res.ExtendedResourceRegistry | None = None,
    namespaces: dict[str, dict[str, str]] | None = None,
) -> bool:
    """Exact verdict with full cluster context: can `pod` schedule on `node`?

    `namespaces` (name → labels) makes affinity namespace_selector terms
    exact; without it such terms match nothing beyond their explicit
    namespace lists (models/api.term_matches_pod contract)."""
    registry = registry or res.ExtendedResourceRegistry()
    if not node_schedulable(node):
        return False
    if not selector_matches(pod, node):
        return False
    if not taints_tolerated(pod, node):
        return False
    pods_on_node = pods_by_node.get(node.name, [])
    if not ports_free(pod, pods_on_node):
        return False
    cap = node_capacity_vector(node, registry).astype(int)
    used = sum(
        (pod_request_vector(q, registry)[0].astype(int) for q in pods_on_node),
        start=cap * 0,
    )
    req, _ = pod_request_vector(pod, registry)
    if not bool((req.astype(int) <= cap - used).all()):
        return False
    if pod.anti_affinity and not anti_affinity_ok(pod, node, nodes,
                                                  pods_by_node, namespaces):
        return False
    if pod.pod_affinity and not pod_affinity_ok(pod, node, nodes,
                                                pods_by_node, namespaces):
        return False
    if not spread_ok(pod, node, nodes, pods_by_node):
        return False
    return True


def fresh_node_from_template(template: Node,
                             fresh_name: str = "template-fresh-node") -> Node:
    """Template → concrete fresh node, the estimator's sanitization
    (binpacking_estimator.go:330 via SanitizedNodeInfo). Shared by the
    oracle and the ConfirmOracle cache so their worlds cannot diverge."""
    return Node(
        name=fresh_name,
        labels={**template.labels, HOSTNAME_KEY: fresh_name},
        annotations=dict(template.annotations),
        capacity=dict(template.capacity),
        allocatable=dict(template.allocatable),
        taints=list(template.taints),
        ready=True,
        unschedulable=False,
    )


def check_pod_on_new_node(
    pod: Pod,
    template: Node,
    nodes: list[Node],
    pods_by_node: dict[str, list[Pod]],
    registry: res.ExtendedResourceRegistry | None = None,
    fresh_name: str = "template-fresh-node",
    namespaces: dict[str, dict[str, str]] | None = None,
    resident_pods: list[Pod] | None = None,
) -> bool:
    """Can `pod` schedule on a FRESH node stamped from `template`, given the
    current cluster? This is the scale-up winner-verification question
    (reference: the estimator schedules against a sanitized template NodeInfo
    added to the forked snapshot, binpacking_estimator.go:330).
    `resident_pods` pre-load the fresh node — DaemonSet overhead, the
    reference's DS-loaded template NodeInfos (node_info_utils.go:45)."""
    fresh = fresh_node_from_template(template, fresh_name)
    if resident_pods:
        pods_by_node = {**pods_by_node, fresh.name: list(resident_pods)}
    return check_pod_in_cluster(
        pod, fresh, list(nodes) + [fresh], pods_by_node, registry,
        namespaces=namespaces,
    )
