"""ConfirmOracle: the exact oracle with incrementally-maintained constraint
state, for the scale-down confirmation pass.

utils/oracle.check_pod_in_cluster is the ground truth, but its cluster-wide
constraint checks walk all nodes x resident pods PER CALL — O(N*P) — which
the confirmation pass may invoke per candidate destination. At 5k nodes x
50k pods one call is ~2.5e8 label matches: the "unbounded host-check tier"
of the round-3 review (Weak #4 / item #6). This cache makes each verdict
O(domains + pod fields) by:

  * precomputing, lazily per distinct constraint signature, the per-domain
    match counts (and per-term counts for (anti-)affinity) over the CURRENT
    world;
  * maintaining them under the pass's mutations — `move(pod, src, dst)` and
    `remove_node(name)` — instead of rescanning;
  * memoizing pod-class (namespace + labels) selector matches and
    node-inclusion verdicts.

Contract: `check(pod, node)` returns exactly what
oracle.check_pod_in_cluster(pod, node, alive_nodes, pods_by_node,
registry, namespaces) returns for the equivalent world.
tests/test_oracle_cache.py property-tests this under randomized
move/remove sequences.
"""

from __future__ import annotations

from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.models.api import (
    Node,
    Pod,
    labels_match,
    term_matches_pod,
)
from kubernetes_autoscaler_tpu.utils import oracle as _o


def _pod_class(p: Pod) -> tuple:
    return (p.namespace, tuple(sorted(p.labels.items())))


def _term_sig(term, pod: Pod) -> tuple:
    return ("term", term.topology_key,
            tuple(sorted(term.match_labels.items())),
            term.namespaces or (pod.namespace,),
            tuple(sorted(term.namespace_selector.items()))
            if term.namespace_selector is not None else None)


def _spread_sig(c, pod: Pod) -> tuple:
    sel = c.merged_selector(pod.labels)
    return ("spread", c.topology_key, tuple(sorted(sel.items())),
            pod.namespace)


class _CountIndex:
    """Per-domain (and total) count of pods matching one selector/term.

    `node_filter` (spread indexes) restricts counting to nodes passing the
    constraint's inclusion policies — the vendored prefilter counts matches
    only on included nodes. `total` counts matches on ALL nodes regardless
    of topology key (the oracle's matched_anywhere semantics)."""

    __slots__ = ("by_domain", "total", "matcher", "topology_key",
                 "node_filter", "domains", "filter_memo")

    def __init__(self, topology_key, matcher, node_filter=None):
        self.topology_key = topology_key
        self.matcher = matcher        # Pod -> bool (memoized by caller)
        self.node_filter = node_filter  # Node -> bool (memoized), or None
        self.filter_memo: dict[str, bool] = {}
        self.by_domain: dict[str, int] = {}
        self.total = 0
        # domain value -> number of included alive nodes holding it (spread
        # indexes only; lets the skew check avoid any per-check node walk)
        self.domains: dict[str, int] = {}

    def add(self, pod: Pod, node: Node, sign: int) -> None:
        if not self.matcher(pod):
            return
        self.bump(node, sign)

    def bump(self, node: Node, sign: int) -> None:
        """add() for a pod the caller already knows matches."""
        self.total += sign
        if self.node_filter is not None and not self.node_filter(node):
            return
        v = _o.topology_value(node, self.topology_key)
        if v is None:
            return
        self.by_domain[v] = self.by_domain.get(v, 0) + sign


class ConfirmOracle:
    def __init__(
        self,
        nodes: list[Node],
        pods_by_node: dict[str, list[Pod]],
        registry: res.ExtendedResourceRegistry | None = None,
        namespaces: dict[str, dict[str, str]] | None = None,
    ):
        self.registry = registry or res.ExtendedResourceRegistry()
        self.namespaces = namespaces
        self.node_by_name: dict[str, Node] = {nd.name: nd for nd in nodes}
        self.pods_by_node = {k: list(v) for k, v in pods_by_node.items()}
        self._indexes: dict[tuple, _CountIndex] = {}
        # (sig-key, pod-class) -> bool match memo backing the indexes
        self._match_memo: dict[tuple, bool] = {}
        self._req_memo: dict[int, object] = {}   # id(pod) -> request vector
        # pod -> the indexes whose selector it matches (rebuilt when a new
        # index appears): makes move() O(matched) instead of O(indexes)
        self._indexes_version = 0
        self._pod_matched: dict[int, tuple[int, list]] = {}
        self._used: dict[str, object] = {}       # node name -> used vector
        self._cap_memo: dict[str, object] = {}   # node name -> capacity vec

    # ------------------------------------------------------------ mutations

    def move(self, pod: Pod, src: str, dst: str) -> None:
        """pod leaves node `src` (name, may be "") and lands on `dst`."""
        if src:
            lst = self.pods_by_node.get(src, [])
            if pod in lst:
                lst.remove(pod)
            nd = self.node_by_name.get(src)
            if nd is not None:
                for idx in self._matched_indexes(pod):
                    idx.bump(nd, -1)
                if src in self._used:
                    self._used[src] = self._used[src] - self._req(pod)
        if dst:
            self.pods_by_node.setdefault(dst, []).append(pod)
            nd = self.node_by_name.get(dst)
            if nd is not None:
                for idx in self._matched_indexes(pod):
                    idx.bump(nd, +1)
                if dst in self._used:
                    self._used[dst] = self._used[dst] + self._req(pod)

    def add_node(self, node: Node) -> None:
        """A node joins the world (e.g. a FRESH template instantiation for
        scale-up winner verification) — spread domain sets grow where the
        node passes a constraint's inclusion policies."""
        self.node_by_name[node.name] = node
        for idx in self._indexes.values():
            if idx.node_filter is not None and idx.node_filter(node):
                v = _o.topology_value(node, idx.topology_key)
                if v is not None:
                    idx.domains[v] = idx.domains.get(v, 0) + 1

    def check_on_new_node(self, pod: Pod, template: Node,
                          fresh_name: str = "template-fresh-node",
                          resident_pods: list | None = None) -> bool:
        """≡ oracle.check_pod_on_new_node over the cache's current world:
        can `pod` schedule on a FRESH node stamped from `template`?
        `resident_pods` pre-load the fresh node (DaemonSet overhead —
        reference template NodeInfos carry their DS pods)."""
        fresh = _o.fresh_node_from_template(template, fresh_name)
        self.add_node(fresh)
        if resident_pods:
            self.pods_by_node[fresh.name] = list(resident_pods)
            for q in resident_pods:          # symmetric with remove_node's -1
                for idx in self._matched_indexes(q):
                    idx.bump(fresh, +1)
        try:
            return self.check(pod, fresh)
        finally:
            self.remove_node(fresh.name)

    def remove_node(self, name: str) -> None:
        """Node leaves the world; any pods still listed on it vanish with it
        (the pass's by_node.pop semantics — daemonset leftovers)."""
        nd = self.node_by_name.pop(name, None)
        if nd is None:
            return
        for q in self.pods_by_node.pop(name, []):
            for idx in self._matched_indexes(q):
                idx.bump(nd, -1)
        self._used.pop(name, None)
        # NAME-keyed memos must die with the node: a different node may
        # reuse the name (the fresh template-node name does, every
        # check_on_new_node call) and would otherwise see stale verdicts
        self._cap_memo.pop(name, None)
        for idx in self._indexes.values():
            if idx.node_filter is not None and idx.node_filter(nd):
                v = _o.topology_value(nd, idx.topology_key)
                if v is not None and v in idx.domains:
                    idx.domains[v] -= 1
                    if idx.domains[v] <= 0:
                        del idx.domains[v]
            idx.filter_memo.pop(name, None)


    # ------------------------------------------------------------- internal

    def _matched_indexes(self, pod: Pod) -> list:
        ver, lst = self._pod_matched.get(id(pod), (-1, None))
        if ver != self._indexes_version:
            lst = [idx for idx in self._indexes.values()
                   if idx.matcher(pod)]
            self._pod_matched[id(pod)] = (self._indexes_version, lst)
        return lst

    def _index_for(self, sig: tuple, topology_key: str, matcher,
                   node_filter=None):
        idx = self._indexes.get(sig)
        if idx is None:
            # two-level memo: by pod IDENTITY first (one dict hit per add —
            # the pass calls move() per placement and every index sees every
            # moved pod), falling back to the pod-class memo so equal-labeled
            # pods share one selector evaluation
            cls_memo = self._match_memo
            id_memo: dict[int, bool] = {}

            def memo_matcher(q: Pod, _sig=sig, _m=matcher):
                hit = id_memo.get(id(q))
                if hit is None:
                    key = (_sig, _pod_class(q))
                    hit = cls_memo.get(key)
                    if hit is None:
                        hit = cls_memo[key] = _m(q)
                    id_memo[id(q)] = hit
                return hit

            filt = None
            fmemo: dict[str, bool] = {}
            if node_filter is not None:
                def filt(nd: Node, _f=node_filter, _memo=fmemo):
                    hit = _memo.get(nd.name)
                    if hit is None:
                        hit = _memo[nd.name] = _f(nd)
                    return hit

            idx = _CountIndex(topology_key, memo_matcher, filt)
            idx.filter_memo = fmemo
            for name, qs in self.pods_by_node.items():
                nd = self.node_by_name.get(name)
                if nd is None:
                    continue
                for q in qs:
                    idx.add(q, nd, +1)
            if filt is not None:  # spread index: precompute the domain set
                for nd in self.node_by_name.values():
                    if not filt(nd):
                        continue
                    v = _o.topology_value(nd, topology_key)
                    if v is not None:
                        idx.domains[v] = idx.domains.get(v, 0) + 1
            self._indexes[sig] = idx
            self._indexes_version += 1
        return idx

    def _included(self, pod: Pod, nd: Node, honor_affinity: bool,
                  honor_taints: bool) -> bool:
        if honor_affinity and not _o.selector_matches(pod, nd):
            return False
        if honor_taints and not _o.taints_tolerated(pod, nd):
            return False
        return True

    # --------------------------------------------------------------- checks

    def _spread_ok(self, pod: Pod, node: Node) -> bool:
        for c in pod.spread_constraints():
            v_here = _o.topology_value(node, c.topology_key)
            if v_here is None:
                return False
            sel = c.merged_selector(pod.labels)
            honor_aff = c.node_affinity_policy != "Ignore"
            honor_taints = c.node_taints_policy == "Honor"
            # inclusion fingerprint: pods of one equivalence class share
            # selector content, so indexes key on VALUES, not object ids
            incl_sig = (
                tuple(sorted(pod.node_selector.items())) if honor_aff else (),
                repr(pod.affinity_node_terms()) if honor_aff else "",
                repr([(t.key, t.operator, t.value, t.effect)
                      for t in pod.tolerations]) if honor_taints else "",
                honor_aff, honor_taints,
            )
            sig = _spread_sig(c, pod) + (incl_sig,)
            idx = self._index_for(
                sig, c.topology_key,
                lambda q, _sel=sel, _ns=pod.namespace:
                    q.namespace == _ns and labels_match(_sel, q.labels),
                node_filter=lambda nd, _p=pod, _a=honor_aff, _t=honor_taints:
                    self._included(_p, nd, _a, _t))
            min_count = min(
                (idx.by_domain.get(v, 0) for v in idx.domains), default=0)
            if len(idx.domains) < max(int(c.min_domains), 1):
                min_count = 0
            self_match = 1 if labels_match(sel, pod.labels) else 0
            if idx.by_domain.get(v_here, 0) + self_match - min_count \
                    > c.max_skew:
                return False
        return True

    def _anti_ok(self, pod: Pod, node: Node) -> bool:
        for term in pod.anti_affinity:
            v_here = _o.topology_value(node, term.topology_key)
            if v_here is None:
                continue
            idx = self._index_for(
                _term_sig(term, pod), term.topology_key,
                lambda q, _t=term, _p=pod:
                    term_matches_pod(_t, _p, q, self.namespaces))
            if idx.by_domain.get(v_here, 0) > 0:
                return False
        return True

    def _aff_ok(self, pod: Pod, node: Node) -> bool:
        for term in pod.pod_affinity:
            v_here = _o.topology_value(node, term.topology_key)
            if v_here is None:
                return False
            idx = self._index_for(
                _term_sig(term, pod), term.topology_key,
                lambda q, _t=term, _p=pod:
                    term_matches_pod(_t, _p, q, self.namespaces))
            if idx.by_domain.get(v_here, 0) > 0:
                continue
            if idx.total == 0 and term_matches_pod(term, pod, pod,
                                                   self.namespaces):
                continue  # first-pod exception
            return False
        return True

    def _req(self, pod: Pod):
        from kubernetes_autoscaler_tpu.models.encode import pod_request_vector

        v = self._req_memo.get(id(pod))
        if v is None:
            v = self._req_memo[id(pod)] = \
                pod_request_vector(pod, self.registry)[0].astype(int)
        return v

    def check_constraints(self, pod: Pod, node: Node) -> bool:
        """Cluster-wide-constraint-only verdict — inter-pod (anti-)affinity
        and topology spread over the cache's current world, each O(domains)
        instead of O(nodes × pods). For callers that gate capacity,
        selector, taints and ports themselves (the scale-down planner's
        phantom injection runs those against device-true free capacity the
        oracle world cannot see). ≡ the corresponding utils/oracle checks:
        anti_affinity_ok ∧ pod_affinity_ok ∧ spread_ok."""
        if pod.anti_affinity and not self._anti_ok(pod, node):
            return False
        if pod.pod_affinity and not self._aff_ok(pod, node):
            return False
        return self._spread_ok(pod, node)

    def check(self, pod: Pod, node: Node) -> bool:
        """≡ oracle.check_pod_in_cluster over the cache's current world."""
        if not _o.node_schedulable(node):
            return False
        if not _o.selector_matches(pod, node):
            return False
        if not _o.taints_tolerated(pod, node):
            return False
        pods_on_node = self.pods_by_node.get(node.name, [])
        if not _o.ports_free(pod, pods_on_node):
            return False
        from kubernetes_autoscaler_tpu.models.encode import (
            node_capacity_vector,
        )

        cap = self._cap_memo.get(node.name)
        if cap is None:
            cap = self._cap_memo[node.name] = \
                node_capacity_vector(node, self.registry).astype(int)
        used = self._used.get(node.name)
        if used is None:
            used = self._used[node.name] = sum(
                (self._req(q) for q in pods_on_node), start=cap * 0)
        if not bool((self._req(pod) <= cap - used).all()):
            return False
        if pod.anti_affinity and not self._anti_ok(pod, node):
            return False
        if pod.pod_affinity and not self._aff_ok(pod, node):
            return False
        if not self._spread_ok(pod, node):
            return False
        return True
