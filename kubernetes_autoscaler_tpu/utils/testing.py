"""Test fixture builders, mirroring the reference's utils/test idiom
(cluster-autoscaler/utils/test/test_utils.go: BuildTestNode, BuildTestPod,
SetNodeReadyState — used across every core test)."""

from __future__ import annotations

from kubernetes_autoscaler_tpu.models.api import (
    Node,
    OwnerRef,
    Pod,
    Taint,
    Toleration,
)

_MIB = 1024 * 1024


def build_test_node(
    name: str,
    cpu_milli: int = 1000,
    mem_mib: int = 2048,
    pods: int = 110,
    labels: dict[str, str] | None = None,
    taints: list[Taint] | None = None,
    zone: str = "",
    ready: bool = True,
    gpus: int = 0,
    gpu_resource: str = "nvidia.com/gpu",
) -> Node:
    lbl = {"kubernetes.io/hostname": name}
    if zone:
        lbl["topology.kubernetes.io/zone"] = zone
    if labels:
        lbl.update(labels)
    cap: dict[str, float] = {
        "cpu": cpu_milli / 1000.0,
        "memory": mem_mib * _MIB,
        "pods": pods,
    }
    if gpus:
        cap[gpu_resource] = gpus
    return Node(
        name=name,
        labels=lbl,
        capacity=dict(cap),
        allocatable=dict(cap),
        taints=list(taints or []),
        ready=ready,
    )


def build_test_pod(
    name: str,
    cpu_milli: int = 100,
    mem_mib: int = 128,
    namespace: str = "default",
    node_name: str = "",
    labels: dict[str, str] | None = None,
    node_selector: dict[str, str] | None = None,
    tolerations: list[Toleration] | None = None,
    owner_kind: str = "ReplicaSet",
    owner_name: str = "",
    gpus: int = 0,
    gpu_resource: str = "nvidia.com/gpu",
    host_port: int = 0,
    priority: int = 0,
) -> Pod:
    req: dict[str, float] = {}
    if cpu_milli:
        req["cpu"] = cpu_milli / 1000.0
    if mem_mib:
        req["memory"] = mem_mib * _MIB
    if gpus:
        req[gpu_resource] = gpus
    owner = None
    if owner_kind:
        oname = owner_name or f"{name}-owner"
        owner = OwnerRef(kind=owner_kind, name=oname, uid=f"uid-{oname}")
    return Pod(
        name=name,
        namespace=namespace,
        uid=f"uid-{namespace}/{name}",
        labels=dict(labels or {}),
        requests=req,
        node_selector=dict(node_selector or {}),
        tolerations=list(tolerations or []),
        owner=owner,
        node_name=node_name,
        host_ports=((host_port, "TCP"),) if host_port else (),
        priority=priority,
        phase="Running" if node_name else "Pending",
    )


def replicate(pod_factory, count: int, prefix: str):
    """count pods sharing one controller (one equivalence group)."""
    pods = []
    for i in range(count):
        p = pod_factory(f"{prefix}-{i}")
        pods.append(p)
    return pods
