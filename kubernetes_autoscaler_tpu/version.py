"""Framework version (reference: cluster-autoscaler/version/version.go).

Tracks the reference release line this framework targets for behavior parity,
plus the framework's own version.
"""

# reference line whose flags/metrics/semantics this framework tracks
REFERENCE_VERSION = "cluster-autoscaler-1.33"
VERSION = "0.3.0"  # round 3


def version_string() -> str:
    return f"kubernetes-autoscaler-tpu {VERSION} (parity: {REFERENCE_VERSION})"
