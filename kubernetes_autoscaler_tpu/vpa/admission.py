"""VPA admission logic: patch pod requests to the current recommendation.

Reference counterpart: vertical-pod-autoscaler/pkg/admission-controller/ —
a mutating webhook (logic/server.go) that, on pod create, applies the matching
VPA's recommendation to container requests (resource/pod/patch) and proportionally
adjusts limits. The webhook transport (TLS server) is deployment plumbing; the
patch computation here is the product logic, exposed as a pure function plus an
optional HTTP server in sidecar/http.py.
"""

from __future__ import annotations

from dataclasses import dataclass

from kubernetes_autoscaler_tpu.vpa.model import UpdateMode, VerticalPodAutoscaler


@dataclass
class PatchOp:
    container: str
    resource: str
    value: float


def validate_vpa(vpa: VerticalPodAutoscaler) -> list[str]:
    """Validate a VPA object (reference: admission-controller also validates
    VPA create/update — vpa_lint: sane min<=max policy bounds, known modes,
    a target ref). Returns human-readable problems; empty means valid."""
    problems: list[str] = []
    if not vpa.target_name:
        problems.append("spec.targetRef is required")
    if vpa.min_replicas < 0:
        problems.append("minReplicas must be >= 0")
    for cp in vpa.resource_policies:
        if cp.mode not in ("Auto", "Off"):
            problems.append(
                f"container {cp.container_name!r}: unknown mode {cp.mode!r}")
        if cp.controlled_values not in ("RequestsOnly", "RequestsAndLimits"):
            problems.append(
                f"container {cp.container_name!r}: unknown controlledValues "
                f"{cp.controlled_values!r}")
        for res in set(cp.min_allowed) | set(cp.max_allowed):
            lo = cp.min_allowed.get(res)
            hi = cp.max_allowed.get(res)
            if lo is not None and lo < 0:
                problems.append(
                    f"container {cp.container_name!r}: minAllowed[{res}] < 0")
            if hi is not None and hi < 0:
                problems.append(
                    f"container {cp.container_name!r}: maxAllowed[{res}] < 0")
            if hi is not None and lo is not None and hi < lo:
                problems.append(
                    f"container {cp.container_name!r}: maxAllowed[{res}] < "
                    f"minAllowed[{res}]")
    return problems


def patch_for_pod(
    namespace: str,
    owner_name: str,
    containers: dict[str, dict[str, float]],     # container -> current requests
    limits: dict[str, dict[str, float]] | None,
    vpas: list[VerticalPodAutoscaler],
) -> list[PatchOp]:
    """Compute request patches for a pod being admitted."""
    vpa = next(
        (v for v in vpas
         if v.namespace == namespace and v.target_name == owner_name
         and v.update_mode is not UpdateMode.OFF),
        None,
    )
    if vpa is None or not vpa.recommendation:
        return []
    ops: list[PatchOp] = []
    for rec in vpa.recommendation:
        cur = containers.get(rec.container_name)
        if cur is None:
            continue
        policy = vpa.policy_for(rec.container_name)
        if policy.mode == "Off":
            continue
        for res, target in rec.target.items():
            current = cur.get(res, 0.0)
            if abs(current - target) < 1e-12:
                continue
            ops.append(PatchOp(rec.container_name, res, target))
            # proportional limit scaling (reference:
            # resource/pod/recommendation/...limit proportion logic)
            if limits and policy.controlled_values == "RequestsAndLimits":
                lim = limits.get(rec.container_name, {}).get(res)
                if lim is not None and current > 0:
                    ops.append(PatchOp(rec.container_name, f"limit:{res}",
                                       lim * target / current))
    return ops
