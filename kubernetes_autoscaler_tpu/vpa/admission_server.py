"""VPA admission webhook SERVER: the AdmissionReview HTTP surface.

Reference counterpart: vertical-pod-autoscaler/pkg/admission-controller/
logic/server.go — a mutating webhook for pods (patch container requests to the
matching VPA's recommendation) and a validating webhook for VPA objects. The
reference additionally self-manages its serving certificate
(certs/manager.go); here TLS is injected (pass an ssl.SSLContext or cert/key
paths) because certificate issuance belongs to the deployment, not the
decision logic. The request/response wire shape is the k8s
admission.k8s.io/v1 AdmissionReview JSON, base64-JSONPatch response included,
so a real apiserver could call this endpoint unmodified.
"""

from __future__ import annotations

import base64
import json
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_autoscaler_tpu.vpa.admission import patch_for_pod, validate_vpa
from kubernetes_autoscaler_tpu.vpa.model import VerticalPodAutoscaler


_QUANTITY_SUFFIX = {
    "m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
}


def parse_quantity(v) -> float:
    """k8s resource.Quantity string → float ('100m' → 0.1, '128Mi' → bytes).
    Real AdmissionReview pods carry quantity STRINGS, never bare numbers."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    for suf in ("Ki", "Mi", "Gi", "Ti", "Pi", "m", "k", "M", "G", "T", "P"):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * _QUANTITY_SUFFIX[suf]
    return float(s)


def _jsonpatch_from_ops(ops, container_index: dict[str, int]) -> list[dict]:
    """PatchOps → RFC-6902 ops against the pod spec. Containers are a JSON
    ARRAY, so paths must use the container's INDEX, not its name (reference:
    resource/pod/patch emits /spec/containers/<i>/...); `add` upserts whether
    or not the requests/limits key already exists."""
    patches = []
    for op in ops:
        idx = container_index.get(op.container)
        if idx is None:
            continue
        if op.resource.startswith("limit:"):
            res = op.resource.split(":", 1)[1]
            path = f"/spec/containers/{idx}/resources/limits/{res}"
        else:
            path = f"/spec/containers/{idx}/resources/requests/{op.resource}"
        patches.append({"op": "add", "path": path, "value": op.value})
    return patches


class AdmissionService:
    """Transport-independent webhook logic; the HTTP handler is a thin shim."""

    def __init__(self, vpas: list[VerticalPodAutoscaler] | None = None):
        self.vpas = list(vpas or [])

    def review(self, body: dict) -> dict:
        req = body.get("request", {})
        uid = req.get("uid", "")
        kind = (req.get("kind") or {}).get("kind", "")
        obj = req.get("object") or {}
        if kind == "Pod":
            response = self._mutate_pod(req, obj)
        elif kind == "VerticalPodAutoscaler":
            response = self._validate_vpa(obj)
        else:
            response = {"allowed": True}
        response["uid"] = uid
        return {"apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview", "response": response}

    def _mutate_pod(self, req: dict, pod: dict) -> dict:
        meta = pod.get("metadata", {})
        namespace = req.get("namespace") or meta.get("namespace", "default")
        owners = meta.get("ownerReferences") or []
        owner = owners[0]["name"] if owners else meta.get("name", "")
        containers = {}
        limits = {}
        container_index: dict[str, int] = {}
        for i, c in enumerate(pod.get("spec", {}).get("containers", [])):
            container_index[c["name"]] = i
            res = c.get("resources", {})
            containers[c["name"]] = {
                k: parse_quantity(v) for k, v in (res.get("requests") or {}).items()}
            limits[c["name"]] = {
                k: parse_quantity(v) for k, v in (res.get("limits") or {}).items()}
        ops = patch_for_pod(namespace, owner, containers, limits, self.vpas)
        if not ops:
            return {"allowed": True}
        patch = json.dumps(_jsonpatch_from_ops(ops, container_index)).encode()
        return {"allowed": True, "patchType": "JSONPatch",
                "patch": base64.b64encode(patch).decode()}

    def _validate_vpa(self, obj: dict) -> dict:
        vpa = VerticalPodAutoscaler(
            name=obj.get("metadata", {}).get("name", ""),
            namespace=obj.get("metadata", {}).get("namespace", "default"),
            target_name=(obj.get("spec", {}).get("targetRef") or {}).get("name", ""),
        )
        problems = validate_vpa(vpa)
        if problems:
            return {"allowed": False,
                    "status": {"message": "; ".join(problems)}}
        return {"allowed": True}


class AdmissionServer:
    """The serving shell (reference: admission-controller main.go + server.go).

    TLS modes (the apiserver requires TLS in real deployments):
      * certfile/keyfile          — operator-provisioned material
      * self_signed_cert_dir      — the server generates AND ROTATES its own
                                    serving certificate there (reference: the
                                    admission controller's cert
                                    self-management, certs/; round-3 review
                                    item #7). Rotation reloads the live
                                    SSLContext — new handshakes pick up the
                                    fresh pair without rebinding.
      * neither                   — plain HTTP (tests/dev only)."""

    def __init__(self, service: AdmissionService, host: str = "127.0.0.1",
                 port: int = 0, certfile: str | None = None,
                 keyfile: str | None = None,
                 self_signed_cert_dir: str | None = None,
                 cert_valid_days: float = 365.0,
                 rotate_before_s: float = 30 * 24 * 3600.0):
        svc = service
        self.cert_manager = None
        if not certfile and self_signed_cert_dir:
            from kubernetes_autoscaler_tpu.utils.certs import CertManager

            self.cert_manager = CertManager(
                self_signed_cert_dir,
                common_name=host if host not in ("", "0.0.0.0") else "localhost",
                valid_days=cert_valid_days, rotate_before_s=rotate_before_s)
            certfile = self.cert_manager.cert_path
            keyfile = self.cert_manager.key_path

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                if self.path not in ("/mutate-pods", "/validate-vpa", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    out = json.dumps(svc.review(body)).encode()
                    code = 200
                except (ValueError, KeyError) as e:
                    out = json.dumps({"error": str(e)}).encode()
                    code = 400
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self._ssl_ctx = None
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            self.httpd.socket = ctx.wrap_socket(self.httpd.socket,
                                                server_side=True)
            self._ssl_ctx = ctx
            if self.cert_manager is not None:
                # rotations reload the serving context in place
                self.cert_manager.on_reload(
                    lambda c, k: self._ssl_ctx.load_cert_chain(c, k))
        self._thread: threading.Thread | None = None

    def rotate_certs_if_needed(self, now: float | None = None) -> bool:
        """Run periodically by the deployment loop (or a timer): regenerates
        the self-signed serving pair when it nears expiry and hot-reloads
        the TLS context. No-op for operator-provisioned certs."""
        if self.cert_manager is None:
            return False
        return self.cert_manager.ensure(now)

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
