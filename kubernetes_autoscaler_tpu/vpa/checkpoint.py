"""VPA checkpointing: persist/restore histogram state.

Reference counterpart: recommender/checkpoint/checkpoint_writer.go +
VerticalPodAutoscalerCheckpoint CRD — serialized bucket weights per
(VPA, container), maintained periodically (routines/recommender.go:154
MaintainCheckpoints) so a recommender restart keeps its history.

Serialization: one npz per recommender (bucket weights + totals + key index) —
the CRD-per-aggregate layout of the reference collapses into two dense arrays.
"""

from __future__ import annotations

import json
import os

import numpy as np

from kubernetes_autoscaler_tpu.vpa.recommender import Recommender


def save_checkpoint(rec: Recommender, path: str, now: float) -> None:
    keys = [list(k) for k, _ in sorted(rec._index.items(), key=lambda kv: kv[1])]
    np.savez_compressed(
        path,
        cpu_weights=np.asarray(rec.cpu.weights),
        cpu_total=np.asarray(rec.cpu.total),
        mem_weights=np.asarray(rec.memory.weights),
        mem_total=np.asarray(rec.memory.total),
        ref_time=np.asarray([now]),
        keys=json.dumps(keys),
    )


def load_checkpoint(path: str) -> Recommender | None:
    if not os.path.exists(path):
        return None
    import jax.numpy as jnp

    data = np.load(path, allow_pickle=False)
    keys = json.loads(str(data["keys"]))
    rec = Recommender(initial_aggregates=int(data["cpu_weights"].shape[0]))
    rec._index = {tuple(k): i for i, k in enumerate(keys)}
    rec.cpu.weights = jnp.asarray(data["cpu_weights"])
    rec.cpu.total = jnp.asarray(data["cpu_total"])
    rec.memory.weights = jnp.asarray(data["mem_weights"])
    rec.memory.total = jnp.asarray(data["mem_total"])
    rec.cpu.ref_time = rec.memory.ref_time = float(data["ref_time"][0])
    return rec
