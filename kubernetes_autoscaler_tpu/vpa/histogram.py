"""Vectorized decaying histograms: the VPA recommender's core state, on TPU.

Reference counterpart: vertical-pod-autoscaler/pkg/recommender/util/
histogram.go + decaying_histogram.go — per-container exponential-bucket
histograms with half-life time decay, one Go object per aggregate, updated
sample-by-sample. Here ALL aggregates are rows of one [A, B] weight tensor:

  * decay        — one elementwise multiply by 2^(-Δt/half_life)
  * add samples  — one segment scatter-add (bucket index math is closed-form
                   for exponential buckets, so it runs on device)
  * percentile   — cumulative sum + first-crossing argmax per row

The reference's checkpointing (VerticalPodAutoscalerCheckpoint CRD) serializes
bucket weights; vpa/checkpoint.py round-trips the same representation.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class BucketScheme:
    """Exponential buckets: bucket i covers [start*ratio^i, start*ratio^(i+1)).

    Reference defaults (model/aggregations_config.go): CPU histograms start at
    0.01 cores with 5% growth; memory at 1e7 bytes with 5% growth."""

    start: float
    ratio: float
    n_buckets: int

    def bucket_of(self, value: jnp.ndarray) -> jnp.ndarray:
        """i32 bucket indices for sample values (clamped to range)."""
        v = jnp.maximum(value, self.start)
        idx = jnp.floor(jnp.log(v / self.start) / jnp.log(self.ratio)).astype(jnp.int32)
        return jnp.clip(idx, 0, self.n_buckets - 1)

    def bucket_start(self, idx) -> jnp.ndarray:
        return self.start * self.ratio ** idx

    def boundaries(self) -> np.ndarray:
        return self.start * self.ratio ** np.arange(self.n_buckets + 1)


CPU_SCHEME = BucketScheme(start=0.01, ratio=1.05, n_buckets=176)
MEMORY_SCHEME = BucketScheme(start=1e7, ratio=1.05, n_buckets=176)

CPU_HALF_LIFE_S = 24.0 * 3600.0   # reference: DefaultCPUHistogramDecayHalfLife
MEMORY_HALF_LIFE_S = 24.0 * 3600.0


class HistogramBank(object):
    """Host handle over the [A, B] weight tensor + reference timestamps."""

    def __init__(self, n_aggregates: int, scheme: BucketScheme,
                 half_life_s: float):
        self.scheme = scheme
        self.half_life_s = half_life_s
        self.weights = jnp.zeros((n_aggregates, scheme.n_buckets), jnp.float32)
        self.total = jnp.zeros((n_aggregates,), jnp.float32)
        self.ref_time = 0.0

    def grow(self, n_aggregates: int) -> None:
        a, b = self.weights.shape
        if n_aggregates <= a:
            return
        self.weights = jnp.concatenate(
            [self.weights, jnp.zeros((n_aggregates - a, b), jnp.float32)]
        )
        self.total = jnp.concatenate(
            [self.total, jnp.zeros((n_aggregates - a,), jnp.float32)]
        )

    def decay_to(self, now: float) -> None:
        dt = now - self.ref_time
        if dt <= 0:
            return
        factor = 2.0 ** (-dt / self.half_life_s)
        self.weights = self.weights * factor
        self.total = self.total * factor
        self.ref_time = now

    def add_samples(self, agg_idx: np.ndarray, values: np.ndarray,
                    sample_weights: np.ndarray | None = None) -> None:
        """Batched sample ingestion: one scatter-add for the whole batch
        (reference: per-sample AddSample, decaying_histogram.go)."""
        if len(agg_idx) == 0:
            return
        w = (jnp.asarray(sample_weights, jnp.float32)
             if sample_weights is not None
             else jnp.ones((len(agg_idx),), jnp.float32))
        self.weights, self.total = _scatter_add(
            self.weights, self.total,
            jnp.asarray(agg_idx, jnp.int32),
            self.scheme.bucket_of(jnp.asarray(values, jnp.float32)),
            w,
        )

    def percentile(self, q: float) -> np.ndarray:
        """f32[A]: value at quantile q per aggregate (0 for empty rows).

        Matches the reference convention (histogram.go:160 Percentile): returns
        the END of the bucket where the cumulative weight crosses q."""
        return np.asarray(_percentile(
            self.weights, self.total, q,
            self.scheme.start, self.scheme.ratio,
        ))


@jax.jit
def _scatter_add(weights, total, agg_idx, bucket_idx, w):
    weights = weights.at[agg_idx, bucket_idx].add(w)
    total = total.at[agg_idx].add(w)
    return weights, total


@partial(jax.jit, static_argnames=("q", "start", "ratio"))
def _percentile(weights, total, q, start, ratio):
    cum = jnp.cumsum(weights, axis=-1)
    threshold = q * total[:, None]
    crossed = cum >= threshold - 1e-12
    first = jnp.argmax(crossed, axis=-1)
    value = start * ratio ** (first.astype(jnp.float32) + 1.0)  # bucket end
    return jnp.where(total > 0, value, 0.0)
