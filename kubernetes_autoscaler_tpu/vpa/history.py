"""Usage-history providers: warm the recommender from stored metrics.

Reference counterpart: recommender/input/history/history_provider.go — on
startup the recommender optionally replays Prometheus range queries
(container_cpu_usage_seconds_total rate / container_memory_working_set_bytes)
into the aggregate histograms so recommendations have confidence from loop
one; otherwise history accrues only from live metrics-server samples.

The Prometheus REST transport is injected (`query_fn`) — this module owns
query construction and sample conversion, the caller owns IO. A canned
`query_fn` makes the whole path testable hermetically (and keeps this image
egress-free)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Protocol

from kubernetes_autoscaler_tpu.vpa.model import ContainerUsageSample
from kubernetes_autoscaler_tpu.vpa.recommender import Recommender

# series shape: {"metric": {label: value, ...}, "values": [[ts, "value"], ...]}
QueryFn = Callable[[str, float, float], list[dict]]

CPU_QUERY = ('rate(container_cpu_usage_seconds_total'
             '{job="kubernetes-cadvisor"}[%(rate)s])')
MEMORY_QUERY = 'container_memory_working_set_bytes{job="kubernetes-cadvisor"}'


class HistoryProvider(Protocol):
    def load_into(self, rec: Recommender, now: float) -> int:
        """Replay stored usage into the recommender; returns sample count."""
        ...


@dataclass
class PrometheusHistoryProvider:
    """Builds the reference's two range queries and feeds the results.

    `pod_owner` maps a pod name to its controlling workload (the reference
    resolves this through pod labels + the aggregation key grouping)."""

    query_fn: QueryFn
    pod_owner: Callable[[str, str], str]     # (namespace, pod name) -> owner
    history_length_s: float = 8 * 24 * 3600.0
    rate_window: str = "5m"

    def load_into(self, rec: Recommender, now: float) -> int:
        start = now - self.history_length_s
        samples: list[ContainerUsageSample] = []
        for query, resource in (
            (CPU_QUERY % {"rate": self.rate_window}, "cpu"),
            (MEMORY_QUERY, "memory"),
        ):
            for series in self.query_fn(query, start, now):
                labels = series.get("metric", {})
                ns = labels.get("namespace", "default")
                pod = labels.get("pod", labels.get("pod_name", ""))
                container = labels.get("container", labels.get("container_name", ""))
                if not pod or not container or container == "POD":
                    continue
                owner = self.pod_owner(ns, pod)
                for ts, val in series.get("values", []):
                    v = float(val)
                    samples.append(ContainerUsageSample(
                        namespace=ns, pod_name=pod, owner_name=owner,
                        container_name=container,
                        cpu_cores=v if resource == "cpu" else None,
                        memory_bytes=v if resource == "memory" else None,
                        timestamp=float(ts),
                    ))
        # One batched, age-weighted ingestion across ALL series and both
        # resources: exact w.r.t. per-timestamp sequential feeding, and a
        # single scatter-add per resource instead of a dispatch per sample.
        rec.feed_history(samples, now=now)
        return len(samples)
