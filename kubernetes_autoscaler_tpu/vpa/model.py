"""VPA object model: the VerticalPodAutoscaler CRD surface.

Reference counterpart: vertical-pod-autoscaler/pkg/apis/autoscaling.k8s.io/v1
types — VPA spec (target ref, update policy, per-container resource policy)
and status (recommendation with target/lower/upper/uncapped bounds).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum


class UpdateMode(Enum):
    OFF = "Off"
    INITIAL = "Initial"
    RECREATE = "Recreate"
    AUTO = "Auto"
    IN_PLACE_OR_RECREATE = "InPlaceOrRecreate"


@dataclass
class ContainerResourcePolicy:
    container_name: str = "*"
    mode: str = "Auto"                    # Auto | Off
    min_allowed: dict[str, float] = field(default_factory=dict)   # cpu cores, memory bytes
    max_allowed: dict[str, float] = field(default_factory=dict)
    controlled_values: str = "RequestsAndLimits"


@dataclass
class RecommendedContainerResources:
    container_name: str
    target: dict[str, float] = field(default_factory=dict)
    lower_bound: dict[str, float] = field(default_factory=dict)
    upper_bound: dict[str, float] = field(default_factory=dict)
    uncapped_target: dict[str, float] = field(default_factory=dict)


@dataclass
class VerticalPodAutoscaler:
    name: str
    namespace: str = "default"
    target_kind: str = "Deployment"
    target_name: str = ""
    update_mode: UpdateMode = UpdateMode.AUTO
    min_replicas: int = 2
    resource_policies: list[ContainerResourcePolicy] = field(default_factory=list)
    recommendation: list[RecommendedContainerResources] = field(default_factory=list)

    def policy_for(self, container: str) -> ContainerResourcePolicy:
        star = ContainerResourcePolicy()
        for p in self.resource_policies:
            if p.container_name == container:
                return p
            if p.container_name == "*":
                star = p
        return star


@dataclass
class ContainerUsageSample:
    """One metrics observation (reference: model.ContainerUsageSample)."""

    namespace: str
    pod_name: str
    container_name: str
    owner_name: str              # controller identity (aggregation key part)
    cpu_cores: float | None = None
    memory_bytes: float | None = None
    is_oom: bool = False
    timestamp: float = 0.0
