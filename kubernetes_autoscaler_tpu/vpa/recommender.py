"""VPA recommender: usage histories → resource recommendations.

Reference counterpart: vertical-pod-autoscaler/pkg/recommender/ —
ClusterStateFeeder ingests pods/VPAs/metrics (input/cluster_feeder.go), each
(controller, container) gets an AggregateContainerState with decaying
histograms, and percentile estimators produce target/lower/upper
recommendations (logic/recommender.go:32-38: target=P90, lower=P50, upper=P95,
×(1+15% margin), floored by min-resources), written to VPA.Status.

TPU re-design: all aggregates' histograms live in two [A, B] tensors
(vpa/histogram.py); decay, sample ingestion and ALL percentile estimations are
three device calls per RunOnce regardless of aggregate count — the reference
iterates Go objects per container.

OOM handling mirrors cluster_feeder's OOM observation: an OOM bumps the memory
sample to max(usage, current-request) × safety margin.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from kubernetes_autoscaler_tpu.vpa.histogram import (
    CPU_HALF_LIFE_S,
    CPU_SCHEME,
    MEMORY_HALF_LIFE_S,
    MEMORY_SCHEME,
    HistogramBank,
)
from kubernetes_autoscaler_tpu.vpa.model import (
    ContainerUsageSample,
    RecommendedContainerResources,
    VerticalPodAutoscaler,
)

# reference: logic/recommender.go percentile/margin constants
TARGET_CPU_PERCENTILE = 0.9
LOWER_BOUND_PERCENTILE = 0.5
UPPER_BOUND_PERCENTILE = 0.95
TARGET_MEMORY_PEAK_PERCENTILE = 0.9
SAFETY_MARGIN = 1.15
MIN_CPU_CORES = 0.025           # reference: pod_min_cpu_millicores=25
MIN_MEMORY_BYTES = 250e6        # reference: pod_min_memory_mb=250
OOM_BUMP_RATIO = 1.2            # reference: model.OOMBumpUpRatio


@dataclass
class AggregateKey:
    namespace: str
    owner_name: str
    container_name: str

    def id(self) -> tuple:
        return (self.namespace, self.owner_name, self.container_name)


@dataclass
class Recommender:
    initial_aggregates: int = 64
    _index: dict[tuple, int] = field(default_factory=dict)

    def __post_init__(self):
        self.cpu = HistogramBank(self.initial_aggregates, CPU_SCHEME, CPU_HALF_LIFE_S)
        self.memory = HistogramBank(self.initial_aggregates, MEMORY_SCHEME,
                                    MEMORY_HALF_LIFE_S)
        self.first_sample_time: dict[tuple, float] = {}
        self.sample_counts: dict[tuple, int] = {}

    # ---- feeding (reference: ClusterStateFeeder.LoadRealTimeMetrics) ----

    def _row(self, key: AggregateKey) -> int:
        kid = key.id()
        if kid not in self._index:
            self._index[kid] = len(self._index)
            if len(self._index) > self.cpu.weights.shape[0]:
                self.cpu.grow(2 * len(self._index))
                self.memory.grow(2 * len(self._index))
        return self._index[kid]

    def feed(self, samples: list[ContainerUsageSample],
             now: float | None = None) -> None:
        now = time.time() if now is None else now
        self.cpu.decay_to(now)
        self.memory.decay_to(now)
        cpu_rows, cpu_vals = [], []
        mem_rows, mem_vals = [], []
        for s in samples:
            key = AggregateKey(s.namespace, s.owner_name, s.container_name)
            row = self._row(key)
            kid = key.id()
            self.first_sample_time.setdefault(kid, s.timestamp or now)
            if s.cpu_cores is not None:
                # confidence counts CPU samples only (reference getConfidence
                # — otherwise cpu+memory datapoints double the sample rate)
                self.sample_counts[kid] = self.sample_counts.get(kid, 0) + 1
                cpu_rows.append(row)
                cpu_vals.append(s.cpu_cores)
            if s.memory_bytes is not None:
                mem_rows.append(row)
                val = s.memory_bytes
                if s.is_oom:
                    val *= OOM_BUMP_RATIO
                mem_vals.append(val)
        # CPU sample weight = max(usage, 0.1) per reference CPU weighting
        # (aggregate_container_state.go: weight by usage); memory weight 1.
        if cpu_rows:
            w = np.maximum(np.asarray(cpu_vals, np.float32), 0.1)
            self.cpu.add_samples(np.asarray(cpu_rows), np.asarray(cpu_vals), w)
        if mem_rows:
            self.memory.add_samples(np.asarray(mem_rows), np.asarray(mem_vals))

    def feed_history(self, samples: list[ContainerUsageSample],
                     now: float) -> None:
        """Batched historical ingestion: mathematically identical to feeding
        each sample at its own timestamp (decay is exponential, so a sample
        aged `now - t` simply carries weight x 2^(-(age)/half_life)), but the
        whole history lands in ONE scatter-add per resource instead of a
        device dispatch pair per sample."""
        self.cpu.decay_to(now)
        self.memory.decay_to(now)
        cpu_rows, cpu_vals, cpu_w = [], [], []
        mem_rows, mem_vals, mem_w = [], [], []
        for s in samples:
            key = AggregateKey(s.namespace, s.owner_name, s.container_name)
            row = self._row(key)
            kid = key.id()
            t = s.timestamp or now
            prev = self.first_sample_time.get(kid)
            if prev is None or t < prev:
                self.first_sample_time[kid] = t
            if s.cpu_cores is not None:
                # CPU samples only, matching feed() (see note there)
                self.sample_counts[kid] = self.sample_counts.get(kid, 0) + 1
            age = max(now - t, 0.0)
            if s.cpu_cores is not None:
                cpu_rows.append(row)
                cpu_vals.append(s.cpu_cores)
                cpu_w.append(max(s.cpu_cores, 0.1)
                             * 2.0 ** (-age / self.cpu.half_life_s))
            if s.memory_bytes is not None:
                mem_rows.append(row)
                val = s.memory_bytes
                if s.is_oom:
                    val *= OOM_BUMP_RATIO
                mem_vals.append(val)
                mem_w.append(2.0 ** (-age / self.memory.half_life_s))
        if cpu_rows:
            self.cpu.add_samples(np.asarray(cpu_rows), np.asarray(cpu_vals),
                                 np.asarray(cpu_w, np.float32))
        if mem_rows:
            self.memory.add_samples(np.asarray(mem_rows), np.asarray(mem_vals),
                                    np.asarray(mem_w, np.float32))

    # ---- estimation (reference: logic/recommender.go RecommendedPodResources) ----

    def _confidence_days(self, kid: tuple, now: float) -> float:
        """History confidence in days (reference: logic/estimator.go
        getConfidence — min of lifespan-days and samples-per-minute-days)."""
        first = self.first_sample_time.get(kid, now)
        life_days = max(now - first, 0.0) / 86400.0
        sample_days = self.sample_counts.get(kid, 0) / (60.0 * 24.0)
        return min(life_days, sample_days)

    @staticmethod
    def _confidence_scale(value: float, conf: float, multiplier: float,
                          exponent: float) -> float:
        """reference: confidenceMultiplier — value x (1 + m/conf)^e; with no
        history the bounds blow wide open (upper) / collapse (lower)."""
        if conf <= 0:
            return value * (1e9 if exponent > 0 else 0.0)
        return value * (1.0 + multiplier / conf) ** exponent

    def recommend(self, vpas: list[VerticalPodAutoscaler],
                  containers_by_target: dict[str, list[str]],
                  now: float | None = None) -> None:
        """Fill VPA.recommendation for every VPA; all percentiles computed in
        six device reductions total (3 quantiles × 2 resources)."""
        now = time.time() if now is None else now
        cpu_p50 = self.cpu.percentile(LOWER_BOUND_PERCENTILE)
        cpu_p90 = self.cpu.percentile(TARGET_CPU_PERCENTILE)
        cpu_p95 = self.cpu.percentile(UPPER_BOUND_PERCENTILE)
        mem_p50 = self.memory.percentile(LOWER_BOUND_PERCENTILE)
        mem_p90 = self.memory.percentile(TARGET_MEMORY_PEAK_PERCENTILE)
        mem_p95 = self.memory.percentile(UPPER_BOUND_PERCENTILE)

        for vpa in vpas:
            recs = []
            for container in containers_by_target.get(vpa.target_name, []):
                kid = (vpa.namespace, vpa.target_name, container)
                row = self._index.get(kid)
                if row is None:
                    continue
                policy = vpa.policy_for(container)
                if policy.mode == "Off":
                    continue

                def capped(cpu, mem):
                    cpu = max(cpu * SAFETY_MARGIN, MIN_CPU_CORES)
                    mem = max(mem * SAFETY_MARGIN, MIN_MEMORY_BYTES)
                    lo_c = policy.min_allowed.get("cpu")
                    hi_c = policy.max_allowed.get("cpu")
                    lo_m = policy.min_allowed.get("memory")
                    hi_m = policy.max_allowed.get("memory")
                    if lo_c is not None:
                        cpu = max(cpu, lo_c)
                    if hi_c is not None:
                        cpu = min(cpu, hi_c)
                    if lo_m is not None:
                        mem = max(mem, lo_m)
                    if hi_m is not None:
                        mem = min(mem, hi_m)
                    return {"cpu": cpu, "memory": mem}

                uncapped = {
                    "cpu": float(cpu_p90[row]) * SAFETY_MARGIN,
                    "memory": float(mem_p90[row]) * SAFETY_MARGIN,
                }
                # Confidence scaling (reference: WithConfidenceMultiplier —
                # lower bound x (1+0.001/conf)^-2, upper bound x (1+1/conf)^1):
                # young aggregates get a wide [lower, upper] band so the
                # updater doesn't churn pods on thin evidence.
                conf = self._confidence_days(kid, now)
                lo_cpu = self._confidence_scale(float(cpu_p50[row]), conf, 0.001, -2.0)
                lo_mem = self._confidence_scale(float(mem_p50[row]), conf, 0.001, -2.0)
                hi_cpu = self._confidence_scale(float(cpu_p95[row]), conf, 1.0, 1.0)
                hi_mem = self._confidence_scale(float(mem_p95[row]), conf, 1.0, 1.0)
                recs.append(RecommendedContainerResources(
                    container_name=container,
                    target=capped(float(cpu_p90[row]), float(mem_p90[row])),
                    lower_bound=capped(lo_cpu, lo_mem),
                    upper_bound=capped(hi_cpu, hi_mem),
                    uncapped_target=uncapped,
                ))
            vpa.recommendation = recs
