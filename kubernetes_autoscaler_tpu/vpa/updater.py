"""VPA updater: act on recommendations by evicting / in-place resizing pods.

Reference counterpart: vertical-pod-autoscaler/pkg/updater/logic/updater.go
(:159 RunOnce): find pods whose requests fall outside the recommendation's
[lower, upper] band (priority/update_priority_calculator.go), respect PDBs and
min-replicas, rate-limit evictions per replica set, evict (or in-place resize
when InPlaceOrRecreate and the kubelet supports it).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

from kubernetes_autoscaler_tpu.vpa.model import UpdateMode, VerticalPodAutoscaler

# reference: priority/update_priority_calculator.go defaults
DEFAULT_SIGNIFICANT_CHANGE = 0.10     # 10% divergence triggers an update
POD_LIFETIME_MIN_S = 12 * 3600.0      # pods younger than this update only if outside bounds


@dataclass
class PodView:
    """What the updater needs to know about one pod."""

    name: str
    namespace: str
    owner_name: str
    containers: dict[str, dict[str, float]]   # container -> {cpu: cores, memory: bytes}
    start_time: float = 0.0
    replicas_of_owner: int = 1


@dataclass
class UpdateDecision:
    pod: PodView
    priority: float
    outside_bounds: bool
    in_place: bool = False


class Updater:
    def __init__(
        self,
        evict: Callable[[PodView], None],
        in_place_resize: Callable[[PodView, dict], bool] | None = None,
        eviction_rate_limit_per_loop: int = 10,
        can_evict: Callable[[PodView], bool] | None = None,
    ):
        self.evict = evict
        self.in_place_resize = in_place_resize
        self.eviction_rate_limit = eviction_rate_limit_per_loop
        # PDB gate (reference: eviction/pods_eviction_restriction.go — the
        # updater consults PodDisruptionBudgets before every eviction); the
        # callback owns the budget bookkeeping so repeated evictions of one
        # controller's pods draw down the same allowance.
        self.can_evict = can_evict

    def run_once(
        self,
        vpas: list[VerticalPodAutoscaler],
        pods: list[PodView],
        now: float | None = None,
    ) -> list[UpdateDecision]:
        now = time.time() if now is None else now
        decisions: list[UpdateDecision] = []
        by_target: dict[tuple, VerticalPodAutoscaler] = {
            (v.namespace, v.target_name): v for v in vpas
        }
        for pod in pods:
            vpa = by_target.get((pod.namespace, pod.owner_name))
            if vpa is None or vpa.update_mode in (UpdateMode.OFF, UpdateMode.INITIAL):
                continue
            if not vpa.recommendation:
                continue
            if pod.replicas_of_owner < vpa.min_replicas:
                continue  # reference: too few replicas to evict safely
            d = self._priority(pod, vpa, now)
            if d is not None:
                decisions.append(d)

        # highest priority first (reference: priority sorting)
        decisions.sort(key=lambda d: -d.priority)
        acted: list[UpdateDecision] = []
        budget = self.eviction_rate_limit
        for d in decisions:
            if budget <= 0:
                break
            if d.in_place and self.in_place_resize is not None:
                targets = {
                    r.container_name: r.target
                    for r in by_target[(d.pod.namespace, d.pod.owner_name)].recommendation
                }
                if self.in_place_resize(d.pod, targets):
                    acted.append(d)
                    continue  # no eviction needed
            if self.can_evict is not None and not self.can_evict(d.pod):
                continue  # PDB exhausted for this pod's controller
            self.evict(d.pod)
            acted.append(d)
            budget -= 1
        return acted

    def _priority(self, pod: PodView, vpa: VerticalPodAutoscaler,
                  now: float) -> UpdateDecision | None:
        """reference: update_priority_calculator.go — resource diff magnitude;
        pods outside [lower, upper] always update, in-band pods only when the
        change is significant and the pod is old enough."""
        outside = False
        total_diff = 0.0
        matched = False
        for rec in vpa.recommendation:
            current = pod.containers.get(rec.container_name)
            if current is None:
                continue
            matched = True
            for res in ("cpu", "memory"):
                cur = current.get(res, 0.0)
                tgt = rec.target.get(res, 0.0)
                lo = rec.lower_bound.get(res, 0.0)
                hi = rec.upper_bound.get(res, float("inf"))
                if cur < lo or cur > hi:
                    outside = True
                if cur > 0:
                    total_diff += abs(tgt - cur) / cur
        if not matched:
            return None
        significant = total_diff >= DEFAULT_SIGNIFICANT_CHANGE
        old_enough = now - pod.start_time >= POD_LIFETIME_MIN_S
        if not outside and not (significant and old_enough):
            return None
        in_place = vpa.update_mode is UpdateMode.IN_PLACE_OR_RECREATE
        return UpdateDecision(pod, total_diff + (10.0 if outside else 0.0),
                              outside, in_place)
