"""Counterfactual multiverse — vmapped what-if batches and device-resident
time-compressed rollouts (docs/WHATIF.md).

PR 7 vmaps *tenants* and the fused loop (docs/FUSED_LOOP.md) collapses the
whole RunOnce into one device program; this package vmaps *hypotheses*: a
leading lane axis B of perturbed worlds + per-lane policy scalars over the
same `ops/autoscale_step.run_once_fused` body, plus a `lax.scan` rollout
that advances the resident planes through T simulated loops entirely
on-device. Lane b=0 is always the null hypothesis — the unperturbed branch
world — and stays bit-identical to the live fused loop by construction
(tests/test_whatif.py pins this).
"""

from kubernetes_autoscaler_tpu.whatif.kernel import (  # noqa: F401
    LaneSummary,
    RolloutStep,
    multiverse_step,
    rollout_fused,
    rollout_multiverse,
)
from kubernetes_autoscaler_tpu.whatif.variants import (  # noqa: F401
    Branch,
    Lanes,
    VariantSpec,
    branch_from_journal,
    branch_from_live,
    build_lanes,
)
from kubernetes_autoscaler_tpu.whatif.generator import (  # noqa: F401
    WorkloadSpec,
    generate_workload,
)
from kubernetes_autoscaler_tpu.whatif.report import (  # noqa: F401
    build_report,
    lane_digests,
)
