import sys

from kubernetes_autoscaler_tpu.whatif.cli import main

sys.exit(main())
