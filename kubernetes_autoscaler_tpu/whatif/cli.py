"""`python -m kubernetes_autoscaler_tpu.whatif` — the what-if CLI.

Branch from a journal cursor (--journal/--upto) or a seeded synthetic world
(default), fan out variant lanes, run the multiverse step and optionally a
time-compressed rollout, and print the JSON report (docs/WHATIF.md).

Examples:
  python -m kubernetes_autoscaler_tpu.whatif --synthetic --rollout 32 \\
      --workload diurnal --variants '[{"price_scale": 2.0}, \\
      {"threshold": 0.8, "name": "aggressive-drain"}]'
  python -m kubernetes_autoscaler_tpu.whatif --journal /var/log/ka.journal \\
      --upto 120 --variants '[{"max_new_cap": 4}]'
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="python -m kubernetes_autoscaler_tpu.whatif",
        description="Counterfactual multiverse: batched what-if evaluation "
                    "over a branched autoscaler world.")
    src = p.add_mutually_exclusive_group()
    src.add_argument("--journal", help="branch from this journal file")
    src.add_argument("--synthetic", action="store_true",
                     help="branch from a seeded synthetic world (default)")
    p.add_argument("--upto", type=int, default=None,
                   help="journal loop cursor to branch at (default: last)")
    p.add_argument("--variants", default="[]",
                   help="JSON list of variant dicts (price_scale, "
                        "max_new_cap, threshold, fail_nodes, pending_scale,"
                        " name); lane 0 null hypothesis is always prepended")
    p.add_argument("--rollout", type=int, default=0, metavar="T",
                   help="time-compressed rollout over T simulated loops "
                        "(0 = single multiverse step)")
    p.add_argument("--workload", default="quiet",
                   help="rollout workload kind: quiet|diurnal|bursty|spot")
    p.add_argument("--workload-seed", type=int, default=0)
    p.add_argument("--base-rate", type=float, default=2.0)
    p.add_argument("--strategy", default="least-waste")
    p.add_argument("--seed", type=int, default=0,
                   help="synthetic world seed")
    p.add_argument("--nodes", type=int, default=8)
    p.add_argument("--pending", type=int, default=6)
    p.add_argument("--out", default="-",
                   help="report path ('-' = stdout)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])

    from kubernetes_autoscaler_tpu.whatif import kernel, report, variants
    from kubernetes_autoscaler_tpu.whatif.generator import (
        WorkloadSpec,
        generate_workload,
        lane_workloads,
    )

    specs = [variants.VariantSpec.from_dict(d)
             for d in json.loads(args.variants)]
    if args.journal:
        branch = variants.branch_from_journal(args.journal, upto=args.upto)
    else:
        from kubernetes_autoscaler_tpu.whatif.synthetic import (
            synthetic_branch,
        )

        branch, _a = synthetic_branch(n_nodes=args.nodes,
                                      n_pending=args.pending,
                                      seed=args.seed)
    from kubernetes_autoscaler_tpu.sidecar.shapes import rung

    want = len(specs) + (0 if specs and specs[0].is_null() else 1)
    lanes = variants.build_lanes(branch, specs, pad_to=rung(want, 4))
    st = lanes.statics
    kw = dict(dims=st["dims"], max_new_nodes=st["max_new_nodes"],
              max_pods_per_node=st["max_pods_per_node"], chunk=st["chunk"],
              strategy=args.strategy)

    decision, summary = kernel.multiverse_step(
        lanes.nodes, lanes.specs, lanes.scheduled, lanes.groups,
        lanes.limit_cap, **kw)
    traj = wl = None
    if args.rollout > 0:
        import numpy as np

        wl = WorkloadSpec(kind=args.workload, seed=args.workload_seed,
                          base_rate=args.base_rate)
        g = int(np.asarray(lanes.specs.count).shape[1])
        n = int(np.asarray(lanes.nodes.valid).shape[1])
        adds, fails = generate_workload(wl, args.rollout, g, n)
        adds_b, fails_b = lane_workloads(lanes.variants, adds, fails)
        traj = kernel.rollout_multiverse(
            lanes.nodes, lanes.specs, lanes.scheduled, lanes.groups,
            lanes.limit_cap, lanes.thresholds, adds_b, fails_b, **kw)

    rep = report.build_report(lanes, summary=summary, decision=decision,
                              traj=traj, workload=wl)
    text = json.dumps(rep, indent=2, sort_keys=True)
    if args.out == "-":
        print(text)
    else:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"whatif report: {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
