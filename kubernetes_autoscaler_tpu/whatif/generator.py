"""Seeded synthetic workload generator for rollouts — kwok-style scenario
traffic (SURVEY §5) as plain arrays.

A `WorkloadSpec` is a value object: (kind, seed, knobs) fully determines the
generated trace, byte for byte, forever — `generate_workload` uses a
dedicated `np.random.RandomState(seed)` and no ambient entropy. That makes
traces journal-recordable: the spec's `to_record()` dict rides a journal's
loop annotations or a what-if report, and replaying it through
`from_record` + `generate_workload` reproduces the exact trace the original
rollout consumed.

Patterns:
- `quiet`   — all zeros (the null workload; steady-state identity runs)
- `diurnal` — sinusoidal arrival rate around `base_rate` with `amplitude`,
              period `period_steps`, Poisson-sampled per (step, group)
- `bursty`  — quiet baseline + Bernoulli(`burst_prob`) bursts of
              `burst_size` pods landing on one random group
- `spot`    — diurnal arrivals + Bernoulli(`reclaim_prob`) per-step spot
              reclaims of `reclaim_nodes` random live nodes
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

KINDS = ("quiet", "diurnal", "bursty", "spot")


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    kind: str = "quiet"
    seed: int = 0
    base_rate: float = 2.0      # mean pod arrivals per step per group
    amplitude: float = 1.0      # diurnal swing as a fraction of base_rate
    period_steps: int = 24      # steps per diurnal cycle
    burst_prob: float = 0.1     # per-step burst probability (bursty)
    burst_size: int = 16        # pods per burst
    reclaim_prob: float = 0.05  # per-step spot-reclaim probability
    reclaim_nodes: int = 1      # nodes reclaimed per event

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown workload kind {self.kind!r}; "
                             f"one of {KINDS}")

    def to_record(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["v"] = 1
        return d

    @classmethod
    def from_record(cls, d: dict[str, Any]) -> "WorkloadSpec":
        d = {k: v for k, v in d.items() if k != "v"}
        return cls(**d)


def generate_workload(spec: WorkloadSpec, t_steps: int, n_groups: int,
                      n_nodes: int) -> tuple[np.ndarray, np.ndarray]:
    """Returns (adds i32[T, G], fails bool[T, N]) for a rollout."""
    rng = np.random.RandomState(np.uint32(spec.seed))
    adds = np.zeros((t_steps, n_groups), np.int32)
    fails = np.zeros((t_steps, n_nodes), bool)
    if spec.kind == "quiet" or n_groups == 0:
        return adds, fails

    steps = np.arange(t_steps, dtype=np.float64)
    if spec.kind in ("diurnal", "spot"):
        period = max(spec.period_steps, 1)
        rate = spec.base_rate * (
            1.0 + spec.amplitude * np.sin(2.0 * np.pi * steps / period))
        rate = np.maximum(rate, 0.0)
        adds = rng.poisson(
            rate[:, None], size=(t_steps, n_groups)).astype(np.int32)
    if spec.kind == "bursty":
        hit = rng.random_sample(t_steps) < spec.burst_prob
        tgt = rng.randint(0, n_groups, size=t_steps)
        adds[hit, tgt[hit]] += np.int32(spec.burst_size)
    if spec.kind == "spot" and n_nodes > 0:
        hit = rng.random_sample(t_steps) < spec.reclaim_prob
        for t in np.nonzero(hit)[0]:
            victims = rng.choice(
                n_nodes, size=min(spec.reclaim_nodes, n_nodes),
                replace=False)
            fails[t, victims] = True
    return adds, fails


def lane_workloads(variants, adds: np.ndarray,
                   fails: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Fan one generated trace out to B lanes, applying each variant's
    `pending_scale`. Lanes with the default scale broadcast the trace
    bitwise untouched (the null lane's trace is THE trace)."""
    b = len(variants)
    adds_b = np.broadcast_to(adds[None], (b,) + adds.shape).copy()
    fails_b = np.broadcast_to(fails[None], (b,) + fails.shape).copy()
    for i, v in enumerate(variants):
        if v.pending_scale != 1.0:
            adds_b[i] = np.ceil(
                adds * np.float64(v.pending_scale)).astype(np.int32)
    return adds_b, fails_b
