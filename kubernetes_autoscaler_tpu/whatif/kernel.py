"""The multiverse kernels: the fused RunOnce body vmapped over a leading
hypothesis axis B, and `lax.scan` time-compressed rollouts over T loops.

Both kernels reuse `run_once_fused.__wrapped__` verbatim — lane arithmetic
is the live loop's arithmetic, so the null-hypothesis lane (b=0, the
unperturbed branch world) produces bit-identical decision planes to a live
fused dispatch on the same world. Per-lane policy knobs ride as TRACED
arrays (limit_cap i32[B, NG], thresholds f32[B], per-lane prices inside the
batched group tensors), so B variant lanes and any knob churn share ONE
compiled program per (shape-class, T) — the same no-fragmentation contract
the tenant batcher pins (docs/SERVING.md).

The rollout applies a *compressed actuation* inside the scan — placement is
the fused filter's exact arithmetic and the placed pods BIND (the carry is
the post-placement world, unlike the live loop where a real scheduler binds
asynchronously); scale-up materializes the winning option's template rows
into invalid node slots; scale-down retires empty drainable nodes below the
lane's utilization threshold — so the host sees only the compact per-step
decision trajectory (O(T·G)), never the worlds. Because every actuation is
a masked select, a world in equilibrium with its own decisions (nothing
placeable, nothing drainable) carries BITWISE unchanged — that is the
null-lane trajectory identity `bench.py --whatif` pins against T live
loops. Single-step identity (multiverse_step lane b ≡ serial
run_once_fused) holds unconditionally on ANY world.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from kubernetes_autoscaler_tpu.models.cluster_state import (
    Dims,
    NodeGroupTensors,
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
)
from kubernetes_autoscaler_tpu.ops import scoring
from kubernetes_autoscaler_tpu.ops.autoscale_step import (
    FusedDecision,
    run_once_fused,
)


class LaneSummary(struct.PyTreeNode):
    """Per-lane scalars reduced ON DEVICE so the multiverse fetch stays
    O(B) + the decision planes — cost / utilization / disruption are the
    deltas the what-if consumer ranks lanes by (report.py subtracts the
    null lane on host)."""

    scaleup_cost: jax.Array   # f32 price of the winning expansion option
    fleet_price: jax.Array    # f32 Σ price_per_node over live grouped nodes
    utilization: jax.Array    # f32 mean post-placement util over valid nodes
    disruption: jax.Array     # i32 drainable (evictable) node count
    pending: jax.Array        # i32 pods still pending after the filter pass
    nodes_added: jax.Array    # i32 node count of the winning option
    best: jax.Array           # i32 winning node-group index (-1 = none)


class RolloutStep(struct.PyTreeNode):
    """One step of the host-visible decision trajectory — the ONLY thing a
    rollout fetches (the worlds stay device-resident inside the scan)."""

    verdict: jax.Array        # i32[G] filter placements (live-loop surface)
    pending_after: jax.Array  # i32[G] pods pending after the filter
    best: jax.Array           # i32 winning node-group index (-1 = none)
    nodes_added: jax.Array    # i32 nodes materialized this step
    nodes_removed: jax.Array  # i32 empty drainable nodes retired this step
    util_mean: jax.Array      # f32 mean utilization over valid nodes
    scaleup_cost: jax.Array   # f32 price of this step's expansion
    fleet_price: jax.Array    # f32 post-actuation fleet price rate


def _summarize(dec: FusedDecision, nodes: NodeTensors,
               groups: NodeGroupTensors, strategy: str) -> LaneSummary:
    best = scoring.best_option(dec.scores, strategy)
    b = jnp.maximum(best, 0)
    n_add = jnp.where(best >= 0, dec.est_node_count[b], 0)
    price = groups.price_per_node
    cost = jnp.where(best >= 0, price[b] * n_add.astype(jnp.float32), 0.0)
    nvalid = nodes.valid.sum()
    util = jnp.where(
        nvalid > 0,
        (dec.util * nodes.valid).sum() / jnp.maximum(nvalid, 1), 0.0)
    gid = jnp.maximum(nodes.group_id, 0)
    fleet = jnp.where(nodes.valid & (nodes.group_id >= 0),
                      price[gid], 0.0).sum()
    disruption = (dec.drainable & ~dec.has_blocker & nodes.valid).sum()
    return LaneSummary(
        scaleup_cost=cost,
        fleet_price=fleet,
        utilization=util,
        disruption=disruption.astype(jnp.int32),
        pending=dec.pending_after.sum().astype(jnp.int32),
        nodes_added=n_add.astype(jnp.int32),
        best=best,
    )


@partial(jax.jit, static_argnames=("dims", "max_new_nodes",
                                   "max_pods_per_node", "chunk", "strategy"))
def _multiverse_step_jit(
    nodes: NodeTensors,              # leading axis B on every tensor input
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    groups: NodeGroupTensors,
    limit_cap: jax.Array,            # i32[B, NG] per-lane composed cap
    dims: Dims,
    max_new_nodes: int,
    max_pods_per_node: int,
    chunk: int,
    strategy: str,
) -> tuple[FusedDecision, LaneSummary]:
    def one(nt, pt, st, gt, cap):
        dec, _res = run_once_fused.__wrapped__(
            nt, pt, st, gt, cap, dims, max_new_nodes,
            max_pods_per_node, chunk, None, False)
        return dec, _summarize(dec, _res.nodes, gt, strategy)

    return jax.vmap(one)(nodes, specs, scheduled, groups, limit_cap)


def multiverse_step(
    nodes: NodeTensors,              # leading axis B on every tensor input
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    groups: NodeGroupTensors,
    limit_cap: jax.Array,            # i32[B, NG] per-lane composed cap
    dims: Dims,
    max_new_nodes: int = 256,
    max_pods_per_node: int = 128,
    chunk: int = 32,
    strategy: str = "least-waste",
) -> tuple[FusedDecision, LaneSummary]:
    """One fused RunOnce step over B hypothesis lanes.

    Returns the FULL batched decision planes (verdict / options / drain —
    every leaf gains axis 0 of size B) plus the on-device LaneSummary
    reduction, fetched together as one batched transfer. Lane b is
    bit-identical to a serial `run_once_fused` call on lane b's world —
    vmap is a dispatch-shape change only, exactly the PR 7 contract.

    The per-lane body is the single-device unconstrained path (planes=None):
    constraint-overlay worlds take the serial fused dispatch instead, same
    split the tenant batcher makes.

    Plain-function wrapper: jax's jit cache keys distinguish a kwarg left
    at its default from the same value passed explicitly, so two callers
    with different calling conventions would silently pay two compiles of
    the same program. The wrapper always forwards every static explicitly."""
    return _multiverse_step_jit(nodes, specs, scheduled, groups, limit_cap,
                                dims=dims, max_new_nodes=max_new_nodes,
                                max_pods_per_node=max_pods_per_node,
                                chunk=chunk, strategy=strategy)


multiverse_step._cache_size = _multiverse_step_jit._cache_size


def _actuate(nodes2: NodeTensors, dec: FusedDecision, tmpl: NodeTensors,
             groups: NodeGroupTensors, threshold: jax.Array, strategy: str):
    """Compressed actuation on the post-placement resident nodes: graft the
    winning option's template rows into invalid slots, retire empty
    drainable nodes under the lane threshold. Every branch is a masked
    select over fixed shapes — a no-op decision (best == -1, nothing
    drainable) leaves the planes BITWISE unchanged, which is what keeps the
    null lane's steady-state trajectory byte-identical to the live loop."""
    best = scoring.best_option(dec.scores, strategy)
    b = jnp.maximum(best, 0)
    n_add = jnp.where(best >= 0, dec.est_node_count[b], 0)
    inv = ~nodes2.valid
    rank = jnp.cumsum(inv.astype(jnp.int32)) * inv.astype(jnp.int32)
    take = inv & (rank > 0) & (rank <= n_add)

    def graft(cur, rows):
        row = rows[b]
        mask = take.reshape(take.shape + (1,) * (cur.ndim - 1))
        return jnp.where(mask, row, cur)

    nodes3 = NodeTensors(
        cap=graft(nodes2.cap, tmpl.cap),
        alloc=graft(nodes2.alloc, tmpl.alloc),
        label_hash=graft(nodes2.label_hash, tmpl.label_hash),
        taint_exact=graft(nodes2.taint_exact, tmpl.taint_exact),
        taint_key=graft(nodes2.taint_key, tmpl.taint_key),
        used_ports=graft(nodes2.used_ports, tmpl.used_ports),
        zone_id=graft(nodes2.zone_id, tmpl.zone_id),
        group_id=jnp.where(take, b.astype(jnp.int32), nodes2.group_id),
        ready=nodes2.ready | take,
        schedulable=nodes2.schedulable | take,
        valid=nodes2.valid | take,
    )
    # retire: drainable, unblocked, below the lane threshold AND empty —
    # the compressed policy never migrates residents, so only pod-free
    # nodes leave the world (the drain verdicts of freshly-grafted rows
    # are last step's sweep of an invalid slot: exclude them)
    empty = nodes3.alloc.sum(axis=1) == 0
    remove = (nodes3.valid & dec.drainable & ~dec.has_blocker
              & (dec.util < threshold) & empty & ~take)
    nodes4 = nodes3.replace(
        ready=nodes3.ready & ~remove,
        schedulable=nodes3.schedulable & ~remove,
        valid=nodes3.valid & ~remove,
    )
    price = groups.price_per_node
    cost = jnp.where(best >= 0, price[b] * n_add.astype(jnp.float32), 0.0)
    gid = jnp.maximum(nodes4.group_id, 0)
    fleet = jnp.where(nodes4.valid & (nodes4.group_id >= 0),
                      price[gid], 0.0).sum()
    return nodes4, best, take.sum(), remove.sum(), cost, fleet


def _rollout_body(nodes, specs, scheduled, groups, limit_cap, threshold,
                  adds, fails, dims, max_new_nodes, max_pods_per_node,
                  chunk, strategy):
    tmpl = groups.as_node_tensors(dims)

    def step(carry, xs):
        nodes_c, specs_c = carry
        add_t, fail_t = xs
        # workload injection for this simulated loop: pending-pod arrivals
        # (negative = completions) and spot reclaims / failures
        specs_c = specs_c.replace(
            count=jnp.maximum(specs_c.count + add_t, 0))
        nodes_c = nodes_c.replace(
            ready=nodes_c.ready & ~fail_t,
            schedulable=nodes_c.schedulable & ~fail_t)
        dec, res = run_once_fused.__wrapped__(
            nodes_c, specs_c, scheduled, groups, limit_cap, dims,
            max_new_nodes, max_pods_per_node, chunk, None, False)
        nodes4, best, added, removed, cost, fleet = _actuate(
            res.nodes, dec, tmpl, groups, threshold, strategy)
        nvalid = res.nodes.valid.sum()
        util = jnp.where(
            nvalid > 0,
            (dec.util * res.nodes.valid).sum() / jnp.maximum(nvalid, 1), 0.0)
        out = RolloutStep(
            verdict=dec.verdict,
            pending_after=dec.pending_after,
            best=best,
            nodes_added=added.astype(jnp.int32),
            nodes_removed=removed.astype(jnp.int32),
            util_mean=util,
            scaleup_cost=cost,
            fleet_price=fleet,
        )
        return (nodes4, res.specs), out

    _final, traj = jax.lax.scan(step, (nodes, specs), (adds, fails))
    return traj


@partial(jax.jit, static_argnames=("dims", "max_new_nodes",
                                   "max_pods_per_node", "chunk", "strategy"))
def _rollout_fused_jit(nodes, specs, scheduled, groups, limit_cap,
                       threshold, adds, fails, dims, max_new_nodes,
                       max_pods_per_node, chunk, strategy) -> RolloutStep:
    return _rollout_body(nodes, specs, scheduled, groups, limit_cap,
                         threshold, adds, fails, dims, max_new_nodes,
                         max_pods_per_node, chunk, strategy)


def rollout_fused(
    nodes: NodeTensors,
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    groups: NodeGroupTensors,
    limit_cap: jax.Array,     # i32[NG]
    threshold: jax.Array,     # f32 scale-down utilization threshold
    adds: jax.Array,          # i32[T, G] pending-pod arrivals per step
    fails: jax.Array,         # bool[T, N] node failures / spot reclaims
    dims: Dims,
    max_new_nodes: int = 256,
    max_pods_per_node: int = 128,
    chunk: int = 32,
    strategy: str = "least-waste",
) -> RolloutStep:
    """T fused loops as ONE device program: 'simulate this week' is a single
    dispatch + one compact trajectory fetch instead of T round trips. The
    scan carries (nodes, specs); `scheduled` (resident pods) stays the
    branch world's — the compressed policy moves capacity, not residents.

    Plain wrapper over the jit so every static forwards explicitly — see
    `multiverse_step` for why (default-vs-explicit kwargs split the cache)."""
    return _rollout_fused_jit(nodes, specs, scheduled, groups, limit_cap,
                              threshold, adds, fails, dims=dims,
                              max_new_nodes=max_new_nodes,
                              max_pods_per_node=max_pods_per_node,
                              chunk=chunk, strategy=strategy)


rollout_fused._cache_size = _rollout_fused_jit._cache_size


@partial(jax.jit, static_argnames=("dims", "max_new_nodes",
                                   "max_pods_per_node", "chunk", "strategy"))
def _rollout_multiverse_jit(nodes, specs, scheduled, groups, limit_cap,
                            thresholds, adds, fails, dims, max_new_nodes,
                            max_pods_per_node, chunk,
                            strategy) -> RolloutStep:
    def one(nt, pt, st, gt, cap, th, ad, fl):
        return _rollout_body(nt, pt, st, gt, cap, th, ad, fl, dims,
                             max_new_nodes, max_pods_per_node, chunk,
                             strategy)

    return jax.vmap(one)(nodes, specs, scheduled, groups, limit_cap,
                         thresholds, adds, fails)


def rollout_multiverse(
    nodes: NodeTensors,       # leading axis B on every tensor input
    specs: PodGroupTensors,
    scheduled: ScheduledPodTensors,
    groups: NodeGroupTensors,
    limit_cap: jax.Array,     # i32[B, NG]
    thresholds: jax.Array,    # f32[B]
    adds: jax.Array,          # i32[B, T, G]
    fails: jax.Array,         # bool[B, T, N]
    dims: Dims,
    max_new_nodes: int = 256,
    max_pods_per_node: int = 128,
    chunk: int = 32,
    strategy: str = "least-waste",
) -> RolloutStep:
    """B lanes × T loops in one dispatch — the headline B·T fused-steps-per-
    dispatch shape (`bench.py --whatif`). Every RolloutStep leaf gains a
    leading lane axis; lane b is bit-identical to `rollout_fused` on lane
    b's world and workload.

    Plain wrapper over the jit so every static forwards explicitly — see
    `multiverse_step` for why (default-vs-explicit kwargs split the cache)."""
    return _rollout_multiverse_jit(nodes, specs, scheduled, groups,
                                   limit_cap, thresholds, adds, fails,
                                   dims=dims, max_new_nodes=max_new_nodes,
                                   max_pods_per_node=max_pods_per_node,
                                   chunk=chunk, strategy=strategy)


rollout_multiverse._cache_size = _rollout_multiverse_jit._cache_size
