"""What-if reporting: per-lane summaries, deltas against the null
hypothesis, and the decision-plane digests that pin lane determinism.

Everything here is host-side post-processing of fetched arrays — no device
work. Digests use the journal's canonical sha256 (replay/journal.py) so a
lane digest from a what-if report can be compared 1:1 against a live
loop's journaled verdict surface.
"""

from __future__ import annotations

import hashlib
from typing import Any

import numpy as np


def _digest(*arrays: np.ndarray) -> str:
    h = hashlib.sha256()
    for a in arrays:
        a = np.asarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def lane_digests(decision, real: int) -> list[str]:
    """Per-lane digest over the full decision surface (verdict, pending,
    options, drain planes) — byte-level lane identity in one string."""
    verdict = np.asarray(decision.verdict)
    pending = np.asarray(decision.pending_after)
    est = np.asarray(decision.est_node_count)
    drain = np.asarray(decision.drainable)
    util = np.asarray(decision.util)
    return [_digest(verdict[b], pending[b], est[b], drain[b], util[b])
            for b in range(real)]


def trajectory_digests(traj, real: int) -> list[str]:
    """Per-lane digest of a rollout's decision trajectory (verdict +
    pending planes over T) — what the null-lane identity gate compares
    against T live fused loops."""
    verdict = np.asarray(traj.verdict)
    pending = np.asarray(traj.pending_after)
    return [_digest(verdict[b], pending[b]) for b in range(real)]


def _lane_row(summary, b: int) -> dict[str, Any]:
    return {
        "scaleupCost": float(np.asarray(summary.scaleup_cost)[b]),
        "fleetPrice": float(np.asarray(summary.fleet_price)[b]),
        "utilization": float(np.asarray(summary.utilization)[b]),
        "disruption": int(np.asarray(summary.disruption)[b]),
        "pending": int(np.asarray(summary.pending)[b]),
        "nodesAdded": int(np.asarray(summary.nodes_added)[b]),
        "best": int(np.asarray(summary.best)[b]),
    }


def build_report(lanes, summary=None, decision=None, traj=None,
                 workload=None) -> dict[str, Any]:
    """The what-if product surface: one JSON-able dict. Lane 0 is the null
    hypothesis; every other lane carries absolute values AND deltas vs
    lane 0. Padding lanes (shape-class rung fill) are excluded."""
    real = lanes.real
    out: dict[str, Any] = {
        "lanes": real,
        "meta": dict(lanes.meta),
        "variants": [v.to_dict() for v in lanes.variants[:real]],
    }
    if workload is not None:
        out["workload"] = workload.to_record()
    if summary is not None:
        rows = [_lane_row(summary, b) for b in range(real)]
        null = rows[0]
        for row in rows:
            row["deltas"] = {
                "scaleupCost": row["scaleupCost"] - null["scaleupCost"],
                "fleetPrice": row["fleetPrice"] - null["fleetPrice"],
                "utilization": row["utilization"] - null["utilization"],
                "disruption": row["disruption"] - null["disruption"],
                "pending": row["pending"] - null["pending"],
            }
        out["summary"] = rows
    if decision is not None:
        out["laneDigests"] = lane_digests(decision, real)
    if traj is not None:
        verdict = np.asarray(traj.verdict)
        out["rollout"] = {
            "steps": int(verdict.shape[1]),
            "trajectoryDigests": trajectory_digests(traj, real),
            "perLane": [{
                "nodesAdded": int(np.asarray(traj.nodes_added)[b].sum()),
                "nodesRemoved": int(np.asarray(traj.nodes_removed)[b].sum()),
                "scaleupCost": float(np.asarray(traj.scaleup_cost)[b].sum()),
                "finalFleetPrice": float(
                    np.asarray(traj.fleet_price)[b, -1]),
                "meanUtil": float(np.asarray(traj.util_mean)[b].mean()),
                "pendingEnd": int(
                    np.asarray(traj.pending_after)[b, -1].sum()),
            } for b in range(real)],
        }
    return out
