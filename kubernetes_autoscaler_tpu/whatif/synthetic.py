"""Synthetic branch worlds — a seeded FakeCluster run through one live
fused loop, so the CLI, the bench, and the determinism tests all branch
from the same kind of branch point a production tenant would give them
(never from hand-built tensors that could drift from the encoder)."""

from __future__ import annotations

import numpy as np


def synthetic_autoscaler(n_nodes: int = 8, n_pending: int = 6, seed: int = 0,
                         n_groups: int = 2, pending_milli: int = 300,
                         **opts_kw):
    """A FakeCluster world (resident load + pending pods + a drain band)
    under a fused-loop StaticAutoscaler. Returns (fake, autoscaler) —
    run_once has NOT been called yet."""
    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.static_autoscaler import (
        StaticAutoscaler,
    )
    from kubernetes_autoscaler_tpu.metrics.metrics import Registry
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    rng = np.random.RandomState(seed)
    fake = FakeCluster()
    for g in range(max(n_groups, 1)):
        tmpl = build_test_node(f"tmpl{g}", cpu_milli=4000 * (g + 1),
                               mem_mib=8192 * (g + 1))
        fake.add_node_group(f"ng{g}", tmpl, min_size=0, max_size=20)
    for i in range(n_nodes):
        nd = build_test_node(f"n{i}", cpu_milli=4000, mem_mib=8192)
        fake.add_existing_node("ng0", nd)
        # every node carries at least one resident pod: the compressed
        # rollout actuation only retires EMPTY nodes, so a fully-resident
        # steady world stays bitwise fixed (the null-lane identity shape)
        fake.add_pod(build_test_pod(
            f"r{i}", cpu_milli=int(rng.choice([400, 800, 1600])),
            mem_mib=512, owner_name=f"rs{i % 3}", node_name=nd.name))
    for i in range(n_pending):
        fake.add_pod(build_test_pod(f"p{i}", cpu_milli=pending_milli,
                                    mem_mib=256, owner_name="prs"))

    base = dict(
        scale_down_delay_after_add_s=0.0,
        scale_down_delay_after_failure_s=0.0,
        node_shape_bucket=16, group_shape_bucket=16,
        max_new_nodes_static=32, max_pods_per_node=32, drain_chunk=8,
        max_bulk_soft_taint_count=0,
        fused_loop=True,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=3600.0,
            scale_down_unready_time_s=3600.0),
    )
    base.update(opts_kw)
    a = StaticAutoscaler(fake.provider, fake, options=AutoscalingOptions(
        **base), eviction_sink=fake, registry=Registry())
    return fake, a


def synthetic_branch(n_nodes: int = 8, n_pending: int = 6, seed: int = 0,
                     n_groups: int = 2, loops: int = 1, now: float = 1000.0,
                     pending_milli: int = 300, **opts_kw):
    """Run `loops` live fused loops on a synthetic world and branch the
    last one. Returns (branch, autoscaler) — the autoscaler is live, so a
    caller can keep running loops to compare trajectories."""
    from kubernetes_autoscaler_tpu.whatif.variants import branch_from_live

    _fake, a = synthetic_autoscaler(n_nodes, n_pending, seed, n_groups,
                                    pending_milli=pending_milli, **opts_kw)
    st = None
    for k in range(max(loops, 1)):
        st = a.run_once(now=now + 10.0 * k)
    if st is None or st.fused_mode != "fused":
        raise RuntimeError(
            f"synthetic world did not take the fused path "
            f"(mode={getattr(st, 'fused_mode', None)!r})")
    br = branch_from_live(a)
    br.meta = {"source": "synthetic", "seed": seed, "nodes": n_nodes,
               "pending": n_pending, "groups": n_groups, "loops": loops}
    return br, a
