"""Branch points and variant lanes — where a multiverse comes from.

A `Branch` is one frozen world: the exact tensors a live fused dispatch
read (`branch_from_live`, straight out of the autoscaler's fused context)
or any journal cursor replayed back to that point (`branch_from_journal`,
riding the PR 9 harness). `build_lanes` fans a Branch out into B hypothesis
lanes: lane 0 is ALWAYS the null hypothesis — the unperturbed branch world,
pinned bit-identical to the live fused loop — and lanes 1.. apply
per-variant perturbations (price schedules, scale-up caps, scale-down
thresholds, injected node failures, workload scaling).

Perturbations are value edits on host copies of the branch planes; the
unperturbed leaves are broadcast, never recomputed, so a knob that a
variant leaves at its default cannot drift the lane.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class VariantSpec:
    """One hypothesis lane. Defaults are the null hypothesis — a spec with
    every field at default IS lane 0's semantics."""

    name: str = ""
    price_scale: float = 1.0          # scales every group's price_per_node
    max_new_cap: int | None = None    # extra min() on the composed limit cap
    threshold: float = 0.5            # scale-down utilization threshold
    fail_nodes: tuple[int, ...] = ()  # node indices reclaimed at branch time
    pending_scale: float = 1.0        # scales pending-pod counts (ceil)

    def is_null(self) -> bool:
        return (self.price_scale == 1.0 and self.max_new_cap is None
                and self.threshold == 0.5 and not self.fail_nodes
                and self.pending_scale == 1.0)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "VariantSpec":
        return cls(
            name=str(d.get("name", "")),
            price_scale=float(d.get("price_scale", 1.0)),
            max_new_cap=(int(d["max_new_cap"])
                         if d.get("max_new_cap") is not None else None),
            threshold=float(d.get("threshold", 0.5)),
            fail_nodes=tuple(int(i) for i in d.get("fail_nodes", ())),
            pending_scale=float(d.get("pending_scale", 1.0)),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name, "price_scale": self.price_scale,
            "max_new_cap": self.max_new_cap, "threshold": self.threshold,
            "fail_nodes": list(self.fail_nodes),
            "pending_scale": self.pending_scale,
        }


@dataclasses.dataclass
class Branch:
    """One frozen branch world + the statics its fused program compiled
    under. Tensors are the SAME objects (or host mirrors) the source loop
    dispatched — branching copies nothing until lanes are built."""

    nodes: Any                  # NodeTensors
    specs: Any                  # PodGroupTensors
    scheduled: Any              # ScheduledPodTensors
    groups: Any                 # NodeGroupTensors
    limit_cap: np.ndarray       # i32[NG] host-composed cap
    statics: dict[str, Any]     # run_once_fused static args (incl. dims)
    meta: dict[str, Any] = dataclasses.field(default_factory=dict)


def branch_from_live(autoscaler) -> Branch:
    """Branch from the live fused context — the exact input tensors of the
    most recent fused dispatch (pre-placement world + group tensors +
    composed cap). Requires a completed fused loop."""
    ctx = getattr(autoscaler, "_fused_ctx", None)
    if ctx is None:
        raise ValueError(
            "no fused context to branch from — run at least one loop with "
            "fused_loop=True (phased/deferred loops leave no branch point)")
    statics = dict(ctx["statics"])
    if statics.get("with_constraints"):
        raise ValueError(
            "constraint-overlay worlds are serial-only (docs/WHATIF.md): "
            "the multiverse lanes run the unconstrained fused body")
    nodes, specs, scheduled, _planes = ctx["inputs"]
    prep = ctx["prep"]
    return Branch(
        nodes=nodes, specs=specs, scheduled=scheduled,
        groups=prep.group_tensors,
        limit_cap=np.asarray(prep.limit_cap, np.int32),
        statics=statics,
        meta={"source": "live"},
    )


def branch_from_journal(path: str, upto: int | None = None) -> Branch:
    """Branch from a journal cursor: replay the journal (fused oracle,
    PR 9 harness) up to loop `upto` and branch the reconstructed fused
    context. Deterministic — the same (journal, cursor) always yields the
    same branch planes, which is what makes what-if reports replayable."""
    from kubernetes_autoscaler_tpu.replay.harness import replay_journal

    rep = replay_journal(path, upto=upto, keep_autoscaler=True,
                         options_override={"fused_loop": True})
    a = rep.get("_autoscaler")
    if a is None or getattr(a, "_fused_ctx", None) is None:
        raise ValueError(
            f"journal {path} yielded no fused context to branch "
            f"(loops replayed: {rep.get('loops', 0)})")
    br = branch_from_live(a)
    br.meta = {"source": "journal", "path": str(path), "upto": upto,
               "loops": rep.get("loops")}
    return br


@dataclasses.dataclass
class Lanes:
    """The stacked multiverse inputs: every tensor gains leading axis B.
    `real` counts requested lanes; rows real.. are null-lane padding up to
    a shape-class rung (sidecar admission) and are masked out of reports."""

    nodes: Any
    specs: Any
    scheduled: Any
    groups: Any
    limit_cap: Any              # i32[B, NG]
    thresholds: Any             # f32[B]
    variants: list[VariantSpec]
    real: int
    statics: dict[str, Any]
    meta: dict[str, Any]


def _bcast(tree, b: int):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: (jnp.broadcast_to(x[None], (b,) + x.shape)
                   if x is not None else None), tree)


def build_lanes(branch: Branch, variants: Sequence[VariantSpec],
                pad_to: int | None = None) -> Lanes:
    """Fan a Branch into B lanes. Prepends the null lane if the caller's
    variants[0] is not already null; pads with null lanes to `pad_to`
    (a shape-class rung) so lane-count churn never changes the dispatch
    shape. Unperturbed knobs broadcast the branch leaves untouched."""
    import jax.numpy as jnp

    vs = list(variants)
    if not vs or not vs[0].is_null():
        vs = [VariantSpec(name="null")] + vs
    real = len(vs)
    if pad_to is not None and pad_to > len(vs):
        vs = vs + [VariantSpec(name="pad")] * (pad_to - len(vs))
    b = len(vs)

    nodes = _bcast(branch.nodes, b)
    specs = _bcast(branch.specs, b)
    scheduled = _bcast(branch.scheduled, b)
    groups = _bcast(branch.groups, b)

    # per-lane knobs, edited on host copies only where a variant moves them
    cap = np.broadcast_to(branch.limit_cap[None],
                          (b,) + branch.limit_cap.shape).copy()
    prices = np.broadcast_to(np.asarray(branch.groups.price_per_node)[None],
                             (b, branch.groups.price_per_node.shape[0]))
    prices = np.array(prices, np.float32)
    n = int(np.asarray(branch.nodes.valid).shape[0])
    fail = np.zeros((b, n), bool)
    counts = np.broadcast_to(np.asarray(branch.specs.count)[None],
                             (b,) + np.asarray(branch.specs.count).shape)
    counts = np.array(counts, np.int32)
    thresholds = np.zeros((b,), np.float32)
    touched_price = touched_count = False
    for i, v in enumerate(vs):
        thresholds[i] = v.threshold
        if v.max_new_cap is not None:
            cap[i] = np.minimum(cap[i], np.int32(v.max_new_cap))
        if v.price_scale != 1.0:
            prices[i] = prices[i] * np.float32(v.price_scale)
            touched_price = True
        if v.pending_scale != 1.0:
            counts[i] = np.ceil(
                counts[i] * np.float64(v.pending_scale)).astype(np.int32)
            touched_count = True
        for idx in v.fail_nodes:
            if 0 <= idx < n:
                fail[i, idx] = True

    if touched_price:
        groups = groups.replace(price_per_node=jnp.asarray(prices))
    if touched_count:
        specs = specs.replace(count=jnp.asarray(counts))
    if fail.any():
        fm = jnp.asarray(fail)
        nodes = nodes.replace(ready=nodes.ready & ~fm,
                              schedulable=nodes.schedulable & ~fm)

    return Lanes(
        nodes=nodes, specs=specs, scheduled=scheduled, groups=groups,
        limit_cap=jnp.asarray(cap), thresholds=jnp.asarray(thresholds),
        variants=vs, real=real, statics=dict(branch.statics),
        meta=dict(branch.meta),
    )
