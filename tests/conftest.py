"""Test harness: force an 8-device virtual CPU mesh before JAX import.

Mirrors the reference's test stance (SURVEY.md §4): pure in-memory fixtures,
no external services. Multi-chip sharding is validated on virtual devices
(xla_force_host_platform_device_count) exactly as the driver's
dryrun_multichip does; real-TPU execution is exercised by bench.py.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override: the session env may point at a real TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize force-registers the axon TPU backend regardless of
# JAX_PLATFORMS; the config knob still wins if set before first backend use.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
