"""Admission layer: per-tenant fairness, explicit backpressure, pipelining.

Pure host-side tests (no jax, no grpc): the queue and scheduler are plain
threading code, so their fairness/backpressure contracts are pinned with a
fake dispatch."""

import threading
import time

import pytest

from kubernetes_autoscaler_tpu.sidecar.admission import (
    AdmissionQueue,
    BatchScheduler,
    QueueFull,
    Ticket,
    split_by_key,
)


def mk(tenant, kind="up", key=("up", "c0"), lane=None):
    return Ticket(tenant=tenant, kind=kind, key=key, lane=lane)


def test_round_robin_window_prevents_starvation():
    """A chatty tenant (50 queued) cannot starve a quiet one (1 queued):
    the window takes one ticket per tenant per cycle, so the quiet tenant's
    request is in the FIRST window regardless of arrival order."""
    q = AdmissionQueue(max_depth=128)
    for i in range(50):
        q.submit(mk("chatty"))
    q.submit(mk("quiet"))
    window = q.collect(max_lanes=8, wait_s=0.1, coalesce_s=0.0)
    assert len(window) == 8
    tenants = [t.tenant for t in window]
    assert "quiet" in tenants
    # cycle structure: first cycle one each, then chatty fills the rest
    assert tenants[0] == "chatty" and tenants[1] == "quiet"
    assert tenants[2:] == ["chatty"] * 6


def test_round_robin_cursor_rotates_across_windows():
    """Fairness holds ACROSS windows: the tenant that led one window does
    not lead the next (persistent cursor, not reset-to-first)."""
    q = AdmissionQueue(max_depth=128)
    for _ in range(4):
        for t in ("a", "b", "c"):
            q.submit(mk(t))
    w1 = q.collect(3, 0.1, 0.0)
    w2 = q.collect(3, 0.1, 0.0)
    assert [t.tenant for t in w1] == ["a", "b", "c"]
    # cursor advanced past the ring once — same rotation, no reset bias
    assert sorted(t.tenant for t in w2) == ["a", "b", "c"]


def test_backpressure_rejects_and_rejected_request_is_retryable():
    q = AdmissionQueue(max_depth=2, retry_after_ms=7)
    q.submit(mk("a"))
    q.submit(mk("b"))
    with pytest.raises(QueueFull) as ei:
        q.submit(mk("c"))
    assert ei.value.retry_after_ms == 7
    assert q.rejected == 1
    # rejection left no partial state: draining frees capacity and the SAME
    # request submits cleanly afterwards
    assert len(q.collect(8, 0.1, 0.0)) == 2
    q.submit(mk("c"))
    assert [t.tenant for t in q.collect(8, 0.1, 0.0)] == ["c"]


def test_collect_times_out_empty():
    q = AdmissionQueue()
    t0 = time.monotonic()
    assert q.collect(8, wait_s=0.05, coalesce_s=0.0) == []
    assert time.monotonic() - t0 < 1.0


def test_coalescing_window_gathers_concurrent_arrivals():
    """A ticket arriving within the coalescing window joins the batch that
    was already forming."""
    q = AdmissionQueue()
    q.submit(mk("a"))

    def late():
        time.sleep(0.02)
        q.submit(mk("b"))

    th = threading.Thread(target=late)
    th.start()
    window = q.collect(max_lanes=8, wait_s=0.1, coalesce_s=0.5)
    th.join()
    assert sorted(t.tenant for t in window) == ["a", "b"]


def test_split_by_key_preserves_window_order():
    w = [mk("a", key=("up", "c0")), mk("b", key=("up", "c1")),
         mk("c", key=("up", "c0"))]
    runs = split_by_key(w)
    assert [[t.tenant for t in r] for r in runs] == [["a", "c"], ["b"]]


class _FakeInflight:
    def __init__(self, tickets, log, harvested):
        self.tickets = tickets
        self.log = log
        self.harvested = harvested

    def harvest(self):
        self.harvested.append([t.tenant for t in self.tickets])
        for t in self.tickets:
            t.resolve(result={"ok": t.tenant})


def test_scheduler_pipelines_harvest_one_window_late():
    """Window k's harvest happens only after window k+1's dispatch was
    issued (encode→dispatch→fetch pipelining): the dispatch log shows
    dispatch(k+1) strictly before harvest(k)."""
    q = AdmissionQueue()
    events = []
    harvested = []

    def dispatch(batch):
        events.append(("dispatch", [t.tenant for t in batch]))
        return _FakeInflight(batch, events, harvested)

    s = BatchScheduler(q, dispatch, lanes=4, window_s=0.01,
                       idle_wait_s=0.01).start()
    try:
        t1, t2 = mk("w1"), mk("w2")
        q.submit(t1)
        assert t1.wait(5.0) == {"ok": "w1"}   # idle path harvests window 1
        q.submit(t2)
        assert t2.wait(5.0) == {"ok": "w2"}
        assert harvested == [["w1"], ["w2"]]
        # now force back-to-back windows and check the interleave
        events.clear()
        a, b = mk("x"), mk("y", key=("up", "other"))
        q.submit(a)
        q.submit(b)          # same window, different key → two batches
        a.wait(5.0)
        b.wait(5.0)
        di = [i for i, e in enumerate(events) if e[0] == "dispatch"]
        assert len(di) == 2
        # second dispatch issued before the first batch resolved its wait:
        # the scheduler dispatched batch 2, then harvested batch 1
        assert harvested[-2:] == [["x"], ["y"]]
    finally:
        s.stop()


def test_scheduler_stop_fails_queued_tickets():
    q = AdmissionQueue()
    s = BatchScheduler(q, lambda b: _FakeInflight(b, [], []), lanes=2,
                       window_s=0.01).start()
    s.stop()
    t = mk("late")
    q.submit(t)     # enqueued after stop: drained with an error
    s.stop()
    with pytest.raises(RuntimeError):
        t.wait(0.5)


def test_dispatch_error_fails_batch_not_scheduler():
    q = AdmissionQueue()
    calls = []

    def dispatch(batch):
        calls.append(len(batch))
        if len(calls) == 1:
            raise RuntimeError("boom")
        return _FakeInflight(batch, [], [])

    s = BatchScheduler(q, dispatch, lanes=4, window_s=0.01).start()
    try:
        bad = mk("a")
        q.submit(bad)
        with pytest.raises(RuntimeError, match="boom"):
            bad.wait(5.0)
        ok = mk("b")
        q.submit(ok)   # scheduler survived the failed batch
        assert ok.wait(5.0) == {"ok": "b"}
    finally:
        s.stop()
