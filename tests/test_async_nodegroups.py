"""Async node-group creation: a slow-creating group never blocks the loop,
its promised capacity counts as upcoming, and the initial scale-up lands when
creation completes.

Reference analog: core/scaleup/orchestrator/orchestrator.go:453
CreateNodeGroupAsync + async_initializer.go + AsyncNodeGroupStateChecker.
"""

import threading
import time

from kubernetes_autoscaler_tpu.cloudprovider.test_provider import TestNodeGroup
from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
from kubernetes_autoscaler_tpu.utils.testing import build_test_node, build_test_pod

from test_runonce import autoscaler_for


class SlowCreateGroup(TestNodeGroup):
    """TestNodeGroup whose create() blocks until the test releases it."""

    gate: threading.Event = threading.Event()
    create_calls: int = 0

    def create(self):
        type(self).create_calls += 1
        assert self.gate.wait(timeout=30), "test never released the gate"
        return super().create()


def _world():
    SlowCreateGroup.gate = threading.Event()
    SlowCreateGroup.create_calls = 0
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.provider.add_machine_type("m-slow", tmpl)

    orig = fake.provider.new_node_group

    def slow_new_node_group(machine_type, max_size=1000):
        g = orig(machine_type, max_size)
        slow = SlowCreateGroup(g._id, 0, max_size, 0, g._template,
                               fake.provider, None, g.price_per_node)
        slow._exists = False
        slow._autoprovisioned = True
        return slow

    fake.provider.new_node_group = slow_new_node_group
    # a tiny seed group so the cluster is actionable; pods don't fit it
    seed = build_test_node("seed-tmpl", cpu_milli=100, mem_mib=256)
    fake.add_node_group("ng-seed", seed, min_size=1, max_size=1)
    fake.add_existing_node(
        "ng-seed", build_test_node("seed-0", cpu_milli=100, mem_mib=256))
    for i in range(4):
        fake.add_pod(build_test_pod(f"p{i}", cpu_milli=1500, mem_mib=512,
                                    owner_name="rs"))
    return fake


def test_slow_creation_does_not_block_loop_and_counts_upcoming():
    # warm the jit caches on a throwaway world first: as the alphabetically
    # first suite test this otherwise pays the whole cold-compile bill inside
    # the timed window and flakes against the blocking budget under CI load
    warm = _world()
    SlowCreateGroup.gate.set()
    warm_a = autoscaler_for(warm, node_autoprovisioning_enabled=True,
                            async_node_group_creation=True)
    warm_a.run_once(now=500.0)
    # the warm create must FINISH before _world() rebinds the class-level
    # gate/counter, or the orphan thread races the timed run's assertions
    warm_a.async_creator.wait_idle()

    fake = _world()
    a = autoscaler_for(fake, node_autoprovisioning_enabled=True,
                       async_node_group_creation=True)
    t0 = time.monotonic()
    status = a.run_once(now=1000.0)
    loop_s = time.monotonic() - t0
    assert status.scale_up is not None and status.scale_up.scaled_up
    assert loop_s < 15, f"loop blocked on slow creation ({loop_s:.1f}s)"
    assert SlowCreateGroup.create_calls == 1
    gid = next(iter(status.scale_up.increases))
    assert a.async_creator.is_upcoming(gid)

    # second loop while creation is STILL in flight: the promised capacity is
    # injected as upcoming, so the same pods must not trigger another
    # scale-up or another create
    status2 = a.run_once(now=1010.0)
    assert status2.pending_pods == 0, "upcoming capacity must absorb the pods"
    assert status2.scale_up is None
    assert SlowCreateGroup.create_calls == 1

    # release the gate: creation completes, initial scale-up lands
    SlowCreateGroup.gate.set()
    a.async_creator.wait_idle()
    assert not a.async_creator.is_upcoming(gid)
    g = next(x for x in fake.provider.node_groups() if x.id() == gid)
    assert g.exist()
    assert g.target_size() == status.scale_up.increases[gid]
    assert len(fake.provider.nodes_of(gid)) == g.target_size()


def test_sync_creation_still_works_when_flag_off():
    fake = _world()
    SlowCreateGroup.gate.set()  # don't block the synchronous path
    a = autoscaler_for(fake, node_autoprovisioning_enabled=True)
    status = a.run_once(now=1000.0)
    assert status.scale_up is not None and status.scale_up.scaled_up
    assert a.async_creator is None
    assert len(fake.nodes) > 0
