"""ZeroOrMaxNodeScaling (atomic groups) and scale-down candidate-pool policy.

Reference counterparts: NodeGroupAutoscalingOptions.ZeroOrMaxNodeScaling
consumed by the scale-up orchestrator (AtomicIncreaseSize) and by the
AtomicResizeFilteringProcessor (ScaleDownSetProcessor default,
processors.go); processors/scaledowncandidates sorting + pool-ratio caps
(--scale-down-candidates-pool-ratio, FAQ.md:1117).
"""

from kubernetes_autoscaler_tpu.cloudprovider.provider import NodeGroupOptions
from kubernetes_autoscaler_tpu.config.options import (
    AutoscalingOptions,
    NodeGroupDefaults,
)
from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
from kubernetes_autoscaler_tpu.utils.testing import build_test_node, build_test_pod


def make_options(**kw):
    base = dict(
        node_shape_bucket=16, group_shape_bucket=16, max_new_nodes_static=32,
        max_pods_per_node=32, drain_chunk=8,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=0.0, scale_down_unready_time_s=0.0),
    )
    base.update(kw)
    return AutoscalingOptions(**base)


def test_atomic_group_scales_all_or_nothing_up():
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group(
        "atomic", tmpl, min_size=0, max_size=6,
        options=NodeGroupOptions(zero_or_max_node_scaling=True))
    # demand worth 2 nodes -> the atomic group must still go to max (6)
    for i in range(4):
        fake.add_pod(build_test_pod(f"p{i}", cpu_milli=1500, mem_mib=512,
                                    owner_name="rs"))
    a = StaticAutoscaler(fake.provider, fake, options=make_options(),
                         eviction_sink=fake)
    status = a.run_once(now=1000.0)
    assert status.scale_up is not None and status.scale_up.scaled_up
    assert status.scale_up.increases == {"atomic": 6}
    assert len(fake.nodes) == 6


def test_atomic_group_scale_down_all_or_nothing():
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group(
        "atomic", tmpl, min_size=0, max_size=4,
        options=NodeGroupOptions(zero_or_max_node_scaling=True))
    fake.add_node_group("plain", tmpl, min_size=0, max_size=4)
    for i in range(3):
        fake.add_existing_node(
            "atomic", build_test_node(f"a{i}", cpu_milli=4000, mem_mib=8192))
    fake.add_existing_node(
        "plain", build_test_node("keeper", cpu_milli=4000, mem_mib=8192))
    # pin one atomic node with an unmovable (naked) pod: the whole atomic
    # group must then stay, even though a0/a1 are idle
    fake.add_pod(build_test_pod("naked", cpu_milli=500, mem_mib=256,
                                node_name="a2"))
    a = StaticAutoscaler(fake.provider, fake, options=make_options(),
                         eviction_sink=fake)
    status = a.run_once(now=1000.0)
    assert all(not n.startswith("a") for n in status.scale_down_deleted), (
        f"partial atomic deletion: {status.scale_down_deleted}")

    # unpin: whole group (all 3 nodes) may now leave in one round
    fake.pods.clear()
    status2 = a.run_once(now=2000.0)
    assert sorted(n for n in status2.scale_down_deleted
                  if n.startswith("a")) == ["a0", "a1", "a2"]


def test_candidate_pool_ratio_caps_and_prefers_previous():
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=32)
    for i in range(10):
        fake.add_existing_node(
            "ng1", build_test_node(f"n{i}", cpu_milli=4000, mem_mib=8192))
    opts = make_options(
        scale_down_candidates_pool_ratio=0.2,       # pool = max(2, 3) = 3
        scale_down_candidates_pool_min_count=3,
        max_empty_bulk_delete=2, max_scale_down_parallelism=2,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=100.0, scale_down_unready_time_s=100.0),
    )
    a = StaticAutoscaler(fake.provider, fake, options=opts, eviction_sink=fake)
    st1 = a.run_once(now=1000.0)
    # pool caps the unneeded set at 3 of 10 idle nodes
    assert len(st1.unneeded_nodes) == 3
    first_pool = set(st1.unneeded_nodes)
    assert st1.scale_down_deleted == []              # unneeded time not met
    # next loop: the SAME nodes stay candidates (previous-first sorting), so
    # their unneeded clocks accrue instead of resetting
    st2 = a.run_once(now=1050.0)
    assert set(st2.unneeded_nodes) == first_pool
    st3 = a.run_once(now=1101.0)
    assert set(st3.scale_down_deleted) <= first_pool
    assert len(st3.scale_down_deleted) == 2          # deletion budgets apply


def test_atomic_group_exceeding_budget_does_not_starve_plain():
    """An atomic group bigger than the deletion budgets must be skipped up
    front — not consume the budgets and then be dropped, starving plain
    candidates forever (reference: budgets.go CropNodes treats atomic
    groups as a unit)."""
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group(
        "atomic", tmpl, min_size=0, max_size=8,
        options=NodeGroupOptions(zero_or_max_node_scaling=True))
    fake.add_node_group("plain", tmpl, min_size=0, max_size=8)
    for i in range(4):
        fake.add_existing_node(
            "atomic", build_test_node(f"a{i}", cpu_milli=4000, mem_mib=8192))
    fake.add_existing_node(
        "plain", build_test_node("idle", cpu_milli=4000, mem_mib=8192))
    fake.add_existing_node(
        "plain", build_test_node("busy", cpu_milli=4000, mem_mib=8192))
    fake.add_pod(build_test_pod("b", cpu_milli=3000, mem_mib=512,
                                owner_name="rs", node_name="busy"))
    opts = make_options(max_scale_down_parallelism=2,
                        max_empty_bulk_delete=2, max_drain_parallelism=2)
    a = StaticAutoscaler(fake.provider, fake, options=opts, eviction_sink=fake)
    status = a.run_once(now=1000.0)
    # atomic group (4 nodes) exceeds the budget of 2 -> whole group skipped;
    # the plain idle node must still be deleted
    assert "idle" in status.scale_down_deleted
    assert all(not n.startswith("a") for n in status.scale_down_deleted)


def test_atomic_partial_confirm_retries_without_group():
    """Review scenario: an all-empty atomic group passes the size pre-screen
    (4 <= empty+drain budgets) but only 2 members fit the empty budget; the
    pass must re-run WITHOUT the group so plain candidates still drain."""
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group(
        "atomic", tmpl, min_size=0, max_size=8,
        options=NodeGroupOptions(zero_or_max_node_scaling=True))
    fake.add_node_group("plain", tmpl, min_size=0, max_size=8)
    for i in range(4):
        fake.add_existing_node(
            "atomic", build_test_node(f"a{i}", cpu_milli=4000, mem_mib=8192))
    fake.add_existing_node(
        "plain", build_test_node("idle", cpu_milli=4000, mem_mib=8192))
    opts = make_options(max_scale_down_parallelism=4,
                        max_empty_bulk_delete=2, max_drain_parallelism=2)
    a = StaticAutoscaler(fake.provider, fake, options=opts, eviction_sink=fake)
    status = a.run_once(now=1000.0)
    assert "idle" in status.scale_down_deleted
    assert all(not n.startswith("a") for n in status.scale_down_deleted)
