"""Balancer distribution policies + addon-resizer formula."""

from kubernetes_autoscaler_tpu.balancer.balancer import (
    BalancerSpec,
    TargetSpec,
    distribute,
)
from kubernetes_autoscaler_tpu.nanny.nanny import (
    Nanny,
    ResourceEstimatorSpec,
    estimate,
    needs_update,
)


def test_proportional_split():
    spec = BalancerSpec(name="b", replicas=10, targets=[
        TargetSpec("a", proportion=3), TargetSpec("b", proportion=1)])
    out = distribute(spec)
    assert out == {"a": 8, "b": 2}  # 7.5 rounds via largest remainder


def test_proportional_respects_max():
    spec = BalancerSpec(name="b", replicas=10, targets=[
        TargetSpec("a", proportion=3, max_replicas=4),
        TargetSpec("b", proportion=1)])
    out = distribute(spec)
    assert out["a"] == 4 and out["b"] == 6


def test_priority_fills_in_order():
    spec = BalancerSpec(name="b", replicas=7, policy="priority", targets=[
        TargetSpec("cheap", priority=10, max_replicas=5),
        TargetSpec("fallback", priority=1)])
    out = distribute(spec)
    assert out == {"cheap": 5, "fallback": 2}


def test_min_replicas_honored():
    spec = BalancerSpec(name="b", replicas=6, targets=[
        TargetSpec("a", min_replicas=2, proportion=1),
        TargetSpec("b", min_replicas=1, proportion=1)])
    out = distribute(spec)
    assert out["a"] >= 2 and out["b"] >= 1 and sum(out.values()) == 6


def test_fallback_avoids_problem_domain():
    spec = BalancerSpec(name="b", replicas=4, targets=[
        TargetSpec("bad", proportion=1), TargetSpec("good", proportion=1)])
    out = distribute(spec, problem_domains={"bad"})
    assert out == {"bad": 0, "good": 4}  # unhealthy domain scaled to zero


def test_nanny_formula_and_threshold():
    spec = ResourceEstimatorSpec(
        base={"cpu": 0.1, "memory": 200e6},
        extra_per_node={"cpu": 0.001, "memory": 2e6},
    )
    want = estimate(spec, 1000)
    assert abs(want["cpu"] - 1.1) < 1e-9
    assert abs(want["memory"] - 2.2e9) < 1e-3
    # within 10%: no update
    assert not needs_update(spec, {"cpu": 1.05, "memory": 2.1e9}, 1000)
    assert needs_update(spec, {"cpu": 0.5, "memory": 2.1e9}, 1000)


def test_nanny_patches_when_drifted():
    patched = []
    n = Nanny(ResourceEstimatorSpec(base={"cpu": 0.1},
                                    extra_per_node={"cpu": 0.001}),
              patch_resources=patched.append)
    assert n.poll_once(2000, {"cpu": 0.5})
    assert abs(patched[0]["cpu"] - 2.1) < 1e-9
    assert not n.poll_once(2000, patched[0])
