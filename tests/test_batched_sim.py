"""Batched-vmapped ≡ per-tenant-serial: the multi-cluster dispatch contract.

The serving tentpole (docs/SERVING.md) claims batching is a DISPATCH-SHAPE
change only: lane i of `scale_up_sim_batch` / `scale_down_sim_batch` must be
bit-for-bit the serial `scale_up_sim` / `scale_down_sim` result on lane i's
world — across mixed shape classes, occupancy padding (duplicated lanes) and
tenant order permutations. Everything here runs on encode_cluster worlds, no
native codec or gRPC needed."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kubernetes_autoscaler_tpu.models.cluster_state import DEFAULT_DIMS  # noqa: E402
from kubernetes_autoscaler_tpu.models.encode import (  # noqa: E402
    encode_cluster,
    encode_node_groups,
)
from kubernetes_autoscaler_tpu.ops.autoscale_step import (  # noqa: E402
    scale_down_sim,
    scale_down_sim_batch,
    scale_up_sim,
    scale_up_sim_batch,
)
from kubernetes_autoscaler_tpu.sidecar.batch import pad_lanes  # noqa: E402
from kubernetes_autoscaler_tpu.utils.testing import (  # noqa: E402
    build_test_node,
    build_test_pod,
)


def make_world(seed: int, n_nodes: int, n_pods: int, node_bucket: int = 16,
               group_bucket: int = 16, pod_bucket: int = 64):
    """A randomized small world + 3 expansion templates, padded to the given
    buckets (one bucket triple = one shape class)."""
    rng = np.random.RandomState(seed)
    nodes = [
        build_test_node(
            f"n{i}", cpu_milli=int(rng.choice([4000, 8000, 16000])),
            mem_mib=16384, pods=110,
            labels={"pool": "a" if i % 2 else "b"})
        for i in range(n_nodes)
    ]
    pods = [
        build_test_pod(
            f"p{i}", cpu_milli=int(rng.choice([250, 500, 1000])),
            mem_mib=int(rng.choice([256, 512])),
            owner_name=f"rs{i % 5}",
            node_name=(f"n{i % n_nodes}" if i % 3 == 0 else None))
        for i in range(n_pods)
    ]
    enc = encode_cluster(nodes, pods, node_bucket=node_bucket,
                         group_bucket=group_bucket, pod_bucket=pod_bucket)
    tmpl = [(build_test_node(f"t{k}", cpu_milli=8000, mem_mib=32768,
                             pods=110), 50, 1.0 + k) for k in range(3)]
    groups = encode_node_groups(tmpl, enc.registry, enc.zone_table, bucket=4)
    return enc, groups


def stack(trees):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def assert_lane_equal(serial_out, batched_out, lane: int, ctx=""):
    ls = jax.tree_util.tree_leaves_with_path(serial_out)
    lb = jax.tree_util.tree_leaves_with_path(batched_out)
    assert len(ls) == len(lb)
    for (path, a), (_, b) in zip(ls, lb):
        a = np.asarray(a)
        b = np.asarray(b)[lane]
        assert a.dtype == b.dtype and a.shape == b.shape, (ctx, path)
        np.testing.assert_array_equal(a, b, err_msg=f"{ctx} lane={lane} {path}")


def batch_inputs(worlds):
    nt = stack([w[0].nodes for w in worlds])
    gt = stack([w[0].specs for w in worlds])
    pt = stack([w[0].scheduled for w in worlds])
    gr = stack([w[1] for w in worlds])
    return nt, gt, pt, gr


WORLDS = [make_world(s, n_nodes=6 + s, n_pods=30 + 7 * s) for s in range(4)]


def test_scale_up_batched_equals_serial_bit_for_bit():
    nt, gt, pt, gr = batch_inputs(WORLDS)
    out_b = scale_up_sim_batch(nt, gt, pt, gr, DEFAULT_DIMS, 16, "least-waste")
    for i, (enc, groups) in enumerate(WORLDS):
        out_s = scale_up_sim(enc.nodes, enc.specs, enc.scheduled, groups,
                             DEFAULT_DIMS, 16, "least-waste")
        assert_lane_equal(out_s, out_b, i, "scale_up")


def test_scale_down_batched_equals_serial_bit_for_bit():
    nt, gt, pt, _ = batch_inputs(WORLDS)
    thresholds = jnp.asarray([0.5, 0.35, 0.65, 0.5], jnp.float32)
    out_b = scale_down_sim_batch(nt, gt, pt, thresholds,
                                 max_pods_per_node=16, chunk=8, max_zones=16)
    for i, (enc, _) in enumerate(WORLDS):
        out_s = scale_down_sim(enc.nodes, enc.specs, enc.scheduled,
                               float(thresholds[i]), 16, 8, None, 16, False)
        assert_lane_equal(out_s, out_b, i, "scale_down")


def test_batched_is_order_independent():
    """Tenant order inside the batch cannot change any lane's verdicts —
    permuting lanes permutes outputs, bit-for-bit."""
    nt, gt, pt, gr = batch_inputs(WORLDS)
    out_a = scale_up_sim_batch(nt, gt, pt, gr, DEFAULT_DIMS, 16, "least-waste")
    perm = [2, 0, 3, 1]
    nt2, gt2, pt2, gr2 = batch_inputs([WORLDS[i] for i in perm])
    out_b = scale_up_sim_batch(nt2, gt2, pt2, gr2, DEFAULT_DIMS, 16,
                               "least-waste")
    for new_lane, old_lane in enumerate(perm):
        la = jax.tree_util.tree_leaves(out_a)
        lb = jax.tree_util.tree_leaves(out_b)
        for a, b in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(a)[old_lane],
                                          np.asarray(b)[new_lane])


def test_padded_lanes_do_not_perturb_members():
    """Occupancy padding (sidecar/batch.pad_lanes duplicates lane 0) must
    leave member lanes bit-identical to a full-occupancy batch of the same
    worlds — padded lanes are dead weight, not neighbors that interact."""
    members = WORLDS[:2]
    padded = pad_lanes(list(members), 4)
    assert len(padded) == 4 and padded[2] is padded[0]
    nt, gt, pt, gr = batch_inputs(padded)
    out_p = scale_up_sim_batch(nt, gt, pt, gr, DEFAULT_DIMS, 16, "least-waste")
    for i, (enc, groups) in enumerate(members):
        out_s = scale_up_sim(enc.nodes, enc.specs, enc.scheduled, groups,
                             DEFAULT_DIMS, 16, "least-waste")
        assert_lane_equal(out_s, out_p, i, "padded")
    # and the pad lanes replicate lane 0's result exactly
    for leaf in jax.tree_util.tree_leaves(out_p):
        leaf = np.asarray(leaf)
        np.testing.assert_array_equal(leaf[2], leaf[0])
        np.testing.assert_array_equal(leaf[3], leaf[0])


def test_mixed_shape_classes_batch_per_class():
    """Two shape classes (different padded buckets) each batch internally
    and match their serial results — the per-class dispatch the admission
    scheduler performs after split_by_key."""
    small = [make_world(s, 5, 20, node_bucket=8, group_bucket=8,
                        pod_bucket=32) for s in range(2)]
    big = [make_world(10 + s, 20, 90, node_bucket=32, group_bucket=16,
                      pod_bucket=128) for s in range(2)]
    for cls in (small, big):
        nt, gt, pt, gr = batch_inputs(cls)
        out_b = scale_up_sim_batch(nt, gt, pt, gr, DEFAULT_DIMS, 16,
                                   "least-waste")
        for i, (enc, groups) in enumerate(cls):
            out_s = scale_up_sim(enc.nodes, enc.specs, enc.scheduled, groups,
                                 DEFAULT_DIMS, 16, "least-waste")
            assert_lane_equal(out_s, out_b, i, "mixed-class")


def test_fuzzed_worlds_many_seeds():
    """Wider fuzz at one shape class: every seed's lane stays bit-exact.
    Sizes stay inside one (16, 16, 64) bucket triple so the lanes stack —
    exactly the class membership the ladder enforces in production."""
    worlds = [make_world(100 + s, n_nodes=4 + (s % 9), n_pods=10 + 5 * s,
                         group_bucket=32)
              for s in range(8)]
    nt, gt, pt, gr = batch_inputs(worlds)
    out_b = scale_up_sim_batch(nt, gt, pt, gr, DEFAULT_DIMS, 16, "least-waste")
    for i, (enc, groups) in enumerate(worlds):
        out_s = scale_up_sim(enc.nodes, enc.specs, enc.scheduled, groups,
                             DEFAULT_DIMS, 16, "least-waste")
        assert_lane_equal(out_s, out_b, i, f"fuzz seed={100 + i}")
