"""bench.py resilience: bounded retry-with-backoff around every tunnel touch,
a TOTAL init budget capping the ladder, and the never-null contract — a
broken/hung backend degrades to a CPU floor metric (same headline metric
name, backend=cpu-floor) instead of shipping a null.

Rounds 1-5 lesson encoded as tests: five consecutive null JSONs meant the
perf trajectory was never measured; now a null is only possible under an
explicit --require-tpu.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_with_retries_recovers_after_transient_failures():
    bench = _load_bench()
    calls = {"n": 0}
    delays = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("tunnel flapped")
        return 7

    out = bench.with_retries(flaky, "probe", attempts=5, backoff_s=2,
                             sleep=delays.append)
    assert out == 7
    assert calls["n"] == 3
    assert delays == [2, 4]  # exponential backoff


def test_with_retries_exhausts_and_reraises():
    bench = _load_bench()
    delays = []

    def dead():
        raise ConnectionError("no route to TPU")

    with pytest.raises(ConnectionError):
        bench.with_retries(dead, "probe", attempts=3, backoff_s=1,
                           sleep=delays.append)
    assert len(delays) == 2  # no sleep after the final attempt


def test_emit_failure_prints_parseable_json(capsys):
    bench = _load_bench()
    bench.emit_failure("scaleup_sim_p50_ms_x", RuntimeError("boom"))
    line = capsys.readouterr().out.strip()
    doc = json.loads(line)
    assert doc["metric"] == "scaleup_sim_p50_ms_x"
    assert doc["value"] is None
    assert doc["unit"] == "ms"
    assert doc["vs_baseline"] == 0.0
    assert "backend" in doc
    assert "RuntimeError: boom" in doc["error"]


def test_bench_degrades_to_cpu_floor_when_backend_unreachable():
    """The never-null contract: a backend that cannot even initialize must
    still produce a measured value — the CPU floor child, emitting the SAME
    headline metric with backend=cpu-floor and exit 0."""
    env = {k: v for k, v in os.environ.items() if "AXON" not in k.upper()}
    env["JAX_PLATFORMS"] = "nonexistent-backend"
    env["KA_TPU_BENCH_RETRIES"] = "2"
    env["KA_TPU_BENCH_BACKOFF_S"] = "0.01"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--nodes", "8", "--pods", "8", "--pod-groups", "2",
         "--nodegroups", "2", "--iters", "1", "--chain", "2"],
        capture_output=True, text=True, env=env, timeout=420, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert lines, f"no stdout; stderr={proc.stderr[-500:]}"
    doc = json.loads(lines[-1])
    assert doc["value"] is not None and doc["value"] > 0
    assert doc["backend"] == "cpu-floor"
    assert doc["mode"] == "floor"
    # the headline metric name survives degradation (the trajectory series
    # keeps its key); the actual reduced shapes are declared next to it
    assert doc["metric"] == "scaleup_sim_p50_ms_0kpods_8nodes_2ng"
    assert doc["floor_shapes"]["nodes"] > 0
    assert "degrading to CPU floor" in proc.stderr


def test_bench_require_tpu_is_the_only_null_path():
    """--require-tpu disables degradation: no TPU ⇒ the null error JSON and
    exit 1 — and nothing else produces a null."""
    env = {k: v for k, v in os.environ.items() if "AXON" not in k.upper()}
    env["JAX_PLATFORMS"] = "nonexistent-backend"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--nodes", "8", "--pods", "8", "--pod-groups", "2",
         "--nodegroups", "2", "--require-tpu"],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["value"] is None
    assert "error" in doc and "--require-tpu" in doc["error"]


def test_probe_backend_contains_broken_discovery(monkeypatch):
    bench = _load_bench()
    monkeypatch.setenv("JAX_PLATFORMS", "nonexistent-backend")
    assert bench.probe_backend(60) is None


def test_init_budget_clamps_and_exhausts():
    bench = _load_bench()
    t = {"now": 100.0}
    budget = bench.InitBudget(total_s=30, clock=lambda: t["now"])
    assert budget.clamp(120) == 30          # stage timeout bounded by budget
    t["now"] = 125.0
    assert budget.clamp(120) == 5           # remaining shrinks monotonically
    t["now"] = 131.0
    with pytest.raises(TimeoutError):
        budget.clamp(120)                   # exhausted: degrade, don't start
    assert budget.remaining() == 0.0


def test_with_retries_stops_at_deadline():
    """The retry ladder must not compound past the init budget: once the
    next backoff would cross the deadline, the last error surfaces
    immediately (a hung tunnel degrades in minutes, not 5×120 s)."""
    bench = _load_bench()
    t = {"now": 0.0}
    delays = []

    def dead():
        t["now"] += 10.0            # each attempt burns 10 "seconds"
        raise RuntimeError("tunnel hang")

    def sleep(s):
        delays.append(s)
        t["now"] += s

    with pytest.raises(RuntimeError):
        bench.with_retries(dead, "probe", attempts=10, backoff_s=8,
                           sleep=sleep, deadline=30.0,
                           clock=lambda: t["now"])
    # attempt 1 at t=10 (sleep 8 → t=18), attempt 2 at t=28: next backoff 16
    # would land at 44 > 30 → stop. NOT 10 attempts.
    assert delays == [8]


def test_with_timeout_accepts_callable_seconds():
    bench = _load_bench()
    calls = []

    def secs():
        calls.append(1)
        return 5.0

    assert bench.with_timeout(lambda: 7, seconds=secs)() == 7
    assert calls  # re-evaluated per attempt (budget-aware timeouts)


def test_bench_small_run_on_cpu_produces_metric():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--nodes", "64", "--pods", "128", "--pod-groups", "4",
         "--nodegroups", "2", "--max-new-nodes", "16",
         "--iters", "1", "--chain", "3", "--e2e-loops", "4"],
        capture_output=True, text=True, env=env, timeout=560, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-800:]
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["unit"] == "ms"
    assert doc["value"] is not None and doc["value"] > 0
    assert "error" not in doc


def test_with_timeout_raises_on_hang():
    bench = _load_bench()
    import time as _time

    def hang():
        _time.sleep(30)

    wrapped = bench.with_timeout(hang, seconds=0.2)
    with pytest.raises(TimeoutError):
        wrapped()

    def quick():
        return 42

    assert bench.with_timeout(quick, seconds=5)() == 42


def test_hang_then_recover_via_retries():
    bench = _load_bench()
    calls = {"n": 0}
    import time as _time

    def flaky_hang():
        calls["n"] += 1
        if calls["n"] == 1:
            _time.sleep(30)   # first attempt: tunnel hang
        return "ok"

    out = bench.with_retries(bench.with_timeout(flaky_hang, seconds=0.2),
                             "probe", attempts=3, backoff_s=0.01,
                             sleep=lambda s: None)
    assert out == "ok" and calls["n"] == 2


@pytest.mark.slow
def test_marshal_cache_zero_gxg_rebuild_on_unchanged_cluster():
    """Steady-state microbenchmark for the constrained-tier marshal cache:
    on an UNCHANGED cluster, the second plan() cycle must do zero G×G
    rebuild work — the composition fingerprint hits and only the per-call
    count-plane copies remain (acceptance criterion of the host-path PR)."""
    import numpy as np

    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.scaledown import native_confirm
    from kubernetes_autoscaler_tpu.core.scaledown.planner import Planner
    from kubernetes_autoscaler_tpu.models.api import (
        AffinityTerm,
        TopologySpreadConstraint,
    )
    from kubernetes_autoscaler_tpu.models.encode import encode_cluster
    from kubernetes_autoscaler_tpu.simulator.drainability.rules import (
        DrainOptions,
        apply_drainability,
    )
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    if not native_confirm.available():
        pytest.skip("native toolchain unavailable")
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=16000, mem_mib=65536)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=1000)
    nodes, pods = [], []
    for i in range(120):
        nd = build_test_node(f"n{i}", cpu_milli=16000, mem_mib=65536,
                             zone=["za", "zb", "zc"][i % 3])
        fake.add_existing_node("ng1", nd)
        nodes.append(nd)
        for j in range(2):
            app = f"a{(i + j) % 8}"
            p = build_test_pod(f"p{i}-{j}", cpu_milli=900, mem_mib=512,
                               owner_name=f"rs-{app}", node_name=f"n{i}",
                               labels={"app": app})
            p.phase = "Running"
            if (i + j) % 2:
                p.topology_spread = [TopologySpreadConstraint(
                    max_skew=4, topology_key="topology.kubernetes.io/zone",
                    match_labels={"app": app})]
            else:
                p.anti_affinity = [AffinityTerm(
                    match_labels={"app": app},
                    topology_key="kubernetes.io/hostname")]
            fake.add_pod(p)
            pods.append(p)
    enc = encode_cluster(nodes, pods,
                         node_group_ids={nd.name: 0 for nd in nodes},
                         node_bucket=64, group_bucket=64)
    apply_drainability(enc, DrainOptions(), now=0.0)
    opts = AutoscalingOptions(
        max_scale_down_parallelism=200, max_drain_parallelism=200,
        max_empty_bulk_delete=200,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=0.0, scale_down_unready_time_s=0.0))
    planner = Planner(fake.provider, opts)
    planner.update(enc, nodes, now=1000.0)
    planner.nodes_to_delete(enc, nodes, now=1000.0)
    assert planner.marshal_cache_misses == 1, "cold loop builds the matrices"
    gxg_before = planner.marshal_cache_misses
    import time as _time

    t0 = _time.perf_counter()
    planner.update(enc, nodes, now=1001.0)
    planner.nodes_to_delete(enc, nodes, now=1001.0)
    warm_s = _time.perf_counter() - t0
    assert planner.marshal_cache_misses == gxg_before, \
        "unchanged cluster must not rebuild the G×G matrices"
    assert planner.marshal_cache_hits >= 1
    assert planner.elig_cache_misses == 1 and planner.elig_cache_hits >= 1
    # breathing room only — the real assertion is the counter above
    assert warm_s < 30.0
