"""Reference-mirror scenario benchmarks as correctness tests.

These reproduce the reference's two RunOnce microbenchmark scenarios as
assertions (core/bench/benchmark_runonce_test.go):

  * BenchmarkRunOnceScaleUp (:493-503, setup :393-418): N pending pods,
    one node group scaling 0 -> N/50 where each node holds 50 pods — the
    whole demand must be satisfied in one RunOnce.
  * BenchmarkRunOnceScaleDown (:505-520, setup :424-453): a fleet at 40%
    utilization must consolidate — 60% of the nodes drain onto the other
    40% in one RunOnce (the reference asserts 240 of 400 tainted).

REFERENCE scale runs by DEFAULT (each scenario is seconds on the virtual CPU
mesh); KA_TPU_BENCH_FULL=0 opts down to reduced shapes for tiny machines.
"""

import os

from kubernetes_autoscaler_tpu.config.options import (
    AutoscalingOptions,
    NodeGroupDefaults,
)
from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
from kubernetes_autoscaler_tpu.utils.testing import build_test_node, build_test_pod

FULL = os.environ.get("KA_TPU_BENCH_FULL", "1") == "1"


def test_runonce_scale_up_benchmark_scenario():
    """One node group 0->N, 50 pods per node (pods-slot constrained)."""
    pods_total = 10_000 if FULL else 500
    pods_per_node = 50
    want_nodes = pods_total // pods_per_node

    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=200 * pods_per_node + 1000,
                           mem_mib=128 * pods_per_node + 1024, pods=pods_per_node)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=max(want_nodes, 1000))
    for i in range(pods_total):
        fake.add_pod(build_test_pod(f"p{i}", cpu_milli=200, mem_mib=128,
                                    owner_name="rs"))
    opts = AutoscalingOptions(
        node_shape_bucket=64,
        group_shape_bucket=16,
        max_new_nodes_static=max(2 * want_nodes, 32),
        max_pods_per_node=pods_per_node + 4,
        drain_chunk=8,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=0.0, scale_down_unready_time_s=0.0),
    )
    a = StaticAutoscaler(fake.provider, fake, options=opts, eviction_sink=fake)
    status = a.run_once(now=1000.0)
    assert status.scale_up is not None and status.scale_up.scaled_up
    assert status.scale_up.increases == {"ng1": want_nodes}
    assert len(fake.nodes) == want_nodes


def test_runonce_scale_down_benchmark_scenario():
    """Fleet at 40% utilization consolidates: 60% of nodes drain in one
    RunOnce onto the remaining 40% (reference: 240 of 400 tainted)."""
    n_nodes = 400 if FULL else 40
    pods_per_node = 2          # 2 x 2000m on a 10000m node = 40% utilization
    want_deleted = int(n_nodes * 0.6)

    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=10_000, mem_mib=32_768,
                           pods=16)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=2 * n_nodes)
    for i in range(n_nodes):
        fake.add_existing_node("ng1", build_test_node(
            f"n{i}", cpu_milli=10_000, mem_mib=32_768, pods=16))
        for j in range(pods_per_node):
            fake.add_pod(build_test_pod(
                f"p{i}-{j}", cpu_milli=2000, mem_mib=512,
                owner_name=f"rs{i % 7}", node_name=f"n{i}"))
    opts = AutoscalingOptions(
        node_shape_bucket=64,
        group_shape_bucket=16,
        max_new_nodes_static=32,
        max_pods_per_node=16,
        drain_chunk=8,
        max_scale_down_parallelism=n_nodes,
        max_drain_parallelism=n_nodes,
        max_empty_bulk_delete=n_nodes,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=0.0, scale_down_unready_time_s=0.0),
    )
    a = StaticAutoscaler(fake.provider, fake, options=opts, eviction_sink=fake)
    status = a.run_once(now=1000.0)
    deleted = status.scale_down_deleted
    # Identical pods: first-fit consolidation is optimal, exactly 60% drain
    # (each survivor fills 2 own + 3 received = 5 x 2000m = 100%).
    assert len(deleted) == want_deleted, f"deleted {len(deleted)} of {n_nodes}"
    assert len(fake.nodes) == n_nodes - want_deleted


def test_consolidation_destinations_are_survivors():
    """A destination chosen early in the confirmation pass can itself be
    deleted later; the plan must report each pod's FINAL landing node."""
    from kubernetes_autoscaler_tpu.core.scaledown.planner import Planner
    from kubernetes_autoscaler_tpu.models.encode import encode_cluster
    from kubernetes_autoscaler_tpu.simulator.drainability.rules import (
        apply_drainability,
    )

    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=10_000, mem_mib=32_768, pods=16)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=100)
    nodes = []
    pods = []
    for i in range(8):
        nd = build_test_node(f"n{i}", cpu_milli=10_000, mem_mib=32_768, pods=16)
        fake.add_existing_node("ng1", nd)
        nodes.append(fake.nodes[nd.name])
        for j in range(2):
            p = build_test_pod(f"p{i}-{j}", cpu_milli=2000, mem_mib=512,
                               owner_name=f"rs{i % 3}", node_name=f"n{i}")
            fake.add_pod(p)
            pods.append(p)
    enc = encode_cluster(nodes, pods, node_bucket=64, group_bucket=16)
    apply_drainability(enc)
    opts = AutoscalingOptions(
        node_shape_bucket=64, group_shape_bucket=16, max_new_nodes_static=32,
        max_pods_per_node=16, drain_chunk=8,
        max_scale_down_parallelism=16, max_drain_parallelism=16,
        max_empty_bulk_delete=16,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=0.0, scale_down_unready_time_s=0.0),
    )
    planner = Planner(fake.provider, opts)
    planner.update(enc, nodes, now=1000.0)
    plan = planner.nodes_to_delete(enc, nodes, now=1000.0)
    # 16 pods / (5 per survivor: 2 own + 3 received) -> 4 survivors, 4 deleted
    assert len(plan) == 4
    deleted_idx = {i for i, nd in enumerate(nodes)
                   if nd.name in {r.node.name for r in plan}}
    for r in plan:
        for slot, d in r.destinations.items():
            assert d not in deleted_idx, (
                f"{r.node.name} pod slot {slot} routed to deleted node idx {d}")


