"""Binpacking estimator: mirrors the reference's TestBinpackingEstimate shapes
(estimator/binpacking_estimator_test.go:66) plus multi-nodegroup batching."""

import jax.numpy as jnp
import numpy as np

from kubernetes_autoscaler_tpu.models.cluster_state import DEFAULT_DIMS
from kubernetes_autoscaler_tpu.models.encode import encode_cluster, encode_node_groups
from kubernetes_autoscaler_tpu.ops.binpack import estimate_all
from kubernetes_autoscaler_tpu.utils.testing import build_test_node, build_test_pod


def encode_world(pods, templates):
    enc = encode_cluster([], pods)
    groups = encode_node_groups(templates, enc.registry, enc.zone_table)
    return enc, groups


def est(pods, templates, max_new=64):
    enc, groups = encode_world(pods, templates)
    return enc, estimate_all(enc.specs, groups, DEFAULT_DIMS, max_new)


def test_uniform_pods_pack_exactly():
    # 10 pods × (500m, 1000Mi) onto 5-CPU/5000Mi templates → 10 per bin by
    # cpu (5000/500), 5 per bin by mem (5000/1000) → mem-bound: 5/node → 2 nodes.
    pods = [build_test_pod(f"p{i}", cpu_milli=500, mem_mib=1000, owner_name="rs")
            for i in range(10)]
    tmpl = build_test_node("t", cpu_milli=5000, mem_mib=5000)
    enc, r = est(pods, [(tmpl, 100, 1.0)])
    assert int(r.node_count[0]) == 2
    assert int(r.scheduled[0].sum()) == 10
    ppn = np.asarray(r.pods_per_node[0])
    assert list(ppn[:2]) == [5, 5]


def test_pod_count_capacity_limits():
    pods = [build_test_pod(f"p{i}", cpu_milli=1, mem_mib=1, owner_name="rs")
            for i in range(30)]
    tmpl = build_test_node("t", cpu_milli=10000, mem_mib=10000, pods=10)
    enc, r = est(pods, [(tmpl, 100, 1.0)])
    assert int(r.node_count[0]) == 3  # pods-capacity bound


def test_max_new_nodes_truncates():
    pods = [build_test_pod(f"p{i}", cpu_milli=900, mem_mib=100, owner_name="rs")
            for i in range(10)]
    tmpl = build_test_node("t", cpu_milli=1000, mem_mib=4096)
    enc, r = est(pods, [(tmpl, 4, 1.0)])  # group allows only 4 more nodes
    assert int(r.node_count[0]) == 4
    assert int(r.scheduled[0].sum()) == 4


def test_pod_too_big_for_template():
    pods = [build_test_pod("p", cpu_milli=8000, mem_mib=100, owner_name="rs")]
    tmpl = build_test_node("t", cpu_milli=4000, mem_mib=4096)
    enc, r = est(pods, [(tmpl, 10, 1.0)])
    assert int(r.node_count[0]) == 0
    assert int(r.scheduled[0].sum()) == 0


def test_multi_nodegroup_batched_options():
    pods = [build_test_pod(f"p{i}", cpu_milli=1000, mem_mib=512, owner_name="rs")
            for i in range(8)]
    small = build_test_node("small", cpu_milli=2000, mem_mib=4096)
    big = build_test_node("big", cpu_milli=8000, mem_mib=16384)
    gpuish = build_test_node("sel", cpu_milli=8000, mem_mib=16384,
                             labels={"pool": "special"})
    enc, r = est(pods, [(small, 100, 1.0), (big, 100, 3.5), (gpuish, 100, 9.0)])
    assert int(r.node_count[0]) == 4   # 2 pods per small node
    assert int(r.node_count[1]) == 1   # 8 pods fit one big node
    assert int(r.node_count[2]) == 1
    assert int(r.scheduled[1].sum()) == 8


def test_selector_respects_template_labels():
    pods = [build_test_pod(f"p{i}", cpu_milli=100, mem_mib=64, owner_name="rs",
                           node_selector={"pool": "special"}) for i in range(3)]
    plain = build_test_node("plain", cpu_milli=4000, mem_mib=4096)
    special = build_test_node("special", cpu_milli=4000, mem_mib=4096,
                              labels={"pool": "special"})
    enc, r = est(pods, [(plain, 10, 1.0), (special, 10, 1.0)])
    assert int(r.node_count[0]) == 0
    assert int(r.node_count[1]) == 1
    assert not bool(np.asarray(r.template_fits)[0].any())


def test_mixed_groups_first_fit_decreasing():
    # Large pods placed first; small ones backfill — classic FFD outcome.
    pods = [build_test_pod(f"big{i}", cpu_milli=3000, mem_mib=100, owner_name="big")
            for i in range(2)]
    pods += [build_test_pod(f"small{i}", cpu_milli=1000, mem_mib=100, owner_name="small")
             for i in range(2)]
    tmpl = build_test_node("t", cpu_milli=4000, mem_mib=4096)
    enc, r = est(pods, [(tmpl, 10, 1.0)])
    # FFD: big(3)+small(1) per node → 2 nodes; naive order could need 3.
    assert int(r.node_count[0]) == 2
    assert int(r.scheduled[0].sum()) == 4
