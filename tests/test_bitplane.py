"""Bit-packed predicate planes (ops/bitplane) and the bit-packed batched
fetch path (ops/hostfetch): round-trips are bit-exact, the transfer-byte
counters measure the ~8× compression, and the PR 4 reason-plane invariant
`feasible ⇔ reason_bits == 0` survives a pack/unpack round trip bit-for-bit.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubernetes_autoscaler_tpu.metrics.phases import PhaseStats
from kubernetes_autoscaler_tpu.ops import bitplane
from kubernetes_autoscaler_tpu.ops.hostfetch import (
    fetch_pytree,
    fetch_pytree_async,
)


@pytest.mark.parametrize("seed", range(4))
def test_group_bits_round_trip_device(seed):
    rng = np.random.default_rng(seed)
    g = int(rng.integers(1, 80))
    n = int(rng.integers(1, 200))
    mask = rng.random((g, n)) < rng.uniform(0.05, 0.95)
    words = bitplane.pack_group_bits(jnp.asarray(mask))
    assert words.shape == (bitplane.words_for(g), n)
    assert words.dtype == jnp.int32
    back = np.asarray(bitplane.unpack_group_bits(words, g))
    np.testing.assert_array_equal(back, mask)


def test_group_bits_device_and_numpy_agree():
    rng = np.random.default_rng(7)
    mask = rng.random((67, 33)) < 0.5           # G straddles a word boundary
    dev = np.asarray(bitplane.pack_group_bits(jnp.asarray(mask)))
    host = bitplane.pack_group_bits_np(mask)
    np.testing.assert_array_equal(dev, host)
    np.testing.assert_array_equal(
        bitplane.unpack_group_bits_np(host, 67), mask)


def test_group_bits_batched_axis():
    rng = np.random.default_rng(9)
    mask = rng.random((3, 40, 17)) < 0.4
    words = bitplane.pack_group_bits(jnp.asarray(mask))
    assert words.shape == (3, 2, 17)
    np.testing.assert_array_equal(
        np.asarray(bitplane.unpack_group_bits(words, 40)), mask)


@pytest.mark.parametrize("n", [0, 1, 31, 32, 33, 257])
def test_flat_bits_round_trip(n):
    rng = np.random.default_rng(n)
    flat = rng.random((n,)) < 0.5
    words = np.asarray(bitplane.pack_flat_bits(jnp.asarray(flat)))
    np.testing.assert_array_equal(
        bitplane.unpack_flat_bits_np(words, n), flat)


# ---- the bit-packed batched fetch ----


def _world(n_nodes=10, n_pods=24):
    from kubernetes_autoscaler_tpu.models.encode import encode_cluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    nodes = [build_test_node(f"n{i}", cpu_milli=4000, mem_mib=8192,
                             labels={"disk": "ssd" if i % 2 else "hdd"})
             for i in range(n_nodes)]
    pods = [build_test_pod(f"p{i}", cpu_milli=250 + 250 * (i % 3),
                           mem_mib=256, owner_name=f"rs{i % 4}",
                           node_selector={"disk": "ssd"} if i % 5 == 0 else {})
            for i in range(n_pods)]
    return encode_cluster(nodes, pods, node_bucket=16, group_bucket=16)


def test_fetch_pytree_bool_planes_bit_exact():
    """Mixed pytree (bool planes + ints + floats) comes home byte-identical
    to per-leaf device_get, with bools riding bit-packed."""
    from kubernetes_autoscaler_tpu.ops import predicates

    enc = _world()
    mask = predicates.feasibility_mask(enc.nodes, enc.specs)
    tree = {
        "mask": mask,
        "valid": enc.specs.valid,
        "req": enc.specs.req,
        "waste": jnp.linspace(0.0, 1.0, 7, dtype=jnp.float32),
        "reason": predicates.reason_mask(enc.nodes, enc.specs),
    }
    got = fetch_pytree(tree)
    for key, leaf in tree.items():
        ref = np.asarray(jax.device_get(leaf))
        assert got[key].dtype == ref.dtype, key
        np.testing.assert_array_equal(got[key], ref, err_msg=key)


def test_fetch_pytree_byte_counters_show_plane_compression():
    """The moved/logical counters: a bool-dominated fetch moves ≥4× fewer
    bytes than the unpacked layout (the acceptance criterion bench.py
    asserts in smoke mode rides exactly these counters)."""
    from kubernetes_autoscaler_tpu.ops import predicates

    enc = _world()
    mask = predicates.feasibility_mask(enc.nodes, enc.specs)
    phases = PhaseStats(owner="test")
    got = fetch_pytree({"mask": mask, "valid": enc.specs.valid}, phases=phases)
    moved = phases.events["batched_fetch_bytes_moved"]
    logical = phases.events["batched_fetch_bytes_logical"]
    g, n = mask.shape
    assert logical == g * n + g                  # 1 byte per bool, old layout
    assert moved <= bitplane.words_for(g * n + g) * 4 + 4
    assert logical / moved >= 4.0
    np.testing.assert_array_equal(got["mask"],
                                  np.asarray(jax.device_get(mask)))


def test_fetch_pytree_async_round_trip_and_span():
    """The double-buffer handle: correct data, idempotent get(), and a
    `fetch` span (async=true) that stays OPEN until harvest so overlapped
    work nests inside it on the timeline."""
    from kubernetes_autoscaler_tpu.metrics import trace

    enc = _world()
    tracer = trace.Tracer()
    with trace.active(tracer):
        h = fetch_pytree_async({"req": enc.specs.req,
                                "valid": enc.specs.valid})
        with tracer.span("encode", cat="test"):
            pass                                  # the overlapped work
        out = h.get()
        assert h.get() is out                     # idempotent
    np.testing.assert_array_equal(out["req"],
                                  np.asarray(jax.device_get(enc.specs.req)))
    np.testing.assert_array_equal(out["valid"],
                                  np.asarray(jax.device_get(enc.specs.valid)))
    spans = {s[0]: s for s in tracer.spans}
    fetch_span, encode_span = spans["fetch"], spans["encode"]
    assert (fetch_span[5] or {}).get("async") is True
    # the encode span ran INSIDE the open fetch window — interval containment
    f0, f1 = fetch_span[2], fetch_span[2] + fetch_span[3]
    e0, e1 = encode_span[2], encode_span[2] + encode_span[3]
    assert f0 <= e0 and e1 <= f1


def test_reason_invariant_survives_bit_packing():
    """feasible ⇔ reason_bits == 0, bit-for-bit, THROUGH the packed plane:
    pack(feasibility) → unpack must still equal (reason_mask == 0) on fuzzed
    worlds (the PR 4 invariant with the PR 6 layout)."""
    from kubernetes_autoscaler_tpu.models.encode import encode_cluster
    from kubernetes_autoscaler_tpu.ops import predicates
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    rng = np.random.default_rng(3)
    for trial in range(3):
        nodes = [
            build_test_node(
                f"n{i}", cpu_milli=int(rng.integers(500, 8000)),
                mem_mib=int(rng.integers(256, 16384)),
                pods=int(rng.integers(1, 20)),
                labels={"disk": "ssd" if rng.random() < 0.5 else "hdd"},
                gpus=int(rng.integers(0, 2)),
            )
            for i in range(int(rng.integers(2, 12)))
        ]
        pods = [
            build_test_pod(
                f"p{i}", cpu_milli=int(rng.integers(100, 6000)),
                mem_mib=int(rng.integers(64, 8192)),
                owner_name=f"rs{int(rng.integers(0, 5))}",
                node_selector={"disk": "ssd"} if rng.random() < 0.3 else {},
                gpus=int(rng.integers(0, 2)),
            )
            for i in range(int(rng.integers(3, 30)))
        ]
        enc = encode_cluster(nodes, pods, node_bucket=16, group_bucket=16)
        feas = np.asarray(predicates.feasibility_mask(enc.nodes, enc.specs))
        bits = np.asarray(predicates.reason_mask(enc.nodes, enc.specs))
        packed = bitplane.pack_group_bits(jnp.asarray(feas))
        unpacked = np.asarray(
            bitplane.unpack_group_bits(packed, feas.shape[0]))
        np.testing.assert_array_equal(unpacked, feas,
                                      err_msg=f"round trip, trial {trial}")
        np.testing.assert_array_equal(unpacked, bits == 0,
                                      err_msg=f"invariant, trial {trial}")


def test_planner_async_prefetch_overlaps_screen():
    """Planner.update's candidate-pool prefetch: the sv planes arrive
    through the async handle and the plan is unchanged vs a synchronous
    fetch (the overlap is a latency property; correctness is equality)."""
    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.scaledown.planner import Planner
    from kubernetes_autoscaler_tpu.models.encode import encode_cluster
    from kubernetes_autoscaler_tpu.simulator.drainability.rules import (
        apply_drainability,
    )
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=16000, mem_mib=65536, pods=110)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=64)
    nodes, pods = [], []
    for i in range(12):
        nd = build_test_node(f"n{i}", cpu_milli=16000, mem_mib=65536, pods=110)
        fake.add_existing_node("ng1", nd)
        nodes.append(nd)
        for j in range(2):
            p = build_test_pod(f"p{i}-{j}", cpu_milli=1600, mem_mib=512,
                               owner_name=f"rs{i % 3}", node_name=nd.name)
            fake.add_pod(p)
            pods.append(p)
    enc = encode_cluster(nodes, pods, node_bucket=16, group_bucket=16)
    apply_drainability(enc)
    opts = AutoscalingOptions(
        node_shape_bucket=16, group_shape_bucket=16, max_pods_per_node=16,
        drain_chunk=16,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=0.0, scale_down_unready_time_s=0.0))
    planner = Planner(fake.provider, opts)
    state = planner.update(enc, nodes, now=1000.0)
    assert state.unneeded                        # low-util world drains

    # the async path itself, on a guaranteed miss (nodes.alloc is in
    # _ALWAYS_FETCH, never mirror-served): one async transfer counted, the
    # harvested data byte-identical to a direct device read, and the
    # blocking remainder recorded into the fetch phase totals
    before_async = planner.phases.events.get("batched_fetch_async", 0)
    before_fetch = planner.phases.counts.get("fetch", 0)
    h = planner._fetch_host_async(enc, {"nodes.alloc": enc.nodes.alloc})
    out = h.get()
    assert h.get().keys() == out.keys()                     # idempotent
    np.testing.assert_array_equal(
        out["nodes.alloc"], np.asarray(jax.device_get(enc.nodes.alloc)))
    assert planner.phases.events["batched_fetch_async"] == before_async + 1
    assert planner.phases.counts["fetch"] == before_fetch + 1
    # mirror hits stay free: a mirror-served key issues NO async transfer
    from kubernetes_autoscaler_tpu.core.scaledown.planner import _mirror_hit

    if _mirror_hit(enc, "nodes.valid", enc.nodes.valid):
        n_async = planner.phases.events["batched_fetch_async"]
        h2 = planner._fetch_host_async(enc, {"nodes.valid": enc.nodes.valid})
        np.testing.assert_array_equal(
            h2.get()["nodes.valid"],
            np.asarray(jax.device_get(enc.nodes.valid)))
        assert planner.phases.events["batched_fetch_async"] == n_async
