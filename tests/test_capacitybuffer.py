"""Capacity buffers + pod injection.

Reference analogs: capacitybuffer/ controller+translator tests and
processors/podinjection tests (SURVEY.md §2.6, §2.7).
"""

from kubernetes_autoscaler_tpu.capacitybuffer.api import (
    ACTIVE_PROVISIONING_STRATEGY,
    CapacityBuffer,
)
from kubernetes_autoscaler_tpu.capacitybuffer.controller import (
    BufferController,
    BufferPodListProcessor,
)
from kubernetes_autoscaler_tpu.capacitybuffer.translators import (
    fake_pods_for,
    is_buffer_pod,
    translate_buffer,
)
from kubernetes_autoscaler_tpu.config.options import (
    AutoscalingOptions,
    NodeGroupDefaults,
)
from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from kubernetes_autoscaler_tpu.models.api import Workload
from kubernetes_autoscaler_tpu.processors.podinjection import (
    PodInjectionProcessor,
    injected_pods_for,
)
from kubernetes_autoscaler_tpu.processors.processors import AutoscalingProcessors
from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
from kubernetes_autoscaler_tpu.utils.testing import build_test_node, build_test_pod


def test_translate_pod_template_buffer():
    buf = CapacityBuffer("b1", pod_template=build_test_pod("tmpl", cpu_milli=500),
                         replicas=3)
    translate_buffer(buf)
    assert buf.status.ready()
    pods = fake_pods_for(buf)
    assert len(pods) == 3
    assert all(is_buffer_pod(p) for p in pods)
    assert all(p.phase == "Pending" and not p.node_name for p in pods)
    assert pods[0].name == "capacity-buffer-b1-0"
    assert pods[0].owner.kind == "CapacityBuffer"


def test_translate_percentage_of_scalable():
    w = Workload("Deployment", "web", replicas=10,
                 template=build_test_pod("tmpl", cpu_milli=250))
    buf = CapacityBuffer("b2", scalable_ref=w, percentage=25.0)
    translate_buffer(buf)
    assert buf.status.replicas == 3          # ceil(10 * 0.25)
    buf2 = CapacityBuffer("b3", scalable_ref=w, percentage=1.0,
                          limits_min_replicas=2)
    translate_buffer(buf2)
    assert buf2.status.replicas == 2         # min-replicas floor


def test_translate_rejects_bad_specs():
    buf = CapacityBuffer("bad")
    translate_buffer(buf)
    assert not buf.status.ready()
    assert buf.status.conditions["reason"] == "NoTemplateOrScalableRef"

    w = Workload("Deployment", "web", replicas=10)   # no template
    buf2 = CapacityBuffer("bad2", scalable_ref=w, percentage=50.0)
    translate_buffer(buf2)
    assert not buf2.status.ready()


def test_controller_strategy_filter():
    good = CapacityBuffer("a", pod_template=build_test_pod("t"), replicas=1)
    foreign = CapacityBuffer("b", pod_template=build_test_pod("t"), replicas=1,
                             provisioning_strategy="someone-elses-strategy")
    c = BufferController([good, foreign])
    pods = c.pending_pods()
    assert len(pods) == 1
    assert foreign.status.conditions["reason"] == "UnsupportedProvisioningStrategy"
    assert good.status.conditions["Provisioning"] == "True"


def test_injected_pods_fill_replica_gap():
    w = Workload("Job", "batch", uid="u1", replicas=5,
                 template=build_test_pod("tmpl", cpu_milli=100))
    existing = [
        build_test_pod("p0", owner_name="batch", owner_kind="Job"),
        build_test_pod("p1", owner_name="batch", owner_kind="Job"),
    ]
    fakes = injected_pods_for(w, existing)
    assert len(fakes) == 3
    assert fakes[0].owner.uid == "u1"
    # terminal pods don't count toward the existing total
    existing[0].phase = "Succeeded"
    assert len(injected_pods_for(w, existing)) == 4
    # no gap -> no injection
    w.replicas = 2
    existing[0].phase = "Running"
    assert injected_pods_for(w, existing) == []


def _opts(**kw):
    base = dict(
        scale_down_delay_after_add_s=0.0,
        node_shape_bucket=16, group_shape_bucket=16,
        max_new_nodes_static=32, max_pods_per_node=32, drain_chunk=8,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=0.0, scale_down_unready_time_s=0.0),
    )
    base.update(kw)
    return AutoscalingOptions(**base)


def test_runonce_buffer_provisions_headroom():
    """A buffer alone (zero real pending pods) must trigger scale-up."""
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=10)
    fake.add_existing_node("ng1", build_test_node("n1", cpu_milli=4000, mem_mib=8192))
    # headroom: 4 pods x 1500m won't fit the one existing (empty) node
    controller = BufferController([
        CapacityBuffer("head", pod_template=build_test_pod(
            "t", cpu_milli=1500, mem_mib=512), replicas=4),
    ])
    procs = AutoscalingProcessors.default()
    procs.pod_list_processors.append(BufferPodListProcessor(controller))
    a = StaticAutoscaler(fake.provider, fake, options=_opts(),
                         processors=procs, eviction_sink=fake)
    status = a.run_once(now=1000.0)
    assert status.scale_up is not None and status.scale_up.scaled_up
    # 4x1500m: 2 fit the existing node, 2 need one more 4-CPU node
    assert status.scale_up.increases == {"ng1": 1}


def test_runonce_pod_injection_prescales():
    """A Job with replicas=6 but only 1 created pod injects 5 fakes."""
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=10)
    fake.add_existing_node("ng1", build_test_node("n1", cpu_milli=4000, mem_mib=8192))
    fake.add_pod(build_test_pod("real-0", cpu_milli=1800, mem_mib=256,
                                owner_name="batch", owner_kind="Job"))
    fake.add_workload(Workload(
        "Job", "batch", uid="u1", replicas=6,
        template=build_test_pod("tmpl-pod", cpu_milli=1800, mem_mib=256,
                                owner_name="batch", owner_kind="Job"),
    ))
    procs = AutoscalingProcessors.default()
    procs.pod_list_processors.append(PodInjectionProcessor())
    a = StaticAutoscaler(fake.provider, fake, options=_opts(),
                         processors=procs, eviction_sink=fake)
    status = a.run_once(now=1000.0)
    assert status.scale_up is not None and status.scale_up.scaled_up
    # 6 pods x 1800m, 2 per 4-CPU node -> 3 nodes total, 1 exists -> +2
    assert status.scale_up.increases == {"ng1": 2}


def test_generation_tracking_skips_unchanged_specs():
    from kubernetes_autoscaler_tpu.capacitybuffer.api import CapacityBuffer
    from kubernetes_autoscaler_tpu.capacitybuffer.controller import BufferController

    calls = []
    buf = CapacityBuffer(name="b1",
                         pod_template=build_test_pod("t", cpu_milli=500, mem_mib=256),
                         replicas=2)
    c = BufferController([buf], status_sink=calls.append)
    assert len(c.reconcile()) == 1
    assert buf.status.observed_generation == buf.generation
    assert calls == [buf]
    # unchanged spec: no re-translation, no status write
    c.reconcile()
    assert calls == [buf]
    # spec mutation bumps generation -> re-translated and re-written
    buf.replicas = 5
    buf.bump()
    c.reconcile()
    assert len(calls) == 2
    assert buf.status.replicas == 5


def test_headroom_quota_clamps_buffer_replicas():
    from kubernetes_autoscaler_tpu.capacitybuffer.api import CapacityBuffer
    from kubernetes_autoscaler_tpu.capacitybuffer.controller import BufferController

    big = CapacityBuffer(name="big",
                         pod_template=build_test_pod("t", cpu_milli=1000, mem_mib=256),
                         replicas=10)
    c = BufferController([big], headroom_quota={"cpu": 3.0})
    pairs = c.active_with_replicas()
    assert pairs == [(big, 3)]                  # 3 cores / 1 core per pod
    assert big.status.conditions["reason"] == "LimitedByBufferQuota"
    assert big.status.replicas == 10            # spec-resolved value untouched
    assert len(c.pending_pods()) == 3
    # quota relaxes -> the clamp relaxes WITHOUT a spec bump (non-sticky)
    c.headroom_quota = {"cpu": 100.0}
    assert c.active_with_replicas() == [(big, 10)]
    assert "reason" not in big.status.conditions
    assert len(c.pending_pods()) == 10


def test_runonce_buffer_injection_drives_scale_up():
    from test_runonce import autoscaler_for

    from kubernetes_autoscaler_tpu.capacitybuffer.api import CapacityBuffer
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster

    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group("ng1", tmpl, min_size=1, max_size=10)
    fake.add_existing_node("ng1", build_test_node("seed", cpu_milli=4000,
                                                  mem_mib=8192))
    fake.add_capacity_buffer(CapacityBuffer(
        name="headroom",
        pod_template=build_test_pod("t", cpu_milli=1500, mem_mib=512),
        replicas=6))
    a = autoscaler_for(fake)
    status = a.run_once(now=1000.0)
    # 6 x 1500m headroom: seed holds 2, 4 need 2 new 4-CPU nodes
    assert status.scale_up is not None and status.scale_up.increases == {"ng1": 2}


def test_injection_flag_off_still_reconciles_statuses():
    from test_runonce import autoscaler_for

    from kubernetes_autoscaler_tpu.capacitybuffer.api import CapacityBuffer
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster

    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group("ng1", tmpl, min_size=1, max_size=10)
    fake.add_existing_node("ng1", build_test_node("seed", cpu_milli=4000,
                                                  mem_mib=8192))
    buf = CapacityBuffer(
        name="headroom",
        pod_template=build_test_pod("t", cpu_milli=1500, mem_mib=512),
        replicas=6)
    fake.add_capacity_buffer(buf)
    a = autoscaler_for(fake, capacity_buffer_pod_injection_enabled=False)
    status = a.run_once(now=1000.0)
    assert status.scale_up is None          # no injection
    assert buf.status.ready()               # but reconciliation still ran
    assert buf.status.replicas == 6


def test_fake_pod_identity_stable_across_loops():
    """Injected headroom/ProvReq pods keep OBJECT identity while their spec
    is unchanged — the incremental encoder relies on identity to skip
    re-lowering them every loop (round-4)."""
    buf = CapacityBuffer("hb", pod_template=build_test_pod(
        "tmpl", cpu_milli=500), replicas=3)
    translate_buffer(buf)
    first = fake_pods_for(buf)
    second = fake_pods_for(buf)
    assert [id(p) for p in first] == [id(p) for p in second]
    # a spec change (generation bump + re-translate) yields fresh objects
    buf.generation += 1
    buf.pod_template = build_test_pod("tmpl", cpu_milli=600)
    translate_buffer(buf)
    third = fake_pods_for(buf)
    assert [id(p) for p in third] != [id(p) for p in first]

    from kubernetes_autoscaler_tpu.provisioningrequest.api import (
        PodSet,
        ProvisioningRequest,
    )

    pr = ProvisioningRequest(
        name="pr1", pod_sets=[PodSet(
            template=build_test_pod("t", cpu_milli=100, mem_mib=64,
                                    owner_name="rs"), count=2)])
    assert [id(p) for p in pr.pods()] == [id(p) for p in pr.pods()]


def test_fake_pod_cache_prefix_stable_under_clamp_changes():
    """The quota clamp moves loop-to-loop; pods 0..n-1 must keep identity
    as it shrinks and grows (prefix-slice cache, round-4 review)."""
    buf = CapacityBuffer("hb2", pod_template=build_test_pod(
        "tmpl", cpu_milli=500), replicas=5)
    translate_buffer(buf)
    five = fake_pods_for(buf, replicas=5)
    three = fake_pods_for(buf, replicas=3)
    assert [id(p) for p in three] == [id(p) for p in five[:3]]
    five_again = fake_pods_for(buf, replicas=5)
    assert [id(p) for p in five_again] == [id(p) for p in five]
