"""Cloud-provider parity: external gRPC provider and the kwok/kubemark analog.

Reference counterparts: cloudprovider/externalgrpc (out-of-process provider
over gRPC), cloudprovider/kwok + the kubemark hollow-node harness
(proposals/scalability_tests.md), and deleteCreatedNodesWithErrors
(static_autoscaler.go:1081).
"""

import os

import pytest

from kubernetes_autoscaler_tpu.cloudprovider.external_grpc import (
    ExternalGrpcProvider,
    serve_cloud_provider,
)
from kubernetes_autoscaler_tpu.cloudprovider.kwok import KwokCluster
from kubernetes_autoscaler_tpu.config.options import (
    AutoscalingOptions,
    NodeGroupDefaults,
)
from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
from kubernetes_autoscaler_tpu.utils.testing import build_test_node, build_test_pod

# reference scale by default (the 1000-node kubemark claim runs in ~10s on
# the virtual mesh); KA_TPU_BENCH_FULL=0 opts down for tiny machines
FULL = os.environ.get("KA_TPU_BENCH_FULL", "1") == "1"


def make_options(**kw):
    base = dict(
        node_shape_bucket=16, group_shape_bucket=16, max_new_nodes_static=32,
        max_pods_per_node=32, drain_chunk=8,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=0.0, scale_down_unready_time_s=0.0),
    )
    base.update(kw)
    return AutoscalingOptions(**base)


@pytest.fixture
def grpc_world():
    """A FakeCluster whose provider is reached over a real gRPC hop."""
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=10)
    server, port = serve_cloud_provider(fake.provider)
    server.start()
    try:
        yield fake, ExternalGrpcProvider(port)
    finally:
        server.stop(None)


def test_external_grpc_surface(grpc_world):
    fake, ext = grpc_world
    groups = ext.node_groups()
    assert [g.id() for g in groups] == ["ng1"]
    g = groups[0]
    assert (g.min_size(), g.max_size(), g.target_size()) == (0, 10, 0)
    tmpl = g.template_node_info()
    assert tmpl.capacity["cpu"] == 4.0
    g.increase_size(2)
    assert g.target_size() == 2          # cache invalidated by the mutation
    assert len(fake.nodes) == 2          # materialized server-side
    nd = list(fake.nodes.values())[0]
    back = ext.node_group_for_node(nd)
    assert back is not None and back.id() == "ng1"
    g.delete_nodes([nd])
    assert g.target_size() == 1


def test_external_grpc_full_runonce(grpc_world):
    """A whole RunOnce with every cloud call crossing the gRPC boundary."""
    fake, ext = grpc_world
    for i in range(4):
        fake.add_pod(build_test_pod(f"p{i}", cpu_milli=1500, mem_mib=512,
                                    owner_name="rs"))
    a = StaticAutoscaler(ext, fake, options=make_options(), eviction_sink=fake)
    status = a.run_once(now=1000.0)
    assert status.scale_up is not None and status.scale_up.scaled_up
    assert status.scale_up.increases == {"ng1": 2}
    assert len(fake.nodes) == 2
    # instances now exist: nodes() must round-trip and a SECOND loop (which
    # scans g.nodes() for create-errors) must not crash
    insts = ext.node_groups()[0].nodes()
    assert len(insts) == 2 and all(i.name for i in insts)
    status2 = a.run_once(now=1010.0)
    assert status2.ran and len(fake.nodes) == 2


def test_kwok_boot_delay_counts_upcoming():
    """Instances in flight register late; the registry must report them as
    upcoming so the next loop doesn't double-scale."""
    kwok = KwokCluster(boot_delay_s=30.0)
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    kwok.add_node_group("ng1", tmpl, min_size=0, max_size=10)
    for i in range(4):
        kwok.add_pod(build_test_pod(f"p{i}", cpu_milli=1500, mem_mib=512,
                                    owner_name="rs"))
    a = StaticAutoscaler(kwok.provider, kwok, options=make_options(),
                         eviction_sink=kwok)
    kwok.advance_to(1000.0)
    st1 = a.run_once(now=1000.0)
    assert st1.scale_up.increases == {"ng1": 2}
    assert len(kwok.nodes) == 0                       # still booting
    assert a.cluster_state.upcoming_nodes() == {"ng1": 2}
    # second loop before boot completes: no double scale-up
    kwok.advance_to(1010.0)
    st2 = a.run_once(now=1010.0)
    assert st2.scale_up is None or not st2.scale_up.scaled_up
    # boot completes; pods land
    kwok.advance_to(1035.0)
    assert len(kwok.nodes) == 2
    st3 = a.run_once(now=1035.0)
    assert st3.pending_pods == 0


def test_kwok_failed_boot_reaped_and_backed_off():
    kwok = KwokCluster(boot_delay_s=5.0)
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    kwok.add_node_group("ng1", tmpl, min_size=0, max_size=10)
    kwok.fail_next("ng1", 2)
    for i in range(4):
        kwok.add_pod(build_test_pod(f"p{i}", cpu_milli=1500, mem_mib=512,
                                    owner_name="rs"))
    a = StaticAutoscaler(kwok.provider, kwok, options=make_options(),
                         eviction_sink=kwok)
    kwok.advance_to(1000.0)
    st1 = a.run_once(now=1000.0)
    assert st1.scale_up.increases == {"ng1": 2}
    g = kwok.provider.node_groups()[0]
    assert g.target_size() == 2
    # next loop: errored instances reaped (target back to 0), group backed off
    kwok.advance_to(1010.0)
    a.run_once(now=1010.0)
    assert g.target_size() == 0
    assert not any(i.error_class for i in g.nodes())
    assert a.cluster_state.backoff.is_backed_off("ng1", 1011.0)
    # once backoff expires, scale-up is attempted again
    later = 1010.0 + a.options.initial_node_group_backoff_s + 1.0
    kwok.advance_to(later)
    st3 = a.run_once(now=later)
    assert st3.scale_up is not None and st3.scale_up.scaled_up


def test_kubemark_scale_claim():
    """The GA scale claim (FAQ.md:148): 1000 nodes x 30 pods/node RunOnce.

    Default run uses 100 nodes to keep CPU CI fast; KA_TPU_BENCH_FULL=1 runs
    the full 1000."""
    n_nodes = 1000 if FULL else 100
    kwok = KwokCluster()
    tmpl = build_test_node("tmpl", cpu_milli=8000, mem_mib=65536, pods=110)
    g = kwok.add_node_group("ng1", tmpl, min_size=0, max_size=2 * n_nodes)
    g.increase_size(n_nodes)
    kwok.advance_to(0.0)
    assert len(kwok.nodes) == n_nodes
    kwok.saturate(pods_per_node=30, cpu_milli=250)   # 7500m of 8000m used
    assert len(kwok.pods) == 30 * n_nodes
    # add pending load requiring ~5% more nodes
    extra = max(n_nodes // 20, 1) * 8
    for i in range(extra):
        kwok.add_pod(build_test_pod(f"pend{i}", cpu_milli=900, mem_mib=512,
                                    owner_name="pend-rs"))
    a = StaticAutoscaler(
        kwok.provider, kwok,
        options=make_options(node_shape_bucket=256,
                             max_new_nodes_static=max(n_nodes // 8, 32),
                             max_pods_per_node=64),
        eviction_sink=kwok)
    status = a.run_once(now=100.0)
    assert status.scale_up is not None and status.scale_up.scaled_up
    added = sum(status.scale_up.increases.values())
    # 8 pending pods of 900m fit a fresh 8-CPU node -> extra/8 new nodes
    assert added == extra // 8
    kwok.advance_to(100.0)   # zero boot delay: instances register on tick
    assert len(kwok.nodes) == n_nodes + added
