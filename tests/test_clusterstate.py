"""Dedicated ClusterStateRegistry coverage (clusterstate/registry.py):
the scale-up-timeout → failed-scale-up → backoff → recovery path, plus the
ExponentialBackoff amortized sweep's growth bound (utils/backoff.py).

Reference counterpart: clusterstate/clusterstate_test.go (the
RegisterOrUpdateScaleUp / updateScaleRequests / backoff suites).
"""

from kubernetes_autoscaler_tpu.clusterstate.registry import (
    ClusterStateRegistry,
    ScaleUpRequest,
)
from kubernetes_autoscaler_tpu.config.options import AutoscalingOptions
from kubernetes_autoscaler_tpu.utils.backoff import ExponentialBackoff
from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
from kubernetes_autoscaler_tpu.utils.testing import build_test_node


def mk_registry(provision_s: float = 100.0, provision_delay_s: float = 0.0):
    fake = FakeCluster(provision_delay_s=provision_delay_s)
    tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=10)
    opts = AutoscalingOptions()
    opts.node_group_defaults.max_node_provision_time_s = provision_s
    return fake, ClusterStateRegistry(fake.provider, opts)


def group(fake, gid="ng1"):
    return next(g for g in fake.provider.node_groups() if g.id() == gid)


def nodes_of(fake):
    return fake.list_nodes()


# ---------------------------------------------------------------- requests


def test_register_scale_up_tracks_request_and_updates_on_repeat():
    fake, reg = mk_registry()
    g = group(fake)
    reg.register_scale_up(g, 2, now=100.0)
    req = reg.scale_up_requests["ng1"]
    assert (req.increase, req.time, req.expected_add_time) == (2, 100.0, 200.0)
    # a second burst merges and re-arms the provision clock
    reg.register_scale_up(g, 3, now=150.0)
    req = reg.scale_up_requests["ng1"]
    assert req.increase == 5 and req.expected_add_time == 250.0
    assert reg.last_scale_up_time == 150.0


def test_scale_up_fulfilled_clears_request_and_backoff():
    fake, reg = mk_registry()
    g = group(fake)
    g.increase_size(2)                    # materializes 2 ready nodes
    reg.register_scale_up(g, 2, now=100.0)
    reg.backoff.backoff("ng1", 90.0)      # pre-existing backoff must clear
    reg.update_nodes(nodes_of(fake), now=110.0)
    assert "ng1" not in reg.scale_up_requests
    assert not reg.backoff.is_backed_off("ng1", 110.0)
    assert reg.is_node_group_safe_to_scale_up(g, 110.0)


def test_scale_up_timeout_fails_and_backs_off_then_recovers():
    """The full ladder: request → provision timeout → failed-scale-up +
    exponential backoff (group stops winning scale-ups) → backoff expiry →
    the group is safe again."""
    # nodes never materialize before the provision deadline
    fake, reg = mk_registry(provision_s=100.0, provision_delay_s=10_000.0)
    g = group(fake)
    g.increase_size(2)                    # target 2, nothing registers
    reg.register_scale_up(g, 2, now=100.0)
    reg.update_nodes(nodes_of(fake), now=150.0)
    assert "ng1" in reg.scale_up_requests, "not expired yet"
    assert reg.is_node_group_safe_to_scale_up(g, 150.0)

    reg.update_nodes(nodes_of(fake), now=201.0)   # past expected_add_time
    assert "ng1" not in reg.scale_up_requests
    assert reg.failed_scale_ups["ng1"] == 201.0
    assert reg.backoff.is_backed_off("ng1", 201.0)
    assert not reg.is_node_group_safe_to_scale_up(g, 201.0), \
        "a timed-out group must stop winning scale-ups"

    # backoff expiry (default initial 300s): safe again
    until = 201.0 + reg.backoff.initial_s
    assert not reg.is_node_group_safe_to_scale_up(g, until - 1.0)
    assert reg.is_node_group_safe_to_scale_up(g, until + 1.0)


def test_repeat_failures_double_backoff_up_to_cap_and_reset_after_quiet():
    fake, reg = mk_registry()
    g = group(fake)
    b = reg.backoff
    now = 1000.0
    prev = 0.0
    for k in range(10):
        until = b.backoff("ng1", now)
        dur = until - now
        assert dur <= b.max_s
        if k and prev < b.max_s:
            assert dur == min(prev * 2, b.max_s), "ladder must double"
        prev = dur
        now = until + 1.0
    assert prev == b.max_s
    # quiet past the reset window starts the ladder fresh
    now += b.reset_timeout_s + 1.0
    assert b.backoff("ng1", now) - now == b.initial_s


def test_failed_scale_up_via_registry_counts_and_backs_off():
    fake, reg = mk_registry()
    g = group(fake)
    reg.register_scale_up(g, 1, now=100.0)
    reg.register_failed_scale_up(g, now=120.0)
    assert "ng1" not in reg.scale_up_requests
    assert reg.backoff.is_backed_off("ng1", 121.0)


def test_unregistered_nodes_tracked_and_upcoming_counted():
    fake, reg = mk_registry(provision_delay_s=10_000.0)
    g = group(fake)
    g.increase_size(3)
    reg.update_nodes(nodes_of(fake), now=100.0)
    assert len(reg.unregistered) == 0, \
        "a delayed provider reports no instances yet"
    assert reg.upcoming_nodes() == {"ng1": 3}


def test_acceptable_range_and_incorrect_size():
    fake, reg = mk_registry()
    g = group(fake)
    g.increase_size(2)
    reg.register_scale_up(g, 2, now=100.0)
    reg.update_nodes(nodes_of(fake), now=110.0)
    # 2 ready = target: fulfilled, range is exactly [target, target]
    rng = reg.acceptable_ranges["ng1"]
    assert rng.min_nodes <= 2 <= rng.max_nodes
    assert not reg.has_incorrect_size("ng1")


# ------------------------------------------------- backoff growth bound


def test_backoff_dict_growth_bounded_under_group_churn():
    """Satellite pin (ISSUE 13): ExponentialBackoff never pruned expired
    entries — autoprovisioned node groups mint fresh ids forever, so long
    runs grew the dict without bound. The amortized sweep keeps the
    population bounded by the groups still inside their backoff/reset
    windows."""
    b = ExponentialBackoff(initial_s=10.0, max_s=20.0, reset_timeout_s=60.0)
    now = 0.0
    peak = 0
    for round_ in range(200):
        for i in range(50):
            b.backoff(f"ng-{round_}-{i}", now)
        peak = max(peak, len(b._entries))
        now += 120.0     # every earlier round is past backoff AND reset
    assert peak < 500, f"peak {peak}: sweep never engaged"
    b.sweep(now)
    assert len(b._entries) == 0 or all(
        now < e.backoff_until or now - e.last_failure < b.reset_timeout_s
        for e in b._entries.values())
    # 10k distinct ids were seen; the dict must not remember them all
    assert len(b._entries) <= 100


def test_backoff_sweep_never_drops_live_entries():
    b = ExponentialBackoff(initial_s=100.0, max_s=200.0, reset_timeout_s=300.0)
    b.backoff("live", 1000.0)
    # flood with garbage that expires immediately relative to the sweep time
    for i in range(200):
        b.backoff(f"g{i}", 0.0)
    b.sweep(1050.0)
    assert b.is_backed_off("live", 1050.0), "sweep must keep live entries"
    # an entry past backoff but inside the reset window must survive too:
    # the NEXT failure's duration doubles off its history
    b2 = ExponentialBackoff(initial_s=10.0, max_s=80.0, reset_timeout_s=1000.0)
    b2.backoff("laddered", 0.0)
    b2.sweep(500.0)                       # backoff over, reset window not
    assert "laddered" in b2._entries
    assert b2.backoff("laddered", 500.0) - 500.0 == 20.0, "ladder preserved"


def test_restart_rehydrated_request_times_out_like_native():
    """The crash-consistent restart record (core/supervisor.py) re-creates
    ScaleUpRequests verbatim; the registry must expire a rehydrated request
    exactly like one it minted itself."""
    fake, reg = mk_registry(provision_s=100.0, provision_delay_s=10_000.0)
    g = group(fake)
    g.increase_size(1)
    reg.scale_up_requests["ng1"] = ScaleUpRequest("ng1", 1, 50.0, 150.0)
    reg.update_nodes(nodes_of(fake), now=100.0)
    assert "ng1" in reg.scale_up_requests
    reg.update_nodes(nodes_of(fake), now=151.0)
    assert "ng1" not in reg.scale_up_requests
    assert reg.backoff.is_backed_off("ng1", 151.0)
